"""Golden tests: rolling ops vs trivially-correct float64 NumPy loops."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_backtesting_exploration_tpu.ops import rolling


RNG = np.random.default_rng(42)
T = 400
# Price-like levels: the numerically nasty case for f32 second moments.
X = (100.0 * np.exp(np.cumsum(RNG.normal(0, 0.02, T)))).astype(np.float64)
Y = (80.0 * np.exp(np.cumsum(RNG.normal(0, 0.02, T)))).astype(np.float64)


def np_rolling(x, w, fn):
    out = np.full_like(x, np.nan)
    for t in range(w - 1, len(x)):
        out[t] = fn(x[t - w + 1: t + 1])
    return out


@pytest.mark.parametrize("w", [2, 5, 20, 128])
def test_rolling_mean(w):
    got = np.asarray(rolling.rolling_mean(jnp.asarray(X, jnp.float32), w))
    want = np_rolling(X, w, np.mean)
    np.testing.assert_allclose(got[w - 1:], want[w - 1:], rtol=1e-4)
    assert np.isnan(got[: w - 1]).all()


@pytest.mark.parametrize("w,ddof", [(5, 0), (20, 0), (20, 1), (64, 1)])
def test_rolling_std(w, ddof):
    got = np.asarray(rolling.rolling_std(jnp.asarray(X, jnp.float32), w, ddof=ddof))
    want = np_rolling(X, w, lambda s: np.std(s, ddof=ddof))
    np.testing.assert_allclose(got[w - 1:], want[w - 1:], rtol=5e-3, atol=1e-4)


@pytest.mark.parametrize("w", [5, 30])
def test_rolling_zscore(w):
    got = np.asarray(rolling.rolling_zscore(jnp.asarray(X, jnp.float32), w))
    m = np_rolling(X, w, np.mean)
    s = np_rolling(X, w, np.std)
    want = (X - m) / s
    np.testing.assert_allclose(got[w - 1:], want[w - 1:], rtol=5e-3, atol=5e-3)


@pytest.mark.parametrize("w", [10, 60])
def test_rolling_ols(w):
    alpha, beta = rolling.rolling_ols(
        jnp.asarray(Y, jnp.float32), jnp.asarray(X, jnp.float32), w)
    alpha, beta = np.asarray(alpha), np.asarray(beta)
    want_a = np.full(T, np.nan)
    want_b = np.full(T, np.nan)
    for t in range(w - 1, T):
        xs, ys = X[t - w + 1: t + 1], Y[t - w + 1: t + 1]
        b, a = np.polyfit(xs, ys, 1)
        want_a[t], want_b[t] = a, b
    np.testing.assert_allclose(beta[w - 1:], want_b[w - 1:], rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(alpha[w - 1:], want_a[w - 1:], rtol=2e-2, atol=2.0)


@pytest.mark.parametrize("span", [3, 21])
def test_ema(span):
    got = np.asarray(rolling.ema(jnp.asarray(X, jnp.float32), span=span))
    a = 2.0 / (span + 1)
    want = np.empty_like(X)
    want[0] = X[0]
    for t in range(1, T):
        want[t] = (1 - a) * want[t - 1] + a * X[t]
    np.testing.assert_allclose(got, want, rtol=1e-4)


@pytest.mark.parametrize("w", [1, 2, 7, 33])
def test_rolling_max_min(w):
    gmax = np.asarray(rolling.rolling_max(jnp.asarray(X, jnp.float32), w))
    gmin = np.asarray(rolling.rolling_min(jnp.asarray(X, jnp.float32), w))
    np.testing.assert_allclose(gmax[w - 1:], np_rolling(X, w, np.max)[w - 1:],
                               rtol=1e-6)
    np.testing.assert_allclose(gmin[w - 1:], np_rolling(X, w, np.min)[w - 1:],
                               rtol=1e-6)


def test_traced_window_vmap_matches_static():
    """vmap over a window grid must equal per-window static calls."""
    x = jnp.asarray(X, jnp.float32)
    windows = jnp.asarray([3, 10, 50], jnp.int32)
    batched = jax.vmap(lambda w: rolling.rolling_mean(x, w, fill=0.0))(windows)
    for i, w in enumerate([3, 10, 50]):
        single = rolling.rolling_mean(x, w, fill=0.0)
        np.testing.assert_allclose(np.asarray(batched[i]), np.asarray(single),
                                   rtol=1e-6)


def test_rolling_sum_under_jit():
    x = jnp.asarray(X, jnp.float32)
    f = jax.jit(lambda x, w: rolling.rolling_sum(x, w, fill=0.0))
    np.testing.assert_allclose(
        np.asarray(f(x, 7)),
        np.asarray(rolling.rolling_sum(x, 7, fill=0.0)), rtol=1e-6)
