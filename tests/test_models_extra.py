"""RSI and MACD model families: golden tests vs pure NumPy recurrences.

The strategies themselves run as fused vectorized transforms (associative
EMA scans, log-depth hysteresis); the references here are deliberately
naive per-bar Python/NumPy loops — trivially auditable semantics.
"""

import jax.numpy as jnp
import numpy as np

from distributed_backtesting_exploration_tpu.models import base, macd, rsi, trix
from distributed_backtesting_exploration_tpu.parallel import sweep
from distributed_backtesting_exploration_tpu.utils import data


def _np_ema(x, alpha):
    out = np.empty_like(x)
    out[0] = x[0]
    for t in range(1, len(x)):
        out[t] = (1.0 - alpha) * out[t - 1] + alpha * x[t]
    return out


def _np_rsi(close, period):
    diff = np.diff(close, prepend=close[:1])
    gains, losses = np.maximum(diff, 0.0), np.maximum(-diff, 0.0)
    ag = _np_ema(gains, 1.0 / period)
    al = _np_ema(losses, 1.0 / period)
    return 100.0 - 100.0 / (1.0 + ag / (al + 1e-12))


def _one_close(T=220, seed=0):
    s = data.synthetic_ohlcv(1, T, seed=seed)
    return np.asarray(s.close[0], np.float64)


def test_rsi_index_matches_numpy():
    close = _one_close()
    got = np.asarray(rsi.rsi_index(jnp.asarray(close, jnp.float32), 14.0))
    want = _np_rsi(close, 14.0)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-3)


def test_rsi_positions_hysteresis_semantics():
    close = _one_close(seed=3)
    period, band = 14.0, 20.0
    strat = base.get_strategy("rsi")

    class _O:
        pass

    o = _O()
    o.close = jnp.asarray(close, jnp.float32)
    got = np.asarray(strat.positions(
        o, dict(period=jnp.float32(period), band=jnp.float32(band))))

    # Serial reference machine over the numpy RSI.
    r = _np_rsi(close, period)
    pos = np.zeros_like(r)
    p = 0.0
    for t in range(len(r)):
        x = r[t] - 50.0
        if p == 0.0:
            p = 1.0 if x < -band else (-1.0 if x > band else 0.0)
        elif p > 0 and x >= 0.0:
            p = 0.0
        elif p < 0 and x <= 0.0:
            p = 0.0
        if t < period:   # warmup masked flat (valid = t >= period)
            p = 0.0
        pos[t] = p
    # f32 RSI vs f64 RSI can disagree exactly at a band edge; allow a
    # vanishing flip count rather than bit-chasing the EMA rounding.
    assert (got != pos).mean() < 0.02


def test_macd_lines_match_numpy():
    close = _one_close(seed=5)
    got_macd, got_sig = macd.macd_lines(
        jnp.asarray(close, jnp.float32), 12.0, 26.0, 9.0)
    ema = lambda x, span: _np_ema(x, 2.0 / (span + 1.0))
    want_macd = ema(close, 12.0) - ema(close, 26.0)
    want_sig = ema(want_macd, 9.0)
    np.testing.assert_allclose(np.asarray(got_macd), want_macd,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(got_sig), want_sig,
                               rtol=1e-4, atol=1e-4)


def test_trix_lines_match_numpy():
    close = _one_close(seed=9)
    got_trix, got_sig = trix.trix_lines(
        jnp.asarray(close, jnp.float32), 9.0, 4.0)
    ema = lambda x, span: _np_ema(x, 2.0 / (span + 1.0))
    e3 = ema(ema(ema(close, 9.0), 9.0), 9.0)
    prev = np.concatenate([e3[:1], e3[:-1]])
    want_trix = e3 / prev - 1.0
    want_sig = ema(want_trix, 4.0)
    np.testing.assert_allclose(np.asarray(got_trix), want_trix,
                               rtol=1e-3, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_sig), want_sig,
                               rtol=1e-3, atol=1e-6)


def test_obv_series_matches_numpy():
    s = data.synthetic_ohlcv(1, 220, seed=11)
    close = np.asarray(s.close[0], np.float64)
    volume = np.asarray(s.volume[0], np.float64)
    from distributed_backtesting_exploration_tpu.models import obv as obv_mod

    got = np.asarray(obv_mod.obv_series(
        jnp.asarray(close[None], jnp.float32),
        jnp.asarray(volume[None], jnp.float32))[0])
    v = volume / volume[0]
    step = np.sign(np.diff(close, prepend=close[:1])) * v
    want = np.cumsum(step)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    assert got[0] == 0.0     # first bar: sign(0) * v0 = 0


def test_rsi_macd_sweep_end_to_end():
    """Both families run through the standard sweep engine."""
    ohlcv = data.synthetic_ohlcv(3, 160, seed=7)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))

    rgrid = sweep.product_grid(
        period=jnp.asarray([7.0, 14.0], jnp.float32),
        band=jnp.asarray([15.0, 25.0], jnp.float32))
    m = sweep.jit_sweep(panel, base.get_strategy("rsi"), dict(rgrid),
                        cost=1e-3)
    assert np.asarray(m.sharpe).shape == (3, 4)
    assert np.isfinite(np.asarray(m.sharpe)).all()

    mgrid = sweep.product_grid(
        fast=jnp.asarray([8.0, 12.0], jnp.float32),
        slow=jnp.asarray([26.0, 35.0], jnp.float32),
        signal=jnp.asarray([9.0], jnp.float32))
    m2 = sweep.jit_sweep(panel, base.get_strategy("macd"), dict(mgrid),
                         cost=1e-3)
    assert np.asarray(m2.sharpe).shape == (3, 4)
    assert np.isfinite(np.asarray(m2.sharpe)).all()


def test_new_strategies_registered():
    names = base.available_strategies()
    assert "rsi" in names and "macd" in names


def test_rolling_vwap_matches_numpy():
    from distributed_backtesting_exploration_tpu.models import vwap

    s = data.synthetic_ohlcv(1, 120, seed=31)
    close = np.asarray(s.close[0], np.float64)
    volume = np.asarray(s.volume[0], np.float64)
    w = 10
    got = np.asarray(vwap.rolling_vwap(
        jnp.asarray(close, jnp.float32), jnp.asarray(volume, jnp.float32),
        jnp.float32(w)))
    want = np.full_like(close, np.nan)
    for t in range(w - 1, len(close)):
        sl = slice(t - w + 1, t + 1)
        want[t] = (close[sl] * volume[sl]).sum() / volume[sl].sum()
    np.testing.assert_allclose(got[w - 1:], want[w - 1:], rtol=2e-5)


def test_vwap_and_donchian_hl_sweep_end_to_end():
    """The volume- and high/low-consuming families run through the sweep
    engine — the OHLCV panel's non-close columns carry real signal."""
    ohlcv = data.synthetic_ohlcv(3, 160, seed=33)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))

    vgrid = sweep.product_grid(
        window=jnp.asarray([10.0, 20.0], jnp.float32),
        k=jnp.asarray([1.0, 2.0], jnp.float32))
    m = sweep.jit_sweep(panel, base.get_strategy("vwap_reversion"),
                        dict(vgrid), cost=1e-3)
    assert np.isfinite(np.asarray(m.sharpe)).all()
    # Volume must matter: doubling volume on later bars changes the signal.
    skew = panel._replace(volume=panel.volume *
                          jnp.linspace(1.0, 4.0, 160)[None, :])
    m2 = sweep.jit_sweep(skew, base.get_strategy("vwap_reversion"),
                         dict(vgrid), cost=1e-3)
    assert not np.allclose(np.asarray(m.sharpe), np.asarray(m2.sharpe))

    dgrid = sweep.product_grid(window=jnp.asarray([15.0, 30.0], jnp.float32))
    d = sweep.jit_sweep(panel, base.get_strategy("donchian_hl"),
                        dict(dgrid), cost=1e-3)
    assert np.isfinite(np.asarray(d.sharpe)).all()
    # High/low channels differ from close-only channels.
    d_close = sweep.jit_sweep(panel, base.get_strategy("donchian"),
                              dict(dgrid), cost=1e-3)
    assert not np.allclose(np.asarray(d.sharpe), np.asarray(d_close.sharpe))


def test_donchian_hl_serial_reference():
    """Golden: the HL-channel latch vs a naive per-bar loop."""
    s = data.synthetic_ohlcv(1, 140, seed=35)
    high = np.asarray(s.high[0])
    low = np.asarray(s.low[0])
    close = np.asarray(s.close[0])
    w = 12

    class _O:
        pass

    o = _O()
    o.high, o.low, o.close = (jnp.asarray(high), jnp.asarray(low),
                              jnp.asarray(close))
    got = np.asarray(base.get_strategy("donchian_hl").positions(
        o, dict(window=jnp.float32(w))))

    pos = np.zeros_like(close)
    p = 0.0
    for t in range(len(close)):
        hi_prev = high[max(0, t - w):t].max() if t >= 1 else np.inf
        lo_prev = low[max(0, t - w):t].min() if t >= 1 else -np.inf
        if t >= w:   # valid after a full prior channel
            if close[t] >= hi_prev:
                p = 1.0
            elif close[t] <= lo_prev:
                p = -1.0
        else:
            p = 0.0
        pos[t] = p
    np.testing.assert_array_equal(got, pos)
