"""The obs layer: registry semantics, spans, rendering, endpoint, dump CLI."""

import json
import math
import threading
import urllib.request

import pytest

from distributed_backtesting_exploration_tpu import obs
from distributed_backtesting_exploration_tpu.obs import dump, events


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------

def test_counter_semantics():
    reg = obs.Registry()
    c = reg.counter("dbx_t_total", "help", method="A")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    # get-or-create: same name+labels -> same child; new labels -> new child
    assert reg.counter("dbx_t_total", method="A") is c
    c2 = reg.counter("dbx_t_total", method="B")
    assert c2 is not c and c2.value == 0.0
    with pytest.raises(ValueError):
        c.inc(-1)


def test_kind_and_name_validation():
    reg = obs.Registry()
    reg.counter("dbx_x_total")
    with pytest.raises(ValueError):
        reg.gauge("dbx_x_total")          # same name, different kind
    with pytest.raises(ValueError):
        reg.counter("bad-name")           # invalid prometheus name
    with pytest.raises(ValueError):
        reg.counter("dbx_z_total", **{"0bad": 1})  # invalid label name


def test_gauge_set_fn_and_collector():
    reg = obs.Registry()
    g = reg.gauge("dbx_depth")
    g.set(4)
    assert g.value == 4
    reg.gauge_fn("dbx_live", lambda: 7)
    state = {"n": 0}
    reg.add_collector("c", lambda r: r.gauge("dbx_coll").set(
        state.__setitem__("n", state["n"] + 1) or state["n"]))
    snap = reg.snapshot()
    assert snap["dbx_live"]["values"][""] == 7
    assert snap["dbx_coll"]["values"][""] == 1
    reg.snapshot()
    assert state["n"] == 2                 # collector runs once per snapshot
    reg.remove_collector("c")
    reg.snapshot()
    assert state["n"] == 2


def test_histogram_buckets_and_summary():
    reg = obs.Registry()
    h = reg.histogram("dbx_lat_seconds", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.001, 0.005, 0.05, 5.0):
        h.observe(v)
    cum = dict(h.cumulative())
    # le is inclusive: the 0.001 observation lands in the 0.001 bucket
    assert cum[0.001] == 2 and cum[0.01] == 3 and cum[0.1] == 4
    assert cum[math.inf] == 5
    s = h.summary()
    assert s["count"] == 5 and s["max"] == 5.0
    assert s["sum"] == pytest.approx(5.0565)
    assert 0 < s["p50"] <= 0.01


def test_prometheus_rendering():
    reg = obs.Registry()
    reg.counter("dbx_c_total", "a counter", kind="x").inc(2)
    reg.gauge("dbx_g").set(1.5)
    reg.histogram("dbx_h_seconds", buckets=(0.1, 1.0)).observe(0.5)
    txt = reg.render_prometheus()
    assert "# TYPE dbx_c_total counter" in txt
    assert 'dbx_c_total{kind="x"} 2.0' in txt
    assert "dbx_g 1.5" in txt
    assert 'dbx_h_seconds_bucket{le="0.1"} 0' in txt
    assert 'dbx_h_seconds_bucket{le="1.0"} 1' in txt
    assert 'dbx_h_seconds_bucket{le="+Inf"} 1' in txt
    assert "dbx_h_seconds_count 1" in txt


def test_registry_thread_safety():
    reg = obs.Registry()
    c = reg.counter("dbx_mt_total")
    h = reg.histogram("dbx_mt_seconds")

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.001)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000


# ---------------------------------------------------------------------------
# Spans + event log
# ---------------------------------------------------------------------------

def test_span_nesting_and_event_log(tmp_path):
    path = str(tmp_path / "events.jsonl")
    events.configure(path)
    try:
        with obs.span("outer"):
            assert obs.current_span() == "outer"
            with obs.span("inner", jobs=3):
                assert obs.current_span() == "inner"
        assert obs.current_span() is None
    finally:
        events.configure(None)
    recs = [json.loads(ln) for ln in open(path)]
    inner = next(r for r in recs if r["name"] == "inner")
    outer = next(r for r in recs if r["name"] == "outer")
    assert inner["parent"] == "outer" and inner["jobs"] == 3
    assert outer["parent"] is None
    assert inner["dur_s"] <= outer["dur_s"]
    # span durations also land in the shared registry histogram
    s = obs.get_registry().summaries()
    assert s["dbx_span_seconds{span=inner}"]["count"] >= 1


def test_span_records_on_exception(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    events.configure(path)
    try:
        with pytest.raises(RuntimeError):
            with obs.span("boom"):
                raise RuntimeError("x")
    finally:
        events.configure(None)
    rec = json.loads(open(path).read().splitlines()[-1])
    assert rec["name"] == "boom" and rec["ok"] is False


# ---------------------------------------------------------------------------
# HTTP endpoint + dump CLI (the tier-1 smoke of the tooling)
# ---------------------------------------------------------------------------

def test_metrics_endpoint_and_dump_cli(tmp_path, capsys):
    reg = obs.Registry()
    reg.counter("dbx_cli_total").inc(3)
    h = reg.histogram("dbx_cli_seconds")
    h.observe(0.01)
    srv = obs.start_metrics_server(0, registry=reg)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "dbx_cli_total 3.0" in body
        snap = json.loads(
            urllib.request.urlopen(base + "/stats.json").read())
        assert snap["dbx_cli_seconds"]["type"] == "histogram"
        # dump CLI against the live endpoint
        assert dump.main([base]) == 0
        out = capsys.readouterr().out
        assert "dbx_cli_seconds" in out and "dbx_cli_total" in out
    finally:
        srv.stop()

    # dump CLI against a JSONL event log
    path = str(tmp_path / "trace.jsonl")
    events.configure(path)
    try:
        with obs.span("phase_a"):
            with obs.span("phase_b"):
                pass
    finally:
        events.configure(None)
    assert dump.main([path]) == 0
    out = capsys.readouterr().out
    assert "phase_a" in out and "phase_a/phase_b" in out and "share" in out


def test_event_log_env_opt_in_is_lazy(tmp_path, monkeypatch):
    """DBX_OBS_JSONL is consulted at FIRST USE, not import (dbxlint
    import-time-config): setting it after import but before first use
    enables logging, and an explicit configure() always wins over the
    environment — in-process toggling, no reimport."""
    path = str(tmp_path / "lazy.jsonl")
    monkeypatch.setattr(events, "_env_checked", False)
    monkeypatch.setattr(events, "_fh", None)
    monkeypatch.setattr(events, "_path", None)
    monkeypatch.setenv("DBX_OBS_JSONL", path)
    try:
        assert events.enabled()                    # first use reads the env
        assert events.configured_path() == path
        events.emit("lazy_probe", k=1)
        assert json.loads(open(path).read())["ev"] == "lazy_probe"
        # Explicit configure(None) disables even though the env is set.
        events.configure(None)
        assert not events.enabled()
    finally:
        events.configure(None)


def test_steptimer_gauge():
    reg = obs.Registry()
    g = reg.gauge("dbx_rate")
    t = obs.StepTimer(g)
    t.add(100)
    assert t.rate > 0
    assert g.value > 0   # published at add() time (rate decays after)


# ---------------------------------------------------------------------------
# utils.trace deprecation shim
# ---------------------------------------------------------------------------

def test_utils_trace_shim_warns_and_reexports():
    import importlib
    import warnings

    import distributed_backtesting_exploration_tpu.utils.trace as shim

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        importlib.reload(shim)
    assert any(issubclass(x.category, DeprecationWarning) for x in w)
    from distributed_backtesting_exploration_tpu.obs import trace as obs_trace

    assert shim.timed is obs_trace.timed
    assert shim.StepTimer is obs_trace.StepTimer
    assert shim.device_profile is obs_trace.device_profile


# ---------------------------------------------------------------------------
# Fused-kernel substrate observability (worker backend)
# ---------------------------------------------------------------------------

def test_backend_publishes_substrate_info_and_route_counters(monkeypatch):
    """A fleet operator must be able to read which epilogue/table/lane
    substrate a worker serves from GetStats obs_json / /stats.json alone:
    the backend publishes an info gauge at construction and counts every
    fused group into dbx_fused_substrate_total."""
    import numpy as np

    from distributed_backtesting_exploration_tpu.rpc import (
        backtesting_pb2 as pb, compute, wire)
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        synthetic_jobs)

    monkeypatch.delenv("DBX_EPILOGUE", raising=False)
    monkeypatch.delenv("DBX_SMA_TABLE", raising=False)
    monkeypatch.delenv("DBX_LANES_CAP", raising=False)
    backend = compute.JaxSweepBackend(use_fused=True, use_mesh=False)
    summ = obs.get_registry().summaries(prefix="dbx_fused_substrate_info")
    info = [k for k in summ if "epilogue=scan" in k]
    assert info, f"substrate info gauge missing: {summ}"
    assert any("table_sma=inline" in k and "lanes_cap=0" in k for k in info)

    (rec,) = synthetic_jobs(1, 64, "sma_crossover",
                            {"fast": np.asarray([3.0], np.float32),
                             "slow": np.asarray([10.0], np.float32)},
                            seed=5)
    spec = pb.JobSpec(id=rec.id, strategy=rec.strategy, ohlcv=rec.ohlcv,
                      grid=wire.grid_to_proto(rec.grid), cost=rec.cost,
                      periods_per_year=252)
    (done,) = backend.process([spec])
    assert done.metrics   # the group really ran fused
    summ = obs.get_registry().summaries(prefix="dbx_fused_substrate_total")
    key = [k for k in summ
           if "kernel=sma_crossover" in k and "epilogue=scan" in k
           and "table=inline" in k]
    assert key and summ[key[0]] >= 1
