"""The obs layer: registry semantics, spans, rendering, endpoint, dump CLI,
distributed-trace plumbing, and the timeline analyzer."""

import json
import math
import threading
import urllib.request

import pytest

from distributed_backtesting_exploration_tpu import obs
from distributed_backtesting_exploration_tpu.obs import (
    dump, events, timeline)


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------

def test_counter_semantics():
    reg = obs.Registry()
    c = reg.counter("dbx_t_total", "help", method="A")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    # get-or-create: same name+labels -> same child; new labels -> new child
    assert reg.counter("dbx_t_total", method="A") is c
    c2 = reg.counter("dbx_t_total", method="B")
    assert c2 is not c and c2.value == 0.0
    with pytest.raises(ValueError):
        c.inc(-1)


def test_kind_and_name_validation():
    reg = obs.Registry()
    reg.counter("dbx_x_total")
    with pytest.raises(ValueError):
        reg.gauge("dbx_x_total")          # same name, different kind
    with pytest.raises(ValueError):
        reg.counter("bad-name")           # invalid prometheus name
    with pytest.raises(ValueError):
        reg.counter("dbx_z_total", **{"0bad": 1})  # invalid label name


def test_gauge_set_fn_and_collector():
    reg = obs.Registry()
    g = reg.gauge("dbx_depth")
    g.set(4)
    assert g.value == 4
    reg.gauge_fn("dbx_live", lambda: 7)
    state = {"n": 0}
    reg.add_collector("c", lambda r: r.gauge("dbx_coll").set(
        state.__setitem__("n", state["n"] + 1) or state["n"]))
    snap = reg.snapshot()
    assert snap["dbx_live"]["values"][""] == 7
    assert snap["dbx_coll"]["values"][""] == 1
    reg.snapshot()
    assert state["n"] == 2                 # collector runs once per snapshot
    reg.remove_collector("c")
    reg.snapshot()
    assert state["n"] == 2


def test_histogram_buckets_and_summary():
    reg = obs.Registry()
    h = reg.histogram("dbx_lat_seconds", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.001, 0.005, 0.05, 5.0):
        h.observe(v)
    cum = dict(h.cumulative())
    # le is inclusive: the 0.001 observation lands in the 0.001 bucket
    assert cum[0.001] == 2 and cum[0.01] == 3 and cum[0.1] == 4
    assert cum[math.inf] == 5
    s = h.summary()
    assert s["count"] == 5 and s["max"] == 5.0
    assert s["sum"] == pytest.approx(5.0565)
    assert 0 < s["p50"] <= 0.01


def test_prometheus_rendering():
    reg = obs.Registry()
    reg.counter("dbx_c_total", "a counter", kind="x").inc(2)
    reg.gauge("dbx_g").set(1.5)
    reg.histogram("dbx_h_seconds", buckets=(0.1, 1.0)).observe(0.5)
    txt = reg.render_prometheus()
    assert "# TYPE dbx_c_total counter" in txt
    assert 'dbx_c_total{kind="x"} 2.0' in txt
    assert "dbx_g 1.5" in txt
    assert 'dbx_h_seconds_bucket{le="0.1"} 0' in txt
    assert 'dbx_h_seconds_bucket{le="1.0"} 1' in txt
    assert 'dbx_h_seconds_bucket{le="+Inf"} 1' in txt
    assert "dbx_h_seconds_count 1" in txt


def test_prometheus_escaping_hostile_label_and_help_values():
    """Backslash, double-quote, and newline in label values (and backslash/
    newline in HELP text) must be escaped per the text exposition format —
    emitted raw they terminate the sample line mid-value and the scrape
    fails to parse."""
    reg = obs.Registry()
    hostile = 'C:\\data\n"quoted"'
    reg.counter("dbx_esc_total", help="line one\nline two \\ backslash",
                path_kind=hostile).inc()
    txt = reg.render_prometheus()
    assert ('dbx_esc_total{path_kind='
            '"C:\\\\data\\n\\"quoted\\""} 1.0') in txt
    assert "# HELP dbx_esc_total line one\\nline two \\\\ backslash" in txt
    # No raw newline survives inside any line: every line is one sample
    # or one comment, never a torn continuation.
    for line in txt.splitlines():
        assert line.startswith(("#", "dbx_")), line


def test_registry_thread_safety():
    reg = obs.Registry()
    c = reg.counter("dbx_mt_total")
    h = reg.histogram("dbx_mt_seconds")

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.001)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000


# ---------------------------------------------------------------------------
# Spans + event log
# ---------------------------------------------------------------------------

def test_span_nesting_and_event_log(tmp_path):
    path = str(tmp_path / "events.jsonl")
    events.configure(path)
    try:
        with obs.span("outer"):
            assert obs.current_span() == "outer"
            with obs.span("inner", jobs=3):
                assert obs.current_span() == "inner"
        assert obs.current_span() is None
    finally:
        events.configure(None)
    recs = [json.loads(ln) for ln in open(path)]
    inner = next(r for r in recs if r["name"] == "inner")
    outer = next(r for r in recs if r["name"] == "outer")
    assert inner["parent"] == "outer" and inner["jobs"] == 3
    assert outer["parent"] is None
    assert inner["dur_s"] <= outer["dur_s"]
    # span durations also land in the shared registry histogram
    s = obs.get_registry().summaries()
    assert s["dbx_span_seconds{span=inner}"]["count"] >= 1


def test_event_log_open_runs_outside_the_module_lock(tmp_path,
                                                     monkeypatch):
    """Round-12 lock-blocking fix: configure() and the DBX_OBS_JSONL
    first-use path used to open the file INSIDE the module lock — a
    slow open (NFS, a fifo) stalled every concurrent emit. Both opens
    now run with the lock free; a failed configure() leaves the
    previous log attached instead of half-torn-down."""
    path = str(tmp_path / "ev.jsonl")
    lock_states = []
    real_open = open

    def spy_open(*a, **k):
        if a and str(a[0]).endswith("ev.jsonl"):
            lock_states.append(events._lock.locked())
        return real_open(*a, **k)

    monkeypatch.setattr("builtins.open", spy_open)
    events.configure(path)
    try:
        assert lock_states == [False]
        # An unopenable reconfigure raises WITHOUT killing the live log.
        with pytest.raises(OSError):
            events.configure(str(tmp_path / "no" / "dir" / "x.jsonl"))
        assert events.enabled() and events.configured_path() == path
        events.emit("still_alive")
    finally:
        events.configure(None)
    assert "still_alive" in real_open(path).read()


def test_span_records_on_exception(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    events.configure(path)
    try:
        with pytest.raises(RuntimeError):
            with obs.span("boom"):
                raise RuntimeError("x")
    finally:
        events.configure(None)
    rec = json.loads(open(path).read().splitlines()[-1])
    assert rec["name"] == "boom" and rec["ok"] is False


def test_span_trace_ids_context_and_ring(tmp_path):
    """Every span carries a (trace_id, span_id, parent_id) triple: nested
    spans parent locally, the outermost span of a trace_context adopts the
    remote parent, and completed spans land in the bounded ring with the
    same record the JSONL log gets."""
    path = str(tmp_path / "t.jsonl")
    events.configure(path)
    tid = obs.new_trace_id()
    try:
        with obs.trace_context(tid, parent_span_id="remote-parent"):
            assert obs.current_trace() == tid
            with obs.span("outer_t"):
                with obs.span("inner_t"):
                    pass
        assert obs.current_trace() is None
    finally:
        events.configure(None)
    recs = {r["name"]: r for r in map(json.loads, open(path))}
    outer, inner = recs["outer_t"], recs["inner_t"]
    assert outer["trace_id"] == inner["trace_id"] == tid
    assert outer["parent_id"] == "remote-parent"
    assert inner["parent_id"] == outer["span_id"]
    assert outer["span_id"] != inner["span_id"]
    assert "t0" in outer and "pid" in outer
    # The ring holds the same records (minus the writer-stamped ts/pid).
    ring = {r["name"]: r for r in obs.recent_spans()
            if r["name"] in ("outer_t", "inner_t")}
    assert ring["outer_t"]["span_id"] == outer["span_id"]

    # Multi-trace context (one batch, several jobs): spans carry a
    # `traces` pair list instead of a single trace_id.
    pairs = [(obs.new_trace_id(), "p1"), (obs.new_trace_id(), "p2")]
    with obs.trace_context(pairs):
        assert obs.current_trace() is None
        with obs.span("multi_t"):
            pass
    multi = next(r for r in reversed(obs.recent_spans())
                 if r["name"] == "multi_t")
    assert multi["traces"] == [list(p) for p in pairs]
    assert "trace_id" not in multi


def test_stats_payload_ships_recent_spans():
    from distributed_backtesting_exploration_tpu.obs import http as obs_http

    with obs.span("payload_probe"):
        pass
    reg = obs.Registry()
    payload = obs_http.stats_payload(reg)
    fam = payload["dbx_spans_recent"]
    assert fam["type"] == "spans"
    assert any(r["name"] == "payload_probe" for r in fam["values"])
    # dump's snapshot renderer must skip the spans family, not crash.
    assert "payload_probe" not in dump.render_snapshot(
        {"dbx_spans_recent": fam})


# ---------------------------------------------------------------------------
# HTTP endpoint + dump CLI (the tier-1 smoke of the tooling)
# ---------------------------------------------------------------------------

def test_metrics_endpoint_and_dump_cli(tmp_path, capsys):
    reg = obs.Registry()
    reg.counter("dbx_cli_total").inc(3)
    h = reg.histogram("dbx_cli_seconds")
    h.observe(0.01)
    srv = obs.start_metrics_server(0, registry=reg)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "dbx_cli_total 3.0" in body
        snap = json.loads(
            urllib.request.urlopen(base + "/stats.json").read())
        assert snap["dbx_cli_seconds"]["type"] == "histogram"
        # dump CLI against the live endpoint
        assert dump.main([base]) == 0
        out = capsys.readouterr().out
        assert "dbx_cli_seconds" in out and "dbx_cli_total" in out
    finally:
        srv.stop()

    # dump CLI against a JSONL event log
    path = str(tmp_path / "trace.jsonl")
    events.configure(path)
    try:
        with obs.span("phase_a"):
            with obs.span("phase_b"):
                pass
    finally:
        events.configure(None)
    assert dump.main([path]) == 0
    out = capsys.readouterr().out
    assert "phase_a" in out and "phase_a/phase_b" in out and "share" in out


def test_dump_and_timeline_cli_multi_input_malformed_and_empty(tmp_path,
                                                               capsys):
    """The CI/tooling contract of BOTH CLIs: several --jsonl inputs merge,
    malformed lines are skipped AND counted, and zero parseable events
    exits non-zero (a typo'd path must not render as a healthy quiet
    fleet)."""
    tid = obs.new_trace_id()
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    a.write_text(
        json.dumps({"ev": "span", "name": "job.queue_wait", "t0": 10.0,
                    "dur_s": 1.0, "trace_id": tid, "span_id": "s1",
                    "job": "j1"}) + "\n"
        + json.dumps({"ev": "span", "name": "job", "t0": 10.0,
                      "dur_s": 4.0, "trace_id": tid, "span_id": "s0",
                      "job": "j1", "worker": "w0"}) + "\n"
        + "{torn line\n")
    b.write_text(
        json.dumps({"ev": "span", "name": "worker.process", "t0": 12.0,
                    "dur_s": 1.5, "trace_id": tid, "span_id": "s2",
                    "parent_id": "d1"}) + "\n"
        + "not json at all\n")

    rc = dump.main([str(a), "--jsonl", str(b)])
    out = capsys.readouterr()
    assert rc == 0
    assert "job.queue_wait" in out.out and "worker.process" in out.out
    assert "2 malformed line(s) skipped" in out.out

    rc = timeline.main(["--jsonl", str(a), str(b), "--format", "json"])
    out = capsys.readouterr()
    assert rc == 0
    assert "skipped 2 malformed line(s)" in out.err
    summary = json.loads(out.out)
    assert summary["jobs"] == 1
    job = summary["per_job"][0]
    assert job["job"] == "j1" and job["worker"] == "w0"
    # Critical path partitions the 4s e2e window: 1s queue-wait, 1.5s
    # execute (worker.process fallback), the rest transport.
    assert job["stages"]["queue_wait"] == pytest.approx(1.0)
    assert job["stages"]["execute"] == pytest.approx(1.5)
    assert job["stages"]["transport"] == pytest.approx(1.5)
    assert sum(job["stages"].values()) == pytest.approx(job["e2e_s"])

    # --job filter: a non-matching id exits non-zero.
    assert timeline.main(["--jsonl", str(a), "--job", "nope"]) == 2
    capsys.readouterr()

    # Zero parseable events -> non-zero exit for both CLIs.
    empty = tmp_path / "empty.jsonl"
    empty.write_text("garbage\n{more garbage\n")
    assert dump.main([str(empty)]) == 2
    assert timeline.main(["--jsonl", str(empty)]) == 2
    capsys.readouterr()


def test_timeline_straggler_flagging():
    """Jobs whose stage time exceeds the fleet p95 are flagged once the
    fleet is big enough; below min_straggler_jobs the p95 of a tiny
    sample flags nobody."""
    spans = []
    for i in range(10):
        tid = f"{i:032x}"
        dur = 5.0 if i == 9 else 1.0   # job 9: 5x the fleet's execute
        spans.append({"ev": "span", "name": "job", "t0": 0.0,
                      "dur_s": dur + 1.0, "trace_id": tid, "span_id": "r",
                      "job": f"job{i}", "worker": f"w{i % 2}"})
        spans.append({"ev": "span", "name": "worker.execute", "t0": 0.5,
                      "dur_s": dur, "trace_id": tid, "span_id": "e"})
    tls = timeline.reconstruct(spans)
    assert len(tls) == 10
    s = timeline.summarize(tls)
    flagged = {x["job"] for x in s["stragglers"]
               if x["stage"] == "execute"}
    assert flagged == {"job9"}
    # Per-worker attribution covers both workers.
    assert set(s["workers"]) == {"w0", "w1"}
    # A 3-job fleet flags nothing (p95 of a tiny sample is noise).
    tiny = timeline.reconstruct(spans[:6])
    assert timeline.summarize(tiny)["stragglers"] == []


def test_timeline_panel_cache_hit_pseudo_stage():
    """Dispatch-by-digest attribution: a decode span with a truthy
    `cache_hit` attr charges its window to the `panel_cache_hit`
    pseudo-stage (not decode, and never silently to transport); a d2h
    span's cache_hit flag is informational only — the result drain it
    times is real work and stays d2h. Stage seconds still sum exactly to
    the e2e window."""
    tid = obs.new_trace_id()
    spans = [
        {"ev": "span", "name": "job", "t0": 0.0, "dur_s": 4.0,
         "trace_id": tid, "span_id": "s0", "job": "j1", "worker": "w0"},
        {"ev": "span", "name": "job.queue_wait", "t0": 0.0, "dur_s": 1.0,
         "trace_id": tid, "span_id": "s1", "job": "j1"},
        # The digest-cache hit window: decode span, cache_hit=True.
        {"ev": "span", "name": "worker.decode", "t0": 1.5, "dur_s": 0.5,
         "trace_id": tid, "span_id": "s2", "cache_hit": True,
         "cache_hits": 1},
        {"ev": "span", "name": "worker.execute", "t0": 2.0, "dur_s": 1.0,
         "trace_id": tid, "span_id": "s3"},
        {"ev": "span", "name": "worker.d2h", "t0": 3.0, "dur_s": 0.5,
         "trace_id": tid, "span_id": "s4", "cache_hit": True},
    ]
    tls = timeline.reconstruct(spans)
    stages = timeline.critical_path(tls[tid])
    assert stages["panel_cache_hit"] == pytest.approx(0.5)
    assert stages["decode"] == 0.0
    assert stages["d2h"] == pytest.approx(0.5)   # drain stays d2h
    assert stages["execute"] == pytest.approx(1.0)
    assert sum(stages.values()) == pytest.approx(4.0)

    # Without the attr the same window is ordinary decode work.
    spans[2] = dict(spans[2], cache_hit=False)
    stages = timeline.critical_path(timeline.reconstruct(spans)[tid])
    assert stages["decode"] == pytest.approx(0.5)
    assert stages["panel_cache_hit"] == 0.0


def test_timeline_carry_hit_pseudo_stage():
    """Streaming-append attribution: a worker.append span with a truthy
    `carry_hit` attr charges its window to the `carry_hit` pseudo-stage
    (the O(ΔT) advance); a checkpoint-miss full reprice — same span name,
    no flag — stays execute. Stage seconds still sum exactly to the e2e
    window, and the BENCH straggler digest path is unaffected (the stage
    participates in summarize like any other)."""
    tid = obs.new_trace_id()
    spans = [
        {"ev": "span", "name": "job", "t0": 0.0, "dur_s": 3.0,
         "trace_id": tid, "span_id": "s0", "job": "a1", "worker": "w0"},
        {"ev": "span", "name": "job.queue_wait", "t0": 0.0, "dur_s": 1.0,
         "trace_id": tid, "span_id": "s1", "job": "a1"},
        {"ev": "span", "name": "worker.append", "t0": 1.5, "dur_s": 0.25,
         "trace_id": tid, "span_id": "s2", "carry_hit": True},
        {"ev": "span", "name": "worker.report", "t0": 2.5, "dur_s": 0.5,
         "trace_id": tid, "span_id": "s3"},
    ]
    tls = timeline.reconstruct(spans)
    stages = timeline.critical_path(tls[tid])
    assert stages["carry_hit"] == pytest.approx(0.25)
    assert stages["execute"] == 0.0
    assert sum(stages.values()) == pytest.approx(3.0)
    summary = timeline.summarize(tls)
    assert summary["stages"]["carry_hit"]["total_s"] == pytest.approx(0.25)

    # A full reprice (no carry_hit flag) is ordinary execute work.
    spans[2] = dict(spans[2], carry_hit=False)
    stages = timeline.critical_path(timeline.reconstruct(spans)[tid])
    assert stages["execute"] == pytest.approx(0.25)
    assert stages["carry_hit"] == 0.0


def test_timeline_push_stage():
    """Live fan-out attribution (serve/): a `job.push` span — the
    dispatcher-side completion->fanned-out window, emitted before the
    e2e span closes — charges its window to the `push` stage; stage
    seconds still sum exactly to the e2e window. The span overlapping
    the worker's report ENVELOPE wins it (priority 2 vs 1): those
    instants are fan-out work, not report wall."""
    tid = obs.new_trace_id()
    spans = [
        {"ev": "span", "name": "job", "t0": 0.0, "dur_s": 3.0,
         "trace_id": tid, "span_id": "s0", "job": "p1", "worker": "w0"},
        {"ev": "span", "name": "job.queue_wait", "t0": 0.0, "dur_s": 1.0,
         "trace_id": tid, "span_id": "s1", "job": "p1"},
        {"ev": "span", "name": "worker.report", "t0": 2.0, "dur_s": 1.0,
         "trace_id": tid, "span_id": "s2"},
        {"ev": "span", "name": "job.push", "t0": 2.8, "dur_s": 0.2,
         "trace_id": tid, "span_id": "s3", "job": "p1"},
    ]
    tls = timeline.reconstruct(spans)
    stages = timeline.critical_path(tls[tid])
    assert stages["push"] == pytest.approx(0.2)
    assert stages["report"] == pytest.approx(0.8)
    assert sum(stages.values()) == pytest.approx(3.0)
    summary = timeline.summarize(tls)
    assert summary["stages"]["push"]["total_s"] == pytest.approx(0.2)


def test_timeline_inflight_span_charges_execute_not_transport():
    """Round 14: the pipelined worker's `worker.inflight` span (the
    submit-return -> collect-start window while the batch runs on
    device) charges to execute at envelope priority — without it the
    analyzer's uncovered-gap rule would mis-charge the overlap window to
    transport. Stage seconds still sum exactly to the e2e window."""
    tid = obs.new_trace_id()
    spans = [
        {"ev": "span", "name": "job", "t0": 0.0, "dur_s": 4.0,
         "trace_id": tid, "span_id": "s0", "job": "j1", "worker": "w0"},
        {"ev": "span", "name": "job.queue_wait", "t0": 0.0, "dur_s": 0.5,
         "trace_id": tid, "span_id": "s1", "job": "j1"},
        {"ev": "span", "name": "worker.submit", "t0": 1.0, "dur_s": 1.0,
         "trace_id": tid, "span_id": "s2"},
        {"ev": "span", "name": "worker.inflight", "t0": 2.0, "dur_s": 1.0,
         "trace_id": tid, "span_id": "s3"},
        {"ev": "span", "name": "worker.collect", "t0": 3.0, "dur_s": 1.0,
         "trace_id": tid, "span_id": "s4"},
    ]
    stages = timeline.critical_path(timeline.reconstruct(spans)[tid])
    assert stages["execute"] == pytest.approx(2.0)   # submit + inflight
    assert stages["d2h"] == pytest.approx(1.0)
    assert stages["transport"] == pytest.approx(0.5)  # only the real gap
    assert sum(stages.values()) == pytest.approx(4.0)

    # Without the inflight span the same window reads as transport —
    # the mis-charge the overlap-aware mode exists to prevent.
    stages = timeline.critical_path(
        timeline.reconstruct(spans[:3] + spans[4:])[tid])
    assert stages["transport"] == pytest.approx(1.5)


def test_timeline_overlap_factor_pipelined_vs_serial():
    """The overlap-aware mode's `overlap_factor` (round 14): lane
    seconds (submit-half + collect-half) per covered wall second on the
    job's worker — ~1.0 for a serial worker whose lanes tile the busy
    wall, rising toward 2.0 when batch N's device drain overlaps batch
    N+1's host submit. A multi-job batch's fanned-out span lands in the
    lane union once, so co-batching alone never reads as pipelining."""
    tid_a, tid_b = obs.new_trace_id(), obs.new_trace_id()

    def job_spans(tid, name, t0, dur):
        return [
            {"ev": "span", "name": "job", "t0": t0, "dur_s": dur,
             "trace_id": tid, "span_id": f"{name}-e2e", "job": name,
             "worker": "w0"},
            {"ev": "span", "name": "job.queue_wait", "t0": t0,
             "dur_s": 0.5, "trace_id": tid, "span_id": f"{name}-q",
             "job": name}]

    def pipeline_spans():
        return (
            job_spans(tid_a, "A", 0.0, 4.0)
            + job_spans(tid_b, "B", 0.5, 4.5)
            + [
                {"ev": "span", "name": "worker.submit", "t0": 1.0,
                 "dur_s": 1.0, "trace_id": tid_a, "span_id": "a-sub"},
                # Fanned-out decode (a shared-batch span) inside A's
                # submit window: present in BOTH timelines, counted once.
                {"ev": "span", "name": "worker.decode", "t0": 1.0,
                 "dur_s": 0.5, "span_id": "shared-dec", "parent_id": "",
                 "traces": [[tid_a, ""], [tid_b, ""]]},
                {"ev": "span", "name": "worker.inflight", "t0": 2.0,
                 "dur_s": 0.5, "trace_id": tid_a, "span_id": "a-inf"},
                {"ev": "span", "name": "worker.collect", "t0": 2.5,
                 "dur_s": 1.5, "trace_id": tid_a, "span_id": "a-col"},
                # B's submit overlaps A's collect drain: the pipeline.
                {"ev": "span", "name": "worker.submit", "t0": 2.0,
                 "dur_s": 2.0, "trace_id": tid_b, "span_id": "b-sub"},
                {"ev": "span", "name": "worker.collect", "t0": 4.0,
                 "dur_s": 1.0, "trace_id": tid_b, "span_id": "b-col"},
            ])

    tls = timeline.reconstruct(pipeline_spans())
    s = timeline.summarize(tls, overlap=True)
    # Lanes on w0: submit [1,4] (3s), collect [2.5,5] (2.5s), covered
    # wall [1,5] (4s) -> fleet factor 5.5/4.
    assert s["overlap"]["overlap_factor"] == pytest.approx(1.375)
    assert s["overlap"]["workers"]["w0"] == pytest.approx(1.375)
    assert s["overlap"]["lane_seconds"]["submit"] == pytest.approx(3.0)
    assert s["overlap"]["lane_seconds"]["collect"] == pytest.approx(2.5)
    by_job = {j["job"]: j for j in s["per_job"]}
    # A's window [0,4]: submit 3s + collect 1.5s over 3s covered wall.
    assert by_job["A"]["overlap_factor"] == pytest.approx(1.5)
    assert by_job["B"]["overlap_factor"] == pytest.approx(1.375)

    # Serial twin: same stage walls, lanes tiling the busy wall -> 1.0
    # everywhere (and overlap=False keeps the key out entirely).
    serial = (
        job_spans(tid_a, "A", 0.0, 3.5)
        + job_spans(tid_b, "B", 2.5, 2.5)
        + [
            {"ev": "span", "name": "worker.submit", "t0": 1.0,
             "dur_s": 1.0, "trace_id": tid_a, "span_id": "a-sub"},
            {"ev": "span", "name": "worker.collect", "t0": 2.0,
             "dur_s": 1.0, "trace_id": tid_a, "span_id": "a-col"},
            {"ev": "span", "name": "worker.submit", "t0": 3.0,
             "dur_s": 1.0, "trace_id": tid_b, "span_id": "b-sub"},
            {"ev": "span", "name": "worker.collect", "t0": 4.0,
             "dur_s": 1.0, "trace_id": tid_b, "span_id": "b-col"},
        ])
    s = timeline.summarize(timeline.reconstruct(serial), overlap=True)
    assert s["overlap"]["overlap_factor"] == pytest.approx(1.0)
    assert all(j["overlap_factor"] == pytest.approx(1.0)
               for j in s["per_job"])
    s_off = timeline.summarize(timeline.reconstruct(serial))
    assert "overlap" not in s_off
    assert all("overlap_factor" not in j for j in s_off["per_job"])

    # The in-memory ring hook (bench's entry point) passes the mode
    # through and keeps the digest-not-rows discipline.
    ring_summary = timeline.summarize_spans(pipeline_spans(), overlap=True)
    assert ring_summary["overlap"]["overlap_factor"] == pytest.approx(1.375)
    assert "per_job" not in ring_summary


def test_event_log_env_opt_in_is_lazy(tmp_path, monkeypatch):
    """DBX_OBS_JSONL is consulted at FIRST USE, not import (dbxlint
    import-time-config): setting it after import but before first use
    enables logging, and an explicit configure() always wins over the
    environment — in-process toggling, no reimport."""
    path = str(tmp_path / "lazy.jsonl")
    monkeypatch.setattr(events, "_env_checked", False)
    monkeypatch.setattr(events, "_fh", None)
    monkeypatch.setattr(events, "_path", None)
    monkeypatch.setenv("DBX_OBS_JSONL", path)
    try:
        assert events.enabled()                    # first use reads the env
        assert events.configured_path() == path
        events.emit("lazy_probe", k=1)
        assert json.loads(open(path).read())["ev"] == "lazy_probe"
        # Explicit configure(None) disables even though the env is set.
        events.configure(None)
        assert not events.enabled()
    finally:
        events.configure(None)


def test_steptimer_gauge():
    reg = obs.Registry()
    g = reg.gauge("dbx_rate")
    t = obs.StepTimer(g)
    t.add(100)
    assert t.rate > 0
    assert g.value > 0   # published at add() time (rate decays after)


# ---------------------------------------------------------------------------
# utils.trace deprecation shim
# ---------------------------------------------------------------------------

def test_utils_trace_shim_warns_and_reexports():
    import importlib
    import warnings

    import distributed_backtesting_exploration_tpu.utils.trace as shim

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        importlib.reload(shim)
    assert any(issubclass(x.category, DeprecationWarning) for x in w)
    from distributed_backtesting_exploration_tpu.obs import trace as obs_trace

    assert shim.timed is obs_trace.timed
    assert shim.StepTimer is obs_trace.StepTimer
    assert shim.device_profile is obs_trace.device_profile


# ---------------------------------------------------------------------------
# Fused-kernel substrate observability (worker backend)
# ---------------------------------------------------------------------------

def test_backend_publishes_substrate_info_and_route_counters(monkeypatch):
    """A fleet operator must be able to read which epilogue/table/lane
    substrate a worker serves from GetStats obs_json / /stats.json alone:
    the backend publishes an info gauge at construction and counts every
    fused group into dbx_fused_substrate_total."""
    import numpy as np

    from distributed_backtesting_exploration_tpu.rpc import (
        backtesting_pb2 as pb, compute, wire)
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        synthetic_jobs)

    monkeypatch.delenv("DBX_EPILOGUE", raising=False)
    monkeypatch.delenv("DBX_SMA_TABLE", raising=False)
    monkeypatch.delenv("DBX_LANES_CAP", raising=False)
    backend = compute.JaxSweepBackend(use_fused=True, use_mesh=False)
    summ = obs.get_registry().summaries(prefix="dbx_fused_substrate_info")
    info = [k for k in summ if "epilogue=scan" in k]
    assert info, f"substrate info gauge missing: {summ}"
    assert any("table_sma=inline" in k and "lanes_cap=0" in k for k in info)

    (rec,) = synthetic_jobs(1, 64, "sma_crossover",
                            {"fast": np.asarray([3.0], np.float32),
                             "slow": np.asarray([10.0], np.float32)},
                            seed=5)
    spec = pb.JobSpec(id=rec.id, strategy=rec.strategy, ohlcv=rec.ohlcv,
                      grid=wire.grid_to_proto(rec.grid), cost=rec.cost,
                      periods_per_year=252)
    (done,) = backend.process([spec])
    assert done.metrics   # the group really ran fused
    summ = obs.get_registry().summaries(prefix="dbx_fused_substrate_total")
    key = [k for k in summ
           if "kernel=sma_crossover" in k and "epilogue=scan" in k
           and "table=inline" in k]
    assert key and summ[key[0]] >= 1
