"""Checkpoint/restore roundtrip and multihost single-process paths."""

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.ops.metrics import Metrics
from distributed_backtesting_exploration_tpu.parallel import multihost
from distributed_backtesting_exploration_tpu.utils import checkpoint


def _mk_metrics(seed=0, shape=(3, 4)):
    rng = np.random.default_rng(seed)
    return Metrics(*(rng.standard_normal(shape).astype(np.float32)
                     for _ in Metrics._fields))


def test_metrics_checkpoint_roundtrip(tmp_path):
    m = _mk_metrics()
    checkpoint.save_metrics(str(tmp_path / "ckpt"), m, meta={"cost": 1e-3})
    back, meta = checkpoint.load_metrics(str(tmp_path / "ckpt"))
    assert meta == {"cost": 1e-3}
    for a, b in zip(m, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sweep_checkpointer_resume(tmp_path):
    ck = checkpoint.SweepCheckpointer(str(tmp_path / "campaign"))
    assert ck.done() == set()
    ck.add("t0-p0", _mk_metrics(1), meta={"tickers": [0, 8]})
    ck.add("t0-p1", _mk_metrics(2))
    # A "restarted" campaign sees both blocks and can skip them.
    ck2 = checkpoint.SweepCheckpointer(str(tmp_path / "campaign"))
    assert ck2.done() == {"t0-p0", "t0-p1"}
    m, meta = ck2.get("t0-p0")
    np.testing.assert_array_equal(
        np.asarray(m.sharpe), np.asarray(_mk_metrics(1).sharpe))
    assert meta["tickers"] == [0, 8]


def test_multihost_single_process_noop():
    assert multihost.initialize() == 1


def test_host_shard_covers_work_list():
    s = multihost.host_shard(10)     # single process: everything
    assert list(range(10))[s] == list(range(10))
