"""Multi-chip sweep correctness on the 8-virtual-device CPU mesh.

The sharded path must be numerically identical to the single-device sweep —
sweeps are embarrassingly parallel over tickers, so any divergence is a
sharding bug, not math.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.models import sma_crossover  # noqa: F401
from distributed_backtesting_exploration_tpu.models.base import get_strategy
from distributed_backtesting_exploration_tpu.parallel import sharding, sweep
from distributed_backtesting_exploration_tpu.utils import data


@pytest.fixture(scope="module")
def panel():
    ohlcv = data.synthetic_ohlcv(12, 256, seed=3)
    return type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))


@pytest.fixture(scope="module")
def grid():
    return sweep.product_grid(fast=jnp.array([3, 5, 8]),
                              slow=jnp.array([21, 34]))


def test_sharded_sweep_matches_single_device(devices, panel, grid):
    mesh = sharding.make_mesh(devices[:4])
    strat = get_strategy("sma_crossover")
    ref = sweep.jit_sweep(panel, strat, dict(grid))
    sh_ohlcv, sh_grid, _, n = sharding.device_put_sweep(mesh, panel, grid)
    got = sharding.sharded_sweep(mesh, sh_ohlcv, strat, sh_grid)
    for name in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name))[:n],
            np.asarray(getattr(ref, name)), rtol=2e-5, atol=2e-5,
            err_msg=name)


def test_ticker_padding_uneven(devices, grid):
    # 10 tickers over 8 shards: padded to 16, results sliced back to 10.
    mesh = sharding.make_mesh(devices)
    ohlcv = data.synthetic_ohlcv(10, 256, seed=4)
    strat = get_strategy("sma_crossover")
    ref = sweep.jit_sweep(
        type(ohlcv)(*(jnp.asarray(f) for f in ohlcv)), strat, dict(grid))
    sh_ohlcv, sh_grid, _, n = sharding.device_put_sweep(mesh, ohlcv, grid)
    assert n == 10 and sh_ohlcv.close.shape[0] == 16
    got = sharding.sharded_sweep(mesh, sh_ohlcv, strat, sh_grid)
    np.testing.assert_allclose(np.asarray(got.sharpe)[:n],
                               np.asarray(ref.sharpe), rtol=2e-5, atol=2e-5)


def test_output_stays_sharded(devices, panel, grid):
    mesh = sharding.make_mesh(devices[:4])
    strat = get_strategy("sma_crossover")
    sh_ohlcv, sh_grid, _, _ = sharding.device_put_sweep(mesh, panel, grid)
    got = sharding.sharded_sweep(mesh, sh_ohlcv, strat, sh_grid)
    shard_devs = {s.device for s in got.sharpe.addressable_shards}
    assert len(shard_devs) == 4, "metrics should stay row-sharded on the mesh"


def test_best_over_grid_global_argmax(devices, panel, grid):
    mesh = sharding.make_mesh(devices[:4])
    strat = get_strategy("sma_crossover")
    ref = sweep.jit_sweep(panel, strat, dict(grid))
    sharpe = np.asarray(ref.sharpe)
    want_flat = int(sharpe.argmax())
    want_ticker, want_param = divmod(want_flat, sharpe.shape[1])

    sh_ohlcv, sh_grid, _, _ = sharding.device_put_sweep(mesh, panel, grid)
    best_v, ticker, chosen = sharding.best_over_grid(
        mesh, sh_ohlcv, strat, sh_grid, metric="sharpe")
    assert int(ticker) == want_ticker
    np.testing.assert_allclose(float(best_v), sharpe.max(), rtol=2e-5)
    for k in grid:
        np.testing.assert_allclose(
            float(chosen[k]), float(np.asarray(grid[k])[want_param]))
