"""Golden tests for the pairs-trade kernel and walk-forward engine."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.models import pairs
from distributed_backtesting_exploration_tpu.models.base import get_strategy
from distributed_backtesting_exploration_tpu.ops import pnl
from distributed_backtesting_exploration_tpu.parallel import sweep, walkforward
from distributed_backtesting_exploration_tpu.utils import data


def _cointegrated_pair(T=512, seed=0):
    """y tracks 1.5*x + noise, so OLS beta should hover near 1.5."""
    rng = np.random.default_rng(seed)
    x = 50.0 * np.exp(np.cumsum(rng.normal(0, 0.01, T)))
    y = 1.5 * x + rng.normal(0, 0.5, T) + 20.0
    return (jnp.asarray(y, jnp.float32), jnp.asarray(x, jnp.float32))


def test_rolling_beta_recovers_hedge_ratio():
    y, x = _cointegrated_pair()
    beta, z, valid = pairs.pair_signals(y, x, 60)
    b = np.asarray(beta)[120:]
    assert np.all(np.abs(b - 1.5) < 0.4), (b.min(), b.max())
    assert abs(float(np.median(b)) - 1.5) < 0.1


def test_pairs_machine_enters_and_exits():
    y, x = _cointegrated_pair(seed=1)
    pos, _ = pairs.pairs_positions(
        y, x, {"lookback": jnp.asarray(40),
               "z_entry": jnp.asarray(1.5), "z_exit": jnp.asarray(0.0)})
    p = np.asarray(pos)
    assert set(np.unique(p)).issubset({-1.0, 0.0, 1.0})
    assert (p != 0).any(), "never entered"
    # hysteresis: no direct +1 -> -1 flips without passing flat
    flips = p[1:] * p[:-1]
    assert not (flips < 0).any(), "position flipped sign without exiting"


def test_pairs_sweep_shapes_and_finiteness():
    ys, xs = zip(*(_cointegrated_pair(seed=s) for s in range(3)))
    y = jnp.stack(ys)
    x = jnp.stack(xs)
    grid = sweep.product_grid(lookback=jnp.array([30, 60]),
                              z_entry=jnp.array([1.0, 2.0]),
                              z_exit=jnp.array([0.0]))
    m = pairs.run_pairs_sweep(y, x, grid, cost=1e-4)
    assert m.sharpe.shape == (3, 4)
    assert np.isfinite(np.asarray(m.sharpe)).all()
    assert np.isfinite(np.asarray(m.max_drawdown)).all()


def test_walkforward_matches_manual_loop():
    """Scan+vmap walk-forward == a hand-rolled numpy window loop."""
    ohlcv = data.synthetic_ohlcv(4, 640, seed=7)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(fast=jnp.array([3, 5]), slow=jnp.array([13, 21]))
    train, test = 256, 64
    strat = get_strategy("sma_crossover")
    res = walkforward.walk_forward(
        panel, strat, grid, train=train, test=test, metric="sharpe")

    T = 640
    starts = np.arange((T - train) // test) * test
    n_windows = len(starts)
    assert res.oos_returns.shape == (4, n_windows * test)

    # Manual reference for ticker 0, window 0.
    win = type(panel)(*(f[0:1, starts[0]:starts[0] + train + test]
                        for f in panel))
    per_param = sweep.run_sweep(
        win, strat, dict(grid),
        bar_mask=jnp.broadcast_to(jnp.arange(train + test) < train,
                                  (1, train + test)))
    best = int(np.asarray(per_param.sharpe)[0].argmax())
    params = {k: v[best] for k, v in grid.items()}
    pos = strat.positions(type(panel)(*(f[0] for f in win)), params)
    ref = pnl.backtest_prefix(win.close[0], pos)
    want_oos = np.asarray(ref.returns)[train:].copy()
    # Window 0 starts flat in deployment: its first OOS bar earns nothing
    # (the in-window backtest carried the train-span position into it).
    want_oos[0] = 0.0
    np.testing.assert_allclose(
        np.asarray(res.oos_returns)[0, :test], want_oos, rtol=1e-5, atol=1e-6)
    for k in grid:
        assert float(res.chosen[k][0, 0]) == float(np.asarray(grid[k])[best])


def test_walkforward_oos_metrics_finite():
    ohlcv = data.synthetic_ohlcv(3, 512, seed=9)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(fast=jnp.array([4, 8]), slow=jnp.array([16, 32]))
    res = walkforward.walk_forward(
        panel, get_strategy("sma_crossover"), grid, train=128, test=64)
    assert np.isfinite(np.asarray(res.oos_metrics.sharpe)).all()
    assert np.isfinite(np.asarray(res.train_metric)).all()


def test_walkforward_lower_is_better_metric():
    """metric='max_drawdown' must pick the SMALLEST-drawdown param."""
    ohlcv = data.synthetic_ohlcv(2, 512, seed=11)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(fast=jnp.array([3, 6]), slow=jnp.array([12, 24]))
    train, test = 128, 64
    res = walkforward.walk_forward(
        panel, get_strategy("sma_crossover"), grid,
        train=train, test=test, metric="max_drawdown")
    # Manual check, window 0 / ticker 0: chosen train drawdown is the min.
    from distributed_backtesting_exploration_tpu.ops import metrics as M
    strat = get_strategy("sma_crossover")
    win = type(panel)(*(f[0, :train] for f in panel))
    dds = []
    P = len(np.asarray(grid["fast"]))
    for i in range(P):
        params = {k: v[i] for k, v in grid.items()}
        pos = strat.positions(win, params)
        r = pnl.backtest_prefix(win.close, pos)
        dds.append(float(M.max_drawdown(r.equity)))
    np.testing.assert_allclose(float(res.train_metric[0, 0]), min(dds),
                               rtol=1e-5, atol=1e-7)


def test_walkforward_boundary_rebalance_cost():
    """The stitched series prices exactly the positions it reports.

    Reprice the stitched position series from scratch: bar-over-bar returns
    of the underlying closes times the lagged stitched position, minus cost
    on the stitched turnover (starting flat). That must equal oos_returns —
    including at window boundaries, where the in-window charge from
    backtest_prefix has to have been swapped for the deployed-transition
    charge.
    """
    cost = 1e-2
    ohlcv = data.synthetic_ohlcv(2, 512, seed=21)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(fast=jnp.array([3., 6.]),
                              slow=jnp.array([12., 24.]))
    train, test = 128, 64
    res = walkforward.walk_forward(panel, get_strategy("sma_crossover"), grid,
                                   train=train, test=test, cost=cost)
    pos = np.asarray(res.oos_positions, np.float64)   # (tickers, W*test)
    close = np.asarray(panel.close, np.float64)
    W = (512 - train) // test
    # Global bar index of each stitched OOS bar: window w spans
    # [w*test + train, w*test + train + test).
    idx = np.concatenate(
        [np.arange(w * test + train, w * test + train + test)
         for w in range(W)])
    r = close[:, idx] / close[:, idx - 1] - 1.0       # per-bar simple returns
    prev = np.concatenate([np.zeros((2, 1)), pos[:, :-1]], axis=1)
    want = prev * r - cost * np.abs(pos - prev)
    np.testing.assert_allclose(np.asarray(res.oos_returns), want,
                               rtol=1e-4, atol=1e-6)


def test_chunked_pairs_sweep_matches_full():
    from distributed_backtesting_exploration_tpu.models import pairs as pm

    rng = np.random.default_rng(17)
    T, n_pairs = 200, 3
    x = np.cumsum(rng.standard_normal((n_pairs, T)) * 0.5, axis=1) + 100
    y = 1.3 * x + rng.standard_normal((n_pairs, T)) * 2.0
    yj, xj = jnp.asarray(y, jnp.float32), jnp.asarray(x, jnp.float32)
    grid = sweep.product_grid(lookback=jnp.array([20., 30.]),
                              z_entry=jnp.array([1.0, 1.5, 2.0]))
    ref = pm.run_pairs_sweep(yj, xj, grid, cost=1e-3)
    got = pm.chunked_pairs_sweep(yj, xj, grid, param_chunk=3, cost=1e-3)
    for name in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            rtol=1e-6, atol=1e-7, err_msg=name)
    with pytest.raises(ValueError, match="divisible"):
        pm.chunked_pairs_sweep(yj, xj, grid, param_chunk=4)


def test_walk_forward_fused_matches_generic():
    """walk_forward_fused (fused train sweep + chosen-param repricing) must
    reproduce walk_forward wherever the train argmax agrees — on CPU
    interpret mode that is everywhere for this grid/seed."""
    import functools

    import numpy as np

    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.ops import fused
    from distributed_backtesting_exploration_tpu.parallel import (
        sweep, walkforward)
    from distributed_backtesting_exploration_tpu.utils import data

    ohlcv = data.synthetic_ohlcv(4, 260, seed=21)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(fast=jnp.asarray([3.0, 5.0], jnp.float32),
                              slow=jnp.asarray([13.0, 21.0], jnp.float32))
    strat = base.get_strategy("sma_crossover")
    train, test = 120, 40

    want = walkforward.walk_forward(panel, strat, grid, train=train,
                                    test=test, cost=1e-3)
    fa, sl = np.asarray(grid["fast"]), np.asarray(grid["slow"])
    got = walkforward.walk_forward_fused(
        panel, strat, grid,
        functools.partial(fused.fused_sma_sweep, fast=fa, slow=sl,
                          cost=1e-3),
        train=train, test=test, cost=1e-3)

    # Chosen params should agree (knife-edge argmax ties could differ on
    # TPU; on CPU interpret mode the train metrics match tightly).
    for k in grid:
        np.testing.assert_array_equal(np.asarray(got.chosen[k]),
                                      np.asarray(want.chosen[k]), err_msg=k)
    np.testing.assert_allclose(np.asarray(got.oos_returns),
                               np.asarray(want.oos_returns),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got.oos_positions),
                               np.asarray(want.oos_positions),
                               rtol=0, atol=0)
    for name in want.oos_metrics._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got.oos_metrics, name)),
            np.asarray(getattr(want.oos_metrics, name)),
            rtol=1e-4, atol=1e-5, err_msg=name)
    np.testing.assert_allclose(np.asarray(got.train_metric),
                               np.asarray(want.train_metric),
                               rtol=2e-4, atol=2e-5)


def test_walk_forward_pairs_matches_manual_windows():
    """walk_forward_pairs == a hand-rolled loop: per window, argmax the
    train metrics from run_pairs_sweep on the TRAIN slice, reprice the
    winner over the span with pair_backtest internals, stitch with the
    re-hedged boundary adjustment."""
    from distributed_backtesting_exploration_tpu.models import pairs
    from distributed_backtesting_exploration_tpu.ops import (
        metrics as metrics_mod, pnl)
    from distributed_backtesting_exploration_tpu.parallel import (
        sweep, walkforward)
    from distributed_backtesting_exploration_tpu.utils import data

    n_pairs, T, train, test = 3, 240, 120, 40
    cost = 1e-3
    ohlcv = data.synthetic_ohlcv(2 * n_pairs, T, seed=17)
    y = jnp.asarray(ohlcv.close[:n_pairs])
    x = jnp.asarray(ohlcv.close[n_pairs:])
    grid = sweep.product_grid(
        lookback=jnp.asarray([8.0, 12.0], jnp.float32),
        z_entry=jnp.asarray([0.8, 1.5], jnp.float32))

    got = walkforward.walk_forward_pairs(y, x, dict(grid), train=train,
                                         test=test, cost=cost)

    # Manual reference via independent library paths.
    starts = np.asarray(walkforward.window_starts(T, train, test))
    P = sweep.grid_size(grid)
    all_r, all_p = [], []
    prev_deployed = np.zeros(n_pairs, np.float32)
    for s0 in starts:
        tm = pairs.run_pairs_sweep(y[:, s0:s0 + train], x[:, s0:s0 + train],
                                   dict(grid), cost=cost)
        best = np.argmax(np.asarray(tm.sharpe), axis=1)       # (n_pairs,)
        np.testing.assert_array_equal(
            np.asarray(got.chosen["lookback"])[:, list(starts).index(s0)],
            np.asarray(grid["lookback"])[best])
        for i in range(n_pairs):
            p1 = {k: jnp.asarray(v)[best[i]] for k, v in grid.items()}
            y1 = y[i, s0:s0 + train + test]
            x1 = x[i, s0:s0 + train + test]
            pos, beta = pairs.pairs_positions(y1, x1, p1)
            pos, beta = np.asarray(pos), np.asarray(beta)
            ry = np.asarray(pnl.simple_returns(y1))
            rx = np.asarray(pnl.simple_returns(x1))
            prev_pos = np.concatenate([[0.0], pos[:-1]])
            prev_beta = np.concatenate([[0.0], beta[:-1]])
            gross = 1.0 + np.abs(prev_beta)
            hr = (ry - prev_beta * rx) / np.maximum(gross, 1.0)
            net = (prev_pos * hr
                   - cost * np.abs(pos - prev_pos)).astype(np.float32)
            oos = net[train:].copy()
            # Boundary: swap the window's own prev-in for the deployed one.
            first, prev_in = pos[train], pos[train - 1]
            oos[0] += ((prev_deployed[i] - prev_in) * hr[train]
                       - cost * (abs(first - prev_deployed[i])
                                 - abs(first - prev_in)))
            all_r.append((i, oos))
            all_p.append((i, pos[train:]))
            prev_deployed[i] = pos[-1]
    want_r = np.stack([np.concatenate([r for j, r in all_r if j == i])
                       for i in range(n_pairs)])
    np.testing.assert_allclose(np.asarray(got.oos_returns), want_r,
                               rtol=2e-4, atol=2e-5)
    eq = 1.0 + np.cumsum(want_r, axis=-1)
    want_p = np.stack([np.concatenate([p for j, p in all_p if j == i])
                       for i in range(n_pairs)])
    want_m = metrics_mod.summary_metrics(
        jnp.asarray(want_r), jnp.asarray(eq), jnp.asarray(want_p))
    for name in want_m._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got.oos_metrics, name)),
            np.asarray(getattr(want_m, name)), rtol=2e-3, atol=2e-4,
            err_msg=name)
