"""Fused Pallas sweep kernel vs the generic sweep path (golden equality).

On CPU the kernel runs in interpret mode and must match the generic
jit+vmap path to float32 tolerance for every metric, including the
unaligned-T padding path and non-square grids.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.models.base import get_strategy
from distributed_backtesting_exploration_tpu.ops import fused
from distributed_backtesting_exploration_tpu.parallel import sweep
from distributed_backtesting_exploration_tpu.utils import data


def _check(n_tickers, T, fast_axis, slow_axis, cost=1e-3, seed=0):
    ohlcv = data.synthetic_ohlcv(n_tickers, T, seed=seed)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(fast=jnp.asarray(fast_axis, jnp.float32),
                              slow=jnp.asarray(slow_axis, jnp.float32))
    ref = sweep.jit_sweep(panel, get_strategy("sma_crossover"), dict(grid),
                          cost=cost)
    got = fused.fused_sma_sweep(
        panel.close, np.asarray(grid["fast"]), np.asarray(grid["slow"]),
        cost=cost)
    for name in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


def test_fused_matches_generic_small():
    _check(3, 200, [3, 5, 8], [13, 21])


def test_fused_matches_generic_unaligned_T():
    # T=251 pads to 256: padded bars must not alter any metric.
    _check(2, 251, [4, 6], [17, 29], seed=3)


def test_fused_matches_generic_wide_grid():
    # More params than one 128-lane block; shared windows across combos.
    _check(2, 320, list(range(3, 14)), list(range(20, 44, 2)), seed=5)


def test_fused_single_param():
    _check(1, 137, [5], [20], seed=7)


def test_fused_zero_cost():
    _check(2, 200, [3, 7], [15, 31], cost=0.0, seed=9)


def test_fused_rejects_non_integer_windows():
    with pytest.raises(ValueError, match="integral"):
        fused.fused_sma_sweep(
            jnp.ones((1, 64)), np.asarray([3.5]), np.asarray([10.0]))


def test_fused_inline_table_matches_hbm_table():
    # The in-kernel (VMEM-scratch) table build must be BIT-identical to the
    # XLA-built HBM table path — same op sequence per row, wrapped rotate
    # lanes zeroed like _shift_t's fill (ops/fused.py `_kernel_inline`).
    ohlcv = data.synthetic_ohlcv(3, 300, seed=11)
    close = jnp.asarray(ohlcv.close)
    grid = sweep.product_grid(fast=jnp.asarray([3, 5, 8], jnp.float32),
                              slow=jnp.asarray([13, 21, 34], jnp.float32))
    fa, sl = np.asarray(grid["fast"]), np.asarray(grid["slow"])
    a = fused.fused_sma_sweep(close, fa, sl, cost=1e-3, table="hbm")
    b = fused.fused_sma_sweep(close, fa, sl, cost=1e-3, table="inline")
    for name in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name)),
            err_msg=name)


def test_fused_inline_table_multi_block_scratch_persists():
    # 25x25 = 625 combos -> P_pad 640 -> lanes 128 -> n_blocks = 5: the
    # VMEM-scratch table is built at param-block j == 0 only and must
    # still be live (and correct) for j = 1..4. A stale/garbage scratch
    # would corrupt every combo beyond the first 128 lanes while the
    # single-block tests stay green.
    ohlcv = data.synthetic_ohlcv(2, 220, seed=13)
    close = jnp.asarray(ohlcv.close)
    grid = sweep.product_grid(
        fast=jnp.arange(3, 28, dtype=jnp.float32),
        slow=jnp.arange(30, 80, 2, dtype=jnp.float32))
    fa, sl = np.asarray(grid["fast"]), np.asarray(grid["slow"])
    assert fa.size == 625
    a = fused.fused_sma_sweep(close, fa, sl, cost=1e-3, table="hbm")
    b = fused.fused_sma_sweep(close, fa, sl, cost=1e-3, table="inline")
    for name in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name)),
            err_msg=name)


def test_fused_inline_table_matches_hbm_table_ragged():
    ohlcv = data.synthetic_ohlcv(3, 300, seed=12)
    close = jnp.asarray(ohlcv.close)
    t_real = np.asarray([300, 251, 170], np.int32)
    fa = np.asarray([4.0, 6.0], np.float32)
    sl = np.asarray([17.0, 29.0], np.float32)
    a = fused.fused_sma_sweep(close, fa, sl, t_real=t_real, cost=1e-3,
                              table="hbm")
    b = fused.fused_sma_sweep(close, fa, sl, t_real=t_real, cost=1e-3,
                              table="inline")
    for name in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name)),
            err_msg=name)


def test_fused_rejects_unknown_table_mode():
    with pytest.raises(ValueError, match="table"):
        fused.fused_sma_sweep(
            jnp.ones((1, 64)), np.asarray([3.0]), np.asarray([10.0]),
            table="nope")


def test_momentum_donchian_inline_tables_match_hbm():
    # The momentum past-close and Donchian breakout-sign in-kernel tables
    # involve no arithmetic (rotate / max / compare of raw prices), so
    # unlike the SMA inline table they must be bit-identical to the
    # XLA-table substrate on EVERY backend. 300 params -> P_pad 384 ->
    # 128-lane blocks x 3 for every cap, so the scratch-persistence
    # window (blocks j > 0 reading the table built at j == 0) is
    # exercised for all three inline kernels.
    ohlcv = data.synthetic_ohlcv(3, 300, seed=21)
    close = jnp.asarray(ohlcv.close)
    high = jnp.asarray(ohlcv.high)
    low = jnp.asarray(ohlcv.low)
    lb = np.linspace(4, 90, 300).round().astype(np.float32)
    assert lb.size == 300 and -(-lb.size // 128) * 128 == 384
    cases = [
        ("momentum", lambda m: fused.fused_momentum_sweep(
            close, lb, cost=1e-3, table=m)),
        ("donchian", lambda m: fused.fused_donchian_sweep(
            close, lb, cost=1e-3, table=m)),
        ("donchian_hl", lambda m: fused.fused_donchian_hl_sweep(
            close, high, low, lb, cost=1e-3, table=m)),
    ]
    for name, mk in cases:
        a, b = mk("hbm"), mk("inline")
        for field in a._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(a, field)), np.asarray(getattr(b, field)),
                err_msg=f"{name}.{field}")


def test_obv_inline_table_matches_hbm():
    # SMA-of-OBV table built in VMEM scratch (`_obv_kernel_inline`) vs the
    # W-major XLA table: bit-identical on CPU (the on-TPU 1-ULP division
    # caveat is the SMA inline substrate's, gated by bench --verify).
    # 300 params -> P_pad 384 -> 3 blocks: covers scratch persistence.
    ohlcv = data.synthetic_ohlcv(3, 300, seed=23)
    w = np.linspace(5, 90, 300).round().astype(np.float32)
    a = fused.fused_obv_sweep(ohlcv.close, ohlcv.volume, w, cost=1e-3,
                              table="hbm")
    b = fused.fused_obv_sweep(ohlcv.close, ohlcv.volume, w, cost=1e-3,
                              table="inline")
    for field in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, field)), np.asarray(getattr(b, field)),
            err_msg=field)


def test_bollinger_inline_ztable_matches_hbm():
    # The in-kernel z-table build (`_band_kernel_inline` /
    # `_build_boll_z_scratch`) vs the XLA-built z-table, both machines:
    # bit-identical on CPU (the on-TPU 1-ULP div/sqrt caveat is gated by
    # bench --verify). window axis deliberately sized so W_pad (8-row
    # sublane padding) EXCEEDS the distinct-window count — the scratch pad
    # rows must be zeroed, not left as garbage VMEM (a NaN there survives
    # the 0-weight one-hot contraction and silently flattens positions).
    ohlcv = data.synthetic_ohlcv(3, 300, seed=29)
    close = jnp.asarray(ohlcv.close)
    grid = sweep.product_grid(
        window=jnp.asarray([10, 17, 26], jnp.float32),
        k=jnp.asarray([0.8, 1.5, 2.2], jnp.float32))
    w, k = np.asarray(grid["window"]), np.asarray(grid["k"])
    cases = [
        ("bollinger", lambda m: fused.fused_bollinger_sweep(
            close, w, k, cost=1e-3, table=m)),
        ("bollinger_touch", lambda m: fused.fused_bollinger_touch_sweep(
            close, w, k, cost=1e-3, table=m)),
    ]
    for name, mk in cases:
        a, b = mk("hbm"), mk("inline")
        for field in a._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(a, field)), np.asarray(getattr(b, field)),
                err_msg=f"{name}.{field}")


def test_bollinger_inline_ztable_multi_block_ragged():
    # 25 windows x 24 k = 600 combos -> P_pad 640 -> 128-lane blocks x 5:
    # scratch persistence across param blocks, plus per-ticker lengths.
    ohlcv = data.synthetic_ohlcv(3, 300, seed=31)
    close = jnp.asarray(ohlcv.close)
    t_real = np.asarray([300, 254, 147], np.int32)
    grid = sweep.product_grid(
        window=jnp.arange(10, 60, 2, dtype=jnp.float32),
        k=jnp.linspace(0.5, 3.0, 24).astype(jnp.float32))
    w, k = np.asarray(grid["window"]), np.asarray(grid["k"])
    for machine, fn in (("bollinger", fused.fused_bollinger_sweep),
                        ("bollinger_touch",
                         fused.fused_bollinger_touch_sweep)):
        a = fn(close, w, k, t_real=t_real, cost=1e-3, table="hbm")
        b = fn(close, w, k, t_real=t_real, cost=1e-3, table="inline")
        for field in a._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(a, field)), np.asarray(getattr(b, field)),
                err_msg=f"{machine}.{field}")


def test_momentum_inline_table_ragged_matches_hbm():
    ohlcv = data.synthetic_ohlcv(3, 300, seed=22)
    close = jnp.asarray(ohlcv.close)
    t_real = np.asarray([300, 240, 130], np.int32)
    lb = np.asarray([5.0, 20.0, 63.0], np.float32)
    a = fused.fused_momentum_sweep(close, lb, t_real=t_real, cost=1e-3,
                                   table="hbm")
    b = fused.fused_momentum_sweep(close, lb, t_real=t_real, cost=1e-3,
                                   table="inline")
    for field in a._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, field)), np.asarray(getattr(b, field)),
            err_msg=field)


def _check_boll(n_tickers, T, window_axis, k_axis, cost=1e-3, seed=0,
                z_exit=0.0):
    ohlcv = data.synthetic_ohlcv(n_tickers, T, seed=seed)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(k=jnp.asarray(k_axis, jnp.float32),
                              window=jnp.asarray(window_axis, jnp.float32))
    ref = sweep.jit_sweep(panel, get_strategy("bollinger"), dict(grid),
                          cost=cost)
    got = fused.fused_bollinger_sweep(
        panel.close, np.asarray(grid["window"]), np.asarray(grid["k"]),
        cost=cost, z_exit=z_exit)
    for name in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


def test_fused_bollinger_matches_generic_small():
    _check_boll(3, 200, [10, 20, 30], [0.5, 1.0, 2.0])


def test_fused_bollinger_unaligned_T():
    _check_boll(2, 251, [8, 16], [1.0, 1.5], seed=3)


def test_fused_bollinger_wide_grid():
    # More params than one 128-lane block; shared windows across combos.
    _check_boll(2, 320, list(range(5, 16)), [0.5, 1.0, 1.5, 2.0, 2.5, 3.0],
                seed=5)


def test_fused_bollinger_single_param():
    _check_boll(1, 137, [12], [1.5], seed=7)


def test_fused_bollinger_zero_cost():
    _check_boll(2, 200, [10, 25], [1.0, 2.0], cost=0.0, seed=9)


def test_fused_bollinger_rejects_non_integer_windows():
    with pytest.raises(ValueError, match="integral"):
        fused.fused_bollinger_sweep(
            jnp.ones((1, 64)), np.asarray([10.5]), np.asarray([1.0]))


def _check_ragged(strategy, fused_fn, axes, lengths, cost=1e-3, seed=0):
    """Fused with per-ticker t_real vs the generic ragged path
    (pad_and_stack + bar_mask)."""
    series = []
    for i, T in enumerate(lengths):
        one = data.synthetic_ohlcv(1, T, seed=seed + i)
        series.append(type(one)(*(f[0] for f in one)))
    batch, lens, mask = data.pad_and_stack(series)
    panel = type(batch)(*(jnp.asarray(f) for f in batch))
    grid = sweep.product_grid(**axes)
    ref = sweep.jit_sweep(panel, get_strategy(strategy), dict(grid),
                          cost=cost, bar_mask=jnp.asarray(mask))
    got = fused_fn(batch.close, grid, lens)
    for name in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


def test_fused_sma_ragged_lengths():
    _check_ragged(
        "sma_crossover",
        lambda close, g, lens: fused.fused_sma_sweep(
            close, np.asarray(g["fast"]), np.asarray(g["slow"]),
            t_real=lens, cost=1e-3),
        dict(fast=jnp.asarray([3, 5, 8], jnp.float32),
             slow=jnp.asarray([13, 21], jnp.float32)),
        lengths=[150, 200, 97, 200])


def test_fused_bollinger_ragged_lengths():
    _check_ragged(
        "bollinger",
        lambda close, g, lens: fused.fused_bollinger_sweep(
            close, np.asarray(g["window"]), np.asarray(g["k"]),
            t_real=lens, cost=1e-3),
        dict(window=jnp.asarray([10, 20], jnp.float32),
             k=jnp.asarray([1.0, 2.0], jnp.float32)),
        lengths=[180, 131, 256], seed=11)


def test_fused_uniform_t_real_matches_default():
    # An explicit full-length t_real routes through the dynamic-length
    # kernel; it must agree with the static fast path to float noise
    # (ulp-level fusion differences only).
    ohlcv = data.synthetic_ohlcv(2, 100, seed=3)
    close = jnp.asarray(ohlcv.close)
    fa, sl = np.asarray([3.0, 5.0]), np.asarray([11.0, 17.0])
    a = fused.fused_sma_sweep(close, fa, sl, cost=1e-3)
    b = fused.fused_sma_sweep(close, fa, sl, t_real=np.asarray([100, 100]),
                              cost=1e-3)
    for name in a._fields:
        np.testing.assert_allclose(np.asarray(getattr(a, name)),
                                   np.asarray(getattr(b, name)),
                                   rtol=1e-5, atol=1e-6, err_msg=name)


def test_padding_invariance_property():
    """Appending pad bars (repeat-last close + t_real) must not change any
    metric — the padding-discipline invariant every kernel's correctness
    rests on, asserted directly rather than only via generic-path parity."""
    ohlcv = data.synthetic_ohlcv(3, 150, seed=13)
    close = np.asarray(ohlcv.close)
    padded = np.concatenate(
        [close, np.repeat(close[:, -1:], 37, axis=1)], axis=1)
    t_real = np.full(3, 150, np.int32)

    fa, sl = np.asarray([4.0, 7.0]), np.asarray([15.0, 25.0])
    a = fused.fused_sma_sweep(close, fa, sl, t_real=t_real, cost=1e-3)
    b = fused.fused_sma_sweep(padded, fa, sl, t_real=t_real, cost=1e-3)
    for name in a._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name)),
            rtol=1e-5, atol=1e-6, err_msg=f"sma/{name}")

    w, k = np.asarray([10.0, 20.0]), np.asarray([1.0, 2.0])
    a = fused.fused_bollinger_sweep(close, w, k, t_real=t_real, cost=1e-3)
    b = fused.fused_bollinger_sweep(padded, w, k, t_real=t_real, cost=1e-3)
    for name in a._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(a, name)), np.asarray(getattr(b, name)),
            rtol=1e-5, atol=1e-6, err_msg=f"boll/{name}")


def _check_pairs(n_pairs, T, lookback_axis, z_entry_axis, cost=1e-3, seed=0,
                 z_exit=None):
    from distributed_backtesting_exploration_tpu.models import pairs

    ohlcv = data.synthetic_ohlcv(2 * n_pairs, T, seed=seed)
    closes = jnp.asarray(ohlcv.close)
    y_close, x_close = closes[:n_pairs], closes[n_pairs:]
    axes = dict(lookback=jnp.asarray(lookback_axis, jnp.float32),
                z_entry=jnp.asarray(z_entry_axis, jnp.float32))
    if z_exit is not None:
        axes["z_exit"] = jnp.asarray(z_exit, jnp.float32)
    grid = sweep.product_grid(**axes)
    ref = pairs.run_pairs_sweep(y_close, x_close, dict(grid), cost=cost)
    got = fused.fused_pairs_sweep(
        y_close, x_close, np.asarray(grid["lookback"]),
        np.asarray(grid["z_entry"]),
        z_exit=np.asarray(grid["z_exit"]) if z_exit is not None else 0.0,
        cost=cost)
    # The fused prep computes windowed sums as banded-matrix tree sums (MXU);
    # the generic path differences a cumsum. Both are valid f32 evaluations,
    # so z-scores differ by ~1e-6 — which (a) loosens per-metric tolerances
    # vs the single-asset kernels and (b) can flip a knife-edge band entry,
    # diverging that cell's whole position path. Flips must stay rare
    # (<= 1% of cells); non-flipped cells must match tightly.
    # A flipped cell shows a *gross* mismatch in at least one metric (a
    # diverged path can coincidentally preserve, say, total turnover, so no
    # single field is a reliable detector — union them).
    flipped = np.zeros_like(np.asarray(got.turnover), dtype=bool)
    for name in ref._fields:
        a, b = np.asarray(getattr(got, name)), np.asarray(getattr(ref, name))
        flipped |= np.abs(a - b) > (0.01 + 0.01 * np.abs(b))
    n_flips = int(flipped.sum())
    assert n_flips <= max(1, int(0.01 * flipped.size)), (
        f"{n_flips}/{flipped.size} position-path flips")
    for name in ref._fields:
        a = np.asarray(getattr(got, name))[~flipped]
        b = np.asarray(getattr(ref, name))[~flipped]
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4, err_msg=name)


def test_fused_pairs_matches_generic_small():
    _check_pairs(3, 200, [10, 20, 30], [0.5, 1.0, 2.0])


def test_fused_pairs_unaligned_T():
    # T=251 pads to 256: padded bars must not alter any metric.
    _check_pairs(2, 251, [8, 16], [1.0, 1.5], seed=3)


def test_fused_pairs_wide_grid():
    # More params than one 128-lane block; shared lookbacks across combos.
    _check_pairs(2, 320, list(range(5, 16)),
                 [0.5, 1.0, 1.5, 2.0, 2.5, 3.0, 0.8, 1.2, 1.8, 2.2, 2.8, 0.6],
                 seed=5)


def test_fused_pairs_single_param():
    _check_pairs(1, 137, [12], [1.5], seed=7)


def test_fused_pairs_zero_cost():
    _check_pairs(2, 200, [10, 25], [1.0, 2.0], cost=0.0, seed=9)


def test_fused_pairs_per_lane_z_exit():
    # z_exit in the grid: each lane carries its own exit band.
    _check_pairs(2, 200, [10, 20], [1.0, 2.0], z_exit=[0.0, 0.5], seed=11)


def test_fused_pairs_rejects_non_integer_lookbacks():
    with pytest.raises(ValueError, match="integral"):
        fused.fused_pairs_sweep(
            jnp.ones((1, 64)), jnp.ones((1, 64)),
            np.asarray([10.5]), np.asarray([1.0]))


def _check_single_axis(strategy, fused_fn, axis_name, axis_vals, n_tickers=3,
                       T=200, cost=1e-3, seed=0):
    ohlcv = data.synthetic_ohlcv(n_tickers, T, seed=seed)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(
        **{axis_name: jnp.asarray(axis_vals, jnp.float32)})
    ref = sweep.jit_sweep(panel, get_strategy(strategy), dict(grid),
                          cost=cost)
    got = fused_fn(panel.close, np.asarray(grid[axis_name]), cost=cost)
    for name in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


def test_fused_momentum_matches_generic():
    _check_single_axis("momentum", fused.fused_momentum_sweep, "lookback",
                       [5, 10, 21, 63])


def test_fused_momentum_unaligned_T():
    _check_single_axis("momentum", fused.fused_momentum_sweep, "lookback",
                       [8, 13], T=251, seed=3)


def test_fused_donchian_matches_generic():
    _check_single_axis("donchian", fused.fused_donchian_sweep, "window",
                       [10, 20, 55], seed=5)


def test_fused_donchian_unaligned_T():
    _check_single_axis("donchian", fused.fused_donchian_sweep, "window",
                       [15, 30], T=251, seed=7)


def test_fused_momentum_donchian_ragged():
    series = []
    for i, T in enumerate([150, 200, 97]):
        one = data.synthetic_ohlcv(1, T, seed=20 + i)
        series.append(type(one)(*(f[0] for f in one)))
    batch, lens, mask = data.pad_and_stack(series)
    panel = type(batch)(*(jnp.asarray(f) for f in batch))
    for strategy, fused_fn, axis in (
            ("momentum", fused.fused_momentum_sweep, "lookback"),
            ("donchian", fused.fused_donchian_sweep, "window")):
        grid = sweep.product_grid(
            **{axis: jnp.asarray([10.0, 20.0], jnp.float32)})
        ref = sweep.jit_sweep(panel, get_strategy(strategy), dict(grid),
                              cost=1e-3, bar_mask=jnp.asarray(mask))
        got = fused_fn(batch.close, np.asarray(grid[axis]), t_real=lens,
                       cost=1e-3)
        for name in ref._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(ref, name)),
                rtol=2e-4, atol=2e-5, err_msg=f"{strategy}/{name}")


def test_fused_momentum_rejects_non_integer_lookbacks():
    with pytest.raises(ValueError, match="integral"):
        fused.fused_momentum_sweep(jnp.ones((1, 64)), np.asarray([10.5]))


def test_fused_rsi_matches_generic():
    ohlcv = data.synthetic_ohlcv(3, 200, seed=17)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(
        period=jnp.asarray([7.0, 14.0, 21.0], jnp.float32),
        band=jnp.asarray([15.0, 20.0, 25.0], jnp.float32))
    ref = sweep.jit_sweep(panel, get_strategy("rsi"), dict(grid), cost=1e-3)
    got = fused.fused_rsi_sweep(panel.close, np.asarray(grid["period"]),
                                np.asarray(grid["band"]), cost=1e-3)
    for name in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


def test_fused_rsi_ragged():
    series = []
    for i, T in enumerate([150, 200, 97]):
        one = data.synthetic_ohlcv(1, T, seed=30 + i)
        series.append(type(one)(*(f[0] for f in one)))
    batch, lens, mask = data.pad_and_stack(series)
    panel = type(batch)(*(jnp.asarray(f) for f in batch))
    grid = sweep.product_grid(period=jnp.asarray([10.0, 14.0], jnp.float32),
                              band=jnp.asarray([20.0], jnp.float32))
    ref = sweep.jit_sweep(panel, get_strategy("rsi"), dict(grid), cost=1e-3,
                          bar_mask=jnp.asarray(mask))
    got = fused.fused_rsi_sweep(batch.close, np.asarray(grid["period"]),
                                np.asarray(grid["band"]), t_real=lens,
                                cost=1e-3)
    for name in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


def _macd_flip_aware_check(got, ref):
    # The in-kernel signal-EMA ladder rounds differently from XLA's
    # associative_scan, so a knife-edge macd/signal crossing can resolve
    # differently and diverge that cell's path; require such flips rare and
    # everything else tight (same discipline as the pairs kernel).
    flipped = np.zeros_like(np.asarray(got.turnover), dtype=bool)
    for name in ref._fields:
        a, b = np.asarray(getattr(got, name)), np.asarray(getattr(ref, name))
        flipped |= np.abs(a - b) > (0.01 + 0.01 * np.abs(b))
    assert int(flipped.sum()) <= max(1, int(0.01 * flipped.size)), (
        f"{int(flipped.sum())}/{flipped.size} flips")
    for name in ref._fields:
        a = np.asarray(getattr(got, name))[~flipped]
        b = np.asarray(getattr(ref, name))[~flipped]
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4, err_msg=name)


def test_fused_macd_matches_generic():
    ohlcv = data.synthetic_ohlcv(3, 200, seed=19)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(
        fast=jnp.asarray([8.0, 12.0], jnp.float32),
        slow=jnp.asarray([26.0, 35.0], jnp.float32),
        signal=jnp.asarray([9.0, 5.0], jnp.float32))
    ref = sweep.jit_sweep(panel, get_strategy("macd"), dict(grid), cost=1e-3)
    got = fused.fused_macd_sweep(
        panel.close, np.asarray(grid["fast"]), np.asarray(grid["slow"]),
        np.asarray(grid["signal"]), cost=1e-3)
    _macd_flip_aware_check(got, ref)


def test_fused_macd_ragged():
    series = []
    for i, T in enumerate([150, 200, 97]):
        one = data.synthetic_ohlcv(1, T, seed=40 + i)
        series.append(type(one)(*(f[0] for f in one)))
    batch, lens, mask = data.pad_and_stack(series)
    panel = type(batch)(*(jnp.asarray(f) for f in batch))
    grid = sweep.product_grid(
        fast=jnp.asarray([8.0, 12.0], jnp.float32),
        slow=jnp.asarray([26.0], jnp.float32),
        signal=jnp.asarray([9.0], jnp.float32))
    ref = sweep.jit_sweep(panel, get_strategy("macd"), dict(grid), cost=1e-3,
                          bar_mask=jnp.asarray(mask))
    got = fused.fused_macd_sweep(
        batch.close, np.asarray(grid["fast"]), np.asarray(grid["slow"]),
        np.asarray(grid["signal"]), t_real=lens, cost=1e-3)
    _macd_flip_aware_check(got, ref)


def test_fused_trix_matches_generic():
    ohlcv = data.synthetic_ohlcv(3, 200, seed=23)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(
        span=jnp.asarray([5.0, 9.0, 15.0], jnp.float32),
        signal=jnp.asarray([4.0, 9.0], jnp.float32))
    ref = sweep.jit_sweep(panel, get_strategy("trix"), dict(grid), cost=1e-3)
    got = fused.fused_trix_sweep(
        panel.close, np.asarray(grid["span"]), np.asarray(grid["signal"]),
        cost=1e-3)
    _macd_flip_aware_check(got, ref)


def test_fused_trix_ragged():
    series = []
    for i, T in enumerate([150, 200, 97]):
        one = data.synthetic_ohlcv(1, T, seed=60 + i)
        series.append(type(one)(*(f[0] for f in one)))
    batch, lens, mask = data.pad_and_stack(series)
    panel = type(batch)(*(jnp.asarray(f) for f in batch))
    grid = sweep.product_grid(
        span=jnp.asarray([5.0, 9.0], jnp.float32),
        signal=jnp.asarray([4.0], jnp.float32))
    ref = sweep.jit_sweep(panel, get_strategy("trix"), dict(grid), cost=1e-3,
                          bar_mask=jnp.asarray(mask))
    got = fused.fused_trix_sweep(
        batch.close, np.asarray(grid["span"]), np.asarray(grid["signal"]),
        t_real=lens, cost=1e-3)
    _macd_flip_aware_check(got, ref)


def test_fused_trix_rejects_non_integer_spans():
    with pytest.raises(ValueError, match="integral"):
        fused.fused_trix_sweep(
            jnp.ones((1, 64)), np.asarray([10.5]), np.asarray([4.0]))


def _check_panel_sweep(strategy, fused_call, grid_axes, n_tickers=3, T=200,
                       cost=1e-3, seed=0, rtol=2e-4, atol=2e-5):
    """Generic-vs-fused parity for strategies consuming non-close columns:
    the fused callable receives the full panel + materialized grid."""
    ohlcv = data.synthetic_ohlcv(n_tickers, T, seed=seed)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(**grid_axes)
    ref = sweep.jit_sweep(panel, get_strategy(strategy), dict(grid),
                          cost=cost)
    got = fused_call(panel, grid, None)
    for name in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            rtol=rtol, atol=atol, err_msg=name)


def _check_panel_ragged(strategy, fused_call, grid_axes, lengths, cost=1e-3,
                        seed=0):
    series = []
    for i, T in enumerate(lengths):
        one = data.synthetic_ohlcv(1, T, seed=seed + i)
        series.append(type(one)(*(f[0] for f in one)))
    batch, lens, mask = data.pad_and_stack(series)
    panel = type(batch)(*(jnp.asarray(f) for f in batch))
    grid = sweep.product_grid(**grid_axes)
    ref = sweep.jit_sweep(panel, get_strategy(strategy), dict(grid),
                          cost=cost, bar_mask=jnp.asarray(mask))
    got = fused_call(panel, grid, lens)
    for name in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


def _don_hl_call(panel, grid, lens):
    return fused.fused_donchian_hl_sweep(
        panel.close, panel.high, panel.low, np.asarray(grid["window"]),
        t_real=lens, cost=1e-3)


def _vwap_call(panel, grid, lens):
    return fused.fused_vwap_sweep(
        panel.close, panel.volume, np.asarray(grid["window"]),
        np.asarray(grid["k"]), t_real=lens, cost=1e-3)


def test_fused_donchian_hl_matches_generic():
    _check_panel_sweep(
        "donchian_hl", _don_hl_call,
        dict(window=jnp.asarray([10, 20, 55], jnp.float32)), seed=5)


def test_fused_donchian_hl_unaligned_T():
    _check_panel_sweep(
        "donchian_hl", _don_hl_call,
        dict(window=jnp.asarray([15, 30], jnp.float32)), T=251, seed=7)


def test_fused_donchian_hl_ragged():
    _check_panel_ragged(
        "donchian_hl", _don_hl_call,
        dict(window=jnp.asarray([10.0, 20.0], jnp.float32)),
        lengths=[150, 200, 97], seed=50)


def test_fused_vwap_matches_generic():
    _check_panel_sweep(
        "vwap_reversion", _vwap_call,
        dict(window=jnp.asarray([10, 20, 30], jnp.float32),
             k=jnp.asarray([0.5, 1.0, 2.0], jnp.float32)), seed=13)


def test_fused_vwap_unaligned_T():
    _check_panel_sweep(
        "vwap_reversion", _vwap_call,
        dict(window=jnp.asarray([8, 16], jnp.float32),
             k=jnp.asarray([1.0, 1.5], jnp.float32)), T=251, seed=15)


def test_fused_vwap_ragged():
    _check_panel_ragged(
        "vwap_reversion", _vwap_call,
        dict(window=jnp.asarray([10.0, 20.0], jnp.float32),
             k=jnp.asarray([1.0, 2.0], jnp.float32)),
        lengths=[180, 131, 256], seed=60)


def _obv_call(panel, grid, lens):
    return fused.fused_obv_sweep(
        panel.close, panel.volume, np.asarray(grid["window"]),
        t_real=lens, cost=1e-3)


def test_fused_obv_matches_generic():
    _check_panel_sweep(
        "obv_trend", _obv_call,
        dict(window=jnp.asarray([8, 15, 30], jnp.float32)), seed=17)


def test_fused_obv_unaligned_T():
    _check_panel_sweep(
        "obv_trend", _obv_call,
        dict(window=jnp.asarray([10, 21], jnp.float32)), T=251, seed=19)


def test_fused_obv_ragged():
    _check_panel_ragged(
        "obv_trend", _obv_call,
        dict(window=jnp.asarray([8.0, 20.0], jnp.float32)),
        lengths=[180, 131, 256], seed=70)


def test_fused_obv_rejects_non_integer_windows():
    with pytest.raises(ValueError, match="integral"):
        fused.fused_obv_sweep(jnp.ones((1, 64)), jnp.ones((1, 64)),
                              np.asarray([10.5]))


def test_fused_vwap_rejects_non_integer_windows():
    with pytest.raises(ValueError, match="integral"):
        fused.fused_vwap_sweep(jnp.ones((1, 64)), jnp.ones((1, 64)),
                               np.asarray([10.5]), np.asarray([1.0]))


def test_fused_vwap_window_beyond_history():
    # A window larger than the padded history must not crash the static
    # slicing in the table prep; such lanes never pass warmup, so they must
    # match the generic path's all-flat result.
    _check_panel_sweep(
        "vwap_reversion", _vwap_call,
        dict(window=jnp.asarray([10.0, 150.0], jnp.float32),
             k=jnp.asarray([1.0], jnp.float32)), T=100, seed=23)


def test_fused_donchian_window_beyond_history():
    # A window larger than the (padded) history must not crash the shared
    # sparse-table prep; such lanes never pass warmup and must match the
    # generic all-flat result (window still within the generic view bound).
    ohlcv = data.synthetic_ohlcv(2, 100, seed=31)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(window=jnp.asarray([10.0, 200.0], jnp.float32))
    for strategy, call in (
            ("donchian", lambda: fused.fused_donchian_sweep(
                panel.close, np.asarray(grid["window"]), cost=1e-3)),
            ("donchian_hl", lambda: fused.fused_donchian_hl_sweep(
                panel.close, panel.high, panel.low,
                np.asarray(grid["window"]), cost=1e-3))):
        ref = sweep.jit_sweep(panel, get_strategy(strategy), dict(grid),
                              cost=1e-3)
        got = call()
        for name in ref._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(ref, name)),
                rtol=2e-4, atol=2e-5, err_msg=f"{strategy}/{name}")


def _touch_call(panel, grid, lens):
    return fused.fused_bollinger_touch_sweep(
        panel.close, np.asarray(grid["window"]), np.asarray(grid["k"]),
        t_real=lens, cost=1e-3)


def test_fused_bollinger_touch_matches_generic():
    _check_panel_sweep(
        "bollinger_touch", _touch_call,
        dict(window=jnp.asarray([10, 20, 30], jnp.float32),
             k=jnp.asarray([0.5, 1.0, 2.0], jnp.float32)), seed=33)


def test_fused_bollinger_touch_unaligned_T():
    # Known knife-edge case (failing since seed on jax 0.4.37): at this
    # (seed, T) exactly one cell's |z| - k margin sits at ~1e-7 relative,
    # and the XLA version's different fusion of the generic path's
    # z-score resolves the touch differently — the documented MXU/fusion
    # rounding class, not a regression. Assert the flip-budget contract
    # (the `bench --verify` discipline: rare flips, everything else
    # tight) instead of demanding bit-level agreement on a razor edge.
    ohlcv = data.synthetic_ohlcv(3, 251, seed=35)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(window=jnp.asarray([8, 16], jnp.float32),
                              k=jnp.asarray([1.0, 1.5], jnp.float32))
    ref = sweep.jit_sweep(panel, get_strategy("bollinger_touch"),
                          dict(grid), cost=1e-3)
    got = _touch_call(panel, grid, None)
    _macd_flip_aware_check(got, ref)


def test_fused_bollinger_touch_ragged():
    _check_ragged(
        "bollinger_touch",
        lambda close, g, lens: fused.fused_bollinger_touch_sweep(
            close, np.asarray(g["window"]), np.asarray(g["k"]),
            t_real=lens, cost=1e-3),
        dict(window=jnp.asarray([10, 20], jnp.float32),
             k=jnp.asarray([1.0, 2.0], jnp.float32)),
        lengths=[180, 131, 256], seed=37)


def _stoch_call(panel, grid, lens):
    return fused.fused_stochastic_sweep(
        panel.close, panel.high, panel.low, np.asarray(grid["window"]),
        np.asarray(grid["band"]), t_real=lens, cost=1e-3)


def test_fused_stochastic_matches_generic():
    _check_panel_sweep(
        "stochastic", _stoch_call,
        dict(window=jnp.asarray([10, 14, 21], jnp.float32),
             band=jnp.asarray([20.0, 30.0], jnp.float32)), seed=41)


def test_fused_stochastic_unaligned_T():
    _check_panel_sweep(
        "stochastic", _stoch_call,
        dict(window=jnp.asarray([8, 16], jnp.float32),
             band=jnp.asarray([25.0], jnp.float32)), T=251, seed=43)


def test_fused_stochastic_ragged():
    _check_panel_ragged(
        "stochastic", _stoch_call,
        dict(window=jnp.asarray([10.0, 14.0], jnp.float32),
             band=jnp.asarray([20.0, 30.0], jnp.float32)),
        lengths=[150, 200, 97], seed=45)


def test_fused_stochastic_rejects_non_integer_windows():
    with pytest.raises(ValueError, match="integral"):
        fused.fused_stochastic_sweep(
            jnp.ones((1, 64)), jnp.ones((1, 64)), jnp.ones((1, 64)),
            np.asarray([10.5]), np.asarray([20.0]))


def _keltner_call(panel, grid, lens):
    return fused.fused_keltner_sweep(
        panel.close, panel.high, panel.low, np.asarray(grid["window"]),
        np.asarray(grid["k"]), t_real=lens, cost=1e-3)


def test_fused_keltner_matches_generic():
    # The in-prep EMA ladder rounds differently from the generic
    # associative_scan (the RSI/MACD caveat); loosened tolerance only.
    _check_panel_sweep(
        "keltner", _keltner_call,
        dict(window=jnp.asarray([10, 14, 21], jnp.float32),
             k=jnp.asarray([1.5, 2.5], jnp.float32)), seed=47,
        rtol=2e-3, atol=2e-4)


def test_fused_keltner_unaligned_T():
    _check_panel_sweep(
        "keltner", _keltner_call,
        dict(window=jnp.asarray([8, 16], jnp.float32),
             k=jnp.asarray([2.0], jnp.float32)), T=251, seed=49,
        rtol=2e-3, atol=2e-4)


def test_fused_keltner_rejects_non_integer_windows():
    with pytest.raises(ValueError, match="integral"):
        fused.fused_keltner_sweep(
            jnp.ones((1, 64)), jnp.ones((1, 64)), jnp.ones((1, 64)),
            np.asarray([10.5]), np.asarray([1.5]))


# ---------------------------------------------------------------------------
# DBX_LANES_CAP: validation + in-process recompile (ADVICE.md findings)
# ---------------------------------------------------------------------------

def test_lanes_cap_rejects_off_ladder_values(monkeypatch):
    """A cap below 128 (or any non-ladder value) used to fall through to
    the FULL un-blocked P_pad — the opposite of a cap. It must raise."""
    for bad in ("64", "100", "1000", "abc", "-512"):
        monkeypatch.setenv("DBX_LANES_CAP", bad)
        with pytest.raises(ValueError, match="DBX_LANES_CAP"):
            fused.resolve_lanes_cap()
        with pytest.raises(ValueError, match="DBX_LANES_CAP"):
            fused.fused_sma_sweep(
                jnp.ones((1, 64)) + jnp.arange(64.0),
                np.asarray([3.0], np.float32), np.asarray([10.0], np.float32))


def test_lanes_cap_accepts_ladder_values(monkeypatch):
    # "0" is the explicit-disable sentinel, same as unset (old behavior)
    for good, want in (("128", 128), ("256", 256), ("512", 512),
                       ("1024", 1024), ("0", 0)):
        monkeypatch.setenv("DBX_LANES_CAP", good)
        assert fused.resolve_lanes_cap() == want
    monkeypatch.delenv("DBX_LANES_CAP")
    assert fused.resolve_lanes_cap() == 0


def test_widest_lanes_env_cap_never_unblocks():
    # env cap narrows sign-kernel calls; cap <= 256 calls ignore it
    assert fused._widest_lanes(1024, 512, 1280, env_cap=256) == 256
    assert fused._widest_lanes(1024, 512, 1280, env_cap=0) == 512
    assert fused._widest_lanes(1024, 256, 1280, env_cap=512) == 256


def test_lanes_cap_change_recompiles_in_process(monkeypatch):
    """The resolved cap is a static jit argument: changing DBX_LANES_CAP
    within one process must compile a NEW kernel, not silently reuse the
    stale lane width (the in-process A/B measured nothing before)."""
    monkeypatch.delenv("DBX_LANES_CAP", raising=False)
    close = np.cumsum(np.ones((2, 64), np.float32), axis=1) + 100.0
    fast = np.asarray([3.0, 3.0], np.float32)
    slow = np.asarray([10.0, 12.0], np.float32)
    m_default = fused.fused_sma_sweep(close, fast, slow)
    n_before = fused._fused_call._cache_size()
    monkeypatch.setenv("DBX_LANES_CAP", "512")
    m_capped = fused.fused_sma_sweep(close, fast, slow)
    assert fused._fused_call._cache_size() == n_before + 1
    # identical numerics either way — the cap changes blocking, not math
    for name in m_default._fields:
        np.testing.assert_allclose(np.asarray(getattr(m_capped, name)),
                                   np.asarray(getattr(m_default, name)))
    # same setting again: cache hit, no further compile
    fused.fused_sma_sweep(close, fast, slow)
    assert fused._fused_call._cache_size() == n_before + 1
