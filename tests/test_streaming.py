"""Streaming carry checkpoints: scan-form vs recurrent-form parity.

The acceptance contract of the streaming subsystem (DESIGN.md "Streaming
backtests"): for every kernel family, (sweep@T + append@ΔT) must match
the cold sweep at T+ΔT — positions bit-identical on these fixtures (so
the count metrics turnover / n_trades / hit_rate merge bit-exactly),
moment metrics within one f32 association boundary, equity-path metrics
within the PR-3 block-association budget. Plus the checkpoint lifecycle:
serialize -> evict -> restore -> append bit-matches a never-evicted
append, and the two-level CarryStore's bounds/counters behave.

Shapes are deliberately tiny and shared across tests (the tier-1 compile
budget); T_BASE exceeds every family's tail_bars so the PARTIAL-tail
recurrent heads — the production path — are what's exercised.
"""

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.ops import fused
from distributed_backtesting_exploration_tpu.parallel.sweep import (
    product_grid)
from distributed_backtesting_exploration_tpu.streaming import (
    CarryStore, recurrent as rc)
from distributed_backtesting_exploration_tpu.utils import data

T_BASE, DT = 128, 16
T_FULL = T_BASE + DT

# Small axes (window maxes well under T_BASE) so every family's
# tail_bars < T_BASE: the append runs the partial-tail head, not the
# full-history replay.
_GRIDS = {
    "sma_crossover": dict(fast=[3.0, 5.0], slow=[10.0, 12.0]),
    "momentum": dict(lookback=[4.0, 9.0]),
    "bollinger": dict(window=[8.0, 12.0], k=[1.0, 1.5]),
    "bollinger_touch": dict(window=[8.0, 12.0], k=[1.0, 1.5]),
    "obv_trend": dict(window=[6.0, 10.0]),
    "donchian": dict(window=[6.0, 10.0]),
    "donchian_hl": dict(window=[6.0, 10.0]),
    "stochastic": dict(window=[6.0, 10.0], band=[15.0, 25.0]),
    "keltner": dict(window=[6.0, 10.0], k=[1.0, 1.5]),
    "vwap_reversion": dict(window=[5.0, 8.0], k=[1.0, 1.5]),
    "rsi": dict(period=[5.0, 8.0], band=[10.0, 20.0]),
    "macd": dict(fast=[3.0, 5.0], slow=[8.0, 12.0], signal=[4.0]),
    "trix": dict(span=[4.0, 6.0], signal=[3.0]),
    "pairs": dict(lookback=[5.0, 8.0], z_entry=[1.0, 1.5], z_exit=[0.0]),
}

_PANEL = data.synthetic_ohlcv(2, T_FULL, seed=3)
_PAIR_X = data.synthetic_ohlcv(2, T_FULL, seed=6)

# The count metrics merge bit-exactly whenever appended positions match
# the cold sweep's (values are f32 sums of exact small integers).
_EXACT = ("turnover", "n_trades", "hit_rate")


def _grid(strategy):
    return {k: np.asarray(v)
            for k, v in product_grid(**_GRIDS[strategy]).items()}


def _fields(strategy, hi, lo=0):
    out = {f: np.asarray(getattr(_PANEL, f))[:, lo:hi]
           for f in rc.stream_fields(strategy) if f != "close2"}
    if "close2" in rc.stream_fields(strategy):
        out["close2"] = np.asarray(_PAIR_X.close)[:, lo:hi]
    return out


def _assert_parity(got, want, *, rtol=2e-5, atol=2e-6, what="",
                   max_flips=0):
    """Cold-vs-append parity with an explicit knife-edge budget: lanes
    whose turnover matches bit-exactly (positions identical) must agree
    on every metric to f32 association; lanes where a knife-edge
    indicator rounding flipped a position (turnover differs) are counted
    against ``max_flips`` — the same flip-budget contract the fused
    substrate A/Bs use."""
    flips = ~np.isclose(np.asarray(got.turnover), np.asarray(want.turnover),
                        rtol=0, atol=0)
    assert flips.sum() <= max_flips, (
        f"{what}: {int(flips.sum())} flipped lanes exceed the knife-edge "
        f"budget of {max_flips}")
    ok = ~flips
    for name in want._fields:
        g, w = np.asarray(getattr(got, name)), np.asarray(getattr(want,
                                                                  name))
        if name in _EXACT:
            assert np.array_equal(g[ok], w[ok]), \
                f"{what}: {name} not bit-exact on unflipped lanes"
        else:
            np.testing.assert_allclose(
                g[ok], w[ok], rtol=rtol, atol=atol,
                err_msg=f"{what}: {name}")


@pytest.mark.parametrize("strategy", sorted(_GRIDS))
def test_append_matches_cold_sweep(strategy):
    """sweep@T + append@ΔT vs the cold sweep at T+ΔT, per family —
    through the partial-tail recurrent head (the serving path)."""
    grid = _grid(strategy)
    cold = rc.finalize(rc.build_carry(strategy, _fields(strategy, T_FULL),
                                      grid))
    base = rc.build_carry(strategy, _fields(strategy, T_BASE), grid)
    # The production path: the tail no longer covers the history.
    assert base.tail["close"].shape[-1] < base.n_bars, \
        "fixture too short: append would take the full-replay path"
    stepped = rc.append_step(base, _fields(strategy, T_FULL, T_BASE))
    assert stepped.n_bars == T_FULL
    # Pairs carries the widest budget: its window-OLS z re-derives beta
    # on the tail, historically the fleet's worst knife-edge family
    # (VERIFY_r03) — allow ONE flipped lane of 8; everything else must
    # hold the tight budget on unflipped lanes.
    rtol = 5e-3 if strategy == "pairs" else 2e-5
    atol = 5e-4 if strategy == "pairs" else 2e-6
    _assert_parity(rc.finalize(stepped), cold, rtol=rtol, atol=atol,
                   what=strategy,
                   max_flips=1 if strategy == "pairs" else 0)


def test_append_in_two_slices_matches_one():
    """Chained ΔT appends compose: 2 x ΔT/2 ends in the same state class
    as one ΔT (count metrics bit-exact, moments to association)."""
    grid = _grid("bollinger")
    base = rc.build_carry("bollinger", _fields("bollinger", T_BASE), grid)
    one = rc.append_step(base, _fields("bollinger", T_FULL, T_BASE))
    half = T_BASE + DT // 2
    two = rc.append_step(
        rc.append_step(base, _fields("bollinger", half, T_BASE)),
        _fields("bollinger", T_FULL, half))
    assert two.n_bars == one.n_bars == T_FULL
    _assert_parity(rc.finalize(two), rc.finalize(one), what="2-slice")


def test_full_cover_append_while_tail_holds_history():
    """While the tail still covers the whole history (short panels) the
    append replays the generic models verbatim — appended positions are
    the cold sweep's by construction."""
    grid = _grid("sma_crossover")
    t0 = rc.tail_bars("sma_crossover", grid)   # = max(slow) + 2 = 14
    base = rc.build_carry("sma_crossover", _fields("sma_crossover", t0),
                          grid)
    assert base.tail["close"].shape[-1] == t0   # full cover
    stepped = rc.append_step(base,
                             _fields("sma_crossover", t0 + 8, t0))
    cold = rc.finalize(rc.build_carry("sma_crossover",
                                      _fields("sma_crossover", t0 + 8),
                                      grid))
    _assert_parity(rc.finalize(stepped), cold, what="full-cover")


def test_checkpoint_roundtrip_evict_restore_bit_matches():
    """serialize -> evict (device level) -> restore -> append must
    bit-match the never-evicted append (the CarryStore host level is
    lossless)."""
    grid = _grid("bollinger")
    base = rc.build_carry("bollinger", _fields("bollinger", T_BASE), grid)
    delta = _fields("bollinger", T_FULL, T_BASE)
    want = rc.finalize(rc.append_step(base, delta))

    store = CarryStore(max_bytes=1 << 22)
    key = ("digest-abc", rc.stream_key("bollinger", grid, 0.0, 252))
    store.put(key, base)
    store.evict_device(key)
    restored = store.get(key)                # host-level deserialize
    assert restored is not None and restored.n_bars == T_BASE
    got = rc.finalize(rc.append_step(restored, delta))
    for name in want._fields:
        assert np.array_equal(np.asarray(getattr(got, name)),
                              np.asarray(getattr(want, name))), name


def test_carry_store_levels_bounds_and_counters():
    from distributed_backtesting_exploration_tpu import obs

    reg = obs.Registry()
    grid = _grid("momentum")
    carry = rc.build_carry("momentum", _fields("momentum", T_BASE), grid)
    store = CarryStore(max_bytes=1 << 22, registry=reg)
    key = ("d1", "s1")
    assert store.get(key) is None            # cold: both levels miss
    store.put(key, carry)
    assert store.get(key) is not None        # device hit
    assert reg.counter("dbx_carry_cache_hits_total",
                       level="device").value == 1
    store.evict_device(key)
    assert store.get(key) is not None        # host restore
    assert reg.counter("dbx_carry_cache_hits_total",
                       level="host").value == 1
    assert reg.gauge("dbx_carry_cache_bytes").value > 0
    assert store.stats()["host_carries"] == 1

    # A bound smaller than one checkpoint indexes-then-evicts: the store
    # simply never retains it (ByteLRU semantics), no error.
    tiny = CarryStore(max_bytes=16, registry=reg)
    tiny.put(key, carry)
    assert tiny.get(key) is None


def test_carry_store_reprime_does_not_overwrite_racer(monkeypatch):
    """Round-12 atomicity fix (dbxlint check-then-act): the host-restore
    path used to re-prime the device level blindly — a carry checkpointed
    by a racing thread in the deserialize window (same key, MORE bars
    advanced) was overwritten by this thread's older copy, silently
    losing the advance. get() now re-validates under the second
    acquisition and serves the resident carry."""
    grid = _grid("momentum")
    older = rc.build_carry("momentum", _fields("momentum", T_BASE), grid)
    newer = rc.append_step(older, _fields("momentum", T_FULL, T_BASE))
    store = CarryStore(max_bytes=1 << 22)
    key = ("d-race", "s-race")
    store.put(key, older)
    store.evict_device(key)               # host blob = the OLDER state

    real = rc.carry_from_bytes

    def racing_deserialize(blob):
        out = real(blob)
        # The race, made deterministic: a racer re-checkpoints the key
        # while this thread is between the two lock acquisitions.
        with store._lock:
            store._device.put(key, newer, newer.nbytes)
        return out

    monkeypatch.setattr(rc, "carry_from_bytes", racing_deserialize)
    got = store.get(key)
    assert got is newer                   # the resident (newer) carry wins
    with store._lock:
        assert store._device.get(key) is newer   # never overwritten


def test_append_epilogue_substrates_agree():
    """The append's equity advance under scan vs ladder: selection-only
    state is identical (count metrics bit-exact); the equity path differs
    only by block association."""
    grid = _grid("sma_crossover")
    base = rc.build_carry("sma_crossover", _fields("sma_crossover",
                                                   T_BASE), grid)
    delta = _fields("sma_crossover", T_FULL, T_BASE)
    scan = rc.finalize(rc.append_step(base, delta, epilogue="scan:8"))
    ladder = rc.finalize(rc.append_step(base, delta, epilogue="ladder"))
    _assert_parity(scan, ladder, what="scan-vs-ladder")


def test_fused_wrapper_carry_out_mode():
    """The kernels' carry_out=True mode: (metrics, carry) with the carry
    appendable; ragged panels are rejected loudly."""
    close = np.asarray(_PANEL.close)[:, :T_BASE]
    g = _GRIDS["sma_crossover"]
    prod = product_grid(**g)
    m, carry = fused.fused_sma_sweep(
        close, np.asarray(prod["fast"]), np.asarray(prod["slow"]),
        carry_out=True)
    assert carry.n_bars == T_BASE and carry.strategy == "sma_crossover"
    # The carry's scan-form metrics agree with the kernel's to the
    # documented fused-vs-generic budget.
    np.testing.assert_allclose(np.asarray(rc.finalize(carry).sharpe),
                               np.asarray(m.sharpe), rtol=1e-4, atol=1e-5)
    stepped = rc.append_step(carry, _fields("sma_crossover", T_FULL,
                                            T_BASE))
    cold = rc.finalize(rc.build_carry("sma_crossover",
                                      _fields("sma_crossover", T_FULL),
                                      _grid("sma_crossover")))
    _assert_parity(rc.finalize(stepped), cold, what="carry_out")

    with pytest.raises(ValueError, match="uniform full-history"):
        fused.fused_sma_sweep(
            close, np.asarray(prod["fast"]), np.asarray(prod["slow"]),
            t_real=np.asarray([T_BASE, T_BASE - 5]), carry_out=True)


def test_stream_key_addresses_param_block():
    grid = _grid("sma_crossover")
    k0 = rc.stream_key("sma_crossover", grid, 0.0, 252)
    assert k0 == rc.stream_key("sma_crossover", dict(grid), 0.0, 252)
    other = {**grid, "fast": grid["fast"] + 1.0}
    assert k0 != rc.stream_key("sma_crossover", other, 0.0, 252)
    assert k0 != rc.stream_key("sma_crossover", grid, 1e-3, 252)
    assert k0 != rc.stream_key("momentum", grid, 0.0, 252)


def test_dispatcher_streamable_set_pins_the_registry():
    """The dispatcher validates AppendBars strategies against a LITERAL
    set (it must not import the jax-backed streaming package); this pin
    keeps it from drifting when a family is added to the registry."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        STREAMABLE_STRATEGIES)

    want = {s for s in rc._STREAM_FAMILIES if s != "pairs"}
    assert STREAMABLE_STRATEGIES == want


def test_validation_errors():
    grid = _grid("sma_crossover")
    with pytest.raises(ValueError, match="no streaming family"):
        rc.build_carry("nope", {"close": np.ones((1, 8), np.float32)},
                       grid)
    with pytest.raises(ValueError, match="needs fields"):
        rc.build_carry("obv_trend", {"close": np.ones((1, 8), np.float32)},
                       _grid("obv_trend"))
    carry = rc.build_carry("sma_crossover",
                           _fields("sma_crossover", T_BASE), grid)
    with pytest.raises(ValueError, match="empty delta"):
        rc.append_step(carry,
                       {"close": np.ones((2, 0), np.float32)})
    with pytest.raises(ValueError, match="delta fields"):
        rc.append_step(carry, {"volume": np.ones((2, 4), np.float32)})
