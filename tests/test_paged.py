"""Ragged paged panel batching (round 10): pool, kernels, backend routing.

Numerics contract under test (DESIGN.md "Ragged paged panels"):

- a UNIFORM group through the paged path is bit-identical to the dense
  fused sweep (the assembled block is the same f32 bits, the same kernel
  runs) — under BOTH ``DBX_EPILOGUE`` substrates;
- a RAGGED group is bit-identical to the dense repeat-last ragged stack,
  and matches per-job unpadded sweeps within the documented
  repeat-last-pad tolerance;
- an append-extended digest (PR 6 chains) reuses all of its base's full
  pages: pool bytes grow O(ΔT/page), not O(T), and the appended sweep
  bit-matches the dense path.

All tests run in-process on tiny shapes (CPU interpret mode) with
explicit PagePool bounds — no subprocesses, no fresh-jax processes (the
tier-1 budget rule). The full 13-family parity loop is ``slow``; the
flagship SMA + the bit-exact band machine stay tier-1.
"""

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu import obs
from distributed_backtesting_exploration_tpu.ops import fused
from distributed_backtesting_exploration_tpu.ops.metrics import Metrics
from distributed_backtesting_exploration_tpu.parallel import sweep
from distributed_backtesting_exploration_tpu.rpc import (
    backtesting_pb2 as pb, compute, wire)
from distributed_backtesting_exploration_tpu.rpc.page_pool import (
    PagePool, page_key, paginate)
from distributed_backtesting_exploration_tpu.rpc.panel_store import (
    panel_digest)
from distributed_backtesting_exploration_tpu.utils import data

B = 16   # test page size (bars); a multiple of 8, small enough that tiny
         # panels span several pages


def _series(t: int, seed: int) -> data.OHLCV:
    panel = data.synthetic_ohlcv(1, t, seed=seed)
    return data.OHLCV(*(np.asarray(f)[0] for f in panel))


def _pool_for(series_list, fields, digests=None, **kw):
    pool = PagePool(page_bars=B, registry=obs.Registry(), **kw)
    digests = digests or [f"d{i}" for i in range(len(series_list))]
    prep = pool.prepare(digests, series_list, fields)
    assert prep is not None
    return pool, prep


SMA_GRID = {k: np.asarray(v) for k, v in sweep.product_grid(
    fast=np.asarray([2.0, 3.0]), slow=np.asarray([8.0, 13.0])).items()}
BOLL_GRID = {k: np.asarray(v) for k, v in sweep.product_grid(
    window=np.asarray([4.0, 6.0]), k=np.asarray([0.5, 1.0])).items()}


def _assert_bit_equal(got: Metrics, want: Metrics, what: str):
    for name, a, b in zip(Metrics._fields, want, got):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (what, name)


@pytest.mark.parametrize("epilogue", ["scan:8", "ladder"])
def test_paged_uniform_bit_identical_sma(epilogue):
    series = [_series(52, seed=i) for i in range(3)]
    _, (pool_arr, tables, _) = _pool_for(series, ("close",))
    dense = fused.fused_sma_sweep(
        np.stack([np.asarray(s.close) for s in series]),
        SMA_GRID["fast"], SMA_GRID["slow"], cost=1e-3, epilogue=epilogue)
    paged = fused.fused_paged_sweep(
        "sma_crossover", pool_arr, tables, [52, 52, 52], SMA_GRID,
        cost=1e-3, epilogue=epilogue)
    _assert_bit_equal(paged, dense, f"sma@{epilogue}")


@pytest.mark.parametrize("epilogue", ["scan:8", "ladder"])
def test_paged_uniform_bit_identical_bollinger(epilogue):
    # The band machine: compose path is selection-only, so the paged
    # twin must be bit-exact on every backend under both substrates.
    series = [_series(48, seed=10 + i) for i in range(2)]
    _, (pool_arr, tables, _) = _pool_for(series, ("close",))
    dense = fused.fused_bollinger_sweep(
        np.stack([np.asarray(s.close) for s in series]),
        BOLL_GRID["window"], BOLL_GRID["k"], cost=1e-3, epilogue=epilogue)
    paged = fused.fused_paged_sweep(
        "bollinger", pool_arr, tables, [48, 48], BOLL_GRID,
        cost=1e-3, epilogue=epilogue)
    _assert_bit_equal(paged, dense, f"bollinger@{epilogue}")


@pytest.mark.parametrize("epilogue", ["scan:8", "ladder"])
def test_paged_ragged_bit_identical_to_dense_ragged(epilogue):
    # Mixed lengths, same page count (one bin) AND different page counts
    # (two bins): either way the assembled block must equal the dense
    # repeat-last ragged stack bit-for-bit, and so must the metrics.
    lens = [52, 41, 23]    # pages 4, 3, 2 at B=16 -> three bins
    series = [_series(52, seed=20 + i) for i in range(3)]
    series = [data.OHLCV(*(np.asarray(f)[:t] for f in s))
              for s, t in zip(series, lens)]
    _, (pool_arr, tables, _) = _pool_for(series, ("close",))
    paged = fused.fused_paged_sweep(
        "sma_crossover", pool_arr, tables, lens, SMA_GRID, cost=1e-3,
        epilogue=epilogue)
    # Dense ragged reference PER PAGE-COUNT BIN: the paged schedule pads
    # each ticker only to its own bin max, so the bit-exact twin is the
    # dense ragged stack of that bin (globally it is the repeat-last
    # contract, asserted in the tolerance test below).
    for idx in ([0], [1], [2]):
        stack = compute._stack_field_ragged(
            [series[i] for i in idx], max(lens[i] for i in idx))
        t_real = (None if len({lens[i] for i in idx}) == 1
                  and stack.shape[1] == lens[idx[0]] else
                  np.asarray([lens[i] for i in idx], np.int32))
        dense = fused.fused_sma_sweep(
            stack, SMA_GRID["fast"], SMA_GRID["slow"], cost=1e-3,
            t_real=t_real, epilogue=epilogue)
        for name, a, b in zip(Metrics._fields, dense, paged):
            got = np.asarray(b)[np.asarray(idx)]
            assert np.array_equal(got, np.asarray(a)), (name, idx)


def test_paged_ragged_repeat_last_contract():
    # vs per-job UNPADDED sweeps: the documented repeat-last-pad contract
    # (pad bars earn zero and hold the last position) within f32
    # association tolerance — for the flagship and a band machine.
    lens = [52, 37, 29]
    series = [data.OHLCV(*(np.asarray(f)[:t] for f in _series(52, 30 + i)))
              for i, t in enumerate(lens)]
    _, (pool_arr, tables, _) = _pool_for(series, ("close",))
    for strategy, grid in (("sma_crossover", SMA_GRID),
                           ("bollinger", BOLL_GRID)):
        paged = fused.fused_paged_sweep(
            strategy, pool_arr, tables, lens, grid, cost=1e-3)
        _, _, call = fused._PAGED_FAMILIES[strategy]
        for i, s in enumerate(series):
            ref = call([np.asarray(s.close)[None, :]], grid,
                       t_real=None, cost=1e-3, periods_per_year=252,
                       interpret=True, epilogue=None)
            for name, a, b in zip(Metrics._fields, ref, paged):
                np.testing.assert_allclose(
                    np.asarray(b)[i], np.asarray(a)[0], rtol=2e-5,
                    atol=2e-6, err_msg=f"{strategy}:{name}:job{i}")


@pytest.mark.slow
@pytest.mark.parametrize("strategy", sorted(fused._PAGED_FAMILIES))
def test_paged_parity_all_families(strategy):
    # Full-family paged-vs-dense twins (ragged, both substrates) — the
    # tier-1 gate keeps the flagship + band machine; this loop is the
    # exhaustive slow twin.
    fields, axes, call = fused._PAGED_FAMILIES[strategy]
    vals = {"fast": [2.0, 3.0], "slow": [8.0, 13.0], "window": [3.0, 5.0],
            "k": [0.5, 1.0], "lookback": [2.0, 4.0], "period": [3.0, 5.0],
            "band": [10.0, 20.0], "signal": [2.0, 3.0], "span": [2.0, 3.0]}
    grid = {a: np.asarray(v) for a, v in sweep.product_grid(
        **{a: np.asarray(vals[a], np.float32) for a in axes}).items()}
    lens = [52, 41]
    series = [data.OHLCV(*(np.asarray(f)[:t] for f in _series(52, 40 + i)))
              for i, t in enumerate(lens)]
    _, (pool_arr, tables, _) = _pool_for(series, fields)
    for epilogue in ("scan:8", "ladder"):
        paged = fused.fused_paged_sweep(
            strategy, pool_arr, tables, lens, grid, cost=1e-3,
            epilogue=epilogue)
        for idx in ([0], [1]):
            t_bin = [lens[i] for i in idx]
            arrays = [compute._stack_field_ragged(
                [series[i] for i in idx], max(t_bin), f) for f in fields]
            dense = call(arrays, grid, t_real=None, cost=1e-3,
                         periods_per_year=252, interpret=True,
                         epilogue=epilogue)
            for name, a, b in zip(Metrics._fields, dense, paged):
                assert np.array_equal(np.asarray(b)[np.asarray(idx)],
                                      np.asarray(a)), \
                    (strategy, epilogue, name)


def test_append_chain_shares_base_pages():
    # Satellite: an append-extended digest (PR 6 chain) reuses all of its
    # base's FULL pages — pool bytes grow by O(ΔT/page) + the boundary
    # page, never O(T).
    t_base, dt = 7 * B + 5, 9      # partial boundary page + small delta
    base = _series(t_base + dt, seed=7)
    base_panel = data.OHLCV(*(np.asarray(f)[:t_base] for f in base))
    ext_panel = data.OHLCV(*(np.asarray(f)[:t_base + dt] for f in base))
    pool = PagePool(page_bars=B, registry=obs.Registry())
    prep = pool.prepare(["base"], [base_panel], ("close",))
    assert prep is not None
    pages_base = pool.stats()["pages"]
    bytes_base = pool.stats()["bytes"]
    assert pages_base == -(-t_base // B)
    prep2 = pool.prepare(["ext"], [ext_panel], ("close",))
    assert prep2 is not None
    added = pool.stats()["pages"] - pages_base
    # ΔT=9 with a partial boundary page: the boundary page's content
    # changed (pad -> real bars) and no new page index is needed, so
    # exactly one page uploads; never more than ceil(ΔT/B) + 1.
    assert added <= -(-dt // B) + 1, added
    assert pool.stats()["bytes"] - bytes_base == added * B * 4
    # The appended sweep bit-matches the dense path (the PR 3/6 contract:
    # same kernel, same assembled bits).
    pool_arr, tables, _ = prep2
    paged = fused.fused_paged_sweep(
        "sma_crossover", pool_arr, tables, [t_base + dt], SMA_GRID,
        cost=1e-3)
    dense = fused.fused_sma_sweep(
        np.asarray(ext_panel.close)[None, :], SMA_GRID["fast"],
        SMA_GRID["slow"], cost=1e-3)
    _assert_bit_equal(paged, dense, "append-chain")


def test_overlapping_histories_share_pages_across_digests():
    # Content keying: two DIFFERENT digests whose histories share a
    # full-page-aligned prefix share those pages — device bytes sublinear
    # in ticker count for overlapping histories.
    s = _series(6 * B, seed=9)
    a = data.OHLCV(*(np.asarray(f)[:5 * B] for f in s))
    b = data.OHLCV(*(np.asarray(f)[:6 * B] for f in s))
    pool = PagePool(page_bars=B, registry=obs.Registry())
    assert pool.prepare(["da"], [a], ("close",)) is not None
    before = pool.stats()["pages"]
    assert pool.prepare(["db"], [b], ("close",)) is not None
    assert pool.stats()["pages"] - before == 1   # only the new tail page


def test_pool_bounds_eviction_and_reject():
    reg = obs.Registry()
    pool = PagePool(page_bars=B, max_bytes=4 * B * 4, registry=reg)
    assert pool.capacity == 4
    s1 = _series(3 * B, seed=1)
    assert pool.prepare(["d1"], [s1], ("close",)) is not None
    assert pool.stats()["pages"] == 3
    # A second 3-page panel fits only by evicting LRU pages of the first.
    s2 = _series(3 * B, seed=2)
    assert pool.prepare(["d2"], [s2], ("close",)) is not None
    assert pool.stats()["pages"] <= 4
    assert pool.stats()["bytes"] <= pool.max_bytes
    # A group larger than the whole pool is REJECTED, not thrashed.
    s3 = _series(6 * B, seed=3)
    assert pool.prepare(["d3"], [s3], ("close",)) is None
    assert reg.counter("dbx_page_pool_rejects_total").value >= 1


def test_pool_counters_and_gauges():
    reg = obs.Registry()
    pool = PagePool(page_bars=B, registry=reg)
    s = _series(2 * B + 3, seed=4)
    assert pool.prepare(["d"], [s], ("close",)) is not None
    assert reg.counter("dbx_page_pool_misses_total", field="close").value \
        == 3
    assert pool.prepare(["d"], [s], ("close",)) is not None   # warm
    assert reg.counter("dbx_page_pool_hits_total", field="close").value \
        == 3
    assert reg.gauge("dbx_page_pool_pages").value == 3
    assert reg.gauge("dbx_page_pool_bytes").value == 3 * B * 4


def _specs(series_list, grid, strategy="sma_crossover", cost=1e-3):
    out = []
    for i, s in enumerate(series_list):
        raw = data.to_wire_bytes(s)
        out.append(pb.JobSpec(
            id=f"j{i}", strategy=strategy, ohlcv=raw,
            panel_digest=panel_digest(raw), grid=wire.grid_to_proto(grid),
            cost=cost, periods_per_year=252))
    return out


def _backend(monkeypatch, **kw):
    monkeypatch.setenv("DBX_PAGE_BARS", str(B))
    monkeypatch.setenv("DBX_PAGE_POOL_MB", "4")
    return compute.JaxSweepBackend(use_fused=True, use_mesh=False, **kw)


def test_backend_mixed_lengths_fuse_and_route_paged(monkeypatch):
    be = _backend(monkeypatch)
    assert be.use_paged
    lens = (64, 41, 52, 64)
    series = [data.OHLCV(*(np.asarray(f)[:t]
                           for f in _series(64, 50 + i)))
              for i, t in enumerate(lens)]
    axes = {"fast": np.asarray([2.0, 3.0]), "slow": np.asarray([8.0])}
    specs = _specs(series, axes)
    # One submit group despite four lengths: the paged key drops the
    # length bucket entirely.
    assert len({be._length_bucket(j, axes) for j in specs}) == 1
    comps = {c.job_id: c for c in be.process(specs)}
    assert len(comps) == 4 and all(c.metrics for c in comps.values())
    prod = {k: np.asarray(v)
            for k, v in sweep.product_grid(**axes).items()}
    for i, s in enumerate(series):
        ref = fused.fused_sma_sweep(
            np.asarray(s.close)[None, :], prod["fast"], prod["slow"],
            cost=1e-3)
        got = wire.metrics_from_bytes(comps[f"j{i}"].metrics)
        np.testing.assert_allclose(
            np.asarray(got.sharpe).ravel(),
            np.asarray(ref.sharpe).ravel(), rtol=2e-5, atol=2e-6)
    # Pool observability advanced: pages resident, misses counted, the
    # partial tail pages' pad accounted to the paged path.
    st = be.panel_cache.stats()["page_pool"]
    assert st["pages"] > 0 and st["bytes"] > 0
    reg = obs.get_registry()
    assert reg.counter("dbx_page_pool_misses_total", field="close").value \
        > 0
    assert reg.counter("dbx_pad_bars_total", path="paged").value > 0
    # Warm re-submit: every page hits, nothing uploads, and the pending
    # entry's h2d-hit flag (collect's d2h span cache_hit attr) reports
    # the pool-warm state like a device-block hit.
    misses = reg.counter("dbx_page_pool_misses_total", field="close").value
    pend = be.submit(_specs(series, axes))
    assert len(pend) == 1 and pend[0][5] is True
    be.collect(pend)
    assert reg.counter("dbx_page_pool_misses_total",
                       field="close").value == misses
    assert reg.counter("dbx_page_pool_hits_total", field="close").value > 0


def test_backend_over_cap_ragged_splits_through_paging(monkeypatch):
    # The generic-path demotion for over-VMEM-cap ragged groups routes
    # through paging first: only the genuinely-long member demotes, the
    # under-cap members keep the fused (paged) route. The cap is a class
    # attr — shrink it so the "long" panel stays test-sized.
    monkeypatch.setattr(compute.JaxSweepBackend, "_FUSED_MAX_BARS", 64)
    be = _backend(monkeypatch)
    lens = (48, 96, 33)
    series = [data.OHLCV(*(np.asarray(f)[:t]
                           for f in _series(96, 70 + i)))
              for i, t in enumerate(lens)]
    axes = {"fast": np.asarray([2.0]), "slow": np.asarray([8.0])}
    specs = _specs(series, axes)
    # No length buckets -> one merged group whose t_max breaks the cap.
    assert len({be._length_bucket(j, axes) for j in specs}) == 1
    comps = {c.job_id: c for c in be.process(specs)}
    assert len(comps) == 3 and all(c.metrics for c in comps.values())
    for i, s in enumerate(series):
        ref = fused.fused_sma_sweep(
            np.asarray(s.close)[None, :], axes["fast"], axes["slow"],
            cost=1e-3)
        got = wire.metrics_from_bytes(comps[f"j{i}"].metrics)
        np.testing.assert_allclose(
            np.asarray(got.sharpe).ravel(),
            np.asarray(ref.sharpe).ravel(), rtol=2e-5, atol=2e-6)
    # The under-cap members went through the pool (pages resident for
    # the 48- and 33-bar panels: 3 + 3 pages at B=16), the 96-bar panel
    # stayed off it.
    st = be.panel_cache.stats()["page_pool"]
    assert st["pages"] == -(-48 // B) + -(-33 // B)


def test_backend_pool_reject_falls_back_dense(monkeypatch):
    # A pool too small for even one group degrades to the dense stacks —
    # jobs still complete, bit-for-bit the same results.
    monkeypatch.setenv("DBX_PAGE_BARS", str(B))
    monkeypatch.setenv("DBX_PAGE_POOL_MB",
                       str(2 * B * 4 / (1024 * 1024)))   # 2 slots
    be = compute.JaxSweepBackend(use_fused=True, use_mesh=False)
    series = [data.OHLCV(*(np.asarray(f)[:t]
                           for f in _series(64, 60 + i)))
              for i, t in enumerate((64, 41))]
    axes = {"fast": np.asarray([2.0]), "slow": np.asarray([8.0])}
    comps = {c.job_id: c for c in be.process(_specs(series, axes))}
    assert len(comps) == 2 and all(c.metrics for c in comps.values())
    for i, s in enumerate(series):
        ref = fused.fused_sma_sweep(
            np.asarray(s.close)[None, :], axes["fast"], axes["slow"],
            cost=1e-3)
        got = wire.metrics_from_bytes(comps[f"j{i}"].metrics)
        np.testing.assert_allclose(
            np.asarray(got.sharpe).ravel(),
            np.asarray(ref.sharpe).ravel(), rtol=2e-5, atol=2e-6)


def test_paged_kill_switch_and_knob_validation(monkeypatch):
    axes = {"fast": np.asarray([2.0]), "slow": np.asarray([8.0])}
    monkeypatch.setenv("DBX_PAGED", "0")
    be = compute.JaxSweepBackend(use_fused=True, use_mesh=False)
    assert not be.use_paged
    job = pb.JobSpec(strategy="sma_crossover", wf_train=0,
                     panel_digest="d" * 32, panel_bytes_len=1000)
    assert be._length_bucket(job, axes) == (1000).bit_length()
    monkeypatch.delenv("DBX_PAGED")
    be2 = compute.JaxSweepBackend(use_fused=True, use_mesh=False)
    assert be2.use_paged and be2._length_bucket(job, axes) == 0
    # wf/pairs/best_returns jobs keep the bucket even when paged is live,
    # as do digestless jobs (they cannot take the paged route, and one
    # of them must not drag a merged group onto the dense fallback) and
    # jobs whose grid fails the length-independent fused gates.
    wf = pb.JobSpec(strategy="sma_crossover", wf_train=10,
                    panel_digest="d" * 32, panel_bytes_len=1000)
    assert be2._length_bucket(wf, axes) == (1000).bit_length()
    nodigest = pb.JobSpec(strategy="sma_crossover", panel_bytes_len=1000)
    assert be2._length_bucket(nodigest, axes) == (1000).bit_length()
    bad_grid = {"fast": np.asarray([2.5]), "slow": np.asarray([8.0])}
    assert be2._length_bucket(job, bad_grid) == (1000).bit_length()
    for bad in ("x", "-8", "12"):
        monkeypatch.setenv("DBX_PAGE_BARS", bad)
        with pytest.raises(ValueError):
            fused.resolve_page_bars()
    monkeypatch.setenv("DBX_PAGE_BARS", "64")
    assert fused.resolve_page_bars() == 64


def test_paged_fields_match_fused_registry():
    # ONE source of truth: the worker prepares page tables from
    # fused.paged_fields, and the two registries' field tuples AND grid
    # axes must agree for every family (a drift would raise mid-submit,
    # or misbuild the hygiene probe's grid).
    for strategy, spec in compute.JaxSweepBackend._FUSED_STRATEGIES.items():
        assert fused.paged_fields(strategy) == spec.fields, strategy
        _, axes, _ = fused._PAGED_FAMILIES[strategy]
        assert set(axes) == spec.axes, strategy


def test_backend_pool_reject_resplits_mixed_group(monkeypatch):
    # A pool-rejected MERGED mixed-length group re-splits by the pre-
    # paging power-of-two bucket before stacking densely — the ~2x pad
    # bound survives the fallback (jobs complete, two dense groups).
    monkeypatch.setenv("DBX_PAGE_BARS", str(B))
    monkeypatch.setenv("DBX_PAGE_POOL_MB",
                       str(1 * B * 4 / (1024 * 1024)))    # 1 slot: reject
    be = compute.JaxSweepBackend(use_fused=True, use_mesh=False)
    series = [data.OHLCV(*(np.asarray(f)[:t]
                           for f in _series(256, 80 + i)))
              for i, t in enumerate((256, 48))]    # different pow2 buckets
    axes = {"fast": np.asarray([2.0]), "slow": np.asarray([8.0])}
    reg = obs.get_registry()
    pad0 = reg.counter("dbx_pad_bars_total", path="dense").value
    comps = {c.job_id: c for c in be.process(_specs(series, axes))}
    assert len(comps) == 2 and all(c.metrics for c in comps.values())
    # Re-split means NO cross-bucket padding: the dense pad counter must
    # not have been charged 256-48 bars for the short job.
    assert reg.counter("dbx_pad_bars_total",
                       path="dense").value - pad0 == 0
    for i, s in enumerate(series):
        ref = fused.fused_sma_sweep(
            np.asarray(s.close)[None, :], axes["fast"], axes["slow"],
            cost=1e-3)
        got = wire.metrics_from_bytes(comps[f"j{i}"].metrics)
        np.testing.assert_allclose(
            np.asarray(got.sharpe).ravel(),
            np.asarray(ref.sharpe).ravel(), rtol=2e-5, atol=2e-6)


def test_page_key_and_paginate_canonical():
    v = np.arange(B + 3, dtype=np.float32)
    pages = paginate(v, B)
    assert len(pages) == 2 and pages[1].shape == (B,)
    # repeat-last pad inside the partial page is canonical content.
    assert np.all(pages[1][3:] == v[-1])
    assert page_key(pages[0].tobytes()) != page_key(pages[1].tobytes())
    # full-page prefix of a longer series hashes identically (the
    # sharing property the append-chain test exercises end to end).
    w = np.arange(2 * B, dtype=np.float32)
    assert page_key(paginate(w, B)[0].tobytes()) == \
        page_key(pages[0].tobytes())


def test_paged_hygiene_probe_traces(monkeypatch):
    # The lint gate runs the full registry; this pins the probe contract
    # itself (tier-1-cheap: one family, both substrates) and the loud
    # failure for unregistered strategies.
    import jax

    for epi in ("scan:8", "ladder"):
        monkeypatch.setenv("DBX_EPILOGUE", epi)
        fn, args = fused.paged_hygiene_probe("sma_crossover")
        jaxpr = jax.make_jaxpr(fn)(*args)
        assert jaxpr.out_avals   # traced through gather + kernel
    with pytest.raises(KeyError):
        fused.paged_hygiene_probe("no_such_family")
