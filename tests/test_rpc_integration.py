"""In-process dispatcher+worker integration over a loopback gRPC channel.

The strategy SURVEY.md §4 prescribes: real server, real worker, fake/instant
compute backend for control-plane tests, and the real JAX backend once for a
numerical end-to-end check against a directly-computed sweep.
"""

import threading
import time

import grpc
import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.rpc import (
    backtesting_pb2 as pb, compute, service, wire)
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    Dispatcher, DispatcherServer, JobQueue, PeerRegistry, parse_grid,
    synthetic_jobs)
from distributed_backtesting_exploration_tpu.rpc.worker import Worker


def _server(queue, *, prune_window_s=10.0, prune_interval_s=0.1,
            results_dir=None):
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=prune_window_s),
                      results_dir=results_dir)
    srv = DispatcherServer(disp, bind="localhost:0",
                           prune_interval_s=prune_interval_s).start()
    return disp, srv


_LIVE_WORKERS: list = []


def _run_worker(target, backend, *, max_idle_polls=10, **kw):
    w = Worker(target, backend, poll_interval_s=0.02,
               status_interval_s=0.05, **kw)
    t = threading.Thread(target=lambda: w.run(max_idle_polls=max_idle_polls),
                         daemon=True)
    t.start()
    _LIVE_WORKERS.append((w, t))
    return w, t


@pytest.fixture(autouse=True)
def _stop_workers():
    """Stop every worker thread at test end.

    A leaked polling worker from one test can land on a later test's
    OS-assigned port (reuse) and steal its jobs — observed as a flaky
    metrics mismatch in the golden end-to-end test.
    """
    yield
    while _LIVE_WORKERS:
        w, t = _LIVE_WORKERS.pop()
        w.stop()
        t.join(timeout=10)


GRID = parse_grid("fast=3:5,slow=10:14:2")


def _wait(pred, timeout=20.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def test_end_to_end_instant_backend(tmp_path):
    queue = JobQueue()
    for rec in synthetic_jobs(6, 64, "sma_crossover", GRID):
        queue.enqueue(rec)
    disp, srv = _server(queue, results_dir=str(tmp_path / "results"))
    try:
        backend = compute.InstantBackend()
        w, t = _run_worker(f"localhost:{srv.port}", backend)
        _wait(lambda: queue.drained, msg="queue drained")
        t.join(timeout=10)
        s = queue.stats()
        assert s["jobs_completed"] == 6 and s["jobs_pending"] == 0
        assert not disp.results, "results stay on disk when results_dir set"
        assert w.jobs_completed == 6
        # every result file written
        assert len(list((tmp_path / "results").glob("*.dbxm"))) == 6
    finally:
        srv.stop()


def test_jax_backend_fused_ragged_batch_matches_direct():
    """A mixed-length job batch stays on the fused path (use_fused=True,
    interpret mode on CPU) and matches per-job direct sweeps — the routing
    must not silently drop ragged fleets to the generic path (VERDICT r2 #6).
    """
    import jax.numpy as jnp

    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.parallel import sweep
    from distributed_backtesting_exploration_tpu.utils import data

    grid = parse_grid("fast=3:5,slow=10:14:2")
    jobs = (synthetic_jobs(2, 96, "sma_crossover", grid, cost=1e-3, seed=6)
            + synthetic_jobs(2, 150, "sma_crossover", grid, cost=1e-3,
                             seed=7))
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        grid=wire.grid_to_proto(r.grid), cost=r.cost,
                        periods_per_year=252) for r in jobs]
    backend = compute.JaxSweepBackend(use_fused=True)
    completions = backend.process(specs)
    assert len(completions) == len(jobs)
    by_id = {c.job_id: c for c in completions}

    for rec in jobs:
        series = data.from_wire_bytes(rec.ohlcv)
        panel = type(series)(*(jnp.asarray(f)[None, :] for f in series))
        canonical_axes = dict(sorted(rec.grid.items()))
        want = sweep.jit_sweep(
            panel, base.get_strategy("sma_crossover"),
            sweep.product_grid(**canonical_axes), cost=1e-3)
        got = wire.metrics_from_bytes(by_id[rec.id].metrics)
        for name in want._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name))[0], rtol=2e-4, atol=2e-5,
                err_msg=name)


def test_pairs_jobs_over_the_wire_match_direct_sweep():
    """Two-legged pairs jobs travel the full dispatch loop (JobSpec.ohlcv2,
    round 3) and the recorded metrics match a direct run_pairs_sweep — the
    distributed plane covers every strategy family, including BASELINE
    configs[3]."""
    import jax.numpy as jnp

    from distributed_backtesting_exploration_tpu.models import pairs
    from distributed_backtesting_exploration_tpu.parallel import sweep
    from distributed_backtesting_exploration_tpu.utils import data

    grid = {"lookback": np.asarray([8.0, 10.0], np.float32),
            "z_entry": np.asarray([1.0, 2.0], np.float32)}
    queue = JobQueue()
    jobs = synthetic_jobs(3, 96, "pairs", grid, cost=1e-3, seed=9)
    for rec in jobs:
        queue.enqueue(rec)
    disp, srv = _server(queue)
    try:
        w, t = _run_worker(f"localhost:{srv.port}",
                           compute.JaxSweepBackend())
        _wait(lambda: queue.drained, timeout=120.0, msg="queue drained")
        t.join(timeout=10)
    finally:
        srv.stop()
    assert queue.stats()["jobs_completed"] == 3

    for rec in jobs:
        y = data.from_wire_bytes(rec.ohlcv)
        x = data.from_wire_bytes(rec.ohlcv2)
        canonical_axes = dict(sorted(rec.grid.items()))
        want = pairs.run_pairs_sweep(
            jnp.asarray(y.close)[None, :], jnp.asarray(x.close)[None, :],
            sweep.product_grid(**canonical_axes), cost=1e-3)
        got = wire.metrics_from_bytes(disp.results[rec.id])
        for name in want._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name))[0], rtol=2e-4, atol=2e-5,
                err_msg=name)


def test_pairs_jobs_fused_backend_path():
    """use_fused=True routes pairs groups to the Pallas kernel (interpret
    mode on CPU); results match the generic sweep modulo the documented
    knife-edge flip allowance."""
    grid = {"lookback": np.asarray([8.0, 10.0], np.float32),
            "z_entry": np.asarray([1.0, 2.0], np.float32)}
    jobs = synthetic_jobs(2, 96, "pairs", grid, cost=1e-3, seed=11)
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        ohlcv2=r.ohlcv2, grid=wire.grid_to_proto(r.grid),
                        cost=r.cost, periods_per_year=252) for r in jobs]
    fused_out = {c.job_id: wire.metrics_from_bytes(c.metrics)
                 for c in compute.JaxSweepBackend(use_fused=True
                                                  ).process(specs)}
    generic_out = {c.job_id: wire.metrics_from_bytes(c.metrics)
                   for c in compute.JaxSweepBackend(use_fused=False
                                                    ).process(specs)}
    assert set(fused_out) == set(generic_out) == {r.id for r in jobs}
    for jid in fused_out:
        a, b = fused_out[jid], generic_out[jid]
        flipped = np.zeros_like(np.asarray(a.turnover), dtype=bool)
        for name in a._fields:
            av, bv = np.asarray(getattr(a, name)), np.asarray(
                getattr(b, name))
            flipped |= np.abs(av - bv) > (0.01 + 0.01 * np.abs(bv))
        assert flipped.mean() <= 0.05
        for name in a._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(a, name))[~flipped],
                np.asarray(getattr(b, name))[~flipped],
                rtol=2e-3, atol=2e-4, err_msg=name)


def test_pairs_jobs_malformed_complete_empty_not_requeue_loop():
    """A pairs job missing its second leg (or with unequal legs) completes
    with empty metrics and a logged error instead of poisoning co-batched
    jobs or looping through lease requeues forever."""
    from distributed_backtesting_exploration_tpu.utils import data

    grid = {"lookback": np.asarray([8.0], np.float32),
            "z_entry": np.asarray([1.0], np.float32)}
    good = synthetic_jobs(1, 96, "pairs", grid, cost=1e-3, seed=13)[0]
    no_leg = synthetic_jobs(1, 96, "pairs", grid, cost=1e-3, seed=14)[0]
    short = data.synthetic_ohlcv(1, 50, seed=15)
    uneven = synthetic_jobs(1, 96, "pairs", grid, cost=1e-3, seed=16)[0]
    uneven_x = data.to_wire_bytes(type(short)(*(f[0] for f in short)))
    specs = [
        pb.JobSpec(id=good.id, strategy="pairs", ohlcv=good.ohlcv,
                   ohlcv2=good.ohlcv2, grid=wire.grid_to_proto(grid),
                   cost=1e-3, periods_per_year=252),
        pb.JobSpec(id=no_leg.id, strategy="pairs", ohlcv=no_leg.ohlcv,
                   grid=wire.grid_to_proto(grid), cost=1e-3,
                   periods_per_year=252),
        pb.JobSpec(id=uneven.id, strategy="pairs", ohlcv=uneven.ohlcv,
                   ohlcv2=uneven_x, grid=wire.grid_to_proto(grid),
                   cost=1e-3, periods_per_year=252),
    ]
    out = {c.job_id: c for c in compute.JaxSweepBackend().process(specs)}
    assert set(out) == {good.id, no_leg.id, uneven.id}
    assert len(out[good.id].metrics) > 0
    assert out[no_leg.id].metrics == b"" and out[uneven.id].metrics == b""


def test_pairs_job_record_journal_roundtrip(tmp_path):
    """ohlcv2 survives the journal (restart must not lose the second leg)."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        JobRecord)
    from distributed_backtesting_exploration_tpu.rpc.journal import Journal

    jp = str(tmp_path / "j.jsonl")
    queue = JobQueue(Journal(jp))
    rec = synthetic_jobs(1, 32, "pairs",
                         {"lookback": np.asarray([8.0], np.float32),
                          "z_entry": np.asarray([1.0], np.float32)})[0]
    queue.enqueue(rec)
    q2 = JobQueue()
    assert q2.restore(jp) == 1
    restored = q2.take(1, "w")[0][0]
    assert restored.ohlcv2 == rec.ohlcv2 and restored.ohlcv == rec.ohlcv
    assert isinstance(restored, JobRecord)


class _PipelineProbeBackend:
    """submit/collect backend that records event order and slows collect,
    so the worker's double-buffering is observable: with several batches
    queued, submit(k+1) must precede collected(k) for some k."""

    chips = 1

    def __init__(self, delay_s: float = 0.15):
        self.delay_s = delay_s
        self.events: list = []
        self._lock = threading.Lock()
        self._n = 0

    def submit(self, jobs):
        with self._lock:
            self._n += 1
            n = self._n
        self.events.append(("submit", n))
        return (n, list(jobs))

    def collect(self, handle):
        n, jobs = handle
        time.sleep(self.delay_s)
        self.events.append(("collected", n))
        return [compute.Completion(j.id, b"", self.delay_s) for j in jobs]


def test_pipelined_backend_overlaps_batches():
    """The compute loop must launch batch k+1 while batch k's results are
    still being collected (SURVEY.md §2.3 PP row: decode/H2D/compute
    double-buffering vs the reference's serial loop,
    reference src/worker/process.rs:21-25)."""
    queue = JobQueue()
    for rec in synthetic_jobs(8, 32, "sma_crossover", GRID):
        queue.enqueue(rec)
    disp, srv = _server(queue)
    backend = _PipelineProbeBackend()
    try:
        w, t = _run_worker(f"localhost:{srv.port}", backend, jobs_per_chip=2)
        _wait(lambda: queue.drained, msg="queue drained")
        # drained flips inside the dispatcher's handler, possibly before the
        # worker thread has counted the reply — join before asserting.
        t.join(timeout=10)
    finally:
        srv.stop()
    assert w.jobs_completed == 8
    ev = backend.events
    overlapped = any(
        ev.index(("submit", k + 1)) < ev.index(("collected", k))
        for k in range(1, backend._n)
        if ("submit", k + 1) in ev and ("collected", k) in ev)
    assert overlapped, f"no overlapped batch observed in {ev}"


class _CrashyCollectBackend:
    """submit/collect backend whose collect dies after ``ok_batches``
    collections — a worker whose in-flight pipeline batch is lost
    mid-drain. Those completions never materialize; the LEASE must bring
    the jobs back, never a silent drop."""

    chips = 1

    def __init__(self, ok_batches: int = 2, delay_s: float = 0.08):
        self.ok_batches = ok_batches
        self.delay_s = delay_s
        self._lock = threading.Lock()
        self._n = 0

    def submit(self, jobs):
        return list(jobs)

    def collect(self, jobs):
        time.sleep(self.delay_s)
        with self._lock:
            self._n += 1
            n = self._n
        if n > self.ok_batches:
            raise RuntimeError("simulated mid-batch pipeline death")
        return [compute.Completion(j.id, b"", self.delay_s,
                                   trace_id=j.trace_id) for j in jobs]


def test_pipelined_graceful_stop_zero_lost_completions():
    """Round-14 drain regression: stop a pipelined worker mid-batch with
    batches dying in its collector. Every batch it took must be either
    completed-and-reported (the ordered sentinel drain) or left leased —
    after lease expiry a second worker finishes the remainder and the
    fleet records ZERO lost completions."""
    queue = JobQueue(lease_s=1.5)
    recs = synthetic_jobs(8, 32, "sma_crossover", GRID, seed=812)
    for rec in recs:
        queue.enqueue(rec)
    disp, srv = _server(queue, prune_window_s=30.0)
    backend = _CrashyCollectBackend(ok_batches=2)
    try:
        w, t = _run_worker(f"localhost:{srv.port}", backend,
                           jobs_per_chip=2, max_idle_polls=None)
        _wait(lambda: w.jobs_completed >= 2, msg="first completions")
        w.stop()   # mid-batch: the pipeline still holds taken batches
        t.join(timeout=30)
        assert not t.is_alive(), "graceful stop wedged"
        s = queue.stats()
        # Finish-or-requeue: at stop time every job is accounted for —
        # completed, still pending, or leased awaiting expiry. None gone.
        assert (s["jobs_completed"] + s["jobs_pending"]
                + s["jobs_leased"]) == 8, s
        assert s["jobs_completed"] >= 2
        assert s["jobs_completed"] < 8, \
            "the crashy backend should have stranded some batches"
        # Lease expiry returns the stranded jobs; a healthy worker
        # finishes them.
        w2, t2 = _run_worker(f"localhost:{srv.port}",
                             compute.InstantBackend(), max_idle_polls=50)
        _wait(lambda: queue.drained, timeout=60.0,
              msg="second worker drains the requeued jobs")
        t2.join(timeout=20)
    finally:
        srv.stop()
    s = queue.stats()
    assert s["jobs_completed"] == 8 and s["jobs_pending"] == 0, s
    assert s["jobs_failed"] == 0
    assert s["jobs_requeued"] >= 1, \
        "the stranded batches must have come back through lease expiry"


def test_pipelined_vs_serial_bit_identity_across_routes(monkeypatch):
    """The round-14 acceptance bar: DBX_PIPELINE=1 must not change a
    single result bit vs the serial loop (DBX_PIPELINE=0) on any route —
    dense fused, paged fused, and generic here; the append/carry-hit
    streaming route in its own test below. Completion ORDER may differ;
    bytes per job id may not."""

    def run_route(*, pipeline, use_fused, paged, seed):
        monkeypatch.setenv("DBX_PIPELINE", "1" if pipeline else "0")
        monkeypatch.setenv("DBX_PAGED", "1" if paged else "0")
        recs = (synthetic_jobs(2, 64, "sma_crossover", GRID, cost=1e-3,
                               seed=seed)
                + synthetic_jobs(2, 96, "sma_crossover", GRID, cost=1e-3,
                                 seed=seed + 1))
        # synthetic ids are uuid4 — pin them so the serial and pipelined
        # runs are comparable job-for-job.
        for i, rec in enumerate(recs):
            rec.id = f"bit-{seed}-{i}"
        queue = JobQueue()
        for rec in recs:
            queue.enqueue(rec)
        disp, srv = _server(queue)
        try:
            w, t = _run_worker(f"localhost:{srv.port}",
                               compute.JaxSweepBackend(use_fused=use_fused),
                               jobs_per_chip=2)
            _wait(lambda: queue.drained, timeout=180.0, msg="queue drained")
            w.stop()
            t.join(timeout=20)
        finally:
            srv.stop()
        assert queue.stats()["jobs_failed"] == 0
        assert len(disp.results) == len(recs)
        return {jid: bytes(b) for jid, b in disp.results.items()}

    for route, kw in (
            ("fused", dict(use_fused=True, paged=False, seed=600)),
            ("paged", dict(use_fused=True, paged=True, seed=610)),
            ("generic", dict(use_fused=False, paged=False, seed=620)),
    ):
        serial = run_route(pipeline=False, **kw)
        piped = run_route(pipeline=True, **kw)
        assert set(serial) == set(piped), route
        for jid in serial:
            assert piped[jid] == serial[jid], (route, jid)


def test_pipelined_vs_serial_bit_identity_append_carry_hit(monkeypatch):
    """Bit identity on the streaming route: an append chain served from
    carry checkpoints produces identical bytes under the pipelined and
    serial loops — and the carry HIT actually happened in both (the
    pipeline must not silently degrade appends to full reprices)."""
    import grpc as grpc_mod

    from distributed_backtesting_exploration_tpu.rpc import service

    monkeypatch.setenv("DBX_PAGED", "0")

    def run_chain(*, pipeline, seed):
        monkeypatch.setenv("DBX_PIPELINE", "1" if pipeline else "0")
        full, rec, cut = _stream_setup(seed=seed)
        queue = JobQueue()
        queue.enqueue(rec)
        disp, srv = _server(queue)
        backend = compute.JaxSweepBackend(use_fused=True)
        hit0 = backend._c_append["carry_hit"].value
        channel = grpc_mod.insecure_channel(
            f"localhost:{srv.port}",
            options=service.default_channel_options())
        stub = service.DispatcherStub(channel)
        try:
            w, t = _run_worker(f"localhost:{srv.port}", backend,
                               max_idle_polls=None)
            _wait(lambda: queue.drained, msg="base drained")
            r1 = _append(stub, rec.panel_digest, 128, cut(128, 144))
            assert r1.ok
            _wait(lambda: queue.drained, msg="append 1 drained")
            r2 = _append(stub, r1.panel_digest, 144, cut(144, 160))
            assert r2.ok
            _wait(lambda: queue.drained, msg="append 2 drained")
            w.stop()
            t.join(timeout=20)
        finally:
            channel.close()
            srv.stop()
        assert backend._c_append["carry_hit"].value - hit0 >= 1
        return {"base": bytes(disp.results[rec.id]),
                "r1": bytes(disp.results[r1.job_id]),
                "r2": bytes(disp.results[r2.job_id])}

    serial = run_chain(pipeline=False, seed=77)
    piped = run_chain(pipeline=True, seed=77)
    assert piped == serial


def test_end_to_end_jax_backend_matches_direct_sweep():
    import jax.numpy as jnp

    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.parallel import sweep
    from distributed_backtesting_exploration_tpu.utils import data

    queue = JobQueue()
    jobs = synthetic_jobs(3, 128, "sma_crossover", GRID, cost=1e-3, seed=5)
    for rec in jobs:
        queue.enqueue(rec)
    disp, srv = _server(queue)
    try:
        w, t = _run_worker(f"localhost:{srv.port}",
                           compute.JaxSweepBackend())
        # Generous timeout: the sweep jit-compiles inside the worker's
        # compute thread, and this box has one CPU core.
        _wait(lambda: queue.drained, timeout=120.0, msg="queue drained")
        t.join(timeout=10)
    finally:
        srv.stop()

    # Direct computation of the same jobs.
    for rec in jobs:
        series = data.from_wire_bytes(rec.ohlcv)
        panel = type(series)(*(jnp.asarray(f)[None, :] for f in series))
        # DBXM param order is canonical: row-major over axes sorted by name
        # (wire.grid_from_proto) — proto map iteration order is unspecified,
        # so decoders must NOT rely on the submitter's dict order.
        canonical_axes = dict(sorted(rec.grid.items()))
        want = sweep.jit_sweep(
            panel, base.get_strategy("sma_crossover"),
            sweep.product_grid(**canonical_axes), cost=1e-3)
        got = wire.metrics_from_bytes(disp.results[rec.id])
        for name in want._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name))[0], rtol=2e-5, atol=2e-6,
                err_msg=name)


def test_dead_worker_jobs_requeued_and_finished_by_second_worker():
    """Fault injection: a worker leases jobs and vanishes; lease expiry +
    peer pruning put them back, and a healthy worker finishes the run."""
    queue = JobQueue(lease_s=0.5)
    for rec in synthetic_jobs(4, 64, "sma_crossover", GRID):
        queue.enqueue(rec)
    disp, srv = _server(queue, prune_window_s=0.5, prune_interval_s=0.05)
    try:
        # Ghost worker: leases 2 jobs via a bare stub, never completes them.
        channel = grpc.insecure_channel(f"localhost:{srv.port}")
        stub = service.DispatcherStub(channel)
        reply = stub.RequestJobs(pb.JobsRequest(
            worker_id="ghost", chips=2, jobs_per_chip=1), timeout=5)
        assert len(reply.jobs) == 2
        channel.close()

        _wait(lambda: queue.stats()["jobs_requeued"] >= 2,
              msg="ghost's leases requeued")
        backend = compute.InstantBackend()
        w, t = _run_worker(f"localhost:{srv.port}", backend)
        _wait(lambda: queue.drained, msg="queue drained by healthy worker")
        assert queue.stats()["jobs_completed"] == 4
        stats = disp.GetStats(pb.StatsRequest(), None)
        assert stats.jobs_completed == 4 and stats.jobs_requeued >= 2
    finally:
        srv.stop()


def test_three_workers_share_queue_without_double_compute():
    """Contention: several live workers race the queue; every job completes
    exactly once (lease discipline + new/dup completion accounting), and
    the per-worker completion counts sum to the job count."""
    queue = JobQueue()
    for rec in synthetic_jobs(30, 32, "sma_crossover", GRID):
        queue.enqueue(rec)
    disp, srv = _server(queue)
    workers = []
    try:
        for i in range(3):
            backend = compute.InstantBackend()
            w, t = _run_worker(f"localhost:{srv.port}", backend,
                               worker_id=f"w{i}", jobs_per_chip=2)
            workers.append((w, t, backend))
        _wait(lambda: queue.drained, msg="queue drained")
        for w, t, _ in workers:
            t.join(timeout=15)
    finally:
        srv.stop()
    s = queue.stats()
    assert s["jobs_completed"] == 30 and s["jobs_failed"] == 0
    total = sum(w.jobs_completed for w, _, _ in workers)
    assert total == 30, f"double-counted completions: {total}"
    # Every job ran exactly once: the backends' seen-lists are disjoint.
    seen = [j for _, _, b in workers for j in b.seen]
    assert len(seen) == len(set(seen)) == 30


def test_worker_survives_dispatcher_restart(tmp_path):
    """The reference panics if the server dies mid-completion; ours retries.

    Run a server, let the worker start polling, stop the server, verify the
    worker thread stays alive through the outage, restart a server on the
    same port with the remaining jobs (journal replay), and finish."""
    jpath = str(tmp_path / "q.jsonl")
    from distributed_backtesting_exploration_tpu.rpc.journal import Journal
    queue = JobQueue(Journal(jpath))
    for rec in synthetic_jobs(2, 64, "sma_crossover", GRID):
        rec.ohlcv, rec.path = rec.ohlcv, None
        queue.enqueue(rec)
    disp, srv = _server(queue)
    port = srv.port
    backend = compute.SleepBackend(0.05)
    w, t = _run_worker(f"localhost:{port}", backend)
    _wait(lambda: queue.stats()["jobs_completed"] >= 1, msg="first completion")
    srv.stop()
    time.sleep(0.3)                      # outage; worker keeps polling
    assert t.is_alive(), "worker must survive a dispatcher outage"

    # Restart on the same port from the journal. Journaled specs carry paths,
    # not inline payloads, so rebuild the pending records with fresh payloads.
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import JobRecord
    queue2 = JobQueue()
    state = Journal.replay(jpath)
    pending = set(state.pending)
    for jid in pending:
        rec = JobRecord.from_journal(state.jobs[jid])
        rec.ohlcv = synthetic_jobs(1, 64, "sma_crossover", GRID)[0].ohlcv
        queue2.enqueue(rec, journal=False)
    disp2 = Dispatcher(queue2, PeerRegistry())
    srv2 = DispatcherServer(disp2, bind=f"localhost:{port}").start()
    try:
        _wait(lambda: queue2.drained, msg="restarted queue drained")
        t.join(timeout=10)
        assert queue2.stats()["jobs_completed"] == len(pending)
    finally:
        srv2.stop()


def test_worker_cli_sigterm_graceful_drain():
    """SIGTERM mid-run: the worker CLI finishes its in-flight batch, flushes
    completions, and exits 0 (the reference worker had no shutdown path —
    its own limitations list, reference README.md:75-88)."""
    import os
    import signal as signal_mod
    import subprocess
    import sys

    queue = JobQueue()
    for rec in synthetic_jobs(6, 32, "sma_crossover", GRID):
        queue.enqueue(rec)
    disp, srv = _server(queue)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "distributed_backtesting_exploration_tpu.rpc.worker",
             "--connect", f"localhost:{srv.port}", "--backend", "sleep",
             "--poll-s", "0.02", "--status-s", "0.1"],
            cwd=repo_root, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        _wait(lambda: queue.stats()["jobs_completed"] >= 1,
              timeout=60.0, msg="first completion before SIGTERM")
        proc.send_signal(signal_mod.SIGTERM)
        out, err = proc.communicate(timeout=60)
        assert proc.returncode == 0, err[-2000:]
    finally:
        srv.stop()
    s = queue.stats()
    # At least the pre-signal completion landed, and nothing was lost in a
    # crash: every non-completed job is either still pending or back on the
    # queue via its lease (not stuck leased to a dead process forever).
    assert s["jobs_completed"] >= 1
    assert s["jobs_completed"] + s["jobs_pending"] + s["jobs_leased"] == 6


def test_empty_queue_returns_empty_reply_not_error():
    queue = JobQueue()
    disp, srv = _server(queue)
    try:
        channel = grpc.insecure_channel(f"localhost:{srv.port}")
        stub = service.DispatcherStub(channel)
        reply = stub.RequestJobs(pb.JobsRequest(
            worker_id="w", chips=1), timeout=5)
        assert len(reply.jobs) == 0      # no gRPC error raised
        stats = stub.GetStats(pb.StatsRequest(), timeout=5)
        assert stats.workers_alive == 1
        channel.close()
    finally:
        srv.stop()


# Wire-contract grid table for every registered strategy (+ the two-legged
# pairs path). Tier-1 runs the four structurally distinct decode shapes
# (test_representative_strategies_travel_the_wire); the full-registry loop
# is its slow twin — each family costs a ~4s generic-path CPU compile and
# the per-kernel fused/generic parity lives elsewhere in tier-1.
_WIRE_GRIDS = {
        "sma_crossover": {"fast": np.float32([3, 5]),
                          "slow": np.float32([13.0])},
        "momentum": {"lookback": np.float32([5, 10])},
        "bollinger": {"window": np.float32([10, 20]),   # two multi-valued
                      "k": np.float32([1.0, 2.0])},     # axes: order matters
        "bollinger_touch": {"window": np.float32([10.0]),
                            "k": np.float32([1.0, 2.0])},
        "donchian": {"window": np.float32([10, 20])},
        "donchian_hl": {"window": np.float32([10, 20])},
        "rsi": {"period": np.float32([7.0]), "band": np.float32([20.0])},
        "stochastic": {"window": np.float32([10.0]),
                       "band": np.float32([25.0])},
        "keltner": {"window": np.float32([12.0]),
                    "k": np.float32([1.5])},
        "macd": {"fast": np.float32([5.0]), "slow": np.float32([13.0]),
                 "signal": np.float32([4.0])},
        "trix": {"span": np.float32([6.0, 9.0]),
                 "signal": np.float32([4.0])},
        "obv_trend": {"window": np.float32([8.0, 15.0])},
        "vwap_reversion": {"window": np.float32([8.0]),
                           "k": np.float32([1.0])},
        "pairs": {"lookback": np.float32([10.0]),
                  "z_entry": np.float32([1.0])},
}


def _assert_strategies_travel_the_wire(grids):
    """Each strategy round-trips through the worker backend (decode, grid
    materialization, routing, metric packing) and matches the direct sweep
    on the same panels — no family is CLI/RPC-only on paper."""
    import jax.numpy as jnp

    from distributed_backtesting_exploration_tpu.models import base, pairs
    from distributed_backtesting_exploration_tpu.parallel import sweep
    from distributed_backtesting_exploration_tpu.utils import data

    backend = compute.JaxSweepBackend(use_fused=False)
    for strategy, grid in grids.items():
        recs = synthetic_jobs(2, 128, strategy, grid, cost=1e-3, seed=3)
        specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                            ohlcv2=r.ohlcv2 or b"",
                            grid=wire.grid_to_proto(r.grid), cost=r.cost)
                 for r in recs]
        got = {c.job_id: wire.metrics_from_bytes(c.metrics)
               for c in backend.process(specs)}
        assert set(got) == {r.id for r in recs}, strategy

        # Canonical sorted-axis order — the wire contract's DBXM row order
        # (wire.grid_from_proto) — not dict insertion order.
        flat = sweep.product_grid(
            **{k: jnp.asarray(v) for k, v in sorted(grid.items())})
        if strategy == "pairs":
            ys = [data.from_wire_bytes(s.ohlcv) for s in specs]
            xs = [data.from_wire_bytes(s.ohlcv2) for s in specs]
            want = pairs.run_pairs_sweep(
                jnp.asarray(np.stack([y.close for y in ys])),
                jnp.asarray(np.stack([x.close for x in xs])),
                dict(flat), cost=1e-3)
        else:
            series = [data.from_wire_bytes(s.ohlcv) for s in specs]
            panel = type(series[0])(
                *(jnp.asarray(np.stack([np.asarray(getattr(s, f))
                                        for s in series]))
                  for f in series[0]._fields))
            want = sweep.jit_sweep(panel, base.get_strategy(strategy),
                                   dict(flat), cost=1e-3)
        for i, rec in enumerate(recs):
            for name in want._fields:
                np.testing.assert_allclose(
                    np.asarray(getattr(got[rec.id], name)),
                    np.asarray(getattr(want, name))[i],
                    rtol=2e-4, atol=2e-5,
                    err_msg=f"{strategy}/{name}")


def test_representative_strategies_travel_the_wire():
    """Tier-1 twin of the full-registry loop: the four structurally distinct
    wire shapes — single-field close-only (sma), multi-valued multi-axis
    grid ordering (bollinger), 3-param grid (macd), and the two-legged
    ohlcv2 path (pairs). Also pins the registry against _WIRE_GRIDS so a new
    strategy family can't dodge the slow completeness loop unnoticed."""
    from distributed_backtesting_exploration_tpu.models import base

    # Pairs is the two-legged path (models/pairs.py), not a registry entry.
    assert set(_WIRE_GRIDS) - {"pairs"} == set(base.available_strategies()), (
        "registry changed; extend _WIRE_GRIDS")
    rep = ("sma_crossover", "bollinger", "macd", "pairs")
    _assert_strategies_travel_the_wire({k: _WIRE_GRIDS[k] for k in rep})


@pytest.mark.slow   # ~4s generic-path CPU compile per family, x14 families
def test_every_registered_strategy_travels_the_wire():
    rest = {k: v for k, v in _WIRE_GRIDS.items()
            if k not in ("sma_crossover", "bollinger", "macd", "pairs")}
    _assert_strategies_travel_the_wire(rest)


def test_walkforward_jobs_over_the_wire_match_direct():
    """Walk-forward mode (JobSpec.wf_*): the worker backend's stitched OOS
    metrics row per job must equal the direct walk_forward result; a job
    too short for one train+test window completes with an empty block."""
    import jax.numpy as jnp

    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.parallel import (
        sweep, walkforward)
    from distributed_backtesting_exploration_tpu.utils import data

    grid = {"fast": np.float32([3, 5]), "slow": np.float32([13.0])}
    recs = synthetic_jobs(3, 200, "sma_crossover", grid, cost=1e-3, seed=7,
                          wf_train=80, wf_test=30, wf_metric="sharpe")
    short = synthetic_jobs(1, 60, "sma_crossover", grid, cost=1e-3, seed=8,
                           wf_train=80, wf_test=30, wf_metric="sharpe")
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        grid=wire.grid_to_proto(r.grid), cost=r.cost,
                        wf_train=r.wf_train, wf_test=r.wf_test,
                        wf_metric=r.wf_metric)
             for r in recs + short]
    got = {c.job_id: c.metrics
           for c in compute.JaxSweepBackend(use_fused=False).process(specs)}
    assert got[short[0].id] == b""   # too short: empty block, still completed

    series = [data.from_wire_bytes(s.ohlcv) for s in specs[:3]]
    panel = type(series[0])(
        *(jnp.asarray(np.stack([np.asarray(getattr(s, f)) for s in series]))
          for f in series[0]._fields))
    flat = sweep.product_grid(
        **{k: jnp.asarray(v) for k, v in sorted(grid.items())})
    want = walkforward.walk_forward(
        panel, base.get_strategy("sma_crossover"), dict(flat), train=80,
        test=30, metric="sharpe", cost=1e-3).oos_metrics
    for i, rec in enumerate(recs):
        m = wire.metrics_from_bytes(got[rec.id])
        for name in m._fields:
            got_v = np.asarray(getattr(m, name))
            assert got_v.shape == (1,), f"{name}: one OOS row expected"
            np.testing.assert_allclose(
                got_v[0], np.asarray(getattr(want, name))[i],
                rtol=2e-4, atol=2e-5, err_msg=name)


def test_walkforward_job_record_journal_roundtrip(tmp_path):
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        JobRecord)

    rec = JobRecord(id="w1", strategy="sma_crossover",
                    grid={"fast": np.float32([3.0])}, cost=1e-3,
                    ohlcv=b"\x01\x02", wf_train=80, wf_test=30,
                    wf_metric="sortino")
    back = JobRecord.from_journal(rec.journal_form())
    assert (back.wf_train, back.wf_test, back.wf_metric) == (80, 30,
                                                             "sortino")
    plain = JobRecord.from_journal(
        JobRecord(id="p1", strategy="sma_crossover",
                  grid={}, ohlcv=b"\x01").journal_form())
    assert (plain.wf_train, plain.wf_test, plain.wf_metric) == (0, 0, "")


def test_walkforward_unknown_metric_completes_empty():
    """A typo'd wf_metric must complete the jobs with empty blocks (loud
    error), never raise — raising would requeue the group through lease
    expiry forever."""
    grid = {"fast": np.float32([3.0]), "slow": np.float32([13.0])}
    recs = synthetic_jobs(2, 200, "sma_crossover", grid, cost=1e-3, seed=9,
                          wf_train=80, wf_test=30, wf_metric="sharp")
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        grid=wire.grid_to_proto(r.grid), cost=r.cost,
                        wf_train=r.wf_train, wf_test=r.wf_test,
                        wf_metric=r.wf_metric) for r in recs]
    got = {c.job_id: c.metrics
           for c in compute.JaxSweepBackend(use_fused=False).process(specs)}
    assert set(got) == {r.id for r in recs}
    assert all(v == b"" for v in got.values())


def test_chaos_soak_exactly_once(tmp_path):
    """Combined-failure soak: three workers churn a journaled queue while a
    ghost worker abandons leases and the dispatcher restarts mid-run. Every
    job must complete EXACTLY once (the journal's completion record is the
    witness) — none lost, none double-recorded."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        JobRecord)
    from distributed_backtesting_exploration_tpu.rpc.journal import Journal

    n_jobs = 40
    jpath = str(tmp_path / "q.jsonl")
    queue = JobQueue(Journal(jpath), lease_s=1.0)
    recs = synthetic_jobs(n_jobs, 48, "sma_crossover", GRID, seed=3)
    for rec in recs:
        queue.enqueue(rec)
    disp, srv = _server(queue, prune_window_s=2.0)
    port = srv.port

    # A ghost takes leases and vanishes: expiry must requeue its jobs.
    ghost_taken = queue.take(6, "ghost-worker")
    assert len(ghost_taken) == 6

    # No idle self-exit: a momentarily-empty queue (ghost jobs leased,
    # everything else dispatched) must not let the fleet die pre-crash.
    workers = [_run_worker(f"localhost:{port}", compute.InstantBackend(),
                           max_idle_polls=None)
               for _ in range(3)]
    _wait(lambda: queue.stats()["jobs_completed"] >= n_jobs // 3,
          timeout=60.0, msg="first third completed")

    # Dispatcher crash + restart on the same port, state from the journal.
    srv.stop()
    time.sleep(0.3)
    assert all(t.is_alive() for _, t in workers)
    state = Journal.replay(jpath)
    queue2 = JobQueue(lease_s=1.0)
    for jid in state.pending:
        # Inline payloads are journaled (ohlcv_b64), so from_journal
        # restores a fully dispatchable record.
        queue2.enqueue(JobRecord.from_journal(state.jobs[jid]),
                       journal=False)
    already = len(state.completed)
    disp2 = Dispatcher(queue2, PeerRegistry(prune_window_s=2.0))
    srv2 = DispatcherServer(disp2, bind=f"localhost:{port}",
                            prune_interval_s=0.1).start()
    try:
        _wait(lambda: queue2.drained, timeout=120.0,
              msg="post-restart queue drained")
        for w, t in workers:
            w.stop()
        for w, t in workers:
            t.join(timeout=10)
    finally:
        srv2.stop()

    # Exactly once: pre-crash completions + post-crash completions cover
    # every job id with no overlap and no loss.
    assert already + queue2.stats()["jobs_completed"] == n_jobs
    post = queue2.completed_ids()
    assert set(state.completed).isdisjoint(post)
    assert set(state.completed) | post == {r.id for r in recs}


def test_topk_jobs_over_the_wire_match_direct_sweep(tmp_path):
    """JobSpec.top_k: workers reduce on-device and ship DBXS blocks whose
    rows are the direct sweep's top-k by the rank metric (the reduce-on-
    chip, move-scalars-over-DCN mode)."""
    import jax.numpy as jnp

    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.parallel import sweep
    from distributed_backtesting_exploration_tpu.utils import data

    grid = parse_grid("fast=3:6,slow=10:16:2")   # P = 9 combos
    k = 4
    queue = JobQueue()
    recs = synthetic_jobs(4, 96, "sma_crossover", grid, cost=1e-3, seed=3,
                          top_k=k, rank_metric="sharpe")
    for rec in recs:
        queue.enqueue(rec)
    results = tmp_path / "results"
    disp, srv = _server(queue, results_dir=str(results))
    try:
        w, t = _run_worker(f"localhost:{srv.port}",
                           compute.JaxSweepBackend(use_fused=False))
        _wait(lambda: queue.drained, msg="queue drained")
    finally:
        srv.stop()

    canonical_axes = sweep.product_grid(**dict(sorted(recs[0].grid.items())))
    for rec in recs:
        blob = (results / f"{rec.id}.dbxm").read_bytes()
        assert wire.result_kind(blob) == "topk"
        idx, got, metric = wire.topk_from_bytes(blob)
        assert metric == "sharpe" and idx.shape == (k,)

        series = data.from_wire_bytes(rec.ohlcv)
        panel = type(series)(*(jnp.asarray(f)[None, :] for f in series))
        want = sweep.jit_sweep(panel, base.get_strategy("sma_crossover"),
                               canonical_axes, cost=1e-3)
        sharpe = np.asarray(want.sharpe)[0]
        order = np.argsort(-sharpe, kind="stable")[:k]
        np.testing.assert_array_equal(np.sort(idx), np.sort(order))
        # Rows are best-first and carry the full metric tuple at idx.
        np.testing.assert_allclose(np.asarray(got.sharpe),
                                   sharpe[idx], rtol=1e-5, atol=1e-6)
        for name in want._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name))[0][idx],
                rtol=2e-4, atol=2e-5, err_msg=name)


def test_topk_unknown_rank_metric_completes_empty(tmp_path):
    """A top-k request naming an unknown metric is validated-bad: the jobs
    complete with EMPTY payloads (no requeue loop, no result files)."""
    queue = JobQueue()
    recs = synthetic_jobs(2, 64, "sma_crossover", GRID, top_k=3,
                          rank_metric="not_a_metric")
    for rec in recs:
        queue.enqueue(rec)
    results = tmp_path / "results"
    disp, srv = _server(queue, results_dir=str(results))
    try:
        w, t = _run_worker(f"localhost:{srv.port}",
                           compute.JaxSweepBackend(use_fused=False))
        _wait(lambda: queue.drained, msg="queue drained")
        s = queue.stats()
        assert s["jobs_completed"] == 2
        assert not list(results.glob("*.dbxm"))
    finally:
        srv.stop()


def test_topk_fused_and_pairs_paths_match_generic():
    """top_k composes with the fused routing and the two-legged pairs path
    (backend-level, no server): each completion is a DBXS block matching
    the corresponding full sweep's top-k rows."""
    from distributed_backtesting_exploration_tpu.parallel import sweep

    k = 3
    grid = parse_grid("fast=3:5,slow=10:14:2")
    sma = synthetic_jobs(2, 96, "sma_crossover", grid, cost=1e-3, seed=6,
                         top_k=k, rank_metric="total_return")
    pgrid = parse_grid("lookback=6;10,z_entry=0.5;1.0;1.5")
    prs = synthetic_jobs(2, 96, "pairs", pgrid, cost=1e-3, seed=7,
                         top_k=k, rank_metric="sharpe")
    recs = sma + prs
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        ohlcv2=r.ohlcv2 or b"",
                        grid=wire.grid_to_proto(r.grid), cost=r.cost,
                        periods_per_year=252, top_k=r.top_k,
                        rank_metric=r.rank_metric) for r in recs]
    fused_backend = compute.JaxSweepBackend(use_fused=True)
    got = {c.job_id: c.metrics for c in fused_backend.process(specs)}

    full_specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                             ohlcv2=r.ohlcv2 or b"",
                             grid=wire.grid_to_proto(r.grid), cost=r.cost,
                             periods_per_year=252) for r in recs]
    full = {c.job_id: wire.metrics_from_bytes(c.metrics)
            for c in compute.JaxSweepBackend(use_fused=True)
            .process(full_specs)}

    from distributed_backtesting_exploration_tpu.ops.metrics import (
        metric_sign)

    for rec in recs:
        idx, m, metric = wire.topk_from_bytes(got[rec.id])
        assert metric == rec.rank_metric
        ref = np.asarray(getattr(full[rec.id], metric))
        order = np.argsort(-metric_sign(metric) * ref, kind="stable")[:k]
        np.testing.assert_array_equal(np.sort(idx), np.sort(order))
        np.testing.assert_allclose(np.asarray(getattr(m, metric)),
                                   ref[idx], rtol=1e-5, atol=1e-6)


def test_pairs_walkforward_jobs_over_the_wire_match_direct():
    """Walk-forward pairs jobs (JobSpec.wf_* + two legs): the worker's
    stitched OOS row per job equals walk_forward_pairs directly; a job too
    short for one train+test window completes with an empty block."""
    import jax.numpy as jnp

    from distributed_backtesting_exploration_tpu.parallel import (
        sweep, walkforward)
    from distributed_backtesting_exploration_tpu.utils import data

    grid = parse_grid("lookback=8;12,z_entry=0.8;1.5")
    recs = synthetic_jobs(3, 240, "pairs", grid, cost=1e-3, seed=21,
                          wf_train=120, wf_test=40, wf_metric="sharpe")
    short = synthetic_jobs(1, 60, "pairs", grid, cost=1e-3, seed=22,
                           wf_train=120, wf_test=40, wf_metric="sharpe")
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        ohlcv2=r.ohlcv2, grid=wire.grid_to_proto(r.grid),
                        cost=r.cost, wf_train=r.wf_train, wf_test=r.wf_test,
                        wf_metric=r.wf_metric)
             for r in recs + short]
    got = {c.job_id: c.metrics
           for c in compute.JaxSweepBackend(use_fused=False).process(specs)}
    assert got[short[0].id] == b""   # too short: empty block, completed

    ys = [data.from_wire_bytes(r.ohlcv) for r in recs]
    xs = [data.from_wire_bytes(r.ohlcv2) for r in recs]
    y = jnp.asarray(np.stack([np.asarray(s.close) for s in ys]))
    x = jnp.asarray(np.stack([np.asarray(s.close) for s in xs]))
    flat = sweep.product_grid(
        **{k: jnp.asarray(v) for k, v in sorted(grid.items())})
    want = walkforward.walk_forward_pairs(
        y, x, dict(flat), train=120, test=40, metric="sharpe",
        cost=1e-3).oos_metrics
    for i, rec in enumerate(recs):
        m = wire.metrics_from_bytes(got[rec.id])
        for name in m._fields:
            got_v = np.asarray(getattr(m, name))
            assert got_v.shape == (1,), f"{name}: one OOS row expected"
            np.testing.assert_allclose(
                got_v[0], np.asarray(getattr(want, name))[i],
                rtol=2e-4, atol=2e-5, err_msg=name)


def test_best_returns_jobs_over_the_wire_match_direct_composition(tmp_path):
    """JobSpec.best_returns end to end over real gRPC: workers ship DBXP
    blocks (best combo + net-return series) and `aggregate --portfolio`
    composes them into the book the direct library composition produces."""
    import jax.numpy as jnp

    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.parallel import (
        portfolio as portfolio_mod, sweep)
    from distributed_backtesting_exploration_tpu.rpc import aggregate
    from distributed_backtesting_exploration_tpu.rpc.journal import Journal
    from distributed_backtesting_exploration_tpu.utils import data

    journal_path = str(tmp_path / "journal.jsonl")
    results = tmp_path / "results"
    queue = JobQueue(Journal(journal_path))
    grid = parse_grid("fast=3:6,slow=10:16:2")
    recs = synthetic_jobs(4, 96, "sma_crossover", grid, cost=1e-3, seed=9,
                          best_returns=True, rank_metric="sharpe")
    for rec in recs:
        queue.enqueue(rec)
    disp, srv = _server(queue, results_dir=str(results))
    try:
        _run_worker(f"localhost:{srv.port}",
                    compute.JaxSweepBackend(use_fused=False))
        _wait(lambda: queue.drained, msg="queue drained")
    finally:
        srv.stop()

    for rec in recs:
        blob = (results / f"{rec.id}.dbxm").read_bytes()
        assert wire.result_kind(blob) == "returns"
        _, _, ret, metric = wire.best_returns_from_bytes(blob)
        assert metric == "sharpe" and ret.shape == (96,)

    out = aggregate.portfolio(str(results), journal_path, weights="equal")
    assert out["legs_composed"] == 4 and out["bars"] == 96

    series = [data.from_wire_bytes(rec.ohlcv) for rec in recs]
    panel = type(series[0])(*(jnp.stack([np.asarray(getattr(s, f))
                                         for s in series])
                              for f in series[0]._fields))
    canonical = sweep.product_grid(**dict(sorted(recs[0].grid.items())))
    pm, _ = portfolio_mod.sweep_and_compose(
        panel, base.get_strategy("sma_crossover"), canonical, cost=1e-3)
    assert out["portfolio"]["sharpe"] == pytest.approx(
        float(pm.sharpe), rel=2e-4, abs=2e-5)


def test_obs_end_to_end_metrics_and_extended_stats(tmp_path):
    """The observability acceptance path: a dispatcher+worker run exports
    non-empty RPC latency histograms, queue-depth gauges, and worker
    per-batch span timings via BOTH /metrics (Prometheus text) and the
    extended GetStats obs_json payload."""
    import json
    import urllib.request

    from distributed_backtesting_exploration_tpu import obs
    from distributed_backtesting_exploration_tpu.obs import dump

    # Fresh registry: assertions must not depend on what earlier tests
    # recorded into the process-global one. The worker's span chain and
    # the compute backend record globally, so only dispatcher/worker
    # families use the injected registry.
    reg = obs.Registry()
    queue = JobQueue()
    for rec in synthetic_jobs(8, 64, "sma_crossover", GRID):
        queue.enqueue(rec)
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=10.0),
                      results_dir=str(tmp_path / "results"), registry=reg)
    srv = DispatcherServer(disp, bind="localhost:0", prune_interval_s=0.1,
                           metrics_port=0).start()
    try:
        backend = compute.InstantBackend()
        w = Worker(f"localhost:{srv.port}", backend, poll_interval_s=0.02,
                   status_interval_s=0.05, registry=reg)
        t = threading.Thread(target=lambda: w.run(max_idle_polls=10),
                             daemon=True)
        t.start()
        _LIVE_WORKERS.append((w, t))
        _wait(lambda: queue.drained, msg="queue drained")

        # -- extended stats over the existing wire --------------------------
        import grpc as grpc_mod

        from distributed_backtesting_exploration_tpu.rpc import (
            backtesting_pb2 as pb2, service as service_mod)

        channel = grpc_mod.insecure_channel(f"localhost:{srv.port}")
        try:
            stub = service_mod.DispatcherStub(channel)
            reply = stub.GetStats(pb2.StatsRequest(), timeout=10.0)
            assert reply.jobs_completed == 8
            ext = json.loads(reply.obs_json)
        finally:
            channel.close()
        assert ext["dbx_rpc_seconds{method=RequestJobs}"]["count"] > 0
        assert ext["dbx_rpc_seconds{method=CompleteJobs}"]["count"] > 0
        assert ext["dbx_rpc_seconds{method=RequestJobs}"]["sum"] > 0
        assert ext["dbx_queue_jobs{pool=completed}"] == 8.0
        assert ext["dbx_queue_jobs{pool=pending}"] == 0.0
        assert ext["dbx_jobs_dispatched_total"] == 8.0
        assert ext["dbx_completions_total{outcome=new}"] == 8.0

        # -- /metrics (Prometheus text) -------------------------------------
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.metrics.port}/metrics",
            timeout=10).read().decode()
        assert 'dbx_rpc_seconds_count{method="RequestJobs"}' in body
        assert 'dbx_rpc_seconds_bucket{method="RequestJobs",le="+Inf"}' \
            in body
        assert 'dbx_queue_jobs{pool="completed"} 8.0' in body
        # worker-side client RPC latency + per-batch span chain
        assert 'dbx_worker_rpc_seconds_count{method="CompleteJobs"}' in body
        ws = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.metrics.port}/stats.json",
            timeout=10).read())
        spans = obs.get_registry().summaries(prefix="dbx_span")
        assert spans["dbx_span_seconds{span=worker.process}"]["count"] > 0
        assert ws["dbx_worker_rpc_seconds"]["type"] == "histogram"

        # -- dump CLI smoke against the live endpoint -----------------------
        assert dump.main([f"http://127.0.0.1:{srv.metrics.port}"]) == 0
    finally:
        srv.stop()


def test_obs_pipelined_span_chain_and_kernel_attribution(tmp_path):
    """The JAX backend populates the decode -> submit -> collect span chain,
    per-route kernel wall-time, and the JSONL event log — and (round 7)
    every span joins the dispatcher-minted trace: one merged trace per
    job whose reconstructed timeline covers the whole lifecycle with
    critical-path stage attribution summing to the measured end-to-end
    wall (the acceptance contract)."""
    import json

    from distributed_backtesting_exploration_tpu import obs
    from distributed_backtesting_exploration_tpu.obs import (
        events, timeline)

    jsonl = str(tmp_path / "events.jsonl")
    events.configure(jsonl)
    try:
        queue = JobQueue()
        jobs = synthetic_jobs(3, 64, "sma_crossover", GRID)
        for rec in jobs:
            queue.enqueue(rec)
        # Minted at enqueue: every record carries a distinct trace id.
        assert all(rec.trace_id for rec in jobs)
        assert len({rec.trace_id for rec in jobs}) == 3
        disp, srv = _server(queue, results_dir=str(tmp_path / "results"))
        try:
            _run_worker(f"localhost:{srv.port}",
                        compute.JaxSweepBackend(use_fused=True))
            _wait(lambda: queue.drained, msg="queue drained")
        finally:
            srv.stop()
    finally:
        events.configure(None)

    s = obs.get_registry().summaries()
    assert s["dbx_span_seconds{span=worker.submit}"]["count"] > 0
    assert s["dbx_span_seconds{span=worker.collect}"]["count"] > 0
    assert s["dbx_span_seconds{span=worker.report}"]["count"] > 0
    assert s["dbx_compute_decode_seconds"]["count"] > 0
    assert s["dbx_compute_decode_bytes_total"] > 0
    assert s["dbx_compute_collect_seconds"]["count"] > 0
    assert s["dbx_compute_d2h_bytes_total"] > 0
    # per-strategy kernel wall keyed by route:strategy, compile/execute split
    kern = [k for k in s if k.startswith("dbx_kernel_submit_seconds")
            and "fused:sma_crossover" in k]
    assert kern, sorted(k for k in s if k.startswith("dbx_kernel"))
    assert any("phase=compile" in k for k in kern)
    # combos credited: 3 jobs x |GRID| combos
    import numpy as np

    combos = int(np.prod([v.size for v in GRID.values()]))
    assert s["dbx_backtests_total"] >= 3 * combos
    # event log carries the span chain for post-mortem reconstruction
    names = {json.loads(ln)["name"] for ln in open(jsonl)
             if json.loads(ln).get("ev") == "span"}
    assert {"worker.submit", "worker.collect", "worker.report"} <= names

    # -- distributed-trace stitching (the tentpole acceptance) --------------
    evs, malformed = timeline.parse_events([jsonl])
    assert malformed == 0
    timelines = timeline.reconstruct(evs)
    by_trace = {rec.trace_id: rec for rec in jobs}
    assert set(timelines) == set(by_trace)
    all_stages_seen = set()
    per_job_stages = {}
    for tid, tl in timelines.items():
        assert tl.job_id == by_trace[tid].id
        span_names = {sp["name"] for sp in tl.spans}
        # Dispatcher- and worker-side spans share ONE trace id.
        assert {"job.queue_wait", "job.dispatch", "job",
                "worker.submit", "worker.report"} <= span_names
        # Worker-side chain parents onto the dispatcher's dispatch span.
        dispatch_sid = next(sp["span_id"] for sp in tl.spans
                            if sp["name"] == "job.dispatch")
        submit = next(sp for sp in tl.spans
                      if sp["name"] == "worker.submit")
        assert submit["parent_id"] == dispatch_sid
        # Critical-path stage attribution sums to the measured e2e wall
        # (within the acceptance's 10% slack; equality by construction,
        # the slack absorbs clock jitter only).
        stages = timeline.critical_path(tl)
        assert tl.e2e_dur > 0
        assert sum(stages.values()) == pytest.approx(tl.e2e_dur, rel=0.10)
        assert stages["queue_wait"] > 0 and stages["dispatch"] > 0
        per_job_stages[tid] = {k for k, v in stages.items() if v > 0}
        all_stages_seen |= per_job_stages[tid]
    # Across the batch every lifecycle stage appears (compile lands on the
    # cold-jit job, execute on the warm ones; jobs_per_chip=1 dispatches
    # them as separate single-job batches)...
    assert {"queue_wait", "dispatch", "decode", "compile", "execute",
            "d2h", "report"} <= all_stages_seen
    # ...and the cold job's SINGLE timeline contains the full lifecycle
    # (the acceptance contract: one job, one merged trace, every stage).
    assert any({"queue_wait", "dispatch", "decode", "compile", "execute",
                "d2h", "report"} <= st for st in per_job_stages.values()), \
        per_job_stages

    # The obs_json wire surface ships the same spans (bounded ring tail).
    ext = json.loads(disp.GetStats(pb.StatsRequest(), None).obs_json)
    ring_names = {r["name"] for r in ext["dbx_spans_recent"]}
    assert {"job", "job.dispatch"} <= ring_names

    # CLI smoke over the real log: text + json, --job filter.
    assert timeline.main(["--jsonl", jsonl, "--format", "json",
                          "--job", jobs[0].id]) == 0


@pytest.mark.slow   # subprocess worker + real cross-process log merge
def test_trace_stitching_across_processes(tmp_path):
    """Multi-process twin of the stitching test: the dispatcher logs to
    one JSONL in this process while a worker CLI SUBPROCESS (DBX_OBS_JSONL
    env opt-in) logs to another; obs.timeline merges the two files into
    one trace per job with both processes' spans."""
    import json as json_mod
    import os
    import subprocess
    import sys

    from distributed_backtesting_exploration_tpu.obs import (
        events, timeline)

    disp_log = str(tmp_path / "dispatcher.jsonl")
    work_log = str(tmp_path / "worker.jsonl")
    events.configure(disp_log)
    try:
        queue = JobQueue()
        jobs = synthetic_jobs(4, 32, "sma_crossover", GRID)
        for rec in jobs:
            queue.enqueue(rec)
        disp, srv = _server(queue)
        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 "distributed_backtesting_exploration_tpu.rpc.worker",
                 "--connect", f"localhost:{srv.port}", "--backend", "sleep",
                 "--poll-s", "0.02", "--status-s", "0.1",
                 "--exit-after-idle", "10"],
                cwd=repo_root, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
                env={**os.environ, "DBX_OBS_JSONL": work_log,
                     "JAX_PLATFORMS": "cpu"})
            out, err = proc.communicate(timeout=120)
            assert proc.returncode == 0, err[-2000:]
            assert queue.stats()["jobs_completed"] == 4
        finally:
            srv.stop()
    finally:
        events.configure(None)

    evs, _ = timeline.parse_events([disp_log, work_log])
    pids = {r.get("pid") for r in evs}
    assert len(pids) == 2, "expected spans from two processes"
    timelines = timeline.reconstruct(evs)
    assert set(timelines) == {rec.trace_id for rec in jobs}
    for tl in timelines.values():
        names = {sp["name"] for sp in tl.spans}
        assert {"job.queue_wait", "job.dispatch", "job"} <= names
        assert {"worker.process", "worker.report"} & names
        assert len({sp["pid"] for sp in tl.spans}) == 2
        stages = timeline.critical_path(tl)
        assert sum(stages.values()) == pytest.approx(tl.e2e_dur, rel=0.10)
        assert stages["execute"] > 0 and stages["report"] >= 0


def _shared_panel_jobs(n, n_bars=96, seed=11, grid=None):
    """N sma jobs all carrying the SAME panel bytes — the multi-job-per-
    panel workload dispatch-by-digest exists for."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        JobRecord)
    from distributed_backtesting_exploration_tpu.utils import data

    series = data.synthetic_ohlcv(1, n_bars, seed=seed)
    one = type(series)(*(np.asarray(f[0]) for f in series))
    blob = data.to_wire_bytes(one)
    return one, [JobRecord(id=f"dig-{seed}-{i}", strategy="sma_crossover",
                           grid=grid or GRID, cost=1e-3, ohlcv=blob)
                 for i in range(n)]


def test_dispatch_by_digest_cache_hits_and_matching_results(tmp_path,
                                                            monkeypatch):
    """The dispatch-by-digest tentpole end to end: jobs sharing ONE panel
    ship the bytes once (every later delivery is digest-only), the
    worker's two-level cache serves the repeats — decode AND h2d skipped,
    asserted via the spans' cache_hit attrs — and the stored results
    still match the direct sweep.

    Pinned to the DENSE path (DBX_PAGED=0): with round-10 paging live,
    fused groups serve from the page pool and never touch the device
    block level this test asserts — the paged twin of this flow (pool
    hits, no re-upload on warm re-submit) lives in tests/test_paged.py,
    and the kill switch gets its integration coverage here."""
    import jax.numpy as jnp

    from distributed_backtesting_exploration_tpu import obs
    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.parallel import sweep

    monkeypatch.setenv("DBX_PAGED", "0")
    one, recs = _shared_panel_jobs(4)
    queue = JobQueue()
    for rec in recs:
        queue.enqueue(rec)
    # Content-addressed: four jobs, ONE stored panel.
    assert queue.panel_store.stats()["panels"] == 1
    assert len({r.panel_digest for r in recs}) == 1

    disp, srv = _server(queue, results_dir=str(tmp_path / "results"))
    backend = compute.JaxSweepBackend(use_fused=True)
    digest_only0 = disp._c_payloads["digest_only"].value
    saved0 = disp._c_bytes_saved.value
    host_hits0 = backend.panel_cache._c_hits["host"].value
    dev_hits0 = backend.panel_cache._c_hits["device"].value
    try:
        # jobs_per_chip=1 -> one job per poll, so deliveries 2..4 are
        # digest-only and hit the cache delivery 1 primed.
        w, t = _run_worker(f"localhost:{srv.port}", backend,
                           jobs_per_chip=1)
        _wait(lambda: queue.drained, msg="queue drained")
        t.join(timeout=10)
    finally:
        srv.stop()
    assert w.jobs_completed == 4
    assert queue.stats()["jobs_failed"] == 0
    assert disp._c_payloads["digest_only"].value - digest_only0 >= 3
    assert disp._c_bytes_saved.value - saved0 >= 3 * len(recs[0].ohlcv)
    assert backend.panel_cache._c_hits["host"].value - host_hits0 >= 3
    assert backend.panel_cache._c_hits["device"].value - dev_hits0 >= 3
    # The spans say so too (obs.timeline's panel_cache_hit pseudo-stage
    # and the h2d-skip report key on these attrs).
    ring = obs.recent_spans()
    assert any(s.get("name") == "worker.decode" and s.get("cache_hit")
               for s in ring), "no cache_hit decode span reached the ring"
    assert any(s.get("name") == "worker.d2h" and s.get("cache_hit")
               for s in ring), "no device-cache-hit d2h span in the ring"

    # Digest-only dispatch must not change a single metric bit vs the
    # directly-computed sweep.
    panel = type(one)(*(jnp.asarray(f)[None, :] for f in one))
    want = sweep.jit_sweep(
        panel, base.get_strategy("sma_crossover"),
        sweep.product_grid(**dict(sorted(GRID.items()))), cost=1e-3)
    for rec in recs:
        got = wire.metrics_from_bytes(
            (tmp_path / "results" / f"{rec.id}.dbxm").read_bytes())
        for name in want._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name))[0], rtol=2e-4, atol=2e-5,
                err_msg=name)


def test_digest_only_miss_recovers_via_fetch_payload(tmp_path, monkeypatch):
    """Third leg of graceful degradation: a worker whose cache cannot
    retain anything (DBX_PANEL_CACHE_MB=0) receives digest-only jobs,
    misses, and recovers the bytes by content address over FetchPayload —
    every job still completes; none fail, none wedge."""
    monkeypatch.setenv("DBX_PANEL_CACHE_MB", "0")
    _, recs = _shared_panel_jobs(4, seed=12)
    queue = JobQueue()
    for rec in recs:
        queue.enqueue(rec)
    disp, srv = _server(queue, results_dir=str(tmp_path / "results"))
    backend = compute.JaxSweepBackend(use_fused=True)
    assert backend.panel_cache.max_bytes == 0
    fetch_hits0 = disp._c_fetches["hit"].value
    try:
        w, t = _run_worker(f"localhost:{srv.port}", backend,
                           jobs_per_chip=1)
        _wait(lambda: queue.drained, msg="queue drained")
        t.join(timeout=10)
    finally:
        srv.stop()
    assert w.jobs_completed == 4
    assert queue.stats()["jobs_failed"] == 0
    # Deliveries 2..4 were digest-only; each recovered via FetchPayload.
    assert disp._c_fetches["hit"].value - fetch_hits0 >= 3
    assert len(list((tmp_path / "results").glob("*.dbxm"))) == 4


def test_digest_only_requires_worker_capability_flag(tmp_path):
    """Rolling-upgrade safety: a client that does NOT set
    JobsRequest.accepts_digest_only (an older worker binary, proto3
    default false) always receives full payload bytes — even for a panel
    the dispatcher already delivered to it — because it has no
    FetchPayload to recover an empty ohlcv with."""
    import grpc

    from distributed_backtesting_exploration_tpu.rpc import service

    _, recs = _shared_panel_jobs(3, seed=13)
    queue = JobQueue()
    for rec in recs:
        queue.enqueue(rec)
    disp, srv = _server(queue, results_dir=str(tmp_path / "results"))
    channel = grpc.insecure_channel(f"localhost:{srv.port}",
                                    options=service.default_channel_options())
    stub = service.DispatcherStub(channel)
    try:
        got = []
        for _ in range(3):
            reply = stub.RequestJobs(pb.JobsRequest(
                worker_id="legacy", chips=1, jobs_per_chip=1))
            got.extend(reply.jobs)
        assert len(got) == 3
        # Every delivery ships the full panel; digests still ride along
        # (harmless to a reader that ignores unknown fields).
        assert all(j.ohlcv == recs[0].ohlcv for j in got)
        assert all(j.panel_digest == recs[0].panel_digest for j in got)
        for j in got:
            disp.CompleteJob(pb.CompleteRequest(
                id=j.id, worker_id="legacy"), None)
        # The capable path on the SAME dispatcher still dedupes.
        _, recs2 = _shared_panel_jobs(2, seed=14)
        for rec in recs2:
            queue.enqueue(rec)
        full = []
        for _ in range(2):
            reply = stub.RequestJobs(pb.JobsRequest(
                worker_id="capable", chips=1, jobs_per_chip=1,
                accepts_digest_only=True))
            full.extend(reply.jobs)
        assert len(full) == 2
        assert full[0].ohlcv == recs2[0].ohlcv
        assert full[1].ohlcv == b"" and full[1].panel_digest
    finally:
        channel.close()
        srv.stop()


# ---------------------------------------------------------------------------
# Streaming appends (AppendBars): O(ΔT) live-bar serving
# ---------------------------------------------------------------------------

def _stream_setup(n_bars=160, base_bars=128, seed=42):
    """One full synthetic history + its base/delta DBX1 slices."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        JobRecord)
    from distributed_backtesting_exploration_tpu.utils import data

    full = data.synthetic_ohlcv(1, n_bars, seed=seed)
    def cut(lo, hi):
        return data.to_wire_bytes(
            type(full)(*(np.asarray(f[0, lo:hi]) for f in full)))
    rec = JobRecord(id=f"stream-base-{seed}", strategy="sma_crossover",
                    grid=GRID, ohlcv=cut(0, base_bars))
    return full, rec, cut


def _cold_stream_metrics(full, n_bars):
    """The cold streaming sweep over the first n_bars — the parity
    target every append result must match."""
    from distributed_backtesting_exploration_tpu.parallel import sweep
    from distributed_backtesting_exploration_tpu.streaming import (
        recurrent as rc)

    grid = {k: np.asarray(v) for k, v in sweep.product_grid(
        **dict(sorted(GRID.items()))).items()}
    return rc.finalize(rc.build_carry(
        "sma_crossover",
        {"close": np.asarray(full.close)[:, :n_bars]}, grid))


def _append(stub, digest, base_len, delta):
    tmpl = pb.JobSpec(strategy="sma_crossover",
                      grid=wire.grid_to_proto(GRID), cost=0.0,
                      periods_per_year=252)
    return stub.AppendBars(pb.AppendRequest(
        worker_id="feed", panel_digest=digest, base_len=base_len,
        delta=delta, job=tmpl))


def test_append_bars_stream_serves_carry_hits_and_matches_cold(tmp_path):
    """The streaming tentpole end to end: a cold sweep leaves no
    checkpoint, so append #1 full-reprices (graceful, not failed) AND
    stores the carry; append #2 advances it in O(ΔT) — asserted via the
    carry-cache counters, the worker append outcomes, the delta-only
    dispatch counter, and the carry_hit span — and both append results
    match the cold streaming sweep at their lengths."""
    import grpc

    from distributed_backtesting_exploration_tpu import obs
    from distributed_backtesting_exploration_tpu.rpc import service

    full, rec, cut = _stream_setup()
    queue = JobQueue()
    queue.enqueue(rec)
    disp, srv = _server(queue, results_dir=str(tmp_path / "results"))
    backend = compute.JaxSweepBackend(use_fused=True)
    hit0 = backend._c_append["carry_hit"].value
    miss0 = backend._c_append["full_reprice"].value
    delta_mode0 = disp._c_payloads["delta"].value
    # Registry counters are global: earlier tests (the pipelined
    # bit-identity append chains) may already have appended.
    ext0 = disp._c_appends["extended"].value
    channel = grpc.insecure_channel(f"localhost:{srv.port}",
                                    options=service.default_channel_options())
    stub = service.DispatcherStub(channel)
    try:
        w, t = _run_worker(f"localhost:{srv.port}", backend,
                           max_idle_polls=None)
        _wait(lambda: queue.drained, msg="base job drained")
        r1 = _append(stub, rec.panel_digest, 128, cut(128, 144))
        assert r1.ok and r1.new_len == 144
        _wait(lambda: queue.drained, msg="append 1 drained")
        r2 = _append(stub, r1.panel_digest, 144, cut(144, 160))
        assert r2.ok and r2.new_len == 160
        _wait(lambda: queue.drained, msg="append 2 drained")
        w.stop()
        t.join(timeout=10)
    finally:
        channel.close()
        srv.stop()
    assert queue.stats()["jobs_failed"] == 0
    assert disp._c_appends["extended"].value - ext0 == 2
    # Append 1: no checkpoint anywhere -> full reprice; append 2: the
    # stored carry advances.
    assert backend._c_append["full_reprice"].value - miss0 == 1
    assert backend._c_append["carry_hit"].value - hit0 == 1
    # The worker held the base panel, so at least one append shipped
    # delta-only (empty ohlcv + append_delta).
    assert disp._c_payloads["delta"].value - delta_mode0 >= 1
    ring = obs.recent_spans()
    assert any(s.get("name") == "worker.append" and s.get("carry_hit")
               for s in ring), "no carry_hit append span in the ring"

    for reply, n_bars in ((r1, 144), (r2, 160)):
        got = wire.metrics_from_bytes(
            (tmp_path / "results" / f"{reply.job_id}.dbxm").read_bytes())
        want = _cold_stream_metrics(full, n_bars)
        for name in want._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name))[0], rtol=2e-5, atol=2e-6,
                err_msg=f"{n_bars}:{name}")


def test_append_bars_restart_replays_delta_chain(tmp_path):
    """Dispatcher restart mid-stream: the journal's `delta` events rebuild
    the append chain, the NEXT append extends the chain's tip (the store
    re-splices lazily), and a fresh worker — no checkpoint — degrades to
    a full reprice, never a failed job."""
    import grpc

    from distributed_backtesting_exploration_tpu.rpc import service
    from distributed_backtesting_exploration_tpu.rpc.journal import Journal
    from distributed_backtesting_exploration_tpu.utils import data

    jpath = str(tmp_path / "stream.jsonl")
    full, rec, cut = _stream_setup(seed=43)
    queue = JobQueue(Journal(jpath))
    queue.enqueue(rec)
    disp, srv = _server(queue, results_dir=str(tmp_path / "res1"))
    channel = grpc.insecure_channel(f"localhost:{srv.port}",
                                    options=service.default_channel_options())
    stub = service.DispatcherStub(channel)
    try:
        backend = compute.JaxSweepBackend(use_fused=True)
        w, t = _run_worker(f"localhost:{srv.port}", backend,
                           max_idle_polls=None)
        _wait(lambda: queue.drained, msg="base drained")
        r1 = _append(stub, rec.panel_digest, 128, cut(128, 144))
        assert r1.ok
        _wait(lambda: queue.drained, msg="append 1 drained")
        w.stop()
        t.join(timeout=10)
    finally:
        channel.close()
        srv.stop()

    # Restart: fresh queue replays the journal (empty panel store, but
    # the delta chain knows how to rebuild the extended panel).
    queue2 = JobQueue(Journal(jpath))
    queue2.restore(jpath)
    blob = queue2.payload_for_digest(r1.panel_digest)
    assert blob is not None
    assert data.from_wire_bytes(blob).n_bars == 144

    disp2, srv2 = _server(queue2, results_dir=str(tmp_path / "res2"))
    channel2 = grpc.insecure_channel(
        f"localhost:{srv2.port}", options=service.default_channel_options())
    stub2 = service.DispatcherStub(channel2)
    try:
        backend2 = compute.JaxSweepBackend(use_fused=True)
        miss0 = backend2._c_append["full_reprice"].value
        w2, t2 = _run_worker(f"localhost:{srv2.port}", backend2,
                             max_idle_polls=None)
        r2 = _append(stub2, r1.panel_digest, 144, cut(144, 160))
        assert r2.ok and r2.new_len == 160
        _wait(lambda: queue2.drained, msg="post-restart append drained")
        w2.stop()
        t2.join(timeout=10)
        # Fresh worker, no checkpoint: degraded full reprice, zero fails.
        assert backend2._c_append["full_reprice"].value - miss0 == 1
        assert queue2.stats()["jobs_failed"] == 0
        got = wire.metrics_from_bytes(
            (tmp_path / "res2" / f"{r2.job_id}.dbxm").read_bytes())
        want = _cold_stream_metrics(full, 160)
        for name in want._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name))[0], rtol=2e-5, atol=2e-6,
                err_msg=name)
    finally:
        channel2.close()
        srv2.stop()


def test_append_bars_reject_outcomes():
    """Stale or malformed appends are explicit ok=false replies with the
    reason — nothing enqueued, nothing failed."""
    _, rec, cut = _stream_setup(seed=44)
    queue = JobQueue()
    queue.enqueue(rec)
    disp = Dispatcher(queue, PeerRegistry())
    try:
        tmpl = pb.JobSpec(strategy="sma_crossover",
                          grid=wire.grid_to_proto(GRID))
        r = disp.AppendBars(pb.AppendRequest(
            worker_id="feed", panel_digest="ffff" * 8, base_len=128,
            delta=cut(128, 144), job=tmpl), None)
        assert not r.ok and r.detail == "base_missing"
        r = disp.AppendBars(pb.AppendRequest(
            worker_id="feed", panel_digest=rec.panel_digest, base_len=99,
            delta=cut(128, 144), job=tmpl), None)
        assert not r.ok and r.detail == "base_len_mismatch"
        assert r.new_len == 128   # the real base length, for re-sync
        r = disp.AppendBars(pb.AppendRequest(
            worker_id="feed", panel_digest=rec.panel_digest, base_len=128,
            delta=b"garbage", job=tmpl), None)
        assert not r.ok and r.detail == "bad_delta"
        # Non-streamable strategies reject synchronously too (pairs
        # cannot ride a one-panel wire) — no job burns a dispatch round
        # trip only to complete empty.
        r = disp.AppendBars(pb.AppendRequest(
            worker_id="feed", panel_digest=rec.panel_digest, base_len=128,
            delta=cut(128, 144),
            job=pb.JobSpec(strategy="pairs",
                           grid=wire.grid_to_proto(GRID))), None)
        assert not r.ok and r.detail == "unsupported_strategy"
        assert queue.stats()["jobs_pending"] == 1   # only the base job
        assert disp._c_appends["base_missing"].value == 1
        assert disp._c_appends["base_len_mismatch"].value == 1
        assert disp._c_appends["bad_delta"].value == 1
        assert disp._c_appends["unsupported_strategy"].value == 1
    finally:
        disp.close()


def test_append_affinity_routes_to_base_holder(tmp_path):
    """RequestJobs placement (round 20, generalizing the round-6
    append-affinity hook): an append job is deferred from a worker that
    does NOT hold the base while the score table ranks the base holder
    better; the holder then receives it delta-only (empty ohlcv +
    append_delta). The deferral is bounded — with the holder gone
    silent the non-holder is served the job in full once the
    DBX_PLACEMENT_DEFER_CAP budget is spent."""
    import grpc

    from distributed_backtesting_exploration_tpu.rpc import service
    from distributed_backtesting_exploration_tpu.sched import (
        placement as sched_placement)

    _, rec, cut = _stream_setup(seed=45)
    queue = JobQueue()
    queue.enqueue(rec)
    disp, srv = _server(queue, prune_window_s=60.0,
                        results_dir=str(tmp_path / "results"))
    channel = grpc.insecure_channel(f"localhost:{srv.port}",
                                    options=service.default_channel_options())
    stub = service.DispatcherStub(channel)
    try:
        def poll(worker):
            # The live table normally refreshes on the decision plane's
            # 50ms daemon tick; rebuild it synchronously here so the
            # test never races the daemon.
            disp.decisions.refresh_placement_table()
            return list(stub.RequestJobs(pb.JobsRequest(
                worker_id=worker, chips=1, jobs_per_chip=4,
                accepts_digest_only=True)).jobs)

        # holder takes (and completes) the base job: its delivered set
        # now contains the base digest — the table's ground truth.
        base_jobs = poll("holder")
        assert len(base_jobs) == 1 and base_jobs[0].ohlcv
        disp.CompleteJobs(pb.CompleteBatch(
            worker_id="holder",
            items=[pb.CompleteItem(id=base_jobs[0].id)]), None)

        r = _append(stub, rec.panel_digest, 128, cut(128, 144))
        assert r.ok
        # The non-holder polls first: the append job is deferred to give
        # the base holder (carry-store hit, no h2d) first claim.
        assert poll("other") == []
        got = poll("holder")
        assert len(got) == 1
        job = got[0]
        assert job.append_parent_digest == rec.panel_digest
        assert job.append_base_len == 128
        assert job.ohlcv == b"" and job.append_delta   # delta-only
        disp.CompleteJobs(pb.CompleteBatch(
            worker_id="holder",
            items=[pb.CompleteItem(id=job.id)]), None)

        # Bounded deferral: with the holder gone silent, a SECOND append
        # reaches the non-holder in full bytes after exactly
        # defer_cap() deferred polls — work-conserving by construction.
        r2 = _append(stub, r.panel_digest, 144, cut(144, 160))
        assert r2.ok
        for _ in range(sched_placement.defer_cap()):
            assert poll("other") == []        # budget burning down
        job2 = poll("other")
        assert len(job2) == 1 and job2[0].ohlcv   # then served, in full
    finally:
        channel.close()
        srv.stop()
