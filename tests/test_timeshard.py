"""Time-axis sharding: distributed scans must equal their local versions."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.ops import rolling
from distributed_backtesting_exploration_tpu.parallel import timeshard
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@pytest.fixture(scope="module")
def tmesh(devices):
    return Mesh(np.asarray(devices), (timeshard.TIME_AXIS,))


# Each per-family sharded-vs-single-device parity test below costs 20-100s
# of XLA SPMD compile on a CPU-only box for one assertion; together they
# dominated the tier-1 wall budget and starved the alphabetical tail. The
# SMA flagship keeps the full-depth parity here; the demoted families stay
# covered in tier-1 by their served-path parity twins (test_timeshard_wire
# long-context family tests drive the same sharded_*_backtest functions
# through the backend route) plus the bit-exact band machine and scan
# primitives above/below, and the full set still runs under `-m slow`.
_heavy_parity = pytest.mark.slow


def _time_sharded(mesh, x):
    spec = P(*((None,) * (x.ndim - 1) + (timeshard.TIME_AXIS,)))
    return jax.device_put(x, NamedSharding(mesh, spec))


def test_sharded_cumsum_matches_local(tmesh):
    x = jnp.asarray(np.random.default_rng(0).standard_normal((3, 512)),
                    jnp.float32)
    got = timeshard.sharded_cumsum(tmesh, _time_sharded(tmesh, x))
    np.testing.assert_allclose(np.asarray(got), np.cumsum(np.asarray(x), -1),
                               rtol=1e-5, atol=1e-5)


def test_sharded_linear_scan_matches_ema(tmesh):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 512)), jnp.float32)
    span = 20
    ref = rolling.ema(x, span=span)
    alpha = 2.0 / (span + 1.0)
    a = jnp.full_like(x, 1.0 - alpha)
    b = x * alpha
    t0 = jnp.arange(x.shape[-1]) == 0
    a = jnp.where(t0, 0.0, a)
    b = jnp.where(t0, x, b)
    got = timeshard.sharded_linear_scan(
        tmesh, _time_sharded(tmesh, a), _time_sharded(tmesh, b))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@_heavy_parity   # same sharded_linear_scan machinery as the EMA-parity
                 # test above, just random coefficients vs a float64 loop
def test_sharded_linear_scan_random_coeffs(tmesh):
    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.uniform(0.1, 0.99, (512,)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((512,)), jnp.float32)
    want = np.zeros(512, np.float64)
    y = 0.0
    an, bn = np.asarray(a, np.float64), np.asarray(b, np.float64)
    for t in range(512):
        y = an[t] * y + bn[t]
        want[t] = y
    got = timeshard.sharded_linear_scan(
        tmesh, _time_sharded(tmesh, a), _time_sharded(tmesh, b))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_chunked_scan_equals_flat_scan():
    rng = np.random.default_rng(3)
    xs = jnp.asarray(rng.standard_normal((256, 4)), jnp.float32)

    def step(carry, x):
        nxt = 0.9 * carry + jnp.sum(x)
        return nxt, nxt

    want_carry, want_ys = jax.lax.scan(step, 0.0, xs)
    got_carry, got_ys = timeshard.chunked_scan(step, 0.0, xs, chunk=32)
    np.testing.assert_allclose(float(got_carry), float(want_carry), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got_ys), np.asarray(want_ys),
                               rtol=1e-5, atol=1e-5)


def test_sharded_sma_backtest_matches_single_device(devices):
    """The composed long-context path: a full SMA backtest with the bar
    axis sharded over 8 devices matches the unsharded computation."""
    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.ops import (
        metrics as metrics_mod, pnl)
    from distributed_backtesting_exploration_tpu.parallel import timeshard
    from distributed_backtesting_exploration_tpu.utils import data

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(3, 1024, seed=23)
    close = jnp.asarray(ohlcv.close)
    fast, slow = 5, 21

    got = timeshard.sharded_sma_backtest(mesh, close, fast, slow, cost=1e-3)

    strat = base.get_strategy("sma_crossover")
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    pos = jax.vmap(lambda o: strat.positions(
        o, dict(fast=jnp.float32(fast), slow=jnp.float32(slow))))(panel)
    res = pnl.backtest_prefix(close, pos, cost=1e-3)
    want = metrics_mod.summary_metrics(res.returns, res.equity,
                                       res.positions)
    for name in want._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


def test_sharded_sma_backtest_rejects_oversized_window(devices):
    from distributed_backtesting_exploration_tpu.parallel import timeshard

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    with pytest.raises(ValueError, match="halo"):
        timeshard.sharded_sma_backtest(mesh, jnp.ones((1, 256)), 5, 100)


def test_sharded_sma_backtest_2d_mesh(devices):
    """Divisibility/halo checks key on the TIME axis size, not total
    devices: a (batch=2, time=4) mesh shards bars 4-way."""
    from distributed_backtesting_exploration_tpu.parallel import timeshard
    from distributed_backtesting_exploration_tpu.utils import data

    mesh = Mesh(np.asarray(devices[:8]).reshape(2, 4),
                ("batch", timeshard.TIME_AXIS))
    close = jnp.asarray(data.synthetic_ohlcv(2, 512, seed=29).close)
    # slow=100 fits the 128-bar time block (would be spuriously rejected if
    # the check divided by all 8 devices).
    m = timeshard.sharded_sma_backtest(mesh, close, 5, 100, cost=1e-3)
    assert np.isfinite(np.asarray(m.sharpe)).all()


def test_sharded_band_positions_bit_exact(devices):
    """The band-hysteresis machine time-shards EXACTLY: 3-state transition
    maps compose associatively, so the sharded path must reproduce
    band_hysteresis_assoc bit for bit."""
    from distributed_backtesting_exploration_tpu.ops import signals
    from distributed_backtesting_exploration_tpu.parallel import timeshard

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    rng = np.random.default_rng(7)
    z = jnp.asarray(rng.standard_normal((4, 512)) * 1.5, jnp.float32)
    valid = jnp.arange(512) >= 10

    want = signals.band_hysteresis_assoc(z, valid, 1.0, 0.25)
    zs = jax.device_put(
        z, jax.NamedSharding(mesh, P(None, timeshard.TIME_AXIS)))
    got = timeshard.sharded_band_positions(mesh, zs, valid, 1.0, 0.25)
    assert (np.asarray(got) == np.asarray(want)).all()


@_heavy_parity
def test_sharded_bollinger_backtest_matches_single_device(devices):
    """The stateful long-context composition: a full Bollinger
    mean-reversion backtest with the bar axis sharded over 8 chips matches
    the unsharded computation."""
    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.ops import (
        metrics as metrics_mod, pnl)
    from distributed_backtesting_exploration_tpu.parallel import timeshard
    from distributed_backtesting_exploration_tpu.utils import data

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(3, 1024, seed=29)
    close = jnp.asarray(ohlcv.close)
    window, k = 20, 1.5

    got = timeshard.sharded_bollinger_backtest(mesh, close, window, k,
                                               cost=1e-3)

    strat = base.get_strategy("bollinger")
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    pos = jax.vmap(lambda o: strat.positions(
        o, dict(window=jnp.float32(window), k=jnp.float32(k))))(panel)
    res = pnl.backtest_prefix(close, pos, cost=1e-3)
    want = metrics_mod.summary_metrics(res.returns, res.equity,
                                       res.positions)
    for name in want._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


def test_sharded_bollinger_backtest_rejects_oversized_window(devices):
    from distributed_backtesting_exploration_tpu.parallel import timeshard

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    with pytest.raises(ValueError, match="halo"):
        timeshard.sharded_bollinger_backtest(mesh, jnp.ones((1, 256)), 100,
                                             1.0)


@_heavy_parity   # EMA recurrence machinery stays fast via the
                 # sharded_linear_scan twins above (sharded_ema is a thin
                 # coefficient wrapper over the same distributed scan).
def test_sharded_ema_matches_local(tmesh):
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal((3, 512)), jnp.float32)
    for kw in (dict(span=20), dict(alpha=1.0 / 14)):
        ref = rolling.ema(x, **kw)
        got = timeshard.sharded_ema(tmesh, _time_sharded(tmesh, x), **kw)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="exactly one"):
        timeshard.sharded_ema(tmesh, x, span=20, alpha=0.1)
    with pytest.raises(ValueError, match="divisible"):
        timeshard.sharded_ema(tmesh, jnp.ones((1, 100)), span=20)


@_heavy_parity
def test_sharded_rsi_backtest_matches_single_device(devices):
    """The EMA-state long-context composition: a full RSI mean-reversion
    backtest with the bar axis sharded over 8 chips matches the unsharded
    computation — the carry is O(1) per chip (no window halo)."""
    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.ops import (
        metrics as metrics_mod, pnl)
    from distributed_backtesting_exploration_tpu.utils import data
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(3, 1024, seed=31)
    close = jnp.asarray(ohlcv.close)
    period, band = 14, 20.0

    got = timeshard.sharded_rsi_backtest(mesh, close, period, band,
                                         cost=1e-3)

    strat = base.get_strategy("rsi")
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    pos = jax.vmap(lambda o: strat.positions(
        o, dict(period=jnp.float32(period), band=jnp.float32(band))))(panel)
    res = pnl.backtest_prefix(close, pos, cost=1e-3)
    want = metrics_mod.summary_metrics(res.returns, res.equity,
                                       res.positions)
    for name in want._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


@_heavy_parity
def test_sharded_pairs_backtest_matches_single_device(devices):
    """The two-legged long-context composition: a full rolling-OLS pairs
    backtest with the bar axis sharded over 8 chips matches the unsharded
    pair_backtest. Flip-aware, like the fused pairs parity tests: the
    blockwise cumsum rounds z by ~1e-6 relative to the one-device cumsum,
    so a knife-edge band entry can resolve differently and diverge that
    pair's whole position path — such pairs must stay rare and every
    non-flipped pair must match tightly."""
    from distributed_backtesting_exploration_tpu.models import pairs
    from distributed_backtesting_exploration_tpu.utils import data
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    n_pairs = 8
    ohlcv = data.synthetic_ohlcv(2 * n_pairs, 1024, seed=37)
    y = jnp.asarray(ohlcv.close[:n_pairs])
    x = jnp.asarray(ohlcv.close[n_pairs:])
    lookback, z_entry = 20, 1.2

    got = timeshard.sharded_pairs_backtest(mesh, y, x, lookback, z_entry,
                                           cost=1e-3)

    params = dict(lookback=jnp.float32(lookback),
                  z_entry=jnp.float32(z_entry))
    want = jax.vmap(lambda y1, x1: pairs.pair_backtest(
        y1, x1, params, cost=1e-3))(y, x)
    flipped = np.zeros(n_pairs, dtype=bool)
    for name in want._fields:
        a = np.asarray(getattr(got, name))
        b = np.asarray(getattr(want, name))
        flipped |= np.abs(a - b) > (0.01 + 0.01 * np.abs(b))
    assert int(flipped.sum()) <= 2, f"{int(flipped.sum())}/{n_pairs} flips"
    # Non-flipped tolerance is 2e-3, not the 2e-4 of the other sharded
    # backtests: a SINGLE knife-edge bar resolving differently moves a
    # 1024-bar history's metrics by ~1e-3 relative without being a gross
    # path divergence (the windowed single-asset signals have no such
    # razor edge — their z feeds a sign, not a band crossing).
    for name in want._fields:
        a = np.asarray(getattr(got, name))[~flipped]
        b = np.asarray(getattr(want, name))[~flipped]
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4,
                                   err_msg=name)


def _single_device_strategy_metrics(ohlcv, strat_name, params, *, cost=1e-3):
    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.ops import (
        metrics as metrics_mod, pnl)

    strat = base.get_strategy(strat_name)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    pos = jax.vmap(lambda o: strat.positions(
        o, {k: jnp.float32(v) for k, v in params.items()}))(panel)
    res = pnl.backtest_prefix(jnp.asarray(ohlcv.close), pos, cost=cost)
    return metrics_mod.summary_metrics(res.returns, res.equity,
                                       res.positions)


@_heavy_parity
def test_sharded_donchian_backtest_matches_single_device(devices):
    """The rolling-extrema long-context composition (fourth state shape):
    a full Donchian breakout backtest with the bar axis sharded over 8
    chips matches the unsharded computation — channel extrema via bounded
    halo + sliding reduce_window, the breakout latch via the 3-state
    transition-map fold."""
    from distributed_backtesting_exploration_tpu.utils import data

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(3, 1024, seed=41)
    close = jnp.asarray(ohlcv.close)
    window = 20

    got = timeshard.sharded_donchian_backtest(mesh, close, window, cost=1e-3)
    want = _single_device_strategy_metrics(ohlcv, "donchian",
                                           dict(window=window))
    for name in want._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


@_heavy_parity
def test_sharded_donchian_hl_backtest_matches_single_device(devices):
    """High/low-channel variant: the three OHLCV columns ride one stacked
    halo exchange and must reproduce models.donchian_hl exactly."""
    from distributed_backtesting_exploration_tpu.utils import data

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(3, 1024, seed=43)
    window = 24

    got = timeshard.sharded_donchian_hl_backtest(
        mesh, jnp.asarray(ohlcv.close), jnp.asarray(ohlcv.high),
        jnp.asarray(ohlcv.low), window, cost=1e-3)
    want = _single_device_strategy_metrics(ohlcv, "donchian_hl",
                                           dict(window=window))
    for name in want._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


@_heavy_parity
def test_sharded_stochastic_backtest_matches_single_device(devices):
    """Rolling-extrema state feeding the band machine: the sharded %K
    backtest matches models.stochastic on the unsharded path."""
    from distributed_backtesting_exploration_tpu.utils import data

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(3, 1024, seed=47)
    window, band = 14, 20.0

    got = timeshard.sharded_stochastic_backtest(
        mesh, jnp.asarray(ohlcv.close), jnp.asarray(ohlcv.high),
        jnp.asarray(ohlcv.low), window, band, cost=1e-3)
    want = _single_device_strategy_metrics(
        ohlcv, "stochastic", dict(window=window, band=band))
    for name in want._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


def test_sharded_extrema_backtests_reject_oversized_window(devices):
    from distributed_backtesting_exploration_tpu.parallel import timeshard

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ones = jnp.ones((1, 256))
    with pytest.raises(ValueError, match="halo"):
        timeshard.sharded_donchian_backtest(mesh, ones, 100)
    with pytest.raises(ValueError, match="halo"):
        timeshard.sharded_stochastic_backtest(mesh, ones, ones, ones, 100,
                                              20.0)


def test_sharded_pairs_backtest_rejects_oversized_lookback(devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    with pytest.raises(ValueError, match="halo"):
        timeshard.sharded_pairs_backtest(mesh, jnp.ones((1, 256)),
                                         jnp.ones((1, 256)), 100, 1.0)


@_heavy_parity
def test_sharded_trix_backtest_matches_single_device(devices):
    """The round-4 EMA-state composition: a full TRIX signal-line backtest
    with the bar axis sharded over 8 chips matches the unsharded
    computation — four chained blockwise EMAs, O(1) carry each.

    Flip-aware, like the pairs test: sign(trix - sig) is a razor edge and
    the blockwise associative_scan rounds ~1e-7 differently from the
    generic path's ema_ladder, so a knife-edge crossing can diverge one
    series' whole path — such series must stay rare and every non-flipped
    series must match tightly."""
    from distributed_backtesting_exploration_tpu.utils import data
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(8, 1024, seed=41)
    close = jnp.asarray(ohlcv.close)
    span, signal = 9, 4

    got = timeshard.sharded_trix_backtest(mesh, close, span, signal,
                                          cost=1e-3)
    want = _single_device_strategy_metrics(
        ohlcv, "trix", dict(span=span, signal=signal))

    flipped = np.zeros(8, dtype=bool)
    for name in want._fields:
        a = np.asarray(getattr(got, name))
        b = np.asarray(getattr(want, name))
        flipped |= np.abs(a - b) > (0.01 + 0.01 * np.abs(b))
    assert int(flipped.sum()) <= 2, f"{int(flipped.sum())}/8 flips"
    for name in want._fields:
        a = np.asarray(getattr(got, name))[~flipped]
        b = np.asarray(getattr(want, name))[~flipped]
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4,
                                   err_msg=name)


@_heavy_parity
def test_sharded_obv_backtest_matches_single_device(devices):
    """The double-accumulation composition: OBV (distributed cumsum of
    signed volume) vs its rolling mean (second distributed cumsum + halo)
    matches the unsharded obv_trend backtest."""
    from distributed_backtesting_exploration_tpu.utils import data
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(3, 1024, seed=43)
    close = jnp.asarray(ohlcv.close)
    volume = jnp.asarray(ohlcv.volume)
    window = 20

    got = timeshard.sharded_obv_backtest(mesh, close, volume, window,
                                         cost=1e-3)
    want = _single_device_strategy_metrics(
        ohlcv, "obv_trend", dict(window=window))
    for name in want._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


def test_sharded_obv_window_must_fit_block(devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ones = jnp.ones((1, 256))
    with pytest.raises(ValueError, match="exceeds"):
        timeshard.sharded_obv_backtest(mesh, ones, ones, 100)


@_heavy_parity
def test_sharded_momentum_backtest_matches_single_device(devices):
    """Pure bounded-halo lag: the time-sharded momentum backtest matches
    models.momentum on the unsharded path (14/14 family completion)."""
    from distributed_backtesting_exploration_tpu.utils import data

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(3, 1024, seed=51)
    got = timeshard.sharded_momentum_backtest(
        mesh, jnp.asarray(ohlcv.close), 21, cost=1e-3)
    want = _single_device_strategy_metrics(ohlcv, "momentum",
                                           dict(lookback=21))
    for name in want._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


@_heavy_parity
def test_sharded_bollinger_touch_backtest_matches_single_device(devices):
    """Path-free band touch: same sharded z-score as the hysteresis
    Bollinger, memoryless exposure — no cross-chip state at all."""
    from distributed_backtesting_exploration_tpu.utils import data

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(3, 1024, seed=53)
    got = timeshard.sharded_bollinger_touch_backtest(
        mesh, jnp.asarray(ohlcv.close), 20, 1.5, cost=1e-3)
    want = _single_device_strategy_metrics(
        ohlcv, "bollinger_touch", dict(window=20, k=1.5))
    for name in want._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


@_heavy_parity
def test_sharded_keltner_backtest_matches_single_device(devices):
    """Mixed EMA-midline + windowed-ATR state feeding the band machine:
    the sharded Keltner backtest matches models.keltner unsharded."""
    from distributed_backtesting_exploration_tpu.utils import data

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(3, 1024, seed=57)
    got = timeshard.sharded_keltner_backtest(
        mesh, jnp.asarray(ohlcv.close), jnp.asarray(ohlcv.high),
        jnp.asarray(ohlcv.low), 20, 1.5, cost=1e-3)
    want = _single_device_strategy_metrics(
        ohlcv, "keltner", dict(window=20, k=1.5))
    for name in want._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


@_heavy_parity
def test_sharded_vwap_backtest_matches_single_device(devices):
    """The volume-weighted composition: sharded rolling VWAP + deviation
    z-score + band machine matches models.vwap_reversion unsharded."""
    from distributed_backtesting_exploration_tpu.utils import data

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(3, 1024, seed=59)
    got = timeshard.sharded_vwap_backtest(
        mesh, jnp.asarray(ohlcv.close), jnp.asarray(ohlcv.volume), 20, 1.5,
        cost=1e-3)
    want = _single_device_strategy_metrics(
        ohlcv, "vwap_reversion", dict(window=20, k=1.5))
    for name in want._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(want, name)),
            rtol=2e-4, atol=2e-5, err_msg=name)


@_heavy_parity
def test_sharded_macd_backtest_matches_single_device(devices):
    """EMA-chain composition with the global-first-bar demean. Flip-aware
    like TRIX: the model's ema_ladder and the blockwise associative scan
    round ~1e-7 apart, enough to flip a knife-edge sign crossing."""
    from distributed_backtesting_exploration_tpu.utils import data

    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ohlcv = data.synthetic_ohlcv(8, 1024, seed=61)
    got = timeshard.sharded_macd_backtest(
        mesh, jnp.asarray(ohlcv.close), 12, 26, 9, cost=1e-3)
    want = _single_device_strategy_metrics(
        ohlcv, "macd", dict(fast=12, slow=26, signal=9))

    flipped = np.zeros(8, dtype=bool)
    for name in want._fields:
        a = np.asarray(getattr(got, name))
        b = np.asarray(getattr(want, name))
        flipped |= np.abs(a - b) > (0.01 + 0.01 * np.abs(b))
    assert int(flipped.sum()) <= 2, f"{int(flipped.sum())}/8 flips"
    for name in want._fields:
        a = np.asarray(getattr(got, name))[~flipped]
        b = np.asarray(getattr(want, name))[~flipped]
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4,
                                   err_msg=name)


def test_new_sharded_families_reject_bad_windows(devices):
    mesh = Mesh(np.asarray(devices[:8]), (timeshard.TIME_AXIS,))
    ones = jnp.ones((1, 256))
    with pytest.raises(ValueError, match="halo"):
        timeshard.sharded_momentum_backtest(mesh, ones, 100)
    with pytest.raises(ValueError, match="halo"):
        timeshard.sharded_keltner_backtest(mesh, ones, ones, ones, 100, 1.0)
    with pytest.raises(ValueError, match="halo"):
        timeshard.sharded_vwap_backtest(mesh, ones, ones, 100, 1.0)
    with pytest.raises(ValueError, match="halo"):
        timeshard.sharded_bollinger_touch_backtest(mesh, ones, 100, 1.0)
    with pytest.raises(ValueError, match=">= 1"):
        timeshard.sharded_macd_backtest(mesh, ones, 0, 26, 9)
