"""Live signal fan-out (serve/): registry units, the Subscribe RPC end
to end, result-cache correctness against a cold reprice, restart
semantics, and whale-subscriber fairness — all on the in-process gRPC
fixture (no fresh subprocesses; tier-1 budget discipline)."""

import threading
import time

import grpc
import numpy as np
import pytest

from distributed_backtesting_exploration_tpu import obs, serve
from distributed_backtesting_exploration_tpu.rpc import (
    backtesting_pb2 as pb, compute, service, wire)
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    Dispatcher, DispatcherServer, JobQueue, PeerRegistry, parse_grid)
from distributed_backtesting_exploration_tpu.rpc.worker import Worker
from distributed_backtesting_exploration_tpu.utils import data

GRID = parse_grid("fast=3:5,slow=10:14:2")


def _wait(pred, timeout=20.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _panel_job(n_bars=192, base_bars=128, seed=50, *, jid=None):
    """A base job over the first ``base_bars`` of a longer synthetic
    history; the remainder feeds the ticks (``_cut``)."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        JobRecord)

    full = data.synthetic_ohlcv(1, n_bars, seed=seed)
    blob = data.to_wire_bytes(
        type(full)(*(np.asarray(f[0, :base_bars]) for f in full)))
    return JobRecord(id=jid or f"serve-base-{seed}",
                     strategy="sma_crossover", grid=GRID, ohlcv=blob), full


def _cut(full, lo, hi):
    return data.to_wire_bytes(
        type(full)(*(np.asarray(f[0, lo:hi]) for f in full)))


def _server(queue, *, results_dir=None, max_workers=16):
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0),
                      results_dir=results_dir)
    srv = DispatcherServer(disp, bind="localhost:0", prune_interval_s=0.5,
                           max_workers=max_workers).start()
    return disp, srv


def _stub(port):
    channel = grpc.insecure_channel(
        f"localhost:{port}", options=service.default_channel_options())
    return channel, service.DispatcherStub(channel)


class _Collector:
    """Drains one Subscribe stream on a daemon thread."""

    def __init__(self, stub, request, *, sleep_per_item=0.0):
        self.items: list = []
        self.recv_times: list = []
        self.sleep_per_item = sleep_per_item
        self._call = stub.Subscribe(request)
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self):
        try:
            for item in self._call:
                self.recv_times.append(time.time())
                self.items.append(item)
                if self.sleep_per_item:
                    time.sleep(self.sleep_per_item)
        except grpc.RpcError:
            pass   # cancelled / server stopped

    def stop(self):
        self._call.cancel()
        self._thread.join(timeout=10)


def _interest(digest, *, strategy="sma_crossover", grid=GRID, cost=0.0,
              ppy=252):
    return pb.JobSpec(strategy=strategy, panel_digest=digest,
                      grid=wire.grid_to_proto(grid), cost=cost,
                      periods_per_year=ppy)


def _append(stub, digest, base_len, delta, *, strategy="", grid=GRID):
    tmpl = (pb.JobSpec(strategy=strategy, grid=wire.grid_to_proto(grid),
                       cost=0.0, periods_per_year=252)
            if strategy else pb.JobSpec())
    return stub.AppendBars(pb.AppendRequest(
        worker_id="feed", panel_digest=digest, base_len=base_len,
        delta=delta, job=tmpl))


# ---------------------------------------------------------------------------
# stream_key cross-pin + hub units (no gRPC)
# ---------------------------------------------------------------------------

def test_stream_key_pins_recurrent_implementation():
    """serve.stream_key is a deliberate mirror (the dispatcher must not
    import the jax-backed carry machinery to hash a grid) — the two
    implementations may never drift, or pushes and carry checkpoints
    would address different streams."""
    from distributed_backtesting_exploration_tpu.streaming import (
        recurrent as rc)

    for grid, cost, ppy in (
            (GRID, 0.0, 252),
            ({"fast": np.asarray([3.0, 9.0], np.float32)}, 1e-3, 365),
            ({}, 0.5, 12)):
        assert serve.stream_key("sma_crossover", grid, cost, ppy) == \
            rc.stream_key("sma_crossover", grid, cost, ppy)
    assert serve.stream_key("rsi", GRID, 0.0, 252) != \
        serve.stream_key("sma_crossover", GRID, 0.0, 252)


def test_hub_tick_advances_are_per_unique_stream():
    """Three subscribers over ONE stream cost one advance; a second
    param block on the same chain is a second stream. The template's
    own stream never double-advances."""
    hub = serve.SubscriptionHub(registry=obs.Registry())
    spec = serve.StreamSpec("sma_crossover", GRID, 0.0, 252, digest="d0")
    grid2 = {"fast": np.asarray([7.0, 8.0], np.float32)}
    spec2 = serve.StreamSpec("sma_crossover", grid2, 0.0, 252,
                             digest="d0")
    subs = [hub.subscribe(f"c{i}", "default", [spec]) for i in range(3)]
    sub2 = hub.subscribe("c3", "default", [spec2])
    plan = hub.on_tick("d0", "d1", 100)
    assert {s.key for s in plan.advances} == {spec.key, spec2.key}
    assert not plan.template_live
    # Same tick re-announced (duplicate feed): nothing new to advance.
    plan2 = hub.on_tick("d0", "d1", 100)
    assert plan2.advances == []
    # Template covering stream 1: only stream 2 needs its own advance.
    hub2 = serve.SubscriptionHub(registry=obs.Registry())
    for i in range(3):
        hub2.subscribe(f"c{i}", "default", [spec])
    hub2.subscribe("c3", "default", [spec2])
    plan3 = hub2.on_tick("d0", "d1", 100, template_key=spec.key)
    assert plan3.template_live
    assert [s.key for s in plan3.advances] == [spec2.key]
    for s in subs + [sub2]:
        hub.unsubscribe(s)
    assert hub.stats()["streams"] == 0 and hub.stats()["chains"] == 0


def test_hub_fanout_pushes_to_every_subscriber_once():
    hub = serve.SubscriptionHub(registry=obs.Registry())
    spec = serve.StreamSpec("sma_crossover", GRID, 0.0, 252, digest="d0")
    subs = [hub.subscribe(f"c{i}", "default", [spec]) for i in range(4)]
    plan = hub.on_tick("d0", "d1", 100)
    assert len(plan.advances) == 1
    hub.register_advance("job-1", plan.chain, spec.key, "d1", 100, 1.0)
    from distributed_backtesting_exploration_tpu.ops.metrics import (
        Metrics)

    blob = wire.metrics_to_bytes(Metrics(*(
        np.zeros(4, np.float32) for _ in Metrics._fields)))
    assert hub.on_result("job-1", blob) == 4
    for sub in subs:
        items = sub.pull(timeout=2.0)
        assert len(items) == 1
        it = items[0]
        assert it.digest == "d1" and it.key == spec.key
        assert it.metrics == blob and it.seq == 1
        assert it.changed == -1     # nothing cached to diff against
    # Unknown job ids (ordinary batch work) fan out nothing.
    assert hub.on_result("job-unknown", blob) == 0
    # Next tick: the cached d1 block diffs against an identical d2
    # block -> changed == 0.
    plan = hub.on_tick("d1", "d2", 101)
    hub.register_advance("job-2", plan.chain, spec.key, "d2", 101, 2.0)
    assert hub.on_result("job-2", blob) == 4
    it = subs[0].pull(timeout=2.0)[0]
    assert it.changed == 0 and it.seq == 2
    for s in subs:
        hub.unsubscribe(s)


def test_hub_slow_subscriber_drops_oldest_and_counts():
    """The degradation ladder's middle rung: a full per-subscriber queue
    drops the OLDEST push (live serving wants the freshest result) and
    counts it; the tick path never blocks."""
    hub = serve.SubscriptionHub(registry=obs.Registry(), queue_max=2)
    spec = serve.StreamSpec("sma_crossover", GRID, 0.0, 252, digest="d0")
    sub = hub.subscribe("slow", "default", [spec])
    from distributed_backtesting_exploration_tpu.ops.metrics import (
        Metrics)

    parent = "d0"
    for i in range(1, 5):
        digest = f"d{i}"
        plan = hub.on_tick(parent, digest, 100 + i)
        hub.register_advance(f"j{i}", plan.chain, spec.key, digest,
                             100 + i, float(i))
        blob = wire.metrics_to_bytes(Metrics(*(
            np.full(2, float(i), np.float32) for _ in Metrics._fields)))
        hub.on_result(f"j{i}", blob)
        parent = digest
    items = sub.pull(timeout=2.0)
    # 4 pushes into a 2-slot queue: the two oldest dropped + counted.
    assert [it.digest for it in items] == ["d3", "d4"]
    assert sub.dropped == 2
    assert items[-1].dropped == 2
    assert [it.seq for it in items] == [3, 4]   # seq holes mark the gap
    hub.unsubscribe(sub)


def test_hub_sub_quota_demotes_never_rejects(monkeypatch):
    monkeypatch.setenv("DBX_TENANT_SUB_QUOTA", "whale:2,*:100")
    reg = obs.Registry()
    hub = serve.SubscriptionHub(registry=reg)
    spec = serve.StreamSpec("sma_crossover", GRID, 0.0, 252, digest="d0")
    w1 = hub.subscribe("w1", "whale", [spec, spec])   # at quota: kept
    assert not w1.demoted
    w2 = hub.subscribe("w2", "whale", [spec])         # over: demoted
    assert w2.demoted
    small = hub.subscribe("s1", "small", [spec])      # other tenant: fine
    assert not small.demoted
    assert reg.counter("dbx_sub_demotions_total").value == 1
    # Demoted connections still receive pushes (never rejected).
    plan = hub.on_tick("d0", "d1", 10)
    hub.register_advance("j1", plan.chain, spec.key, "d1", 10, 1.0)
    from distributed_backtesting_exploration_tpu.ops.metrics import (
        Metrics)

    blob = wire.metrics_to_bytes(Metrics(*(
        np.zeros(1, np.float32) for _ in Metrics._fields)))
    assert hub.on_result("j1", blob) == 3
    assert len(w2.pull(timeout=2.0)) == 1
    # Release: the whale's charge drops with its connections.
    hub.unsubscribe(w1)
    hub.unsubscribe(w2)
    w3 = hub.subscribe("w3", "whale", [spec])
    assert not w3.demoted
    hub.unsubscribe(w3)
    hub.unsubscribe(small)


def test_hub_out_of_order_completion_is_suppressed_not_regressed():
    """Two quick ticks race on different workers and the OLDER advance
    completes last: chain lengths totally order a stream's advances, so
    the late completion is suppressed and counted — pushing it would
    regress every subscriber's view (seq grows, panel shrinks) and
    caching it would evict the newer block new subscribers catch up
    from."""
    from distributed_backtesting_exploration_tpu.ops.metrics import (
        Metrics)

    reg = obs.Registry()
    hub = serve.SubscriptionHub(registry=reg)
    spec = serve.StreamSpec("sma_crossover", GRID, 0.0, 252, digest="d0")
    sub = hub.subscribe("c0", "default", [spec])
    plan = hub.on_tick("d0", "d1", 65)
    hub.register_advance("j1", plan.chain, spec.key, "d1", 65, 1.0)
    plan = hub.on_tick("d1", "d2", 66)
    hub.register_advance("j2", plan.chain, spec.key, "d2", 66, 2.0)

    def blk(v):
        return wire.metrics_to_bytes(Metrics(*(
            np.full(2, float(v), np.float32) for _ in Metrics._fields)))

    # The NEWER advance (j2) completes first...
    assert hub.on_result("j2", blk(2)) == 1
    # ...then the raced older one: suppressed, never pushed.
    assert hub.on_result("j1", blk(1)) == 0
    assert reg.counter("dbx_sub_pushes_total",
                       outcome="stale").value == 1
    items = sub.pull(timeout=2.0)
    assert [it.digest for it in items] == ["d2"]
    # The newer cached block survived: a late subscriber catches up
    # from d2, not the stale d1.
    late = hub.subscribe("c1", "default", [spec])
    cu = late.pull(timeout=2.0)
    assert len(cu) == 1 and cu[0].digest == "d2"
    assert cu[0].metrics == blk(2)
    hub.unsubscribe(sub)
    hub.unsubscribe(late)


def test_hub_malformed_completion_bytes_drop_the_push_loudly():
    """A buggy worker completing a registered advance with non-DBXM
    bytes must not crash the completion path (the CompleteJobs batch
    would die mid-loop): the push is dropped and counted, the registry
    stays consistent, and the next tick serves normally."""
    from distributed_backtesting_exploration_tpu.ops.metrics import (
        Metrics)

    reg = obs.Registry()
    hub = serve.SubscriptionHub(registry=reg)
    spec = serve.StreamSpec("sma_crossover", GRID, 0.0, 252, digest="d0")
    sub = hub.subscribe("c0", "default", [spec])
    plan = hub.on_tick("d0", "d1", 65)
    hub.register_advance("j1", plan.chain, spec.key, "d1", 65, 1.0)
    assert hub.on_result("j1", b"not a dbxm block") == 0
    assert reg.counter("dbx_sub_pushes_total",
                       outcome="dropped").value == 1
    assert sub.pull(timeout=0.1) == []
    # The stream is not wedged: the next tick's well-formed result
    # pushes (the head DID move — the completion was recorded — so the
    # follow-on tick extends from d1).
    plan = hub.on_tick("d1", "d2", 66)
    hub.register_advance("j2", plan.chain, spec.key, "d2", 66, 2.0)
    blob = wire.metrics_to_bytes(Metrics(*(
        np.ones(2, np.float32) for _ in Metrics._fields)))
    assert hub.on_result("j2", blob) == 1
    assert sub.pull(timeout=2.0)[0].digest == "d2"
    hub.unsubscribe(sub)


def test_hub_catch_up_from_result_cache():
    hub = serve.SubscriptionHub(registry=obs.Registry())
    spec = serve.StreamSpec("sma_crossover", GRID, 0.0, 252, digest="d0")
    first = hub.subscribe("c0", "default", [spec])
    plan = hub.on_tick("d0", "d1", 64)
    hub.register_advance("j1", plan.chain, spec.key, "d1", 64, 1.0)
    from distributed_backtesting_exploration_tpu.ops.metrics import (
        Metrics)

    blob = wire.metrics_to_bytes(Metrics(*(
        np.ones(2, np.float32) for _ in Metrics._fields)))
    hub.on_result("j1", blob)
    late = hub.subscribe("c1", "default", [spec])
    items = late.pull(timeout=2.0)
    assert len(items) == 1 and items[0].catch_up
    assert items[0].metrics == blob and items[0].digest == "d1"
    # Cache evicted: the late-late subscriber just waits for the next
    # tick (documented: a catch-up miss is one tick of patience).
    hub.cache.pop(("d1", spec.key))
    latest = hub.subscribe("c2", "default", [spec])
    assert latest.pull(timeout=0.1) == []
    for s in (first, late, latest):
        hub.unsubscribe(s)


# ---------------------------------------------------------------------------
# Subscribe RPC end to end (instant backend)
# ---------------------------------------------------------------------------

def test_subscribe_e2e_advances_equal_streams_not_subscribers(tmp_path):
    """The serving-cost contract over the real wire: 3 subscribers on
    one (chain, param-block) stream + 1 on a second param block; one
    tick-only AppendBars triggers exactly 2 advance jobs (unique
    streams), every subscriber gets its push, and the job queue never
    saw a per-subscriber job."""
    rec, full = _panel_job()
    queue = JobQueue()
    queue.enqueue(rec)
    disp, srv = _server(queue, results_dir=str(tmp_path / "results"))
    channel, stub = _stub(srv.port)
    worker = Worker(f"localhost:{srv.port}", compute.InstantBackend(),
                    worker_id="w0", poll_interval_s=0.01,
                    status_interval_s=0.5, jobs_per_chip=8)
    wt = threading.Thread(target=worker.run, daemon=True)
    collectors = []
    try:
        wt.start()
        _wait(lambda: queue.drained, msg="base job drained")
        grid2 = {"fast": np.asarray([7.0, 8.0], np.float32)}
        for i in range(3):
            collectors.append(_Collector(stub, pb.SubscribeRequest(
                subscriber_id=f"c{i}",
                interests=[_interest(rec.panel_digest)])))
        collectors.append(_Collector(stub, pb.SubscribeRequest(
            subscriber_id="c3",
            interests=[_interest(rec.panel_digest, grid=grid2)])))
        _wait(lambda: disp.hub.stats()["subscriptions"] == 4,
              msg="subscriptions registered")
        jobs_before = queue.stats()["jobs_completed"]
        r = _append(stub, rec.panel_digest, 128, _cut(full, 128, 132))
        assert r.ok and r.job_id == ""        # tick-only: no template job
        _wait(lambda: all(c.items for c in collectors),
              msg="pushes delivered")
        s = queue.stats()
        # Exactly 2 advance jobs (unique streams), not 4 (subscribers).
        assert s["jobs_completed"] - jobs_before == 2
        assert disp.hub.stats()["advances_inflight"] == 0
        for c in collectors[:3]:
            assert len(c.items) == 1
            it = c.items[0]
            assert it.panel_digest == r.panel_digest
            assert it.new_len == 132 and it.seq == 1 and not it.catch_up
            assert it.tick_unix > 0
            assert wire.metrics_from_bytes(it.metrics)  # decodes
        assert collectors[3].items[0].stream_key != \
            collectors[0].items[0].stream_key
        # Second tick: the SAME streams advance again from the new head.
        r2 = _append(stub, r.panel_digest, 132, _cut(full, 132, 136))
        assert r2.ok
        _wait(lambda: all(len(c.items) >= 2 for c in collectors),
              msg="second round of pushes")
        assert queue.stats()["jobs_completed"] - jobs_before == 4
        assert collectors[0].items[1].seq == 2
        # Fan-out obs on the shared registry surface.
        reg = disp.obs
        assert reg.counter("dbx_stream_advances_total").value >= 4
        assert reg.counter("dbx_sub_pushes_total",
                           outcome="queued").value >= 8
        ring = obs.recent_spans()
        assert any(s.get("name") == "job.push" for s in ring), \
            "no push span in the ring"
    finally:
        for c in collectors:
            c.stop()
        worker.stop()
        wt.join(timeout=10)
        channel.close()
        srv.stop()


def test_subscribe_rejects_unstreamable_strategy(tmp_path):
    rec, _ = _panel_job(seed=51)
    queue = JobQueue()
    queue.enqueue(rec)
    disp, srv = _server(queue)
    channel, stub = _stub(srv.port)
    try:
        call = stub.Subscribe(pb.SubscribeRequest(
            subscriber_id="bad",
            interests=[_interest(rec.panel_digest, strategy="pairs")]))
        with pytest.raises(grpc.RpcError) as err:
            next(iter(call))
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert disp.hub.stats()["subscriptions"] == 0
    finally:
        channel.close()
        srv.stop()


def test_unsubscribe_on_cancel_prunes_registry(tmp_path):
    rec, full = _panel_job(seed=52)
    queue = JobQueue()
    queue.enqueue(rec)
    disp, srv = _server(queue, results_dir=str(tmp_path / "results"))
    channel, stub = _stub(srv.port)
    try:
        c = _Collector(stub, pb.SubscribeRequest(
            subscriber_id="c0", interests=[_interest(rec.panel_digest)]))
        _wait(lambda: disp.hub.stats()["subscriptions"] == 1,
              msg="subscribed")
        c.stop()
        _wait(lambda: disp.hub.stats()["subscriptions"] == 0,
              msg="unsubscribed on cancel")
        assert disp.hub.stats()["streams"] == 0
        assert disp.hub.stats()["chains"] == 0
    finally:
        channel.close()
        srv.stop()


# ---------------------------------------------------------------------------
# Result-cache correctness: pushes match a cold full reprice
# ---------------------------------------------------------------------------

def test_push_bit_matches_cold_full_reprice(tmp_path):
    """Evict -> resubscribe -> next tick: the pushed block bit-matches a
    cold full-reprice of the extended chain. A FRESH worker backend (no
    carry checkpoint) serves the advance as a full scan-form reprice,
    and a directly-enqueued full job over the same extended panel bytes
    runs the identical sweep — byte equality, not tolerance."""
    rec, full = _panel_job(seed=53)
    queue = JobQueue()
    queue.enqueue(rec)
    disp, srv = _server(queue, results_dir=str(tmp_path / "results"))
    channel, stub = _stub(srv.port)
    worker = Worker(f"localhost:{srv.port}",
                    compute.JaxSweepBackend(use_fused=True),
                    worker_id="w0", poll_interval_s=0.01,
                    status_interval_s=0.5, jobs_per_chip=8)
    wt = threading.Thread(target=worker.run, daemon=True)
    worker2 = wt2 = None
    collectors = []
    try:
        wt.start()
        _wait(lambda: queue.drained, msg="base drained")
        c0 = _Collector(stub, pb.SubscribeRequest(
            subscriber_id="c0", interests=[_interest(rec.panel_digest)]))
        collectors.append(c0)
        _wait(lambda: disp.hub.stats()["subscriptions"] == 1,
              msg="subscribed")
        r1 = _append(stub, rec.panel_digest, 128, _cut(full, 128, 144))
        assert r1.ok
        _wait(lambda: c0.items, msg="push 1", timeout=60.0)
        skey = c0.items[0].stream_key

        # Evict the stream's cached result, drop the subscriber,
        # re-subscribe: no catch-up (documented), and the NEXT tick's
        # push comes from a fresh advance. Worker 1 retires and a FRESH
        # backend serves it — no carry checkpoint, so the advance is a
        # full scan-form reprice: byte equality is the contract (a
        # carry HIT matches within the PR-6 numerics budget instead,
        # covered by test_rpc_integration's append parity).
        worker.stop()
        wt.join(timeout=10)
        disp.hub.cache.pop((r1.panel_digest, skey))
        c0.stop()
        _wait(lambda: disp.hub.stats()["subscriptions"] == 0,
              msg="unsubscribed")
        c1 = _Collector(stub, pb.SubscribeRequest(
            subscriber_id="c1", interests=[_interest(r1.panel_digest)]))
        collectors.append(c1)
        _wait(lambda: disp.hub.stats()["subscriptions"] == 1,
              msg="resubscribed")
        assert not c1.items   # no cached head result -> no catch-up
        worker2 = Worker(f"localhost:{srv.port}",
                         compute.JaxSweepBackend(use_fused=True),
                         worker_id="w1", poll_interval_s=0.01,
                         status_interval_s=0.5, jobs_per_chip=8)
        wt2 = threading.Thread(target=worker2.run, daemon=True)
        wt2.start()
        r2 = _append(stub, r1.panel_digest, 144, _cut(full, 144, 160))
        assert r2.ok
        _wait(lambda: c1.items, msg="push 2", timeout=60.0)
        push = c1.items[0]
        assert push.panel_digest == r2.panel_digest
        assert push.changed == -1   # previous block was evicted

        # Cold full reprice of the extended chain: the scan-form build
        # over the chain's full 160-bar history — exactly the path the
        # checkpoint-miss worker served the advance through, computed
        # independently here. Bitwise value equality per metric, not a
        # tolerance.
        from distributed_backtesting_exploration_tpu.parallel import (
            sweep)
        from distributed_backtesting_exploration_tpu.streaming import (
            recurrent as rc)

        grid = {k: np.asarray(v) for k, v in sweep.product_grid(
            **dict(sorted(GRID.items()))).items()}
        want = rc.finalize(rc.build_carry(
            "sma_crossover",
            {"close": np.asarray(full.close)[:, :160]}, grid))
        got = wire.metrics_from_bytes(push.metrics)
        for name in want._fields:
            assert np.array_equal(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name))[0]), \
                f"pushed {name} != cold full reprice of the chain"
    finally:
        for c in collectors:
            c.stop()
        worker.stop()
        wt.join(timeout=10)
        if worker2 is not None:
            worker2.stop()
            wt2.join(timeout=10)
        channel.close()
        srv.stop()


def test_restart_drops_subscriptions_and_resubscribe_resumes(tmp_path):
    """Documented restart semantics: subscriptions are in-memory only —
    the stream ends with the dispatcher — and a re-subscribe against
    the journal-replayed chain serves the next tick (the delta chain
    re-splices lazily, PR-6)."""
    from distributed_backtesting_exploration_tpu.rpc.journal import (
        Journal)

    jpath = str(tmp_path / "serve.jsonl")
    rec, full = _panel_job(seed=54)
    queue = JobQueue(Journal(jpath))
    queue.enqueue(rec)
    disp, srv = _server(queue, results_dir=str(tmp_path / "res1"))
    channel, stub = _stub(srv.port)
    worker = Worker(f"localhost:{srv.port}", compute.InstantBackend(),
                    worker_id="w0", poll_interval_s=0.01,
                    status_interval_s=0.5)
    wt = threading.Thread(target=worker.run, daemon=True)
    try:
        wt.start()
        _wait(lambda: queue.drained, msg="base drained")
        c0 = _Collector(stub, pb.SubscribeRequest(
            subscriber_id="c0", interests=[_interest(rec.panel_digest)]))
        _wait(lambda: disp.hub.stats()["subscriptions"] == 1,
              msg="subscribed")
        r1 = _append(stub, rec.panel_digest, 128, _cut(full, 128, 144))
        assert r1.ok
        _wait(lambda: c0.items, msg="pre-restart push")
    finally:
        worker.stop()
        wt.join(timeout=10)
        channel.close()
        srv.stop()
    # The server stop CLOSED the stream (hub.close) — the collector's
    # iterator ended rather than hanging.
    c0._thread.join(timeout=10)
    assert not c0._thread.is_alive()

    # Restart: journal replay rebuilds the chain; subscriptions do not
    # survive (by design), so the hub starts empty.
    queue2 = JobQueue(Journal(jpath))
    queue2.restore(jpath)
    disp2, srv2 = _server(queue2, results_dir=str(tmp_path / "res2"))
    channel2, stub2 = _stub(srv2.port)
    worker2 = Worker(f"localhost:{srv2.port}", compute.InstantBackend(),
                     worker_id="w1", poll_interval_s=0.01,
                     status_interval_s=0.5)
    wt2 = threading.Thread(target=worker2.run, daemon=True)
    try:
        wt2.start()
        assert disp2.hub.stats()["subscriptions"] == 0
        c1 = _Collector(stub2, pb.SubscribeRequest(
            subscriber_id="c1", interests=[_interest(r1.panel_digest)]))
        _wait(lambda: disp2.hub.stats()["subscriptions"] == 1,
              msg="resubscribed")
        r2 = _append(stub2, r1.panel_digest, 144, _cut(full, 144, 160))
        assert r2.ok and r2.new_len == 160
        _wait(lambda: c1.items, msg="post-restart push", timeout=60.0)
        assert c1.items[0].panel_digest == r2.panel_digest
        c1.stop()
    finally:
        worker2.stop()
        wt2.join(timeout=10)
        channel2.close()
        srv2.stop()


# ---------------------------------------------------------------------------
# Lockdep gate: no pushes (or waits) while holding the registry lock
# ---------------------------------------------------------------------------

def test_subscribe_scenario_under_lockdep_is_violation_free(tmp_path):
    """The serve tier's race-harness gate (the test_lockdep e2e twin):
    subscribe over real gRPC, tick, fan out, deliver — with every
    package lock instrumented. Zero violations pins the concurrency
    contract in registry.py's docstring: nothing pushes, waits or
    blocks while the hub's registry lock (or a subscription mutex) is
    held."""
    from distributed_backtesting_exploration_tpu.analysis import lockdep

    was_active = lockdep.active()
    lockdep.install()
    lockdep.reset()
    try:
        rec, full = _panel_job(seed=56)
        queue = JobQueue()
        queue.enqueue(rec)
        disp, srv = _server(queue, results_dir=str(tmp_path / "results"))
        assert isinstance(disp.hub._lock, lockdep._LockdepLock)
        channel, stub = _stub(srv.port)
        worker = Worker(f"localhost:{srv.port}", compute.InstantBackend(),
                        worker_id="w0", poll_interval_s=0.01,
                        status_interval_s=0.5)
        wt = threading.Thread(target=worker.run, daemon=True)
        collectors = []
        try:
            wt.start()
            _wait(lambda: queue.drained, msg="base drained")
            for i in range(3):
                collectors.append(_Collector(stub, pb.SubscribeRequest(
                    subscriber_id=f"c{i}",
                    interests=[_interest(rec.panel_digest)])))
            _wait(lambda: disp.hub.stats()["subscriptions"] == 3,
                  msg="subscribed")
            r = _append(stub, rec.panel_digest, 128,
                        _cut(full, 128, 132))
            assert r.ok
            _wait(lambda: all(c.items for c in collectors),
                  msg="pushes under lockdep")
        finally:
            for c in collectors:
                c.stop()
            worker.stop()
            wt.join(timeout=10)
            channel.close()
            srv.stop()
        rep = lockdep.report()
        assert rep["violations"] == [], rep["violations"]
        # Non-vacuous: the hub's registry lock was actually exercised.
        assert any("SubscriptionHub" in cls for cls in rep["held"]), \
            rep["held"]
    finally:
        if not was_active:
            lockdep.uninstall()
        lockdep.reset()


# ---------------------------------------------------------------------------
# Fairness: a whale subscriber cannot move small tenants' push latency
# ---------------------------------------------------------------------------

def test_whale_subscriber_cannot_move_small_tenant_push_p95(
        tmp_path, monkeypatch):
    """Six slow-draining whale connections pile onto the SAME stream as
    two small tenants (over quota: demoted, fanned out last). Fan-out
    only ever APPENDS to per-subscriber bounded queues, so the whale's
    lag lives in its own queues and the small tenants' tick-to-push p95
    stays within 2x of their solo run — the ISSUE's acceptance bar, on
    the in-process gRPC fixture. (The whale deliberately adds NO streams
    of its own: extra unique streams are extra advance COMPUTE, which on
    a 2-core box measures CPU scarcity, not push-path fairness — that
    dimension is governed by the WFQ tenant charge on advance jobs.)"""
    monkeypatch.setenv("DBX_TENANT_SUB_QUOTA", "whale:3")

    def run_pass(with_whale):
        rec, full = _panel_job(seed=55)
        queue = JobQueue()
        queue.enqueue(rec)
        disp, srv = _server(queue,
                            results_dir=str(tmp_path / "results"),
                            max_workers=24)
        channel, stub = _stub(srv.port)
        worker = Worker(f"localhost:{srv.port}", compute.InstantBackend(),
                        worker_id="w0", poll_interval_s=0.005,
                        status_interval_s=0.5, jobs_per_chip=16)
        wt = threading.Thread(target=worker.run, daemon=True)
        collectors = {}
        try:
            wt.start()
            _wait(lambda: queue.drained, msg="base drained")
            for name in ("small_a", "small_b"):
                collectors[name] = _Collector(stub, pb.SubscribeRequest(
                    subscriber_id=name, tenant_id=name,
                    interests=[_interest(rec.panel_digest)]))
            n_expected = 2
            if with_whale:
                # Six slow-draining whale connections on the SAME
                # stream the smalls follow: max fan-out amplification,
                # zero added advance work. Over DBX_TENANT_SUB_QUOTA=3
                # the later connections are demoted (fan-out-last).
                for w in range(6):
                    collectors[f"whale{w}"] = _Collector(
                        stub, pb.SubscribeRequest(
                            subscriber_id=f"whale{w}",
                            tenant_id="whale",
                            interests=[_interest(rec.panel_digest)]),
                        sleep_per_item=0.05)
                n_expected = 8
            _wait(lambda: disp.hub.stats()["subscriptions"] == n_expected,
                  msg="subscribed")
            if with_whale:
                # Connections 4..6 arrived over the whale's quota of 3:
                # admitted demoted, never rejected.
                assert disp.hub.stats()["subscriptions"] == 8
                assert obs.get_registry().counter(
                    "dbx_sub_demotions_total").value >= 3
            digest, n_bars = rec.panel_digest, 128
            ticks = 12
            lat = []
            for i in range(ticks):
                r = _append(stub, digest, n_bars,
                            _cut(full, n_bars, n_bars + 1))
                assert r.ok, r.detail
                digest, n_bars = r.panel_digest, r.new_len
                deadline = time.monotonic() + 30.0
                want = i + 1
                while time.monotonic() < deadline:
                    if all(len(collectors[n].items) >= want
                           for n in ("small_a", "small_b")):
                        break
                    time.sleep(0.005)
            for name in ("small_a", "small_b"):
                c = collectors[name]
                assert len(c.items) == ticks, \
                    f"{name}: {len(c.items)}/{ticks} pushes"
                assert c.items[-1].dropped == 0
                lat.extend(t_recv - it.tick_unix
                           for t_recv, it in zip(c.recv_times, c.items))
            return sorted(lat), disp, collectors
        finally:
            for c in collectors.values():
                c.stop()
            worker.stop()
            wt.join(timeout=10)
            channel.close()
            srv.stop()

    from distributed_backtesting_exploration_tpu.obs.timeline import (
        _quantile)

    solo, _, _ = run_pass(with_whale=False)
    contended, _, _ = run_pass(with_whale=True)
    # Floor the solo p95 at 5ms: on a 2-core box the absolute numbers
    # are sub-ms and a 2x ratio over noise would be flakiness, not
    # fairness (same honest-numbers discipline as the bench's torn-job
    # filter — the bar is meaningful only over a measurable baseline).
    p95_solo = max(_quantile(solo, 0.95), 0.005)
    p95_cont = _quantile(contended, 0.95)
    assert p95_cont <= 2.0 * p95_solo, \
        f"whale moved small tenants' push p95 {p95_solo:.4f}s -> " \
        f"{p95_cont:.4f}s (> 2x)"
