"""Flight recorder + cost-model drift plane (obs/flight.py,
obs/costmodel.py, round 17): hostile-path recorder behavior (trigger
storm -> one bundle, retention eviction, unwritable dir degrades to
counting, restart keeps bundles), the induced-incident e2e captures
(job failure and SLO breach each -> exactly one bundle whose embedded
timeline stitches the offending job), the mis-modeled-stage residual
trigger, residual surfacing through FleetView//fleet.json/dbxtop, the
TriggerDump admin RPC, the `dbxflight` CLI smoke, and the DBX_LOCKDEP
zero-violations gate — all in-process (tier-1 budget discipline)."""

import json
import os
import threading
import time

import numpy as np

from distributed_backtesting_exploration_tpu.obs import costmodel, flight
from distributed_backtesting_exploration_tpu.obs import fleet
from distributed_backtesting_exploration_tpu.obs import trace
from distributed_backtesting_exploration_tpu.obs.registry import Registry
from distributed_backtesting_exploration_tpu.rpc import compute
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    Dispatcher, DispatcherServer, JobQueue, JobRecord, PeerRegistry,
    synthetic_jobs)
from distributed_backtesting_exploration_tpu.rpc.worker import Worker
from distributed_backtesting_exploration_tpu.sched.tenancy import (
    worker_bucket)

GRID = {"fast": np.arange(5.0, 9.0, dtype=np.float32)}


def _wait(pred, timeout=30.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _bundles(d) -> list:
    try:
        return sorted(n for n in os.listdir(d) if n.endswith(".json"))
    except OSError:
        return []


def _load(d, name) -> dict:
    with open(os.path.join(d, name)) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# Recorder hostile paths
# ---------------------------------------------------------------------------

def test_trigger_storm_dedupes_to_one_bundle(tmp_path, monkeypatch):
    """A crash loop firing the same (kind, subject) 40 times within the
    dedupe window produces ONE bundle; everything else is a counted
    drop — the black box must never amplify the incident."""
    d = tmp_path / "fl"
    monkeypatch.setenv("DBX_FLIGHT_DIR", str(d))
    reg = Registry()
    rec = flight.FlightRecorder(registry=reg)
    try:
        for _ in range(40):
            rec.trigger("job_fail", subject="job-1", reason="boom")
        assert rec.flush(timeout=15)
        assert len(_bundles(d)) == 1
        assert reg.peek("dbx_flight_triggers_total",
                        trigger="job_fail") == 40
        assert reg.peek("dbx_flight_dropped_total",
                        reason="dedupe") == 39
        assert reg.peek("dbx_flight_bundles_total") == 1
    finally:
        rec.close()


def test_retention_evicts_oldest(tmp_path, monkeypatch):
    """Count cap: 6 captures through a MAX_BUNDLES=3 recorder keep the
    3 newest on disk (oldest-first eviction)."""
    d = tmp_path / "fl"
    monkeypatch.setenv("DBX_FLIGHT_DIR", str(d))
    monkeypatch.setenv("DBX_FLIGHT_MAX_BUNDLES", "3")
    rec = flight.FlightRecorder(registry=Registry())
    try:
        paths = [rec.capture_now("admin", subject=f"s{i}")
                 for i in range(6)]
        assert all(paths)
        kept = _bundles(d)
        assert len(kept) == 3
        assert os.path.basename(paths[-1]) in kept
    finally:
        rec.close()


def test_unwritable_dir_degrades_to_counting(tmp_path, monkeypatch):
    """DBX_FLIGHT_DIR pointing under a regular file: captures fail, but
    nothing raises — the error is a counter, never a failed job."""
    blocker = tmp_path / "blocker"
    blocker.write_text("not a dir")
    monkeypatch.setenv("DBX_FLIGHT_DIR", str(blocker / "sub"))
    reg = Registry()
    rec = flight.FlightRecorder(registry=reg)
    try:
        assert rec.capture_now("admin", subject="s") is None
        rec.trigger("job_fail", subject="j", reason="boom")
        assert rec.flush(timeout=15)
        assert reg.peek("dbx_flight_dropped_total",
                        reason="error") == 2
        assert reg.peek("dbx_flight_bundles_total") == 0
    finally:
        rec.close()


def test_unarmed_recorder_counts_only(monkeypatch):
    """No DBX_FLIGHT_DIR: triggers are counted (through the bounded
    bucket — an unknown kind folds to "other") and dropped as disabled;
    nothing is written anywhere."""
    monkeypatch.delenv("DBX_FLIGHT_DIR", raising=False)
    reg = Registry()
    rec = flight.FlightRecorder(registry=reg)
    try:
        rec.trigger("totally_novel_kind", subject="x")
        assert rec.capture_now("admin", subject="y") is None
        assert reg.peek("dbx_flight_triggers_total",
                        trigger="other") == 1
        assert reg.peek("dbx_flight_dropped_total",
                        reason="disabled") == 2
    finally:
        rec.close()


def test_restart_keeps_bundles(tmp_path, monkeypatch):
    """Bundles survive the process that wrote them: a fresh recorder
    (restart) neither clobbers nor evicts prior evidence below the
    caps."""
    d = tmp_path / "fl"
    monkeypatch.setenv("DBX_FLIGHT_DIR", str(d))
    rec1 = flight.FlightRecorder(registry=Registry())
    p1 = rec1.capture_now("admin", subject="one")
    p2 = rec1.capture_now("job_fail", subject="two")
    rec1.close()
    rec2 = flight.FlightRecorder(registry=Registry())
    p3 = rec2.capture_now("admin", subject="three")
    rec2.close()
    assert all((p1, p2, p3))
    kept = set(_bundles(d))
    assert {os.path.basename(p) for p in (p1, p2, p3)} <= kept
    assert len(kept) == 3


# ---------------------------------------------------------------------------
# Induced incidents through the served dispatcher (acceptance e2e)
# ---------------------------------------------------------------------------

def _drain_fleet(tmp_path, queue, n_good=8, bad=None, worker_id="fl-0"):
    """Serve a dispatcher, drain ``n_good`` synthetic jobs (plus an
    optional failing record) through one real gRPC worker."""
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=60.0),
                      results_dir=str(tmp_path / "results"))
    srv = DispatcherServer(disp, bind="localhost:0",
                           prune_interval_s=5.0).start()
    worker = Worker(f"localhost:{srv.port}", compute.InstantBackend(),
                    worker_id=worker_id, poll_interval_s=0.05,
                    status_interval_s=0.5, jobs_per_chip=8)
    wt = threading.Thread(target=worker.run, daemon=True)
    try:
        wt.start()
        for r in synthetic_jobs(n_good, 32, "sma_crossover", GRID,
                                seed=5):
            queue.enqueue(r)
        if bad is not None:
            queue.enqueue(bad)
        _wait(lambda: queue.drained, msg="drain")
        assert flight.get_recorder().flush(timeout=15)
    finally:
        worker.stop()
        wt.join(timeout=30)
        srv.stop()


def test_job_failure_captures_one_stitched_bundle(tmp_path, monkeypatch):
    """An unreadable file-backed job fails at take: exactly ONE bundle
    lands, and its embedded timeline stitches the offending job end to
    end (enqueue -> failure IS its whole life: the queue_wait span and
    the ok=False e2e span, reconstructed with a critical path)."""
    fl_dir = tmp_path / "fl"
    monkeypatch.setenv("DBX_FLIGHT_DIR", str(fl_dir))
    monkeypatch.setenv("DBX_COSTMODEL", "0")
    costmodel.reset_tracker()
    flight.reset(registry=Registry())
    try:
        bad = JobRecord(id="bad-job", strategy="sma_crossover",
                        grid=GRID, path=str(tmp_path / "missing.dbx1"))
        _drain_fleet(tmp_path, JobQueue(), bad=bad)
        names = _bundles(fl_dir)
        assert len(names) == 1, names
        doc = _load(fl_dir, names[0])
        assert doc["kind"] == "job_fail"
        assert doc["subject"] == "bad-job"
        assert doc["detail"]["reason"]
        # Every registered dispatcher source scraped into the bundle.
        for src in ("metrics", "fleet", "queue", "schedule", "lockdep"):
            assert src in doc["sources"], src
        jobs = doc["jobs"]
        assert len(jobs) == 1 and jobs[0]["job_id"] == "bad-job"
        assert "queue_wait" in jobs[0]["stages"]
        span_names = {s["name"] for s in jobs[0]["spans"]}
        assert {"job.queue_wait", "job"} <= span_names
        assert any(s["name"] == "job" and not s.get("ok", True)
                   for s in jobs[0]["spans"])
    finally:
        costmodel.reset_tracker()
        flight.reset()


def test_slo_breach_captures_one_bundle(tmp_path, monkeypatch):
    """A sub-microsecond queue-wait SLO makes every dispatch a breach:
    the (kind, tenant-bucket) dedupe folds the storm into exactly ONE
    bundle, stitched to the first breaching job."""
    fl_dir = tmp_path / "fl"
    monkeypatch.setenv("DBX_FLIGHT_DIR", str(fl_dir))
    monkeypatch.setenv("DBX_TENANT_SLO_S", "0.0000001")
    monkeypatch.setenv("DBX_COSTMODEL", "0")
    costmodel.reset_tracker()
    flight.reset(registry=Registry())
    try:
        _drain_fleet(tmp_path, JobQueue(), worker_id="fl-slo")
        names = _bundles(fl_dir)
        assert len(names) == 1, names
        doc = _load(fl_dir, names[0])
        assert doc["kind"] == "slo_breach"
        assert doc["detail"]["wait_s"] >= 0.0
        jid = doc["detail"]["job"]
        assert jid
        jobs = [j for j in doc["jobs"] if j.get("job_id") == jid]
        assert jobs, doc["jobs"]
        assert "queue_wait" in jobs[0]["stages"]
    finally:
        costmodel.reset_tracker()
        flight.reset()


def test_trigger_dump_rpc(tmp_path, monkeypatch):
    """The TriggerDump admin RPC: armed -> a synchronous bundle whose
    basename comes back on the reply; unarmed -> ok=False with a
    diagnostic, never an exception."""
    import grpc

    from distributed_backtesting_exploration_tpu.rpc import (
        backtesting_pb2 as pb, service)

    fl_dir = tmp_path / "fl"
    monkeypatch.setenv("DBX_FLIGHT_DIR", str(fl_dir))
    flight.reset(registry=Registry())
    try:
        disp = Dispatcher(JobQueue(), PeerRegistry(prune_window_s=60.0),
                          results_dir=str(tmp_path / "results"))
        srv = DispatcherServer(disp, bind="localhost:0",
                               prune_interval_s=5.0).start()
        channel = grpc.insecure_channel(
            f"localhost:{srv.port}",
            options=service.default_channel_options(),
            compression=grpc.Compression.Gzip)
        stub = service.DispatcherStub(channel)
        try:
            reply = stub.TriggerDump(
                pb.DumpRequest(reason="ops probe", subject="dump-1"))
            assert reply.ok, reply.detail
            assert reply.bundle in _bundles(fl_dir)
            doc = _load(fl_dir, reply.bundle)
            assert doc["kind"] == "admin"
            assert doc["subject"] == "dump-1"
            assert doc["detail"] == {"reason": "ops probe"}
            monkeypatch.delenv("DBX_FLIGHT_DIR")
            reply2 = stub.TriggerDump(pb.DumpRequest(subject="dump-2"))
            assert not reply2.ok
            assert "DBX_FLIGHT_DIR" in reply2.detail
        finally:
            channel.close()
            srv.stop()
    finally:
        flight.reset()


# ---------------------------------------------------------------------------
# Cost-model drift plane
# ---------------------------------------------------------------------------

def _execute_rec(mult, units, bars=512, combos=16):
    """A worker.execute span record whose duration is the op model's
    prediction times ``mult`` at 1 ns/model-unit — residuals are pure
    math (log2 of a ratio, scale-free), no wall clock. ns-scale keeps
    the emitted spans in the lowest latency bucket: the process-wide
    fleet stage collector hears every real span for the life of the
    process, and seconds-scale durations here would tilt the fleet p95
    that the bench's straggler probe is judged against."""
    return {"name": "worker.execute", "kernel": "fused:sma_crossover",
            "dur_s": units * 1e-9 * mult, "bars": bars, "combos": combos,
            "jobs": 1}


def test_misspredicted_stage_trips_residual_trigger(tmp_path,
                                                    monkeypatch):
    """Acceptance: a deliberately mis-modeled stage (measured wall 16x
    the calibrated prediction, +4 log2 past the 3.0 blowout bar) fires
    the flight recorder's ``residual`` trigger through the REAL span
    listener — emit_span -> tracker -> blowout -> bundle."""
    fl_dir = tmp_path / "fl"
    monkeypatch.setenv("DBX_FLIGHT_DIR", str(fl_dir))
    monkeypatch.setenv("DBX_COSTMODEL", "1")
    monkeypatch.delenv("DBX_COSTMODEL_WARMUP", raising=False)
    monkeypatch.delenv("DBX_COSTMODEL_BLOWOUT", raising=False)
    costmodel.reset_tracker()
    flight.reset(registry=Registry())
    try:
        tr = costmodel.tracker()
        units = costmodel._model_units("sma_crossover", 512, 16)

        def emit(mult):
            r = _execute_rec(mult, units)
            trace.emit_span(r["name"], time.time() - r["dur_s"],
                            r["dur_s"], kernel=r["kernel"],
                            jobs=r["jobs"], bars=r["bars"],
                            combos=r["combos"])

        for _ in range(costmodel.warmup_n() + 1):
            emit(1.0)              # seed + warmup + one zero residual
        emit(16.0)                 # +4 log2 -> blowout
        assert tr.frame()["blowouts"] == 1
        rec = flight.get_recorder()
        assert rec.flush(timeout=15)
        names = _bundles(fl_dir)
        assert len(names) == 1, names
        doc = _load(fl_dir, names[0])
        assert doc["kind"] == "residual"
        assert doc["subject"] == "sma_crossover:fused"
        assert doc["detail"]["residual"] >= 3.0
    finally:
        costmodel.reset_tracker()
        flight.reset()


def test_costmodel_residuals_surface_in_fleet_and_dbxtop(monkeypatch):
    """The drift plane end to end on the wire: a tracker's residuals
    ride the telemetry frame, merge through FleetView into per-worker
    and fleet-rollup views (/fleet.json shape), feed the drift gauges,
    and render as `dbxtop` columns."""
    monkeypatch.setenv("DBX_COSTMODEL", "1")
    monkeypatch.delenv("DBX_COSTMODEL_WARMUP", raising=False)
    monkeypatch.delenv("DBX_FLEET_FRAME_MIN_S", raising=False)
    tr = costmodel.CostModelTracker(registry=Registry())
    units = costmodel._model_units("sma_crossover", 512, 16)
    tr.observe(_execute_rec(1.0, units))          # seed
    for _ in range(costmodel.warmup_n() - 1):
        tr.observe(_execute_rec(1.0, units))      # warmup
    for mult in (2.0,) * 6 + (16.0,):             # +1 log2 body, 1 blowout
        tr.observe(_execute_rec(mult, units))
    fr = tr.frame()
    assert fr["n"] == 7 and fr["blowouts"] == 1

    wt = fleet.WorkerTelemetry("cm-0", registry=Registry(), costmodel=tr)
    payload = wt.take_frame_json()
    assert payload and '"costmodel"' in payload

    reg = Registry()
    fv = fleet.FleetView(registry=reg, clock=lambda: 100.0)
    assert fv.update("cm-0", payload)
    snap = fv.snapshot(now=100.0)
    wcm = snap["workers"]["cm-0"]["costmodel"]
    assert wcm["n"] == 7 and wcm["blowouts"] == 1
    assert wcm["ewma"] > 0.0
    fcm = snap["fleet"]["costmodel"]
    assert fcm["n"] == 7 and fcm["blowouts"] == 1
    assert fcm["residual_p95"] >= fcm["residual_p50"] > 0.0

    fv.collect(reg)
    assert reg.peek("dbx_fleet_cost_drift_p95") == fcm["residual_p95"]
    assert reg.peek("dbx_fleet_worker_cost_drift",
                    worker=worker_bucket("cm-0")) == wcm["ewma"]

    text = fleet.render_text(snap)
    assert "cost-model drift:" in text
    assert "drift" in text and f"{wcm['ewma']:+.2f}" in text


def test_costmodel_kill_switch_and_hostile_attrs(monkeypatch):
    """DBX_COSTMODEL=0 makes observe a no-op; garbage span attrs
    (missing shape, junk kernel, non-numeric durations) are skipped,
    never raised — drift tracking must never cost a job."""
    monkeypatch.setenv("DBX_COSTMODEL", "0")
    tr = costmodel.CostModelTracker(registry=Registry())
    units = costmodel._model_units("sma_crossover", 512, 16)
    tr.observe(_execute_rec(1.0, units))
    assert tr.frame() == {}
    monkeypatch.setenv("DBX_COSTMODEL", "1")
    for rec in (
        {"name": "worker.execute", "kernel": "no-colon", "dur_s": 1.0},
        {"name": "worker.execute", "kernel": "fused:sma_crossover",
         "dur_s": "NaNish", "bars": 10, "combos": 2},
        {"name": "worker.execute", "kernel": "fused:sma_crossover",
         "dur_s": 1.0, "bars": 0, "combos": 2},
        {"name": "worker.execute", "kernel": "fused:not_a_family",
         "dur_s": 1.0, "bars": 10, "combos": 2},
        {"name": "worker.compile", "kernel": "fused:sma_crossover",
         "dur_s": 1.0, "bars": 10, "combos": 2},
    ):
        tr.observe(rec)
    assert tr.frame() == {}


# ---------------------------------------------------------------------------
# dbxflight CLI
# ---------------------------------------------------------------------------

def test_dbxflight_cli_smoke(tmp_path, monkeypatch, capsys):
    """list + show + diff over real bundles; exit 2 on an empty dir."""
    empty = tmp_path / "empty"
    empty.mkdir()
    assert flight.main(["--dir", str(empty)]) == 2
    capsys.readouterr()

    d = tmp_path / "fl"
    monkeypatch.setenv("DBX_FLIGHT_DIR", str(d))
    rec = flight.FlightRecorder(registry=Registry())
    trace.emit_span("worker.execute", time.time() - 0.01, 0.01,
                    kernel="fused:sma_crossover", jobs=1)
    pa = rec.capture_now("admin", subject="cli-a",
                         detail={"reason": "smoke"})
    pb = rec.capture_now("job_fail", subject="cli-b")
    rec.close()
    assert pa and pb and pa != pb
    na, nb = os.path.basename(pa), os.path.basename(pb)

    assert flight.main(["--dir", str(d), "list"]) == 0
    out = capsys.readouterr().out
    assert na in out and nb in out and "cli-a" in out

    assert flight.main(["--dir", str(d), "show", na]) == 0
    out = capsys.readouterr().out
    assert "admin" in out and "cli-a" in out

    assert flight.main(["--dir", str(d), "show", na, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["subject"] == "cli-a" and doc["v"] == 1

    assert flight.main(["--dir", str(d), "diff", na, nb]) == 0
    out = capsys.readouterr().out
    assert "kind" in out and "subject" in out

    assert flight.main(["--dir", str(d), "show", "no-such-bundle"]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# Lockdep gate: capture under instrumented locks
# ---------------------------------------------------------------------------

def test_flight_capture_under_lockdep_is_violation_free(tmp_path,
                                                        monkeypatch):
    """The race-harness gate (the test_fleet twin): a real drain with an
    induced job failure — trigger on the take path, async capture
    scraping every dispatcher source — with every package lock
    instrumented. Zero violations pins the contract: no source is
    scraped under the recorder's lock, and no trigger site holds a
    queue/fleet lock into the recorder."""
    from distributed_backtesting_exploration_tpu.analysis import lockdep

    fl_dir = tmp_path / "fl"
    monkeypatch.setenv("DBX_FLIGHT_DIR", str(fl_dir))
    monkeypatch.setenv("DBX_COSTMODEL", "0")
    was_active = lockdep.active()
    lockdep.install()
    lockdep.reset()
    try:
        costmodel.reset_tracker()
        flight.reset(registry=Registry())
        try:
            bad = JobRecord(id="ld-bad", strategy="sma_crossover",
                            grid=GRID,
                            path=str(tmp_path / "missing.dbx1"))
            _drain_fleet(tmp_path, JobQueue(), bad=bad,
                         worker_id="fl-ld")
            assert len(_bundles(fl_dir)) == 1
        finally:
            costmodel.reset_tracker()
            flight.reset()
        rep = lockdep.report()
        assert rep["violations"] == [], rep["violations"]
        # Non-vacuous: the recorder's own lock was really exercised
        # under instrumentation.
        assert any("FlightRecorder" in cls for cls in rep["held"]), (
            sorted(rep["held"]))
    finally:
        if not was_active:
            lockdep.uninstall()
        lockdep.reset()


# ---------------------------------------------------------------------------
# Bundle-kind forward compat (round 19)
# ---------------------------------------------------------------------------

def test_unknown_bundle_kind_skipped_and_counted(tmp_path, capsys):
    """The PR-16 skip-and-count seam extended to bundle KINDS: a bundle
    written by a newer binary (a kind outside this binary's catalogue)
    must be skipped-and-counted by `dbxflight list` and rendered as a
    generic envelope by `show` — never a crash, and never a misrender
    against a schema this binary predates. `show --json` stays a raw
    passthrough either way."""
    d = tmp_path / "fl"
    d.mkdir()
    known = {"v": 1, "kind": "job_fail", "subject": "k1", "t_wall": 0.0,
             "pid": 1, "spans": [], "jobs": [], "sources": {}}
    novel = {"v": 9, "kind": "decision_replay", "subject": "n1",
             "t_wall": 0.0, "novel_body": {"schema": "from-the-future"}}
    (d / "20260101T000000-job_fail-aaaa.json").write_text(
        json.dumps(known))
    (d / "20260101T000001-other-bbbb.json").write_text(json.dumps(novel))

    assert flight.main(["--dir", str(d), "list"]) == 0
    cap = capsys.readouterr()
    assert "job_fail" in cap.out
    assert "decision_replay" not in cap.out
    assert "skipped 1 bundle(s) with unknown kind" in cap.err

    assert flight.main(["--dir", str(d), "show",
                        "20260101T000001"]) == 0
    cap = capsys.readouterr()
    assert "unknown to this binary" in cap.out
    assert "from-the-future" not in cap.out

    assert flight.main(["--dir", str(d), "show", "20260101T000001",
                        "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["novel_body"]["schema"] == "from-the-future"

    # A dir holding ONLY unknown-kind bundles lists nothing: exit 2,
    # with the skip count still reported.
    only = tmp_path / "only-novel"
    only.mkdir()
    (only / "20260101T000002-other-cccc.json").write_text(
        json.dumps(novel))
    assert flight.main(["--dir", str(only), "list"]) == 2
    cap = capsys.readouterr()
    assert "skipped 1 bundle(s) with unknown kind" in cap.err


def test_regret_is_a_first_class_trigger_kind():
    """The decision plane's sustained-regret trigger must ride a
    catalogued kind — folding it to "other" would strip the bounded
    metric label and the filename tag an operator greps for."""
    assert flight.trigger_bucket("regret") == "regret"
    assert "regret" in flight.known_kinds()
