"""dbxlint rule tests: every rule demonstrated against a seeded fixture
violation (exact file, line, rule id), plus suppression semantics and the
CLI contract. The package-lints-clean gate lives in test_lint_clean.py."""

import importlib.util
import json
import os

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.analysis import (
    ast_rules, core, jaxpr_rules, lint as lint_cli, locks, proto_rules)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "lint")


def _fixture_line(name: str, marker: str) -> int:
    """1-indexed line of the first source line containing ``marker``."""
    with open(os.path.join(FIXTURES, name)) as fh:
        for i, line in enumerate(fh, 1):
            if marker in line:
                return i
    raise AssertionError(f"marker {marker!r} not in {name}")


def _lint_fixture(name: str, rule):
    findings, suppressed, _ = core.lint_path(
        os.path.join(FIXTURES, name), [rule])
    return findings, suppressed


# ---------------------------------------------------------------------------
# One test per rule: exactly the planted finding
# ---------------------------------------------------------------------------

def test_trace_time_env_detects_pre_pr1_lanes_cap_pattern():
    """The regression fixture reproduces the pre-PR-1 ops/fused.py shape
    (DBX_LANES_CAP read inside a helper called from a jitted kernel
    launcher) and the round-11 twin (a DBX_SCHEDULE_DIR registry lookup
    reachable from a traced root — schedule consultation must stay
    host-side). Exactly those reads are flagged; the host-side reads
    (DBX_HOST_ONLY, DBX_AUTOTUNE) are not."""
    findings, _ = _lint_fixture("trace_time_env.py",
                                ast_rules.TraceTimeEnvRule())
    assert sorted((f.rule, f.path, f.line) for f in findings) == sorted([
        ("trace-time-env", "trace_time_env.py",
         _fixture_line("trace_time_env.py",
                       'os.environ.get("DBX_LANES_CAP")')),
        ("trace-time-env", "trace_time_env.py",
         _fixture_line("trace_time_env.py",
                       'os.environ.get("DBX_SCHEDULE_DIR", "")')),
    ])
    assert "static argument" in findings[0].message
    assert not any("DBX_AUTOTUNE" in f.message or "DBX_HOST_ONLY"
                   in f.message for f in findings)


def test_lock_discipline_flags_unlocked_mutation_only():
    findings, _ = _lint_fixture("lock_discipline.py",
                                locks.LockDisciplineRule())
    assert [(f.rule, f.path, f.line) for f in findings] == [
        ("lock-discipline", "lock_discipline.py",
         _fixture_line("lock_discipline.py", "self._pending.remove(item)"))]
    assert "_pending" in findings[0].message
    # `_done` is never mutated under the lock -> unguarded, not flagged.
    assert not any("_done" in f.message for f in findings)


def test_lock_discipline_interprocedural_proves_helpers_clean():
    """The PagePool `prepare()` shape: a private helper mutating guarded
    fields is CLEAN when every caller path holds the lock (previously
    only expressible as a suppression) — and still flagged when one
    reachable path (a public method, or a lock-free caller chain) does
    not."""
    findings, _ = _lint_fixture("lock_discipline_interproc.py",
                                locks.LockDisciplineRule())
    fname = "lock_discipline_interproc.py"
    assert [(f.rule, f.path, f.line) for f in findings] == [
        ("lock-discipline", fname,
         _fixture_line(fname, "self._slots.pop(key, None)")),
        ("lock-discipline", fname,
         _fixture_line(fname, "self._slots.clear()")),
    ]
    # The helper chain under prepare()'s lock is proven, not suppressed.
    assert not any(f.line == _fixture_line(fname, "self._slots[key] = slot")
                   for f in findings)
    assert not any(f.line == _fixture_line(fname,
                                           "self._free.extend(range(8))")
                   for f in findings)
    # The lock-free path is named in the interprocedural finding.
    sweep = next(f for f in findings
                 if f.line == _fixture_line(fname, "self._slots.clear()"))
    assert "audit" in sweep.message


def test_lock_discipline_covers_nested_classes(tmp_path):
    """A lock-owning class defined inside a function must not lint
    blind, and an inner class's `self._lock` must never be credited to
    the enclosing class's lock set."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import threading\n"
        "def factory():\n"
        "    class Inner:\n"
        "        def __init__(self):\n"
        "            self._lock = threading.Lock()\n"
        "            self._items = []\n"
        "        def ok(self, x):\n"
        "            with self._lock:\n"
        "                self._items.append(x)\n"
        "        def bad(self, x):\n"
        "            self._items.append(x)\n"
        "    return Inner\n"
        "class Outer:\n"
        "    def __init__(self):\n"
        "        self._free = []\n"
        "        class Helper:\n"
        "            def __init__(self):\n"
        "                self._lock = threading.Lock()\n"
        "        self.h = Helper()\n"
        "    def touch(self):\n"
        "        self._free.append(1)   # Outer owns NO lock: clean\n")
    findings, _, _ = core.lint_path(str(mod),
                                    [locks.LockDisciplineRule()])
    assert [(f.rule, f.line) for f in findings] == [("lock-discipline", 11)]
    assert "_items" in findings[0].message


def test_lock_order_detects_abba_cycle_and_self_nest():
    """The seeded 2-lock cycle reports BOTH inner acquisition sites (with
    the reverse site cross-referenced), the consistent-order hierarchy
    pair stays clean, and re-acquiring a held non-reentrant lock through
    a helper is a self-deadlock finding."""
    findings, _ = _lint_fixture("lock_order.py", locks.LockOrderRule())
    fname = "lock_order.py"
    ab = _fixture_line(fname, "VIOLATION: beta-under-alpha")
    ba = _fixture_line(fname, "VIOLATION: alpha-under-beta")
    nest = _fixture_line(fname, "VIOLATION: self-nest")
    sp = _fixture_line(fname, "VIOLATION: stats-under-pipeline")
    ps = _fixture_line(fname, "VIOLATION: pipeline-under-stats")
    assert sorted((f.rule, f.path, f.line) for f in findings) == sorted([
        ("lock-order", fname, ab),
        ("lock-order", fname, ba),
        ("lock-order", fname, nest),
        ("lock-order", fname, sp),
        ("lock-order", fname, ps),
    ])
    # The producer/consumer handoff ABBA (round 14) names both sides.
    handoff = next(f for f in findings if f.line == sp)
    assert "_pipeline" in handoff.message and "_stats" in handoff.message
    cyc = next(f for f in findings if f.line == ab)
    assert "cycle" in cyc.message and "_alpha" in cyc.message \
        and "_beta" in cyc.message
    assert f"lock_order.py:{ba}" in cyc.message   # reverse site named
    self_nest = next(f for f in findings if f.line == nest)
    assert "non-reentrant" in self_nest.message
    assert "reenter" in self_nest.message
    # The clean hierarchy never appears.
    assert not any("_inner" in f.message or "_outer" in f.message
                   for f in findings if f.line != nest)


def test_atomicity_flags_check_then_act_across_release():
    """Read under lock -> unlocked branch -> re-acquired write is the
    seeded violation; the double-checked and single-critical-section
    forms are clean."""
    findings, _ = _lint_fixture("atomicity.py", locks.AtomicityRule())
    fname = "atomicity.py"
    assert [(f.rule, f.path, f.line) for f in findings] == [
        ("atomicity", fname,
         _fixture_line(fname, "self._spent[tenant] = spent + cost"))]
    msg = findings[0].message
    assert "_spent" in msg and "stale" in msg and "re-validate" in msg
    # The clean twins: charge_checked (revalidated) and charge_atomic
    # (one critical section) must not be flagged — pinned by the single
    # finding assertion above (their writes are on different lines).


def test_lock_blocking_flags_device_sync_under_lock():
    """The PR-9 PagePool scrape-stall class as a rule: a device sync
    under the index lock is flagged; the same sync on a lock-free path
    is not (that is blocking-call's servicer variant, below). The
    round-14 producer/consumer case: a bounded handoff `put(timeout=)`
    under the producer's accounting lock is the same stall, while the
    consumer's lock-free `get(timeout=)` stays clean."""
    findings, _ = _lint_fixture("blocking_call.py",
                                locks.LockBlockingRule())
    fname = "blocking_call.py"
    sync = _fixture_line(fname, "jax.block_until_ready(page)")
    put = _fixture_line(fname, "self._q.put(item, timeout=1.0)")
    assert sorted((f.rule, f.path, f.line) for f in findings) == sorted([
        ("lock-blocking", fname, sync),
        ("lock-blocking", fname, put),
    ])
    by_line = {f.line: f for f in findings}
    assert "_lock" in by_line[sync].message
    assert "block_until_ready" in by_line[sync].message
    assert "put" in by_line[put].message
    assert "PipelineHandoff.submit" in by_line[put].message
    # The consumer's lock-free timeout'd get is not a finding.
    assert not any("collect" in f.message for f in findings)


def test_import_time_config_flags_module_level_env_and_io():
    findings, _ = _lint_fixture("import_time_config.py",
                                ast_rules.ImportTimeConfigRule())
    assert [(f.rule, f.path, f.line) for f in findings] == [
        ("import-time-config", "import_time_config.py",
         _fixture_line("import_time_config.py",
                       '_CAP = os.environ.get')),
        ("import-time-config", "import_time_config.py",
         _fixture_line("import_time_config.py", '_CONFIG = open')),
    ]


def test_blocking_call_flags_sleep_and_device_sync_in_servicer():
    findings, _ = _lint_fixture("blocking_call.py",
                                ast_rules.BlockingCallRule())
    fname = "blocking_call.py"
    sleep = _fixture_line(fname, "time.sleep(0.5)")
    sync = _fixture_line(fname, "jax.block_until_ready(request)")
    sub_get = _fixture_line(fname, "self._q.get(timeout=5.0)")
    run_get = _fixture_line(fname, "self._q.get(timeout=1.0)")
    assert sorted((f.rule, f.path, f.line) for f in findings) == sorted([
        ("blocking-call", fname, sleep),
        ("blocking-call", fname, sync),
        ("blocking-call", fname, sub_get),
        ("blocking-call", fname, run_get),
    ])
    by_line = {f.line: f for f in findings}
    assert "SlowDispatcher.RequestJobs" in by_line[sleep].message
    # Device-sync vocabulary (round 12): a handler blocking on the
    # accelerator is the same thread-pool theft as a sleep.
    assert "SlowDispatcher.GetStats" in by_line[sync].message
    # Timeout'd queue waits (round 14): flagged in a handler and on the
    # worker control thread; the allowlisted pipeline collector wait
    # (Worker._collect_loop) is clean.
    assert "SlowDispatcher.Subscribe" in by_line[sub_get].message
    assert "Worker.run" in by_line[run_get].message
    assert not any("_collect_loop" in f.message for f in findings)
    # StallingPool's under-lock sync belongs to lock-blocking, not here
    # (StallingPool is not a servicer / control-plane class).
    assert not any("StallingPool" in f.message for f in findings)
    assert not any("PipelineHandoff" in f.message for f in findings)


def test_obs_cardinality_flags_unbounded_label_values():
    """The seeded fixture plants a param-named id, a path, a peer address,
    an f-string built from a path, and a one-hop alias of an unbounded
    attribute (`wid = self.worker_id`) — all flagged; bounded literals and
    non-matching names are not, and the suppressed site counts as
    suppressed."""
    findings, suppressed = _lint_fixture("obs_cardinality.py",
                                         ast_rules.ObsCardinalityRule())
    assert suppressed == 1
    assert [(f.rule, f.path, f.line) for f in findings] == [
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'worker=wid')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'job=job_id')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'file=path')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'peer=peer_addr')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'site=f"{path}')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'panel=panel_digest')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'tenant=tenant_id')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'shape=panel_key')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'stream=stream_key')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'sub=subscriber_id')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'worker=worker_id')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'worker=worker)')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'candidate=candidate')),
        ("obs-cardinality", "obs_cardinality.py",
         _fixture_line("obs_cardinality.py", 'regret=regret_s')),
    ]
    alias = findings[0]
    assert "wid = self.worker_id" in alias.message
    # Last binding wins in BOTH directions: `wid` above was first bound
    # to a literal, and `endpoint` (unbounded first, literal last) must
    # not be flagged.
    assert _fixture_line("obs_cardinality.py", "pool=endpoint") \
        not in [f.line for f in findings]
    assert not any("fx_ok_total" in f.message
                   or "fx_by_kernel_total" in f.message for f in findings)
    # Digest vocabulary (dispatch-by-digest round): content digests are
    # unbounded; the bounded cache-level label is not.
    assert not any("fx_cache_hits_total" in f.message for f in findings)
    # Tenant vocabulary (multi-tenant round): a RAW tenant id is
    # unbounded, but the bounded tenant-bucket map is a sanctioned
    # label source — both the direct call and its one-hop alias.
    tb_ok = _fixture_line("obs_cardinality.py", "tenant=tenant_bucket")
    tb_alias = _fixture_line("obs_cardinality.py", "tenant=bucket")
    assert tb_ok not in [f.line for f in findings]
    assert tb_alias not in [f.line for f in findings]
    # Shape-bucket vocabulary (autotuner round): a raw shape key is
    # unbounded; the clamped power-of-two shape_bucket rails are a
    # sanctioned label source.
    sb_ok = _fixture_line("obs_cardinality.py", "shape=shape_bucket")
    assert sb_ok not in [f.line for f in findings]
    # Stream vocabulary (live fan-out round): raw stream keys and
    # subscriber ids are unbounded; the bounded stream-bucket map is a
    # sanctioned label source.
    st_ok = _fixture_line("obs_cardinality.py", "stream=stream_bucket")
    assert st_ok not in [f.line for f in findings]
    # Worker vocabulary (fleet telemetry round): a raw worker id is
    # unbounded (one series per registration, forever); the bounded
    # worker-bucket map is a sanctioned label source.
    wb_ok = _fixture_line("obs_cardinality.py", "worker=worker_bucket")
    assert wb_ok not in [f.line for f in findings]
    # Decision-plane vocabulary (round 19): actual/candidate worker ids
    # and per-decision regret are unbounded runtime data; the bounded
    # route/outcome literals and the worker-bucket rails are not.
    assert not any("fx_decisions_ok_total" in f.message
                   or "fx_shadow_ok_total" in f.message for f in findings)
    dec_wb_ok = _fixture_line("obs_cardinality.py",
                              "worker=worker_bucket(worker))")
    assert dec_wb_ok not in [f.line for f in findings]


def test_obs_cardinality_ignores_splats_and_bounded_loops(tmp_path):
    """**label splats are opaque (judged at construction, not the splat)
    and loop variables over literal tuples don't match the unbounded
    vocabulary — the package's method=m / phase=phase idiom stays clean."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "def wire(reg, labels):\n"
        "    reg.gauge('fx_info', **labels).set(1)\n"
        "    hs = {m: reg.histogram('fx_rpc_seconds', method=m)\n"
        "          for m in ('RequestJobs', 'CompleteJobs')}\n"
        "    return hs\n")
    findings, _, _ = core.lint_path(str(mod),
                                    [ast_rules.ObsCardinalityRule()])
    assert findings == []


def _load_bad_kernels():
    spec = importlib.util.spec_from_file_location(
        "dbxlint_fixture_bad_kernel", os.path.join(FIXTURES, "bad_kernel.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_kernel_hygiene_flags_host_callback():
    mod = _load_bad_kernels()
    x = np.ones((4, 8), np.float32)
    findings = jaxpr_rules.check_traced(
        "cb", mod.kernel_with_callback, [x], path="bad_kernel.py", line=13)
    assert [(f.rule, f.path, f.line) for f in findings] == [
        ("kernel-hygiene", "bad_kernel.py", 13)]
    assert "pure_callback" in findings[0].message


def test_kernel_hygiene_flags_float64_leak():
    import jax

    mod = _load_bad_kernels()
    x = np.ones((4, 8), np.float32)
    with jax.experimental.enable_x64():
        findings = jaxpr_rules.check_traced(
            "f64", mod.kernel_with_f64, [x], path="bad_kernel.py", line=22)
    assert any("float64" in f.message for f in findings)
    assert all(f.rule == "kernel-hygiene" and f.path == "bad_kernel.py"
               and f.line == 22 for f in findings)


def test_kernel_hygiene_flags_weak_type_escape_and_passes_clean():
    mod = _load_bad_kernels()
    x = np.ones((4, 8), np.float32)
    weak = jaxpr_rules.check_traced("weak", mod.kernel_weak_output, [x])
    assert len(weak) == 1 and "weakly typed" in weak[0].message
    assert jaxpr_rules.check_traced("clean", mod.kernel_clean, [x]) == []


def test_kernel_hygiene_unknown_axis_is_a_finding_not_a_crash(monkeypatch):
    """A newly registered fused kernel with a grid axis/field the rule has
    no tiny-input template for must surface as a loud finding (telling the
    maintainer to extend the template), never crash the lint run."""
    from distributed_backtesting_exploration_tpu.rpc import compute

    spec = compute._FusedSpec({"threshold"}, ("threshold",),
                              lambda *a, **k: None)
    monkeypatch.setattr(compute.JaxSweepBackend, "_FUSED_STRATEGIES",
                        {"novel_strategy": spec})
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(
        ast_rules.__file__)))
    ctx = core.load_context(pkg)
    findings = jaxpr_rules.KernelHygieneRule().check(ctx)
    # TWO loud findings, one per coverage surface: the dense tiny-input
    # template gap AND the paged-path probe gap (a registry entry must
    # not silently skip the round-10 paged variants either). Each is
    # reported once (scan pass), never per substrate.
    assert len(findings) == 2
    assert all(f.rule == "kernel-hygiene"
               and "novel_strategy" in f.message for f in findings)
    assert any("'threshold'" in f.message for f in findings)
    assert any("paged" in f.message for f in findings)


def test_kernel_hygiene_skip_is_reported_not_clean_coverage():
    """Outside the package the kernel registry cannot be traced: the rule
    must land in rules_skipped, never in `rules` (skipped != clean)."""
    result = lint_cli.run([FIXTURES], core.all_rules())
    assert "kernel-hygiene" in result["rules_skipped"]
    assert "kernel-hygiene" not in result["rules"]
    assert "trace-time-env" in result["rules"]


def test_proto_drift_detects_planted_divergences():
    """Drifted copy of the real contract vs the real pb2 descriptor: a
    renumbered field, a renamed field (missing+extra pair), and a field
    the descriptor lacks — nothing else."""
    from distributed_backtesting_exploration_tpu.rpc import backtesting_pb2

    path = os.path.join(FIXTURES, "proto_drift", "drifted.proto")
    with open(path) as fh:
        text = fh.read()
    model = proto_rules.parse_proto_text(text)
    pb2_model = proto_rules.describe_pb2(backtesting_pb2)
    findings = proto_rules.diff_models(model, pb2_model,
                                       path="drifted.proto")

    def line_of(marker):
        for i, line in enumerate(text.splitlines(), 1):
            if marker in line:
                return i
        raise AssertionError(marker)

    assert len(findings) == 4, [f.message for f in findings]
    renum = next(f for f in findings
                 if "CompleteItem.elapsed_s" in f.message)
    assert "number 4" in renum.message and "3 in the pb2" in renum.message
    assert renum.line == line_of("DRIFT: pb2 has number 3")
    missing = next(f for f in findings if "Ack.details" in f.message)
    assert "missing from the pb2" in missing.message
    assert missing.line == line_of("string details = 2;")
    extra = next(f for f in findings
                 if "`Ack.detail`" in f.message)
    assert "does not declare" in extra.message
    prio = next(f for f in findings if "JobsRequest.priority" in f.message)
    assert "missing from the pb2" in prio.message
    assert prio.line == line_of("int32 priority = 4;")


def test_proto_parser_survives_oneof_and_nested_blocks():
    """A `oneof`'s closing brace must pop only its own frame: its fields
    attribute to the enclosing message (descriptor semantics) and fields
    declared AFTER it are not lost."""
    model = proto_rules.parse_proto_text(
        "message M {\n"
        "  int32 a = 1;\n"
        "  oneof kind {\n"
        "    int32 b = 2;\n"
        "  }\n"
        "  int32 c = 3;\n"
        "}\n"
        "message N { int32 d = 1; }\n")
    assert model.messages == {"M": {"a": 1, "b": 2, "c": 3},
                              "N": {"d": 1}}


def test_proto_drift_real_contract_is_clean():
    from distributed_backtesting_exploration_tpu.rpc import backtesting_pb2

    proto = os.path.join(
        os.path.dirname(FIXTURES), "..", "..",
        "distributed_backtesting_exploration_tpu", "rpc",
        "backtesting.proto")
    with open(proto) as fh:
        model = proto_rules.parse_proto_text(fh.read())
    assert proto_rules.diff_models(
        model, proto_rules.describe_pb2(backtesting_pb2),
        path="backtesting.proto") == []
    # Sanity that the parser actually saw the contract (not vacuous).
    assert "JobSpec" in model.messages
    assert model.messages["JobSpec"]["best_returns"] == 13
    assert model.services["Dispatcher"]["GetStats"] == ("StatsRequest",
                                                        "StatsReply")


# ---------------------------------------------------------------------------
# Suppressions + CLI
# ---------------------------------------------------------------------------

def test_suppression_respected_same_line_and_line_above():
    findings, suppressed = _lint_fixture("suppressed.py",
                                         ast_rules.ImportTimeConfigRule())
    # _A (same-line) and _B (line-above) suppressed; _C names the wrong
    # rule so its finding survives.
    assert suppressed == 2
    assert [(f.rule, f.line) for f in findings] == [
        ("import-time-config",
         _fixture_line("suppressed.py", "DBX_SUP_C"))]


def test_suppression_directive_inside_string_literal_does_not_count(tmp_path):
    """A directive appearing in a STRING VALUE (docs, error messages) must
    not silence findings — only real comment tokens do."""
    mod = tmp_path / "m.py"
    mod.write_text(
        'import os\n'
        '_X = os.environ.get("A", "see dbxlint: disable=all in docs")\n')
    findings, suppressed = core.lint_path(
        str(mod), [ast_rules.ImportTimeConfigRule()])[:2]
    assert suppressed == 0
    assert [(f.rule, f.line) for f in findings] == [("import-time-config", 2)]


def test_import_time_config_flags_attribute_form_io(tmp_path):
    """Network IO at import is spelled as attributes (socket.create_connection,
    urllib.request.urlopen) — the rule must match terminal names, not just
    bare `open`."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import socket\n"
        '_CONN = socket.create_connection(("localhost", 1))\n')
    findings, _, _ = core.lint_path(str(mod),
                                    [ast_rules.ImportTimeConfigRule()])
    assert [(f.rule, f.line) for f in findings] == [("import-time-config", 2)]
    assert "create_connection" in findings[0].message


def test_blocking_call_allowlist_is_sleep_only(tmp_path):
    """The Worker.run allowlist sanctions the poll-tick SLEEP only: any
    other blocking call added to an allowlisted method is still flagged."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import subprocess, time\n"
        "class Worker:\n"
        "    def run(self):\n"
        "        time.sleep(0.05)          # sanctioned poll tick\n"
        "        subprocess.run(['x'])     # NOT sanctioned\n")
    findings, _, _ = core.lint_path(str(mod), [ast_rules.BlockingCallRule()])
    assert [(f.rule, f.line) for f in findings] == [("blocking-call", 5)]
    assert "subprocess.run" in findings[0].message


def test_proto_drift_skipped_for_single_file_targets():
    """Single-file lint targets have no proto scan: proto-drift must land
    in rules_skipped, not claim clean coverage."""
    result = lint_cli.run([os.path.join(FIXTURES, "lock_discipline.py")],
                          core.all_rules())
    assert "proto-drift" in result["rules_skipped"]
    assert "kernel-hygiene" in result["rules_skipped"]
    assert "proto-drift" not in result["rules"]


def test_suppression_comma_space_list_and_justification_tail(tmp_path):
    """`disable=a, b -- why` (comma-space style) suppresses BOTH rules;
    prose after `--` never parses as a rule name."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import os\n"
        "# dbxlint: disable=import-time-config, trace-time-env -- test\n"
        '_A = os.environ.get("A")\n')
    findings, suppressed = core.lint_path(
        str(mod), [ast_rules.ImportTimeConfigRule()])[:2]
    assert findings == [] and suppressed == 1


def test_lock_discipline_ignores_local_shadow_of_guarded_global(tmp_path):
    """A function-local variable that shadows a guarded module global is
    local for the WHOLE function (Python scoping) — mutating it without
    the lock is not a violation."""
    mod = tmp_path / "m.py"
    mod.write_text(
        "import threading\n"
        "_lock = threading.Lock()\n"
        "_buf = []\n"
        "def guarded(x):\n"
        "    with _lock:\n"
        "        _buf.append(x)\n"
        "def shadow(x):\n"
        "    _buf = []\n"
        "    _buf.append(x)   # the LOCAL, not the guarded global\n"
        "def real_violation(x):\n"
        "    _buf.append(x)\n")
    findings, _, _ = core.lint_path(str(mod),
                                    [locks.LockDisciplineRule()])
    assert [(f.rule, f.line) for f in findings] == [("lock-discipline", 11)]


def test_cli_json_format_and_exit_codes(capsys, tmp_path):
    rc = lint_cli.main([os.path.join(FIXTURES, "import_time_config.py"),
                        "--rules", "import-time-config",
                        "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert not out["clean"]
    assert {f["rule"] for f in out["findings"]} == {"import-time-config"}
    assert out["rules"] == ["import-time-config"]

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    rc = lint_cli.main([str(clean), "--rules", "import-time-config"])
    assert rc == 0
    assert "clean" in capsys.readouterr().out


def test_cli_unknown_rule_errors():
    with pytest.raises(SystemExit):
        lint_cli.main(["--rules", "no-such-rule"])


def test_unparseable_file_is_loud(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def broken(:\n")
    findings, _, ctx = core.lint_path(str(tmp_path), core.all_rules())
    assert findings == []
    assert len(ctx.skipped) == 1 and ctx.skipped[0][0] == "bad.py"
    result = lint_cli.run([str(tmp_path)], core.all_rules())
    assert not result["clean"]          # a syntax error never passes silently


def test_journal_discipline_flags_mutation_before_append():
    """The seeded fixture publishes into live state (records map, state
    FIFO, WFQ lane) BEFORE journaling the enqueue records — every
    journal-covered mutation above the append is flagged; the appends
    themselves and the payload staging above them are not."""
    findings, suppressed = _lint_fixture("journal_discipline.py",
                                         ast_rules.JournalDisciplineRule())
    assert suppressed == 0
    assert [(f.rule, f.path, f.line) for f in findings] == [
        ("journal-discipline", "journal_discipline.py",
         _fixture_line("journal_discipline.py",
                       "BUG: published before journaled")),
        ("journal-discipline", "journal_discipline.py",
         _fixture_line("journal_discipline.py", "._state.enqueue_n(")),
        ("journal-discipline", "journal_discipline.py",
         _fixture_line("journal_discipline.py", "._sched.push(")),
    ]
    assert "journal first, then publish" in findings[0].message
