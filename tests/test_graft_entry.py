"""Driver-contract tests for ``__graft_entry__``.

Round 1's only red check was ``dryrun_multichip`` asserting on the ambient
device count instead of provisioning its own mesh (MULTICHIP_r01: rc=1 in the
1-TPU driver process).  These tests pin the fix: the inline path on the
conftest's 8 virtual devices, and the subprocess re-exec path that a
device-starved process (like the driver's) must take.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    import jax

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert np.isfinite(np.asarray(out.sharpe)).all()


def test_dryrun_inline_on_virtual_devices():
    # conftest provisions 8 CPU devices, so this takes the inline path
    # (4 <= 8). A 4-way mesh drives the identical body — every branch is
    # written against n_devices — at roughly half the SPMD-partitioning
    # compile wall of the 8-way flavor, which the slow-marked subprocess
    # twins and the driver itself still exercise. The long-context sweep
    # keeps the structurally hardest machine (two-legged pairs; ~15-30s of
    # CPU compile per family for one finiteness assert): the band/windowed
    # machine has a bit-exact sharded parity test in tier-1
    # (test_timeshard), and all eight families have served-path parity
    # tests in tier-1 (test_timeshard_wire).
    graft.dryrun_multichip(4, lc_families=("pairs",))


@pytest.mark.slow   # fresh-jax subprocess: minutes of wall on CPU-only boxes
def test_dryrun_subprocess_path():
    # Force the re-exec path regardless of ambient device count: the child
    # must self-provision its mesh from a bare environment.
    graft._dryrun_in_subprocess(2)


def test_dryrun_subprocess_propagates_failure(monkeypatch):
    real_run = subprocess.run

    def failing_run(*a, **kw):
        proc = real_run([sys.executable, "-c",
                         "import sys; sys.stderr.write('boom'); sys.exit(3)"],
                        capture_output=True, text=True)
        return proc

    monkeypatch.setattr(subprocess, "run", failing_run)
    with pytest.raises(RuntimeError, match="boom"):
        graft._dryrun_in_subprocess(2)


@pytest.mark.slow   # fresh-jax subprocess: minutes of wall on CPU-only boxes
def test_driver_style_import_and_call():
    # Replicate the driver exactly: fresh process, ambient (TPU or 1-device)
    # platform, direct import + call — no __main__ env setup.
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(4)"],
        cwd=root, env=env, capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, proc.stderr[-2000:]
