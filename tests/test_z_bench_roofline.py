"""Tier-1 smoke for bench.py's roofline_stages scaffold.

The per-stage attribution is the instrument every roofline decision in
DESIGN.md is cut from; a silent bitrot there (stage kernel drifting from
ops/fused.py, a renamed attribution key) would invalidate the next round's
measurements without failing anything. This runs the REAL scaffold
in-process on tiny shapes (CPU interpret mode) and asserts the attribution
keys exist and every stage time is positive — a structure test, not a
performance test.

(Named ``test_z_*`` deliberately: tier-1 runs under a fixed wall budget
that can truncate the alphabetical tail on slow boxes — additions must be
the tests a truncation drops, never the seed suite.)
"""

import contextlib
import io
import json
import os

import pytest

import bench

_TINY_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_TICKERS": "2", "DBX_BENCH_BARS": "64",
    "DBX_BENCH_PARAMS": "8", "DBX_BENCH_ITERS": "1",
    "DBX_BENCH_WARMUP": "0", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "roofline_stages",
}


@pytest.fixture(scope="module")
def roofline():
    """One tiny in-process roofline_stages run, shared by the module."""
    prior = {k: os.environ.get(k) for k in _TINY_ENV}
    prior["DBX_EPILOGUE"] = os.environ.pop("DBX_EPILOGUE", None)
    os.environ.update(_TINY_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


SMA_STAGE_KEYS = (
    "prep_l128_s_per_sweep", "touch_l128_s_per_sweep",
    "matmul_l128_s_per_sweep", "signal_l128_s_per_sweep",
    "no_ladders_l128_s_per_sweep", "full_l128_s_per_sweep",
    "full_ladder_l128_s_per_sweep", "table_hbm_s_per_sweep",
    "table_inline_s_per_sweep", "epilogue_scan_s_per_sweep",
    "epilogue_ladder_s_per_sweep",
)
BOLL_STAGE_KEYS = (
    "prep_l128_s_per_sweep", "touch_l128_s_per_sweep",
    "matmul_l128_s_per_sweep", "signal_l128_s_per_sweep",
    "signal_ladder_l128_s_per_sweep", "no_ladders_l128_s_per_sweep",
    "full_l128_s_per_sweep", "full_ladder_l128_s_per_sweep",
    "epilogue_scan_s_per_sweep", "epilogue_ladder_s_per_sweep",
)
ATTRIBUTION_KEYS = (
    "selection_matmul_pct", "signal_delta_pct", "reductions_delta_pct",
    "ladders_delta_pct", "ladder_fallback_delta_pct",
    "epilogue_scan_speedup", "epilogue_e2e_speedup",
)


def test_sma_stage_attribution_present(roofline):
    stages = roofline["roofline"]["sma_stages"]
    for key in SMA_STAGE_KEYS:
        assert key in stages, key
        assert stages[key] > 0.0, key
    for key in ATTRIBUTION_KEYS + ("inline_table_speedup",):
        assert key in stages, key
    assert stages["epilogue_scan_speedup"] > 0.0
    assert stages["inline_table_speedup"] > 0.0


def test_bollinger_stage_attribution_present(roofline):
    stages = roofline["roofline"]["bollinger_stages"]
    for key in BOLL_STAGE_KEYS:
        assert key in stages, key
        assert stages[key] > 0.0, key
    for key in ATTRIBUTION_KEYS + ("compose_delta_pct",
                                   "compose_ladder_delta_pct"):
        assert key in stages, key


def test_roofline_rates_reported(roofline):
    assert roofline["configs"]["roofline_stages_full"] > 0.0
    assert roofline["configs"]["roofline_stages_boll_full"] > 0.0
