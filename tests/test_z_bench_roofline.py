"""Tier-1 smoke for bench.py's roofline_stages scaffold.

The per-stage attribution is the instrument every roofline decision in
DESIGN.md is cut from; a silent bitrot there (stage kernel drifting from
ops/fused.py, a renamed attribution key) would invalidate the next round's
measurements without failing anything. This runs the REAL scaffold
in-process on tiny shapes (CPU interpret mode) and asserts the attribution
keys exist and every stage time is positive — a structure test, not a
performance test.

(Named ``test_z_*`` deliberately: tier-1 runs under a fixed wall budget
that can truncate the alphabetical tail on slow boxes — additions must be
the tests a truncation drops, never the seed suite.)
"""

import contextlib
import io
import json
import os

import pytest

import bench
from distributed_backtesting_exploration_tpu.runtime import (
    _core as native_core)

_TINY_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_TICKERS": "2", "DBX_BENCH_BARS": "64",
    "DBX_BENCH_PARAMS": "8", "DBX_BENCH_ITERS": "1",
    "DBX_BENCH_WARMUP": "0", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "roofline_stages",
}


@pytest.fixture(scope="module")
def roofline():
    """One tiny in-process roofline_stages run, shared by the module."""
    prior = {k: os.environ.get(k) for k in _TINY_ENV}
    prior["DBX_EPILOGUE"] = os.environ.pop("DBX_EPILOGUE", None)
    os.environ.update(_TINY_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


SMA_STAGE_KEYS = (
    "prep_l128_s_per_sweep", "touch_l128_s_per_sweep",
    "matmul_l128_s_per_sweep", "signal_l128_s_per_sweep",
    "no_ladders_l128_s_per_sweep", "full_l128_s_per_sweep",
    "full_ladder_l128_s_per_sweep", "table_hbm_s_per_sweep",
    "table_inline_s_per_sweep", "epilogue_scan_s_per_sweep",
    "epilogue_ladder_s_per_sweep",
)
BOLL_STAGE_KEYS = (
    "prep_l128_s_per_sweep", "touch_l128_s_per_sweep",
    "matmul_l128_s_per_sweep", "signal_l128_s_per_sweep",
    "signal_ladder_l128_s_per_sweep", "no_ladders_l128_s_per_sweep",
    "full_l128_s_per_sweep", "full_ladder_l128_s_per_sweep",
    "epilogue_scan_s_per_sweep", "epilogue_ladder_s_per_sweep",
)
ATTRIBUTION_KEYS = (
    "selection_matmul_pct", "signal_delta_pct", "reductions_delta_pct",
    "ladders_delta_pct", "ladder_fallback_delta_pct",
    "epilogue_scan_speedup", "epilogue_e2e_speedup",
)


def test_sma_stage_attribution_present(roofline):
    stages = roofline["roofline"]["sma_stages"]
    for key in SMA_STAGE_KEYS:
        assert key in stages, key
        assert stages[key] > 0.0, key
    for key in ATTRIBUTION_KEYS + ("inline_table_speedup",):
        assert key in stages, key
    assert stages["epilogue_scan_speedup"] > 0.0
    assert stages["inline_table_speedup"] > 0.0


def test_bollinger_stage_attribution_present(roofline):
    stages = roofline["roofline"]["bollinger_stages"]
    for key in BOLL_STAGE_KEYS:
        assert key in stages, key
        assert stages[key] > 0.0, key
    for key in ATTRIBUTION_KEYS + ("compose_delta_pct",
                                   "compose_ladder_delta_pct"):
        assert key in stages, key


def test_roofline_rates_reported(roofline):
    assert roofline["configs"]["roofline_stages_full"] > 0.0
    assert roofline["configs"]["roofline_stages_boll_full"] > 0.0


_LOCAL_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "e2e_local,direct_dispatch",
    # Tiny-but-real loopback runs: one worker count, few jobs, a small
    # shared panel for the dedupe A/B — structure smoke, not performance.
    "DBX_BENCH_LOCAL_JOBS": "48", "DBX_BENCH_LOCAL_WORKERS": "1",
    "DBX_BENCH_DEDUPE_BARS": "256",
}


@pytest.fixture(scope="module")
def local_bench():
    """One tiny in-process e2e_local + direct_dispatch run (loopback gRPC,
    instant backend — no kernels, no compiles), shared by the module."""
    prior = {k: os.environ.get(k) for k in _LOCAL_ENV}
    os.environ.update(_LOCAL_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_wire_bytes_per_job_keys_present(local_bench):
    """Transport savings are a first-class bench column: e2e_local and
    direct_dispatch_floor both record wire_bytes_per_job, and the dedupe
    A/B records its jobs/s + wire columns (the dispatch-by-digest
    acceptance numbers ride these keys)."""
    e2e = local_bench["roofline"]["e2e_local"]
    assert e2e["wire_bytes_per_job"]["w1"] > 0.0
    dd = e2e["dedupe"]
    for key in ("panel_bytes", "jobs_per_s_on", "jobs_per_s_off",
                "dedupe_speedup", "wire_bytes_per_job_on",
                "wire_bytes_per_job_off", "wire_reduction"):
        assert key in dd, key
    assert dd["jobs_per_s_on"] > 0.0 and dd["jobs_per_s_off"] > 0.0
    # Digest-only dispatch must actually shrink the wire even at smoke
    # scale (the >=10x acceptance bar is asserted on the real-size run).
    assert dd["wire_bytes_per_job_on"] < dd["wire_bytes_per_job_off"]

    floor = local_bench["roofline"]["direct_dispatch_floor"]
    assert floor["wire_bytes_per_job"]["b32"] > 0.0
    assert floor["wire_bytes_per_job"]["b128"] > 0.0


def test_direct_dispatch_lockdep_ab_keys_present(local_bench):
    """Round 12: the direct_dispatch floor is re-measured with the
    runtime lockdep shim on — overhead and violation count are tracked
    bench columns (DBX_LOCKDEP=1 must stay fleet-viable), and the
    instrumented control-plane cycle must be violation-free."""
    ld = local_bench["roofline"]["direct_dispatch_floor"]["lockdep"]
    for key in ("batch32_jobs_per_s", "overhead_pct", "floor_ok",
                "edges", "violations"):
        assert key in ld, key
    assert ld["batch32_jobs_per_s"] > 0.0
    assert ld["violations"] == 0


_STREAM_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "streaming_append",
    # Tiny-but-real in-process A/B: base history past the carry's tail
    # (the partial-tail recurrent head — the serving path), few updates.
    "DBX_BENCH_STREAM_T": "192", "DBX_BENCH_STREAM_DT": "8",
    "DBX_BENCH_ITERS": "2",
}


@pytest.fixture(scope="module")
def stream_bench():
    """One tiny in-process streaming_append run, shared by the module."""
    prior = {k: os.environ.get(k) for k in _STREAM_ENV}
    os.environ.update(_STREAM_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


_CERTIFY_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "certify",
    # Tiny-but-real: two families through the REAL certifier (4 rows
    # each: 2 epilogue substrates x {build_carry, append_step}) plus the
    # digest cones — the analysis cost instrument, not a numerics check
    # (the contract gate itself lives in test_lint_clean.py).
    "DBX_BENCH_CERTIFY_FAMILIES": "sma_crossover,bollinger",
}


@pytest.fixture(scope="module")
def certify_bench():
    """One tiny in-process certify run, shared by the module."""
    prior = {k: os.environ.get(k) for k in _CERTIFY_ENV}
    os.environ.update(_CERTIFY_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_certify_wall_keys_present(certify_bench):
    """The certifier's analysis cost rides BENCH JSON like every other
    stage: certify_wall_s per family + the digest cones, and a rows
    count matching families x substrates x forms + 3 digest cones
    (scenario_synth, scenario_fused, splice)."""
    cf = certify_bench["roofline"]["certify"]
    for key in ("certify_wall_s", "rows", "wall_s_total"):
        assert key in cf, key
    walls = cf["certify_wall_s"]
    assert set(walls) == {"sma_crossover", "bollinger", "digest"}
    assert all(w > 0.0 for w in walls.values())
    assert cf["rows"] == 2 * 4 + 3
    assert cf["wall_s_total"] > 0.0
    assert certify_bench["configs"]["certify"] > 0.0


_MC_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "modelcheck",
    # Tiny-but-real: a short dbxmc sweep through the REAL explorer on
    # every available substrate — the analysis cost instrument, not the
    # invariant gate (that lives in test_mc_clean.py).
    "DBX_BENCH_MC_SCHEDULES": "30",
}


@pytest.fixture(scope="module")
def modelcheck_bench():
    """One tiny in-process dbxmc run, shared by the module."""
    prior = {k: os.environ.get(k) for k in _MC_ENV}
    os.environ.update(_MC_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_modelcheck_bench_keys(modelcheck_bench):
    """dbxmc's exploration cost rides BENCH JSON like every other CI
    stage: schedules/crash_points/wall_s summed over the available
    substrates, plus a violations count that must read zero on a
    healthy tree."""
    mc = modelcheck_bench["roofline"]["modelcheck"]
    for key in ("schedules", "crash_points", "boundaries", "violations",
                "wall_s"):
        assert key in mc, key
    n_subs = 1 + (1 if native_core.available() else 0)
    assert mc["schedules"] == 30 * n_subs
    assert mc["crash_points"] >= 10 * n_subs
    assert mc["boundaries"] > mc["crash_points"]
    assert mc["violations"] == 0
    assert mc["wall_s"] > 0.0
    assert modelcheck_bench["configs"]["modelcheck"] > 0.0


_FANOUT_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "fanout",
    # Tiny-but-real: 12 subscriptions over 3 symbol chains on 4
    # connections — the serving-cost invariant (advances == unique
    # streams, pushes_per_advance == subs/streams) is exact at any
    # size; the p99 bar gets its real numbers from the full-size run.
    "DBX_BENCH_SUB_N": "12", "DBX_BENCH_SUB_SYMBOLS": "3",
    "DBX_BENCH_SUB_CONNS": "4",
}


@pytest.fixture(scope="module")
def fanout_bench():
    """One tiny in-process fanout run (loopback gRPC, streaming
    Subscribe calls, instant backend), shared by the module."""
    prior = {k: os.environ.get(k) for k in _FANOUT_ENV}
    os.environ.update(_FANOUT_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_fanout_keys_present(fanout_bench):
    """The live fan-out acceptance numbers ride these BENCH JSON keys
    (advances_per_tick == unique streams, pushes_per_advance, the p99
    latency bar) — a renamed key would silently invalidate BENCH_r12's
    successors. The tiny run's invariants are exact: 3 ticks, 3
    advances, 12 pushes, nothing dropped."""
    fb = fanout_bench["roofline"]["fanout"]
    for key in ("subscriptions", "symbols", "unique_streams", "ticks",
                "advances_total", "advances_per_tick",
                "advances_eq_streams", "pushes_delivered",
                "pushes_dropped", "pushes_per_advance",
                "tick_to_push_p50_s", "tick_to_push_p99_s", "p99_bar_s",
                "p99_ok", "tick_wall_s", "drain_wall_s"):
        assert key in fb, key
    assert fb["advances_total"] == 3
    assert fb["advances_per_tick"] == 1.0
    assert fb["advances_eq_streams"] is True
    assert fb["pushes_delivered"] == 12
    assert fb["pushes_dropped"] == 0
    assert fb["pushes_per_advance"] == 4.0
    assert fb["tick_to_push_p99_s"] > 0.0


_TENANT_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "e2e_local_tenants,scenario_sweep",
    # Tiny-but-real loopback drains + generator runs — structure smoke,
    # not performance; the 2x fairness bar is asserted on the real-size
    # run, not here (tiny samples make p95 noisy).
    "DBX_BENCH_TENANT_SMALL_JOBS": "6", "DBX_BENCH_TENANT_WHALE_JOBS": "18",
    "DBX_BENCH_TENANT_WHALE_COMBOS": "16",
    "DBX_BENCH_SCENARIO_BARS": "192", "DBX_BENCH_SCENARIO_N": "4",
}


@pytest.fixture(scope="module")
def tenant_bench():
    """One tiny in-process e2e_local_tenants + scenario_sweep run (loopback
    gRPC, instant backend, tiny generator shapes), shared by the module."""
    prior = {k: os.environ.get(k) for k in _TENANT_ENV}
    os.environ.update(_TENANT_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_tenant_fairness_keys_present(tenant_bench):
    """The 3-tenant adversarial A/B's acceptance numbers ride these BENCH
    JSON keys (tenant_p95_queue_wait_{solo,contended} + the ratio) — a
    renamed key would silently invalidate the next round's measurement."""
    tb = tenant_bench["roofline"]["e2e_local_tenants"]
    for key in ("small_jobs", "whale_jobs", "small_combos_per_job",
                "whale_combos_per_job", "tenant_p95_queue_wait_solo",
                "tenant_p95_queue_wait_contended", "fairness_ratio",
                "fairness_ok", "per_tenant_p95_contended",
                "jobs_per_s_solo", "jobs_per_s_contended"):
        assert key in tb, key
    assert tb["tenant_p95_queue_wait_solo"] > 0.0
    assert tb["tenant_p95_queue_wait_contended"] > 0.0
    assert tb["jobs_per_s_contended"] > 0.0
    for t in ("whale", "small_a", "small_b"):
        assert t in tb["per_tenant_p95_contended"], t
    assert tenant_bench["configs"]["e2e_local_tenants"] > 0.0


def test_scenario_sweep_keys_present(tenant_bench):
    """Scenario synthesis facts: generator rate, the (digest, params)
    spec-vs-panel wire columns, e2e dispatcher-materialized drain, and
    — structurally true at ANY scale — bit-reproducible digests."""
    sc = tenant_bench["roofline"]["scenario_sweep"]
    for key in ("panels", "bars", "gen_s_per_panel", "panels_per_s",
                "bar_rate", "digest_deterministic", "panel_bytes",
                "spec_bytes", "spec_wire_reduction", "jobs_per_s_e2e"):
        assert key in sc, key
    assert sc["digest_deterministic"] is True
    assert sc["panels_per_s"] > 0.0
    assert sc["jobs_per_s_e2e"] > 0.0
    assert sc["spec_bytes"] < sc["panel_bytes"]
    assert tenant_bench["configs"]["scenario_sweep"] > 0.0


_MEGAKERNEL_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "scenario_megakernel",
    # Tiny-but-real fused-vs-materialized A/B drains (loopback gRPC,
    # real JAX worker) — structure smoke; the 10x throughput bar is
    # asserted on the real-size run, not here. The store-bytes-flat-in-K
    # invariant IS structural and holds at any scale.
    "DBX_BENCH_MEGAKERNEL_BARS": "96", "DBX_BENCH_MEGAKERNEL_K": "4",
}


@pytest.fixture(scope="module")
def megakernel_bench():
    """One tiny in-process scenario_megakernel A/B run, shared by the
    module."""
    prior = {k: os.environ.get(k) for k in _MEGAKERNEL_ENV}
    os.environ.update(_MEGAKERNEL_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_scenario_megakernel_keys_present(megakernel_bench):
    """The round-18 acceptance numbers ride these BENCH JSON keys (the
    fused-vs-materialized scenarios/s ratio and the store-bytes-vs-K
    curve) — a renamed key would silently invalidate the next round's
    measurement."""
    mk = megakernel_bench["roofline"]["scenario_megakernel"]
    for key in ("scenarios", "bars", "combos", "fused_scn_per_s",
                "materialized_scn_per_s", "speedup",
                "store_bytes_by_k_fused", "store_bytes_by_k_materialized",
                "store_bytes_flat_in_k"):
        assert key in mk, key
    assert mk["fused_scn_per_s"] > 0.0
    assert mk["materialized_scn_per_s"] > 0.0
    assert megakernel_bench["configs"]["scenario_megakernel"] > 0.0


def test_scenario_megakernel_store_bytes_flat_in_k(megakernel_bench):
    """Device/store residency is O(1) in K on the fused route: every
    curve point holds exactly the base panel (1 entry, same byte count),
    while the materialized route's store grows with K — the structural
    half of the megakernel claim, true at any scale."""
    mk = megakernel_bench["roofline"]["scenario_megakernel"]
    fused = mk["store_bytes_by_k_fused"]
    mat = mk["store_bytes_by_k_materialized"]
    assert len(fused) >= 2 and len(mat) >= 2
    assert mk["store_bytes_flat_in_k"] is True
    assert len({p["store_bytes"] for p in fused}) == 1
    assert all(p["store_panels"] == 1 for p in fused)
    # Materialized stores base + K scenario panels: strictly growing.
    ks = [p["k"] for p in mat]
    assert all(p["store_panels"] == p["k"] + 1 for p in mat)
    bytes_by_k = [p["store_bytes"] for p in mat]
    assert bytes_by_k == sorted(bytes_by_k) and ks == sorted(ks)
    assert bytes_by_k[-1] > fused[-1]["store_bytes"]


_RAGGED_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "ragged_paged",
    # Tiny-but-real mixed-length fleet through the page pool (CPU
    # interpret mode) — structure smoke; the 1.3x ratio bar is asserted
    # on the real-size run, not here.
    "DBX_BENCH_RAGGED_TICKERS": "6", "DBX_BENCH_RAGGED_SPREAD": "3",
    "DBX_BENCH_BARS": "96", "DBX_BENCH_ITERS": "1",
    "DBX_BENCH_WARMUP": "0", "DBX_PAGE_BARS": "16",
}


@pytest.fixture(scope="module")
def ragged_bench():
    """One tiny in-process ragged_paged run, shared by the module."""
    prior = {k: os.environ.get(k) for k in _RAGGED_ENV}
    os.environ.update(_RAGGED_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_ragged_paged_keys_present(ragged_bench):
    """The ragged paged A/B's acceptance numbers (paged_vs_uniform_ratio
    <= 1.3 at real scale, the launch/pad-bar savings and the pool
    residency cost) ride these BENCH JSON keys — a renamed key would
    silently invalidate the next round's measurement."""
    rp = ragged_bench["roofline"]["ragged_paged"]
    for key in ("tickers", "t_max", "t_min", "total_bars", "uniform_bars",
                "combos", "page_bars", "paged_s_per_sweep",
                "uniform_s_per_sweep", "paged_vs_uniform_ratio",
                "ratio_ok", "launches_dense", "launches_paged",
                "pad_bars_dense", "pad_bars_paged", "pool_bytes",
                "pool_bytes_per_ticker"):
        assert key in rp, key
    assert rp["paged_s_per_sweep"] > 0.0
    assert rp["uniform_s_per_sweep"] > 0.0
    assert rp["paged_vs_uniform_ratio"] > 0.0
    assert rp["launches_paged"] >= 1
    # The pad saving is structural (one page per ticker vs up-to-2x
    # bucket padding), true at any scale with a mixed-length fleet.
    assert rp["pad_bars_paged"] <= rp["tickers"] * rp["page_bars"]
    assert rp["pool_bytes"] > 0
    assert ragged_bench["configs"]["ragged_paged"] > 0.0


def test_streaming_append_keys_present(stream_bench):
    """The streaming A/B's acceptance numbers (append_speedup at the
    headline T=8192/ΔT=16, and the delta-vs-full wire columns) ride
    these BENCH JSON keys — a renamed key would silently invalidate the
    next round's measurement."""
    sa = stream_bench["roofline"]["streaming_append"]
    for key in ("bars_base", "delta_bars", "updates", "combos",
                "append_s_per_update", "full_reprice_s_per_update",
                "append_speedup", "wire_bytes_full", "wire_bytes_delta",
                "wire_reduction"):
        assert key in sa, key
    assert sa["append_s_per_update"] > 0.0
    assert sa["full_reprice_s_per_update"] > 0.0
    assert sa["append_speedup"] > 0.0
    # The wire saving is structural (ΔT vs T+ΔT bars), true at any scale.
    assert sa["wire_bytes_delta"] < sa["wire_bytes_full"]
    assert stream_bench["configs"]["streaming_append"] > 0.0


_AUTOTUNE_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "autotune",
    # Tiny-but-real: a handful of measured candidates per family on tiny
    # shapes, and a small compile probe through the REAL gRPC compile
    # exchange — structure smoke; the 1.2x / 5x acceptance bars are
    # asserted on the real-size run (BENCH_r10.json), not here.
    "DBX_BENCH_AUTOTUNE_BARS": "64", "DBX_BENCH_AUTOTUNE_TICKERS": "2",
    "DBX_BENCH_AUTOTUNE_COMPILE_DEPTH": "4",
    "DBX_AUTOTUNE_TRIALS": "2", "DBX_BENCH_ITERS": "1",
}


@pytest.fixture(scope="module")
def autotune_bench():
    """One tiny in-process autotune run, shared by the module."""
    prior = {k: os.environ.get(k) for k in _AUTOTUNE_ENV}
    prior["DBX_AUTOTUNE"] = os.environ.pop("DBX_AUTOTUNE", None)
    os.environ.update(_AUTOTUNE_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


_PIPELINE_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "pipeline",
    # Tiny-but-real: a short saturated drain through the REAL gRPC
    # worker loop in both loop modes — structure smoke; the 1.4x / 1.6x
    # acceptance bars are asserted on the real-size run (BENCH_r13.json),
    # not here.
    "DBX_BENCH_PIPELINE_JOBS": "8", "DBX_BENCH_PIPELINE_BARS": "128",
    "DBX_BENCH_PIPELINE_FAST": "2", "DBX_BENCH_PIPELINE_SLOW": "2",
    "DBX_BENCH_PIPELINE_BATCH": "2",
    "DBX_BENCH_PIPELINE_DEVICE_MS": "3",
}


@pytest.fixture(scope="module")
def pipeline_bench():
    """One tiny in-process pipeline A/B run, shared by the module."""
    prior = {k: os.environ.get(k) for k in _PIPELINE_ENV}
    for knob in ("DBX_PIPELINE", "DBX_PREFETCH"):
        prior[knob] = os.environ.pop(knob, None)
    os.environ.update(_PIPELINE_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_pipeline_ab_keys_present(pipeline_bench):
    """The round-14 pipelined-executor A/B's acceptance numbers
    (pipeline_speedup, overlap_factor, the per-stage before/after
    attribution) ride these BENCH JSON keys — a renamed key would
    silently invalidate BENCH_r13's acceptance record."""
    pl = pipeline_bench["roofline"]["pipeline"]
    for key in ("jobs", "bars", "combos_per_job", "batch",
                "host_stage_ms", "device_stage_ms",
                "jobs_per_s_serial", "jobs_per_s_pipelined",
                "pipeline_speedup", "overlap_factor",
                "overlap_factor_serial", "stages_serial",
                "stages_pipelined"):
        assert key in pl, key
    assert pl["jobs_per_s_serial"] > 0.0
    assert pl["jobs_per_s_pipelined"] > 0.0
    assert pl["pipeline_speedup"] > 0.0
    # Overlap factors are ratios >= ~1; no performance bar here (tiny
    # shapes on a loaded CI core), but the serial arm must never read
    # as pipelined.
    assert pl["overlap_factor"] >= 1.0
    assert pl["overlap_factor_serial"] == pytest.approx(1.0, abs=0.25)
    # The before/after stage attribution actually attributed: both arms
    # saw decode (host staging) and d2h (device drain) walls.
    for stages in (pl["stages_serial"], pl["stages_pipelined"]):
        assert stages.get("decode", 0.0) > 0.0
        assert stages.get("d2h", 0.0) > 0.0
    assert pipeline_bench["configs"]["pipeline"] > 0.0


_FLEET_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "fleet_telemetry",
    # Tiny-but-real: a short direct-dispatch A/B plus a 2-worker
    # loopback drain with real telemetry frames — structure smoke; the
    # <=5% overhead and staleness bars are asserted on the real-size
    # run (BENCH_r14.json), not here (tiny samples are noise).
    "DBX_BENCH_LOCAL_JOBS": "96", "DBX_BENCH_FLEET_JOBS": "48",
    "DBX_BENCH_FLEET_WORKERS": "2", "DBX_BENCH_FLEET_POLL_S": "0.1",
}


@pytest.fixture(scope="module")
def fleet_bench():
    """One tiny in-process fleet_telemetry run (loopback gRPC, instant
    backend, real telemetry frames + FleetView), shared by the module."""
    prior = {k: os.environ.get(k) for k in _FLEET_ENV}
    os.environ.update(_FLEET_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_fleet_telemetry_keys_present(fleet_bench):
    """The fleet telemetry plane's acceptance numbers
    (telemetry_overhead_pct <= 5 with the 2k floor holding,
    fleet_staleness_p95_s <= 2 poll periods, frame_bytes_p50) ride
    these BENCH JSON keys — a renamed key would silently invalidate
    BENCH_r14's successors. Structurally true at any scale: both A/B
    arms drain, frames flow, and every live worker is visible in the
    merged view."""
    ft = fleet_bench["roofline"]["fleet_telemetry"]
    for key in ("jobs", "batch", "jobs_per_s_off", "jobs_per_s_on",
                "telemetry_overhead_pct", "overhead_ok", "floor_ok",
                "frame_bytes_p50", "frames_sampled", "e2e_jobs",
                "e2e_workers", "e2e_poll_s", "workers_seen",
                "all_workers_visible", "fleet_staleness_p95_s",
                "staleness_bar_s", "staleness_ok", "straggler_flagged",
                "histogram_merge_exact"):
        assert key in ft, key
    assert ft["jobs_per_s_off"] > 0.0
    assert ft["jobs_per_s_on"] > 0.0
    # Frames really flowed, and the merged /fleet.json saw every worker
    # (the 2 instant workers + the fast/slow straggler probes).
    assert ft["frame_bytes_p50"] > 0
    assert ft["frames_sampled"] >= 1
    assert ft["workers_seen"] == ft["e2e_workers"] + 2
    assert ft["all_workers_visible"] is True
    assert ft["fleet_staleness_p95_s"] >= 0.0
    # Structurally true at any scale: the slowed probe's execute EWMA
    # sits far above the healthy bulk's p95, and the fleet histogram is
    # the exact fold of the per-worker rows.
    assert ft["straggler_flagged"] is True
    assert ft["histogram_merge_exact"] is True
    assert fleet_bench["configs"]["fleet_telemetry"] > 0.0


_FLIGHT_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "flight",
    # Tiny-but-real: a short recorder-armed direct-dispatch A/B plus the
    # deterministic synthetic residual feed — structure smoke; the <=2%
    # overhead bar is asserted on the real-size run (tiny samples are
    # noise), but the residual math is exact at any scale.
    "DBX_BENCH_LOCAL_JOBS": "96", "DBX_COSTMODEL": "1",
}


@pytest.fixture(scope="module")
def flight_bench():
    """One tiny in-process flight run (loopback gRPC, armed recorder in a
    tempdir, synthetic residual stream), shared by the module."""
    prior = {k: os.environ.get(k) for k in _FLIGHT_ENV}
    for knob in ("DBX_FLIGHT_DIR", "DBX_COSTMODEL_WARMUP",
                 "DBX_COSTMODEL_BLOWOUT"):
        prior[knob] = os.environ.pop(knob, None)
    os.environ.update(_FLIGHT_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_flight_keys_present(flight_bench):
    """The flight recorder's acceptance numbers (recorder-armed overhead
    <= 2% on the direct_dispatch floor, zero bundles on the happy path)
    and the drift plane's costmodel_residual_{p50,p95} ride these BENCH
    JSON keys — a renamed key would silently invalidate the round-17
    acceptance record. Structurally true at any scale: the armed cycle
    writes NO bundles (the hot path never captures), capture_now really
    writes one, and the synthetic residual stream is exact math — 20
    scored observations, exactly one past the blowout bar."""
    fl = flight_bench["roofline"]["flight"]
    for key in ("jobs", "batch", "jobs_per_s_off", "jobs_per_s_on",
                "overhead_pct", "overhead_ok", "floor_ok",
                "bundles_during_run", "quiet_ok", "capture_smoke_ok",
                "costmodel_obs", "costmodel_blowouts",
                "costmodel_residual_p50", "costmodel_residual_p95"):
        assert key in fl, key
    assert fl["jobs_per_s_off"] > 0.0
    assert fl["jobs_per_s_on"] > 0.0
    assert fl["bundles_during_run"] == 0
    assert fl["quiet_ok"] is True
    assert fl["capture_smoke_ok"] is True
    # The synthetic feed is deterministic: warmup_n()-1 calibration obs
    # after the seed, then 20 drifted durations computed FROM the op
    # model — 20 scored residuals, the first (+3.5 log2) past the
    # default 3.0 blowout bar, tail above body.
    assert fl["costmodel_obs"] == 20
    assert fl["costmodel_blowouts"] == 1
    assert fl["costmodel_residual_p95"] >= fl["costmodel_residual_p50"]
    assert flight_bench["configs"]["flight"] > 0.0


def test_autotune_keys_present(autotune_bench):
    """The substrate-autotuner A/B's acceptance numbers
    (autotuned_vs_default_speedup{family} with its modeled twin, and the
    fleet compile-cache second_worker_compile_wall_{cold,warm}_s /
    compile_wall_reduction pair) ride these BENCH JSON keys — a renamed
    key would silently invalidate the next round's measurement."""
    at = autotune_bench["roofline"]["autotune"]
    for key in ("autotuned_vs_default_speedup",
                "autotuned_vs_default_speedup_modeled", "families",
                "speedup_families_ok", "second_worker_compile_wall_cold_s",
                "second_worker_compile_wall_warm_s",
                "compile_wall_reduction", "fleet_entries_offered",
                "fleet_entries_installed", "platform"):
        assert key in at, key
    # >= 3 kernel families measured, each with a winner recorded.
    assert len(at["autotuned_vs_default_speedup"]) >= 3
    for fam, row in at["families"].items():
        assert row["default_s_per_sweep"] > 0.0, fam
        assert row["tuned_s_per_sweep"] > 0.0, fam
        assert at["autotuned_vs_default_speedup"][fam] > 0.0, fam
        assert at["autotuned_vs_default_speedup_modeled"][fam] > 0.0, fam
    assert at["second_worker_compile_wall_cold_s"] > 0.0
    assert at["second_worker_compile_wall_warm_s"] > 0.0
    assert at["compile_wall_reduction"] > 0.0
    assert autotune_bench["configs"]["autotune"] > 0.0


_DECISION_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "decision_plane",
    # Tiny-but-real: five short paired killed/armed direct-dispatch
    # rounds plus the deterministic synthetic shadow stream. The <=2%
    # median-paired-delta bar is asserted on the real-size run (tiny
    # samples are pure noise), but the shadow math is exact at any
    # scale.
    "DBX_BENCH_LOCAL_JOBS": "96",
}


@pytest.fixture(scope="module")
def decision_bench():
    """One tiny in-process decision_plane run (loopback gRPC A/B plus
    the synthetic two-worker shadow stream), shared by the module."""
    prior = {k: os.environ.get(k) for k in _DECISION_ENV}
    for knob in ("DBX_DECISIONS", "DBX_DECISIONS_RATE",
                 "DBX_DECISIONS_H2D_GBPS"):
        prior[knob] = os.environ.pop(knob, None)
    os.environ.update(_DECISION_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_decision_plane_keys_present(decision_bench):
    """The decision plane's acceptance numbers (recorder-armed median
    paired delta on the direct_dispatch floor, shadow-scorer agreement
    and regret percentiles) ride these BENCH JSON keys — a renamed key
    would silently invalidate the round-19 acceptance record. The
    overhead/floor verdicts are asserted as present, not True: at 96
    jobs the paired deltas are box noise, and the bar belongs to the
    real-size run. The shadow stream IS exact at any scale: 16
    deterministic decisions over a two-worker fleet, 12 placed on the
    panel-resident worker — agreement is 75% by construction and every
    mis-placement's regret is the panel's h2d wall."""
    dp = decision_bench["roofline"]["decision_plane"]
    for key in ("jobs", "batch", "jobs_per_s_off", "jobs_per_s_on",
                "decision_overhead_delta_pct", "overhead_rounds_pct",
                "overhead_ok", "floor_ok", "shadow_scored",
                "shadow_agreement_pct", "regret_p50", "regret_p95",
                "regret_expected_s"):
        assert key in dp, key
    assert dp["jobs_per_s_off"] > 0.0
    assert dp["jobs_per_s_on"] > 0.0
    assert len(dp["overhead_rounds_pct"]) == 5
    assert isinstance(dp["overhead_ok"], bool)
    assert isinstance(dp["floor_ok"], bool)
    # Deterministic synthetic stream: all 16 scored, 12/16 agree.
    assert dp["shadow_scored"] == 16
    assert dp["shadow_agreement_pct"] == 75.0
    assert dp["regret_expected_s"] > 0.0
    assert dp["regret_p95"] >= dp["regret_p50"] >= 0.0
    assert decision_bench["configs"]["decision_plane"] > 0.0


_PLACEMENT_ENV = {
    "DBX_BENCH_CPU": "1", "DBX_BENCH_CACHE": "",
    "DBX_BENCH_CONFIGS": "e2e_local_placement",
    # Tiny-but-real: 3 chains x 4 links plus the repeat/cold tail,
    # virtual stage costs scaled down 4x so the A/B finishes in seconds.
    "DBX_BENCH_PL_SCALE": "0.25",
    "DBX_BENCH_PL_CHAINS": "3",
    "DBX_BENCH_PL_LINKS": "4",
}


@pytest.fixture(scope="module")
def placement_bench():
    """One tiny in-process e2e_local_placement A/B (locality-blind vs
    live placement over two loopback workers), shared by the module."""
    prior = {k: os.environ.get(k) for k in _PLACEMENT_ENV}
    for knob in ("DBX_PLACEMENT", "DBX_PLACEMENT_DEFER_CAP",
                 "DBX_DECISIONS", "DBX_DECISIONS_RATE",
                 "DBX_DECISIONS_H2D_GBPS"):
        prior[knob] = os.environ.pop(knob, None)
    os.environ.update(_PLACEMENT_ENV)
    bench.ROOFLINE.clear()
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench.main()
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def test_placement_keys_present(placement_bench):
    """Round-20 acceptance numbers (placement_speedup >= 1.5x vs the
    locality-blind arm, live regret strictly below the round-19 shadow
    baseline) ride these BENCH JSON keys — a renamed key would silently
    invalidate the acceptance record. Keys-present only: at 3x4 links
    with scaled virtual costs the speedup and regret verdicts are box
    noise, and the bar belongs to the real-size run. The structural
    facts ARE exact at any scale: both arms score every take, and the
    admit counters partition every placement-gate consultation."""
    pl = placement_bench["roofline"]["e2e_local_placement"]
    for key in ("jobs", "workers", "jobs_per_s_blind", "jobs_per_s_live",
                "placement_speedup", "defer_rate", "admit_counts",
                "regret_seconds_shadow", "regret_seconds_live",
                "scored_shadow", "scored_live", "speedup_ok",
                "regret_ok"):
        assert key in pl, key
    assert pl["jobs"] > 0 and pl["workers"] == 2
    assert pl["jobs_per_s_blind"] > 0.0
    assert pl["jobs_per_s_live"] > 0.0
    assert pl["scored_shadow"] > 0 and pl["scored_live"] > 0
    assert 0.0 <= pl["defer_rate"] <= 1.0
    assert set(pl["admit_counts"]) <= {"served", "deferred", "cap"}
    assert pl["admit_counts"]["served"] > 0
    assert isinstance(pl["speedup_ok"], bool)
    assert isinstance(pl["regret_ok"], bool)
    assert placement_bench["configs"]["e2e_local_placement"] > 0.0
