"""Fleet telemetry plane (obs/fleet.py, round 15): frame-merge
determinism, the staleness flag -> evict lifecycle against a stopped
worker, straggler flags, merged-histogram exactness vs a single-process
registry, hostile worker ids through the bounded bucket map, the
`dbxtop` surfaces (--url CLIs), and the DBX_LOCKDEP zero-violations
gate — all in-process (tier-1 budget discipline)."""

import contextlib
import io
import json
import threading
import time

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu import obs
from distributed_backtesting_exploration_tpu.obs import fleet
from distributed_backtesting_exploration_tpu.obs.registry import (
    Histogram, Registry)
from distributed_backtesting_exploration_tpu.rpc import compute
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    Dispatcher, DispatcherServer, JobQueue, PeerRegistry, synthetic_jobs)
from distributed_backtesting_exploration_tpu.rpc.worker import Worker
from distributed_backtesting_exploration_tpu.sched import tenancy

GRID = {"fast": np.arange(5.0, 9.0, dtype=np.float32)}


def _wait(pred, timeout=30.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _frame(gen="g1", pid=1, seq=1, t=1000.0, jobs=10, rate=2.5,
           stages=None, proc=None, caches=None, proc_id=None):
    """A hand-built telemetry frame (the schema is the wire contract).
    ``proc_id`` omitted exercises the pre-token fallback (dedupe keys
    on pid)."""
    doc = {
        "v": 1, "gen": gen, "pid": pid, "seq": seq, "t": t,
        "uptime_s": 5.0, "busy": 1, "inflight": 1,
        "pipeline": {"on": True, "depth": 2},
        "jobs_completed": jobs, "completions_dropped": 0, "polls": seq,
        "jobs_per_s": rate, "caps": {"backend": "test", "chips": 1},
        "caches": caches or {}, "proc": proc or {},
        "stages": stages or {}}
    if proc_id is not None:
        doc["proc_id"] = proc_id
    return json.dumps(doc, sort_keys=True)


def _stage_frame_stats(durs, stage="execute"):
    """Accumulate ``durs`` through a REAL worker-side stage collector
    and return its frame form — the exact accumulation the worker
    ships."""
    st = fleet._StageStats()
    for d in durs:
        st.observe({"name": f"worker.{stage}"
                    if stage != "execute" else "worker.execute",
                    "dur_s": d})
    return st.snapshot()


# ---------------------------------------------------------------------------
# Merge determinism
# ---------------------------------------------------------------------------

def test_frame_merge_is_order_independent():
    """Same frames in ANY arrival order => byte-identical snapshots:
    per generation the highest seq wins, across generations the later
    (t, gen) wins, and the snapshot is a pure function of the retained
    frames + now. This is the contract the placement scorer (ROADMAP
    item 3) and any future shard-to-shard gossip trust."""
    frames = [
        ("w-a", _frame(gen="a1", seq=1, t=1000.0, jobs=5)),
        ("w-a", _frame(gen="a1", seq=3, t=1002.0, jobs=20)),
        ("w-a", _frame(gen="a1", seq=2, t=1001.0, jobs=12)),
        # A RESTARTED w-a: new generation, later wall stamp — must win
        # over every a1 frame regardless of order.
        ("w-a", _frame(gen="a2", seq=1, t=1005.0, jobs=2)),
        ("w-b", _frame(gen="b1", seq=1, t=1000.5, jobs=7)),
        ("w-b", _frame(gen="b1", seq=2, t=1001.5, jobs=9)),
    ]
    import itertools

    snaps = set()
    for perm in itertools.permutations(range(len(frames))):
        fv = fleet.FleetView(registry=Registry(), clock=lambda: 50.0)
        for i in perm:
            fv.update(*frames[i])
        snaps.add(json.dumps(fv.snapshot(now=50.0), sort_keys=True))
    assert len(snaps) == 1
    snap = json.loads(next(iter(snaps)))
    assert snap["workers"]["w-a"]["gen"] == "a2"
    assert snap["workers"]["w-a"]["jobs_completed"] == 2
    assert snap["workers"]["w-b"]["seq"] == 2
    assert snap["fleet"]["jobs_completed"] == 11


def test_malformed_frames_are_counted_never_raised():
    reg = Registry()
    fv = fleet.FleetView(registry=reg, clock=lambda: 0.0)
    assert not fv.update("w", "not json{")
    assert not fv.update("w", json.dumps(["no", "gen"]))
    assert not fv.update("w", "")
    # JSON-valid but ill-typed fields are malformed too: adopting one
    # would poison every later snapshot() (the int()/float() folds),
    # turning /fleet.json and GetStats into permanent 500s.
    assert not fv.update("w", json.dumps({"gen": "g", "busy": "yes"}))
    assert not fv.update("w", json.dumps({"gen": "g", "seq": "x"}))
    assert not fv.update("w", json.dumps(
        {"gen": "g", "stages": {"execute": {"n": "NaN?"}}}))
    assert not fv.update("w", json.dumps({"gen": "g", "caps": "fast"}))
    # Python's json.loads parses bare NaN/Infinity tokens: non-finite
    # numerics are malformed too (a NaN jobs_per_s would make the fleet
    # rollup NaN and re-serialize as invalid JSON for strict parsers;
    # a NaN t defeats _frame_order — every comparison False).
    assert not fv.update("w", '{"gen": "g", "jobs_per_s": NaN}')
    assert not fv.update("w", '{"gen": "g", "t": Infinity}')
    assert not fv.update("w", '{"gen": "g", "busy": Infinity}')
    assert not fv.update("w", json.dumps(
        {"gen": "g", "stages": {"execute": {"ewma_s": float("inf")}}}))
    assert reg.counter("dbx_fleet_frames_total",
                       outcome="malformed").value == 10
    assert fv.snapshot(now=0.0)["fleet"]["workers"] == 0
    # A corrected follow-up frame heals the worker (nothing poisoned).
    assert fv.update("w", _frame(gen="g2"))
    assert fv.snapshot(now=0.0)["fleet"]["workers"] == 1


def test_unknown_frame_fields_skip_and_count():
    """Forward compatibility (round 17): a frame from a NEWER worker
    carrying fields this dispatcher doesn't know is adopted — the known
    fields merge, the unknown ones are skipped and counted
    (dbx_fleet_frame_unknown_fields_total + a per-worker flag in the
    snapshot/dbxtop), never treated as malformed. The alternative —
    rejecting the frame — would black out telemetry for every worker
    one release ahead of its dispatcher."""
    reg = Registry()
    fv = fleet.FleetView(registry=reg, clock=lambda: 0.0)
    doc = json.loads(_frame())
    doc["shiny_new_field"] = {"whatever": 1}
    doc["another_future_key"] = 2
    assert fv.update("w-f", json.dumps(doc, sort_keys=True))
    snap = fv.snapshot(now=0.0)
    assert snap["workers"]["w-f"]["unknown_fields"] == 2
    assert snap["workers"]["w-f"]["jobs_completed"] == 10
    assert reg.peek("dbx_fleet_frame_unknown_fields_total") == 2
    assert "+2fields" in fleet.render_text(snap)
    # A fully-known frame carries no flag at all.
    assert fv.update("w-g", _frame(gen="g2"))
    assert "unknown_fields" not in fv.snapshot(now=0.0)["workers"]["w-g"]


def test_restart_with_backstepped_clock_supersedes_once_stale():
    """A live restarted worker whose wall clock stepped BACKWARD across
    the restart must not be wedged behind its dead generation: while the
    retained entry is fresh the (t, gen) order holds (the lower-t frame
    is superseded), but once the entry passes the staleness bound a
    differing-generation frame is adopted regardless of wall stamps."""
    clock = [100.0]
    fv = fleet.FleetView(registry=Registry(), clock=lambda: clock[0],
                         stale_s_override=1.0)
    assert fv.update("w", _frame(gen="old", seq=9, t=5000.0, jobs=50))
    # Fresh entry: normal precedence — the backstepped frame loses.
    assert not fv.update("w", _frame(gen="new", seq=1, t=4000.0, jobs=1))
    assert fv.snapshot(now=clock[0])["workers"]["w"]["gen"] == "old"
    # Past the staleness bound the old gen has stopped talking — the
    # new generation wins even with the lower wall stamp.
    clock[0] += 2.0
    assert fv.update("w", _frame(gen="new", seq=2, t=4000.1, jobs=2))
    snap = fv.snapshot(now=clock[0])
    assert snap["workers"]["w"]["gen"] == "new"
    assert not snap["workers"]["w"]["stale"]


# ---------------------------------------------------------------------------
# Staleness: flag -> evict against a stopped worker (real gRPC fixture)
# ---------------------------------------------------------------------------

def test_stopped_worker_goes_stale_then_evicted(tmp_path, monkeypatch):
    """Two live workers gossip frames; one stops. Its entry must decay
    visibly — flagged ``stale`` past DBX_FLEET_STALE_S (rollups exclude
    it) — and then be EVICTED by the maintenance loop's prune path past
    3x the bound, while the surviving worker stays live the whole
    time."""
    monkeypatch.setenv("DBX_FLEET_STALE_S", "0.6")
    monkeypatch.setenv("DBX_FLEET_FRAME_MIN_S", "0.05")
    monkeypatch.setenv("DBX_FLEET_HEARTBEAT_S", "0.1")
    queue = JobQueue()
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=60.0),
                      results_dir=str(tmp_path / "results"))
    srv = DispatcherServer(disp, bind="localhost:0",
                           prune_interval_s=0.1).start()
    workers = [Worker(f"localhost:{srv.port}", compute.InstantBackend(),
                      worker_id=f"st-{i}", poll_interval_s=0.05,
                      status_interval_s=0.5, jobs_per_chip=8)
               for i in range(2)]
    threads = [threading.Thread(target=w.run, daemon=True)
               for w in workers]
    try:
        for t in threads:
            t.start()
        for rec in synthetic_jobs(16, 32, "sma_crossover", GRID, seed=3):
            queue.enqueue(rec)
        _wait(lambda: queue.drained, msg="drain")
        _wait(lambda: set(disp.fleet.snapshot()["workers"])
              == {"st-0", "st-1"}, msg="both workers in the fleet view")
        workers[1].stop()
        threads[1].join(timeout=20)
        # Phase 1: flagged stale (still present — visible decay).
        _wait(lambda: disp.fleet.snapshot()["workers"]
              .get("st-1", {}).get("stale") is True,
              msg="stopped worker flagged stale")
        snap = disp.fleet.snapshot()
        assert snap["workers"]["st-0"]["stale"] is False
        assert snap["fleet"]["live"] == 1
        assert snap["fleet"]["stale"] == 1
        # Phase 2: evicted by the maintenance loop past 3x the bound.
        _wait(lambda: "st-1" not in disp.fleet.snapshot()["workers"],
              msg="stale entry evicted by the prune path")
        assert "st-0" in disp.fleet.snapshot()["workers"]
        assert disp.obs.counter(
            "dbx_fleet_workers_evicted_total").value >= 1
    finally:
        for w in workers:
            w.stop()
        for t in threads:
            t.join(timeout=20)
        srv.stop()


def test_peer_prune_forgets_fleet_entry(tmp_path):
    """A peer pruned for silence drops out of the fleet view
    immediately (forget_worker) — no 3x-staleness wait for a worker the
    registry already declared dead."""
    queue = JobQueue()
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=60.0),
                      results_dir=str(tmp_path / "results"))
    disp.fleet.update("gone", _frame())
    assert "gone" in disp.fleet.snapshot()["workers"]
    disp.forget_worker("gone")
    assert "gone" not in disp.fleet.snapshot()["workers"]
    disp.close()


# ---------------------------------------------------------------------------
# Stragglers
# ---------------------------------------------------------------------------

def test_straggler_flagged_on_slowed_worker():
    """The PR-4 timeline rule applied live: a worker whose per-stage
    EWMA exceeds the fleet p95 (from the MERGED histograms, with the
    bucket-noise margin) is flagged in that stage — and only that
    worker, only that stage. The population shape matters: a straggler
    is slow, so it contributes FEW observations while the healthy bulk
    defines the p95 — exactly the regime the live rule serves."""
    fast = _stage_frame_stats([0.001] * 100)
    slow = _stage_frame_stats([0.8] * 4)
    fv = fleet.FleetView(registry=Registry(), clock=lambda: 0.0)
    fv.update("w-fast", _frame(gen="f", pid=1, stages=fast))
    fv.update("w-slow", _frame(gen="s", pid=2, stages=slow))
    snap = fv.snapshot(now=0.0)
    assert snap["workers"]["w-slow"]["stragglers"] == ["execute"]
    assert snap["workers"]["w-fast"]["stragglers"] == []
    # Transition counter ticks once per episode, not per scrape.
    reg = Registry()
    fv2 = fleet.FleetView(registry=reg, clock=lambda: 0.0)
    fv2.update("w-fast", _frame(gen="f", pid=1, stages=fast))
    fv2.update("w-slow", _frame(gen="s", pid=2, stages=slow))
    fv2.collect(reg)
    fv2.collect(reg)
    assert reg.counter("dbx_fleet_straggler_flags_total",
                       stage="execute").value == 1


def test_no_straggler_below_population_floor():
    """p95 of a tiny sample is noise: below MIN_STRAGGLER_OBS merged
    observations (or with a single live worker) nothing is flagged."""
    fv = fleet.FleetView(registry=Registry(), clock=lambda: 0.0)
    fv.update("w-slow", _frame(
        gen="s", pid=2, stages=_stage_frame_stats([0.8] * 3)))
    snap = fv.snapshot(now=0.0)
    assert snap["workers"]["w-slow"]["stragglers"] == []


# ---------------------------------------------------------------------------
# Histogram merge exactness
# ---------------------------------------------------------------------------

def test_merged_stage_histogram_is_exact_vs_single_registry():
    """The fleet fold and a single-process registry histogram see the
    SAME durations => identical count, sum and quantile estimates (the
    bucket bounds are shared and summing per-bucket counts commutes —
    exactness, not approximation)."""
    durs_a = [0.0001, 0.004, 0.004, 0.02, 0.3]
    durs_b = [0.0002, 0.008, 0.05, 1.2, 0.0007, 0.09]
    fv = fleet.FleetView(registry=Registry(), clock=lambda: 0.0)
    fv.update("w-a", _frame(gen="a", pid=1,
                            stages=_stage_frame_stats(durs_a)))
    fv.update("w-b", _frame(gen="b", pid=2,
                            stages=_stage_frame_stats(durs_b)))
    merged = fv.snapshot(now=0.0)["fleet"]["stages"]["execute"]

    # Reference 1: ONE worker-side collector fed every duration.
    ref = fleet._StageStats()
    for d in durs_a + durs_b:
        ref.observe({"name": "worker.execute", "dur_s": d})
    one = ref.snapshot()["execute"]
    assert merged["n"] == one["n"] == len(durs_a) + len(durs_b)
    assert merged["sum_s"] == pytest.approx(one["sum_s"])
    assert merged["p50_s"] == pytest.approx(
        fleet._hist_quantile(one["buckets"], 0.5))
    assert merged["p95_s"] == pytest.approx(
        fleet._hist_quantile(one["buckets"], 0.95))

    # Reference 2: the registry Histogram with the same (shared) bucket
    # bounds holds identical per-bucket counts.
    h = Histogram(fleet.STAGE_BUCKETS_S)
    for d in durs_a + durs_b:
        h.observe(d)
    reg_counts = []
    prev = 0
    for _, acc in h.cumulative():
        reg_counts.append(acc - prev)
        prev = acc
    assert reg_counts == one["buckets"]
    assert h.count == merged["n"]
    assert h.sum == pytest.approx(merged["sum_s"])


def test_cohosted_workers_fold_once_per_pid():
    """Co-hosted workers share one process-scope span stream; the fold
    dedupes per process so a 2-workers-1-process bench cannot
    double-count stage observations (same proc_id token; the bare-pid
    fallback for pre-token frames behaves the same)."""
    shared = _stage_frame_stats([0.01] * 10)
    fv = fleet.FleetView(registry=Registry(), clock=lambda: 0.0)
    fv.update("w-a", _frame(gen="a", pid=7, proc_id="proc-x",
                            stages=shared))
    fv.update("w-b", _frame(gen="b", pid=7, proc_id="proc-x",
                            stages=shared))
    merged = fv.snapshot(now=0.0)["fleet"]["stages"]["execute"]
    assert merged["n"] == 10   # not 20
    # Pre-token frames (no proc_id) fall back to pid-keyed dedupe.
    fv2 = fleet.FleetView(registry=Registry(), clock=lambda: 0.0)
    fv2.update("w-a", _frame(gen="a", pid=7, stages=shared))
    fv2.update("w-b", _frame(gen="b", pid=7, stages=shared))
    assert fv2.snapshot(now=0.0)["fleet"]["stages"]["execute"]["n"] == 10


def test_multihost_pid_collision_does_not_collapse_stats():
    """Bare OS pids collide across hosts (containers all run pid 1):
    frames from DIFFERENT processes that happen to share a pid must
    both count in the fleet fold — the dedupe keys on the host-unique
    proc_id token, not the pid."""
    s1 = _stage_frame_stats([0.01] * 10)
    s2 = _stage_frame_stats([0.02] * 6)
    fv = fleet.FleetView(registry=Registry(), clock=lambda: 0.0)
    fv.update("host-a/w", _frame(gen="a", pid=1, proc_id="proc-a",
                                 stages=s1,
                                 proc={"panel_host": [8, 2]}))
    fv.update("host-b/w", _frame(gen="b", pid=1, proc_id="proc-b",
                                 stages=s2,
                                 proc={"panel_host": [0, 10]}))
    snap = fv.snapshot(now=0.0)
    assert snap["fleet"]["stages"]["execute"]["n"] == 16   # 10 + 6
    # Cache hit counters aggregate across both hosts too: 8/(8+2+0+10).
    assert snap["fleet"]["cache_hit_ratio"]["panel_host"] == 0.4


# ---------------------------------------------------------------------------
# Worker-id cardinality + hostile strings
# ---------------------------------------------------------------------------

def test_hostile_worker_ids_through_bucket_map(monkeypatch):
    """Worker ids are wire-controlled strings: newlines, quotes,
    unicode and kilobyte names must neither break the Prometheus render
    nor mint unbounded label sets — past DBX_WORKER_LABEL_MAX everything
    shares the `other` bucket, and the sticky map stores nothing for
    overflow keys."""
    monkeypatch.setenv("DBX_WORKER_LABEL_MAX", "2")
    tenancy.reset_tenant_buckets()
    reg = Registry()
    fv = fleet.FleetView(registry=reg, clock=lambda: 0.0)
    hostile = ['evil"worker\n# HELP boom', "wörk☃er", "x" * 1024,
               "a\\b", "w-plain"]
    for i, wid in enumerate(hostile):
        fv.update(wid, _frame(gen=f"g{i}", pid=i))
    fv.collect(reg)
    text = reg.render_prometheus()
    # Escaped label values: the embedded newline must never start a
    # line of its own (a raw one would feed the scraper a fake HELP).
    assert not any(line.startswith("# HELP boom")
                   for line in text.splitlines())
    assert r"\n# HELP boom" in text    # escaped form survives in-label
    buckets = {tenancy.worker_bucket(w) for w in hostile}
    assert tenancy.OVERFLOW_BUCKET in buckets
    assert len(buckets) == 3     # 2 sticky names + "other"
    # The JSON surface keeps full ids (per-document, not per-series).
    snap = fv.snapshot(now=0.0)
    assert set(snap["workers"]) == set(hostile)
    json.dumps(snap)             # serializable as served
    tenancy.reset_tenant_buckets()


def test_per_worker_gauges_removed_with_their_workers(monkeypatch):
    """Evicting/forgetting a worker must also retire its per-worker
    gauge series: a dead worker's last jobs/s (or a stuck stale=1) must
    not be served forever. A shared bucket ("other") survives while any
    retained worker still maps to it."""
    monkeypatch.setenv("DBX_WORKER_LABEL_MAX", "16")
    tenancy.reset_tenant_buckets()
    reg = Registry()
    clock = [0.0]
    fv = fleet.FleetView(registry=reg, clock=lambda: clock[0],
                         stale_s_override=1.0)
    fv.update("w-keep", _frame(gen="k1", rate=1.0))
    fv.update("w-drop", _frame(gen="d1", rate=9.0))
    fv.collect(reg)
    assert 'worker="w-drop"' in reg.render_prometheus()
    fv.forget("w-drop")
    fv.collect(reg)
    text = reg.render_prometheus()
    assert 'worker="w-drop"' not in text
    assert 'worker="w-keep"' in text
    # The staleness EVICTION path retires series the same way.
    clock[0] += 10.0             # 3x the 1s bound -> prune evicts
    fv.update("w-late", _frame(gen="l1", t=2000.0))
    assert fv.prune() == ["w-keep"]
    fv.collect(reg)
    text = reg.render_prometheus()
    assert 'worker="w-keep"' not in text
    assert 'worker="w-late"' in text
    tenancy.reset_tenant_buckets()


# ---------------------------------------------------------------------------
# SLO burn windows
# ---------------------------------------------------------------------------

def test_slo_burn_windows_and_counter(monkeypatch):
    monkeypatch.setenv("DBX_FLEET_SLO_BURN", "0.1")
    reg = Registry()
    clock = [1000.0]
    fv = fleet.FleetView(registry=reg, clock=lambda: clock[0])
    for _ in range(8):
        fv.observe_slo(False)
    for _ in range(2):
        fv.observe_slo(True)
    snap = fv.snapshot(now=clock[0])
    for win in ("5m", "1h"):
        assert snap["fleet"]["slo"][win] == {
            "ok": 8, "breach": 2, "burn_rate": 0.2}
    fv.collect(reg)
    assert reg.counter("dbx_fleet_slo_burn_total",
                       window="5m").value == 1
    # Past the 5m window the fast-burn signal clears; 1h still burns.
    clock[0] += 400.0
    snap = fv.snapshot(now=clock[0])
    assert snap["fleet"]["slo"]["5m"]["breach"] == 0
    assert snap["fleet"]["slo"]["1h"]["breach"] == 2


# ---------------------------------------------------------------------------
# dbxtop + --url CLI surfaces
# ---------------------------------------------------------------------------

def test_dbxtop_render_and_url(tmp_path):
    """`dbxtop` end to end: a live dispatcher's /fleet.json scraped over
    HTTP renders the per-worker table with the fleet rollup header."""
    queue = JobQueue()
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=60.0),
                      results_dir=str(tmp_path / "results"))
    srv = DispatcherServer(disp, bind="localhost:0", prune_interval_s=5.0,
                           metrics_port=0,
                           metrics_host="127.0.0.1").start()
    try:
        disp.fleet.update("w-top", _frame(
            gen="t", stages=_stage_frame_stats([0.01] * 3)))
        url = f"http://127.0.0.1:{srv.metrics.port}"
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = fleet.main(["--url", url])
        assert rc == 0
        out = buf.getvalue()
        assert "w-top" in out
        assert "fleet: 1 live" in out
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = fleet.main(["--url", url + "/fleet.json",
                             "--format", "json"])
        assert rc == 0
        assert json.loads(buf.getvalue())["workers"]["w-top"]
    finally:
        srv.stop()


def test_timeline_and_dump_accept_url():
    """The round-15 satellite: obs.timeline / obs.dump point at a live
    /stats.json (the span ring rides it) without any log shipping."""
    from distributed_backtesting_exploration_tpu.obs import (
        dump as dump_mod, timeline as timeline_mod)

    tid = obs.new_trace_id()
    t0 = time.time() - 1
    obs.emit_span("job.queue_wait", t0, 0.4, trace_id=tid, job="u1")
    obs.emit_span("job.dispatch", t0 + 0.4, 0.1, trace_id=tid, job="u1",
                  worker="w-url")
    obs.emit_span("job", t0, 1.0, trace_id=tid, job="u1", worker="w-url")
    srv = obs.MetricsServer(0, bind="127.0.0.1").start()
    try:
        url = f"http://127.0.0.1:{srv.port}"
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = timeline_mod.main(["--url", url])
        assert rc == 0
        assert "critical-path stage attribution" in buf.getvalue()
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = dump_mod.main(["--url", url + "/stats.json"])
        assert rc == 0
        assert "dbx_span_seconds" in buf.getvalue()
    finally:
        srv.stop()


def test_timeline_url_exits_2_on_zero_events():
    """A live endpoint with an empty span ring is a broken pipeline
    from the operator's seat — exit 2, like the zero-parseable-JSONL
    case."""
    from distributed_backtesting_exploration_tpu.obs import (
        timeline as timeline_mod)

    reg = Registry()
    srv = obs.MetricsServer(0, registry=reg, bind="127.0.0.1").start()
    try:
        # A registry-scoped server still serves the PROCESS span ring;
        # point at a snapshot with the ring stripped via a fresh ring.
        obs.configure_ring(0)
        rc = timeline_mod.main(
            ["--url", f"http://127.0.0.1:{srv.port}"])
        assert rc == 2
    finally:
        obs.configure_ring()
        srv.stop()


# ---------------------------------------------------------------------------
# Frame suppression (dirty bit + heartbeat + rate floor)
# ---------------------------------------------------------------------------

def test_frame_dirty_bit_heartbeat_and_remark(monkeypatch):
    monkeypatch.setenv("DBX_FLEET_FRAME_MIN_S", "0")
    monkeypatch.setenv("DBX_FLEET_HEARTBEAT_S", "100")
    state = {"jobs": 1}
    wt = fleet.WorkerTelemetry(
        "w", stats_fn=lambda: {"jobs_completed": state["jobs"]},
        registry=Registry())
    now = 1000.0
    first = wt.take_frame_json(now)
    assert first
    # Clean poll inside the heartbeat: zero wire cost.
    assert wt.take_frame_json(now + 1) == ""
    # Change -> dirty -> frame.
    state["jobs"] = 2
    assert wt.take_frame_json(now + 2)
    # Clean again, but the heartbeat elapsed -> frame anyway.
    assert wt.take_frame_json(now + 200)
    # RPC failure path: remark resends the same content.
    assert wt.take_frame_json(now + 201) == ""
    wt.remark_dirty()
    assert wt.take_frame_json(now + 202)


def test_frame_rate_floor_suppresses_saturated_polls(monkeypatch):
    monkeypatch.setenv("DBX_FLEET_FRAME_MIN_S", "0.5")
    state = {"jobs": 0}

    def stats():
        state["jobs"] += 32     # saturated: dirty on every poll
        return {"jobs_completed": state["jobs"]}

    wt = fleet.WorkerTelemetry("w", stats_fn=stats, registry=Registry())
    now = 1000.0
    sent = sum(1 for i in range(100)
               if wt.take_frame_json(now + i * 0.01))
    assert sent <= 3            # ~1s of 10ms polls, 0.5s floor


# ---------------------------------------------------------------------------
# Lockdep gate: the gossip/merge paths under instrumented locks
# ---------------------------------------------------------------------------

def test_fleet_gossip_under_lockdep_is_violation_free(tmp_path,
                                                      monkeypatch):
    """The race-harness gate for the new paths (the test_serve twin):
    real workers gossip frames over gRPC into the FleetView while
    snapshots/scrapes read it — with every package lock instrumented.
    Zero violations pins the contract: no frame parse, JSON build or
    HTTP work happens under the view's lock."""
    from distributed_backtesting_exploration_tpu.analysis import lockdep

    monkeypatch.setenv("DBX_FLEET_FRAME_MIN_S", "0.02")
    monkeypatch.setenv("DBX_FLEET_HEARTBEAT_S", "0.05")
    was_active = lockdep.active()
    lockdep.install()
    lockdep.reset()
    try:
        queue = JobQueue()
        disp = Dispatcher(queue, PeerRegistry(prune_window_s=60.0),
                          results_dir=str(tmp_path / "results"))
        assert isinstance(disp.fleet._lock, lockdep._LockdepLock)
        srv = DispatcherServer(disp, bind="localhost:0",
                               prune_interval_s=0.1).start()
        worker = Worker(f"localhost:{srv.port}", compute.InstantBackend(),
                        worker_id="ld-0", poll_interval_s=0.02,
                        status_interval_s=0.5, jobs_per_chip=8)
        wt = threading.Thread(target=worker.run, daemon=True)
        try:
            wt.start()
            for rec in synthetic_jobs(24, 32, "sma_crossover", GRID,
                                      seed=9):
                queue.enqueue(rec)
            _wait(lambda: queue.drained, msg="drain under lockdep")
            _wait(lambda: "ld-0" in disp.fleet.snapshot()["workers"],
                  msg="frame merged under lockdep")
            # Concurrent readers: snapshot + full scrape while polls
            # still flow.
            for _ in range(5):
                disp.fleet.snapshot()
                disp.obs.render_prometheus()
                time.sleep(0.02)
        finally:
            worker.stop()
            wt.join(timeout=20)
            srv.stop()
        rep = lockdep.report()
        assert rep["violations"] == [], rep["violations"]
        # Non-vacuous: the view's lock was actually exercised.
        assert any("FleetView" in cls for cls in rep["held"]), rep["held"]
    finally:
        if not was_active:
            lockdep.uninstall()
        lockdep.reset()
