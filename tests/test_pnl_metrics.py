"""Golden tests: PnL engines and metrics vs pure-Python float64 loops."""

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_backtesting_exploration_tpu.ops import pnl, metrics, rolling
from distributed_backtesting_exploration_tpu.models import bollinger as boll
from distributed_backtesting_exploration_tpu.utils import data as data_mod


RNG = np.random.default_rng(7)
T = 300
CLOSE = (50.0 * np.exp(np.cumsum(RNG.normal(0.0005, 0.02, T)))).astype(np.float64)
POS = RNG.choice([-1.0, 0.0, 1.0], T)
POS[:20] = 0.0


def loop_backtest(close, pos, cost):
    r = np.zeros_like(close)
    r[1:] = close[1:] / close[:-1] - 1.0
    prev = 0.0
    net = np.zeros_like(close)
    for t in range(len(close)):
        net[t] = prev * r[t] - cost * abs(pos[t] - prev)
        prev = pos[t]
    return net, 1.0 + np.cumsum(net)


@pytest.mark.parametrize("cost", [0.0, 0.001])
def test_backtest_prefix_matches_loop(cost):
    res = pnl.backtest_prefix(
        jnp.asarray(CLOSE, jnp.float32), jnp.asarray(POS, jnp.float32), cost=cost)
    net, eq = loop_backtest(CLOSE, POS, cost)
    np.testing.assert_allclose(np.asarray(res.returns), net, atol=2e-5)
    np.testing.assert_allclose(np.asarray(res.equity), eq, atol=2e-4)


def test_backtest_prefix_compound():
    res = pnl.backtest_prefix(
        jnp.asarray(CLOSE, jnp.float32), jnp.asarray(POS, jnp.float32),
        cost=0.0005, compound=True)
    net, _ = loop_backtest(CLOSE, POS, 0.0005)
    eq = np.cumprod(1.0 + net)
    np.testing.assert_allclose(np.asarray(res.equity), eq, rtol=2e-4)


def loop_bollinger_positions(close, w, k):
    m = np.full_like(close, np.nan)
    s = np.full_like(close, np.nan)
    for t in range(w - 1, len(close)):
        win = close[t - w + 1: t + 1]
        m[t], s[t] = win.mean(), win.std()
    z = (close - m) / s
    pos = 0.0
    out = np.zeros_like(close)
    for t in range(len(close)):
        if t < w - 1:
            pos = 0.0
        elif pos == 0.0:
            pos = 1.0 if z[t] < -k else (-1.0 if z[t] > k else 0.0)
        elif pos == 1.0 and z[t] >= 0:
            pos = 0.0
        elif pos == -1.0 and z[t] <= 0:
            pos = 0.0
        out[t] = pos
    return out


@pytest.mark.parametrize("w,k", [(20, 1.5), (10, 2.0)])
def test_bollinger_scan_matches_loop(w, k):
    ohlcv = data_mod.OHLCV(*(jnp.asarray(CLOSE, jnp.float32),) * 5)
    got = np.asarray(boll.BOLLINGER.positions(
        ohlcv, {"window": jnp.asarray(w), "k": jnp.asarray(k, jnp.float32)}))
    want = loop_bollinger_positions(CLOSE, w, k)
    # f32 z-scores can flip a knife-edge comparison on isolated bars; the
    # state machines must agree on the overwhelming majority of bars.
    agree = (got == want).mean()
    assert agree > 0.99, f"positions agree on only {agree:.3f} of bars"


def test_metrics_against_numpy():
    net, eq = loop_backtest(CLOSE, POS, 0.0)
    rj = jnp.asarray(net, jnp.float32)
    ej = jnp.asarray(eq, jnp.float32)
    pj = jnp.asarray(POS, jnp.float32)

    got = metrics.summary_metrics(rj, ej, pj)
    ann = np.sqrt(252)
    np.testing.assert_allclose(
        float(got.sharpe), net.mean() / net.std() * ann, rtol=1e-3)
    peak = np.maximum.accumulate(eq)
    np.testing.assert_allclose(
        float(got.max_drawdown), ((peak - eq) / peak).max(), rtol=1e-4)
    np.testing.assert_allclose(float(got.total_return), eq[-1] - 1.0, atol=1e-4)
    np.testing.assert_allclose(
        float(got.volatility), net.std() * ann, rtol=1e-3)
    np.testing.assert_allclose(
        float(got.turnover), np.abs(np.diff(np.concatenate([[0.0], POS]))).sum(),
        rtol=1e-5)


def test_metrics_mask_excludes_warmup():
    """Masked sharpe must ignore the dead warmup bars."""
    r = np.zeros(100)
    r[50:] = 0.01  # constant gains in the live region
    mask = np.arange(100) >= 50
    s_masked = metrics.sharpe(jnp.asarray(r, jnp.float32),
                              mask=jnp.asarray(mask))
    # constant returns => ~zero std => huge sharpe; unmasked sees a step
    s_unmasked = metrics.sharpe(jnp.asarray(r, jnp.float32))
    assert float(s_masked) > 100 * float(s_unmasked)


def test_backtest_scan_engine():
    """Generic scan engine: trivial hold-previous-signal machine vs loop."""
    sig = jnp.asarray(RNG.choice([-1.0, 1.0], T), jnp.float32)

    def step(carry, x):
        nxt = jnp.where(x > 0, 1.0, carry * 0.5)
        return nxt, nxt

    res = pnl.backtest_scan(step, jnp.asarray(0.0), sig,
                            jnp.asarray(CLOSE, jnp.float32), cost=0.001)
    carry = 0.0
    want_pos = np.zeros(T)
    for t in range(T):
        carry = 1.0 if float(sig[t]) > 0 else carry * 0.5
        want_pos[t] = carry
    np.testing.assert_allclose(np.asarray(res.positions), want_pos, rtol=1e-6)
    net, _ = loop_backtest(CLOSE, want_pos, 0.001)
    np.testing.assert_allclose(np.asarray(res.returns), net, atol=2e-5)
