"""Long-context jobs over the wire route through the time-sharded path.

VERDICT r4 item 1: a job whose bar count exceeds the fused kernels' VMEM
cap (``_FUSED_MAX_BARS``) on a meshed multi-chip worker must shard its BAR
axis over the chips (``parallel.timeshard``) instead of demoting to one
device's generic path — with DBXM/DBXS payload parity against the
single-device backend, and the demotion warning replaced by a routed log.
The reference's compute slot (reference ``src/worker/process.rs:21-25``)
is the seam this serves; SURVEY.md §5's long-context row prescribes it.

Most tests shrink the trigger by patching the instance's
``_FUSED_MAX_BARS`` (CPU compiles of 8k-bar sharded programs are slow);
one test exercises the real 8192-bar cap end to end.
"""

import logging

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.rpc import (
    backtesting_pb2 as pb, compute, wire)
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    synthetic_jobs)


def _specs(recs, **extra):
    return [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                       ohlcv2=r.ohlcv2 or b"", grid=wire.grid_to_proto(r.grid),
                       cost=r.cost, **extra) for r in recs]


def _run(backend, specs):
    return {c.job_id: c.metrics for c in backend.process(specs)}


def _assert_same_payloads(got_a, got_b, *, rtol=3e-4, atol=3e-5):
    assert set(got_a) == set(got_b)
    for jid in got_a:
        ma = wire.metrics_from_bytes(got_a[jid])
        mb = wire.metrics_from_bytes(got_b[jid])
        for name in ma._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(ma, name)), np.asarray(getattr(mb, name)),
                rtol=rtol, atol=atol, err_msg=f"{jid}/{name}")


@pytest.fixture()
def ts_backend(devices):
    """Mesh backend with the long-context trigger shrunk to 192 bars."""
    b = compute.JaxSweepBackend(use_fused=False, use_mesh=True)
    b._FUSED_MAX_BARS = 192   # instance override: routing reads self.*
    return b


@pytest.fixture(scope="module")
def one_backend(devices):
    return compute.JaxSweepBackend(use_fused=False, use_mesh=False)


def test_long_context_routes_and_matches(ts_backend, one_backend, caplog):
    """A >cap-bar job routes to timeshard (logged, not warned) and its
    DBXM payload matches the single-device generic path; T is chosen
    indivisible by 8 so the repeat-last padding + t_real contract is on
    the hot path."""
    grid = {"fast": np.float32([5, 8]), "slow": np.float32([21.0])}
    specs = _specs(synthetic_jobs(1, 517, "sma_crossover", grid,
                                  cost=1e-3, seed=31))
    with caplog.at_level(logging.INFO, logger="dbx.compute"):
        got = _run(ts_backend, specs)
    assert any("time-sharded long-context path" in r.message
               for r in caplog.records)
    assert not any("demoted to the generic path" in r.message
                   for r in caplog.records)
    _assert_same_payloads(got, _run(one_backend, specs))


def test_long_context_families_parity(ts_backend, one_backend):
    """Sign/latch families (no knife-edge band entries) across the four
    state shapes: windowed (sma), bounded-halo lag (momentum),
    rolling-extrema latch (donchian_hl), double-accumulation (obv)."""
    cases = [
        ("sma_crossover", {"fast": np.float32([5, 8]),
                           "slow": np.float32([21.0])}),
        ("momentum", {"lookback": np.float32([10, 20])}),
        ("donchian_hl", {"window": np.float32([15.0])}),
        ("obv_trend", {"window": np.float32([12.0])}),
    ]
    for i, (strategy, grid) in enumerate(cases):
        specs = _specs(synthetic_jobs(2, 400, strategy, grid, cost=1e-3,
                                      seed=50 + i))
        _assert_same_payloads(_run(ts_backend, specs),
                              _run(one_backend, specs),
                              rtol=5e-4, atol=5e-5)


def test_long_context_ragged_group(ts_backend, one_backend):
    """Mixed lengths: each length subgroup pads to its own mesh multiple
    and passes its own t_real — results must match per job."""
    grid = {"fast": np.float32([5.0]), "slow": np.float32([21.0])}
    recs = []
    for i, bars in enumerate([300, 517, 300]):
        recs += synthetic_jobs(1, bars, "sma_crossover", grid, cost=1e-3,
                               seed=70 + i)
    specs = _specs(recs)
    _assert_same_payloads(_run(ts_backend, specs), _run(one_backend, specs))


def test_long_context_mixed_group_routes_partially(ts_backend, one_backend,
                                                   caplog):
    """One short job in a ragged group must not drag the long jobs off
    the time-sharded route: the group-level gate fails on min(lengths)'
    halo bound, the long job re-gates individually and routes, the short
    one runs generic — both match the single-device path. Lengths are
    chosen to share one power-of-two wire-size bucket (else they never
    group) with a window that fits the long job's per-chip block but not
    the short one's."""
    grid = {"fast": np.float32([5.0]), "slow": np.float32([90.0])}
    recs = synthetic_jobs(1, 600, "sma_crossover", grid, cost=1e-3, seed=80)
    recs += synthetic_jobs(1, 780, "sma_crossover", grid, cost=1e-3, seed=81)
    specs = _specs(recs)
    with caplog.at_level(logging.INFO, logger="dbx.compute"):
        got = _run(ts_backend, specs)
    assert any("route time-sharded individually" in r.message
               for r in caplog.records), \
        [r.message for r in caplog.records]
    _assert_same_payloads(got, _run(one_backend, specs))


def test_long_context_topk(ts_backend, one_backend):
    """top-k reduction composes with the timeshard route (DBXS payloads:
    same chosen combos, same metric rows)."""
    grid = {"fast": np.float32([3, 5, 8]), "slow": np.float32([13, 21])}
    specs = _specs(synthetic_jobs(1, 400, "sma_crossover", grid, cost=1e-3,
                                  seed=90),
                   top_k=3, rank_metric="sharpe")
    got_ts = _run(ts_backend, specs)
    got_one = _run(one_backend, specs)
    for jid in got_ts:
        idx_a, m_a, metric_a = wire.topk_from_bytes(got_ts[jid])
        idx_b, m_b, metric_b = wire.topk_from_bytes(got_one[jid])
        assert metric_a == metric_b == "sharpe"
        np.testing.assert_array_equal(np.asarray(idx_a), np.asarray(idx_b))
        for name in m_a._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(m_a, name)),
                np.asarray(getattr(m_b, name)), rtol=3e-4, atol=3e-5)


def test_long_context_pairs(ts_backend, one_backend):
    """Uniform long pairs groups shard both legs' bar axes. Flip-aware,
    like every pairs parity test: blockwise-cumsum rounding can flip a
    knife-edge band entry and move that pair's whole path — flips must
    stay rare and every non-flipped pair must match tightly."""
    grid = {"lookback": np.float32([15.0]), "z_entry": np.float32([1.2])}
    specs = _specs(synthetic_jobs(4, 450, "pairs", grid, cost=1e-3,
                                  seed=110))
    got_ts = _run(ts_backend, specs)
    got_one = _run(one_backend, specs)
    assert set(got_ts) == set(got_one)
    flips = 0
    for jid in got_ts:
        ma = wire.metrics_from_bytes(got_ts[jid])
        mb = wire.metrics_from_bytes(got_one[jid])
        a = np.asarray(ma.sharpe)
        b = np.asarray(mb.sharpe)
        if np.any(np.abs(a - b) > (0.01 + 0.01 * np.abs(b))):
            flips += 1
            continue
        for name in ma._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(ma, name)),
                np.asarray(getattr(mb, name)), rtol=2e-3, atol=2e-4,
                err_msg=f"{jid}/{name}")
    assert flips <= 1, f"{flips}/4 knife-edge flips"


def test_long_context_not_shardable_falls_back(ts_backend, one_backend,
                                               caplog):
    """A long-context group the sharded path cannot take (window larger
    than the per-chip block) falls back to the generic path loudly and
    still completes correctly."""
    # 400 bars over 8 chips -> 50-bar blocks; window 80 cannot halo.
    grid = {"window": np.float32([80.0])}
    specs = _specs(synthetic_jobs(1, 400, "donchian", grid, cost=1e-3,
                                  seed=130))
    with caplog.at_level(logging.INFO, logger="dbx.compute"):
        got = _run(ts_backend, specs)
    assert any("not time-shardable" in r.message for r in caplog.records)
    _assert_same_payloads(got, _run(one_backend, specs))


def test_real_cap_long_job_routes(devices):
    """The real 8192-bar cap, end to end, on the tie-free family: one
    8201-bar momentum job (T not divisible by 8) routes through timeshard
    and matches single-device tightly — momentum's signal compares RAW
    closes (``sign(close[t] - close[t-lb])``), no cumsum arithmetic, so
    the position path is bit-identical across both disciplines and only
    the metric reductions round differently."""
    ts = compute.JaxSweepBackend(use_fused=False, use_mesh=True)
    one = compute.JaxSweepBackend(use_fused=False, use_mesh=False)
    grid = {"lookback": np.float32([20.0, 60.0])}
    specs = _specs(synthetic_jobs(1, 8201, "momentum", grid, cost=1e-3,
                                  seed=150))
    _assert_same_payloads(_run(ts, specs), _run(one, specs),
                          rtol=2e-3, atol=2e-4)


def test_real_cap_sma_flip_class(devices):
    """The same real-cap route on SMA documents the knife-edge class: at
    8k bars the f32 close-cumsum's ulp (~0.03 at cs~8e5) puts ~0.5%% of
    bars' fast-slow SMA differences below rounding noise, and the
    blockwise and monolithic cumsums resolve those ties differently —
    tens of flipped bars move path metrics at the 1e-1 level. Agreement
    is asserted at that class, not f32-tight (the tight contract is
    proven at 517 bars above, where ties are rare)."""
    ts = compute.JaxSweepBackend(use_fused=False, use_mesh=True)
    one = compute.JaxSweepBackend(use_fused=False, use_mesh=False)
    grid = {"fast": np.float32([10.0]), "slow": np.float32([50.0])}
    specs = _specs(synthetic_jobs(1, 8201, "sma_crossover", grid, cost=1e-3,
                                  seed=150))
    got_ts = _run(ts, specs)
    got_one = _run(one, specs)
    for jid in got_ts:
        ma = wire.metrics_from_bytes(got_ts[jid])
        mb = wire.metrics_from_bytes(got_one[jid])
        for name in ma._fields:
            a, b = np.asarray(getattr(ma, name)), np.asarray(
                getattr(mb, name))
            assert np.all(np.isfinite(a) == np.isfinite(b))
            np.testing.assert_allclose(a, b, rtol=0.25, atol=0.1,
                                       err_msg=f"{jid}/{name}")


def test_long_context_over_live_dispatcher(devices):
    """Over the wire: a live dispatcher hands a long-context job to a
    mesh worker, which completes it via the timeshard route."""
    import threading
    import time

    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        Dispatcher, DispatcherServer, JobQueue, PeerRegistry)
    from distributed_backtesting_exploration_tpu.rpc.worker import Worker

    backend = compute.JaxSweepBackend(use_fused=False, use_mesh=True)
    backend._FUSED_MAX_BARS = 192
    q = JobQueue()
    grid = {"fast": np.float32([5.0]), "slow": np.float32([21.0])}
    for r in synthetic_jobs(3, 517, "sma_crossover", grid, cost=1e-3,
                            seed=170):
        q.enqueue(r)
    disp = Dispatcher(q, PeerRegistry(prune_window_s=30.0))
    srv = DispatcherServer(disp, bind="localhost:0",
                           prune_interval_s=0.5).start()
    w = Worker(f"localhost:{srv.port}", backend=backend,
               poll_interval_s=0.05)
    t = threading.Thread(target=w.run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline and not q.drained:
            time.sleep(0.1)
        assert q.drained, f"queue not drained: {q.stats()}"
        assert q.stats()["jobs_completed"] == 3
    finally:
        w.stop()
        t.join(timeout=20)
        srv.stop()
