"""Result retention bounds + fleet-level aggregation (VERDICT r2 #7).

The reference never reads results back (its completion map is write-only,
reference ``src/server/main.rs:33,66-78``); this framework must both bound
dispatcher-side result memory and turn stored blocks into decisions.
"""

import json
import os

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.ops.metrics import metric_sign
from distributed_backtesting_exploration_tpu.rpc import aggregate, compute
from distributed_backtesting_exploration_tpu.rpc import (
    backtesting_pb2 as pb, wire)
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    Dispatcher, JobQueue, parse_grid, synthetic_jobs)
from distributed_backtesting_exploration_tpu.rpc.journal import Journal


def test_in_memory_results_capped(monkeypatch):
    queue = JobQueue()
    disp = Dispatcher(queue)
    monkeypatch.setattr(Dispatcher, "MAX_RESIDENT_RESULTS", 5)
    recs = synthetic_jobs(8, 16, "sma_crossover",
                          parse_grid("fast=3,slow=8"))
    for rec in recs:
        queue.enqueue(rec)
    queue.take(8, "w1")
    for rec in recs:
        disp._complete_one(rec.id, "w1", b"\x01" * 64, 0.0)
    assert len(disp.results) == 5
    assert disp.results_evicted == 3
    # Oldest evicted, newest retained.
    assert recs[-1].id in disp.results and recs[0].id not in disp.results


def _completed_run(tmp_path, n_jobs=3):
    """Enqueue jobs with a journal, compute real metrics, store blocks."""
    journal_path = str(tmp_path / "journal.jsonl")
    results_dir = str(tmp_path / "results")
    queue = JobQueue(Journal(journal_path))
    grid = parse_grid("fast=3:5,slow=10:14:2")
    recs = synthetic_jobs(n_jobs, 96, "sma_crossover", grid, cost=1e-3,
                          seed=3)
    for rec in recs:
        queue.enqueue(rec)
    disp = Dispatcher(queue, results_dir=results_dir)
    queue.take(n_jobs, "w1")
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        grid=wire.grid_to_proto(r.grid), cost=r.cost,
                        periods_per_year=252) for r in recs]
    backend = compute.JaxSweepBackend()
    for c in backend.process(specs):
        disp._complete_one(c.job_id, "w1", c.metrics, c.elapsed_s)
    return journal_path, results_dir, recs


def test_aggregate_matches_direct_argmax(tmp_path):
    journal_path, results_dir, recs = _completed_run(tmp_path)
    out = aggregate.aggregate(results_dir, journal_path, metric="sharpe",
                              top=10)
    assert out["jobs_aggregated"] == len(recs)
    assert out["jobs_missing"] == 0
    by_job = {r["job"]: r for r in out["best"]}
    assert len(by_job) == len(recs)
    # Cross-check each job's best against a direct argmax over its block.
    for rec in recs:
        with open(f"{results_dir}/{rec.id}.dbxm", "rb") as fh:
            m = wire.metrics_from_bytes(fh.read())
        sharpe = np.asarray(m.sharpe)
        assert by_job[rec.id]["value"] == float(sharpe.max())
    # Fleet ranking is best-first.
    vals = [r["value"] for r in out["best"]]
    assert vals == sorted(vals, reverse=True)


def test_aggregate_lower_is_better_direction(tmp_path):
    journal_path, results_dir, recs = _completed_run(tmp_path)
    out = aggregate.aggregate(results_dir, journal_path,
                              metric="max_drawdown", top=10)
    assert metric_sign("max_drawdown") == -1.0
    for rec in recs:
        with open(f"{results_dir}/{rec.id}.dbxm", "rb") as fh:
            m = wire.metrics_from_bytes(fh.read())
        row = next(r for r in out["best"] if r["job"] == rec.id)
        assert row["value"] == float(np.asarray(m.max_drawdown).min())
    vals = [r["value"] for r in out["best"]]
    assert vals == sorted(vals)   # ascending: smaller drawdown ranks first


def test_np_product_grid_matches_sweep_product_grid():
    # Aggregation is numpy-pure (no device); its grid order must stay
    # locked to the jax product_grid the worker used to lay out DBXM rows.
    from distributed_backtesting_exploration_tpu.parallel import sweep

    axes = dict(fast=np.asarray([3.0, 5.0, 7.0], np.float32),
                slow=np.asarray([10.0, 20.0], np.float32))
    a = aggregate._np_product_grid(axes)
    b = sweep.product_grid(**axes)
    for k in axes:
        np.testing.assert_array_equal(a[k], np.asarray(b[k]), err_msg=k)


def test_aggregate_cli(tmp_path, capsys):
    journal_path, results_dir, recs = _completed_run(tmp_path, n_jobs=2)
    aggregate.main(["--results-dir", results_dir, "--journal", journal_path,
                    "--metric", "sharpe", "--top", "1"])
    out = json.loads(capsys.readouterr().out)
    assert out["jobs_aggregated"] == 2 and len(out["best"]) == 1
    assert set(out["best"][0]["params"]) == {"fast", "slow"}


def test_aggregate_walkforward_blocks(tmp_path):
    """A walk-forward job's stored block is one stitched OOS row: the
    aggregator must report its value without fabricating 'best params'
    (each refit window chose its own)."""
    journal_path = str(tmp_path / "journal.jsonl")
    results_dir = str(tmp_path / "results")
    queue = JobQueue(Journal(journal_path))
    grid = parse_grid("fast=3:5,slow=10:14:2")
    recs = synthetic_jobs(2, 200, "sma_crossover", grid, cost=1e-3, seed=5,
                          wf_train=80, wf_test=30, wf_metric="sharpe")
    for rec in recs:
        queue.enqueue(rec)
    disp = Dispatcher(queue, results_dir=results_dir)
    queue.take(2, "w1")
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        grid=wire.grid_to_proto(r.grid), cost=r.cost,
                        wf_train=r.wf_train, wf_test=r.wf_test,
                        wf_metric=r.wf_metric) for r in recs]
    for c in compute.JaxSweepBackend(use_fused=False).process(specs):
        disp._complete_one(c.job_id, "w1", c.metrics, c.elapsed_s)

    out = aggregate.aggregate(results_dir, journal_path, metric="sharpe")
    assert out["jobs_aggregated"] == 2
    for row in out["best"]:
        assert row["mode"] == "walkforward_oos"
        assert row["params"] == {}
        assert np.isfinite(row["value"])


def test_aggregate_reads_topk_blocks(tmp_path):
    """DBXS blocks aggregate like full matrices: the stored indices map
    back to the canonical grid, mode says the block was pre-reduced."""
    journal_path = str(tmp_path / "journal.jsonl")
    results_dir = str(tmp_path / "results")
    queue = JobQueue(Journal(journal_path))
    grid = parse_grid("fast=3:5,slow=10:14:2")
    k = 3
    recs = synthetic_jobs(3, 96, "sma_crossover", grid, cost=1e-3, seed=3,
                          top_k=k, rank_metric="sharpe")
    for rec in recs:
        queue.enqueue(rec)
    disp = Dispatcher(queue, results_dir=results_dir)
    queue.take(len(recs), "w1")
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        grid=wire.grid_to_proto(r.grid), cost=r.cost,
                        periods_per_year=252, top_k=r.top_k,
                        rank_metric=r.rank_metric) for r in recs]
    full_specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                             grid=wire.grid_to_proto(r.grid), cost=r.cost,
                             periods_per_year=252) for r in recs]
    backend = compute.JaxSweepBackend()
    for c in backend.process(specs):
        disp._complete_one(c.job_id, "w1", c.metrics, c.elapsed_s)
    full = {c.job_id: wire.metrics_from_bytes(c.metrics)
            for c in compute.JaxSweepBackend().process(full_specs)}

    out = aggregate.aggregate(results_dir, journal_path, metric="sharpe",
                              top=10)
    assert out["jobs_aggregated"] == len(recs)
    by_job = {r["job"]: r for r in out["best"]}
    import numpy as np

    from distributed_backtesting_exploration_tpu.parallel import sweep
    canonical = sweep.product_grid(
        **{kk: np.asarray(v, np.float32)
           for kk, v in sorted(recs[0].grid.items())})
    for rec in recs:
        row = by_job[rec.id]
        assert row["mode"] == "sweep_topk"
        sharpe = np.asarray(full[rec.id].sharpe)
        best = int(np.argmax(sharpe))
        assert row["value"] == float(sharpe[best])
        # Params resolve through the stored grid indices, not row position.
        for name, vals in canonical.items():
            assert row["params"][name] == float(np.asarray(vals)[best])


def test_aggregate_warns_on_rank_metric_mismatch(tmp_path, caplog):
    """Re-ranking a top-k block by a DIFFERENT metric is lossy (only the k
    best-by-block-metric rows survived) — aggregate must say so."""
    import logging

    journal_path = str(tmp_path / "journal.jsonl")
    results_dir = str(tmp_path / "results")
    queue = JobQueue(Journal(journal_path))
    recs = synthetic_jobs(1, 96, "sma_crossover",
                          parse_grid("fast=3:5,slow=10:14:2"), cost=1e-3,
                          top_k=2, rank_metric="sharpe")
    for rec in recs:
        queue.enqueue(rec)
    disp = Dispatcher(queue, results_dir=results_dir)
    queue.take(1, "w1")
    spec = pb.JobSpec(id=recs[0].id, strategy=recs[0].strategy,
                      ohlcv=recs[0].ohlcv,
                      grid=wire.grid_to_proto(recs[0].grid),
                      cost=recs[0].cost, periods_per_year=252,
                      top_k=2, rank_metric="sharpe")
    for c in compute.JaxSweepBackend().process([spec]):
        disp._complete_one(c.job_id, "w1", c.metrics, c.elapsed_s)

    with caplog.at_level(logging.WARNING, logger="dbx.aggregate"):
        out = aggregate.aggregate(results_dir, journal_path,
                                  metric="total_return", top=3)
    assert out["jobs_aggregated"] == 1
    assert any("retained top-k rows only" in r.message for r in caplog.records)

    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="dbx.aggregate"):
        aggregate.aggregate(results_dir, journal_path, metric="sharpe")
    assert not [r for r in caplog.records
                if "retained top-k" in r.message]   # same metric: no warning


def test_aggregate_nan_cells_rank_last(tmp_path):
    """ADVICE r3: np.argmax(sign * values) ranks NaN FIRST (NaN wins numpy
    comparisons) — a block with NaN cells must not report a NaN row as the
    job's best while finite rows exist, and an all-NaN job must sort below
    every finite job fleet-wide."""
    journal_path = str(tmp_path / "journal.jsonl")
    results_dir = str(tmp_path / "results")
    queue = JobQueue(Journal(journal_path))
    grid = parse_grid("fast=3:6,slow=10:16:2")   # 3x3 = 9 combos
    recs = synthetic_jobs(2, 96, "sma_crossover", grid, cost=1e-3, seed=3)
    for rec in recs:
        queue.enqueue(rec)
    import os
    os.makedirs(results_dir, exist_ok=True)

    def block(sharpe_row):
        fields = {name: np.full(9, 0.1, np.float32)
                  for name in aggregate.Metrics._fields}
        fields["sharpe"] = np.asarray(sharpe_row, np.float32)
        return wire.metrics_to_bytes(aggregate.Metrics(**fields))

    # Job 0: NaN at the position argmax-without-masking would pick.
    row0 = np.full(9, 0.5, np.float32)
    row0[1] = np.nan
    row0[4] = 2.0            # the true (finite) best
    with open(f"{results_dir}/{recs[0].id}.dbxm", "wb") as fh:
        fh.write(block(row0))
    # Job 1: every cell NaN (e.g. zero-variance returns everywhere).
    with open(f"{results_dir}/{recs[1].id}.dbxm", "wb") as fh:
        fh.write(block(np.full(9, np.nan, np.float32)))

    out = aggregate.aggregate(results_dir, journal_path, metric="sharpe",
                              top=10)
    assert out["jobs_aggregated"] == 2
    assert out["best"][0]["job"] == recs[0].id
    assert out["best"][0]["value"] == 2.0          # finite best, not NaN
    assert np.isnan(out["best"][1]["value"])       # all-NaN job sorts last

    # Same discipline on a DBXS (top-k) block where < k rows are finite.
    idx = np.asarray([4, 1, 3], np.int32)          # row 1 carries NaN
    sel = {name: np.float32([1.0, np.nan, 0.2])
           for name in aggregate.Metrics._fields}
    sel["sharpe"] = np.float32([2.0, np.nan, 0.2])
    blob = wire.topk_to_bytes(idx, aggregate.Metrics(**sel), "sharpe")
    with open(f"{results_dir}/{recs[1].id}.dbxm", "wb") as fh:
        fh.write(blob)
    out2 = aggregate.aggregate(results_dir, journal_path, metric="sharpe",
                               top=10)
    row = next(r for r in out2["best"] if r["job"] == recs[1].id)
    assert row["value"] == 2.0                     # not the NaN row


def test_aggregate_cli_emits_valid_json_for_all_nan_job(tmp_path, capsys):
    """The CLI must serialize an all-NaN job's value as null, not the
    non-standard `NaN` token that breaks strict JSON parsers."""
    journal_path = str(tmp_path / "journal.jsonl")
    results_dir = str(tmp_path / "results")
    queue = JobQueue(Journal(journal_path))
    recs = synthetic_jobs(1, 96, "sma_crossover", parse_grid("fast=3,slow=8"),
                          seed=3)
    for rec in recs:
        queue.enqueue(rec)
    import os
    os.makedirs(results_dir, exist_ok=True)
    fields = {name: np.float32([np.nan])
              for name in aggregate.Metrics._fields}
    with open(f"{results_dir}/{recs[0].id}.dbxm", "wb") as fh:
        fh.write(wire.metrics_to_bytes(aggregate.Metrics(**fields)))
    aggregate.main(["--results-dir", results_dir, "--journal", journal_path])
    out = json.loads(capsys.readouterr().out)   # strict parse must succeed
    assert out["best"][0]["value"] is None


def _best_returns_run(tmp_path, n_jobs=4, n_bars=96, weights="equal"):
    """Fleet run in --best-returns mode: DBXP blocks land in results_dir."""
    journal_path = str(tmp_path / "journal.jsonl")
    results_dir = str(tmp_path / "results")
    queue = JobQueue(Journal(journal_path))
    grid = parse_grid("fast=3:5,slow=10:14:2")
    recs = synthetic_jobs(n_jobs, n_bars, "sma_crossover", grid, cost=1e-3,
                          seed=5, best_returns=True, rank_metric="sharpe")
    for rec in recs:
        queue.enqueue(rec)
    disp = Dispatcher(queue, results_dir=results_dir)
    queue.take(n_jobs, "w1")
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        grid=wire.grid_to_proto(r.grid), cost=r.cost,
                        periods_per_year=252, best_returns=True,
                        rank_metric="sharpe") for r in recs]
    backend = compute.JaxSweepBackend(use_fused=False)
    for c in backend.process(specs):
        disp._complete_one(c.job_id, "w1", c.metrics, c.elapsed_s)
    return journal_path, results_dir, recs


def test_best_returns_blocks_match_direct_composition(tmp_path):
    """The DBXP flow end to end: worker-shipped best-return series, composed
    by aggregate.portfolio(), must equal the direct library composition
    (sweep -> per-ticker best -> weighted book) on the same panels."""
    import jax.numpy as jnp

    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.ops import (
        metrics as metrics_mod, pnl)
    from distributed_backtesting_exploration_tpu.parallel import (
        portfolio as portfolio_mod, sweep)
    from distributed_backtesting_exploration_tpu.utils import data

    journal_path, results_dir, recs = _best_returns_run(tmp_path)
    out = aggregate.portfolio(results_dir, journal_path, weights="equal")
    assert out["legs_composed"] == len(recs)

    # Direct composition: stack the jobs' tickers into one panel.
    series = [data.from_wire_bytes(r.ohlcv) for r in recs]
    panel = type(series[0])(*(jnp.stack([np.asarray(getattr(s, f))
                                         for s in series])
                              for f in series[0]._fields))
    canonical = sweep.product_grid(**dict(sorted(recs[0].grid.items())))
    pm, chosen = portfolio_mod.sweep_and_compose(
        panel, base.get_strategy("sma_crossover"), canonical, cost=1e-3)
    # Portfolio sharpe from the composed book matches the DBXP composition.
    assert out["portfolio"]["sharpe"] == pytest.approx(
        float(pm.sharpe), rel=2e-4, abs=2e-5)
    # Per-leg params match the per-ticker winners.
    by_job = {leg["job"]: leg for leg in out["legs"]}
    for i, rec in enumerate(recs):
        for k in canonical:
            assert by_job[rec.id]["params"][k] == float(chosen[k][i])


def test_portfolio_inverse_vol_and_ranking_path(tmp_path):
    journal_path, results_dir, recs = _best_returns_run(tmp_path)
    out = aggregate.portfolio(results_dir, journal_path,
                              weights="inverse_vol")
    ws = [leg["weight"] for leg in out["legs"]]
    assert pytest.approx(sum(abs(w) for w in ws), abs=1e-6) == 1.0
    assert all(w > 0 for w in ws)
    assert np.isfinite(out["portfolio"]["sharpe"])
    if out["avg_pairwise_correlation"] is not None:
        assert -1.0 <= out["avg_pairwise_correlation"] <= 1.0
    # The plain ranking path reads DBXP blocks too (one row per job).
    ranked = aggregate.aggregate(results_dir, journal_path, metric="sharpe")
    assert ranked["jobs_aggregated"] == len(recs)
    assert all(r["mode"] == "sweep_best_returns" for r in ranked["best"])
    assert all(r["params"] for r in ranked["best"])


def test_portfolio_min_variance_matches_reference(tmp_path):
    """min_variance weights equal the closed-form shrunk Σ⁻¹1 solution
    computed independently, and the resulting book has variance <= the
    equal-weight book's (the property the scheme optimizes, up to the
    unit-gross renormalization and shrinkage)."""
    journal_path, results_dir, recs = _best_returns_run(tmp_path)
    out = aggregate.portfolio(results_dir, journal_path,
                              weights="min_variance")
    ws = {leg["job"]: leg["weight"] for leg in out["legs"]}
    assert pytest.approx(sum(abs(w) for w in ws.values()), abs=1e-6) == 1.0

    # Independent reference from the stored DBXP series themselves.
    from distributed_backtesting_exploration_tpu.rpc.journal import Journal
    state = Journal.replay(journal_path)
    series = {}
    for jid in state.jobs:
        with open(os.path.join(results_dir, f"{jid}.dbxm"), "rb") as fh:
            _, _, ret, _ = wire.best_returns_from_bytes(fh.read())
        series[jid] = np.asarray(ret, np.float64)
    jids = sorted(series)
    R = np.stack([series[j] for j in jids])
    cov = np.cov(R)
    cov_s = 0.9 * cov + 0.1 * np.diag(np.diag(cov))
    ref = np.linalg.solve(cov_s, np.ones(R.shape[0]))
    ref = ref / np.abs(ref).sum()
    for j, r in zip(jids, ref):
        assert ws[j] == pytest.approx(float(r), rel=1e-6, abs=1e-9)
    # Variance property vs the equal book (same unit-gross normalization;
    # compare books scaled to equal NET exposure so the comparison is the
    # optimizer's own objective).
    w_mv = np.array([ws[j] for j in jids])
    w_eq = np.ones(len(jids)) / len(jids)
    var = lambda w: float((w / w.sum()) @ cov @ (w / w.sum()))  # noqa: E731
    assert var(w_mv) <= var(w_eq) + 1e-12


def test_portfolio_min_variance_dead_and_duplicate_legs():
    """Unit gates of the weight solver itself: dead legs get zero weight,
    near-duplicate legs survive via shrinkage (no wild ±blowup), and
    fewer than two live legs degrade to the inverse-vol fallbacks."""
    rng = np.random.default_rng(11)
    a = rng.normal(0, 0.01, 200)
    b = rng.normal(0, 0.02, 200)
    R = np.stack([a, b, np.zeros(200)])
    live = R.std(axis=-1) > 0
    w = aggregate._min_variance_weights(R, live)
    assert w[2] == 0.0 and (w[:2] != 0).all()
    # Near-duplicate legs: shrinkage keeps the solve bounded.
    R2 = np.stack([a, a + rng.normal(0, 1e-6, 200)])
    w2 = aggregate._min_variance_weights(R2, R2.std(axis=-1) > 0)
    assert np.all(np.isfinite(w2))
    assert np.abs(w2 / max(np.abs(w2).sum(), 1e-12)).max() <= 1.0
    # One live leg -> inverse-vol shape; none -> equal.
    w1 = aggregate._min_variance_weights(R[1:], live[1:] * [True, False])
    assert w1[1] == 0.0 and w1[0] > 0
    w0 = aggregate._min_variance_weights(np.zeros((2, 50)),
                                         np.array([False, False]))
    assert np.allclose(w0, 1.0)


def test_np_portfolio_metrics_matches_jax():
    """The aggregate-side NumPy metrics twin must match ops.metrics on the
    returns/equity subset (same population moments, additive equity,
    peak-relative drawdown)."""
    import jax.numpy as jnp

    from distributed_backtesting_exploration_tpu.ops import metrics as mm

    rng = np.random.default_rng(7)
    r = rng.normal(0.0005, 0.01, 512).astype(np.float32)
    got = aggregate._np_portfolio_metrics(r, 252)
    rj = jnp.asarray(r)
    eq = 1.0 + jnp.cumsum(rj)
    want = {
        "sharpe": float(mm.sharpe(rj)),
        "sortino": float(mm.sortino(rj)),
        "max_drawdown": float(mm.max_drawdown(eq)),
        "total_return": float(mm.total_return(eq)),
        "cagr": float(mm.cagr(eq)),
        "volatility": float(np.std(r) * np.sqrt(252.0)),
    }
    for k, v in want.items():
        assert got[k] == pytest.approx(v, rel=2e-4, abs=1e-6), k


def test_portfolio_requires_dbxp_blocks(tmp_path):
    journal_path, results_dir, _ = _completed_run(tmp_path)   # plain DBXM
    with pytest.raises(ValueError, match="best-returns"):
        aggregate.portfolio(results_dir, journal_path)


def test_portfolio_counts_and_warns_on_non_dbxp_blocks(tmp_path, caplog):
    """VERDICT r4 weak #2: a mixed fleet where some worker completed a
    --best-returns job as the wrong kind must not compose a book that is
    quietly missing legs — the skip must be counted and loudly named."""
    import logging

    journal_path, results_dir, recs = _best_returns_run(tmp_path, n_jobs=3)
    # Simulate a wrong-kind completion: overwrite one job's DBXP block with
    # a plain DBXM matrix (what a pre-triage slice worker would store).
    fields = {name: np.full(9, 0.1, np.float32)
              for name in aggregate.Metrics._fields}
    with open(f"{results_dir}/{recs[0].id}.dbxm", "wb") as fh:
        fh.write(wire.metrics_to_bytes(aggregate.Metrics(**fields)))
    with caplog.at_level(logging.WARNING, logger="dbx.aggregate"):
        out = aggregate.portfolio(results_dir, journal_path)
    assert out["legs_composed"] == 2
    assert out["blocks_skipped"] == 1
    warn = [r for r in caplog.records if "missing these jobs" in r.message]
    assert warn and recs[0].id in warn[0].message


def test_portfolio_counts_completed_jobs_with_missing_blocks(tmp_path,
                                                             caplog):
    """A job the journal says COMPLETED whose block file vanished is a
    missing leg too — same loud accounting as a wrong-kind block."""
    import logging
    import os

    journal_path, results_dir, recs = _best_returns_run(tmp_path, n_jobs=3)
    os.remove(f"{results_dir}/{recs[0].id}.dbxm")
    with caplog.at_level(logging.WARNING, logger="dbx.aggregate"):
        out = aggregate.portfolio(results_dir, journal_path)
    assert out["legs_composed"] == 2
    assert out["blocks_skipped"] == 1
    warn = [r for r in caplog.records if "no "
            "stored block" in r.message]
    assert warn and recs[0].id in warn[0].message


def test_portfolio_sanitizes_nonfinite_leg_values(tmp_path):
    """ADVICE r4: a NaN rank-metric value must be nulled BEFORE the sort
    (NaN is truthy, so `-(value or 0.0)` is NaN and ordering goes
    nondeterministic) — and library callers must see the sanitized dict."""
    journal_path, results_dir, recs = _best_returns_run(tmp_path, n_jobs=3)
    jid = recs[0].id
    with open(f"{results_dir}/{jid}.dbxm", "rb") as fh:
        gi, row, ret, metric = wire.best_returns_from_bytes(fh.read())
    nan_row = aggregate.Metrics(*(np.float32(np.nan) for _ in row))
    with open(f"{results_dir}/{jid}.dbxm", "wb") as fh:
        fh.write(wire.best_returns_to_bytes(gi, nan_row, ret, metric))
    out = aggregate.portfolio(results_dir, journal_path)
    by_job = {leg["job"]: leg for leg in out["legs"]}
    assert by_job[jid]["value"] is None          # sanitized, not NaN
    assert out["legs"][-1]["job"] == jid         # None ranks last


def test_slice_worker_triages_best_returns_jobs():
    """VERDICT r4 weak #2 (write side): the slice worker must refuse
    best_returns jobs loudly instead of running them as plain sweeps and
    completing wrong-kind DBXM blocks."""
    from distributed_backtesting_exploration_tpu.rpc.slice_worker import (
        SliceWorker)
    from distributed_backtesting_exploration_tpu.utils import data

    rng = np.random.default_rng(3)
    close = np.cumsum(rng.normal(0, 1, 64)).astype(np.float32) + 100
    ohlcv = data.to_wire_bytes(data.OHLCV(
        open=close, high=close, low=close, close=close,
        volume=np.ones_like(close)))
    grid = wire.grid_to_proto(parse_grid("fast=3,slow=8"))
    jobs = [
        pb.JobSpec(id="j-dbxp", strategy="sma_crossover", ohlcv=ohlcv,
                   grid=grid, best_returns=True, rank_metric="sharpe"),
        pb.JobSpec(id="j-plain", strategy="sma_crossover", ohlcv=ohlcv,
                   grid=grid),
    ]
    # _group_jobs is self-independent (pure triage + decode); bypass the
    # mesh-building __init__ so this runs as a unit test.
    w = object.__new__(SliceWorker)
    groups, decoded, bad = w._group_jobs(jobs)
    assert [j.id for j in bad] == ["j-dbxp"]
    assert sum(len(g) for g in groups.values()) == 1
    assert "j-plain" in decoded


def test_portfolio_inverse_vol_excludes_dead_legs(tmp_path):
    """A never-traded leg (flat return series) must get weight 0 under
    inverse_vol — not 1/eps, which would collapse the book to zero."""
    journal_path, results_dir, recs = _best_returns_run(tmp_path, n_jobs=3)
    jid = recs[0].id
    with open(f"{results_dir}/{jid}.dbxm", "rb") as fh:
        gi, row, ret, metric = wire.best_returns_from_bytes(fh.read())
    with open(f"{results_dir}/{jid}.dbxm", "wb") as fh:
        fh.write(wire.best_returns_to_bytes(
            gi, row, np.zeros_like(ret), metric))
    out = aggregate.portfolio(results_dir, journal_path,
                              weights="inverse_vol")
    w_by_job = {leg["job"]: leg["weight"] for leg in out["legs"]}
    assert w_by_job[jid] == 0.0
    assert sum(w_by_job.values()) == pytest.approx(1.0, abs=1e-6)
    assert np.isfinite(out["portfolio"]["sharpe"])
