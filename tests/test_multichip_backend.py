"""Multi-chip worker backend: job groups sharded over the local chip mesh.

A worker advertising N chips must actually use them: ``JaxSweepBackend``
with ``use_mesh=True`` shards every job group's ticker axis over a 1-D mesh
of the local devices (8 virtual CPU devices here — SURVEY.md §4's strategy)
and must produce the same DBXM payloads as the single-device backend for
every routing path: fused uniform, fused ragged, generic, pairs fused, and
pairs generic.
"""

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.rpc import (
    backtesting_pb2 as pb, compute, wire)
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    synthetic_jobs)


def _specs(recs):
    return [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                       ohlcv2=r.ohlcv2 or b"", grid=wire.grid_to_proto(r.grid),
                       cost=r.cost) for r in recs]


def _assert_same_payloads(got_a, got_b, *, rtol=2e-4, atol=2e-5):
    assert set(got_a) == set(got_b)
    for jid in got_a:
        ma = wire.metrics_from_bytes(got_a[jid])
        mb = wire.metrics_from_bytes(got_b[jid])
        for name in ma._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(ma, name)), np.asarray(getattr(mb, name)),
                rtol=rtol, atol=atol, err_msg=f"{jid}/{name}")


def _run(backend, specs):
    return {c.job_id: c.metrics for c in backend.process(specs)}


@pytest.fixture(scope="module")
def mesh_backends(devices):
    """(mesh, single-device) backend pairs for the fused and generic paths."""
    return {
        "fused_mesh": compute.JaxSweepBackend(use_fused=True, use_mesh=True),
        "fused_one": compute.JaxSweepBackend(use_fused=True, use_mesh=False),
        "generic_mesh": compute.JaxSweepBackend(use_fused=False,
                                                use_mesh=True),
        "generic_one": compute.JaxSweepBackend(use_fused=False,
                                               use_mesh=False),
    }


def test_mesh_backend_builds_mesh(mesh_backends):
    b = mesh_backends["fused_mesh"]
    assert b._mesh is not None and b._mesh.devices.size >= 8
    assert b.chips >= 8
    assert mesh_backends["fused_one"]._mesh is None


def test_mesh_fused_group_matches_single_device(mesh_backends):
    # 11 jobs over 8 shards: uneven split, last block padded by repetition.
    grid = {"fast": np.float32([3, 5]), "slow": np.float32([13, 21])}
    specs = _specs(synthetic_jobs(11, 160, "sma_crossover", grid,
                                  cost=1e-3, seed=3))
    _assert_same_payloads(_run(mesh_backends["fused_mesh"], specs),
                          _run(mesh_backends["fused_one"], specs))


def test_mesh_fused_multifield_group(mesh_backends):
    grid = {"window": np.float32([8, 16]), "k": np.float32([1.0, 2.0])}
    specs = _specs(synthetic_jobs(5, 160, "vwap_reversion", grid,
                                  cost=1e-3, seed=5))
    _assert_same_payloads(_run(mesh_backends["fused_mesh"], specs),
                          _run(mesh_backends["fused_one"], specs))


def test_mesh_fused_ragged_group(mesh_backends):
    # Mixed history lengths keep the fused path (per-ticker t_real) and the
    # ragged lengths column must shard with its rows.
    grid = {"fast": np.float32([3, 5]), "slow": np.float32([13.0])}
    recs = []
    for i, bars in enumerate([150, 200, 97, 130, 180, 160, 140, 110, 125]):
        recs += synthetic_jobs(1, bars, "sma_crossover", grid, cost=1e-3,
                               seed=40 + i)
    specs = _specs(recs)
    mesh_out = _run(mesh_backends["fused_mesh"], specs)
    one_out = _run(mesh_backends["fused_one"], specs)
    _assert_same_payloads(mesh_out, one_out)


def test_mesh_generic_group_matches_single_device(mesh_backends):
    # momentum with a non-integral lookback grid routes generic; the mesh
    # backend must use the library's sharded_sweep and agree.
    grid = {"lookback": np.float32([5.5, 10.25])}
    specs = _specs(synthetic_jobs(9, 160, "momentum", grid, cost=1e-3,
                                  seed=7))
    _assert_same_payloads(_run(mesh_backends["generic_mesh"], specs),
                          _run(mesh_backends["generic_one"], specs))


def test_mesh_pairs_fused_and_generic(mesh_backends):
    grid = {"lookback": np.float32([10, 20]),
            "z_entry": np.float32([1.0, 2.0])}
    specs = _specs(synthetic_jobs(9, 160, "pairs", grid, cost=1e-3, seed=9))
    _assert_same_payloads(_run(mesh_backends["fused_mesh"], specs),
                          _run(mesh_backends["fused_one"], specs))
    _assert_same_payloads(_run(mesh_backends["generic_mesh"], specs),
                          _run(mesh_backends["generic_one"], specs))


def test_mesh_backend_end_to_end_worker(devices):
    """A worker with a mesh backend drains a live dispatcher's queue."""
    import threading
    import time

    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        Dispatcher, DispatcherServer, JobQueue, PeerRegistry)
    from distributed_backtesting_exploration_tpu.rpc.worker import Worker

    q = JobQueue()
    grid = {"fast": np.float32([3, 5]), "slow": np.float32([13.0])}
    for r in synthetic_jobs(10, 120, "sma_crossover", grid, cost=1e-3,
                            seed=11):
        q.enqueue(r)
    disp = Dispatcher(q, PeerRegistry(prune_window_s=30.0))
    srv = DispatcherServer(disp, bind="localhost:0",
                           prune_interval_s=0.5).start()
    w = Worker(f"localhost:{srv.port}",
               backend=compute.JaxSweepBackend(use_fused=True, use_mesh=True),
               poll_interval_s=0.05)
    t = threading.Thread(target=w.run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and not q.drained:
            time.sleep(0.1)
        assert q.drained, f"queue not drained: {q.stats()}"
        assert q.stats()["jobs_completed"] == 10
    finally:
        w.stop()
        t.join(timeout=20)
        srv.stop()


def test_mesh_pad_rows_never_reported_for_bad_pairs_jobs(mesh_backends):
    """A malformed pairs job co-batched with good ones must complete with an
    EMPTY metric blob on the mesh path too — the mesh pads metric rows to a
    chip multiple, and a pad row must never masquerade as its result."""
    from distributed_backtesting_exploration_tpu.utils import data

    grid = {"lookback": np.float32([10.0]), "z_entry": np.float32([1.0])}
    recs = synthetic_jobs(6, 160, "pairs", grid, cost=1e-3, seed=13)
    specs = _specs(recs)
    # Corrupt one job: second leg shorter than the first (validated bad).
    bad = data.synthetic_ohlcv(1, 90, seed=99)
    specs[3].ohlcv2 = data.to_wire_bytes(type(bad)(*(f[0] for f in bad)))
    got = _run(mesh_backends["fused_mesh"], specs)
    assert got[specs[3].id] == b""
    for s in specs:
        if s.id != specs[3].id:
            assert got[s.id] != b""


def test_mesh_generic_param_chunk_composes(devices):
    """param_chunk (the param-axis memory valve) must stay honored under
    the mesh: chunked mesh results equal unchunked single-device results."""
    backend_chunked = compute.JaxSweepBackend(
        use_fused=False, use_mesh=True, param_chunk=2)
    backend_plain = compute.JaxSweepBackend(use_fused=False, use_mesh=False)
    grid = {"lookback": np.float32([5.5, 7.25, 10.5, 12.0])}  # P=4, chunk=2
    specs = _specs(synthetic_jobs(9, 140, "momentum", grid, cost=1e-3,
                                  seed=17))
    _assert_same_payloads(_run(backend_chunked, specs),
                          _run(backend_plain, specs))


def test_mesh_walkforward_group_matches_single_device(mesh_backends):
    """Walk-forward groups shard over the mesh (the per-ticker refit scan
    is row-parallel); the stitched OOS rows must match the single-device
    path, pad rows never reported."""
    grid = {"fast": np.float32([3, 5]), "slow": np.float32([13.0])}
    recs = synthetic_jobs(9, 200, "sma_crossover", grid, cost=1e-3, seed=19,
                          wf_train=80, wf_test=30, wf_metric="sharpe")
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        grid=wire.grid_to_proto(r.grid), cost=r.cost,
                        wf_train=r.wf_train, wf_test=r.wf_test,
                        wf_metric=r.wf_metric) for r in recs]
    _assert_same_payloads(_run(mesh_backends["generic_mesh"], specs),
                          _run(mesh_backends["generic_one"], specs))


def test_meshless_multidevice_backend_advertises_one_chip(devices):
    """A meshless backend computes on one device; advertising all visible
    chips would take dispatcher leases it cannot parallelize."""
    assert compute.JaxSweepBackend(use_mesh=False).chips == 1
    assert compute.JaxSweepBackend(use_mesh=True).chips >= 8


def test_mesh_pairs_walkforward_group_matches_single_device(mesh_backends):
    """Uniform pairs walk-forward groups shard over the mesh like the
    single-asset wf path (per-window refit is row-parallel per pair)."""
    grid = {"lookback": np.float32([8, 12]), "z_entry": np.float32([0.8, 1.5])}
    recs = synthetic_jobs(9, 240, "pairs", grid, cost=1e-3, seed=23,
                          wf_train=120, wf_test=40, wf_metric="sharpe")
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        ohlcv2=r.ohlcv2, grid=wire.grid_to_proto(r.grid),
                        cost=r.cost, wf_train=r.wf_train, wf_test=r.wf_test,
                        wf_metric=r.wf_metric) for r in recs]
    _assert_same_payloads(_run(mesh_backends["generic_mesh"], specs),
                          _run(mesh_backends["generic_one"], specs))
