"""Dispatch decision plane (round 19): WFQ explain determinism, the
shadow placement scorer, and the ``dbxwhy`` CLI.

Tentpole coverage: the pick-time explain record is a pure function of
scheduler logical state (bit-identical across queue substrates, and a
journal-replayed queue reproduces it with virtual time restarting at 0);
the ``DecisionPlane`` scores every dispatch against the live fleet off
the hot path (ring-bounded, kill-switched, calibrated by completions,
firing the flight recorder on sustained regret); and ``dbxwhy`` stitches
the decision chain with the span timeline for an e2e gRPC-dispatched
job — including the second dispatch after a journal-replay restart.
"""

import json
import threading
import time

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu import obs as obs_mod
from distributed_backtesting_exploration_tpu.obs import (
    decisions as dec_mod, events, flight as flight_mod, why)
from distributed_backtesting_exploration_tpu.rpc import compute
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    Dispatcher, DispatcherServer, JobQueue, JobRecord, PeerRegistry,
    parse_grid, synthetic_jobs)
from distributed_backtesting_exploration_tpu.rpc.journal import Journal
from distributed_backtesting_exploration_tpu.rpc.worker import Worker
from distributed_backtesting_exploration_tpu.sched import (
    WfqScheduler, reset_tenant_buckets)


@pytest.fixture(autouse=True)
def _fresh_buckets():
    reset_tenant_buckets()
    yield
    reset_tenant_buckets()


def _grid(combos):
    return {"fast": np.arange(float(combos), dtype=np.float32) + 5.0}


def _mk(tenant, n, combos=2):
    return [JobRecord(id=f"{tenant}-{i}", strategy="sma_crossover",
                      grid=_grid(combos), ohlcv=b"payload", tenant=tenant)
            for i in range(n)]


def _whale_vs_smalls(q):
    """The round-9 adversarial intake: a whale's big-combo sweep enqueued
    ahead of two small tenants."""
    for r in _mk("whale", 6, combos=32):
        q.enqueue(r)
    for r in _mk("small_a", 4, combos=4) + _mk("small_b", 4, combos=4):
        q.enqueue(r)


def _queue(use_native, *args, **kw):
    if use_native:
        from distributed_backtesting_exploration_tpu.runtime import _core
        if not _core.available():
            pytest.skip("native core not available")
    q = JobQueue(*args, use_native=use_native, **kw)
    assert q.substrate == ("native" if use_native else "python")
    return q


# ---------------------------------------------------------------------------
# WFQ explain determinism (satellite: both substrates + journal replay)
# ---------------------------------------------------------------------------

def test_wfq_explain_bit_identical_across_substrates():
    """The explain stream is a pure function of scheduler logical state:
    the SAME pinned whale-vs-smalls intake produces byte-identical
    explain dicts on the python and native queue substrates."""
    streams = []
    for use_native in (False, True):
        q = _queue(use_native)
        _whale_vs_smalls(q)
        exp: dict = {}
        order = [r.id for r, _ in q.take(14, "w1", explain=exp)]
        # take() hands back live PickExplain objects (serialization is
        # deliberately off the take path); compare their JSON forms.
        streams.append((order, {j: exp[j].as_dict() for j in order}))
    (order_py, exp_py), (order_nat, exp_nat) = streams
    assert order_py == order_nat
    assert exp_py == exp_nat
    # And the stream means what the round-9 schedule says: first pick
    # ties at virtual time 0 and falls to arrival order (the whale) —
    # with both small lanes visible as competing heads.
    first = exp_py[order_py[0]]
    assert order_py[0] == "whale-0"
    assert first["vtime"] == 0.0 and first["tag"] == 0.0
    assert set(first["heads"]) == {"whale", "small_a", "small_b"}
    assert first["cost"] == 32.0 and first["vfinish"] == 32.0
    # Every record carries the full field contract.
    for rec in exp_py.values():
        assert {"jid", "tenant", "tag", "vtime", "vfinish", "cost",
                "weight", "over_quota", "demoted", "heads"} <= set(rec)


def test_wfq_explain_journal_replay_restarts_virtual_time_at_zero(
        tmp_path):
    """A journal-restored queue reproduces the original run's explain
    stream exactly: same picks, same tags, virtual time restarting at 0
    (the PR-8 replay semantics — nothing completed pre-crash, so the
    replayed intake IS the original intake)."""
    jpath = str(tmp_path / "journal.jsonl")
    q = JobQueue(Journal(jpath))
    _whale_vs_smalls(q)
    exp1: dict = {}
    order1 = [r.id for r, _ in q.take(14, "w1", explain=exp1)]

    q2 = JobQueue()
    assert q2.restore(jpath) == 14
    exp2: dict = {}
    order2 = [r.id for r, _ in q2.take(14, "w2", explain=exp2)]
    assert order2 == order1
    assert ({j: e.as_dict() for j, e in exp2.items()}
            == {j: e.as_dict() for j, e in exp1.items()})
    assert exp2[order2[0]].as_dict()["vtime"] == 0.0


def test_wfq_explain_quota_demotion_and_work_conservation():
    """The demotion event lands in the explain record of the pick that
    demoted (not the demoted tenant's own later record), and the
    work-conserving over-quota serve is marked ``over_quota``."""
    s = WfqScheduler(weights={}, quotas={"whale": 32.0})
    s.push("w0", "whale", 32.0)
    s.push("w1", "whale", 32.0)
    s.push("s0", "small", 4.0)
    exp: list = []
    assert s.pick(3, explain=exp) == ["w0", "s0", "w1"]
    d0, d1, d2 = (e.as_dict() for e in exp)
    # Pop 1: nobody over quota yet.
    assert not d0["over_quota"] and d0["demoted"] == []
    # Pop 2: the whale's head is at quota — demoted behind the small
    # tenant, recorded on the small tenant's winning pick.
    assert d1["jid"] == "s0" and d1["demoted"] == ["whale"]
    assert not d1["over_quota"]
    assert d1["heads"]["whale"] == 32.0 and d1["heads"]["small"] == 0.0
    # Pop 3: only over-quota work remains — served anyway, marked.
    assert d2["jid"] == "w1" and d2["over_quota"]


def test_wfq_explain_heads_snapshot_is_bounded():
    """Tenant ids are wire-controlled: the competing-heads snapshot in
    the JSON form is clamped at MAX_HEADS with an explicit drop count."""
    s = WfqScheduler(weights={}, quotas={})
    for i in range(12):
        s.push(f"j{i}", f"t{i:02d}", 1.0)
    exp: list = []
    s.pick(1, explain=exp)
    d = exp[0].as_dict()
    assert len(d["heads"]) == 8
    assert d["heads_dropped"] == 4
    assert list(d["heads"]) == sorted(d["heads"])


# ---------------------------------------------------------------------------
# DecisionPlane unit: shadow scoring, bounds, kill switch, regret trigger
# ---------------------------------------------------------------------------

class _FakeFleet:
    def __init__(self, workers):
        self.workers = workers

    def snapshot(self):
        return {"workers": self.workers}


_DIGEST = "ab" * 32


def _raw(jid="j1", worker="slow", route="full", panel_b=200_000_000,
         **over):
    raw = {"jid": jid, "trace_id": jid + "-tr", "worker": worker,
           "tenant": "default", "strategy": "sma_crossover",
           "combos": 4.0, "affinity_skips": 0, "wfq": None,
           "digest": _DIGEST, "panel_b": panel_b, "append_parent": "",
           "base_len": 0, "bars": 512, "t_take": 1.0, "route": route}
    raw.update(over)
    return raw


def _two_worker_fleet():
    """``fast`` holds the panel (top-K sketch hit); ``slow`` does not."""
    return _FakeFleet({
        "fast": {"stale": False, "age_s": 0.25,
                 "caches": {"panel_topk": [{"d": _DIGEST[:12], "b": 1}]}},
        "slow": {"stale": False, "age_s": 0.5, "caches": {}},
    })


def test_shadow_scorer_prices_residency_and_measures_regret():
    plane = dec_mod.DecisionPlane(fleet=_two_worker_fleet(),
                                  registry=obs_mod.Registry())
    try:
        plane.submit([_raw(worker="slow", route="full")])
        assert plane.flush()
        (rec,) = plane.recent()
        shadow = rec["shadow"]
        # Both candidates share the uncalibrated spu and the cold
        # compile, so the ranking is pure residency: ``fast`` skips the
        # 200 MB transfer the actual worker paid.
        assert shadow["candidates"] == 2
        assert shadow["best"] == "fast" and shadow["agree"] is False
        want = 200_000_000 / dec_mod.h2d_rate_bps()
        assert shadow["regret_s"] == pytest.approx(want, rel=1e-6)
        assert shadow["costs"]["slow"]["transfer_s"] > 0.0
        assert shadow["costs"]["fast"]["transfer_s"] == 0.0
        assert shadow["costs"]["fast"]["resident"] is True
        snap = plane.snapshot()
        assert snap["n_scored"] == 1
        assert snap["agreement"]["disagree"] == 1
        assert snap["regret"]["sum_s"] == pytest.approx(want, rel=1e-6)
    finally:
        plane.close()


def test_digest_only_route_trusts_the_dispatchers_residency_check():
    """A digest-only dispatch IS the residency proof for the actual
    worker (the dispatcher verified the cache hold) — no transfer is
    charged even when the telemetry sketch hasn't caught up."""
    plane = dec_mod.DecisionPlane(fleet=_two_worker_fleet(),
                                  registry=obs_mod.Registry())
    try:
        plane.submit([_raw(worker="slow", route="digest_only")])
        assert plane.flush()
        (rec,) = plane.recent()
        assert rec["shadow"]["costs"]["slow"]["resident"] is True
        assert rec["shadow"]["regret_s"] == 0.0
        assert rec["shadow"]["agree"] is True
        assert rec["fleet_age_s"] == 0.5
    finally:
        plane.close()


def test_completion_calibrates_per_worker_spu_and_compile_warmth():
    plane = dec_mod.DecisionPlane(fleet=_two_worker_fleet(),
                                  registry=obs_mod.Registry())
    try:
        plane.submit([_raw(jid="c1", worker="fast", route="digest_only")])
        plane.observe_completion("fast", "c1", elapsed_s=2.0)
        assert plane.flush()
        assert plane.snapshot()["calibrated_workers"] == 1
        # The next decision prices ``fast`` from the measured wall
        # (spu = 2.0s / units) and skips its compile (family now warm).
        plane.submit([_raw(jid="c2", worker="fast", route="digest_only")])
        assert plane.flush()
        rec = plane.recent()[-1]
        costs = rec["shadow"]["costs"]
        assert costs["fast"]["exec_s"] == pytest.approx(2.0, rel=1e-6)
        assert costs["fast"]["compile_s"] == 0.0
        assert costs["slow"]["compile_s"] > 0.0
    finally:
        plane.close()


def test_decision_ring_and_queue_stay_bounded(monkeypatch):
    monkeypatch.setenv("DBX_DECISIONS_RING", "4")
    plane = dec_mod.DecisionPlane(fleet=_two_worker_fleet(),
                                  registry=obs_mod.Registry())
    try:
        for i in range(12):
            plane.submit([_raw(jid=f"r{i}")])
        assert plane.flush()
        tail = plane.recent()
        assert [r["jid"] for r in tail] == ["r8", "r9", "r10", "r11"]
        assert plane.snapshot()["n_scored"] == 12
    finally:
        plane.close()


def test_kill_switch_and_knob_parsing(monkeypatch):
    assert dec_mod.enabled()
    monkeypatch.setenv("DBX_DECISIONS", "0")
    assert not dec_mod.enabled()
    monkeypatch.setenv("DBX_DECISIONS_RING", "not-a-number")
    assert dec_mod.ring_capacity() == 256
    monkeypatch.setenv("DBX_DECISIONS_REGRET_N", "0")
    assert dec_mod.regret_window() == 1


def test_sustained_regret_fires_the_flight_trigger(monkeypatch):
    monkeypatch.setenv("DBX_DECISIONS_REGRET_S", "0.01")
    monkeypatch.setenv("DBX_DECISIONS_REGRET_N", "2")
    fired = []
    monkeypatch.setattr(flight_mod, "trigger",
                        lambda kind, **kw: fired.append((kind, kw)))
    plane = dec_mod.DecisionPlane(fleet=_two_worker_fleet(),
                                  registry=obs_mod.Registry())
    try:
        # Each decision pays ~0.1s of avoidable transfer: the regret
        # EWMA sits past the 10ms bar for 2 consecutive scored
        # decisions -> one trigger (streak resets after firing).
        plane.submit([_raw(jid=f"h{i}", worker="slow") for i in range(2)])
        assert plane.flush()
        assert [k for k, _ in fired] == ["regret"]
        assert fired[0][1]["subject"] == "slow"
        assert fired[0][1]["regret_ewma_s"] > 0.01
    finally:
        plane.close()


def test_scorer_never_fails_a_decision(monkeypatch):
    """Flight-recorder posture: a broken fleet snapshot degrades to a
    candidate-less record, never an exception on (or off) the take
    path."""

    class _Broken:
        def snapshot(self):
            raise RuntimeError("fleet down")

    reg = obs_mod.Registry()
    plane = dec_mod.DecisionPlane(fleet=_Broken(), registry=reg)
    try:
        plane.submit([_raw(worker="")])
        assert plane.flush()
        (rec,) = plane.recent()
        assert rec["shadow"] == {"candidates": 0}
        assert "regret_s" not in rec["shadow"]
    finally:
        plane.close()

# ---------------------------------------------------------------------------
# dbxwhy CLI (satellite: tier-1 smoke — exit codes, formats, merge)
# ---------------------------------------------------------------------------

def _decision_line(jid, worker="w1", t_take=1.0):
    return json.dumps({
        "ev": "decision", "jid": jid, "trace_id": jid + "-tr",
        "worker": worker, "tenant": "default", "route": "full",
        "strategy": "sma_crossover", "combos": 4, "affinity_skips": 0,
        "fleet_age_s": 0.1, "units": 100.0, "t_take": t_take,
        "shadow": {"candidates": 2, "best": "w2", "best_cost_s": 0.1,
                   "actual_cost_s": 0.3, "regret_s": 0.2, "agree": False,
                   "costs": {"w1": {"cost_s": 0.3, "exec_s": 0.1,
                                    "transfer_s": 0.2, "compile_s": 0.0,
                                    "carry_hit": False,
                                    "resident": False},
                             "w2": {"cost_s": 0.1, "exec_s": 0.1,
                                    "transfer_s": 0.0, "compile_s": 0.0,
                                    "carry_hit": False,
                                    "resident": True}}},
        "wfq": {"jid": jid, "tenant": "default", "tag": 0.0, "vtime": 0.0,
                "vfinish": 4.0, "cost": 4.0, "weight": 1.0,
                "over_quota": False, "demoted": [], "heads": {}},
        "placement": {"live": True, "best": "w2", "cost_s": 0.3,
                      "best_cost_s": 0.1, "gap_s": 0.2, "defers": 2,
                      "cap": 2, "outcome": "cap", "table_workers": 2}})


def test_dbxwhy_exit_2_on_no_match_and_no_events(tmp_path, capsys):
    log = tmp_path / "events.jsonl"
    log.write_text("not json\n{\"no\": \"ev key\"}\n")
    assert why.main(["j1", "--jsonl", str(log)]) == 2
    assert "no parseable events" in capsys.readouterr().err
    log.write_text(_decision_line("other-job") + "\n")
    assert why.main(["j1", "--jsonl", str(log)]) == 2
    assert "no decision record matches" in capsys.readouterr().err
    # No inputs at all is an argparse error, not a silent empty report.
    with pytest.raises(SystemExit):
        why.main(["j1"])


def test_dbxwhy_merges_logs_and_orders_the_decision_chain(
        tmp_path, capsys):
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    # The SECOND dispatch (post-restart) lives in another file with an
    # earlier t_take in file order — the chain must sort by take time.
    a.write_text(_decision_line("j1", worker="w9", t_take=7.0) + "\n")
    b.write_text(_decision_line("j1", worker="w1", t_take=1.0) + "\n"
                 + _decision_line("jX", t_take=2.0) + "\n")
    assert why.main(["j1", "--jsonl", str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "decision 1/2" in out and "decision 2/2" in out
    assert out.index("worker w1") < out.index("worker w9")
    assert "shadow preferred w2" in out
    # Round 20: the LIVE placement rank is stitched into the chain —
    # outcome, chosen-vs-best cost gap, deferral budget spent.
    assert "placement: outcome=cap" in out
    assert "best-placed was w2" in out
    assert "defers=2/2" in out
    assert "(no span timeline for this job in the inputs)" in out


def test_dbxwhy_json_format_and_trace_prefix_match(tmp_path, capsys):
    log = tmp_path / "events.jsonl"
    log.write_text(_decision_line("abc123") + "\n")
    assert why.main(["abc123-tr", "--jsonl", str(log),
                     "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["job"] == "abc123-tr"
    assert [d["jid"] for d in doc["decisions"]] == ["abc123"]


# ---------------------------------------------------------------------------
# End-to-end: gRPC dispatch -> decision chain across a journal-replay
# restart (acceptance: dbxwhy reconstructs the full chain)
# ---------------------------------------------------------------------------

GRID = parse_grid("fast=3:5,slow=10:14:2")

_LIVE: list = []


@pytest.fixture(autouse=True)
def _cleanup_e2e():
    yield
    while _LIVE:
        stop = _LIVE.pop()
        stop()
    events.configure(None)


def _server(queue):
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=10.0))
    srv = DispatcherServer(disp, bind="localhost:0").start()
    _LIVE.append(srv.stop)
    return disp, srv


def _drain_with_worker(port, queue, timeout=30.0):
    w = Worker(f"localhost:{port}", compute.InstantBackend(),
               poll_interval_s=0.02, status_interval_s=0.05)
    t = threading.Thread(target=lambda: w.run(max_idle_polls=1000),
                         daemon=True)
    t.start()
    _LIVE.append(lambda: (w.stop(), t.join(timeout=10)))
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if queue.drained:
            # Stop NOW: a worker left polling would steal the jobs the
            # test enqueues next (the leaked-worker flake the rpc
            # integration suite documents).
            w.stop()
            t.join(timeout=10)
            return w
        time.sleep(0.02)
    raise AssertionError("queue never drained")


@pytest.mark.slow
def test_e2e_decision_chain_survives_journal_replay_restart(
        tmp_path, capsys):
    import grpc

    from distributed_backtesting_exploration_tpu.rpc import service

    log = str(tmp_path / "events.jsonl")
    jpath = str(tmp_path / "journal.jsonl")
    events.configure(log)

    # --- life 1: dispatch over real gRPC; one job leases to a worker
    # that dies without completing. ------------------------------------
    queue = JobQueue(Journal(jpath))
    for rec in synthetic_jobs(2, 64, "sma_crossover", GRID, seed=3):
        queue.enqueue(rec)
    disp, srv = _server(queue)
    _drain_with_worker(srv.port, queue)
    jid = "replay-me"
    queue.enqueue(JobRecord(id=jid, strategy="sma_crossover", grid=GRID,
                            ohlcv=b"payload"))
    with grpc.insecure_channel(f"localhost:{srv.port}") as ch:
        reply = service.DispatcherStub(ch).RequestJobs(
            __import__("distributed_backtesting_exploration_tpu.rpc."
                       "backtesting_pb2", fromlist=["JobsRequest"])
            .JobsRequest(worker_id="doomed", chips=1, jobs_per_chip=4,
                         accepts_digest_only=True), timeout=10.0)
    assert [j.id for j in reply.jobs] == [jid]
    assert disp.decisions.flush()
    srv.stop()

    # --- life 2: journal replay re-pends the abandoned lease; a live
    # worker completes it — the job's SECOND decision record. ----------
    q2 = JobQueue(Journal(jpath))
    assert q2.restore(jpath) == 1
    assert q2.stats()["jobs_pending"] == 1
    disp2, srv2 = _server(q2)
    _drain_with_worker(srv2.port, q2)
    assert disp2.decisions.flush()
    live = disp2.decisions.snapshot()
    assert live["n_scored"] == 1 and live["recent"][0]["jid"] == jid
    srv2.stop()

    # --- dbxwhy stitches the whole chain from the shared event log. ---
    assert why.main([jid, "--jsonl", log]) == 0
    out = capsys.readouterr().out
    assert "decision 1/2" in out and "decision 2/2" in out
    assert out.index("worker doomed") < out.index("decision 2/2")
    assert "wfq: tag=" in out
    assert "== what actually happened ==" in out

    # The same chain through the json surface, jids intact.
    assert why.main([jid, "--jsonl", log, "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert [d["jid"] for d in doc["decisions"]] == [jid, jid]
    assert doc["decisions"][0]["worker"] == "doomed"
    assert doc["decisions"][0]["t_take"] <= doc["decisions"][1]["t_take"]
