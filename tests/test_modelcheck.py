"""Unit tests for the dbxmc layers: the schedule combinatorics
(analysis.schedules), the instrumentation seams it drives (virtual lease
clock, lockdep schedule hook, journal crash hook), replayable op
scripts, and the journal compaction edge cases the crash-point forks
lean on (torn tails, mid-compaction crashes, delta/enqueue windows,
scenario-base root protection).

The invariant GATE (500 schedules / 100 crash points per substrate)
lives in test_mc_clean.py; these are the mechanism tests.
"""

import json
import os
import random

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.analysis import (
    lockdep, modelcheck as mc, schedules as scl)
from distributed_backtesting_exploration_tpu.rpc import (
    panel_store as panel_store_mod)
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    JobQueue, JobRecord)
from distributed_backtesting_exploration_tpu.rpc.journal import (
    Journal, JournalCorruptError)


def _grid(n=2):
    return {"p": np.arange(n, dtype=np.float32)}


# ---------------------------------------------------------------------------
# Schedule combinatorics
# ---------------------------------------------------------------------------

def test_canonical_key_merges_commuting_interleavings():
    """Swapping adjacent INDEPENDENT ops (an observer against anything,
    disjoint non-pool ops) does not create a new schedule; swapping
    conflicting ops (two pool ops) does."""
    enq = scl.make_op("client", "enqueue", ids=("a",), combos=(2.0,))
    obs = scl.make_op("maint", "stats")
    assert scl.canonical_key([enq, obs]) == scl.canonical_key([obs, enq])

    take = scl.make_op("workerA", "take", worker="workerA", n=1)
    assert scl.canonical_key([enq, take]) != scl.canonical_key([take, enq])


def test_generate_schedules_distinct_and_deterministic():
    programs = scl.build_programs(12, random.Random(0))
    got = list(scl.generate_schedules(programs, random.Random(1), 50))
    keys = [k for k, _ in got]
    assert len(keys) == len(set(keys)) == 50
    # Same seed -> same schedules, in order (replayability of the sweep).
    again = [k for k, _ in
             scl.generate_schedules(programs, random.Random(1), 50)]
    assert keys == again
    # Every schedule preserves per-thread program order.
    for _key, sched in got[:5]:
        for t, prog in programs.items():
            mine = [op for op in sched if op.thread == t]
            assert mine == prog


def test_enumerate_schedules_exhaustive_twin():
    programs = {
        "client": [scl.make_op("client", "enqueue", ids=("a",),
                               combos=(2.0,))],
        "workerA": [scl.make_op("workerA", "take", worker="workerA", n=1)],
        "maint": [scl.make_op("maint", "stats")],
    }
    got = list(scl.enumerate_schedules(programs, 100))
    keys = [k for k, _ in got]
    assert len(keys) == len(set(keys))
    # enqueue/take conflict (2 orders); stats commutes with everything
    # (1 position class) -> exactly 2 inequivalent interleavings.
    assert len(keys) == 2


def test_op_script_roundtrip_and_unknown_op_rejected():
    op = scl.make_op("client", "enqueue", ids=("a", "b"),
                     combos=(2.0, 3.0), tenant="tenantB")
    assert scl.Op.from_json(op.to_json()) == op
    with pytest.raises(ValueError):
        scl.make_op("client", "enqueue_and_pray", ids=("a",))


# ---------------------------------------------------------------------------
# Instrumentation seams
# ---------------------------------------------------------------------------

def test_virtual_clock_drives_lease_expiry():
    """The JobQueue clock seam: lease deadlines follow the injected
    clock, so the checker expires leases by advancing time, not by
    sleeping past real deadlines."""
    vclock = [0.0]
    q = JobQueue(lease_s=5.0, use_native=False, clock=lambda: vclock[0])
    q.enqueue_many([JobRecord(id="a", strategy="sma_crossover",
                              grid=_grid(), ohlcv=mc._panel_bytes("a"))])
    got = q.take(1, "w")
    assert [rec.id for rec, _ in got] == ["a"]
    assert q.requeue_expired() == []          # deadline at t=5, now t=0
    vclock[0] = 10.0
    assert q.requeue_expired() == ["a"]       # expired under virtual time
    assert q.stats()["jobs_pending"] == 1


def test_lockdep_schedule_hook_sees_acquire_release():
    events = []
    installed = not lockdep.active()
    if installed:
        lockdep.install()
    try:
        lockdep.set_schedule_hook(lambda ph, key: events.append(ph))
        q = JobQueue(use_native=False)   # package lock -> instrumented
        q.stats()                        # one lock round-trip minimum
    finally:
        lockdep.set_schedule_hook(None)
        if installed:
            lockdep.uninstall()
    assert "acquire" in events and "acquired" in events
    assert "release" in events


def test_crash_hook_fires_both_sides_of_append(tmp_path):
    seen = []
    j = Journal(str(tmp_path / "j.jsonl"), fsync=False)
    j.crash_hook = lambda phase, event, rec: seen.append((phase, event))
    q = JobQueue(j, use_native=False)
    q.enqueue_many([JobRecord(id="a", strategy="sma_crossover",
                              grid=_grid(), ohlcv=mc._panel_bytes("a"))])
    assert seen == [("pre", "enqueue"), ("post", "enqueue")]


def test_controlled_scheduler_preempts_and_stays_clean():
    cfg = mc.MCConfig(ops=10, seed=3, schedules=4, depth=3)
    r = mc.explore_substrate(cfg)
    assert r["violations"] == [], r["violations"]
    assert r["schedules"] >= 2
    assert r["preemptions"] > 0


# ---------------------------------------------------------------------------
# Replayable op scripts / CLI
# ---------------------------------------------------------------------------

def test_replay_script_clean_roundtrip(tmp_path):
    cfg = mc.MCConfig(substrate="python")
    ops = [scl.make_op("client", "enqueue", ids=("j0",), combos=(2.0,)),
           scl.make_op("workerA", "take", worker="workerA", n=1),
           scl.make_op("workerA", "complete_taken", worker="workerA")]
    script = mc.script_dump(cfg, ops)
    path = tmp_path / "script.json"
    path.write_text(json.dumps(script))
    res = mc.replay_script(json.loads(path.read_text()))
    assert res["violation"] is None
    assert res["ops"] == 3
    assert mc.main(["--replay", str(path)]) == 0


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert mc.main(["--replay", str(bad)]) == 2
    notscript = tmp_path / "notscript.json"
    notscript.write_text('{"hello": 1}')
    assert mc.main(["--replay", str(notscript)]) == 2
    assert mc.main(["--list-invariants"]) == 0


# ---------------------------------------------------------------------------
# Journal compaction / corruption edge cases (crash-point substrate)
# ---------------------------------------------------------------------------

def _mini_journal(path) -> JobQueue:
    j = Journal(str(path), fsync=False)
    q = JobQueue(j, use_native=False)
    q.enqueue_many([
        JobRecord(id="j0", strategy="sma_crossover", grid=_grid(),
                  ohlcv=mc._panel_bytes("j0")),
        JobRecord(id="j1", strategy="sma_crossover", grid=_grid(),
                  ohlcv=mc._panel_bytes("j1")),
    ])
    got = q.take(1, "w")
    q.complete_batch([rec.id for rec, _ in got], "w")
    return q


def test_crash_between_delta_and_enqueue(tmp_path):
    """append_bars journals the `delta` chain link BEFORE the repricing
    job's enqueue record; a crash in between leaves a delta with no job.
    Recovery must treat it as a harmless chain link: replay clean, the
    extended digest still servable, no phantom job."""
    p = tmp_path / "j.jsonl"
    q = JobQueue(Journal(str(p), fsync=False), use_native=False)
    base = JobRecord(id="j0", strategy="sma_crossover", grid=_grid(),
                     ohlcv=mc._panel_bytes("j0"))
    q.enqueue_many([base])
    rec2, outcome, ndig, _n = q.append_bars(
        base.panel_digest, 0, mc._panel_bytes("d", 3),
        strategy="sma_crossover", grid=_grid())
    assert outcome == "extended" and rec2 is not None

    lines = p.read_text().splitlines()
    assert json.loads(lines[-1])["ev"] == "enqueue"     # the append job
    assert json.loads(lines[-2])["ev"] == "delta"
    crash = tmp_path / "crash.jsonl"
    crash.write_text("\n".join(lines[:-1]) + "\n")      # crash window

    replay = Journal.replay(str(crash))
    assert ndig in replay.deltas
    assert rec2.id not in replay.jobs
    q2 = JobQueue(use_native=False)
    assert q2.restore(str(crash)) == 1                  # j0 only
    blob = q2.payload_for_digest(ndig)
    assert blob is not None
    assert panel_store_mod.panel_digest(blob) == ndig


def test_crash_mid_compaction_leaves_original_intact(tmp_path):
    """A crashed compaction leaves a stale tmp file and an untouched
    original (atomic tmp+rename). A fresh compact must succeed over the
    stale tmp — same pid reuses the name, a foreign pid's tmp is simply
    ignored — and replay semantics must be unchanged."""
    p = tmp_path / "j.jsonl"
    q = _mini_journal(p)
    q._journal.close()
    (tmp_path / f"j.jsonl.compact.{os.getpid()}").write_text("garbage{")
    (tmp_path / "j.jsonl.compact.99999").write_text("garbage{")

    before = Journal.replay(str(p))
    n_before, n_after = Journal.compact(str(p))
    assert n_after <= n_before
    after = Journal.replay(str(p))
    assert set(after.pending) == set(before.pending) == {"j1"}
    assert after.completed == before.completed == {"j0"}
    # The foreign-pid tmp is untouched debris, not a wedge.
    assert (tmp_path / "j.jsonl.compact.99999").exists()
    q2 = JobQueue(use_native=False)
    assert q2.restore(str(p)) == 1


def test_truncated_tail_skipped_interior_counted(tmp_path):
    """Torn FINAL line (crash mid-append): skipped silently — the only
    corruption append+flush can produce. Interior damage: strict replay
    refuses; strict=False counts it and keeps going (never wedge)."""
    p = tmp_path / "j.jsonl"
    q = _mini_journal(p)
    q._journal.close()

    torn = tmp_path / "torn.jsonl"
    torn.write_bytes(p.read_bytes() + b'{"ev": "enqueue", "id": "to')
    replay = Journal.replay(str(torn))
    assert set(replay.jobs) == {"j0", "j1"}
    assert replay.corrupt_lines == 0

    lines = p.read_text().splitlines()
    lines[0] = '{"ev": "enqueue", "id": "j0", CORRUPT'
    hurt = tmp_path / "hurt.jsonl"
    hurt.write_text("\n".join(lines) + "\n")
    with pytest.raises(JournalCorruptError):
        Journal.replay(str(hurt))
    loose = Journal.replay(str(hurt), strict=False)
    assert loose.corrupt_lines == 1
    assert "j1" in loose.jobs


def test_scenario_base_root_survives_compaction(tmp_path):
    """Compaction must keep the inline payload of a COMPLETED job whose
    digest is the base of a pending scenario job (scn root protection);
    the checker's scenario-base-reachability invariant verifies it and
    trips when the root is slimmed."""
    p = tmp_path / "j.jsonl"
    q = JobQueue(Journal(str(p), fsync=False), use_native=False)
    base = JobRecord(id="A", strategy="sma_crossover", grid=_grid(),
                     ohlcv=mc._panel_bytes("A"))
    q.enqueue_many([base])
    q.enqueue_many([JobRecord(id="B", strategy="sma_crossover",
                              grid=_grid(),
                              scenario={"base": base.panel_digest,
                                        "seed": 1})])
    got = q.take(1, "w")
    assert [rec.id for rec, _ in got] == ["A"]
    q.complete_batch(["A"], "w")
    q._journal.close()

    Journal.compact(str(p))
    replay = Journal.replay(str(p))
    assert set(replay.pending) == {"B"}
    mc._check_scenario_roots(replay)          # root kept -> passes

    # Slim the root by hand (the bug the invariant exists to catch).
    lines = [json.loads(ln) for ln in p.read_text().splitlines()]
    for rec in lines:
        if rec.get("ev") == "enqueue" and rec.get("id") == "A":
            rec.pop("ohlcv_b64", None)
    p.write_text("".join(json.dumps(r) + "\n" for r in lines))
    broken = Journal.replay(str(p))
    with pytest.raises(mc._Violation) as ei:
        mc._check_scenario_roots(broken)
    assert ei.value.invariant == "scenario-base-reachability"


@pytest.mark.skipif(
    not __import__(
        "distributed_backtesting_exploration_tpu.runtime._core",
        fromlist=["available"]).available(),
    reason="native core not loadable")
def test_native_step_hook_counts_crossings():
    """The runtime step_hook seam: every batched C-ABI crossing of the
    native state machine fires once, so the checker's native telemetry
    counts real transitions, not Python-side guesses."""
    from distributed_backtesting_exploration_tpu.runtime import _core

    nq = _core.NativeJobQueue()
    steps = []
    nq.step_hook = lambda name, n: steps.append((name, n))
    try:
        nq.enqueue_n(["a", "b"], [1.0, 1.0])
        got = nq.take_begin_n(2)
        nq.take_commit_n(got, "w", 0.0)
        nq.complete_n(got)
        nq.requeue_expired()
    finally:
        nq.step_hook = None
    names = [s[0] for s in steps]
    assert names == ["enqueue_n", "take_begin_n", "take_commit_n",
                     "complete_n", "requeue_expired"]
    # dbxmc's native sweep reports the crossing count.
    r = mc.explore_substrate(mc.MCConfig(ops=10, seed=2, schedules=5,
                                         substrate="native"))
    assert r["native_steps"] > 0
    assert r["violations"] == []
