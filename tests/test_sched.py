"""Multi-tenant WFQ scheduling + digest-seeded scenario synthesis.

Round-9 tentpole coverage: the virtual-time weighted-fair-queueing lane
index over the queue state machine (both substrates), quota demotion
semantics, exact drained/pending accounting with jobs parked in tenant
lanes, the legacy-client compatibility contract (no tenant fields ->
``default`` tenant, single-tenant dispatch order bit-identical to the
pre-tenancy FIFO), mixed-tenant journal replay + compaction, the bounded
tenant-bucket label map, and the scenario generator's reproducibility
contract (same spec -> same bytes -> same content digest, across
dispatcher restarts and store eviction).
"""

import dataclasses

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu import scenarios as scn
from distributed_backtesting_exploration_tpu import obs as obs_mod
from distributed_backtesting_exploration_tpu.rpc import (
    backtesting_pb2 as pb, panel_store)
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    Dispatcher, JobQueue, JobRecord, PeerRegistry, scenario_jobs,
    synthetic_jobs)
from distributed_backtesting_exploration_tpu.rpc.journal import Journal
from distributed_backtesting_exploration_tpu.sched import (
    DEFAULT_TENANT, OVERFLOW_BUCKET, WfqScheduler, parse_tenant_map,
    reset_tenant_buckets, tenant_bucket)
from distributed_backtesting_exploration_tpu.utils import data as data_mod


@pytest.fixture(autouse=True)
def _fresh_buckets():
    """The tenant-bucket map is process-global and sticky; tests that
    assert its contents need a clean slate."""
    reset_tenant_buckets()
    yield
    reset_tenant_buckets()


@pytest.fixture(params=["native", "python"])
def qfactory(request):
    """JobQueue factory over both state-machine substrates — the WFQ lane
    index must behave identically on the native core and the fallback."""
    use_native = request.param == "native"
    if use_native:
        from distributed_backtesting_exploration_tpu.runtime import _core
        if not _core.available():
            pytest.skip("native core not available")

    def make(*args, **kw):
        kw.setdefault("use_native", use_native)
        q = JobQueue(*args, **kw)
        assert q.substrate == request.param
        return q

    return make


def _grid(combos):
    return {"fast": np.arange(float(combos), dtype=np.float32) + 5.0}


def _mk(tenant, n, combos=2, prefix=None):
    prefix = prefix or tenant
    return [JobRecord(id=f"{prefix}-{i}", strategy="sma_crossover",
                      grid=_grid(combos), ohlcv=b"payload", tenant=tenant)
            for i in range(n)]


# ---------------------------------------------------------------------------
# WFQ core
# ---------------------------------------------------------------------------

def test_parse_tenant_map():
    assert parse_tenant_map("whale:4,small:1,*:2") == {
        "whale": 4.0, "small": 1.0, "*": 2.0}
    assert parse_tenant_map("") == {}
    assert parse_tenant_map(None) == {}
    with pytest.raises(ValueError):
        parse_tenant_map("whale")
    with pytest.raises(ValueError):
        parse_tenant_map(":3")


def test_wfq_weighted_interleave():
    s = WfqScheduler(weights={"a": 2.0, "b": 1.0}, quotas={})
    for i in range(30):
        s.push(f"a{i}", "a", 1.0)
    for i in range(30):
        s.push(f"b{i}", "b", 1.0)
    picks = s.pick(30)
    a_served = sum(1 for j in picks if j.startswith("a"))
    b_served = 30 - a_served
    # weight 2:1 in equal-cost jobs -> ~2x the service rate.
    assert abs(a_served - 2 * b_served) <= 2, (a_served, b_served)
    # within each tenant the lane is strictly FIFO.
    assert [j for j in picks if j.startswith("a")] == \
        [f"a{i}" for i in range(a_served)]


def test_wfq_combo_cost_makes_small_jobs_flow_past_a_whale(qfactory):
    """The fairness unit is the COMBO, not the job: a whale's 64-combo
    jobs advance its virtual time 16x faster than a small tenant's
    4-combo jobs, so the small backlog drains ahead even when the whale
    enqueued its whole sweep first."""
    q = qfactory()
    for r in _mk("whale", 10, combos=64):
        q.enqueue(r)
    for r in _mk("small", 16, combos=4):
        q.enqueue(r)
    order = [r.id for r, _ in q.take(26, "w1")]
    # First pick ties at virtual time 0 and falls to arrival order (the
    # whale), then every small job outruns the whale's next finish tag.
    assert order[0] == "whale-0"
    assert order[1:17] == [f"small-{i}" for i in range(16)]
    assert q.stats()["jobs_leased"] == 26


def test_wfq_single_tenant_dispatch_is_bit_identical_fifo(qfactory):
    """Legacy compatibility: with one (default) tenant the WFQ pop IS the
    FIFO — exact order, including mixed combo sizes (cost must not
    reorder within a tenant) and requeue-at-front semantics."""
    q = qfactory(lease_s=60.0)
    recs = [JobRecord(id=f"j{i}", strategy="s", grid=_grid(1 + (i % 5)),
                      ohlcv=b"p") for i in range(40)]
    for r in recs:
        q.enqueue(r)
    assert [r.id for r, _ in q.take(3, "w1")] == ["j0", "j1", "j2"]
    assert sorted(q.requeue_worker("w1")) == ["j0", "j1", "j2"]
    order = [r.id for r, _ in q.take(40, "w2")]
    # Bit-identical to the pre-tenancy state machine, including the
    # requeue path: requeue appendlefts the held ids in order, so the
    # LAST one pops first — [j2, j1, j0], then the untouched tail.
    assert order == ["j2", "j1", "j0"] + [f"j{i}" for i in range(3, 40)]
    assert q.stats()["jobs_pending"] == 0


def test_wfq_quota_demotes_pending_never_blocks_the_fleet(qfactory,
                                                          monkeypatch):
    """DBX_TENANT_QUOTA caps a tenant's IN-FLIGHT combos: at quota its
    pending jobs fall behind every other tenant's virtual time, but the
    discipline stays work-conserving (an over-quota tenant alone in the
    queue is still served) and leased jobs are never yanked."""
    monkeypatch.setenv("DBX_TENANT_QUOTA", "whale:8")
    q = qfactory()
    for r in _mk("whale", 5, combos=4):
        q.enqueue(r)
    for r in _mk("small", 5, combos=4):
        q.enqueue(r)
    first = [r.id for r, _ in q.take(4, "w1")]
    # whale leases 2 jobs (8 combos = its quota), interleaved with small.
    assert first == ["whale-0", "small-0", "whale-1", "small-1"]
    ts = q.tenant_stats()
    assert ts["whale"]["inflight_combos"] == 8.0
    # At quota: only small flows... until small runs dry, then the
    # work-conserving override serves the whale anyway.
    more = [r.id for r, _ in q.take(6, "w1")]
    assert more == ["small-2", "small-3", "small-4",
                    "whale-2", "whale-3", "whale-4"]
    assert q.tenant_stats()["whale"]["demoted"] > 0
    # Leases were never yanked: everything taken is still leased.
    assert q.stats()["jobs_leased"] == 10
    # Completing releases the quota charge — and a fully idle tenant's
    # scheduling state is pruned outright (wire-controlled ids must not
    # accumulate), so absence == zero charge.
    q.complete_batch([r for r in first + more], "w1")
    whale = q.tenant_stats().get("whale", {})
    assert whale.get("inflight_combos", 0.0) == 0.0
    assert q.drained


def test_complete_while_parked_keeps_accounting_exact(qfactory):
    """A completion landing on a job still parked in a tenant lane (late
    RPC straddling a restart/requeue) must come out of pending
    immediately — no tombstone leak, no drained flicker."""
    q = qfactory()
    for r in _mk("a", 2) + _mk("b", 1):
        q.enqueue(r)
    assert q.complete("a-0", "w9") == "new"
    s = q.stats()
    assert s["jobs_pending"] == 2 and s["jobs_completed"] == 1
    assert not q.drained
    got = [r.id for r, _ in q.take(5, "w1")]
    assert got == ["a-1", "b-0"], "completed job must not dispatch"
    q.complete_batch(got, "w1")
    assert q.drained
    assert q.stats()["jobs_pending"] == 0


def test_wfq_lease_expiry_requeues_front_and_releases_quota(qfactory):
    q = qfactory(lease_s=0.0)
    for r in _mk("a", 2) + _mk("b", 2):
        q.enqueue(r)
    taken = [r.id for r, _ in q.take(2, "w1")]
    assert q.tenant_stats()["a"]["inflight_combos"] > 0
    assert sorted(q.requeue_expired()) == sorted(taken)
    assert q.tenant_stats()["a"]["inflight_combos"] == 0.0
    # requeued jobs keep their front-of-lane latency class (cross-tenant
    # order between two equal virtual tags is unspecified).
    assert sorted(r.id for r, _ in q.take(4, "w2")[:2]) == sorted(taken)
    assert q.stats()["jobs_requeued"] == 2


# ---------------------------------------------------------------------------
# Journal replay + compaction (satellite: mixed-tenant restart)
# ---------------------------------------------------------------------------

def test_journal_replay_restores_per_tenant_backlogs(tmp_path, qfactory):
    jpath = str(tmp_path / "journal.jsonl")
    q = qfactory(Journal(jpath))
    # Interleaved mixed-tenant intake: whale first (adversarial), then
    # two small tenants; one whale + one small job complete pre-crash.
    for r in _mk("whale", 6, combos=32):
        q.enqueue(r)
    for r in _mk("small_a", 4, combos=4) + _mk("small_b", 4, combos=4):
        q.enqueue(r)
    done = [r.id for r, _ in q.take(2, "w1")]
    assert done == ["whale-0", "small_a-0"]
    q.complete_batch(done, "w1")

    q2 = qfactory()
    assert q2.restore(jpath) == 12
    ts = q2.tenant_stats()
    assert ts["whale"]["pending"] == 5
    assert ts["small_a"]["pending"] == 3
    assert ts["small_b"]["pending"] == 4
    order = [r.id for r, _ in q2.take(12, "w2")]
    # Virtual-time ordering survives the restart: within-tenant order is
    # journal order, and the small tenants are NOT parked behind the
    # whale's earlier-enqueued backlog (combo-weighted interleave).
    assert [j for j in order if j.startswith("whale")] == \
        [f"whale-{i}" for i in range(1, 6)]
    assert [j for j in order if j.startswith("small_a")] == \
        [f"small_a-{i}" for i in range(1, 4)]
    assert [j for j in order if j.startswith("small_b")] == \
        [f"small_b-{i}" for i in range(4)]
    assert set(order[:8]) & {f"small_b-{i}" for i in range(4)}, \
        "small tenant starved behind the whale after replay"
    # duplicate completion across the restart stays idempotent
    assert q2.complete("whale-0", "w1") == "dup"


def test_compaction_keeps_tenant_on_slim_records(tmp_path):
    jpath = str(tmp_path / "journal.jsonl")
    q = JobQueue(Journal(jpath))
    for r in _mk("gold", 1, combos=2):
        q.enqueue(r)
    for r in _mk("", 1, combos=2, prefix="legacy"):
        q.enqueue(r)
    q.take(2, "w1")
    q.complete_batch(["gold-0", "legacy-0"], "w1")
    Journal.compact(jpath)
    state = Journal.replay(jpath)
    slim = state.jobs["gold-0"]
    assert slim.get("tenant") == "gold"
    assert "ohlcv_b64" not in slim, "compaction must still slim payloads"
    # default-tenant records stay slim: no tenant key at all.
    assert "tenant" not in state.jobs["legacy-0"]
    assert JobRecord.from_journal(
        state.jobs["legacy-0"]).tenant == DEFAULT_TENANT


def test_legacy_journal_record_lands_in_default_tenant():
    rec = JobRecord.from_journal(
        {"id": "old", "strategy": "s", "grid": {}, "cost": 0.0})
    assert rec.tenant == DEFAULT_TENANT


# ---------------------------------------------------------------------------
# Legacy-client compatibility over the real wire
# ---------------------------------------------------------------------------

def test_legacy_jobs_request_lands_in_default_tenant_fifo(tmp_path):
    """A JobsRequest with no tenant anywhere (the pre-tenancy client)
    dispatches from the `default` tenant in exact enqueue order, and the
    dispatched specs carry tenant_id="default" for new readers."""
    import grpc

    from distributed_backtesting_exploration_tpu.rpc import service
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        DispatcherServer)

    queue = JobQueue()
    recs = synthetic_jobs(6, 32, "sma_crossover", _grid(3))
    for rec in recs:
        queue.enqueue(rec)
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0),
                      results_dir=str(tmp_path / "results"))
    srv = DispatcherServer(disp, bind="localhost:0",
                           prune_interval_s=5.0).start()
    try:
        channel = grpc.insecure_channel(
            f"localhost:{srv.port}",
            options=service.default_channel_options())
        stub = service.DispatcherStub(channel)
        reply = stub.RequestJobs(pb.JobsRequest(
            worker_id="legacy", chips=1, jobs_per_chip=6))
        assert [j.id for j in reply.jobs] == [r.id for r in recs], \
            "single-tenant dispatch order must be the pre-tenancy FIFO"
        assert all(j.tenant_id == DEFAULT_TENANT for j in reply.jobs)
        assert all(not j.HasField("scenario") for j in reply.jobs)
        crep = stub.CompleteJobs(pb.CompleteBatch(
            worker_id="legacy",
            items=[pb.CompleteItem(id=j.id) for j in reply.jobs]))
        assert crep.accepted == 6
        channel.close()
    finally:
        srv.stop()
    assert queue.drained
    # Only the default tenant ever existed — and once fully idle even
    # its scheduler state is pruned (absence == nothing but default).
    assert set(queue.tenant_stats()) <= {DEFAULT_TENANT}


def test_jobspec_tenant_and_scenario_wire_roundtrip():
    spec = pb.JobSpec(
        id="x", tenant_id="whale",
        scenario=pb.ScenarioSpec(base_digest="ab" * 16, n_bars=128,
                                 block=8, regimes=3, vol_scale=2.0,
                                 shock=0.01, seed=7))
    out = pb.JobSpec()
    out.ParseFromString(spec.SerializeToString())
    assert out.tenant_id == "whale"
    assert out.scenario.base_digest == "ab" * 16
    assert out.scenario.regimes == 3 and out.scenario.seed == 7
    # legacy bytes (no tenant/scenario on the wire) -> proto3 defaults
    legacy = pb.JobSpec()
    legacy.ParseFromString(pb.JobSpec(id="y").SerializeToString())
    assert legacy.tenant_id == "" and not legacy.HasField("scenario")


# ---------------------------------------------------------------------------
# Bounded tenant-bucket label map + per-tenant obs
# ---------------------------------------------------------------------------

def test_tenant_bucket_bounded_and_sticky(monkeypatch):
    monkeypatch.setenv("DBX_TENANT_LABEL_MAX", "3")
    assert tenant_bucket("a") == "a"
    assert tenant_bucket("b") == "b"
    assert tenant_bucket("c") == "c"
    assert tenant_bucket("d") == OVERFLOW_BUCKET
    assert tenant_bucket("e") == OVERFLOW_BUCKET
    # sticky: earlier tenants keep their label, repeats stay stable
    assert tenant_bucket("a") == "a"
    assert tenant_bucket("d") == OVERFLOW_BUCKET
    # "" normalizes to the default tenant and shares its bucket (here
    # the map is already full, so both land in the overflow bucket).
    assert tenant_bucket("") == tenant_bucket(DEFAULT_TENANT)


def test_dispatcher_emits_bucketed_tenant_obs(monkeypatch):
    """Queue-wait histogram + SLO burn counters land under the bounded
    bucket labels on the dispatcher registry (the same registry /metrics,
    /stats.json and GetStats obs_json serve)."""
    monkeypatch.setenv("DBX_TENANT_LABEL_MAX", "2")
    monkeypatch.setenv("DBX_TENANT_SLO_S", "0.0")  # every wait breaches
    reg = obs_mod.Registry()
    queue = JobQueue()
    for r in (_mk("gold", 1) + _mk("silver", 1) + _mk("bronze", 1)):
        queue.enqueue(r)
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0),
                      registry=reg)
    try:
        reply = disp.RequestJobs(pb.JobsRequest(worker_id="w", chips=1,
                                                jobs_per_chip=3), None)
        assert len(reply.jobs) == 3
        summ = reg.summaries(prefix="dbx_tenant")
        # 3 tenants, bucket cap 2: gold + silver keep names, bronze ->
        # "other"; every wait breached the 0-second SLO.
        assert summ["dbx_tenant_queue_wait_seconds{tenant=gold}"][
            "count"] == 1
        assert summ["dbx_tenant_queue_wait_seconds{tenant=silver}"][
            "count"] == 1
        assert summ["dbx_tenant_queue_wait_seconds{tenant=other}"][
            "count"] == 1
        assert summ[
            "dbx_tenant_slo_queue_wait_total{outcome=breach,tenant=gold}"
        ] == 1.0
    finally:
        disp.close()


# ---------------------------------------------------------------------------
# Scenario synthesis
# ---------------------------------------------------------------------------

def _base_blob(n_bars=96, seed=42):
    s = data_mod.synthetic_ohlcv(1, n_bars, seed=seed)
    return data_mod.to_wire_bytes(
        type(s)(*(np.asarray(f[0]) for f in s)))


def test_scenario_seed_is_pure_function_of_spec():
    p = scn.ScenarioParams(n_bars=64, block=8, regimes=2, seed=1)
    assert scn.scenario_seed("d1", p) == scn.scenario_seed(
        "d1", scn.ScenarioParams.from_dict(p.to_dict()))
    assert scn.scenario_seed("d1", p) != scn.scenario_seed("d2", p)
    assert scn.scenario_seed("d1", p) != scn.scenario_seed(
        "d1", dataclasses.replace(p, seed=2))
    # from_dict ignores foreign keys (the record's base digest)
    assert scn.ScenarioParams.from_dict(
        {"base": "xyz", **p.to_dict()}) == p


def test_scenario_bytes_deterministic_and_digest_addressed():
    blob = _base_blob()
    p = scn.ScenarioParams(n_bars=128, block=8, regimes=3,
                           vol_scale=2.0, shock=0.02, seed=0)
    a = scn.scenario_panel_bytes(blob, p)
    b = scn.scenario_panel_bytes(blob, p)
    assert a == b, "same spec must produce byte-identical panels"
    assert panel_store.panel_digest(a) == panel_store.panel_digest(b)
    c = scn.scenario_panel_bytes(blob, dataclasses.replace(p, seed=1))
    assert c != a, "different seeds must diverge"
    series = data_mod.from_wire_bytes(a)
    assert series.n_bars == 128
    o, h, lo, cl, v = (np.asarray(f) for f in series)
    assert np.all(np.isfinite(np.stack([o, h, lo, cl, v])))
    assert np.all(h >= np.maximum(o, cl) - 1e-4)
    assert np.all(lo <= np.minimum(o, cl) + 1e-4)
    assert np.all(lo > 0)


def test_scenario_generate_validation(monkeypatch):
    blob = _base_blob(16)
    base = data_mod.from_wire_bytes(blob)
    with pytest.raises(ValueError, match="single ticker"):
        scn.generate(data_mod.OHLCV(*(np.stack([f, f]) for f in base)),
                     scn.ScenarioParams(), 0)
    monkeypatch.setenv("DBX_SCENARIO_MAX_BARS", "32")
    with pytest.raises(ValueError, match="DBX_SCENARIO_MAX_BARS"):
        scn.generate(base, scn.ScenarioParams(n_bars=64), 0)
    tiny = data_mod.OHLCV(*(np.asarray(f)[:1] for f in base))
    with pytest.raises(ValueError, match=">= 2 bars"):
        scn.generate(tiny, scn.ScenarioParams(), 0)


def test_scenario_jobs_materialize_through_store_and_survive_restart(
        tmp_path, qfactory):
    """The acceptance property: a scenario sweep is bit-reproducible from
    its (base_digest, params) spec — same scenario digest, same panel
    bytes, after a dispatcher restart replays the journal."""
    blob = _base_blob()
    jpath = str(tmp_path / "journal.jsonl")
    q = qfactory(Journal(jpath))
    base_rec = JobRecord(id="base", strategy="sma_crossover",
                         grid=_grid(1), ohlcv=blob)
    q.enqueue(base_rec)
    assert base_rec.panel_digest
    params = {"n_bars": 64, "block": 8, "regimes": 2, "vol_scale": 1.5,
              "shock": 0.0}
    for rec in scenario_jobs(base_rec.panel_digest, 2, "sma_crossover",
                             _grid(4), params=params, tenant="lab"):
        q.enqueue(rec)
    got = {r.id: (r, payload) for r, payload in q.take(3, "w1")}
    assert len(got) == 3
    scn_recs = [r for r, _ in got.values() if r.scenario]
    assert len(scn_recs) == 2
    digests = {r.id: r.panel_digest for r in scn_recs}
    payloads = {r.id: p for r, p in got.values() if r.scenario}
    assert all(digests.values()), "scenario digests stamped at take"
    assert len(set(digests.values())) == 2, "distinct seeds, panels"
    for rid, p in payloads.items():
        assert data_mod.from_wire_bytes(p).n_bars == 64
        assert panel_store.panel_digest(p) == digests[rid]
        assert got[rid][0].tenant == "lab"

    # Restart: journal replay rebuilds the scenario records; the first
    # take re-derives the SAME panels under the SAME addresses.
    q2 = qfactory()
    assert q2.restore(jpath) == 3
    got2 = {r.id: (r, p) for r, p in q2.take(3, "w2")}
    for rid in digests:
        rec2, p2 = got2[rid]
        assert rec2.panel_digest == digests[rid]
        assert p2 == payloads[rid], "bit-reproducible across restart"


def test_scenario_payload_regenerates_after_eviction(qfactory):
    """FetchPayload recovery for scenario panels: an evicted blob
    re-derives from the spec and must verify to the SAME digest."""
    blob = _base_blob()
    q = qfactory()
    base_rec = JobRecord(id="base", strategy="sma_crossover",
                         grid=_grid(1), ohlcv=blob)
    q.enqueue(base_rec)
    rec = scenario_jobs(base_rec.panel_digest, 1, "sma_crossover",
                        _grid(2), params={"n_bars": 48, "block": 8})[0]
    q.enqueue(rec)
    got = q.take(2, "w1")
    srec = next(r for r, _ in got if r.scenario)
    sblob = next(p for r, p in got if r.scenario)
    # Evict EVERYTHING from the store, then recover via the digest.
    q.panel_store.max_bytes = 0
    q.panel_store.put(b"DBX1evict")
    assert q.panel_store.get(srec.panel_digest) is None
    again = q.payload_for_digest(srec.panel_digest)
    assert again == sblob
    q.panel_store.max_bytes = 256 * 1024 * 1024


def test_compaction_keeps_scenario_base_payload(tmp_path):
    """A COMPLETED base job whose digest pending scenario jobs regenerate
    from must keep its inline payload through compaction (the scenario
    twin of the append-chain-root protection) — slimming it would fail
    every pending scenario job at the restarted dispatcher's first
    take."""
    blob = _base_blob()
    jpath = str(tmp_path / "journal.jsonl")
    q = JobQueue(Journal(jpath))
    base_rec = JobRecord(id="base", strategy="sma_crossover",
                         grid=_grid(1), ohlcv=blob)
    q.enqueue(base_rec)
    rec = scenario_jobs(base_rec.panel_digest, 1, "sma_crossover",
                        _grid(2), params={"n_bars": 48, "block": 8})[0]
    q.enqueue(rec)
    got = {r.id: (r, p) for r, p in q.take(2, "w1")}
    scn_digest = got[rec.id][0].panel_digest
    scn_blob = got[rec.id][1]
    q.complete("base", "w1")           # base done; scenario still leased
    Journal.compact(jpath)
    state = Journal.replay(jpath)
    assert "ohlcv_b64" in state.jobs["base"], \
        "scenario base payload must survive compaction"
    # Restart: the pending (lease lost) scenario job re-materializes to
    # the SAME digest and bytes from the compacted journal alone.
    q2 = JobQueue()
    assert q2.restore(jpath) == 1
    (rec2, p2), = q2.take(1, "w2")
    assert rec2.id == rec.id
    assert rec2.panel_digest == scn_digest and p2 == scn_blob


def test_wfq_one_shot_tenants_leave_no_state_behind():
    """Wire-controlled tenant ids must not accumulate scheduler state:
    after N one-shot tenants each push->pick->lease->release, every
    per-tenant map is empty again (lanes prune at the next pick; the
    release of a fully idle tenant drops the rest)."""
    s = WfqScheduler(weights={}, quotas={})
    for i in range(100):
        t = f"oneshot{i}"
        s.push(f"{t}-j", t, 2.0)
        (jid,) = s.pick(1)
        s.on_lease(jid, t, 2.0)
        s.release(jid)
    s.pick(1)   # sweeps the drained lanes
    assert s.pending() == 0
    assert not s._lanes and not s._inflight and not s._charged
    assert not s._finish and not s._npend and not s._demoted
    assert s.tenants() == []


def test_wfq_quota_charge_lands_at_pick_not_commit():
    """Two workers' picks race inside take()'s unlocked materialization
    window: the second pick must already see the first pick's quota
    charge (charging only at lease commit let an at-quota whale take
    one extra batch per concurrent worker)."""
    s = WfqScheduler(weights={"whale": 100.0}, quotas={"whale": 4.0})
    for i in range(4):
        s.push(f"w{i}", "whale", 4.0)
    for i in range(4):
        s.push(f"s{i}", "small", 4.0)
    assert s.pick(1) == ["w0"]        # worker A's pick; NO on_lease yet
    assert s.pick(1) == ["s0"], \
        "worker B's racing pick must see the whale already at quota"
    # releasing A's charge (e.g. its materialization failed) re-admits
    # the whale at the next pick.
    s.release("w0")
    assert s.pick(1) == ["w1"]


def test_scenario_base_missing_fails_the_job_loudly(tmp_path, qfactory):
    jpath = str(tmp_path / "journal.jsonl")
    q = qfactory(Journal(jpath))
    rec = scenario_jobs("0" * 32, 1, "sma_crossover", _grid(2),
                        params={"n_bars": 32})[0]
    q.enqueue(rec)
    assert q.take(1, "w1") == []
    assert q.stats()["jobs_failed"] == 1
    assert Journal.replay(jpath).failed == {rec.id}
    assert q.drained


def test_wfq_rejects_nonpositive_weights():
    """A zero/negative weight must fail construction loudly — silently
    coercing it to the default would schedule the one tenant the
    operator meant to throttle at full rate."""
    with pytest.raises(ValueError, match="weight must be > 0"):
        WfqScheduler(weights={"whale": 0.0}, quotas={})
    with pytest.raises(ValueError, match="weight must be > 0"):
        WfqScheduler(weights={"*": -1.0}, quotas={})


def test_scenario_generation_is_single_flight(monkeypatch):
    """Concurrent materializations of ONE scenario spec run the
    generator once: racers wait on the winner's event and serve the
    memoized digest from the store."""
    import threading
    import time

    import distributed_backtesting_exploration_tpu.scenarios as scn_mod

    blob = _base_blob()
    q = JobQueue()
    base_rec = JobRecord(id="base", strategy="sma_crossover",
                         grid=_grid(1), ohlcv=blob)
    q.enqueue(base_rec)
    spec = {"base": base_rec.panel_digest, "n_bars": 48, "block": 8,
            "regimes": 2, "vol_scale": 2.0, "shock": 0.0, "seed": 3}
    calls = []
    orig = scn_mod.scenario_panel_bytes

    def slow_counting(*a, **kw):
        calls.append(1)
        time.sleep(0.05)      # widen the race window
        return orig(*a, **kw)

    monkeypatch.setattr(scn_mod, "scenario_panel_bytes", slow_counting)
    results = []
    threads = [threading.Thread(
        target=lambda: results.append(q._scenario_payload(dict(spec))))
        for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(results) == 6
    assert len({(d, p) for p, d in results}) == 1, "divergent results"
    assert len(calls) == 1, f"generator ran {len(calls)}x for one spec"
    assert not q._scn_inflight, "in-flight guard must clean up"


def test_scenario_digest_scheme_matches_panel_store():
    """scenarios/synth derives the base digest inline (the dispatcher is
    not importable from the generator layer); pin it to THE digest
    function so the two can never drift."""
    blob = _base_blob(24)
    import hashlib
    assert hashlib.blake2b(blob, digest_size=16).hexdigest() == \
        panel_store.panel_digest(blob)
