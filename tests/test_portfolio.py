"""Portfolio composition layer: weighted aggregation, per-ticker selection,
diversification diagnostics, and the psum-sharded book.

References are deliberately naive NumPy loops; the sharded path must match
the single-device path on the 8-virtual-device CPU mesh.
"""

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from distributed_backtesting_exploration_tpu.models import base
from distributed_backtesting_exploration_tpu.ops import pnl
from distributed_backtesting_exploration_tpu.parallel import portfolio, sweep
from distributed_backtesting_exploration_tpu.utils import data


def _panel(n=4, T=220, seed=0):
    ohlcv = data.synthetic_ohlcv(n, T, seed=seed)
    return type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))


def test_portfolio_returns_match_numpy_weighted_sum():
    panel = _panel(n=3, seed=1)
    strat = base.get_strategy("momentum")
    params = {"lookback": jnp.asarray([5.0, 10.0, 20.0])}
    pos = portfolio.per_ticker_positions(panel, strat, params)
    w = np.asarray([0.5, 0.3, 0.2], np.float32)
    net, equity, expo = portfolio.portfolio_returns(
        panel.close, pos, weights=w, cost=1e-3)

    close = np.asarray(panel.close, np.float64)
    p = np.asarray(pos, np.float64)
    r = np.zeros_like(close)
    r[:, 1:] = close[:, 1:] / close[:, :-1] - 1.0
    prev = np.concatenate([np.zeros((3, 1)), p[:, :-1]], axis=1)
    per = prev * r - 1e-3 * np.abs(p - prev)
    want_net = (w[:, None] * per).sum(axis=0)
    np.testing.assert_allclose(np.asarray(net), want_net,
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(equity),
                               1.0 + np.cumsum(want_net),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(expo),
                               (w[:, None] * p).sum(axis=0),
                               rtol=1e-5, atol=1e-6)


def test_identical_tickers_equal_weight_match_single():
    """A book of N copies of one ticker == that ticker alone."""
    one = _panel(n=1, seed=2)
    four = type(one)(*(jnp.repeat(f, 4, axis=0) for f in one))
    strat = base.get_strategy("momentum")
    p1 = {"lookback": jnp.asarray([10.0])}
    p4 = {"lookback": jnp.full((4,), 10.0)}
    m1 = portfolio.portfolio_backtest(one, strat, p1, cost=1e-3)
    m4 = portfolio.portfolio_backtest(four, strat, p4, cost=1e-3)
    for name in m1._fields:
        np.testing.assert_allclose(np.asarray(getattr(m4, name)),
                                   np.asarray(getattr(m1, name)),
                                   rtol=1e-4, atol=1e-5, err_msg=name)


def test_select_best_params_direction_and_nan():
    vals = jnp.asarray([[0.5, jnp.nan, 2.0],
                        [jnp.nan, jnp.nan, jnp.nan],
                        [3.0, 1.0, -1.0]])
    grid = {"window": jnp.asarray([10.0, 20.0, 30.0])}
    best, chosen = portfolio.select_best_params(vals, grid, metric="sharpe")
    assert np.asarray(chosen["window"]).tolist() == [30.0, 10.0, 10.0]
    assert float(best[0]) == 2.0 and float(best[2]) == 3.0
    # Lower-is-better metric flips the argmax.
    _, chosen_dd = portfolio.select_best_params(
        jnp.asarray([[0.3, 0.1, 0.2]]), grid, metric="max_drawdown")
    assert float(chosen_dd["window"][0]) == 20.0


def test_sweep_and_compose_consistent_with_manual():
    panel = _panel(n=3, seed=3)
    strat = base.get_strategy("sma_crossover")
    grid = sweep.product_grid(fast=jnp.asarray([3.0, 5.0]),
                              slow=jnp.asarray([13.0, 21.0]))
    pm, chosen = portfolio.sweep_and_compose(panel, strat, grid, cost=1e-3)
    m = sweep.jit_sweep(panel, strat, dict(grid), cost=1e-3)
    _, want = portfolio.select_best_params(m.sharpe, grid, metric="sharpe")
    for k in grid:
        np.testing.assert_array_equal(np.asarray(chosen[k]),
                                      np.asarray(want[k]))
    want_pm = portfolio.portfolio_backtest(panel, strat, want, cost=1e-3)
    np.testing.assert_allclose(np.asarray(pm.sharpe),
                               np.asarray(want_pm.sharpe),
                               rtol=1e-5, atol=1e-6)
    assert np.isfinite(float(pm.sharpe))


def test_inverse_vol_weights():
    rng = np.random.default_rng(0)
    calm = 100.0 + np.cumsum(rng.normal(0, 0.1, 300))
    wild = 100.0 + np.cumsum(rng.normal(0, 2.0, 300))
    close = jnp.asarray(np.stack([calm, wild]), jnp.float32)
    w = np.asarray(portfolio.inverse_vol_weights(close))
    assert w.sum() == pytest.approx(1.0, abs=1e-5)
    assert w[0] > w[1]          # calm ticker gets the bigger weight


def test_correlation_matrix_matches_numpy():
    rng = np.random.default_rng(1)
    r = rng.normal(size=(3, 400)).astype(np.float32)
    r[1] = 0.9 * r[0] + 0.1 * r[1]          # correlated pair
    corr = np.asarray(portfolio.correlation_matrix(jnp.asarray(r)))
    want = np.corrcoef(r)
    np.testing.assert_allclose(corr, want, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.diag(corr), 1.0, atol=1e-5)
    avg = float(portfolio.avg_pairwise_correlation(jnp.asarray(corr)))
    n = 3
    want_avg = (want.sum() - np.trace(want)) / (n * (n - 1))
    assert avg == pytest.approx(want_avg, abs=1e-4)


def test_sharded_portfolio_matches_single_device(devices):
    mesh = Mesh(np.asarray(devices[:8]), ("tickers",))
    panel = _panel(n=16, T=256, seed=5)
    strat = base.get_strategy("momentum")
    params = {"lookback": jnp.full((16,), 10.0)}
    pos = portfolio.per_ticker_positions(panel, strat, params)
    w = jnp.linspace(1.0, 2.0, 16)

    net, equity, expo = portfolio.portfolio_returns(
        panel.close, pos, weights=w, cost=1e-3)
    snet, sequity, sexpo = portfolio.sharded_portfolio_returns(
        mesh, panel.close, pos, weights=w, cost=1e-3)
    np.testing.assert_allclose(np.asarray(snet), np.asarray(net),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(sequity), np.asarray(equity),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(sexpo), np.asarray(expo),
                               rtol=1e-5, atol=1e-6)


def test_portfolio_turnover_uses_net_exposure():
    """Long one ticker, short an identical one: net exposure stays ~0, so
    book-level turnover/trades must read ~0 even though each leg trades."""
    one = _panel(n=1, seed=7)
    two = type(one)(*(jnp.repeat(f, 2, axis=0) for f in one))
    strat = base.get_strategy("momentum")
    pos = portfolio.per_ticker_positions(
        two, strat, {"lookback": jnp.full((2,), 10.0)})
    pos = pos * jnp.asarray([[1.0], [-1.0]])
    net, equity, expo = portfolio.portfolio_returns(two.close, pos, cost=0.0)
    np.testing.assert_allclose(np.asarray(expo), 0.0, atol=1e-7)
    np.testing.assert_allclose(np.asarray(net), 0.0, atol=1e-7)


def test_long_short_weights_normalize_by_gross():
    """Dollar-neutral weights must not divide by zero or flip sign: with
    w = [1, -1] on two identical tickers the book is flat (net 0), and a
    net-short book keeps its direction."""
    one = _panel(n=1, seed=9)
    two = type(one)(*(jnp.repeat(f, 2, axis=0) for f in one))
    strat = base.get_strategy("momentum")
    pos = portfolio.per_ticker_positions(
        two, strat, {"lookback": jnp.full((2,), 10.0)})
    net, equity, expo = portfolio.portfolio_returns(
        two.close, pos, weights=np.float32([1.0, -1.0]), cost=0.0)
    assert np.isfinite(np.asarray(net)).all()
    np.testing.assert_allclose(np.asarray(net), 0.0, atol=1e-7)
    # Net-short [1, -2] on identical tickers == -1/3 of the single book.
    net_s, _, _ = portfolio.portfolio_returns(
        two.close, pos, weights=np.float32([1.0, -2.0]), cost=0.0)
    net_1, _, _ = portfolio.portfolio_returns(
        two.close[:1], pos[:1], cost=0.0)
    np.testing.assert_allclose(np.asarray(net_s),
                               -np.asarray(net_1) / 3.0,
                               rtol=1e-5, atol=1e-7)
