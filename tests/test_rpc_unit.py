"""Unit tests for the control plane's pure pieces.

SURVEY.md §4 calls out exactly these as the spots the reference left untested
and buggy: batch-split math (its split_off was inverted), liveness windowing,
and job materialization including unreadable files.
"""

import os

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.ops.metrics import Metrics
from distributed_backtesting_exploration_tpu.rpc import wire
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    JobQueue, JobRecord, PeerRegistry, parse_grid, synthetic_jobs)
from distributed_backtesting_exploration_tpu.rpc.journal import Journal


def _mk_jobs(n, **kw):
    return [JobRecord(id=f"j{i}", strategy="sma_crossover",
                      grid={"fast": np.asarray([5.0, 10.0], np.float32)},
                      ohlcv=b"payload", **kw) for i in range(n)]


@pytest.fixture(params=["native", "python"])
def qfactory(request):
    """JobQueue factory parameterized over the state-machine substrate.

    The native C++ DbxJobQueue and the pure-Python fallback must be
    behaviorally identical — every queue lifecycle test below runs against
    BOTH (the contract in cpp/dbx_core.h is "mirrors the Python fallback
    byte for byte")."""
    use_native = request.param == "native"
    if use_native:
        from distributed_backtesting_exploration_tpu.runtime import _core
        if not _core.available():
            pytest.skip("native core not available")

    def make(*args, **kw):
        kw.setdefault("use_native", use_native)
        q = JobQueue(*args, **kw)
        assert q.substrate == request.param
        return q

    return make


def test_take_n_semantics(qfactory):
    """Ask for n, get exactly min(n, len) — the reference handed out len-n."""
    q = qfactory()
    for r in _mk_jobs(5):
        q.enqueue(r)
    got = q.take(3, "w1")
    assert [r.id for r, _ in got] == ["j0", "j1", "j2"]
    got = q.take(10, "w1")
    assert [r.id for r, _ in got] == ["j3", "j4"]
    assert q.take(1, "w1") == []          # empty -> empty, not an error


def test_lease_expiry_requeues_front(qfactory):
    q = qfactory(lease_s=0.0)             # leases expire immediately
    for r in _mk_jobs(2):
        q.enqueue(r)
    q.take(1, "w1")
    assert q.requeue_expired() == ["j0"]
    got = q.take(2, "w2")
    assert [r.id for r, _ in got] == ["j0", "j1"]   # requeued at the front


def test_requeue_worker_on_prune(qfactory):
    q = qfactory(lease_s=60.0)
    for r in _mk_jobs(3):
        q.enqueue(r)
    q.take(2, "w1")
    q.take(1, "w2")
    assert sorted(q.requeue_worker("w1")) == ["j0", "j1"]
    s = q.stats()
    assert s["jobs_pending"] == 2 and s["jobs_leased"] == 1
    assert s["jobs_requeued"] == 2


def test_complete_idempotent_and_unknown(qfactory):
    q = qfactory()
    for r in _mk_jobs(1):
        q.enqueue(r)
    q.take(1, "w1")
    assert q.complete("j0", "w1") == "new"
    assert q.complete("j0", "w1") == "dup"   # duplicate is fine, and visible
    assert q.complete("nope", "w1") == "unknown"
    assert q.stats()["jobs_completed"] == 1
    assert q.drained


def test_take_pushes_batch_back_on_unexpected_error(tmp_path, qfactory,
                                                    monkeypatch):
    # An exception in the pop->lease window that is NOT the triaged
    # unreadable-payload class (OSError/ValueError) must not strand the
    # popped batch: the ids go back to pending and the error propagates.
    # Regression for the batched-take refactor (a stranded batch was
    # invisible to lease expiry and let drained() flip True early).
    import distributed_backtesting_exploration_tpu.rpc.dispatcher as dmod

    q = qfactory()
    q.enqueue(JobRecord(id="pathy", strategy="s", grid={},
                        path=str(tmp_path / "whatever.csv")))

    def boom(path):
        raise RuntimeError("infra hiccup, not an unreadable payload")

    monkeypatch.setattr(dmod, "_read_payload", boom)
    with pytest.raises(RuntimeError, match="infra hiccup"):
        q.take(4, "w1")
    assert not q.drained                   # still pending, not stranded
    assert q.stats()["jobs_pending"] == 1
    monkeypatch.undo()
    got = q.take(4, "w1")                  # unreadable now (missing file)
    assert got == []
    assert q.stats()["jobs_failed"] == 1


def test_unreadable_file_marked_failed(tmp_path, qfactory):
    jpath = str(tmp_path / "journal.jsonl")
    q = qfactory(Journal(jpath))
    q.enqueue(JobRecord(id="bad", strategy="s", grid={},
                        path=str(tmp_path / "missing.csv")))
    q.enqueue(_mk_jobs(1)[0])
    got = q.take(2, "w1")
    assert [r.id for r, _ in got] == ["j0"]   # bad one skipped, not dispatched
    assert q.stats()["jobs_failed"] == 1
    state = Journal.replay(jpath)
    assert state.failed == {"bad"}


def test_journal_replay_roundtrip(tmp_path, qfactory):
    from distributed_backtesting_exploration_tpu.utils import data
    csv_path = tmp_path / "t.csv"
    series = data.synthetic_ohlcv(1, 16, seed=0)
    csv_path.write_bytes(
        data.to_csv_bytes(type(series)(*(f[0] for f in series))))
    jpath = str(tmp_path / "journal.jsonl")
    q = qfactory(Journal(jpath))
    for r in _mk_jobs(3, path=None):
        r.ohlcv = None
        r.path = str(csv_path)
        q.enqueue(r)
    q.take(3, "w1")
    q.complete("j1", "w1")

    q2 = qfactory()
    restored = q2.restore(jpath)
    assert restored == 2                      # j0, j2 pending again
    ids = {r.id for r, _ in q2.take(5, "w2")}
    assert ids == {"j0", "j2"}


def test_journal_tolerates_torn_tail(tmp_path):
    jpath = tmp_path / "journal.jsonl"
    jpath.write_text(
        '{"ev":"enqueue","id":"a","strategy":"s","grid":{}}\n'
        '{"ev":"enqueue","id":"b","strategy":"s","grid":{}}\n'
        '{"ev":"comp')                        # crash mid-append
    state = Journal.replay(str(jpath))
    assert set(state.jobs) == {"a", "b"} and state.pending == ["a", "b"]


def test_peer_registry_prune(monkeypatch):
    # Pure-Python backend: the C++ registry keeps its own steady clock and
    # cannot see the monkeypatched time.
    reg = PeerRegistry(prune_window_s=10.0, use_native=False)
    t = [100.0]
    monkeypatch.setattr("time.monotonic", lambda: t[0])
    assert reg.touch("w1", chips=4) is True
    assert reg.touch("w1") is False
    t[0] = 105.0
    reg.touch("w2", chips=8)
    t[0] = 111.0                              # w1 silent 11s, w2 6s
    assert reg.prune() == ["w1"]
    assert reg.alive() == 1


def test_peer_registry_prune_native():
    import time as time_mod

    from distributed_backtesting_exploration_tpu.runtime import _core
    if not _core.available():
        pytest.skip("native core not available")
    reg = PeerRegistry(prune_window_s=0.15, use_native=True)
    assert reg.substrate == "native"
    assert reg.touch("w1", chips=4) is True
    assert reg.touch("w1") is False
    time_mod.sleep(0.08)
    reg.touch("w2", chips=8)
    time_mod.sleep(0.1)                       # w1 silent 0.18s, w2 0.1s
    assert reg.prune() == ["w1"]
    assert reg.alive() == 1


def test_metrics_wire_roundtrip():
    m = Metrics(*(np.arange(4, dtype=np.float32) + i
                  for i in range(len(Metrics._fields))))
    back = wire.metrics_from_bytes(wire.metrics_to_bytes(m))
    for a, b in zip(m, back):
        np.testing.assert_array_equal(np.asarray(a), b)
    with pytest.raises(ValueError):
        wire.metrics_from_bytes(b"XXXX" + b"\0" * 16)


def test_parse_grid():
    g = parse_grid("fast=5:8,slow=30:50:10,k=1.5;2.0")
    np.testing.assert_array_equal(g["fast"], [5, 6, 7])
    np.testing.assert_array_equal(g["slow"], [30, 40])
    np.testing.assert_array_equal(g["k"], [1.5, 2.0])
    assert parse_grid("") == {}


def test_synthetic_jobs_decode():
    from distributed_backtesting_exploration_tpu.utils import data
    jobs = synthetic_jobs(2, 64, "sma_crossover",
                          parse_grid("fast=3:5,slow=10:12"))
    assert len(jobs) == 2 and jobs[0].combos == 4
    series = data.from_wire_bytes(jobs[0].ohlcv)
    assert series.n_bars == 64


def test_late_completion_of_pending_job_removes_it(qfactory):
    """A completion racing a requeue (dispatcher restart / expired lease)
    must remove the job from pending and clear any fresh lease."""
    q = qfactory(lease_s=60.0)
    for r in _mk_jobs(2):
        q.enqueue(r)
    # j0 completed while still pending (late RPC after a restart replay):
    assert q.complete("j0", "w1") == "new"
    got = q.take(5, "w2")
    assert [r.id for r, _ in got] == ["j1"], "completed job must not dispatch"
    # duplicate completion of a re-leased job clears the lease:
    q.complete("j1", "w2")
    q.complete("j1", "w3")
    assert q.drained


def test_complete_batch_outcomes(tmp_path, qfactory):
    """complete_batch: one state-machine crossing per batch, per-id
    outcomes identical to complete(), 'new' completions journaled."""
    jpath = str(tmp_path / "journal.jsonl")
    q = qfactory(Journal(jpath))
    for r in _mk_jobs(3):
        q.enqueue(r)
    q.take(3, "w1")
    assert q.complete_batch(["j0", "j1", "nope"], "w1") == \
        ["new", "new", "unknown"]
    assert q.complete_batch(["j0", "j2"], "w1") == ["dup", "new"]
    assert q.complete_batch([], "w1") == []
    assert q.stats()["jobs_completed"] == 3
    assert q.drained
    assert Journal.replay(jpath).completed == {"j0", "j1", "j2"}


def test_batch_commit_drops_mid_take_completion(qfactory):
    """The take race model, batch-wide: an id completed between
    take_begin_n and take_commit_n is dropped (tombstone cleared), the
    rest of the batch leases normally."""
    q = qfactory()
    st = q._state
    # Drive the state machine directly (register + FIFO push): JobQueue
    # itself now parks pending ids in per-tenant WFQ lanes and keeps
    # this FIFO empty between calls — the take-window race contract
    # under test belongs to the substrate, not the lane index.
    for i in range(3):
        st.register(f"j{i}", 2.0)
        st.push_pending(f"j{i}")
    jids = st.take_begin_n(3)
    assert jids == ["j0", "j1", "j2"]
    assert st.take_begin_n(1) == []          # FIFO drained by the batch
    assert st.complete("j1") == "new"        # lands in the take window
    assert st.take_commit_n(jids, "w1", 60.0) == [True, False, True]
    s = st.stats()
    assert s["leased"] == 2 and s["completed"] == 1
    # the dropped id's orphan tombstone is cleared: draining the leases
    # drains the queue.
    assert st.complete("j0") == "new" and st.complete("j2") == "new"
    assert st.drained()


def test_inline_job_survives_journal_restart(tmp_path, qfactory):
    """Synthetic (inline-payload) jobs must be dispatchable after replay."""
    jpath = str(tmp_path / "journal.jsonl")
    q = qfactory(Journal(jpath))
    rec = synthetic_jobs(1, 32, "sma_crossover", parse_grid("fast=3:5"))[0]
    q.enqueue(rec)
    q2 = qfactory()
    assert q2.restore(jpath) == 1
    got = q2.take(1, "w")
    assert len(got) == 1 and got[0][1] == rec.ohlcv


def test_job_with_no_source_fails_cleanly(qfactory):
    q = qfactory()
    q.enqueue(JobRecord(id="x", strategy="s", grid={}))
    assert q.take(1, "w") == []
    assert q.stats()["jobs_failed"] == 1


def test_grid_from_proto_canonical_order():
    """Proto3 map iteration order is unspecified; the wire contract pins
    sorted-by-name axis order so DBXM param ordering is deterministic."""
    import numpy as np
    from distributed_backtesting_exploration_tpu.rpc import backtesting_pb2 as pb
    from distributed_backtesting_exploration_tpu.rpc import wire

    spec = pb.JobSpec(id="g")
    # Insert in reverse-sorted order; decode must come back sorted.
    spec.grid["slow"].values.extend([50.0, 100.0])
    spec.grid["fast"].values.extend([5.0, 10.0])
    spec.grid["alpha"].values.extend([0.1])
    out = wire.grid_from_proto(spec.grid)
    assert list(out) == ["alpha", "fast", "slow"]
    np.testing.assert_array_equal(out["fast"], np.float32([5.0, 10.0]))


def test_backend_fused_bollinger_matches_generic():
    """A bollinger job routed through the fused kernel (interpret mode on
    CPU) must produce the same DBXM payload as the generic sweep path."""
    import numpy as np
    from distributed_backtesting_exploration_tpu.rpc import compute, wire
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        synthetic_jobs)
    from distributed_backtesting_exploration_tpu.rpc import backtesting_pb2 as pb

    grid = {"window": np.float32([10, 20]), "k": np.float32([1.0, 2.0])}
    recs = synthetic_jobs(2, 160, "bollinger", grid, cost=1e-3, seed=11)
    specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                        grid=wire.grid_to_proto(r.grid), cost=r.cost)
             for r in recs]

    fused_backend = compute.JaxSweepBackend(use_fused=True)
    generic_backend = compute.JaxSweepBackend(use_fused=False)
    got_f = {c.job_id: c.metrics for c in fused_backend.process(specs)}
    got_g = {c.job_id: c.metrics for c in generic_backend.process(specs)}
    assert set(got_f) == {r.id for r in recs}
    for jid in got_f:
        mf = wire.metrics_from_bytes(got_f[jid])
        mg = wire.metrics_from_bytes(got_g[jid])
        for name in mf._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(mf, name)), np.asarray(getattr(mg, name)),
                rtol=2e-4, atol=2e-5, err_msg=name)


def _write_csv(path, n_bars=16, seed=0):
    from distributed_backtesting_exploration_tpu.utils import data
    s = data.synthetic_ohlcv(1, n_bars, seed=seed)
    path.write_bytes(data.to_csv_bytes(type(s)(*(f[0] for f in s))))


def test_complete_during_take_window_no_tombstone_leak(tmp_path, monkeypatch,
                                                       qfactory):
    """ADVICE r2 (medium): a completion landing between take()'s FIFO pop
    and lease creation installed a permanent tombstone, after which
    jobs_pending under-counted and drained never flipped True."""
    from distributed_backtesting_exploration_tpu.rpc import (
        dispatcher as disp)

    csv_path = tmp_path / "t.csv"
    _write_csv(csv_path)
    q = qfactory()
    q.enqueue(disp.JobRecord(id="j0", strategy="s", grid={},
                             path=str(csv_path)))
    orig = disp._read_payload

    def complete_mid_take(path):
        # take() reads the payload outside its lock — exactly the window
        # the race needs.
        q.complete("j0", "late-worker")
        return orig(path)

    monkeypatch.setattr(disp, "_read_payload", complete_mid_take)
    assert q.take(1, "w1") == []          # completed job must not dispatch
    s = q.stats()
    assert s["jobs_pending"] == 0 and s["jobs_leased"] == 0
    assert s["jobs_completed"] == 1
    assert q.drained                      # used to hang at live_pending == -1


def test_complete_during_failed_read_not_marked_failed(tmp_path, monkeypatch,
                                                       qfactory):
    """Same window, but the payload read fails: a job completed mid-take
    must count as completed, not failed."""
    from distributed_backtesting_exploration_tpu.rpc import (
        dispatcher as disp)

    q = qfactory()
    q.enqueue(disp.JobRecord(id="j0", strategy="s", grid={},
                             path=str(tmp_path / "gone.csv")))

    def complete_then_fail(path):
        q.complete("j0", "late-worker")
        raise OSError("disk gone")

    monkeypatch.setattr(disp, "_read_payload", complete_then_fail)
    assert q.take(1, "w1") == []
    s = q.stats()
    assert s["jobs_failed"] == 0 and s["jobs_completed"] == 1
    assert q.drained


def test_journal_corrupt_interior_is_loud(tmp_path):
    """ADVICE r1: replay used to skip EVERY undecodable line; an interior
    corrupt enqueue silently dropped a job from recovery."""
    from distributed_backtesting_exploration_tpu.rpc.journal import (
        JournalCorruptError)

    jpath = tmp_path / "j.jsonl"
    jpath.write_text(
        '{"ev":"enqueue","id":"a","strategy":"s","grid":{}}\n'
        'GARBAGE-NOT-JSON\n'
        '{"ev":"enqueue","id":"b","strategy":"s","grid":{}}\n')
    with pytest.raises(JournalCorruptError):
        Journal.replay(str(jpath))
    state = Journal.replay(str(jpath), strict=False)
    assert state.corrupt_lines == 1
    assert set(state.jobs) == {"a", "b"}
    # The benign torn-tail case stays tolerated in strict mode:
    jpath.write_text(
        '{"ev":"enqueue","id":"a","strategy":"s","grid":{}}\n'
        '{"ev":"comp')
    assert Journal.replay(str(jpath)).pending == ["a"]


def test_restart_does_not_duplicate_file_jobs(tmp_path):
    """ADVICE r1 (medium): rerunning the documented command line after a
    crash re-enqueued every --data path under fresh UUIDs."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        build_dispatcher, make_parser)

    for name in ("a", "b"):
        _write_csv(tmp_path / f"{name}.csv")
    argv = ["--data", str(tmp_path / "*.csv"),
            "--journal", str(tmp_path / "j.jsonl"),
            "--grid", "fast=3:5,slow=8:10"]
    d1 = build_dispatcher(make_parser().parse_args(argv))
    got = d1.queue.take(10, "w")
    assert len(got) == 2
    done_id, survivor_id = got[0][0].id, got[1][0].id
    d1.queue.complete(done_id, "w")

    # Crash (d1 dropped) + restart with the SAME argv:
    d2 = build_dispatcher(make_parser().parse_args(argv))
    assert d2.queue.stats()["jobs_pending"] == 1
    ids = [r.id for r, _ in d2.queue.take(10, "w2")]
    assert ids == [survivor_id], "only the unfinished job may re-dispatch"


def test_restart_does_not_reseed_synthetic(tmp_path):
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        build_dispatcher, make_parser)

    argv = ["--synthetic", "3", "--bars", "32",
            "--journal", str(tmp_path / "j.jsonl"),
            "--grid", "fast=3:5,slow=8:10"]
    d1 = build_dispatcher(make_parser().parse_args(argv))
    assert d1.queue.stats()["jobs_pending"] == 3
    got = d1.queue.take(1, "w")
    d1.queue.complete(got[0][0].id, "w")

    d2 = build_dispatcher(make_parser().parse_args(argv))
    assert d2.queue.stats()["jobs_pending"] == 2   # restored, not 3 + 2


def test_completion_retry_never_blocks_control_thread():
    """ADVICE r1: completion retry used to sleep 0.2+1+5s inline on the
    control thread, starving heartbeats past the prune window."""
    import time
    from types import SimpleNamespace

    import grpc

    from distributed_backtesting_exploration_tpu.rpc import compute
    from distributed_backtesting_exploration_tpu.rpc.worker import Worker

    w = Worker("localhost:1", compute.InstantBackend())
    w._next_status = time.monotonic() + 60.0      # heartbeat not due
    calls = []

    class FlakyStub:
        fail = 2

        def CompleteJobs(self, req, timeout=None):
            calls.extend(i.id for i in req.items)
            if self.fail:
                self.fail -= 1
                raise grpc.RpcError()
            return SimpleNamespace(accepted=len(req.items), unknown_ids=[])

    stub = FlakyStub()
    w._out.put(compute.Completion("j1", b"", 0.0))
    t0 = time.monotonic()
    w._drain_completions(stub)                    # attempt 1 fails -> parks
    assert time.monotonic() - t0 < 0.2, "drain must not sleep"
    assert len(w._deferred) == 1 and w.jobs_completed == 0
    w._drain_completions(stub)                    # not due yet: no attempt
    assert len(calls) == 1

    def force_due():
        w._deferred = [(time.monotonic() - 1, a, c)
                       for _, a, c in w._deferred]

    force_due()
    w._drain_completions(stub)                    # attempt 2 fails -> parks
    force_due()
    w._drain_completions(stub)                    # attempt 3 succeeds
    assert w.jobs_completed == 1 and not w._deferred
    assert w.completions_dropped == 0


def test_completion_drain_yields_to_overdue_heartbeat():
    import time

    from distributed_backtesting_exploration_tpu.rpc import compute
    from distributed_backtesting_exploration_tpu.rpc.worker import Worker

    w = Worker("localhost:1", compute.InstantBackend())
    w._next_status = time.monotonic() - 1.0       # heartbeat overdue

    class NeverCalled:
        def CompleteJobs(self, req, timeout=None):
            raise AssertionError("drain must yield to the heartbeat first")

    w._out.put(compute.Completion("j1", b"", 0.0))
    w._drain_completions(NeverCalled())           # returns without attempting
    assert w.jobs_completed == 0


def test_completion_dropped_after_attempts_exhausted():
    import time

    import grpc

    from distributed_backtesting_exploration_tpu.rpc import compute
    from distributed_backtesting_exploration_tpu.rpc.worker import Worker

    w = Worker("localhost:1", compute.InstantBackend())
    w._next_status = time.monotonic() + 60.0

    class DeadStub:
        def CompleteJobs(self, req, timeout=None):
            raise grpc.RpcError()

    stub = DeadStub()
    w._out.put(compute.Completion("j1", b"", 0.0))
    for _ in range(1 + len(Worker._COMPLETION_BACKOFF_S)):
        w._drain_completions(stub)
        w._deferred = [(time.monotonic() - 1, a, c)
                       for _, a, c in w._deferred]
    assert w.completions_dropped == 1 and not w._deferred


def test_native_substrate_defaults(monkeypatch):
    """The C++ core backs the live paths where it measures fastest: the
    registry and worker channels default native; the job-queue state
    machine defaults PYTHON by measurement (CPython's dict/deque beat the
    ctypes-driven core at Python-call grain even after the batch/
    int-handle redesign — DESIGN.md "queue state machine alone"), with
    ``DBX_NATIVE_QUEUE=1`` / ``use_native=True`` opting in. The native
    machine remains the only substrate at the C ABI (cpp/dbx_core_bench:
    ~1.1M jobs/s there)."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        JobQueue, PeerRegistry)
    from distributed_backtesting_exploration_tpu.rpc.worker import Worker
    from distributed_backtesting_exploration_tpu.rpc import compute
    from distributed_backtesting_exploration_tpu.runtime import _core

    if not _core.available():
        pytest.skip("native core not available")
    monkeypatch.delenv("DBX_NATIVE_QUEUE", raising=False)
    assert JobQueue().substrate == "python"
    monkeypatch.setenv("DBX_NATIVE_QUEUE", "1")
    assert JobQueue().substrate == "native"
    assert JobQueue(use_native=True).substrate == "native"
    assert PeerRegistry().substrate == "native"
    w = Worker("localhost:1", compute.InstantBackend())
    assert w._in.backend == "native" and w._out.backend == "native"


def test_oversized_job_id_rejected_at_intake(qfactory):
    """Ids beyond the native substrate's 511-byte cap are rejected at
    enqueue on BOTH substrates — behavior must not diverge at the edge
    (and a half-registered record must not strand in _records)."""
    q = qfactory()
    big = JobRecord(id="x" * 600, strategy="s", grid={}, ohlcv=b"p")
    with pytest.raises(ValueError, match="511 bytes"):
        q.enqueue(big)
    assert q.stats()["jobs_pending"] == 0
    assert q.complete(big.id, "w") == "unknown"   # nothing half-registered


def test_journal_compaction_preserves_live_state(tmp_path):
    """Compaction drops terminal jobs' payload blobs but preserves exactly
    what recovery and tooling need: pending payloads, completed/failed ids
    (idempotency + tombstones), paths (restart dedupe), and grids
    (aggregation joins)."""
    import json
    import os

    import numpy as np

    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        JobQueue, synthetic_jobs)
    from distributed_backtesting_exploration_tpu.rpc.journal import Journal

    jp = str(tmp_path / "j.jsonl")
    queue = JobQueue(Journal(jp))
    grid = {"fast": np.asarray([3.0], np.float32),
            "slow": np.asarray([8.0], np.float32)}
    recs = synthetic_jobs(4, 64, "sma_crossover", grid, seed=2)
    for rec in recs:
        queue.enqueue(rec)
    queue.take(2, "w1")
    assert queue.complete(recs[0].id, "w1") == "new"
    assert queue.complete(recs[1].id, "w1") == "new"

    size_before = os.path.getsize(jp)
    before, after = Journal.compact(jp)
    assert before >= after       # line count can only shrink...
    assert os.path.getsize(jp) < size_before   # ...and payload bytes MUST

    state = Journal.replay(jp)
    assert set(state.pending) == {recs[2].id, recs[3].id}
    assert state.completed == {recs[0].id, recs[1].id}
    # Completed jobs keep grid (aggregation) but lose the payload.
    done_rec = state.jobs[recs[0].id]
    assert "ohlcv_b64" not in done_rec and "grid" in done_rec
    # Pending jobs keep their full inline payload.
    assert "ohlcv_b64" in state.jobs[recs[2].id]

    # A restored queue behaves identically: pending re-dispatches with
    # payload intact, duplicate completion stays idempotent.
    q2 = JobQueue()
    assert q2.restore(jp) == 2
    taken = q2.take(2, "w2")
    assert {r.id for r, _ in taken} == {recs[2].id, recs[3].id}
    assert all(payload for _, payload in taken)
    assert q2.complete(recs[0].id, "w2") == "dup"
    # Compacted output is well-formed JSONL throughout.
    with open(jp) as fh:
        for line in fh:
            json.loads(line)


def test_journal_compaction_idempotent_and_empty(tmp_path):
    from distributed_backtesting_exploration_tpu.rpc.journal import Journal

    assert Journal.compact(str(tmp_path / "missing.jsonl")) == (0, 0)
    jp = str(tmp_path / "j.jsonl")
    j = Journal(jp)
    j.append("enqueue", id="a", strategy="sma_crossover", grid={})
    j.close()
    b1, a1 = Journal.compact(jp)
    b2, a2 = Journal.compact(jp)
    assert (b2, a2) == (a1, a1)   # second pass is a no-op rewrite


def test_backend_fused_multifield_strategies_match_generic():
    """donchian_hl and vwap_reversion jobs route to fused kernels that
    consume non-close columns (high/low, volume); the backend must ship
    those columns and produce the generic path's DBXM payload."""
    import numpy as np
    from distributed_backtesting_exploration_tpu.rpc import compute, wire
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        synthetic_jobs)
    from distributed_backtesting_exploration_tpu.rpc import backtesting_pb2 as pb

    cases = [
        ("donchian_hl", {"window": np.float32([10, 20])}),
        ("vwap_reversion", {"window": np.float32([8, 16]),
                            "k": np.float32([1.0, 2.0])}),
    ]
    for strategy, grid in cases:
        recs = synthetic_jobs(2, 160, strategy, grid, cost=1e-3, seed=21)
        specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                            grid=wire.grid_to_proto(r.grid), cost=r.cost)
                 for r in recs]
        fused_backend = compute.JaxSweepBackend(use_fused=True)
        assert fused_backend._fused_eligible(
            specs[0], wire.grid_from_proto(specs[0].grid), [160]), strategy
        got_f = {c.job_id: c.metrics
                 for c in fused_backend.process(specs)}
        got_g = {c.job_id: c.metrics
                 for c in compute.JaxSweepBackend(use_fused=False
                                                  ).process(specs)}
        assert set(got_f) == {r.id for r in recs}
        for jid in got_f:
            mf = wire.metrics_from_bytes(got_f[jid])
            mg = wire.metrics_from_bytes(got_g[jid])
            for name in mf._fields:
                np.testing.assert_allclose(
                    np.asarray(getattr(mf, name)),
                    np.asarray(getattr(mg, name)),
                    rtol=2e-4, atol=2e-5, err_msg=f"{strategy}/{name}")


def test_backend_fused_donchian_hl_big_window_stays_generic():
    """Windows beyond models.donchian.MAX_WINDOW poison the generic
    (semantics-defining) path to NaN; the hl router must not let the fused
    kernel silently diverge there."""
    import numpy as np
    from distributed_backtesting_exploration_tpu.models import donchian
    from distributed_backtesting_exploration_tpu.rpc import compute

    class _Job:
        strategy = "donchian_hl"

    grid = {"window": np.float32([10, donchian.MAX_WINDOW + 1])}
    assert not compute.JaxSweepBackend._fused_eligible(_Job(), grid, [160])


def test_fused_demotion_to_generic_path_is_loud(caplog):
    """A job a VMEM/table cap silently routes off the fused kernel is a
    throughput bug nobody can see: submit() must log one warning per job
    group naming the cap that demoted it (round-3 verdict: the >128-window
    and >8192-bar demotions were silent)."""
    import logging
    import numpy as np
    from distributed_backtesting_exploration_tpu.rpc import compute, wire
    from distributed_backtesting_exploration_tpu.rpc import backtesting_pb2 as pb
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        synthetic_jobs)

    backend = compute.JaxSweepBackend(use_fused=True)

    def run(strategy, grid, caplog):
        recs = synthetic_jobs(1, 96, strategy, grid, seed=3)
        specs = [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                            ohlcv2=r.ohlcv2 or b"",
                            grid=wire.grid_to_proto(r.grid))
                 for r in recs]
        with caplog.at_level(logging.WARNING, logger="dbx.compute"):
            backend.process(specs)
        return [r.message for r in caplog.records if "demoted" in r.message]

    # 130 distinct windows exceed the 128-entry selection-table cap.
    wide = {"lookback": np.arange(1, 131, dtype=np.float32)}
    msgs = run("momentum", wide, caplog)
    assert msgs and "130 distinct table windows" in msgs[0]
    assert str(compute.JaxSweepBackend._FUSED_MAX_WINDOWS) in msgs[0]

    # The two-legged path has its own router; it must be loud too.
    caplog.clear()
    pair_grid = {"lookback": np.float32([10.5]),
                 "z_entry": np.float32([1.0])}
    msgs = run("pairs", pair_grid, caplog)
    assert msgs and "non-integral lookback" in msgs[0]

    # An eligible job logs nothing (demotion warnings must not cry wolf).
    caplog.clear()
    ok = {"lookback": np.float32([5, 10])}
    assert run("momentum", ok, caplog) == []


def test_wf_test_without_train_not_stamped(tmp_path):
    """--wf-test without --wf-train must not stamp inert wf fields on
    records (they would split worker co-batching across a restart)."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        build_dispatcher, make_parser)

    args = make_parser().parse_args(
        ["--synthetic", "2", "--bars", "64", "--grid", "fast=3,slow=8",
         "--wf-test", "30", "--results-dir", str(tmp_path)])
    disp = build_dispatcher(args)
    taken = disp.queue.take(2, "w")
    assert len(taken) == 2
    for rec, _ in taken:
        assert (rec.wf_train, rec.wf_test, rec.wf_metric) == (0, 0, "")


def test_make_backend_forwards_fused_and_mesh_flags():
    from distributed_backtesting_exploration_tpu.rpc.worker import (
        make_backend)

    b = make_backend("jax", use_fused=False, use_mesh=True, param_chunk=4)
    assert b.use_fused is False and b.param_chunk == 4
    assert b._mesh is not None          # 8 virtual devices in tests
    b2 = make_backend("jax", use_fused=None, use_mesh=False)
    assert b2._mesh is None


def test_topk_wire_roundtrip():
    """DBXS block: indices + k metric rows + the rank metric's name."""
    idx = np.asarray([5, 2, 9], np.int32)
    m = Metrics(*(np.arange(3, dtype=np.float32) + i for i in range(9)))
    blob = wire.topk_to_bytes(idx, m, "sortino")
    gi, gm, metric = wire.topk_from_bytes(blob)
    assert metric == "sortino"
    np.testing.assert_array_equal(gi, idx)
    for a, b in zip(gm, m):
        np.testing.assert_array_equal(a, b)
    # Kind classification covers all three payload shapes.
    assert wire.result_kind(blob) == "topk"
    assert wire.result_kind(wire.metrics_to_bytes(m)) == "metrics"
    assert wire.result_kind(b"") == "empty"
    with pytest.raises(ValueError, match="magic"):
        wire.result_kind(b"????rest")
    with pytest.raises(ValueError, match="truncated"):
        wire.topk_from_bytes(blob[:-4])
    with pytest.raises(ValueError, match="magic"):
        wire.topk_from_bytes(wire.metrics_to_bytes(m))


def test_topk_fields_travel_journal_and_cli(tmp_path):
    """JobRecord.top_k/rank_metric survive the journal round trip and the
    CLI stamps them only in sweep mode (walk-forward + --top-k is an
    error; unknown --rank-metric is an error)."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        build_dispatcher, make_parser)

    rec = JobRecord(id="t", strategy="sma_crossover",
                    grid={"fast": np.float32([3.0])}, ohlcv=b"x",
                    top_k=8, rank_metric="cagr")
    back = JobRecord.from_journal(rec.journal_form())
    assert (back.top_k, back.rank_metric) == (8, "cagr")
    # Default stays zero-valued (no "topk" journal key).
    assert "topk" not in JobRecord(
        id="u", strategy="s", grid={}, ohlcv=b"x").journal_form()

    args = make_parser().parse_args(
        ["--synthetic", "2", "--bars", "64", "--grid", "fast=3,slow=8",
         "--top-k", "4", "--rank-metric", "sortino",
         "--results-dir", str(tmp_path)])
    disp = build_dispatcher(args)
    for rec, _ in disp.queue.take(2, "w"):
        assert (rec.top_k, rec.rank_metric) == (4, "sortino")

    with pytest.raises(SystemExit, match="rank-metric"):
        build_dispatcher(make_parser().parse_args(
            ["--synthetic", "1", "--top-k", "4", "--rank-metric", "nope",
             "--results-dir", str(tmp_path)]))
    with pytest.raises(SystemExit, match="walk-forward"):
        build_dispatcher(make_parser().parse_args(
            ["--synthetic", "1", "--top-k", "4", "--wf-train", "50",
             "--wf-test", "20", "--results-dir", str(tmp_path)]))


def test_topk_reduce_ranks_nan_last_and_respects_direction():
    """_topk_reduce: NaN metric cells rank behind every finite one (a
    zero-variance backtest has NaN sharpe — it must not win top-k by NaN
    comparison accident), and lower-is-better metrics rank ascending."""
    import numpy as np

    from distributed_backtesting_exploration_tpu.rpc.compute import (
        _topk_reduce)

    P = 6
    fields = {name: np.arange(P, dtype=np.float32)[None, :] + i
              for i, name in enumerate(Metrics._fields)}
    sharpe = np.float32([[0.5, np.nan, 2.0, np.nan, 1.0, -3.0]])
    fields["sharpe"] = sharpe
    m = Metrics(**fields)

    idx, sel = _topk_reduce(m, "sharpe", 4)
    np.testing.assert_array_equal(np.asarray(idx)[0], [2, 4, 0, 5])
    np.testing.assert_array_equal(np.asarray(sel.sharpe)[0],
                                  sharpe[0][[2, 4, 0, 5]])
    # Non-ranking fields travel with their row.
    np.testing.assert_array_equal(
        np.asarray(sel.turnover)[0],
        np.asarray(m.turnover)[0][[2, 4, 0, 5]])

    # Lower-is-better direction: max_drawdown picks the smallest values.
    mdd = np.float32([[0.5, 0.1, np.nan, 0.3, 0.2, 0.9]])
    fields["max_drawdown"] = mdd
    m2 = Metrics(**fields)
    idx2, sel2 = _topk_reduce(m2, "max_drawdown", 3)
    np.testing.assert_array_equal(np.asarray(idx2)[0], [1, 4, 3])


def _write_leg_csvs(tmp_path, n, t=64, prefix=""):
    from distributed_backtesting_exploration_tpu.utils import data as dmod

    batch = dmod.synthetic_ohlcv(n, t, seed=11)
    paths = []
    for i in range(n):
        one = dmod.OHLCV(*(f[i] for f in batch))
        p = tmp_path / f"{prefix}{i}.csv"
        p.write_bytes(dmod.to_csv_bytes(one))
        paths.append(str(p))
    return paths


def test_file_backed_pairs_jobs(tmp_path):
    """--data/--data2: pairs jobs take leg y and leg x from matched files,
    materialized at dispatch time; an unreadable leg-x file marks the job
    failed (not silently dropped); path2 survives the journal."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        build_dispatcher, make_parser)
    from distributed_backtesting_exploration_tpu.utils import data as dmod

    ys = _write_leg_csvs(tmp_path, 2, prefix="y")
    xs = _write_leg_csvs(tmp_path, 2, prefix="x")
    args = make_parser().parse_args(
        ["--strategy", "pairs", "--data", str(tmp_path / "y*.csv"),
         "--data2", str(tmp_path / "x*.csv"),
         "--grid", "lookback=6;10,z_entry=0.8;1.5",
         "--results-dir", str(tmp_path / "res"),
         "--journal", str(tmp_path / "q.jsonl")])
    disp = build_dispatcher(args)
    taken = disp.queue.take(2, "w")
    assert len(taken) == 2
    for (rec, payload), yp, xp in zip(taken, sorted(ys), sorted(xs)):
        assert rec.path == yp and rec.path2 == xp
        y = dmod.from_wire_bytes(payload)
        x = dmod.from_wire_bytes(rec.ohlcv2)
        assert y.n_bars == x.n_bars == 64

    # Journal round trip keeps path2.
    back = JobRecord.from_journal(taken[0][0].journal_form())
    assert back.path2 == taken[0][0].path2

    # Unreadable leg-x -> failed, journaled, leg y was readable.
    import os
    os.unlink(xs[0])
    args2 = make_parser().parse_args(
        ["--strategy", "pairs", "--data", str(tmp_path / "y*.csv"),
         "--data2", str(tmp_path / "x*.csv"),
         "--grid", "lookback=6",
         "--results-dir", str(tmp_path / "res2")])
    import pytest as _pytest
    with _pytest.raises(SystemExit, match="matched"):
        build_dispatcher(args2)   # glob count mismatch is loud

    # Same count, one unreadable: job fails at take time.
    bad = tmp_path / "x0.csv"
    bad.write_bytes(b"not,a,csv\n1,2\n")
    disp3 = build_dispatcher(make_parser().parse_args(
        ["--strategy", "pairs", "--data", str(tmp_path / "y*.csv"),
         "--data2", str(tmp_path / "x*.csv"), "--grid", "lookback=6",
         "--results-dir", str(tmp_path / "res3")]))
    taken3 = disp3.queue.take(2, "w")
    assert len(taken3) == 1            # the good pair
    assert disp3.queue.stats()["jobs_failed"] == 1


def test_data2_flag_validation(tmp_path):
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        build_dispatcher, make_parser)

    with pytest.raises(SystemExit, match="data2"):
        build_dispatcher(make_parser().parse_args(
            ["--strategy", "pairs", "--data", "nope*.csv",
             "--results-dir", str(tmp_path)]))
    with pytest.raises(SystemExit, match="pairs-only"):
        build_dispatcher(make_parser().parse_args(
            ["--strategy", "sma_crossover", "--data", "a*.csv",
             "--data2", "b*.csv", "--results-dir", str(tmp_path)]))
    with pytest.raises(SystemExit, match="leg-y"):
        build_dispatcher(make_parser().parse_args(
            ["--strategy", "pairs", "--data2", "b*.csv",
             "--results-dir", str(tmp_path)]))


def test_inline_leg_y_with_file_leg_x_journal_roundtrip():
    """A record with an inline leg-y payload and a file-backed leg-x must
    journal BOTH (regression: the path2 key once swallowed the inline
    ohlcv_b64 branch, so a restart restored a job with nothing to
    dispatch)."""
    rec = JobRecord(id="m", strategy="pairs",
                    grid={"lookback": np.float32([6.0])},
                    ohlcv=b"leg-y-bytes", path2="/tmp/x.csv")
    form = rec.journal_form()
    assert "ohlcv_b64" in form and form["path2"] == "/tmp/x.csv"
    back = JobRecord.from_journal(form)
    assert back.ohlcv == b"leg-y-bytes" and back.path2 == "/tmp/x.csv"


def test_pairs_restart_with_vanished_leg_file_still_serves(tmp_path):
    """Crash-restart discipline: when every pair is already journaled, a
    since-deleted leg-x file must not SystemExit the dispatcher — the
    restored queue is the workload and nothing new needs the pairing."""
    import os

    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        build_dispatcher, make_parser)

    ys = _write_leg_csvs(tmp_path, 2, prefix="y")
    xs = _write_leg_csvs(tmp_path, 2, prefix="x")
    argv = ["--strategy", "pairs", "--data", str(tmp_path / "y*.csv"),
            "--data2", str(tmp_path / "x*.csv"), "--grid", "lookback=6",
            "--journal", str(tmp_path / "q.jsonl"),
            "--results-dir", str(tmp_path / "res")]
    disp = build_dispatcher(make_parser().parse_args(argv))
    assert disp.queue.stats()["jobs_pending"] == 2

    os.unlink(xs[0])   # leg file vanishes between runs
    disp2 = build_dispatcher(make_parser().parse_args(argv))
    s = disp2.queue.stats()
    assert s["jobs_pending"] == 2          # restored, not re-enqueued


def test_result_block_short_header_raises_valueerror():
    """ADVICE r3: a blob with valid magic but a truncated header must raise
    the contract's ValueError, not leak struct.error into an aggregate run
    (same gap class the differential fuzz closed in data.from_wire_bytes)."""
    for n in range(4, 13):
        with pytest.raises(ValueError, match="truncated"):
            wire.topk_from_bytes(b"DBXS" + b"\x00" * (n - 4))
    for n in range(4, 12):
        with pytest.raises(ValueError, match="truncated"):
            wire.metrics_from_bytes(b"DBXM" + b"\x00" * (n - 4))
    # Header intact but the rank-metric name itself is cut off.
    m = Metrics(*(np.float32([1.0, 2.0]) for _ in range(9)))
    blob = wire.topk_to_bytes(np.int32([0, 1]), m, "sortino")
    with pytest.raises(ValueError, match="truncated"):
        wire.topk_from_bytes(blob[:15])   # 13-byte header + 2 of 7 name bytes


def test_pairs_glob_churn_keeps_journaled_x_legs(tmp_path, caplog):
    """ADVICE r3: y-glob churn between runs with equal counts must not
    silently re-assign an x leg that a journaled pair already claimed —
    the journal's (y, x) pairing is authoritative; ambiguity is loud."""
    import logging
    import os

    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        build_dispatcher, make_parser)

    ys = _write_leg_csvs(tmp_path, 2, prefix="y")
    xs = _write_leg_csvs(tmp_path, 2, prefix="x")
    argv = ["--strategy", "pairs", "--data", str(tmp_path / "y*.csv"),
            "--data2", str(tmp_path / "x*.csv"), "--grid", "lookback=6",
            "--journal", str(tmp_path / "q.jsonl"),
            "--results-dir", str(tmp_path / "res")]
    disp = build_dispatcher(make_parser().parse_args(argv))
    pairing = {r.path: r.path2 for r, _ in disp.queue.take(2, "w")}
    assert pairing == dict(zip(sorted(ys), sorted(xs)))

    # Churn: y0 deleted, y2 added; x set unchanged, counts still equal.
    # Positional pairing would hand y2 the x leg journaled for y1; the
    # fixed intake refuses instead of silently re-assigning.
    os.unlink(ys[0])
    _write_leg_csvs(tmp_path, 3, prefix="y")       # recreates y0,y1 + new y2
    os.unlink(ys[0])                                # keep y0 deleted
    with caplog.at_level(logging.WARNING, logger="dbx.dispatcher"), \
            pytest.raises(SystemExit, match="already paired"):
        build_dispatcher(make_parser().parse_args(argv))
    assert any("churn" in r.message for r in caplog.records)

    # Matching churn on BOTH legs: the new y pairs with the one x no
    # journaled pair has claimed — regardless of sort position.
    _write_leg_csvs(tmp_path, 3, prefix="x")
    os.unlink(xs[0])
    disp3 = build_dispatcher(make_parser().parse_args(argv))
    taken = disp3.queue.take(10, "w2")
    new = [r for r, _ in taken if r.path == str(tmp_path / "y2.csv")]
    assert len(new) == 1
    assert new[0].path2 == str(tmp_path / "x2.csv")
    # The restored y1 job keeps its journaled x1 leg.
    old = [r for r, _ in taken if r.path == str(tmp_path / "y1.csv")]
    assert old and old[0].path2 == str(tmp_path / "x1.csv")


def test_pairs_restart_with_stray_unclaimed_x_still_serves(tmp_path):
    """Code-review r4: a pure crash-restart with a stray unclaimed leg-x
    file (user dropped an extra x into the glob) must serve the restored
    queue, not die on a paths/paths2 length mismatch."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        build_dispatcher, make_parser)

    _write_leg_csvs(tmp_path, 2, prefix="y")
    _write_leg_csvs(tmp_path, 2, prefix="x")
    argv = ["--strategy", "pairs", "--data", str(tmp_path / "y*.csv"),
            "--data2", str(tmp_path / "x*.csv"), "--grid", "lookback=6",
            "--journal", str(tmp_path / "q.jsonl"),
            "--results-dir", str(tmp_path / "res")]
    disp = build_dispatcher(make_parser().parse_args(argv))
    assert disp.queue.stats()["jobs_pending"] == 2

    _write_leg_csvs(tmp_path, 3, prefix="x")   # stray x2.csv appears
    disp2 = build_dispatcher(make_parser().parse_args(argv))
    assert disp2.queue.stats()["jobs_pending"] == 2   # restored + served


def test_best_returns_wire_roundtrip():
    """DBXP block: grid index + one metric row + the net-return series."""
    row = Metrics(*(np.float32(i + 0.5) for i in range(9)))
    ret = np.linspace(-0.01, 0.01, 37).astype(np.float32)
    blob = wire.best_returns_to_bytes(7, row, ret, "sharpe")
    gi, gm, gr, metric = wire.best_returns_from_bytes(blob)
    assert gi == 7 and metric == "sharpe"
    for a, b in zip(gm, row):
        assert float(a) == float(b)
    np.testing.assert_array_equal(gr, ret)
    assert wire.result_kind(blob) == "returns"
    # Truncation at every boundary raises the contract's ValueError, never
    # struct.error (the DBX1/DBXS decoder discipline).
    for cut in (4, 10, 16, 18, 22, len(blob) - 1):
        with pytest.raises(ValueError, match="truncated|magic"):
            wire.best_returns_from_bytes(blob[:cut])
    with pytest.raises(ValueError, match="magic"):
        wire.best_returns_from_bytes(wire.metrics_to_bytes(
            Metrics(*(np.zeros(1, np.float32) for _ in range(9)))))


def test_best_returns_travels_journal_and_cli(tmp_path):
    """JobRecord.best_returns survives the journal round trip; the CLI
    rejects the incompatible mode combinations."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        build_dispatcher, make_parser)

    rec = JobRecord(id="p", strategy="sma_crossover",
                    grid={"fast": np.float32([3.0])}, ohlcv=b"x",
                    best_returns=True, rank_metric="cagr")
    back = JobRecord.from_journal(rec.journal_form())
    assert back.best_returns is True and back.rank_metric == "cagr"
    # Plain records stay untouched.
    plain = JobRecord.from_journal(JobRecord(
        id="q", strategy="s", grid={}, ohlcv=b"x").journal_form())
    assert plain.best_returns is False

    parser = make_parser()
    base_args = ["--synthetic", "1", "--grid", "fast=3,slow=8"]
    for bad in (["--best-returns", "--top-k", "4"],
                ["--best-returns", "--wf-train", "50", "--wf-test", "10"],
                ["--best-returns", "--strategy", "pairs"],
                ["--best-returns", "--rank-metric", "nope"]):
        with pytest.raises(SystemExit):
            build_dispatcher(parser.parse_args(base_args + bad))
    args = parser.parse_args(base_args + ["--best-returns",
                                          "--journal",
                                          str(tmp_path / "j.jsonl")])
    disp = build_dispatcher(args)
    taken = disp.queue.take(1, "w")
    assert taken and taken[0][0].best_returns is True


def test_best_returns_rejected_for_pairs_and_walkforward():
    """A hand-built best_returns spec on pairs or walk-forward jobs is
    validated-bad: complete-with-empty, loudly, no requeue loop."""
    from distributed_backtesting_exploration_tpu.rpc import (
        backtesting_pb2 as pb, compute)
    from distributed_backtesting_exploration_tpu.utils import data

    backend = compute.JaxSweepBackend(use_fused=False)
    one = data.synthetic_ohlcv(1, 48, seed=2)
    ohlcv = data.to_wire_bytes(type(one)(*(f[0] for f in one)))
    specs = [
        pb.JobSpec(id="p1", strategy="pairs", ohlcv=ohlcv, ohlcv2=ohlcv,
                   grid=wire.grid_to_proto(
                       {"lookback": np.float32([8.0]),
                        "z_entry": np.float32([1.0])}),
                   best_returns=True),
        pb.JobSpec(id="w1", strategy="sma_crossover", ohlcv=ohlcv,
                   grid=wire.grid_to_proto({"fast": np.float32([3.0]),
                                            "slow": np.float32([8.0])}),
                   wf_train=24, wf_test=8, best_returns=True),
    ]
    comps = backend.process(specs)
    assert sorted(c.job_id for c in comps) == ["p1", "w1"]
    assert all(c.metrics == b"" for c in comps)


def test_best_returns_unknown_metric_completes_empty():
    from distributed_backtesting_exploration_tpu.rpc import (
        backtesting_pb2 as pb, compute)
    from distributed_backtesting_exploration_tpu.utils import data

    one = data.synthetic_ohlcv(1, 48, seed=3)
    ohlcv = data.to_wire_bytes(type(one)(*(f[0] for f in one)))
    spec = pb.JobSpec(id="m1", strategy="sma_crossover", ohlcv=ohlcv,
                      grid=wire.grid_to_proto({"fast": np.float32([3.0]),
                                               "slow": np.float32([8.0])}),
                      best_returns=True, rank_metric="not_a_metric")
    comps = compute.JaxSweepBackend(use_fused=False).process([spec])
    assert len(comps) == 1 and comps[0].metrics == b""


# ---------------------------------------------------------------------------
# Dispatch by digest: content-addressed panel store (dispatcher side)
# ---------------------------------------------------------------------------

def test_file_backed_job_redispatches_after_source_deleted(tmp_path,
                                                           qfactory):
    """Regression for the requeue re-read bug: a file-backed (CSV) job used
    to re-read AND re-transcode its source on every dispatch — with the
    content-addressed blob store, a requeued job dispatches from memory
    even after the source file is deleted post-first-materialization."""
    import os

    from distributed_backtesting_exploration_tpu.utils import data

    csv_path = tmp_path / "t.csv"
    series = data.synthetic_ohlcv(1, 16, seed=5)
    csv_path.write_bytes(
        data.to_csv_bytes(type(series)(*(f[0] for f in series))))
    q = qfactory(lease_s=60.0)
    rec = JobRecord(id="f1", strategy="sma_crossover",
                    grid={"fast": np.asarray([5.0], np.float32)},
                    path=str(csv_path))
    q.enqueue(rec)
    assert rec.panel_digest == ""          # file-backed: stamped at take
    taken = q.take(1, "w1")
    assert len(taken) == 1
    first_payload = taken[0][1]
    digest = rec.panel_digest
    assert digest and first_payload[:4] == b"DBX1"

    # Lease abandoned, source deleted: the redispatch must come from the
    # store, not the (gone) file, under the SAME content address.
    assert q.requeue_worker("w1") == ["f1"]
    os.remove(csv_path)
    taken2 = q.take(1, "w2")
    assert len(taken2) == 1
    assert taken2[0][1] == first_payload
    assert rec.panel_digest == digest
    # And FetchPayload's backing lookup serves it too.
    assert q.payload_for_digest(digest) == first_payload


def test_panel_digest_journaled_and_restored(tmp_path, qfactory):
    """The digest stamped at first materialization survives a restart (a
    "digest" journal event merges into the enqueue record on replay), so
    a restarted dispatcher keeps addressing the panel the first run
    delivered; the empty store repopulates lazily from the source."""
    from distributed_backtesting_exploration_tpu.utils import data

    csv_path = tmp_path / "t.csv"
    series = data.synthetic_ohlcv(1, 16, seed=6)
    csv_path.write_bytes(
        data.to_csv_bytes(type(series)(*(f[0] for f in series))))
    jpath = str(tmp_path / "journal.jsonl")
    q = qfactory(Journal(jpath))
    rec = JobRecord(id="f1", strategy="sma_crossover",
                    grid={"fast": np.asarray([5.0], np.float32)},
                    path=str(csv_path))
    q.enqueue(rec)
    (payload,) = [p for _, p in q.take(1, "w1")]
    assert rec.panel_digest
    q.requeue_worker("w1")

    q2 = qfactory()
    assert q2.restore(jpath) == 1
    (taken,) = q2.take(1, "w2")
    assert taken[0].panel_digest == rec.panel_digest
    assert taken[1] == payload
    # Inline payloads journal their digest with the enqueue record.
    q3 = qfactory(Journal(str(tmp_path / "j2.jsonl")))
    inline = _mk_jobs(1)[0]
    q3.enqueue(inline)
    assert inline.panel_digest
    q4 = qfactory()
    assert q4.restore(str(tmp_path / "j2.jsonl")) == 1
    (taken4,) = q4.take(1, "w1")
    assert taken4[0].panel_digest == inline.panel_digest


def test_panel_store_lru_bound_and_unservable_digest(tmp_path):
    """The store honors its byte bound (LRU eviction), and an evicted
    digest whose source is also gone is reported unservable (None) — the
    FetchPayload leg that makes the dispatcher forget the delivery."""
    from distributed_backtesting_exploration_tpu.rpc.panel_store import (
        PanelStore, panel_digest)

    store = PanelStore(max_bytes=64)
    d1 = store.put(b"a" * 40)
    d2 = store.put(b"b" * 40)           # evicts the first blob
    assert store.get(d2) == b"b" * 40
    assert store.get(d1) is None
    assert store.stats()["evictions"] == 1
    assert store.stats()["bytes"] <= 64
    assert d1 == panel_digest(b"a" * 40)

    # Queue-level: digest known, store evicted, file gone -> unservable.
    from distributed_backtesting_exploration_tpu.utils import data

    csv_path = tmp_path / "t.csv"
    series = data.synthetic_ohlcv(1, 16, seed=7)
    csv_path.write_bytes(
        data.to_csv_bytes(type(series)(*(f[0] for f in series))))
    q = JobQueue()
    rec = JobRecord(id="f1", strategy="sma_crossover",
                    grid={"fast": np.asarray([5.0], np.float32)},
                    path=str(csv_path))
    q.enqueue(rec)
    q.take(1, "w1")
    q.panel_store.max_bytes = 0
    q.panel_store.put(b"x")             # force the eviction sweep
    import os

    os.remove(csv_path)
    assert q.payload_for_digest(rec.panel_digest) is None


# ---------------------------------------------------------------------------
# Streaming appends: queue half (chain, journal, affinity)
# ---------------------------------------------------------------------------

def _stream_base(n_bars=64, seed=21):
    from distributed_backtesting_exploration_tpu.utils import data

    full = data.synthetic_ohlcv(1, n_bars + 16, seed=seed)

    def cut(lo, hi):
        return data.to_wire_bytes(
            type(full)(*(np.asarray(f[0, lo:hi]) for f in full)))

    rec = JobRecord(id="sb", strategy="sma_crossover",
                    grid=parse_grid("fast=3:5,slow=10:14:2"),
                    ohlcv=cut(0, n_bars))
    return rec, cut


def test_append_bars_chain_journal_and_compaction(tmp_path, qfactory):
    """append_bars journals an O(ΔT) `delta` event (never the extended
    panel), the chain survives replay AND compaction, and a restarted
    queue re-materializes the extended panel bit-identically — same
    content digest — even with an empty panel store."""
    from distributed_backtesting_exploration_tpu.rpc import panel_store
    from distributed_backtesting_exploration_tpu.utils import data

    jp = str(tmp_path / "s.jsonl")
    rec, cut = _stream_base()
    q = qfactory(Journal(jp))
    q.enqueue(rec)
    arec, outcome, ndig, new_len = q.append_bars(
        rec.panel_digest, 64, cut(64, 72), strategy="sma_crossover",
        grid=rec.grid)
    assert outcome == "extended" and new_len == 72
    assert arec.append_parent == rec.panel_digest
    assert arec.ohlcv is None and arec.path is None
    # Journal growth is O(ΔT): no line carries the 72-bar extended panel.
    extended = data.splice_wire_bytes(cut(0, 64), cut(64, 72))
    assert panel_store.panel_digest(extended) == ndig
    import base64 as b64
    blob64 = b64.b64encode(extended).decode()
    with open(jp) as fh:
        assert all(blob64 not in line for line in fh)

    # Drain the base job so compaction has something to fold; the append
    # job stays pending.
    got = q.take(1, "w")
    assert [r.id for r, _ in got] == [rec.id]
    q.complete_batch([rec.id], "w")

    Journal.compact(jp)
    q2 = qfactory(None)
    assert q2.restore(jp) == 1            # the pending append job
    # Store empty after restart: payload_for_digest rebuilds via chain.
    blob = q2.payload_for_digest(ndig)
    assert blob == extended
    # take() of the restored append job materializes through the chain.
    taken = q2.take(1, "w2")
    assert len(taken) == 1
    trec, payload = taken[0]
    assert trec.append_parent == rec.panel_digest and payload == extended


def test_append_bars_base_gone_is_explicit_reject(qfactory):
    rec, cut = _stream_base(seed=22)
    q = qfactory(None)
    q.enqueue(rec)
    _, outcome, _, _ = q.append_bars(
        "00" * 16, 64, cut(64, 72), strategy="sma_crossover",
        grid=rec.grid)
    assert outcome == "base_missing"
    assert q.stats()["jobs_pending"] == 1   # nothing new enqueued


def test_take_admit_defers_then_serves(qfactory):
    """The placement hook's contract (round 20, generalizing the old
    append-only affinity hook): EVERY popped record is consulted — the
    placement stage ranks ordinary jobs too — a rejected job is held OUT
    of the batch (and the FIFO) for that call, re-queued front-of-line
    afterwards, and an admit that keeps rejecting cannot lose a job.
    ``drained`` must stay False while anything is held."""
    rec, cut = _stream_base(seed=23)
    q = qfactory(None)
    q.enqueue(rec)
    arec, outcome, ndig, _ = q.append_bars(
        rec.panel_digest, 64, cut(64, 72), strategy="sma_crossover",
        grid=rec.grid)
    assert outcome == "extended"

    consulted = []

    def deny(r):
        consulted.append(r.id)
        r.affinity_skips += 1
        return False

    got = q.take(4, "w", admit=deny)
    # Both jobs consulted, both deferred — nothing served this call.
    assert got == []
    assert sorted(consulted) == sorted([rec.id, arec.id])
    # Held jobs still count as in-take: an observer must not tear the
    # dispatcher down while placement holds the whole queue.
    assert not q.drained
    # Deferred, not lost: a later take (any admit verdict) serves both,
    # the held pair first in line.
    got2 = q.take(4, "w", admit=lambda r: True)
    assert {r.id for r, _ in got2} == {rec.id, arec.id}
    assert ndig in {r.panel_digest for r, _ in got2}
    q.complete_batch([r.id for r, _ in got2], "w")
    assert q.drained


def test_append_chain_long_stream_survives_restart(tmp_path, qfactory):
    """A long live stream (many chained appends) must stay servable after
    a restart: the chain walk is iterative, so payload reconstruction
    works at any chain length and re-stores every level on the way up."""
    from distributed_backtesting_exploration_tpu.utils import data

    jp = str(tmp_path / "long.jsonl")
    n0, dt, links = 48, 4, 12
    full = data.synthetic_ohlcv(1, n0 + dt * links, seed=31)

    def cut(lo, hi):
        return data.to_wire_bytes(
            type(full)(*(np.asarray(f[0, lo:hi]) for f in full)))

    rec = JobRecord(id="long-base", strategy="sma_crossover",
                    grid=parse_grid("fast=3:5,slow=10:14:2"),
                    ohlcv=cut(0, n0))
    q = qfactory(Journal(jp))
    q.enqueue(rec)
    dig, L = rec.panel_digest, n0
    for _ in range(links):
        arec, outcome, dig, L = q.append_bars(
            dig, L, cut(L, L + dt), strategy="sma_crossover",
            grid=rec.grid)
        assert outcome == "extended"

    q2 = qfactory(Journal(jp))
    q2.restore(jp)
    blob = q2.payload_for_digest(dig)
    assert blob is not None
    assert data.from_wire_bytes(blob).n_bars == n0 + dt * links
    # Restored append jobs keep their delta bytes (delta-only dispatch
    # works across restarts, not just in the first process).
    restored = [r for r in q2._records.values() if r.append_parent]
    assert restored and all(r.delta for r in restored)
