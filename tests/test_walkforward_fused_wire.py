"""Wire walk-forward jobs route through the fused-train two-phase split.

VERDICT r4 item 4: ``walk_forward_fused`` (one stacked fused train sweep
for ALL refit windows) was bench-only and SMA-bound; now every fused
family can serve as its train kernel, and ``_submit_walkforward_group``
routes large-grid groups through it. Parity vs the generic single-program
``walk_forward`` is flip-aware: the fused and generic train sweeps are
rounding twins, and a knife-edge train-metric tie can flip a window's
chosen param (the ``bench.py --verify`` caveat class).
"""

import logging

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.rpc import (
    backtesting_pb2 as pb, compute, wire)
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    synthetic_jobs)


def _wf_specs(recs):
    return [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                       grid=wire.grid_to_proto(r.grid), cost=r.cost,
                       wf_train=r.wf_train, wf_test=r.wf_test,
                       wf_metric=r.wf_metric) for r in recs]


def _run(backend, specs):
    return {c.job_id: c.metrics for c in backend.process(specs)}


def _assert_flip_aware(got_a, got_b, *, max_flips):
    """Stitched OOS rows must match tightly except where a train-argmax
    tie flipped a window's chosen param (detected on sharpe)."""
    assert set(got_a) == set(got_b)
    flips = 0
    for jid in got_a:
        ma = wire.metrics_from_bytes(got_a[jid])
        mb = wire.metrics_from_bytes(got_b[jid])
        a, b = np.asarray(ma.sharpe), np.asarray(mb.sharpe)
        if np.any(np.abs(a - b) > (0.01 + 0.01 * np.abs(b))):
            flips += 1
            continue
        for name in ma._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(ma, name)),
                np.asarray(getattr(mb, name)), rtol=2e-3, atol=2e-4,
                err_msg=f"{jid}/{name}")
    assert flips <= max_flips, f"{flips} flipped jobs"


@pytest.fixture(scope="module")
def generic_backend(devices):
    return compute.JaxSweepBackend(use_fused=False, use_mesh=False)


def _fused_wf_backend(use_mesh):
    b = compute.JaxSweepBackend(use_fused=True, use_mesh=use_mesh)
    b._WF_FUSED_MIN_COMBOS = 1   # force the fused-train route (tiny grids)
    return b


def test_wf_fused_route_taken_and_matches(generic_backend, caplog):
    """SMA walk-forward group routes through walk_forward_fused (logged)
    and matches the generic path flip-aware."""
    grid = {"fast": np.float32([3, 5]), "slow": np.float32([13, 21])}
    recs = synthetic_jobs(3, 200, "sma_crossover", grid, cost=1e-3,
                          seed=210, wf_train=80, wf_test=30,
                          wf_metric="sharpe")
    specs = _wf_specs(recs)
    b = _fused_wf_backend(use_mesh=False)
    with caplog.at_level(logging.INFO, logger="dbx.compute"):
        got = _run(b, specs)
    assert any("fused-train route" in r.message for r in caplog.records)
    _assert_flip_aware(got, _run(generic_backend, specs), max_flips=1)


def test_wf_fused_multifield_family(generic_backend):
    """A multi-field family (stochastic: close/high/low) through the
    generalized train_metrics_fn."""
    grid = {"window": np.float32([8, 12]), "band": np.float32([15.0, 25.0])}
    recs = synthetic_jobs(3, 200, "stochastic", grid, cost=1e-3,
                          seed=230, wf_train=80, wf_test=30,
                          wf_metric="sharpe")
    specs = _wf_specs(recs)
    _assert_flip_aware(_run(_fused_wf_backend(use_mesh=False), specs),
                       _run(generic_backend, specs), max_flips=1)


def test_wf_fused_volume_family(generic_backend):
    """A volume family (obv_trend: close/volume) through the generalized
    train_metrics_fn."""
    grid = {"window": np.float32([8, 12, 16])}
    recs = synthetic_jobs(2, 200, "obv_trend", grid, cost=1e-3,
                          seed=240, wf_train=80, wf_test=30,
                          wf_metric="sharpe")
    specs = _wf_specs(recs)
    _assert_flip_aware(_run(_fused_wf_backend(use_mesh=False), specs),
                       _run(generic_backend, specs), max_flips=1)


def test_wf_fused_mesh_matches(generic_backend):
    """The fused-train route composes with the chip mesh (rows sharded,
    per-block two-phase split) and still matches the generic path."""
    grid = {"fast": np.float32([3, 5]), "slow": np.float32([13, 21])}
    recs = synthetic_jobs(9, 200, "sma_crossover", grid, cost=1e-3,
                          seed=250, wf_train=80, wf_test=30,
                          wf_metric="sharpe")
    specs = _wf_specs(recs)
    _assert_flip_aware(_run(_fused_wf_backend(use_mesh=True), specs),
                       _run(generic_backend, specs), max_flips=2)


def test_wf_small_grid_stays_generic(caplog):
    """Below the grid-size threshold the single-program generic
    walk_forward keeps the route (it measures faster there)."""
    grid = {"fast": np.float32([3.0]), "slow": np.float32([13.0])}
    recs = synthetic_jobs(2, 200, "sma_crossover", grid, cost=1e-3,
                          seed=260, wf_train=80, wf_test=30,
                          wf_metric="sharpe")
    specs = _wf_specs(recs)
    b = compute.JaxSweepBackend(use_fused=True, use_mesh=False)
    with caplog.at_level(logging.INFO, logger="dbx.compute"):
        got = _run(b, specs)
    assert not any("fused-train route" in r.message
                   for r in caplog.records)
    assert all(v for v in got.values())
