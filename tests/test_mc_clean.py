"""Tier-1 gate: dbxmc explores the dispatcher's journaled state machines
and every declared invariant holds, on every available substrate.

This is the control-plane twin of test_lint_clean.py's dbxcert gate: the
REAL JobQueue/Journal/WfqScheduler/PanelStore code is driven through
hundreds of inequivalent interleavings with crash replays forked at
journal append boundaries — a regression that breaks crash recovery,
completion idempotency, quota accounting or the append-first discipline
fails HERE with a minimized replayable op script, not in a fleet run.

The seeded-bug tests close the loop: the journal_discipline fixture
(state published before journaled) must be caught DYNAMICALLY by the
checker (with a minimized trace that reproduces on replay) and flagged
STATICALLY by the `journal-discipline` lint rule.
"""

import importlib.util
import os

import pytest

from distributed_backtesting_exploration_tpu.analysis import (
    ast_rules, core, modelcheck as mc)
from distributed_backtesting_exploration_tpu.rpc.dispatcher import JobQueue
from distributed_backtesting_exploration_tpu.runtime import (
    _core as native_core)

SUBSTRATES = ["python"] + (["native"] if native_core.available() else [])

_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "lint",
                        "journal_discipline.py")


@pytest.mark.parametrize("substrate", SUBSTRATES)
def test_mc_gate(substrate):
    """>= 500 distinct schedules and >= 100 crash-replay points per
    substrate, zero violations across the whole invariant table."""
    cfg = mc.MCConfig(
        ops=int(os.environ.get("DBX_MC_OPS", "12")),
        seed=int(os.environ.get("DBX_MC_SEED", "0")),
        schedules=500, substrate=substrate)
    r = mc.explore_substrate(cfg)
    assert r["violations"] == [], r["violations"]
    assert r["schedules"] >= 500
    assert r["crash_points"] >= 100
    # Every crash point sits at a real append boundary; light replay
    # checks ran at every boundary on both sides of the write.
    assert r["boundaries"] > r["crash_points"]
    assert r["clean"]


def _load_fixture():
    spec = importlib.util.spec_from_file_location("jd_fixture", _FIXTURE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_seeded_bug_caught_dynamically(monkeypatch):
    """The journal_discipline fixture's enqueue (mutate-then-journal)
    trips journal-append-first at the very first crash boundary; the
    minimizer shrinks the trace and the script reproduces on replay."""
    fx = _load_fixture()
    monkeypatch.setattr(JobQueue, "enqueue_many", fx.buggy_enqueue_many)
    cfg = mc.MCConfig(ops=10, seed=1, schedules=10)
    r = mc.explore_substrate(cfg)
    assert not r["clean"]
    v = r["violations"][0]
    assert v["invariant"] == "journal-append-first"
    # Minimized to (at most) the single offending enqueue op.
    assert v["minimized_ops"] <= 2
    assert [o["name"] for o in v["script"]["ops"]].count("enqueue") >= 1
    rep = mc.replay_script(v["script"])
    assert rep["reproduced"], rep


def test_seeded_bug_flagged_statically():
    """The SAME fixture is flagged by the journal-discipline lint rule:
    one finding per journal-covered mutation sitting above the append."""
    rule = ast_rules.JournalDisciplineRule()
    findings, _, _ = core.lint_path(_FIXTURE, [rule])
    assert len(findings) == 3
    assert all(f.rule == "journal-discipline" for f in findings)
    with open(_FIXTURE, encoding="utf-8") as fh:
        lines = fh.read().splitlines()
    marker = next(i + 1 for i, l in enumerate(lines)
                  if "BUG: published before journaled" in l)
    assert marker in {f.line for f in findings}


@pytest.mark.slow
@pytest.mark.parametrize("substrate", SUBSTRATES)
def test_mc_deep_exploration(substrate):
    """Deep config: exhaustive-leaning sweep (more schedules, bigger
    programs, intra-op preemption) — the overnight soak, not the gate."""
    cfg = mc.MCConfig(ops=24, seed=7, schedules=3000,
                      substrate=substrate, crash_every=2)
    r = mc.explore_substrate(cfg)
    assert r["violations"] == [], r["violations"]
    assert r["schedules"] >= 2500
    if substrate == "python":
        deep = mc.MCConfig(ops=16, seed=11, schedules=40, depth=6,
                           substrate=substrate)
        rd = mc.explore_substrate(deep)
        assert rd["violations"] == [], rd["violations"]
        assert rd["preemptions"] > 0
