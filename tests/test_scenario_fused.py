"""Scenario megakernel (round 18): fused in-trace generation A/B'd
against the materialized ladder.

The tentpole claims under test:

- **Bit-match**: a ``fused_scenario_sweep`` row equals the dense fused
  sweep over the host-materialized panel of the same spec — selection
  class exact, moment sums within the committed association budget
  (``test_paged``'s rtol=2e-5/atol=2e-6).
- **Coalescing**: a capability-declaring poll turns K eligible scenario
  records into ONE carrier JobSpec with a K-member ``scenario_batch``
  carrying per-record ids and EFFECTIVE seeds; each member completes
  individually through the existing CompleteJobs path.
- **Degradation ladder**: an old-capability worker, the
  ``DBX_SCENARIO_FUSED=0`` kill switch, and a worker-side fused-launch
  failure all fall back to the materialized path — never a failed job —
  and the materialized rungs produce bit-identical result bytes. A
  dispatcher restart (journal replay) re-coalesces the same specs.
"""

import threading
import time

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu import scenarios as scn
from distributed_backtesting_exploration_tpu.models.base import (
    get_strategy)
from distributed_backtesting_exploration_tpu.ops import fused
from distributed_backtesting_exploration_tpu.parallel import sweep
from distributed_backtesting_exploration_tpu.rpc import (
    backtesting_pb2 as pb, compute, service, wire)
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    Dispatcher, DispatcherServer, JobQueue, JobRecord, PeerRegistry,
    parse_grid, scenario_jobs)
from distributed_backtesting_exploration_tpu.rpc.journal import Journal
from distributed_backtesting_exploration_tpu.rpc.panel_store import (
    panel_digest)
from distributed_backtesting_exploration_tpu.rpc.worker import Worker
from distributed_backtesting_exploration_tpu.utils import data as data_mod

# The committed association budget (test_paged): selection-class fields
# stay exact; accumulated moments may differ by reduction order when a
# fallback rung routes through a different kernel association.
RTOL, ATOL = 2e-5, 2e-6

GRID = parse_grid("fast=3:5,slow=10:14:2")
PARAMS = {"n_bars": 64, "block": 8, "regimes": 2, "vol_scale": 1.5,
          "shock": 0.01}


def _base_blob(bars: int = 96) -> bytes:
    s = data_mod.synthetic_ohlcv(1, bars, seed=42)
    return data_mod.to_wire_bytes(
        type(s)(*(np.asarray(f[0]) for f in s)))


def _wait(pred, timeout=120.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# Wire seed scheme
# ---------------------------------------------------------------------------

def test_seed_to_int64_wire_roundtrip():
    """Effective seeds are unsigned 64-bit; ScenarioSpec.seed is signed
    int64. The two's-complement wrap must roundtrip the proto and leave
    seed_words — the only thing the generator consumes — unchanged."""
    for s in (0, 1, (1 << 63) - 1, 1 << 63, (1 << 64) - 1,
              11734379837973679516):
        w = scn.seed_to_int64(s)
        assert -(1 << 63) <= w < (1 << 63)
        echo = pb.ScenarioSpec.FromString(
            pb.ScenarioSpec(seed=w).SerializeToString()).seed
        assert echo == w
        assert scn.seed_words(echo) == scn.seed_words(s)


# ---------------------------------------------------------------------------
# Numerics: fused row == dense sweep over the host-materialized panel
# ---------------------------------------------------------------------------

def test_fused_sweep_cross_pins_materialized_dense():
    """Row k of the megakernel launch matches the dense fused sweep over
    the panel ``scenario_panel_bytes`` materializes for spec k — the
    in-trace generator and the host generator are ONE program
    (synth._gen_impl), so the match is by construction, not tolerance."""
    blob = _base_blob(160)
    base_d = panel_digest(blob)
    base = data_mod.from_wire_bytes(blob)
    specs = [scn.ScenarioParams(n_bars=96, block=8, regimes=3,
                                vol_scale=vs, shock=sh, seed=i)
             for i, (vs, sh) in enumerate(
                 [(1.5, 0.0), (2.0, 0.02), (1.2, 0.05), (3.0, 0.0)])]
    effs = [scn.scenario_seed(base_d, p) for p in specs]
    words = [scn.seed_words(e) for e in effs]
    pgrid = {k: np.asarray(v, np.float32) for k, v in
             sweep.product_grid(fast=GRID["fast"],
                                slow=GRID["slow"]).items()}
    base_cols = {f: np.asarray(getattr(base, f), np.float32)
                 for f in ("open", "high", "low", "close", "volume")}
    m_fused = fused.fused_scenario_sweep(
        "sma_crossover", base_cols,
        np.asarray([w[0] for w in words], np.int32),
        np.asarray([w[1] for w in words], np.int32),
        np.asarray([p.vol_scale for p in specs], np.float32),
        np.asarray([p.shock for p in specs], np.float32),
        pgrid, n_bars=96, block=8, regimes=3, interpret=True)

    fields, _, call = fused._PAGED_FAMILIES["sma_crossover"]
    epi = fused._resolve_epilogue(None)
    for k, p in enumerate(specs):
        panel = data_mod.from_wire_bytes(scn.scenario_panel_bytes(blob, p))
        arrays = [np.asarray(getattr(panel, f), np.float32)[None, :]
                  for f in fields]
        m_dense = call(arrays, pgrid, t_real=None, cost=0.0,
                       periods_per_year=252, interpret=True, epilogue=epi)
        for name in m_fused._fields:
            got = np.asarray(getattr(m_fused, name))[k]
            want = np.asarray(getattr(m_dense, name))[0]
            if name == "n_trades":   # selection class: exact, always
                assert np.array_equal(got, want), name
            else:
                np.testing.assert_allclose(got, want, rtol=RTOL,
                                           atol=ATOL, err_msg=name)


# ---------------------------------------------------------------------------
# Dispatch-time coalescing over the real wire
# ---------------------------------------------------------------------------

def _scn_queue(k: int = 3, journal: Journal | None = None):
    """Queue holding one base job + ``k`` scenario records; returns
    (queue, base blob, base digest, scenario record ids, base id)."""
    blob = _base_blob()
    queue = JobQueue(journal)
    base_rec = JobRecord(id="base", strategy="sma_crossover", grid=GRID,
                         ohlcv=blob)
    queue.enqueue(base_rec)
    sids = []
    for rec in scenario_jobs(base_rec.panel_digest, k, "sma_crossover",
                             GRID, params=PARAMS):
        queue.enqueue(rec)
        sids.append(rec.id)
    return queue, blob, base_rec.panel_digest, sids, base_rec.id


def _stub(srv):
    import grpc
    channel = grpc.insecure_channel(
        f"localhost:{srv.port}", options=service.default_channel_options())
    return service.DispatcherStub(channel), channel


def test_dispatcher_coalesces_spec_batch():
    """A capability-declaring poll gets ONE carrier JobSpec for the K
    coalescable scenario records: base payload only, per-member record
    ids, and the EFFECTIVE seed (scenario_seed of host-precision params,
    int64-wrapped) — and completing the member ids drains the queue."""
    queue, blob, base_d, sids, base_id = _scn_queue(3)
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0))
    srv = DispatcherServer(disp, bind="localhost:0",
                           prune_interval_s=5.0).start()
    try:
        stub, channel = _stub(srv)
        reply = stub.RequestJobs(pb.JobsRequest(
            worker_id="w", chips=1, jobs_per_chip=8,
            accepts_scenario_batch=True))
        carriers = [j for j in reply.jobs if j.scenario_batch]
        plain = [j for j in reply.jobs if not j.scenario_batch]
        assert len(carriers) == 1 and [j.id for j in plain] == [base_id]
        car = carriers[0]
        assert car.panel_digest == base_d
        assert car.panel_bytes_len == len(blob)
        assert not car.HasField("scenario"), \
            "carrier is a batch, not a single materialized scenario"
        assert [m.id for m in car.scenario_batch] == sids
        for i, m in enumerate(car.scenario_batch):
            assert m.base_digest == base_d
            assert m.trace_id, "per-member trace for obs stitching"
            want = scn.scenario_seed(
                base_d, scn.ScenarioParams(**{**PARAMS, "seed": i}))
            assert m.seed == scn.seed_to_int64(want)
            assert scn.seed_words(m.seed) == scn.seed_words(want)
        crep = stub.CompleteJobs(pb.CompleteBatch(
            worker_id="w",
            items=[pb.CompleteItem(id=i) for i in [base_id] + sids]))
        assert crep.accepted == 4
        channel.close()
    finally:
        srv.stop()
    assert queue.drained and queue.stats()["jobs_failed"] == 0


@pytest.mark.parametrize("declare,killswitch", [(False, False),
                                                (True, True)])
def test_coalescing_falls_back_materialized(declare, killswitch,
                                            monkeypatch):
    """Both de-escalation knobs — an old worker that never declares the
    capability, and DBX_SCENARIO_FUSED=0 with a new worker — keep every
    scenario record on the materialized rung: individually dispatched
    specs with a concrete panel digest, no scenario_batch anywhere."""
    if killswitch:
        monkeypatch.setenv("DBX_SCENARIO_FUSED", "0")
    queue, blob, base_d, sids, base_id = _scn_queue(3)
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0))
    srv = DispatcherServer(disp, bind="localhost:0",
                           prune_interval_s=5.0).start()
    try:
        stub, channel = _stub(srv)
        reply = stub.RequestJobs(pb.JobsRequest(
            worker_id="w", chips=1, jobs_per_chip=8,
            accepts_scenario_batch=declare))
        assert len(reply.jobs) == 4
        assert all(not j.scenario_batch for j in reply.jobs)
        scn_specs = {j.id: j for j in reply.jobs if j.id != base_id}
        assert set(scn_specs) == set(sids)
        for j in scn_specs.values():
            assert j.HasField("scenario")
            assert j.panel_digest and j.panel_digest != base_d, \
                "materialized rung stamps the SCENARIO panel's digest"
        channel.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# End-to-end degradation ladder with the real JAX worker
# ---------------------------------------------------------------------------

class _OldCapabilityBackend(compute.JaxSweepBackend):
    """A pre-round-18 worker: never declares accepts_scenario_batch."""

    accepts_scenario_batch = False


def _drain_ladder_rung(monkeypatch, *, k=3, fused_env="1",
                       backend_cls=compute.JaxSweepBackend,
                       queue=None):
    """Drain base + k scenario jobs through a loopback dispatcher and a
    real JAX worker on one ladder rung; returns {record id: result
    bytes} plus the queue stats."""
    monkeypatch.setenv("DBX_SCENARIO_FUSED", fused_env)
    try:
        sids = None
        if queue is None:
            queue, _, _, sids, _ = _scn_queue(k)
        disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0))
        srv = DispatcherServer(disp, bind="localhost:0",
                               prune_interval_s=5.0).start()
        worker = Worker(f"localhost:{srv.port}", backend_cls(),
                        worker_id="ladder", poll_interval_s=0.01,
                        status_interval_s=0.5, jobs_per_chip=k + 1)
        wt = threading.Thread(target=worker.run, daemon=True)
        try:
            wt.start()
            _wait(lambda: queue.drained, msg="ladder rung drained")
        finally:
            worker.stop()
            wt.join(timeout=30)
            srv.stop()
        stats = queue.stats()
        # Ordered per-seed scenario results: rung-to-rung comparison must
        # key on the SPEC (seed order), not the per-queue uuid ids.
        ordered = ([disp.results[i] for i in sids] if sids is not None
                   else None)
        return dict(disp.results), stats, ordered
    finally:
        monkeypatch.delenv("DBX_SCENARIO_FUSED", raising=False)


def test_degradation_ladder_never_a_failed_job(monkeypatch, tmp_path):
    """The acceptance ladder, e2e: fused route, kill switch, and an
    old-capability worker each drain the SAME sweep with zero failed
    jobs; the two materialized rungs produce bit-identical result bytes
    and the fused rung stays within the association budget; a journal
    replay (dispatcher restart) re-coalesces and completes again."""
    k = 3
    _, st, by_seed_fused = _drain_ladder_rung(monkeypatch, k=k,
                                              fused_env="1")
    assert st["jobs_failed"] == 0 and st["jobs_completed"] == k + 1

    _, st_kill, by_seed_kill = _drain_ladder_rung(monkeypatch, k=k,
                                                  fused_env="0")
    assert st_kill["jobs_failed"] == 0
    _, st_old, by_seed_old = _drain_ladder_rung(
        monkeypatch, k=k, fused_env="1",
        backend_cls=_OldCapabilityBackend)
    assert st_old["jobs_failed"] == 0

    for i in range(k):
        # Materialized rungs: IDENTICAL code path -> identical bytes.
        assert by_seed_kill[i] == by_seed_old[i]
        m_f = wire.metrics_from_bytes(by_seed_fused[i])
        m_m = wire.metrics_from_bytes(by_seed_kill[i])
        for name in m_f._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(m_f, name)),
                np.asarray(getattr(m_m, name)), rtol=RTOL, atol=ATOL,
                err_msg=f"seed {i}:{name}")

    # Dispatcher restart: journal a fresh copy of the sweep, "crash"
    # before any take, replay it into a new queue, and drain fused —
    # the replayed records re-coalesce to bit-identical result bytes
    # (ids are fresh uuids, so compare the result multiset).
    jpath = str(tmp_path / "journal.jsonl")
    _scn_queue(k, Journal(jpath))      # journaled, never taken: "crash"
    queue2 = JobQueue()
    assert queue2.restore(jpath) == k + 1
    res_replay, st_replay, _ = _drain_ladder_rung(monkeypatch, k=k,
                                                  fused_env="1",
                                                  queue=queue2)
    assert st_replay["jobs_failed"] == 0
    assert st_replay["jobs_completed"] == k + 1
    assert sorted(v for i, v in res_replay.items() if i != "base") \
        == sorted(by_seed_fused), \
        "restart re-derives bit-identical fused results"


# ---------------------------------------------------------------------------
# Worker-side fallback when the fused launch itself fails
# ---------------------------------------------------------------------------

def test_backend_falls_back_materialized_on_fused_failure(monkeypatch):
    """A fused-launch failure (simulated compile blowup) must complete
    every spec through the in-process materialized fallback — never a
    failed job — with results matching the dense twin exactly (same
    dense kernel, host-generated panel)."""
    blob = _base_blob()
    base_d = panel_digest(blob)

    def boom(*a, **kw):
        raise RuntimeError("simulated fused-launch failure")

    monkeypatch.setattr(fused, "fused_scenario_sweep", boom)
    job = pb.JobSpec(id="carrier", strategy="sma_crossover", ohlcv=blob,
                     grid=wire.grid_to_proto(GRID), cost=0.0,
                     periods_per_year=252, panel_digest=base_d,
                     panel_bytes_len=len(blob))
    effs = []
    for i in range(2):
        p = scn.ScenarioParams(**{**PARAMS, "seed": i})
        eff = scn.scenario_seed(base_d, p)
        effs.append(eff)
        job.scenario_batch.add(
            base_digest=base_d, n_bars=p.n_bars, block=p.block,
            regimes=p.regimes, vol_scale=p.vol_scale, shock=p.shock,
            seed=scn.seed_to_int64(eff), id=f"s{i}", trace_id="")
    backend = compute.JaxSweepBackend()
    out = backend.collect(backend.submit([job]))
    got = {c.job_id: c.metrics for c in out}
    assert set(got) == {"s0", "s1"}
    base = data_mod.from_wire_bytes(blob)
    for i in range(2):
        assert got[f"s{i}"], "fallback completes with a real result"
        m = wire.metrics_from_bytes(got[f"s{i}"])
        panel = scn.generate(base,
                             scn.ScenarioParams(**{**PARAMS, "seed": i}),
                             effs[i])
        direct = sweep.jit_sweep(
            type(base)(*(np.asarray(f)[None, :] for f in panel)),
            get_strategy("sma_crossover"),
            {kk: np.asarray(vv, np.float32) for kk, vv in
             sweep.product_grid(fast=GRID["fast"],
                                slow=GRID["slow"]).items()})
        for name in m._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(m, name)),
                np.asarray(getattr(direct, name))[0], rtol=RTOL,
                atol=ATOL, err_msg=name)
