"""Native C++ core: decoder golden tests vs the Python codec, queue, registry.

Skipped wholesale when no toolchain/library is available — every consumer of
the native core degrades to pure Python, and these tests prove equivalence.
"""

import ctypes
import subprocess
import threading
import time

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.runtime import _core
from distributed_backtesting_exploration_tpu.utils import data

pytestmark = pytest.mark.skipif(
    not _core.available(), reason="native core not built/buildable")


def _one_ticker(seed=0, T=64):
    s = data.synthetic_ohlcv(1, T, seed=seed)
    return type(s)(*(f[0] for f in s))


def test_csv_decode_matches_python():
    series = _one_ticker()
    raw = data.to_csv_bytes(series)
    fields = _core.csv_decode(raw)
    text = raw.decode()
    lines = [ln for ln in text.splitlines() if ln.strip()]
    want_close = np.asarray(
        [float(ln.split(",")[3]) for ln in lines[1:]], np.float32)
    np.testing.assert_allclose(fields[3], want_close, rtol=1e-6)
    for f in fields:
        assert f.dtype == np.float32 and f.shape == (64,)


def test_csv_decode_extra_columns_and_order():
    raw = (b"date,close,volume,open,high,low\n"
           b"2024-01-01,1.5,100,1.0,2.0,0.5\n"
           b"2024-01-02,2.0,200,1.5,2.5,1.0\n")
    o, h, l, c, v = _core.csv_decode(raw)
    np.testing.assert_allclose(c, [1.5, 2.0])
    np.testing.assert_allclose(o, [1.0, 1.5])
    np.testing.assert_allclose(v, [100.0, 200.0])


def test_csv_decode_errors():
    with pytest.raises(ValueError):
        _core.csv_decode(b"")
    with pytest.raises(ValueError):
        _core.csv_decode(b"a,b,c\n1,2,3\n")
    with pytest.raises(ValueError):
        _core.csv_decode(b"open,high,low,close,volume\n1,2,x,4,5\n")


def test_wire_roundtrip_matches_python_codec():
    series = _one_ticker(seed=3)
    wire_py = data.to_wire_bytes(series)
    fields = _core.wire_decode(wire_py)
    back = data.from_wire_bytes(wire_py)
    for a, b in zip(fields, back):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_from_csv_bytes_uses_native_transparently():
    series = _one_ticker(seed=5)
    raw = data.to_csv_bytes(series)
    got = data.from_csv_bytes(raw)
    np.testing.assert_allclose(
        np.asarray(got.close), np.asarray(series.close), rtol=1e-6)


def test_native_queue_mpmc_and_close():
    q = _core.NativeQueue(capacity=4)
    items = [f"item-{i}".encode() for i in range(32)]
    got = []
    lock = threading.Lock()

    def consumer():
        while True:
            try:
                b = q.pop(timeout_ms=2000)
            except ValueError:
                return          # closed and drained
            if b is not None:
                with lock:
                    got.append(b)

    consumers = [threading.Thread(target=consumer) for _ in range(3)]
    for t in consumers:
        t.start()
    for it in items:
        assert q.push(it)
    q.close()
    for t in consumers:
        t.join(timeout=5)
    assert sorted(got) == sorted(items)


def test_native_queue_timeout():
    q = _core.NativeQueue(capacity=1)
    t0 = time.monotonic()
    assert q.pop(timeout_ms=100) is None
    assert time.monotonic() - t0 >= 0.09
    assert q.push(b"x")
    assert not q.push(b"y", timeout_ms=50)   # full -> timeout False


def test_native_registry_prune():
    reg = _core.NativeRegistry(0.1)          # 100 ms window
    assert reg.touch("w1")
    assert not reg.touch("w1")
    reg.touch("w2")
    assert reg.alive() == 2
    time.sleep(0.15)
    reg.touch("w2")                          # keep w2 alive
    assert reg.prune() == ["w1"]
    assert reg.alive() == 1


def test_native_queue_push_front():
    q = _core.NativeQueue(capacity=8)
    q.push(b"a")
    q.push(b"b")
    q.push_front(b"requeued")
    assert q.pop(0) == b"requeued"
    assert q.pop(0) == b"a"
    assert q.pop(0) == b"b"


def _native_shell_env():
    """Env for the embedded interpreter: venv site-packages (jax, grpc)
    plus the repo root on its path."""
    import os
    import sysconfig

    binary = _core._BUILD_DIR + "/dbx_worker_native"
    if not os.path.exists(binary):
        pytest.skip("dbx_worker_native not built")
    site = sysconfig.get_paths()["purelib"]
    env = dict(os.environ, PYTHONPATH=f"{_core._REPO_ROOT}:{site}")
    return binary, env


def test_native_worker_shell_selftest():
    """The embedded-CPython worker binary boots and runs the worker CLI."""
    binary, env = _native_shell_env()
    res = subprocess.run([binary, "--help"], env=env, capture_output=True,
                         timeout=120, text=True)
    assert "core selftest ok" in res.stderr
    # C++ codegen from the shared .proto: whenever the environment can
    # build it (protoc present), the native round-trip MUST run and pass —
    # accepting 'skipped' unconditionally would let a broken
    # find_package(Protobuf) silently drop the codegen path's only
    # coverage. Protobuf-less environments get the skip path.
    import shutil
    if shutil.which("protoc"):
        assert "proto selftest ok" in res.stderr
    else:
        assert "proto selftest skipped" in res.stderr
    assert "proto selftest FAILED" not in res.stderr
    assert "dbx worker" in res.stdout
    assert res.returncode == 0


def test_native_worker_shell_completes_jobs_end_to_end():
    """The C++ shell connects to a live dispatcher and completes real jobs
    through its embedded interpreter + the JAX engine — the reference's
    worker binary role end to end (reference src/worker/main.rs:27-85)."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        Dispatcher, DispatcherServer, JobQueue, PeerRegistry, parse_grid,
        synthetic_jobs)

    binary, env = _native_shell_env()
    env["JAX_PLATFORMS"] = "cpu"   # jit compiles in the subprocess; keep fast

    queue = JobQueue()
    for rec in synthetic_jobs(2, 48, "sma_crossover",
                              parse_grid("fast=3:5,slow=8:10")):
        queue.enqueue(rec)
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=120.0))
    srv = DispatcherServer(disp, bind="localhost:0",
                           prune_interval_s=0.5).start()
    try:
        res = subprocess.run(
            [binary, "--connect", f"localhost:{srv.port}", "--backend",
             "jax", "--poll-s", "0.05", "--status-s", "0.2",
             "--jobs-per-chip", "2", "--exit-after-idle", "10"],
            env=env, capture_output=True, timeout=290, text=True)
    finally:
        srv.stop()
    assert res.returncode == 0, res.stderr[-2000:]
    assert queue.drained, f"queue not drained; stats={queue.stats()}"
    s = queue.stats()
    assert s["jobs_completed"] == 2 and s["jobs_failed"] == 0
    # The completions carried real metric blocks, recorded dispatcher-side.
    assert len(disp.results) == 2
    assert all(len(block) > 0 for block in disp.results.values())


def test_wire_decode_differential_fuzz():
    """Native and Python DBX1 decoders agree on every input: valid blocks
    round-trip bit-identically, mutated/truncated/garbage blocks are
    accepted or rejected IDENTICALLY (a decoder that accepts what its twin
    rejects is how a fleet gets split-brain payload handling)."""
    rng = np.random.default_rng(123)

    def both(blob):
        try:
            py = data.from_wire_bytes(blob)
            py = [np.asarray(f) for f in py]
        except ValueError:
            py = None
        try:
            nat = list(_core.wire_decode(blob))
        except ValueError:
            nat = None
        return py, nat

    for trial in range(60):
        T = int(rng.integers(0, 40))
        scale = np.float32(10.0 ** rng.integers(-3, 4))
        s = data.OHLCV(*(
            (rng.standard_normal(T) * scale).astype(np.float32)
            for _ in range(5)))
        blob = data.to_wire_bytes(s)
        py, nat = both(blob)
        assert py is not None and nat is not None, f"trial {trial}: rejected valid block"
        for a, b in zip(nat, py):
            np.testing.assert_array_equal(a, b)

        mutations = [
            blob[:int(rng.integers(0, len(blob) + 1))],     # truncation
            b"XXXX" + blob[4:],                             # magic corrupt
            blob[:4] + rng.bytes(4) + blob[8:],             # length corrupt
            rng.bytes(int(rng.integers(0, 64))),            # garbage
        ]
        # Flip one random byte (may or may not keep the block valid).
        if len(blob) > 8:
            i = int(rng.integers(0, len(blob)))
            flipped = bytearray(blob)
            flipped[i] ^= 0xFF
            mutations.append(bytes(flipped))
        for mi, mut in enumerate(mutations):
            py, nat = both(mut)
            assert (py is None) == (nat is None), (
                f"trial {trial} mutation {mi}: python "
                f"{'accepted' if py is not None else 'rejected'} but native "
                f"did the opposite (len={len(mut)})")
            if py is not None:
                for a, b in zip(nat, py):
                    np.testing.assert_array_equal(a, b)

    # The length-prefix overflow edge: a huge T must be rejected by both
    # (size arithmetic must not wrap).
    import struct as _struct
    for T_evil in (0xFFFFFFFF, 0x80000000, 0x0FFFFFFF):
        evil = b"DBX1" + _struct.pack("<I", T_evil) + b"\x00" * 64
        py, nat = both(evil)
        assert py is None and nat is None


def test_csv_decode_differential_on_valid_inputs():
    """On well-formed CSVs the native decoder and the pure-Python parser
    (the semantic reference) agree to f32 round-off."""
    rng = np.random.default_rng(7)
    for trial in range(20):
        T = int(rng.integers(1, 30))
        s = data.OHLCV(*(
            (rng.uniform(0.001, 1000.0, T)).astype(np.float32)
            for _ in range(5)))
        raw = data.to_csv_bytes(s)
        nat = _core.csv_decode(raw)
        # Force the pure-Python path via a non-f32 dtype, then cast.
        py = data.from_csv_bytes(raw, dtype=np.float64)
        for a, b in zip(nat, py):
            np.testing.assert_allclose(a, np.asarray(b, np.float32),
                                       rtol=1e-6, atol=0)
