"""Native C++ core: decoder golden tests vs the Python codec, queue, registry.

Skipped wholesale when no toolchain/library is available — every consumer of
the native core degrades to pure Python, and these tests prove equivalence.
"""

import ctypes
import subprocess
import threading
import time

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.runtime import _core
from distributed_backtesting_exploration_tpu.utils import data

pytestmark = pytest.mark.skipif(
    not _core.available(), reason="native core not built/buildable")


def _one_ticker(seed=0, T=64):
    s = data.synthetic_ohlcv(1, T, seed=seed)
    return type(s)(*(f[0] for f in s))


def test_csv_decode_matches_python():
    series = _one_ticker()
    raw = data.to_csv_bytes(series)
    fields = _core.csv_decode(raw)
    text = raw.decode()
    lines = [ln for ln in text.splitlines() if ln.strip()]
    want_close = np.asarray(
        [float(ln.split(",")[3]) for ln in lines[1:]], np.float32)
    np.testing.assert_allclose(fields[3], want_close, rtol=1e-6)
    for f in fields:
        assert f.dtype == np.float32 and f.shape == (64,)


def test_csv_decode_extra_columns_and_order():
    raw = (b"date,close,volume,open,high,low\n"
           b"2024-01-01,1.5,100,1.0,2.0,0.5\n"
           b"2024-01-02,2.0,200,1.5,2.5,1.0\n")
    o, h, l, c, v = _core.csv_decode(raw)
    np.testing.assert_allclose(c, [1.5, 2.0])
    np.testing.assert_allclose(o, [1.0, 1.5])
    np.testing.assert_allclose(v, [100.0, 200.0])


def test_csv_decode_errors():
    with pytest.raises(ValueError):
        _core.csv_decode(b"")
    with pytest.raises(ValueError):
        _core.csv_decode(b"a,b,c\n1,2,3\n")
    with pytest.raises(ValueError):
        _core.csv_decode(b"open,high,low,close,volume\n1,2,x,4,5\n")


def test_wire_roundtrip_matches_python_codec():
    series = _one_ticker(seed=3)
    wire_py = data.to_wire_bytes(series)
    fields = _core.wire_decode(wire_py)
    back = data.from_wire_bytes(wire_py)
    for a, b in zip(fields, back):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_from_csv_bytes_uses_native_transparently():
    series = _one_ticker(seed=5)
    raw = data.to_csv_bytes(series)
    got = data.from_csv_bytes(raw)
    np.testing.assert_allclose(
        np.asarray(got.close), np.asarray(series.close), rtol=1e-6)


def test_native_queue_mpmc_and_close():
    q = _core.NativeQueue(capacity=4)
    items = [f"item-{i}".encode() for i in range(32)]
    got = []
    lock = threading.Lock()

    def consumer():
        while True:
            try:
                b = q.pop(timeout_ms=2000)
            except ValueError:
                return          # closed and drained
            if b is not None:
                with lock:
                    got.append(b)

    consumers = [threading.Thread(target=consumer) for _ in range(3)]
    for t in consumers:
        t.start()
    for it in items:
        assert q.push(it)
    q.close()
    for t in consumers:
        t.join(timeout=5)
    assert sorted(got) == sorted(items)


def test_native_queue_timeout():
    q = _core.NativeQueue(capacity=1)
    t0 = time.monotonic()
    assert q.pop(timeout_ms=100) is None
    assert time.monotonic() - t0 >= 0.09
    assert q.push(b"x")
    assert not q.push(b"y", timeout_ms=50)   # full -> timeout False


def test_native_registry_prune():
    reg = _core.NativeRegistry(0.1)          # 100 ms window
    assert reg.touch("w1")
    assert not reg.touch("w1")
    reg.touch("w2")
    assert reg.alive() == 2
    time.sleep(0.15)
    reg.touch("w2")                          # keep w2 alive
    assert reg.prune() == ["w1"]
    assert reg.alive() == 1


def test_native_queue_push_front():
    q = _core.NativeQueue(capacity=8)
    q.push(b"a")
    q.push(b"b")
    q.push_front(b"requeued")
    assert q.pop(0) == b"requeued"
    assert q.pop(0) == b"a"
    assert q.pop(0) == b"b"


def test_native_worker_shell_selftest():
    """The embedded-CPython worker binary boots and runs the worker CLI."""
    binary = _core._BUILD_DIR + "/dbx_worker_native"
    import os
    import sysconfig
    if not os.path.exists(binary):
        pytest.skip("dbx_worker_native not built")
    # The embedded interpreter needs the venv's site-packages (jax, grpc)
    # plus the repo root on its path.
    site = sysconfig.get_paths()["purelib"]
    env = dict(os.environ, PYTHONPATH=f"{_core._REPO_ROOT}:{site}")
    res = subprocess.run([binary, "--help"], env=env, capture_output=True,
                         timeout=120, text=True)
    assert "core selftest ok" in res.stderr
    assert "dbx worker" in res.stdout
    assert res.returncode == 0
