"""Epilogue substrate parity: single-pass carry scan vs ladder fallback.

The "scan" epilogue (default) replaces the metrics tail's two full-T shift
ladders and the band machines' 3-state compose ladder with ONE sequential
pass over T-blocks carrying state between blocks (ops/fused.py
`_equity_scan` / `_compose3_path`). The contract these tests pin, per
kernel, on CPU interpret mode:

- Position paths are BIT-IDENTICAL across substrates (the compose scan is
  pure selection — no float arithmetic), so every position-derived metric
  (sharpe, sortino, volatility, hit_rate, n_trades, turnover) must be
  bit-exact between substrates.
- The equity-path metrics (max_drawdown, total_return, cagr) may differ by
  the f32 summation-association class only (~1 ULP): the blocked cumsum
  sums the same values in a different tree than the full-T ladder. They
  must agree to tight float tolerance, never a knife-edge flip (flips come
  from positions, which are exact).

Covered for all 14 fused kernels, including unaligned T (padding rows in
the final scan block), ragged per-ticker ``t_real``, and multi-T-block
shapes (pinned ``scan:<B>`` schedules of 3 blocks per kernel plus a
17-block deep-chain case on the flagship). The fused-vs-generic
golden tests in test_fused.py run under the shipped scan default, gating
the scan substrate against the semantics-defining path as well.

(Named ``test_z_*`` deliberately: tier-1 runs under a fixed wall budget
that can truncate the alphabetical tail on slow boxes — additions must be
the tests a truncation drops, never the seed suite.)
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.ops import fused
from distributed_backtesting_exploration_tpu.utils import data

# Metrics derived from positions / plain sums: bit-exact across substrates.
EXACT_FIELDS = ("sharpe", "sortino", "volatility", "hit_rate", "n_trades",
                "turnover")
# Metrics through the equity path: blocked-vs-full summation order differs.
PATH_FIELDS = ("max_drawdown", "total_return", "cagr")


def _assert_substrate_parity(run, name, scan="scan:32"):
    # "scan:32" pins a REAL multi-block schedule (~4 blocks at these T):
    # the plain "scan" default re-blocks to a single block in interpret
    # mode for test-wall economy (ops/fused.py `_interp_epilogue`), which
    # would not drive the carries across block boundaries.
    a = run(scan)
    b = run("ladder")
    for field in EXACT_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, field)), np.asarray(getattr(b, field)),
            err_msg=f"{name}.{field} (position/sum metrics must be "
                    "bit-exact across epilogue substrates)")
    for field in PATH_FIELDS:
        np.testing.assert_allclose(
            np.asarray(getattr(a, field)), np.asarray(getattr(b, field)),
            rtol=1e-5, atol=1e-6,
            err_msg=f"{name}.{field} (equity-path metrics carry only "
                    "f32 association rounding)")


def _panel(n, T, seed):
    ohlcv = data.synthetic_ohlcv(n, T, seed=seed)
    return type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))


def _ragged_panel(n, T, lengths, seed):
    """Panel honoring the ragged contract: bars at ``t >= t_real`` hold
    the last real value (every serving path pads repeat-last —
    `_stack_field_ragged` / `pad_and_stack`). The kernels' padding
    discipline REQUIRES this: pad bars then earn exactly zero return, so
    plain reductions over T_pad equal the unpadded ones. Junk data beyond
    ``t_real`` is outside the contract (the substrates would read it
    through different reductions)."""
    ohlcv = data.synthetic_ohlcv(n, T, seed=seed)
    fields = []
    for f in ohlcv:
        a = np.asarray(f).copy()
        for i, t in enumerate(lengths):
            a[i, t:] = a[i, t - 1]
        fields.append(jnp.asarray(a))
    return type(ohlcv)(*fields)


_W = np.asarray([10.0, 17.0, 26.0], np.float32)
_K = np.asarray([0.8, 1.5, 2.2], np.float32)


def _kernel_cases(panel, t_real):
    """One callable per fused kernel: (name, run(epilogue))."""
    c, h, lo, v = panel.close, panel.high, panel.low, panel.volume
    fa = np.asarray([3.0, 5.0, 8.0], np.float32)
    sl = np.asarray([13.0, 21.0, 34.0], np.float32)
    sig = np.asarray([4.0, 9.0, 6.0], np.float32)
    kw = dict(t_real=t_real, cost=1e-3)
    return [
        ("sma", lambda e: fused.fused_sma_sweep(c, fa, sl, epilogue=e,
                                                **kw)),
        ("bollinger", lambda e: fused.fused_bollinger_sweep(
            c, _W, _K, epilogue=e, **kw)),
        ("bollinger_touch", lambda e: fused.fused_bollinger_touch_sweep(
            c, _W, _K, epilogue=e, **kw)),
        ("momentum", lambda e: fused.fused_momentum_sweep(
            c, _W, epilogue=e, **kw)),
        ("donchian", lambda e: fused.fused_donchian_sweep(
            c, _W, epilogue=e, **kw)),
        ("donchian_hl", lambda e: fused.fused_donchian_hl_sweep(
            c, h, lo, _W, epilogue=e, **kw)),
        ("rsi", lambda e: fused.fused_rsi_sweep(
            c, _W, np.asarray([15.0, 20.0, 25.0], np.float32),
            epilogue=e, **kw)),
        ("stochastic", lambda e: fused.fused_stochastic_sweep(
            c, h, lo, _W, np.asarray([20.0, 25.0, 30.0], np.float32),
            epilogue=e, **kw)),
        ("keltner", lambda e: fused.fused_keltner_sweep(
            c, h, lo, _W, _K, epilogue=e, **kw)),
        ("macd", lambda e: fused.fused_macd_sweep(
            c, fa, sl, sig, epilogue=e, **kw)),
        ("trix", lambda e: fused.fused_trix_sweep(
            c, fa, sig, epilogue=e, **kw)),
        ("vwap", lambda e: fused.fused_vwap_sweep(
            c, v, _W, _K, epilogue=e, **kw)),
        ("obv", lambda e: fused.fused_obv_sweep(
            c, v, _W, epilogue=e, **kw)),
    ]


_UNIFORM = _panel(2, 96, seed=101)
_CASE_NAMES = [n for n, _ in _kernel_cases(_UNIFORM, None)]


# One uniform-history (t_real=None) spot check on the flagship pins the
# scan epilogue's no-ragged-mask path; the ragged+unaligned
# parametrization below walks ALL kernels — every additional uniform
# repeat is interpret-mode wall (~4-7s each) for no new code path, and
# tier-1 runs under a fixed budget.
@pytest.mark.parametrize("name", ["sma"])
def test_epilogue_parity_uniform(name):
    cases = dict(_kernel_cases(_UNIFORM, None))
    _assert_substrate_parity(cases[name], name)


@pytest.mark.parametrize("name", _CASE_NAMES)
def test_epilogue_parity_unaligned_T_ragged(name):
    # T=84 (pad rows land inside the final scan blocks) + ragged
    # per-ticker real lengths: the carries must freeze at each ticker's
    # tr under the padding discipline. (Substrate-vs-substrate parity is
    # assertion-by-construction, not golden values, so the smallest T
    # that still crosses scan:32 block boundaries for every length is
    # the right tier-1 shape.)
    t_real = np.asarray([84, 64, 44], np.int32)
    panel = _ragged_panel(3, 84, t_real, seed=103)
    cases = dict(_kernel_cases(panel, t_real))
    _assert_substrate_parity(cases[name], name)


def test_epilogue_parity_pairs_ragged():
    # The 14th kernel: pairs shares _metrics_pack and the band compose.
    # (Ragged-only: the uniform flavor adds no substrate path beyond it,
    # and tier-1 runs under a fixed wall budget.)
    t_real = np.asarray([96, 64], np.int32)
    closes = jnp.asarray(np.concatenate([
        np.asarray(_ragged_panel(2, 96, t_real, seed=109).close),
        np.asarray(_ragged_panel(2, 96, t_real, seed=110).close)]))
    y, x = closes[:2], closes[2:]
    lb = np.asarray([10.0, 20.0], np.float32)
    ze = np.asarray([1.0, 1.5], np.float32)
    _assert_substrate_parity(
        lambda e: fused.fused_pairs_sweep(y, x, lb, ze, t_real=t_real,
                                          cost=1e-3, epilogue=e),
        "pairs_ragged")


def test_scan_block_override_is_equivalent():
    # "scan:<B>" pins the T-block size; positions are exact for any B, so
    # the exact fields must match the default scan bit-for-bit and the
    # path fields to association tolerance.
    c = _UNIFORM.close
    fa = np.asarray([3.0, 5.0], np.float32)
    sl = np.asarray([13.0, 21.0], np.float32)
    a = fused.fused_sma_sweep(c, fa, sl, cost=1e-3, epilogue="scan")
    b = fused.fused_sma_sweep(c, fa, sl, cost=1e-3, epilogue="scan:64")
    for field in EXACT_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(a, field)), np.asarray(getattr(b, field)),
            err_msg=field)
    for field in PATH_FIELDS:
        np.testing.assert_allclose(
            np.asarray(getattr(a, field)), np.asarray(getattr(b, field)),
            rtol=1e-5, atol=1e-6, err_msg=field)


def test_deep_block_chain_sma():
    # The production TPU default is an 8-row block (17 chained carries
    # at this T). Drive that depth once on the flagship kernel so long
    # carry chains (not just one boundary crossing) are covered.
    t_real = np.asarray([136, 90], np.int32)
    panel = _ragged_panel(2, 136, t_real, seed=113)
    fa = np.asarray([3.0, 5.0], np.float32)
    sl = np.asarray([13.0, 21.0], np.float32)
    _assert_substrate_parity(
        lambda e: fused.fused_sma_sweep(panel.close, fa, sl, t_real=t_real,
                                        cost=1e-3, epilogue=e),
        "sma_deep", scan="scan:8")


def test_single_block_scan_is_bit_identical_to_ladder():
    # With T_pad inside ONE scan block the carry path degenerates
    # (carry = 0, peak carry = -inf): every metric must be bit-identical
    # to the ladder substrate except total_return/cagr, whose final-sum
    # read differs in association even single-block (documented).
    c = _panel(2, 64, seed=111).close
    fa = np.asarray([3.0, 5.0], np.float32)
    sl = np.asarray([13.0, 21.0], np.float32)
    a = fused.fused_sma_sweep(c, fa, sl, cost=1e-3, epilogue="scan:64")
    b = fused.fused_sma_sweep(c, fa, sl, cost=1e-3, epilogue="ladder")
    for field in EXACT_FIELDS + ("max_drawdown",):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, field)), np.asarray(getattr(b, field)),
            err_msg=field)


def test_epilogue_env_default(monkeypatch):
    # DBX_EPILOGUE routes the default exactly like the explicit argument.
    c = _UNIFORM.close
    fa = np.asarray([3.0, 5.0], np.float32)
    sl = np.asarray([13.0, 21.0], np.float32)
    explicit = fused.fused_sma_sweep(c, fa, sl, cost=1e-3,
                                     epilogue="ladder")
    monkeypatch.setenv("DBX_EPILOGUE", "ladder")
    via_env = fused.fused_sma_sweep(c, fa, sl, cost=1e-3)
    for field in explicit._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(explicit, field)),
            np.asarray(getattr(via_env, field)), err_msg=field)


def test_epilogue_rejects_bad_values(monkeypatch):
    for bad in ("scans", "scan:7", "scan:0", "scan:-8", "scan:x", "lad"):
        with pytest.raises(ValueError, match="epilogue"):
            fused._resolve_epilogue(bad)
        monkeypatch.setenv("DBX_EPILOGUE", bad)
        with pytest.raises(ValueError, match="epilogue"):
            fused.fused_sma_sweep(
                jnp.ones((1, 64)) + jnp.arange(64.0),
                np.asarray([3.0], np.float32),
                np.asarray([10.0], np.float32))
    monkeypatch.delenv("DBX_EPILOGUE")
    assert fused._resolve_epilogue(None) == "scan"
    assert fused._resolve_epilogue("scan:16") == "scan:16"


def test_scan_block_schedule_bounds_unroll():
    # The default schedule starts at one sublane tile and doubles until
    # the unrolled block count fits the Mosaic program-size bound.
    assert fused._scan_block(200, "scan") == 8
    assert fused._scan_block(2048, "scan") == 8
    assert fused._scan_block(2056, "scan") == 16
    assert fused._scan_block(8192, "scan") == 32
    assert fused._scan_block(8192, "scan:8") == 8


def test_substrate_defaults_and_route_substrates(monkeypatch):
    monkeypatch.delenv("DBX_EPILOGUE", raising=False)
    monkeypatch.delenv("DBX_SMA_TABLE", raising=False)
    d = fused.substrate_defaults()
    assert d["epilogue"] == "scan"
    assert d["table_sma"] == "inline"
    assert d["table_don"] == "hbm"       # measured wash, default stays hbm
    assert fused.route_substrates("sma_crossover") == {
        "epilogue": "scan", "table": "inline"}
    # strategies without a table knob always stream the XLA table
    assert fused.route_substrates("keltner")["table"] == "hbm"
    assert fused.route_substrates("pairs")["table"] == "hbm"
    monkeypatch.setenv("DBX_EPILOGUE", "ladder")
    monkeypatch.setenv("DBX_SMA_TABLE", "hbm")
    d = fused.substrate_defaults()
    assert d["epilogue"] == "ladder" and d["table_sma"] == "hbm"
