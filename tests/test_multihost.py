"""Real multi-process jax.distributed bring-up (VERDICT r2 #8).

Two OS processes, each with 4 virtual CPU devices, form one 8-device JAX
slice through a loopback coordinator: ``multihost.initialize`` runs its
*distributed* path (not the single-process no-op), ``host_shard`` splits a
work list across the processes, and a ticker-sharded sweep runs over the
global mesh with each process verifying its addressable shard against a
locally-computed reference.
"""

import os
import socket
import subprocess
import sys

import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import os, sys
pid = int(sys.argv[1]); coord = sys.argv[2]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[3])
import numpy as np
import jax, jax.numpy as jnp
# This environment's sitecustomize pins jax_platforms="axon,cpu" via
# jax.config before user code, so the platform must be re-pinned through the
# config, not the env var (see tests/conftest.py). multihost.initialize
# enables gloo CPU collectives itself when the platform is cpu.
jax.config.update("jax_platforms", "cpu")
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_backtesting_exploration_tpu.parallel import (
    multihost, sharding, sweep as sweep_mod)
from distributed_backtesting_exploration_tpu.models import base
from distributed_backtesting_exploration_tpu.utils import data as data_mod

n = multihost.initialize(coord, num_processes=2, process_id=pid)
assert n == 2, n
assert jax.process_count() == 2
assert jax.local_device_count() == 4 and jax.device_count() == 8

# host_shard: disjoint halves of an 8-item work list.
sl = multihost.host_shard(8)
assert (sl.start, sl.stop) == ((0, 4) if pid == 0 else (4, 8)), sl

# Tiny sweep sharded over the GLOBAL 8-device mesh: every process
# contributes its local ticker rows and verifies its addressable shard.
mesh = sharding.make_mesh()
assert mesh.devices.size == 8
axis = mesh.axis_names[0]
ohlcv_np = data_mod.synthetic_ohlcv(8, 64, seed=0)
row_sh = NamedSharding(mesh, P(axis, None))
rep_sh = NamedSharding(mesh, P())

def global_rows(x):
    return jax.make_array_from_process_local_data(row_sh, np.asarray(x)[sl])

def replicated(x):
    return jax.make_array_from_process_local_data(rep_sh, np.asarray(x))

panel = type(ohlcv_np)(*(global_rows(f) for f in ohlcv_np))
grid_np = sweep_mod.product_grid(
    fast=np.asarray([3.0, 5.0], np.float32),
    slow=np.asarray([10.0, 20.0], np.float32))
grid = {k: replicated(v) for k, v in grid_np.items()}
strategy = base.get_strategy("sma_crossover")
m = sharding.sharded_sweep(mesh, panel, strategy, grid, cost=1e-3)

# Local reference for this process's ticker rows.
local_panel = type(ohlcv_np)(*(jnp.asarray(np.asarray(f)[sl])
                               for f in ohlcv_np))
want = sweep_mod.jit_sweep(local_panel, strategy,
                           {k: jnp.asarray(v) for k, v in grid_np.items()},
                           cost=1e-3)
got_rows = sorted(
    (s.index[0].start or 0, np.asarray(s.data))
    for s in m.sharpe.addressable_shards)
got = np.concatenate([r for _, r in got_rows], axis=0)
np.testing.assert_allclose(got, np.asarray(want.sharpe), rtol=1e-5,
                           atol=1e-6)

# A worker process on a multi-host slice must advertise and mesh over its
# OWN chips only (it cannot device_put to another host's devices); the
# slice-wide scale-out axis is the dispatcher's job-level DP.
from distributed_backtesting_exploration_tpu.rpc import compute
backend = compute.JaxSweepBackend(use_fused=False, use_mesh=True)
assert backend.chips == 4, backend.chips
assert backend._mesh is not None and backend._mesh.devices.size == 4
assert all(d.process_index == jax.process_index()
           for d in backend._mesh.devices.flat)
print("MULTIHOST_OK", pid, flush=True)
"""


_SLICE_CHILD = r"""
import os, sys
pid = int(sys.argv[1]); coord = sys.argv[2]; dispatcher = sys.argv[4]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[3])
import jax
jax.config.update("jax_platforms", "cpu")
from distributed_backtesting_exploration_tpu.parallel import multihost
from distributed_backtesting_exploration_tpu.rpc.slice_worker import (
    SliceWorker)

n = multihost.initialize(coord, num_processes=2, process_id=pid)
assert n == 2 and jax.device_count() == 8
w = SliceWorker(dispatcher, worker_id="slice-under-test",
                poll_interval_s=0.1, jobs_per_chip=1)
assert w.chips == 8
w.run(max_idle_polls=20)
print("SLICE_OK", pid, w.jobs_completed, flush=True)
print("SLICE_TS", pid, len(w._ts_fns), flush=True)
"""


@pytest.mark.slow   # 2-process jax.distributed slice: minutes of wall on
                    # CPU-only boxes (gloo collectives + fresh-jax children)
def test_slice_worker_drains_live_dispatcher(tmp_path):
    """VERDICT r3 #8 — the two proven halves joined: a 2-process
    jax.distributed worker (4+4 virtual devices, ONE 8-device mesh)
    serves a LIVE dispatcher as one logical worker. The slice drains the
    queue and every job's stored DBXM block matches the direct
    single-device sweep."""
    import numpy as np

    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from distributed_backtesting_exploration_tpu.models import base
    from distributed_backtesting_exploration_tpu.parallel import sweep
    from distributed_backtesting_exploration_tpu.rpc import wire
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        Dispatcher, DispatcherServer, JobQueue, PeerRegistry,
        synthetic_jobs)
    from distributed_backtesting_exploration_tpu.utils import data

    grid = {"fast": np.float32([3.0, 5.0]), "slow": np.float32([10.0, 20.0])}
    queue = JobQueue()
    recs = synthetic_jobs(6, 64, "sma_crossover", grid, cost=1e-3, seed=13)
    for rec in recs:
        queue.enqueue(rec)
    # A two-legged job the slice worker does NOT implement: it must be
    # completed empty with a loud error, not crash the slice or
    # requeue-loop forever.
    pair_rec = synthetic_jobs(
        1, 64, "pairs", {"lookback": np.float32([8.0]),
                         "z_entry": np.float32([1.0])}, seed=14)[0]
    queue.enqueue(pair_rec)
    # A long-context job (bars above the shrunk DBX_SLICE_LC_CAP, and NOT
    # divisible by the 8-chip mesh so the t_real pad contract is live):
    # the slice must shard its BAR axis over the global mesh instead of
    # replicating pad rows on every chip. Momentum keeps parity tight —
    # its signal compares raw closes, so positions are exact.
    lc_grid = {"lookback": np.float32([10.0, 20.0])}
    lc_rec = synthetic_jobs(1, 201, "momentum", lc_grid, cost=1e-3,
                            seed=15)[0]
    queue.enqueue(lc_rec)
    results = tmp_path / "results"
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=120.0),
                      results_dir=str(results))
    srv = DispatcherServer(disp, bind="localhost:0").start()

    with socket.socket() as s:
        s.bind(("localhost", 0))
        coord = f"localhost:{s.getsockname()[1]}"
    script = tmp_path / "slice_child.py"
    script.write_text(_SLICE_CHILD)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["DBX_SLICE_LC_CAP"] = "96"   # shrink the long-context trigger
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), coord, _REPO_ROOT,
             f"localhost:{srv.port}"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=280) for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        srv.stop()
        pytest.fail("slice worker children timed out")
    srv.stop()
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{err[-3000:]}"
    assert "SLICE_OK 0 8" in outs[0][0]   # 6 sweeps + empty pairs + lc
    assert "SLICE_OK 1" in outs[1][0]
    # The long-context job compiled a time-sharded program on BOTH
    # processes (the SPMD route ran slice-wide, not leader-only).
    assert "SLICE_TS 0 1" in outs[0][0]
    assert "SLICE_TS 1 1" in outs[1][0]
    assert queue.drained
    s = queue.stats()
    assert s["jobs_completed"] == 8 and s["jobs_failed"] == 0
    # The unsupported pairs job completed with an EMPTY block (which the
    # dispatcher does not persist — no stored result, but no requeue loop).
    assert not (results / f"{pair_rec.id}.dbxm").exists()

    # Per-job parity: each stored DBXM block equals the direct sweep.
    flat = sweep.product_grid(
        **{k: jnp.asarray(v) for k, v in grid.items()})
    strat = base.get_strategy("sma_crossover")
    for rec in recs:
        blob = (results / f"{rec.id}.dbxm").read_bytes()
        got = wire.metrics_from_bytes(blob)
        series = data.from_wire_bytes(rec.ohlcv)
        panel = type(series)(*(jnp.asarray(np.asarray(f))[None, :]
                               for f in series))
        want = sweep.jit_sweep(panel, strat, dict(flat), cost=1e-3)
        for name in want._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(got, name)),
                np.asarray(getattr(want, name))[0],
                rtol=1e-4, atol=1e-5, err_msg=name)

    # Long-context job parity: the time-sharded slice result equals the
    # direct single-device sweep on the same series.
    lc_blob = (results / f"{lc_rec.id}.dbxm").read_bytes()
    lc_got = wire.metrics_from_bytes(lc_blob)
    lc_series = data.from_wire_bytes(lc_rec.ohlcv)
    lc_panel = type(lc_series)(*(jnp.asarray(np.asarray(f))[None, :]
                                 for f in lc_series))
    lc_want = sweep.jit_sweep(
        lc_panel, base.get_strategy("momentum"),
        dict(sweep.product_grid(
            **{k: jnp.asarray(v) for k, v in lc_grid.items()})),
        cost=1e-3)
    for name in lc_want._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(lc_got, name)),
            np.asarray(getattr(lc_want, name))[0],
            rtol=5e-4, atol=5e-5, err_msg=f"long-context/{name}")


@pytest.mark.slow   # 2-process jax.distributed slice (see above)
def test_two_process_distributed_sharded_sweep(tmp_path):
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    coord = f"localhost:{port}"
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), coord, _REPO_ROOT],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=280)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost children timed out")
    for rc, out, err in outs:
        assert rc == 0, f"child failed:\n{err[-3000:]}"
    assert "MULTIHOST_OK 0" in outs[0][1]
    assert "MULTIHOST_OK 1" in outs[1][1]
