"""Real multi-process jax.distributed bring-up (VERDICT r2 #8).

Two OS processes, each with 4 virtual CPU devices, form one 8-device JAX
slice through a loopback coordinator: ``multihost.initialize`` runs its
*distributed* path (not the single-process no-op), ``host_shard`` splits a
work list across the processes, and a ticker-sharded sweep runs over the
global mesh with each process verifying its addressable shard against a
locally-computed reference.
"""

import os
import socket
import subprocess
import sys

import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import os, sys
pid = int(sys.argv[1]); coord = sys.argv[2]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, sys.argv[3])
import numpy as np
import jax, jax.numpy as jnp
# This environment's sitecustomize pins jax_platforms="axon,cpu" via
# jax.config before user code, so the platform must be re-pinned through the
# config, not the env var (see tests/conftest.py). multihost.initialize
# enables gloo CPU collectives itself when the platform is cpu.
jax.config.update("jax_platforms", "cpu")
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_backtesting_exploration_tpu.parallel import (
    multihost, sharding, sweep as sweep_mod)
from distributed_backtesting_exploration_tpu.models import base
from distributed_backtesting_exploration_tpu.utils import data as data_mod

n = multihost.initialize(coord, num_processes=2, process_id=pid)
assert n == 2, n
assert jax.process_count() == 2
assert jax.local_device_count() == 4 and jax.device_count() == 8

# host_shard: disjoint halves of an 8-item work list.
sl = multihost.host_shard(8)
assert (sl.start, sl.stop) == ((0, 4) if pid == 0 else (4, 8)), sl

# Tiny sweep sharded over the GLOBAL 8-device mesh: every process
# contributes its local ticker rows and verifies its addressable shard.
mesh = sharding.make_mesh()
assert mesh.devices.size == 8
axis = mesh.axis_names[0]
ohlcv_np = data_mod.synthetic_ohlcv(8, 64, seed=0)
row_sh = NamedSharding(mesh, P(axis, None))
rep_sh = NamedSharding(mesh, P())

def global_rows(x):
    return jax.make_array_from_process_local_data(row_sh, np.asarray(x)[sl])

def replicated(x):
    return jax.make_array_from_process_local_data(rep_sh, np.asarray(x))

panel = type(ohlcv_np)(*(global_rows(f) for f in ohlcv_np))
grid_np = sweep_mod.product_grid(
    fast=np.asarray([3.0, 5.0], np.float32),
    slow=np.asarray([10.0, 20.0], np.float32))
grid = {k: replicated(v) for k, v in grid_np.items()}
strategy = base.get_strategy("sma_crossover")
m = sharding.sharded_sweep(mesh, panel, strategy, grid, cost=1e-3)

# Local reference for this process's ticker rows.
local_panel = type(ohlcv_np)(*(jnp.asarray(np.asarray(f)[sl])
                               for f in ohlcv_np))
want = sweep_mod.jit_sweep(local_panel, strategy,
                           {k: jnp.asarray(v) for k, v in grid_np.items()},
                           cost=1e-3)
got_rows = sorted(
    (s.index[0].start or 0, np.asarray(s.data))
    for s in m.sharpe.addressable_shards)
got = np.concatenate([r for _, r in got_rows], axis=0)
np.testing.assert_allclose(got, np.asarray(want.sharpe), rtol=1e-5,
                           atol=1e-6)

# A worker process on a multi-host slice must advertise and mesh over its
# OWN chips only (it cannot device_put to another host's devices); the
# slice-wide scale-out axis is the dispatcher's job-level DP.
from distributed_backtesting_exploration_tpu.rpc import compute
backend = compute.JaxSweepBackend(use_fused=False, use_mesh=True)
assert backend.chips == 4, backend.chips
assert backend._mesh is not None and backend._mesh.devices.size == 4
assert all(d.process_index == jax.process_index()
           for d in backend._mesh.devices.flat)
print("MULTIHOST_OK", pid, flush=True)
"""


def test_two_process_distributed_sharded_sweep(tmp_path):
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    coord = f"localhost:{port}"
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), coord, _REPO_ROOT],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=280)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost children timed out")
    for rc, out, err in outs:
        assert rc == 0, f"child failed:\n{err[-3000:]}"
    assert "MULTIHOST_OK 0" in outs[0][1]
    assert "MULTIHOST_OK 1" in outs[1][1]
