"""Substrate autotuner + schedule registry + fleet compile cache (tune/).

The round-11 contracts these tests pin:

- **Determinism**: the registry's serialization is a pure function of the
  measurements (same entries -> same bytes, journal and wire), and merge
  conflict resolution converges regardless of gossip order.
- **Persistence**: journal restore round-trips; a corrupt line is
  skipped AND counted, never fatal.
- **Precedence**: for EVERY substrate knob, explicit arg > env > tuned
  schedule > hardcoded default — an env override always beats a tuned
  schedule, and an invalid tuned value silently degrades to the default
  (tuning must never fail a job).
- **Numerics**: a tuned substrate flip can never change positions — the
  epilogue substrate contract of test_z_epilogue holds when the flip
  arrives via a tuned schedule instead of an arg/env knob.
- **Fleet exchange**: schedule entries gossip worker -> dispatcher ->
  worker over the real in-process gRPC loop, and a cold worker's compile
  cache installs a peer's entry byte-identically
  (dbx_compile_cache_hits_total{source="fleet"} > 0).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu import obs, tune
from distributed_backtesting_exploration_tpu.ops import fused
from distributed_backtesting_exploration_tpu.rpc import (
    backtesting_pb2 as pb, compute, service, wire)
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    Dispatcher, DispatcherServer, JobQueue, PeerRegistry, parse_grid,
    synthetic_jobs)
from distributed_backtesting_exploration_tpu.rpc.worker import Worker
from distributed_backtesting_exploration_tpu.tune import registry as treg


def _wait(pred, timeout=20.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# Shape buckets
# ---------------------------------------------------------------------------

def test_shape_bucket_bounded_pow2_rails():
    assert tune.shape_bucket(1260, 2000) == "t2048_p2048"
    assert tune.shape_bucket(64, 1) == "t64_p128"
    assert tune.shape_bucket(1, 1) == "t64_p128"
    # Clamped: arbitrarily large shapes share the top rail (the label set
    # stays finite — the obs-cardinality contract).
    assert tune.shape_bucket(10**9, 10**9) == "t65536_p4096"
    all_buckets = {tune.shape_bucket(t, p)
                   for t in (1, 100, 5000, 10**7)
                   for p in (1, 300, 10**6)}
    assert len(all_buckets) <= len(treg._T_BUCKETS) * len(treg._P_BUCKETS)


# ---------------------------------------------------------------------------
# Registry: determinism, persistence, corruption, merge
# ---------------------------------------------------------------------------

def _entry_args(i=0):
    return dict(family="sma_crossover", bucket="t128_p128",
                platform="cpu",
                substrates={"epilogue": "scan:32", "lanes_cap": "256"},
                trials=3 + i, best_us=41.5)


def test_registry_same_measurements_same_bytes(tmp_path):
    """Tuned-schedule determinism: identical measurement results produce
    identical registry bytes — journal file AND wire JSON."""
    paths = [str(tmp_path / f"{i}" / "schedule.v1.jsonl") for i in (0, 1)]
    regs = [tune.ScheduleRegistry(p) for p in paths]
    for r in regs:
        r.record(**_entry_args())
        r.record("momentum", "t256_p128", "cpu",
                 {"epilogue": "scan:8"}, trials=2, best_us=10.0)
    blobs = [open(p, "rb").read() for p in paths]
    assert blobs[0] == blobs[1]
    assert regs[0].to_json() == regs[1].to_json()
    # Re-recording the identical winner appends nothing (journal stays
    # byte-stable across re-tunes that reach the same answer).
    assert regs[0].record(**_entry_args()) is False
    assert open(paths[0], "rb").read() == blobs[0]


def test_registry_persistence_restore_and_corrupt_skip(tmp_path):
    path = str(tmp_path / "schedule.v1.jsonl")
    r = tune.ScheduleRegistry(path)
    r.record(**_entry_args())
    # Plant a torn/corrupt line plus schema garbage between valid ones.
    with open(path, "a") as fh:
        fh.write('{"truncated": \n')
        fh.write('"not an object"\n')
        fh.write(json.dumps({"v": 99, "family": "x", "bucket": "b",
                             "platform": "cpu",
                             "substrates": {"epilogue": "scan"}}) + "\n")
    r.record("momentum", "t256_p128", "cpu", {"epilogue": "ladder"},
             trials=1)
    r2 = tune.ScheduleRegistry(path)
    assert len(r2) == 2
    assert r2.corrupt_entries == 3           # skip-and-count, never fatal
    assert r2.lookup("sma_crossover", "t128_p128", "cpu") == {
        "epilogue": "scan:32", "lanes_cap": "256"}
    assert r2.lookup("momentum", "t256_p128", "cpu") == {
        "epilogue": "ladder"}
    # Unknown substrate keys are scrubbed on the way in (forward compat).
    r2.record("rsi", "t128_p128", "cpu",
              {"epilogue": "scan:8", "warp_drive": "on"}, trials=1)
    assert r2.lookup("rsi", "t128_p128", "cpu") == {"epilogue": "scan:8"}
    # An unwritable registry path degrades to memory-only (io_errors
    # counted, nothing raises — tuning never fails a job).
    (tmp_path / "blockfile").write_bytes(b"")
    blocked = tune.ScheduleRegistry(
        str(tmp_path / "blockfile" / "x.jsonl"))
    blocked.record(**_entry_args())
    assert blocked.lookup("sma_crossover", "t128_p128", "cpu") is not None
    assert blocked.io_errors >= 1


def test_registry_journal_write_never_holds_the_lookup_lock(tmp_path):
    """Round-12 lock-blocking fix: the journal append used to run under
    ``_lock``, stalling every lookup() on the worker submit hot path and
    every gossip merge for the write's duration (an NFS pause froze the
    whole resolution chain). Appends now drain through the pending-IO
    queue OUTSIDE it — this pins the contract: the file write happens
    with ``_lock`` free."""
    path = str(tmp_path / "schedule.v1.jsonl")
    reg = tune.ScheduleRegistry(path)
    lock_states = []
    real_open = open

    class SpyFile:
        def __init__(self, fh):
            self._fh = fh

        def write(self, s):
            lock_states.append((reg._lock.locked(),
                                reg._io_lock.locked()))
            return self._fh.write(s)

        def __getattr__(self, name):
            return getattr(self._fh, name)

    def spy_open(*a, **k):
        fh = real_open(*a, **k)
        if a and str(a[0]).endswith("schedule.v1.jsonl") and "a" in str(
                k.get("mode", a[1] if len(a) > 1 else "")):
            return SpyFile(fh)
        return fh

    import builtins

    orig = builtins.open
    builtins.open = spy_open
    try:
        assert reg.record(**_entry_args())
        assert reg.record("momentum", "t256_p128", "cpu",
                          {"epilogue": "ladder"}, trials=1)
    finally:
        builtins.open = orig
    assert lock_states, "no journal write observed"
    assert not any(main for main, _io in lock_states), \
        "journal write ran while the registry lock was held"
    assert all(_io for _main, _io in lock_states), \
        "journal writes must be serialized by the io lock"
    # And the journal still restores everything recorded.
    assert len(tune.ScheduleRegistry(path)) == 2


def test_registry_concurrent_records_restore_to_memory_state(tmp_path):
    """Journal order == mutation order even with the IO outside the lock
    (entries enqueue under ``_lock`` in mutation order; the io-lock
    holder drains sequentially): hammering record() from four threads
    must restore, via later-wins replay, to exactly the final in-memory
    entry for every key."""
    path = str(tmp_path / "schedule.v1.jsonl")
    reg = tune.ScheduleRegistry(path)

    def hammer(tid):
        for n in range(25):
            reg.record("sma_crossover", "t128_p128", "cpu",
                       {"epilogue": f"scan:{8 << (n % 3)}",
                        "lanes_cap": str(64 * (tid + 1))},
                       trials=tid * 100 + n)

    threads = [threading.Thread(target=hammer, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    restored = tune.ScheduleRegistry(path)
    assert len(restored) == len(reg) == 1
    assert restored.lookup("sma_crossover", "t128_p128", "cpu") == \
        reg.lookup("sma_crossover", "t128_p128", "cpu")


def test_registry_merge_is_order_independent():
    """Deterministic conflict resolution: more trials wins, ties resolve
    by canonical line order — both peers converge either way."""
    a = tune.ScheduleRegistry()
    b = tune.ScheduleRegistry()
    e_low = dict(_entry_args(), substrates={"epilogue": "ladder"},
                 trials=1)
    e_high = dict(_entry_args(), substrates={"epilogue": "scan:8"},
                  trials=9)
    a.record(**e_low)
    b.record(**e_high)
    payload_a, payload_b = a.to_json(), b.to_json()
    assert a.merge_json(payload_b) == 1
    assert b.merge_json(payload_a) == 0       # fewer trials: rejected
    assert a.to_json() == b.to_json()
    assert a.lookup("sma_crossover", "t128_p128", "cpu") == {
        "epilogue": "scan:8"}
    # Malformed payloads teach nothing and are counted.
    before = a.corrupt_entries
    assert a.merge_json("{nope") == 0
    assert a.merge_json(json.dumps([{"v": 1, "family": 7}])) == 0
    assert a.corrupt_entries == before + 2


def test_registry_dirty_tracking_and_remark():
    r = tune.ScheduleRegistry()
    r.record(**_entry_args())
    payload = r.take_dirty_json()
    assert json.loads(payload)[0]["family"] == "sma_crossover"
    assert r.take_dirty_json() == ""          # clean poll: zero bytes
    r.remark_dirty(payload)                   # lost-poll retry path
    assert r.take_dirty_json() == payload
    # Fleet-adopted entries (mark_dirty=False) do NOT echo back out.
    r.merge_json(json.dumps([dict(
        v=1, family="rsi", bucket="t128_p128", platform="cpu",
        substrates={"epilogue": "scan:8"}, trials=5, best_us=None)]))
    assert r.take_dirty_json() == ""


# ---------------------------------------------------------------------------
# Precedence: explicit arg > env > tuned schedule > default, per knob
# ---------------------------------------------------------------------------

def test_env_beats_tuned_schedule_every_knob(monkeypatch):
    sched = {"epilogue": "scan:32", "lanes_cap": "256",
             "table_sma": "hbm", "page_bars": "256"}
    with fused.tuned_schedule(sched):
        # Tuned beats default...
        assert fused._resolve_epilogue(None) == "scan:32"
        assert fused.resolve_lanes_cap() == 256
        assert fused._family_table("sma", None) == "hbm"
        assert fused.resolve_page_bars() == 256
        # ...env beats tuned...
        monkeypatch.setenv("DBX_EPILOGUE", "scan:16")
        monkeypatch.setenv("DBX_LANES_CAP", "512")
        monkeypatch.setenv("DBX_SMA_TABLE", "inline")
        monkeypatch.setenv("DBX_PAGE_BARS", "1024")
        assert fused._resolve_epilogue(None) == "scan:16"
        assert fused.resolve_lanes_cap() == 512
        assert fused._family_table("sma", None) == "inline"
        assert fused.resolve_page_bars() == 1024
        # ...and an explicit arg beats both.
        assert fused._resolve_epilogue("ladder") == "ladder"
        assert fused._family_table("sma", "hbm") == "hbm"
    # Outside the context nothing lingers.
    monkeypatch.delenv("DBX_EPILOGUE")
    assert fused._resolve_epilogue(None) == "scan"


def test_invalid_tuned_values_degrade_to_defaults():
    """A corrupt registry entry must NEVER fail a job: invalid tuned
    values fall through to today's hardcoded defaults, while the same
    strings via arg/env still raise (operator error stays loud)."""
    with fused.tuned_schedule({"epilogue": "warp", "lanes_cap": "100",
                               "table_sma": "vmem", "page_bars": "13"}):
        assert fused._resolve_epilogue(None) == "scan"
        assert fused.resolve_lanes_cap() == 0
        assert fused._family_table("sma", None) == "inline"
        assert fused.resolve_page_bars() == 512
    with pytest.raises(ValueError):
        fused._resolve_epilogue("warp")


def test_tuned_defaults_process_layer_below_thread_layer():
    fused.set_tuned_defaults({"page_bars": "1024", "epilogue": "ladder"})
    try:
        assert fused.resolve_page_bars() == 1024
        assert fused._resolve_epilogue(None) == "ladder"
        with fused.tuned_schedule({"epilogue": "scan:8"}):
            # Thread-local schedule wins for its keys; global fills rest.
            assert fused._resolve_epilogue(None) == "scan:8"
            assert fused.resolve_page_bars() == 1024
            assert fused.tuned_schedule_active() == {
                "page_bars": "1024", "epilogue": "scan:8"}
    finally:
        fused.set_tuned_defaults(None)
    assert fused._resolve_epilogue(None) == "scan"


def test_substrate_defaults_and_mesh_key_follow_tuned_schedule():
    """The mesh path's jit cache key folds substrate_defaults(): a tuned
    flip must change the key exactly like an env flip (the stale-compile
    bug class dbxlint trace-time-env exists for)."""
    base = fused.substrate_defaults()
    with fused.tuned_schedule({"epilogue": "scan:32",
                               "table_don": "inline"}):
        tuned = fused.substrate_defaults()
    assert tuned["epilogue"] == "scan:32" and base["epilogue"] == "scan"
    assert tuned["table_don"] == "inline" and base["table_don"] == "hbm"
    with fused.tuned_schedule({"epilogue": "scan:32"}):
        assert fused.route_substrates("sma_crossover")["epilogue"] \
            == "scan:32"


# ---------------------------------------------------------------------------
# Numerics: a tuned substrate flip never changes positions
# ---------------------------------------------------------------------------

def test_tuned_epilogue_flip_bit_identity_pin():
    """Reuses test_z_epilogue's parity harness: the scan-vs-ladder
    contract (positions bit-identical => position/sum metrics bit-exact,
    equity-path metrics within f32 association) must hold when the flip
    arrives via a TUNED SCHEDULE instead of an arg/env knob."""
    import test_z_epilogue as zep

    ohlcv = __import__(
        "distributed_backtesting_exploration_tpu.utils.data",
        fromlist=["data"]).synthetic_ohlcv(3, 84, seed=31)
    close = np.asarray(ohlcv.close, np.float32)
    fast = np.asarray([3.0, 5.0], np.float32)
    slow = np.asarray([10.0, 14.0], np.float32)

    def run(substrate):
        with fused.tuned_schedule({"epilogue": substrate}):
            return fused.fused_sma_sweep(close, fast, slow, cost=1e-3)

    zep._assert_substrate_parity(run, "tuned_sma_flip")


# ---------------------------------------------------------------------------
# Autotuner
# ---------------------------------------------------------------------------

def test_autotune_off_by_default(monkeypatch):
    monkeypatch.delenv("DBX_AUTOTUNE", raising=False)
    tuner = tune.Autotuner(tune.ScheduleRegistry())
    assert tune.autotune_mode() == "off"
    assert tuner.tune("sma_crossover", "t128_p128", "cpu",
                      n_bars=96, n_combos=8) is None


def test_autotune_model_mode_is_deterministic(monkeypatch):
    monkeypatch.setenv("DBX_AUTOTUNE", "model")
    winners = []
    for _ in range(2):
        reg = tune.ScheduleRegistry()
        tuner = tune.Autotuner(reg)
        w = tuner.tune("sma_crossover", "t128_p128", "cpu",
                       n_bars=96, n_combos=8)
        winners.append(w)
        assert reg.lookup("sma_crossover", "t128_p128", "cpu") == w
    assert winners[0] == winners[1]
    # The model prefers the blocked scan over the full-T ladder (PR 3's
    # measured direction) — the prior must not invert it.
    assert winners[0]["epilogue"].startswith("scan")


def test_autotune_measure_mode_ranks_by_measurement(monkeypatch):
    monkeypatch.setenv("DBX_AUTOTUNE", "1")
    monkeypatch.setenv("DBX_AUTOTUNE_TRIALS", "64")   # measure everything
    reg = tune.ScheduleRegistry()
    tuner = tune.Autotuner(reg)
    calls = []

    def measure(substrates):
        calls.append(dict(substrates))
        if substrates.get("lanes_cap") == "256":
            raise RuntimeError("candidate blew VMEM")   # not the winner
        return 0.001 if substrates["epilogue"] == "ladder" else 0.01

    w = tuner.tune("momentum", "t128_p128", "cpu", n_bars=96,
                   n_combos=8, measure=measure)
    # Measurement overrides the model prior (which prefers scan).
    assert w["epilogue"] == "ladder"
    assert w["lanes_cap"] != "256"            # failing candidates skipped
    e = reg.entries()[0]
    assert e["trials"] == len(calls) - sum(
        1 for c in calls if c.get("lanes_cap") == "256")
    assert e["best_us"] == pytest.approx(1000.0)
    c = obs.get_registry().counter("dbx_autotune_trials_total",
                                   family="momentum")
    assert c.value >= e["trials"]


def test_autotune_prune_keeps_incumbent_and_epilogue_diversity():
    """The measured set always contains today's defaults (a tune can
    never regress past the incumbent) and at least one candidate per
    epilogue value (a chip-shaped prior must not prune the whole truth
    away on a platform where it is wrong)."""
    from distributed_backtesting_exploration_tpu.tune import autotune

    scored = sorted(
        tune.candidate_space("sma_crossover"),
        key=lambda c: (tune.modeled_cost("sma_crossover", c,
                                         n_bars=512, n_combos=16),
                       tune.entry_line(c)))
    pruned = tune.Autotuner._pruned("sma_crossover", scored, 4)
    assert pruned[0] == autotune.default_substrates("sma_crossover")
    assert {c["epilogue"] for c in pruned} >= {
        "scan", "scan:8", "scan:32", "scan:128", "ladder"}
    lines = [tune.entry_line(c) for c in pruned]
    assert len(lines) == len(set(lines))       # no duplicates


def test_autotune_measure_mode_cannot_regress_past_default(monkeypatch):
    """When every non-default candidate measures WORSE, the incumbent
    wins and the recorded schedule equals today's defaults."""
    from distributed_backtesting_exploration_tpu.tune import autotune

    monkeypatch.setenv("DBX_AUTOTUNE", "1")
    reg = tune.ScheduleRegistry()
    tuner = tune.Autotuner(reg)
    incumbent = autotune.default_substrates("sma_crossover")

    def measure(substrates):
        return 0.001 if substrates == incumbent else 0.5

    w = tuner.tune("sma_crossover", "t512_p128", "cpu", n_bars=512,
                   n_combos=16, measure=measure)
    assert w == incumbent


def test_autotune_env_pinned_axes_excluded(monkeypatch):
    """An env-pinned knob would make its candidates measure the SAME
    substrate (env beats tuned), so the axis is dropped from the search
    AND from the recorded schedule — a noise-picked value must never
    gossip fleet-wide as a measured winner."""
    monkeypatch.setenv("DBX_AUTOTUNE", "1")
    monkeypatch.setenv("DBX_EPILOGUE", "ladder")
    reg = tune.ScheduleRegistry()
    tuner = tune.Autotuner(reg)
    seen_keys = set()

    def measure(substrates):
        seen_keys.update(substrates)
        return 0.01

    w = tuner.tune("sma_crossover", "t128_p128", "cpu", n_bars=96,
                   n_combos=8, measure=measure)
    assert "epilogue" not in seen_keys
    assert "epilogue" not in w
    assert "epilogue" not in reg.entries()[0]["substrates"]
    # Everything pinned -> nothing to tune, no entry recorded
    # (stochastic has no table axis, so epilogue+lanes is its whole
    # search space).
    monkeypatch.setenv("DBX_LANES_CAP", "256")
    reg2 = tune.ScheduleRegistry()
    assert tune.Autotuner(reg2).tune(
        "stochastic", "t128_p128", "cpu", n_bars=96, n_combos=8,
        measure=measure) is None
    assert len(reg2) == 0


def test_cache_sync_remembers_foreign_rejections_and_unmark(tmp_path):
    """A foreign-tag entry is refused ONCE (missing() stops re-requesting
    it — a mixed-generation fleet must not re-download the foreign set
    every tick), and unmark() re-surfaces offers whose RPC was lost."""
    sync = tune.CacheSync(str(tmp_path / "c"), runtime_tag="t|cpu")
    foreign = [(tune.entry_key("f1", "OTHER|tpu"), "f1", b"x")]
    assert sync.install(foreign) == 0
    assert sync.missing([foreign[0][0]]) == []      # refusal remembered
    with open(os.path.join(str(tmp_path / "c"), "mine"), "wb") as fh:
        fh.write(b"m")
    offers = sync.poll_new()
    assert len(offers) == 1
    assert sync.poll_new() == []                    # marked seen
    sync.unmark(offers)                             # lost-offer retry
    assert sync.poll_new() == offers
    # Interrupted-install temp files are never scanned or offered.
    with open(os.path.join(str(tmp_path / "c"), ".dbx_fetch_x"),
              "wb") as fh:
        fh.write(b"partial")
    assert all(n != ".dbx_fetch_x" for _, n, _ in sync.poll_new())


def test_candidate_space_shape():
    sma = tune.candidate_space("sma_crossover")
    assert all("table_sma" in c for c in sma)
    assert {c["epilogue"] for c in sma} == {"scan:8", "scan:32",
                                            "scan:128", "ladder"}
    mom_paged = tune.candidate_space("momentum", paged=True)
    assert all("page_bars" in c for c in mom_paged)
    assert all("table_" not in k for c in tune.candidate_space("rsi")
               for k in c)


# ---------------------------------------------------------------------------
# Backend consultation at group-submit time
# ---------------------------------------------------------------------------

def _sma_specs(n=2, bars=96, seed=6):
    grid = parse_grid("fast=3:5,slow=10:14:2")
    jobs = synthetic_jobs(n, bars, "sma_crossover", grid, cost=1e-3,
                          seed=seed)
    return [pb.JobSpec(id=r.id, strategy=r.strategy, ohlcv=r.ohlcv,
                       grid=wire.grid_to_proto(r.grid), cost=r.cost,
                       periods_per_year=252) for r in jobs]


def test_backend_serves_tuned_schedule_and_env_still_wins(monkeypatch):
    """The registry-consultation layer at group-submit time: a seeded
    tuned entry routes the group's substrates (visible on the
    dbx_fused_substrate_total counter and the tuned info gauge), and an
    env knob set over it still wins — pinned end to end."""
    monkeypatch.delenv("DBX_AUTOTUNE", raising=False)
    backend = compute.JaxSweepBackend(use_fused=True)
    specs = _sma_specs()
    bucket = tune.shape_bucket(96, 6)
    backend.schedule_registry.record(
        "sma_crossover", bucket, backend._platform,
        {"epilogue": "scan:48"}, trials=1)
    reg = obs.get_registry()
    c_tuned = reg.counter("dbx_fused_substrate_total",
                          kernel="sma_crossover", epilogue="scan:48",
                          table="inline")
    before = c_tuned.value
    assert len(backend.process(specs)) == len(specs)
    assert c_tuned.value == before + 1
    g = reg.gauge("dbx_tuned_substrate_info", kernel="sma_crossover",
                  bucket=bucket, epilogue="scan:48", table="default",
                  lanes_cap="default", page_bars="default")
    assert g.value == 1
    # Env override beats the tuned schedule for the SAME group shape.
    monkeypatch.setenv("DBX_EPILOGUE", "ladder")
    c_env = reg.counter("dbx_fused_substrate_total",
                        kernel="sma_crossover", epilogue="ladder",
                        table="inline")
    env_before = c_env.value
    assert len(backend.process(_sma_specs(seed=7))) == 2
    assert c_env.value == env_before + 1
    assert c_tuned.value == before + 1        # tuned route NOT taken


def test_backend_autotune_first_contact_records_winner(monkeypatch):
    monkeypatch.setenv("DBX_AUTOTUNE", "model")
    backend = compute.JaxSweepBackend(use_fused=True)
    assert len(backend.schedule_registry) == 0
    backend.process(_sma_specs(seed=8))
    assert len(backend.schedule_registry) == 1
    e = backend.schedule_registry.entries()[0]
    assert e["family"] == "sma_crossover"
    assert e["platform"] == backend._platform
    # Second contact with the same bucket re-uses, never re-tunes.
    backend.process(_sma_specs(seed=9))
    assert len(backend.schedule_registry) == 1


# ---------------------------------------------------------------------------
# Compile cache: keys, sync accounting, store bounds
# ---------------------------------------------------------------------------

def test_entry_key_folds_runtime_tag():
    k1 = tune.entry_key("cachefile_abc", "0.4.37|cpu")
    assert k1 == tune.entry_key("cachefile_abc", "0.4.37|cpu")
    assert k1 != tune.entry_key("cachefile_abc", "0.4.38|cpu")
    assert k1 != tune.entry_key("cachefile_abc", "0.4.37|tpu")
    assert len(k1) == 32


def test_cache_sync_accounting_and_store(tmp_path):
    reg = obs.get_registry()

    def counter(kind, source):
        return reg.counter(f"dbx_compile_cache_{kind}_total",
                           source=source)

    d = str(tmp_path / "cache")
    os.makedirs(d)
    with open(os.path.join(d, "prewarm"), "wb") as fh:
        fh.write(b"P" * 8)
    base = {k: counter(*k).value
            for k in (("hits", "local"), ("misses", "local"),
                      ("hits", "fleet"), ("misses", "fleet"))}
    sync = tune.CacheSync(d, runtime_tag="t|cpu")
    assert counter("hits", "local").value == base[("hits", "local")] + 1
    assert sync.poll_new() == []              # prewarm is not re-offered
    with open(os.path.join(d, "compiled_x"), "wb") as fh:
        fh.write(b"X" * 16)
    offers = sync.poll_new()
    assert [(k, n) for k, n, _ in offers] == [
        (tune.entry_key("compiled_x", "t|cpu"), "compiled_x")]
    assert counter("misses", "local").value \
        == base[("misses", "local")] + 1

    store = tune.CompileStore(max_bytes=1 << 20)
    for k, n, payload in offers:
        assert store.offer(k, n, payload)
        assert not store.offer(k, n, payload)     # dup ignored
    assert store.stats()["entries"] == 1
    # A second, cold worker: fetch + install, bit-identical bytes.
    d2 = str(tmp_path / "cache2")
    sync2 = tune.CacheSync(d2, runtime_tag="t|cpu")
    miss = sync2.missing(store.keys())
    assert miss == store.keys()
    entries = [(k,) + store.get(k) for k in miss]
    assert sync2.install(entries) == 1
    assert open(os.path.join(d2, "compiled_x"), "rb").read() == b"X" * 16
    assert counter("hits", "fleet").value == base[("hits", "fleet")] + 1
    assert sync2.missing(store.keys()) == []
    # A peer on a different runtime tag is refused.
    sync3 = tune.CacheSync(str(tmp_path / "cache3"),
                           runtime_tag="OTHER|tpu")
    assert sync3.install(entries) == 0
    sync3.count_fleet_misses(1)
    assert counter("misses", "fleet").value \
        == base[("misses", "fleet")] + 1


def test_compile_store_byte_bound_evicts_lru():
    store = tune.CompileStore(max_bytes=40)
    assert store.offer("k1", "n1", b"a" * 30)
    assert store.offer("k2", "n2", b"b" * 30)   # evicts k1
    assert store.get("k1") is None
    assert store.get("k2") == ("n2", b"b" * 30)
    assert len(store.keys()) == 1
    assert not store.offer("k3", "n3", b"")     # empty payload refused


# ---------------------------------------------------------------------------
# Fleet round-trips over the in-process gRPC loop
# ---------------------------------------------------------------------------

class _TuneProbeBackend:
    """Instant completions + a schedule registry (so the worker's tune
    sync legs engage without paying jax compiles)."""

    chips = 1

    def __init__(self):
        self.schedule_registry = tune.ScheduleRegistry()

    def process(self, jobs):
        return [compute.Completion(j.id, b"", 0.0, trace_id=j.trace_id)
                for j in jobs]


def _server(queue, **kw):
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=30.0), **kw)
    srv = DispatcherServer(disp, bind="localhost:0",
                           prune_interval_s=0.5).start()
    return disp, srv


def test_schedule_gossip_worker_to_fleet_to_worker():
    """Worker A tunes an entry; it rides JobsRequest.schedule_json into
    the dispatcher's fleet registry and worker B adopts it from GetStats
    — the Nth worker inherits the first worker's tuning."""
    queue = JobQueue()
    disp, srv = _server(queue)
    a, b = _TuneProbeBackend(), _TuneProbeBackend()
    a.schedule_registry.record("sma_crossover", "t128_p128", "cpu",
                               {"epilogue": "scan:32"}, trials=2,
                               best_us=7.0)
    workers, threads = [], []
    try:
        for backend in (a, b):
            w = Worker(f"localhost:{srv.port}", backend,
                       poll_interval_s=0.02, status_interval_s=0.05)
            w.tune_sync_interval_s = 0.05
            t = threading.Thread(target=lambda w=w: w.run(), daemon=True)
            t.start()
            workers.append(w)
            threads.append(t)
        _wait(lambda: len(disp.fleet_schedule) == 1,
              msg="fleet registry adopts worker A's entry")
        _wait(lambda: b.schedule_registry.lookup(
                  "sma_crossover", "t128_p128", "cpu") is not None,
              msg="worker B inherits the tuned schedule")
        assert b.schedule_registry.lookup(
            "sma_crossover", "t128_p128", "cpu") == {"epilogue": "scan:32"}
        # Adopted entries are not gossiped back as dirty.
        assert b.schedule_registry.take_dirty_json() == ""
    finally:
        for w in workers:
            w.stop()
        for t in threads:
            t.join(timeout=10)
        srv.stop()


def test_fleet_compile_cache_round_trip_over_grpc(tmp_path):
    """Worker B's cold start hits worker A's compile-cache entry through
    the real FetchCompiled/OfferCompiled RPCs: bytes install
    bit-identically and the fleet hit counter moves — the integration
    pin for dbx_compile_cache_hits_total{source="fleet"} > 0."""
    import grpc

    reg = obs.get_registry()
    hits = reg.counter("dbx_compile_cache_hits_total", source="fleet")
    before = hits.value
    queue = JobQueue()
    disp, srv = _server(queue)
    try:
        channel = grpc.insecure_channel(
            f"localhost:{srv.port}",
            options=service.default_channel_options())
        stub = service.DispatcherStub(channel)
        # Worker A: one entry its own compile just wrote.
        dir_a = str(tmp_path / "a")
        sync_a = tune.CacheSync(dir_a, runtime_tag="t|cpu")
        blob = os.urandom(512)
        with open(os.path.join(dir_a, "jitcache_deadbeef"), "wb") as fh:
            fh.write(blob)
        offers = sync_a.poll_new()
        stub.OfferCompiled(pb.CompiledOffer(
            worker_id="wa",
            entries=[pb.CompiledEntry(key=k, name=n, payload=p)
                     for k, n, p in offers]))
        assert disp.compile_store.stats()["entries"] == 1
        # Worker B: cold dir, listing -> fetch -> install.
        sync_b = tune.CacheSync(str(tmp_path / "b"), runtime_tag="t|cpu")
        listing = stub.FetchCompiled(pb.CompiledRequest(worker_id="wb"))
        assert not listing.entries            # listing carries keys only
        miss = sync_b.missing(listing.known_keys)
        assert len(miss) == 1
        got = stub.FetchCompiled(pb.CompiledRequest(worker_id="wb",
                                                    keys=miss))
        installed = sync_b.install(
            (e.key, e.name, e.payload) for e in got.entries)
        assert installed == 1
        assert open(os.path.join(str(tmp_path / "b"),
                                 "jitcache_deadbeef"), "rb").read() == blob
        assert hits.value == before + 1
        channel.close()
    finally:
        srv.stop()


def test_stats_reply_ships_fleet_schedule():
    import grpc

    queue = JobQueue()
    disp, srv = _server(queue)
    try:
        disp.fleet_schedule.record("rsi", "t256_p128", "cpu",
                                   {"epilogue": "scan:8"}, trials=4)
        channel = grpc.insecure_channel(
            f"localhost:{srv.port}",
            options=service.default_channel_options())
        stub = service.DispatcherStub(channel)
        reply = stub.GetStats(pb.StatsRequest())
        entries = json.loads(reply.schedule_json)
        assert [e["family"] for e in entries] == ["rsi"]
        channel.close()
    finally:
        srv.stop()
