"""Sweep engine: grids, shapes, best-param selection, padding invariance."""

import numpy as np
import jax.numpy as jnp

from distributed_backtesting_exploration_tpu.models import (
    sma_crossover, base as base_mod)
from distributed_backtesting_exploration_tpu.parallel import sweep as sweep_mod
from distributed_backtesting_exploration_tpu.utils import data as data_mod


def jx(ohlcv):
    return data_mod.OHLCV(*(jnp.asarray(f) for f in ohlcv))


def test_product_grid():
    g = sweep_mod.product_grid(fast=[5, 10], slow=[50, 100, 200])
    assert sweep_mod.grid_size(g) == 6
    np.testing.assert_array_equal(np.asarray(g["fast"]), [5, 5, 5, 10, 10, 10])
    np.testing.assert_array_equal(np.asarray(g["slow"]),
                                  [50, 100, 200, 50, 100, 200])


def test_registry():
    assert "sma_crossover" in base_mod.available_strategies()
    s = base_mod.get_strategy("sma_crossover")
    assert s.param_fields == ("fast", "slow")


def test_sweep_shapes_and_values():
    batch = data_mod.synthetic_ohlcv(4, 256, seed=3)
    grid = sweep_mod.product_grid(fast=[5, 10, 20], slow=[50, 100])
    m = sweep_mod.jit_sweep(jx(batch), sma_crossover.SMA_CROSSOVER, dict(grid),
                            cost=0.001)
    assert m.sharpe.shape == (4, 6)
    assert np.isfinite(np.asarray(m.sharpe)).all()
    assert (np.asarray(m.n_trades) >= 0).all()


def test_sweep_matches_single_backtest():
    """One grid point of the sweep == a directly-computed backtest."""
    from distributed_backtesting_exploration_tpu.ops import pnl, metrics

    batch = data_mod.synthetic_ohlcv(2, 200, seed=5)
    grid = {"fast": jnp.asarray([10]), "slow": jnp.asarray([30])}
    m = sweep_mod.run_sweep(jx(batch), sma_crossover.SMA_CROSSOVER, grid,
                            cost=0.0005)

    one = data_mod.OHLCV(*(jnp.asarray(f[1]) for f in batch))
    pos = sma_crossover.SMA_CROSSOVER.positions(
        one, {"fast": jnp.asarray(10), "slow": jnp.asarray(30)})
    res = pnl.backtest_prefix(one.close, pos, cost=0.0005)
    want = metrics.summary_metrics(res.returns, res.equity, res.positions)
    np.testing.assert_allclose(float(m.sharpe[1, 0]), float(want.sharpe),
                               rtol=1e-5)
    np.testing.assert_allclose(float(m.total_return[1, 0]),
                               float(want.total_return), rtol=1e-5)


def test_best_params():
    vals = jnp.asarray([[0.1, 0.9, 0.5], [0.7, 0.2, 0.3]])
    grid = {"w": jnp.asarray([10, 20, 30])}
    best, chosen = sweep_mod.best_params(vals, grid)
    np.testing.assert_allclose(np.asarray(best), [0.9, 0.7])
    np.testing.assert_array_equal(np.asarray(chosen["w"]), [20, 10])


def test_padding_invariance():
    """Padding a history to lane multiples must not change the economics.

    Nonzero cost is load-bearing: zeroing positions at padded bars (instead
    of holding the last valid position) charges a phantom exit trade when
    the final position is open — caught only when cost != 0 and turnover /
    n_trades / hit_rate are compared too. Regression for exactly that bug.
    """
    full = data_mod.synthetic_ohlcv(1, 300, seed=11)
    series = data_mod.OHLCV(*(f[0] for f in full))
    padded, lengths, mask = data_mod.pad_and_stack([series], lane_multiple=128)
    assert padded.close.shape[-1] == 384

    grid = sweep_mod.product_grid(fast=[5, 10], slow=[40, 80])
    m_unpadded = sweep_mod.run_sweep(
        jx(data_mod.OHLCV(*(f[None, :] for f in series))),
        sma_crossover.SMA_CROSSOVER, grid, cost=1e-3)
    m_padded = sweep_mod.run_sweep(
        jx(padded), sma_crossover.SMA_CROSSOVER, grid, cost=1e-3,
        bar_mask=jnp.asarray(mask))
    # SMA crossover is always in the market after warmup, so the final
    # position is open and the phantom-exit bug would fire on every combo.
    assert (np.abs(np.asarray(m_unpadded.total_return)) > 0).all()

    for name in m_unpadded._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(m_padded, name)),
            np.asarray(getattr(m_unpadded, name)),
            rtol=1e-3, atol=1e-5, err_msg=name)


def test_padding_invariance_ragged_stateful():
    """Two tickers of different lengths, stateful strategy, all metrics."""
    from distributed_backtesting_exploration_tpu.models.base import get_strategy

    full = data_mod.synthetic_ohlcv(2, 300, seed=21)
    s0 = data_mod.OHLCV(*(f[0] for f in full))
    s1 = data_mod.OHLCV(*(np.asarray(f[1])[:211] for f in full))
    padded, lengths, mask = data_mod.pad_and_stack([s0, s1], lane_multiple=128)

    grid = sweep_mod.product_grid(k=[0.5, 1.5], window=[10., 20.])
    strat = get_strategy("bollinger")
    m_padded = sweep_mod.run_sweep(jx(padded), strat, grid, cost=1e-3,
                                   bar_mask=jnp.asarray(mask))
    for i, s in enumerate((s0, s1)):
        m_one = sweep_mod.run_sweep(
            jx(data_mod.OHLCV(*(np.asarray(f)[None, :] for f in s))),
            strat, grid, cost=1e-3)
        for name in m_one._fields:
            np.testing.assert_allclose(
                np.asarray(getattr(m_padded, name))[i],
                np.asarray(getattr(m_one, name))[0],
                rtol=1e-3, atol=1e-5, err_msg=f"ticker {i} {name}")


def test_chunked_sweep_matches_jit_sweep():
    """Param-chunked lax.map sweep must equal the fully-vmapped sweep."""
    import jax.numpy as jnp
    from distributed_backtesting_exploration_tpu.models.base import get_strategy
    from distributed_backtesting_exploration_tpu.parallel import sweep as sw
    from distributed_backtesting_exploration_tpu.utils import data as d

    ohlcv = d.synthetic_ohlcv(5, 256, seed=13)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sw.product_grid(fast=jnp.array([3., 5., 8.]),
                           slow=jnp.array([13., 21., 34., 55.]))
    strat = get_strategy("sma_crossover")
    ref = sw.jit_sweep(panel, strat, dict(grid), cost=1e-3)
    got = sw.chunked_sweep(panel, strat, dict(grid), param_chunk=4, cost=1e-3)
    for name in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            rtol=1e-6, atol=1e-7, err_msg=name)


def test_best_params_ranks_nan_last():
    """NaN metric cells must lose to any finite cell (jnp.argmax alone
    would rank NaN first); an all-NaN row still reports NaN. Direction
    awareness: lower-is-better metrics select the minimum."""
    import jax.numpy as jnp
    from distributed_backtesting_exploration_tpu.parallel import sweep as sw

    vals = jnp.asarray([[0.5, jnp.nan, 2.0],
                        [jnp.nan, jnp.nan, jnp.nan],
                        [3.0, 1.0, -1.0]])
    grid = {"window": jnp.asarray([10.0, 20.0, 30.0])}
    best, chosen = sw.best_params(vals, grid, metric="sharpe")
    assert np.asarray(chosen["window"]).tolist() == [30.0, 10.0, 10.0]
    assert float(best[0]) == 2.0 and float(best[2]) == 3.0
    assert np.isnan(float(best[1]))
    _, chosen_dd = sw.best_params(
        jnp.asarray([[0.3, 0.1, jnp.nan]]), grid, metric="max_drawdown")
    assert float(chosen_dd["window"][0]) == 20.0
