"""Sweep engine: grids, shapes, best-param selection, padding invariance."""

import numpy as np
import jax.numpy as jnp

from distributed_backtesting_exploration_tpu.models import (
    sma_crossover, base as base_mod)
from distributed_backtesting_exploration_tpu.parallel import sweep as sweep_mod
from distributed_backtesting_exploration_tpu.utils import data as data_mod


def jx(ohlcv):
    return data_mod.OHLCV(*(jnp.asarray(f) for f in ohlcv))


def test_product_grid():
    g = sweep_mod.product_grid(fast=[5, 10], slow=[50, 100, 200])
    assert sweep_mod.grid_size(g) == 6
    np.testing.assert_array_equal(np.asarray(g["fast"]), [5, 5, 5, 10, 10, 10])
    np.testing.assert_array_equal(np.asarray(g["slow"]),
                                  [50, 100, 200, 50, 100, 200])


def test_registry():
    assert "sma_crossover" in base_mod.available_strategies()
    s = base_mod.get_strategy("sma_crossover")
    assert s.param_fields == ("fast", "slow")


def test_sweep_shapes_and_values():
    batch = data_mod.synthetic_ohlcv(4, 256, seed=3)
    grid = sweep_mod.product_grid(fast=[5, 10, 20], slow=[50, 100])
    m = sweep_mod.jit_sweep(jx(batch), sma_crossover.SMA_CROSSOVER, dict(grid),
                            cost=0.001)
    assert m.sharpe.shape == (4, 6)
    assert np.isfinite(np.asarray(m.sharpe)).all()
    assert (np.asarray(m.n_trades) >= 0).all()


def test_sweep_matches_single_backtest():
    """One grid point of the sweep == a directly-computed backtest."""
    from distributed_backtesting_exploration_tpu.ops import pnl, metrics

    batch = data_mod.synthetic_ohlcv(2, 200, seed=5)
    grid = {"fast": jnp.asarray([10]), "slow": jnp.asarray([30])}
    m = sweep_mod.run_sweep(jx(batch), sma_crossover.SMA_CROSSOVER, grid,
                            cost=0.0005)

    one = data_mod.OHLCV(*(jnp.asarray(f[1]) for f in batch))
    pos = sma_crossover.SMA_CROSSOVER.positions(
        one, {"fast": jnp.asarray(10), "slow": jnp.asarray(30)})
    res = pnl.backtest_prefix(one.close, pos, cost=0.0005)
    want = metrics.summary_metrics(res.returns, res.equity, res.positions)
    np.testing.assert_allclose(float(m.sharpe[1, 0]), float(want.sharpe),
                               rtol=1e-5)
    np.testing.assert_allclose(float(m.total_return[1, 0]),
                               float(want.total_return), rtol=1e-5)


def test_best_params():
    vals = jnp.asarray([[0.1, 0.9, 0.5], [0.7, 0.2, 0.3]])
    grid = {"w": jnp.asarray([10, 20, 30])}
    best, chosen = sweep_mod.best_params(vals, grid)
    np.testing.assert_allclose(np.asarray(best), [0.9, 0.7])
    np.testing.assert_array_equal(np.asarray(chosen["w"]), [20, 10])


def test_padding_invariance():
    """Padding a history to lane multiples must not change the economics."""
    full = data_mod.synthetic_ohlcv(1, 300, seed=11)
    series = data_mod.OHLCV(*(f[0] for f in full))
    padded, lengths, mask = data_mod.pad_and_stack([series], lane_multiple=128)
    assert padded.close.shape[-1] == 384

    grid = sweep_mod.product_grid(fast=[5, 10], slow=[40, 80])
    m_unpadded = sweep_mod.run_sweep(
        jx(data_mod.OHLCV(*(f[None, :] for f in series))),
        sma_crossover.SMA_CROSSOVER, grid, cost=0.0)
    m_padded = sweep_mod.run_sweep(
        jx(padded), sma_crossover.SMA_CROSSOVER, grid, cost=0.0,
        bar_mask=jnp.asarray(mask))

    np.testing.assert_allclose(np.asarray(m_padded.total_return),
                               np.asarray(m_unpadded.total_return), atol=1e-5)
    np.testing.assert_allclose(np.asarray(m_padded.sharpe),
                               np.asarray(m_unpadded.sharpe), rtol=1e-3)
    np.testing.assert_allclose(np.asarray(m_padded.max_drawdown),
                               np.asarray(m_unpadded.max_drawdown), atol=1e-5)


def test_chunked_sweep_matches_jit_sweep():
    """Param-chunked lax.map sweep must equal the fully-vmapped sweep."""
    import jax.numpy as jnp
    from distributed_backtesting_exploration_tpu.models.base import get_strategy
    from distributed_backtesting_exploration_tpu.parallel import sweep as sw
    from distributed_backtesting_exploration_tpu.utils import data as d

    ohlcv = d.synthetic_ohlcv(5, 256, seed=13)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sw.product_grid(fast=jnp.array([3., 5., 8.]),
                           slow=jnp.array([13., 21., 34., 55.]))
    strat = get_strategy("sma_crossover")
    ref = sw.jit_sweep(panel, strat, dict(grid), cost=1e-3)
    got = sw.chunked_sweep(panel, strat, dict(grid), param_chunk=4, cost=1e-3)
    for name in ref._fields:
        np.testing.assert_allclose(
            np.asarray(getattr(got, name)), np.asarray(getattr(ref, name)),
            rtol=1e-6, atol=1e-7, err_msg=name)
