"""dbxcert tests: the dataflow lattice on seeded mini-jaxprs (one per
provenance class), contract-table canonical-bytes determinism, committed
coverage, the empirical substrate cross-check (a `selection`-certified
family really is bit-identical across scan:8 vs ladder), the deliberate
reassociated-kernel-edit drift fixture, and the CLI exit-code contract.
The package-wide certify-clean gate lives in test_lint_clean.py."""

import copy
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.analysis import (
    certify, core, dataflow, jaxpr_rules)
from distributed_backtesting_exploration_tpu.streaming import recurrent

# Non-integral float values so integrality must be *proven*, never
# accidental.
_X = (np.linspace(0.1, 2.3, 8) + 0.017).astype(np.float32)


def _analyze(fn, *args, integral_inputs=None):
    return dataflow.analyze(jax.make_jaxpr(fn)(*args),
                            integral_inputs=integral_inputs)


# ---------------------------------------------------------------------------
# Lattice: one seeded mini-jaxpr per provenance class
# ---------------------------------------------------------------------------

def test_selection_machine_classifies_selection_with_zero_census():
    """The band/latch shape: float data reaches the output only through
    comparisons and select branches over literals — selection class, no
    association boundary, provably integer-valued."""
    def machine(z):
        def step(pos, z_t):
            ent = jnp.where(z_t < -1.0, jnp.float32(1.0),
                            jnp.where(z_t > 1.0, jnp.float32(-1.0),
                                      jnp.float32(0.0)))
            nxt = jnp.where(pos == 0, ent,
                            jnp.where((pos > 0) & (z_t >= 0.0),
                                      jnp.float32(0.0), pos))
            return nxt, nxt

        _, path = jax.lax.scan(step, jnp.zeros((), jnp.float32), z)
        return path

    (v,) = _analyze(machine, _X).out_vals
    assert v.class_name == "selection"
    assert v.boundaries == 0
    assert v.integral


def test_int_exact_sum_of_bool_casts():
    """f32 sums of exact small ints (the win/active/turnover shape):
    int-exact, zero boundary census — associativity holds exactly."""
    (v,) = _analyze(lambda x: jnp.sum((x > 0.5).astype(jnp.float32)),
                    _X).out_vals
    assert v.class_name == "int-exact"
    assert v.boundaries == 0


def test_float_accum_census_counts_known_boundaries():
    """One reduce_sum = one site; one cumsum = one site; a deliberately
    split summation tree (two half-sums + a merge add of overlapping
    lineage) = three sites."""
    (s,) = _analyze(lambda x: jnp.sum(x * x), _X).out_vals
    assert (s.class_name, s.boundaries) == ("float-accum", 1)
    (c,) = _analyze(lambda x: jnp.cumsum(x)[-1], _X).out_vals
    assert (c.class_name, c.boundaries) == ("float-accum", 1)
    (sp,) = _analyze(lambda x: jnp.sum(x[::2]) + jnp.sum(x[1::2]),
                     _X).out_vals
    assert (sp.class_name, sp.boundaries) == ("float-accum", 3)


def test_structural_reassociation_ladder_is_counted():
    """The Hillis–Steele shift-doubling ladder (ops.fused._cumsum_last's
    shape) has NO reduce primitive — every `x + shift(x)` step is an add
    of overlapping lineage and must be counted as a site (log2(8) = 3)."""
    def ladder(x):
        s = 1
        while s < x.shape[-1]:
            x = x + jnp.concatenate([jnp.zeros((s,), x.dtype), x[:-s]])
            s *= 2
        return x

    (v,) = _analyze(ladder, _X).out_vals
    assert (v.class_name, v.boundaries) == ("float-accum", 3)


def test_scan_carry_accumulation_is_a_boundary_site():
    """A float carry updated arithmetically from itself is a scan-carry
    site; a carry moved only through selects (the position machine,
    above) is not."""
    def accum(x):
        c, _ = jax.lax.scan(lambda c, t: (c + t, c),
                            jnp.zeros((), jnp.float32), x)
        return c

    (v,) = _analyze(accum, _X).out_vals
    assert v.class_name == "float-accum"
    assert v.boundaries == 2   # the in-body merge add + the carry site
    assert any("carry" in s for s in v.sites)


def test_scatter_add_is_nondet_with_site_recorded():
    def scatter(x):
        idx = jnp.array([0, 1, 1, 2, 3, 3, 0, 2])
        return jnp.zeros((4,), jnp.float32).at[idx].add(x)

    an = _analyze(scatter, _X)
    (v,) = an.out_vals
    assert v.class_name == "nondet"
    assert an.nondet_sites and an.nondet_sites[0][0] == "scatter-add"
    assert v.chain   # the introducing equation chain rides the value

    # Integer-valued updates order-independently sum exactly: int-exact.
    def scatter_int(x):
        idx = jnp.array([0, 1, 1, 2])
        ones = (x[:4] > 0).astype(jnp.float32)
        return jnp.zeros((4,), jnp.float32).at[idx].add(ones)

    (vi,) = _analyze(scatter_int, _X).out_vals
    assert vi.class_name == "int-exact"


def test_nextafter_breaks_integrality():
    """nextafter(2.0, 3.0) = 2.0000002 — it must NOT be treated as
    integer-preserving, or a sum over it would be unsoundly certified
    int-exact."""
    (v,) = _analyze(lambda x: jnp.sum(jnp.nextafter(x, x + 1.0)), _X,
                    integral_inputs=[True]).out_vals
    assert v.class_name == "float-accum"
    assert not v.integral


def test_nondet_site_in_scan_body_deduped_across_fixpoint():
    """A single scatter-add inside a scan body is ONE nondet site, not
    one per fixpoint re-evaluation of the body."""
    def step(c, t):
        idx = jnp.array([0, 1, 1, 2])
        return c + jnp.zeros((4,), jnp.float32).at[idx].add(t), None

    an = _analyze(
        lambda x: jax.lax.scan(step, jnp.zeros((4,), jnp.float32),
                               x)[0], _X)
    assert len(an.nondet_sites) == 1


def test_weak_type_provenance_chain_recorded():
    (v,) = _analyze(lambda x: jnp.where(x > 0, 1.0, 0.0), _X).out_vals
    assert v.weak
    assert v.weak_chain and any("@" in f for f in v.weak_chain)


def test_integral_input_hint_proves_int_exact_merge():
    """The carry contract's hint: turnover-shaped |Δpos| sums over a
    pos_last input asserted integer-valued classify int-exact; without
    the hint the same program is float-accum."""
    def turnover(p):
        prev = jnp.concatenate([jnp.zeros((1,), jnp.float32), p[:-1]])
        return jnp.sum(jnp.abs(p - prev))

    (hinted,) = _analyze(turnover, _X, integral_inputs=[True]).out_vals
    assert hinted.class_name == "int-exact"
    (plain,) = _analyze(turnover, _X).out_vals
    assert plain.class_name == "float-accum"


def test_comparison_launders_accumulation_but_census_keeps_exposure():
    """Per the contract semantics, a comparison's discrete result is
    selection-class even over a reassociated operand — but the census
    still records the knife-edge exposure on the cone."""
    (v,) = _analyze(
        lambda x: jnp.where(jnp.sum(x) > 1.0, jnp.float32(1.0),
                            jnp.float32(0.0)), _X).out_vals
    assert v.class_name == "selection"
    assert v.boundaries == 1


def test_elementwise_float_arithmetic_stays_exact():
    (v,) = _analyze(lambda x: x * jnp.float32(2.0) - jnp.exp(-x),
                    _X).out_vals
    assert v.class_name == "exact"
    assert v.boundaries == 0


def test_kernel_hygiene_weak_finding_carries_provenance_chain():
    """kernel-hygiene's weak-type flag now rides the shared dataflow
    walk: same file/line/label, message upgraded with the chain."""
    weak = jaxpr_rules.check_traced(
        "weak", lambda x: jnp.full(x.shape, 2.0),
        [np.ones((4, 8), np.float32)])
    assert len(weak) == 1 and "weakly typed" in weak[0].message
    assert "provenance:" in weak[0].message


# ---------------------------------------------------------------------------
# Contract table: coverage, canonical bytes, drift detection
# ---------------------------------------------------------------------------

def test_committed_contract_covers_all_families_substrates_forms():
    committed = certify.load_contract()
    assert committed is not None, "numerics.contract.json must be committed"
    fams = certify.stream_families()
    assert len(fams) == 14
    expect = {certify.row_key(f, s, fo)
              for f in fams
              for s in certify.SUBSTRATES
              for fo in certify.FORMS}
    expect |= set(certify.DIGEST_KEYS)
    assert set(committed["rows"]) == expect
    assert committed["schema"] == certify.SCHEMA
    # Canonical = sorted keys, no timestamps: nothing beyond the schema.
    assert set(committed) == {"schema", "rows"}


def test_contract_table_canonical_bytes_deterministic():
    """Same trace twice => identical canonical JSON bytes (fresh traces,
    not the cache)."""
    def one_pass():
        rows = {}
        for sub in certify.SUBSTRATES:
            for form in certify.FORMS:
                r = certify.streaming_row("momentum", sub, form)
                rows[r.key] = r
        return certify.canonical_bytes(certify.table_from_rows(rows))

    assert one_pass() == one_pass()


def test_selection_certified_outputs_bit_identical_across_substrates():
    """The empirical cross-check: every output the table certifies at or
    below int-exact really is bit-identical between the scan:8 and
    ladder epilogue substrates on the pinned tiny shapes — and the
    certifier's selection claim covers the position state."""
    committed = certify.load_contract()
    _, _, grid, fields = recurrent._probe_inputs("bollinger")
    c_scan = recurrent.build_carry("bollinger", fields, grid,
                                   epilogue="scan:8")
    c_lad = recurrent.build_carry("bollinger", fields, grid,
                                  epilogue="ladder")
    row = committed["rows"][certify.row_key("bollinger", "scan:8",
                                            "build_carry")]["outputs"]
    checked = 0
    for label, rec in row.items():
        if not label.startswith("metric/"):
            continue
        if rec["class"] not in ("exact", "selection", "int-exact"):
            continue
        name = label.split("/", 1)[1]
        np.testing.assert_array_equal(
            np.asarray(c_scan.metric[name]), np.asarray(c_lad.metric[name]),
            err_msg=f"{label} certified {rec['class']} must be "
                    f"bit-identical across substrates")
        checked += 1
    assert checked >= 3          # pos_last + the count accumulators
    assert row["metric/pos_last"]["class"] == "selection"


def test_reassociated_kernel_edit_is_caught_as_contract_diff(monkeypatch):
    """The acceptance fixture: a deliberate reassociation (an extra
    summation-tree merge on s1's cone) must fail the drift gate with the
    introducing equation chain reported."""
    orig = recurrent._advance_metrics

    def reassociated(metric, pos, ret, *, cost, block):
        out = orig(metric, pos, ret, cost=cost, block=block)
        # Split-and-remerge: algebraically a no-op, numerically one more
        # association boundary on the moment-sum path.
        out["s1"] = (out["s1"] - metric["s1"]) + metric["s1"]
        return out

    monkeypatch.setattr(recurrent, "_advance_metrics", reassociated)
    key = certify.row_key("sma_crossover", "scan:8", "append_step")
    live = certify.streaming_row("sma_crossover", "scan:8", "append_step")
    committed = certify.load_contract()
    diffs = certify.diff_rows(committed, {key: live})
    s1 = [d for d in diffs
          if d["output"] == "metric/s1" and d["field"] == "boundaries"]
    assert s1, f"reassociation not caught; diffs={diffs}"
    assert s1[0]["now"] == s1[0]["was"] + 1
    assert s1[0]["chain"] and any("add" in f for f in s1[0]["chain"])
    assert "introduced by" in s1[0]["message"]


def test_unpatched_row_matches_committed_contract():
    """The drift fixture above proves sensitivity; this proves
    specificity — the live unpatched row diffs empty (fresh trace, cache
    not consulted)."""
    key = certify.row_key("sma_crossover", "scan:8", "append_step")
    live = certify.streaming_row("sma_crossover", "scan:8", "append_step")
    assert certify.diff_rows(certify.load_contract(), {key: live}) == []


# ---------------------------------------------------------------------------
# Digest cones + rules + CLI exit codes
# ---------------------------------------------------------------------------

def test_digest_cones_certified_deterministic():
    rows = {r.key: r for r in certify.digest_rows()}
    synth = rows["digest/scenario_synth"]
    assert not synth.nondet
    assert all(rec["class"] != "nondet"
               for rec in synth.outputs.values())
    fused_row = rows["digest/scenario_fused"]
    assert not fused_row.nondet
    assert all(rec["class"] != "nondet"
               for rec in fused_row.outputs.values())
    splice = rows["digest/splice"]
    assert all(rec["class"] == "exact" and rec["boundaries"] == 0
               for rec in splice.outputs.values())


def _package_ctx():
    import distributed_backtesting_exploration_tpu as dbx

    return core.load_context(os.path.dirname(os.path.abspath(
        dbx.__file__)))


def test_digest_determinism_rule_flags_injected_scatter_add(monkeypatch):
    """A nondet primitive slipped into a digest cone is a finding (CLI
    exit 1 path), reported with the introducing chain."""
    rows = dict(certify.cached_rows())

    def poisoned(o, h, l, c, v, key):
        idx = jnp.array([0, 1, 1, 2])
        return {"close": jnp.zeros((4,), jnp.float32).at[idx].add(c[:4])}

    fn_args = [np.asarray(getattr(x, "close", x), np.float32)
               for x in [np.ones(8)] * 5] + [np.zeros(2, np.uint32)]
    rows["digest/scenario_synth"] = certify.certify_callable(
        "digest/scenario_synth", poisoned, fn_args)
    monkeypatch.setattr(certify, "cached_rows", lambda: rows)
    findings = certify.DigestDeterminismRule().check(_package_ctx())
    assert findings
    assert any("scatter-add" in f.message for f in findings)
    assert all(f.rule == "digest-determinism" for f in findings)


def test_run_certify_exit_codes(monkeypatch, tmp_path):
    """0 clean / 1 findings / 2 table drift — the documented contract."""
    clean = certify.run_certify()
    assert certify.exit_code(clean) == 0
    assert clean["rows"] == 59

    # Drift: a doctored committed table (one boundary count off).
    doctored = copy.deepcopy(certify.load_contract())
    key = certify.row_key("sma_crossover", "scan:8", "append_step")
    doctored["rows"][key]["outputs"]["metric/s1"]["boundaries"] += 1
    p = tmp_path / "numerics.contract.json"
    p.write_bytes(certify.canonical_bytes(doctored))
    monkeypatch.setenv("DBX_CONTRACT_PATH", str(p))
    drifted = certify.run_certify()
    assert certify.exit_code(drifted) == 2
    assert any(d["rule"] == "substrate-contract" for d in drifted["drift"])
    monkeypatch.delenv("DBX_CONTRACT_PATH")

    # Findings: a poisoned digest cone (drift-free table, nondet cone).
    rows = dict(certify.cached_rows())
    poisoned = certify.certify_callable(
        "digest/scenario_synth",
        lambda c: {"close": jnp.zeros((4,), jnp.float32)
                   .at[jnp.array([0, 1, 1, 2])].add(c[:4])},
        [_X])
    rows["digest/scenario_synth"] = poisoned
    monkeypatch.setattr(certify, "cached_rows", lambda: rows)
    monkeypatch.setenv("DBX_CONTRACT_PATH",
                       str(tmp_path / "match.json"))
    (tmp_path / "match.json").write_bytes(
        certify.canonical_bytes(certify.table_from_rows(rows)))
    poisoned_run = certify.run_certify()
    assert certify.exit_code(poisoned_run) == 1
    assert poisoned_run["findings"]


def test_corrupt_contract_table_is_not_missing(monkeypatch, tmp_path):
    """A truncated/merge-conflicted table must surface as unparseable —
    never as 'missing, run --update' (that advice would overwrite the
    only record of what was pinned)."""
    p = tmp_path / "corrupt.json"
    p.write_bytes(b'{"schema": 1, "rows": {')
    monkeypatch.setenv("DBX_CONTRACT_PATH", str(p))
    with pytest.raises(ValueError):
        certify.load_contract()
    res = certify.run_certify()
    assert certify.exit_code(res) == 2
    assert any("unparseable" in d["message"] for d in res["drift"])
    assert not any("no committed" in d["message"] for d in res["drift"])


def test_missing_contract_table_is_drift(monkeypatch, tmp_path):
    monkeypatch.setenv("DBX_CONTRACT_PATH",
                       str(tmp_path / "absent.json"))
    res = certify.run_certify()
    assert certify.exit_code(res) == 2
    assert any("no committed numerics contract" in d["message"]
               for d in res["drift"])


def test_update_writes_canonical_table(monkeypatch, tmp_path):
    p = tmp_path / "regen.json"
    monkeypatch.setenv("DBX_CONTRACT_PATH", str(p))
    res = certify.run_certify(update=True)
    assert certify.exit_code(res) == 0 and res["updated"]
    # The regenerated bytes equal the committed table's (same trace, same
    # canonical form) — byte-reproducibility across runs.
    committed = os.path.join(os.path.dirname(certify._PKG_DIR),
                             certify.CONTRACT_BASENAME)
    with open(committed, "rb") as fh:
        assert p.read_bytes() == fh.read()


def test_certify_rules_skipped_outside_package():
    """Like kernel-hygiene: no registry to certify outside the package —
    skipped, never silently clean."""
    from distributed_backtesting_exploration_tpu.analysis import (
        lint as lint_cli)

    fixtures = os.path.join(os.path.dirname(__file__), "fixtures", "lint")
    result = lint_cli.run([fixtures], core.all_rules())
    for rule in ("substrate-contract", "weak-type-provenance",
                 "digest-determinism"):
        assert rule in result["rules_skipped"]
        assert rule not in result["rules"]


def test_cli_certify_json_shape(capsys, monkeypatch):
    from distributed_backtesting_exploration_tpu.analysis import certify \
        as c

    rc = c.main(["--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["rows"] == 59
    assert out["drift"] == [] and out["findings"] == []
    assert out["contract"].endswith("numerics.contract.json")
