"""Tier-1 gate: the whole package lints clean under every dbxlint rule.

This is the check that turns review findings into mechanical invariants:
a new trace-time env read, an unlocked guarded-field mutation, an
import-time config capture, a sleeping RPC handler, a host callback /
f64 leak in a fused kernel, or proto/pb2 drift fails the suite — not the
next round of advice. Suppressions (with justification) are the escape
hatch; see DESIGN.md "Static analysis".
"""

import os

import distributed_backtesting_exploration_tpu as dbx
from distributed_backtesting_exploration_tpu.analysis import (
    certify, core, lint)


def test_package_lints_clean():
    pkg_dir = os.path.dirname(os.path.abspath(dbx.__file__))
    result = lint.run([pkg_dir], core.all_rules())
    assert result["unparseable"] == [], result["unparseable"]
    assert result["findings"] == [], "\n".join(
        f"{f['path']}:{f['line']}: [{f['rule']}] {f['message']}"
        for f in result["findings"])
    assert result["clean"]
    # The gate must actually have run every registered rule.
    assert set(result["rules"]) == {
        "trace-time-env", "lock-discipline", "lock-order", "atomicity",
        "lock-blocking", "import-time-config", "blocking-call",
        "obs-cardinality", "journal-discipline", "kernel-hygiene",
        "substrate-contract", "weak-type-provenance", "digest-determinism",
        "proto-drift"}


def test_certify_clean_and_contract_table_pinned():
    """The numerics drift gate: regenerate the contract table from a live
    trace on the tiny pinned shapes and require BYTE equality with the
    committed numerics.contract.json (canonical form: sorted keys, no
    timestamps), plus zero weak-type/digest findings. A kernel edit that
    adds an association boundary, drops a selection guarantee, or leaks
    a nondet primitive into a digest path fails here with the
    introducing equation chain (exit-code contract: dbxcert 0 clean /
    1 findings / 2 drift)."""
    result = certify.run_certify()
    assert result["findings"] == [], result["findings"]
    assert result["drift"] == [], "\n".join(
        d["message"] for d in result["drift"])
    assert certify.exit_code(result) == 0
    live = certify.canonical_bytes(
        certify.table_from_rows(certify.cached_rows()))
    with open(certify.contract_path(), "rb") as fh:
        assert live == fh.read(), \
            "numerics.contract.json is stale — regenerate with " \
            "`dbxcert --update` and review the diff"


def test_cli_module_entrypoint_is_wired():
    """`python -m ...analysis.lint --list-rules` is the documented CLI and
    the `dbxlint` console script drives the same main()."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-m",
         "distributed_backtesting_exploration_tpu.analysis.lint",
         "--list-rules"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    for rule in ("trace-time-env", "kernel-hygiene", "proto-drift"):
        assert rule in out.stdout
