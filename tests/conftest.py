"""Test harness setup: run JAX on CPU with 8 virtual devices.

Multi-chip code paths (mesh/shard_map/ppermute) are validated without TPU
hardware by forcing the host platform to expose 8 devices — the strategy
SURVEY.md section 4 prescribes. Must run before the first ``import jax``.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (env must be set first)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {devs}"
    return devs
