"""Test harness setup: run JAX on CPU with 8 virtual devices.

Multi-chip code paths (mesh/shard_map/ppermute) are validated without TPU
hardware by forcing the host platform to expose 8 devices — the strategy
SURVEY.md section 4 prescribes. The environment's ``sitecustomize`` registers
the real-TPU "axon" backend and pins ``jax_platforms="axon,cpu"`` via
``jax.config`` *before any user code runs*, so an env-var override is
ineffective — the config must be updated through ``jax.config`` after import
and before the first backend initialization. ``XLA_FLAGS`` must still be set
before the CPU client spins up.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (flags must be set first)

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache shared across test processes/runs: the
# suite's wall on the 1-core box is dominated by CPU compiles (SPMD
# partitioning, interpret-mode pallas), and every entry is keyed by the HLO
# hash so re-runs of unchanged kernels skip straight to execution (measured
# cross-process hit on this box). Threshold configs are best-effort — names
# have drifted across jax generations.
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("DBX_TEST_COMPILE_CACHE",
                                 "/tmp/dbx_test_jax_cache"))
for _opt, _val in (("jax_persistent_cache_min_compile_time_secs", 0.5),
                   ("jax_persistent_cache_min_entry_size_bytes", 0)):
    try:
        jax.config.update(_opt, _val)
    except Exception:  # pragma: no cover - older/newer jax
        pass

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {devs}"
    return devs
