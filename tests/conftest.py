"""Test harness setup: run JAX on CPU with 8 virtual devices.

Multi-chip code paths (mesh/shard_map/ppermute) are validated without TPU
hardware by forcing the host platform to expose 8 devices — the strategy
SURVEY.md section 4 prescribes. The environment's ``sitecustomize`` registers
the real-TPU "axon" backend and pins ``jax_platforms="axon,cpu"`` via
``jax.config`` *before any user code runs*, so an env-var override is
ineffective — the config must be updated through ``jax.config`` after import
and before the first backend initialization. ``XLA_FLAGS`` must still be set
before the CPU client spins up.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (flags must be set first)

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache shared across test processes/runs: the
# suite's wall on the 1-core box is dominated by CPU compiles (SPMD
# partitioning, interpret-mode pallas), and every entry is keyed by the HLO
# hash so re-runs of unchanged kernels skip straight to execution (measured
# cross-process hit on this box). The configuration (including the
# best-effort threshold options whose names drift across jax generations)
# lives in ONE place — tune.compile_cache, the same module dispatcher and
# worker runtimes use.
from distributed_backtesting_exploration_tpu.tune import (  # noqa: E402
    compile_cache as _compile_cache)

_compile_cache.configure(os.environ.get("DBX_TEST_COMPILE_CACHE",
                                        "/tmp/dbx_test_jax_cache"))

# Runtime lockdep (analysis.lockdep): DBX_LOCKDEP=1 turns the WHOLE
# tier-1 suite into a race harness — every in-process gRPC integration
# fixture then runs with instrumented package locks recording real
# acquisition edges and blocking-under-lock violations. Installed here,
# before any fixture constructs a queue/worker/cache, so every package
# lock is wrapped; a no-op (nothing patched) when the knob is unset.
from distributed_backtesting_exploration_tpu.analysis import (  # noqa: E402
    lockdep as _lockdep)

_lockdep.maybe_install()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {devs}"
    return devs
