"""Runtime lockdep (analysis.lockdep): unit tests on synthetic locks —
the ABBA near-deadlock, blocking-under-lock, the obs-registry exemption,
table bounds, zero-cost-off — plus the tier-1 gate: an existing
rpc_integration scenario run under ``DBX_LOCKDEP=1`` semantics with zero
violations (every dispatcher/worker test doubles as a race harness via
the conftest env hook; this test pins one scenario explicitly)."""

import sys
import threading
import time
import types

import pytest

from distributed_backtesting_exploration_tpu.analysis import lockdep


@pytest.fixture()
def installed():
    """Install + clean tables. Teardown restores the PRIOR state: when
    the suite itself runs under ``DBX_LOCKDEP=1`` (the conftest race
    harness) the shim must stay active for every later test — only a
    test-local install is torn down."""
    was_active = lockdep.active()
    lockdep.install()
    lockdep.reset()
    try:
        yield
    finally:
        if not was_active:
            lockdep.uninstall()
        lockdep.reset()


def _synthetic_locks(n=2, reentrant=False):
    """Instrumented locks with distinct synthetic creation-site classes
    (the factory's frame-detection has its own test below)."""
    real = threading.RLock if reentrant else lockdep._RealLock
    return [lockdep._LockdepLock(real(), f"test._Syn:{i}", reentrant)
            for i in range(n)]


def test_abba_cycle_detected_without_deadlocking(installed):
    """Two threads take two locks in OPPOSITE orders, sequenced so the
    real deadlock never materializes — lockdep must still report the
    order-graph cycle (that is the point: the report arrives before the
    freeze ever does)."""
    a, b = _synthetic_locks(2)
    first_done = threading.Event()

    def t1():
        with a:
            with b:        # edge a -> b
                pass
        first_done.set()

    def t2():
        first_done.wait(timeout=10)
        with b:
            with a:        # edge b -> a: closes the cycle
                pass

    th1 = threading.Thread(target=t1)
    th2 = threading.Thread(target=t2)
    th1.start()
    th2.start()
    th1.join(timeout=10)
    th2.join(timeout=10)
    r = lockdep.report()
    assert r["edges"] == 2
    cycles = [v for v in r["violations"] if v["kind"] == "order-cycle"]
    assert len(cycles) == 1
    assert "test._Syn:0" in cycles[0]["path"]
    assert "test._Syn:1" in cycles[0]["path"]


def test_consistent_order_records_edges_but_no_violation(installed):
    a, b = _synthetic_locks(2)
    for _ in range(3):
        with a:
            with b:
                pass
    r = lockdep.report()
    assert r["edges"] == 1
    assert r["edge_counts"]["test._Syn:0 -> test._Syn:1"] == 3
    assert r["violations"] == []
    # Held-duration stats accumulate per lock class.
    assert r["held"]["test._Syn:0"]["acquires"] == 3


def test_blocking_call_under_lock_is_a_violation(installed):
    (a,) = _synthetic_locks(1)
    time.sleep(0)              # lock-free sleep: clean
    with a:
        time.sleep(0)          # VIOLATION: sleep while holding a
    r = lockdep.report()
    blocking = [v for v in r["violations"] if v["kind"] == "blocking"]
    assert len(blocking) == 1
    assert blocking[0]["call"] == "time.sleep"
    assert "test._Syn:0" in blocking[0]["locks"]


def test_self_reacquire_of_plain_lock_reported(installed):
    # Sequenced so the real deadlock never happens: report-then-proceed
    # is exercised on a lock the thread merely ATTEMPTS to re-take via
    # a non-blocking probe after the violation is recorded.
    (a,) = _synthetic_locks(1)
    with a:
        lockdep._before_blocking_acquire(a)   # what a blocking re-take does
    r = lockdep.report()
    kinds = [v["kind"] for v in r["violations"]]
    assert kinds == ["self-deadlock"]


def test_trylock_records_nothing(installed):
    a, b = _synthetic_locks(2)
    with a:
        assert b.acquire(blocking=False)
        b.release()
    assert lockdep.report()["edges"] == 0   # a trylock cannot deadlock


def test_rlock_reentry_is_not_a_violation(installed):
    (r_lock,) = _synthetic_locks(1, reentrant=True)
    with r_lock:
        with r_lock:
            pass
    r = lockdep.report()
    assert r["violations"] == []
    assert r["edges"] == 0


def test_edge_table_is_bounded(installed, monkeypatch):
    monkeypatch.setenv("DBX_LOCKDEP_MAX_EDGES", "1")
    locks_ = _synthetic_locks(3)
    with locks_[0]:
        with locks_[1]:
            pass
    with locks_[0]:
        with locks_[2]:
            pass
    r = lockdep.report()
    assert r["edges"] == 1
    assert r["dropped_edges"] == 1   # counted, never silent


def test_factory_wraps_package_locks_only(installed):
    """The patched ``threading.Lock`` instruments locks created from
    this package's modules (class = creation site) and passes every
    other creator through raw."""
    mod = types.ModuleType(
        "distributed_backtesting_exploration_tpu._lockdep_fixture")
    sys.modules[mod.__name__] = mod
    try:
        exec(compile(
            "import threading\n"
            "class Box:\n"
            "    def __init__(self):\n"
            "        self.lck = threading.Lock()\n",
            "<fixture>", "exec"), mod.__dict__)
        box = mod.Box()
        assert isinstance(box.lck, lockdep._LockdepLock)
        assert box.lck.key.startswith("_lockdep_fixture.Box:")
        # Non-package creator: raw.
        outside = threading.Lock()
        assert not isinstance(outside, lockdep._LockdepLock)
    finally:
        del sys.modules[mod.__name__]


def test_obs_registry_and_events_locks_are_exempt(installed):
    """Satellite: Gauge/Counter internal locks must NOT be instrumented
    — every metric increment takes one, so edge recording there would
    flood the table with a metrics-path edge under every package lock
    (including from lockdep's own reporting)."""
    from distributed_backtesting_exploration_tpu import obs

    reg = obs.Registry()
    c = reg.counter("fx_lockdep_exempt_total")
    g = reg.gauge("fx_lockdep_exempt")
    assert not isinstance(reg._lock, lockdep._LockdepLock)
    assert not isinstance(c._lock, lockdep._LockdepLock)
    assert not isinstance(g._lock, lockdep._LockdepLock)
    # Metric updates under an instrumented lock record NO edges.
    (a,) = _synthetic_locks(1)
    with a:
        c.inc()
        g.set(3)
    assert lockdep.report()["edges"] == 0


def test_zero_cost_when_off():
    """Without install() nothing is patched; maybe_install() without the
    env knob is a no-op. (Skipped when the suite itself runs as the
    DBX_LOCKDEP=1 race harness — the shim is then rightfully live.)"""
    import os

    if lockdep.enabled():
        pytest.skip("suite running under the DBX_LOCKDEP=1 harness")
    assert not lockdep.active()
    assert threading.Lock is lockdep._RealLock
    assert time.sleep is lockdep._real_sleep
    if os.environ.get("DBX_LOCKDEP") is None:
        lockdep.maybe_install()
        assert threading.Lock is lockdep._RealLock


def test_violations_surface_on_obs_metrics(installed):
    from distributed_backtesting_exploration_tpu import obs

    (a,) = _synthetic_locks(1)
    with a:
        time.sleep(0)
    snap = obs.get_registry().snapshot()
    fam = snap["dbx_lockdep_violations_total"]["values"]
    assert fam.get("kind=blocking", 0) >= 1
    assert "dbx_lockdep_edges" in snap


# ---------------------------------------------------------------------------
# Tier-1 gate: an existing rpc_integration scenario under lockdep
# ---------------------------------------------------------------------------

def test_rpc_integration_scenario_under_lockdep_is_violation_free(
        installed, tmp_path):
    """The end-to-end instant-backend scenario (test_rpc_integration's
    first test) runs with every package lock instrumented: real gRPC
    loopback server, real worker thread, journaled queue. Zero lockdep
    violations is the acceptance bar the pipelined-executor PR will be
    held to; the acquisition-edge table doubles as living documentation
    of the fleet's real lock nesting."""
    from distributed_backtesting_exploration_tpu.rpc import compute
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        Dispatcher, DispatcherServer, JobQueue, PeerRegistry,
        parse_grid, synthetic_jobs)
    from distributed_backtesting_exploration_tpu.rpc.worker import Worker

    queue = JobQueue()
    # The queue's own lock must be instrumented — install ran before
    # construction (the same ordering the conftest env hook guarantees).
    assert isinstance(queue._lock, lockdep._LockdepLock)
    grid = parse_grid("fast=3:5,slow=10:14:2")
    for rec in synthetic_jobs(6, 64, "sma_crossover", grid):
        queue.enqueue(rec)
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=10.0),
                      results_dir=str(tmp_path / "results"))
    srv = DispatcherServer(disp, bind="localhost:0",
                           prune_interval_s=0.1).start()
    w = None
    t = None
    try:
        w = Worker(f"localhost:{srv.port}", compute.InstantBackend(),
                   poll_interval_s=0.02, status_interval_s=0.05)
        t = threading.Thread(target=lambda: w.run(max_idle_polls=10),
                             daemon=True)
        t.start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not queue.drained:
            time.sleep(0.02)
        assert queue.drained, "queue did not drain under lockdep"
        assert queue.stats()["jobs_completed"] == 6
    finally:
        if w is not None:
            w.stop()
        if t is not None:
            t.join(timeout=10)
        srv.stop()
    r = lockdep.report()
    assert r["violations"] == [], r["violations"]
    # The harness actually instrumented the hot path (non-vacuous).
    assert any("JobQueue" in cls for cls in r["held"]), r["held"]


def test_pipelined_executor_under_lockdep_is_violation_free(installed):
    """Round-14 acceptance gate (the PR-12 precedent this PR was built
    to be held to): the double-buffered pipeline — submit thread,
    collector thread, bounded handoff queue, pipeline accounting lock,
    writer-serialized page pool — drains a real gRPC loopback fleet with
    every package lock instrumented and ZERO ordering or
    blocking-under-lock violations."""
    from distributed_backtesting_exploration_tpu.rpc import compute
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        Dispatcher, DispatcherServer, JobQueue, PeerRegistry,
        parse_grid, synthetic_jobs)
    from distributed_backtesting_exploration_tpu.rpc import worker as wmod

    assert wmod.pipeline_enabled(), \
        "the gate must exercise the pipelined path (DBX_PIPELINE left on)"

    class _TwoPhase:
        """submit/collect backend: slow collect so batches genuinely
        overlap through the handoff queue."""

        chips = 1

        def submit(self, jobs):
            return list(jobs)

        def collect(self, jobs):
            time.sleep(0.02)
            return [compute.Completion(j.id, b"", 0.02,
                                       trace_id=j.trace_id)
                    for j in jobs]

    queue = JobQueue()
    assert isinstance(queue._lock, lockdep._LockdepLock)
    grid = parse_grid("fast=3:5,slow=10:14:2")
    for rec in synthetic_jobs(12, 32, "sma_crossover", grid):
        queue.enqueue(rec)
    disp = Dispatcher(queue, PeerRegistry(prune_window_s=10.0))
    srv = DispatcherServer(disp, bind="localhost:0",
                           prune_interval_s=0.1).start()
    w = None
    t = None
    try:
        w = wmod.Worker(f"localhost:{srv.port}", _TwoPhase(),
                        poll_interval_s=0.01, status_interval_s=0.05,
                        jobs_per_chip=2)
        # The pipeline accounting lock itself is instrumented.
        assert isinstance(w._pipeline_lock, lockdep._LockdepLock)
        t = threading.Thread(target=lambda: w.run(max_idle_polls=10),
                             daemon=True)
        t.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not queue.drained:
            time.sleep(0.02)
        assert queue.drained, "pipelined drain wedged under lockdep"
        assert queue.stats()["jobs_completed"] == 12
    finally:
        if w is not None:
            w.stop()
        if t is not None:
            t.join(timeout=10)
        srv.stop()
    r = lockdep.report()
    assert r["violations"] == [], r["violations"]
    # Non-vacuous: the pipeline lock recorded real held intervals.
    assert any("Worker" in cls for cls in r["held"]), r["held"]
