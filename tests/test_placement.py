"""Locality-scored placement (round 20): the live dispatch stage.

Tentpole coverage: the take()-time placement gate defers a job — within
the ``DBX_PLACEMENT_DEFER_CAP`` budget — toward the worker the shared op
model scores cheapest (carry-store hit vs full reprice, panel residency
vs h2d, compile warmth), the chain-settling rule holds an append link
while its parent job is still undispatched, and the degradation ladder
bottoms out at pure WFQ order bit-identically (kill switch, empty fleet
view). The live table and the round-19 shadow scorer price through ONE
``placement_cost`` implementation — cross-pinned here. Fairness stays
WFQ's: a whale workload under live placement inflates small tenants'
service by bounded deferrals only, never starvation.
"""

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu import obs as obs_mod
from distributed_backtesting_exploration_tpu.obs import (
    decisions as dec_mod, why)
from distributed_backtesting_exploration_tpu.rpc import (
    backtesting_pb2 as pb, panel_store)
from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
    Dispatcher, JobQueue, JobRecord, PeerRegistry, parse_grid)
from distributed_backtesting_exploration_tpu.rpc.journal import Journal
from distributed_backtesting_exploration_tpu.sched import (
    placement, reset_tenant_buckets)
from distributed_backtesting_exploration_tpu.utils import data


@pytest.fixture(autouse=True)
def _fresh_buckets():
    reset_tenant_buckets()
    yield
    reset_tenant_buckets()


GRID = parse_grid("fast=3:5,slow=10:14:2")


# ---------------------------------------------------------------------------
# Policy core (sched/placement.py): pure functions, env knobs
# ---------------------------------------------------------------------------

def test_should_defer_budget_semantics():
    """The entire deferral budget in one function: relative ratio bar,
    cap exhaustion, NaN-safety — ties and garbage always serve."""
    # Best worker wins by > PLACEMENT_RATIO with budget left: defer.
    assert placement.should_defer(1.0, 0.1, 0, 2)
    assert placement.should_defer(1.0, 0.1, 1, 2)
    # Budget spent: serve no matter the gap.
    assert not placement.should_defer(1.0, 0.001, 2, 2)
    # cap=0 keeps scoring live but never defers.
    assert not placement.should_defer(1.0, 0.001, 0, 0)
    # Inside the ratio bar (including exact ties): serve.
    assert not placement.should_defer(1.0, 1.0, 0, 2)
    assert not placement.should_defer(1.0, 0.7, 0, 2)
    # Non-finite garbage from a poisoned model: serve.
    assert not placement.should_defer(float("nan"), 0.1, 0, 2)
    assert not placement.should_defer(1.0, float("nan"), 0, 2)
    # Chain settling draws on the SAME budget.
    assert placement.should_wait_for_parent(0, 2)
    assert placement.should_wait_for_parent(1, 2)
    assert not placement.should_wait_for_parent(2, 2)
    assert not placement.should_wait_for_parent(0, 0)


def test_knob_parsing(monkeypatch):
    monkeypatch.delenv("DBX_PLACEMENT", raising=False)
    assert placement.enabled()                        # default on
    for off in ("0", "off", "FALSE"):
        monkeypatch.setenv("DBX_PLACEMENT", off)
        assert not placement.enabled()
    monkeypatch.setenv("DBX_PLACEMENT", "1")
    assert placement.enabled()
    monkeypatch.delenv("DBX_PLACEMENT_DEFER_CAP", raising=False)
    assert placement.defer_cap() == 2                 # default
    monkeypatch.setenv("DBX_PLACEMENT_DEFER_CAP", "7")
    assert placement.defer_cap() == 7
    monkeypatch.setenv("DBX_PLACEMENT_DEFER_CAP", "-3")
    assert placement.defer_cap() == 0                 # floored
    monkeypatch.setenv("DBX_PLACEMENT_DEFER_CAP", "garbage")
    assert placement.defer_cap() == 2                 # parse -> default


# ---------------------------------------------------------------------------
# Score table: stale/straggler score-down, cross-pin vs the shadow scorer
# ---------------------------------------------------------------------------

_D = "ab" * 32


class _ViewFleet:
    """Fleet stub exposing only the table builder's placement_view."""

    def __init__(self, view):
        self._view = view

    def placement_view(self):
        return self._view


def test_table_scores_down_degraded_workers_never_excludes(monkeypatch):
    """A stale+straggling worker is penalized multiplicatively (loses
    ties and close calls) but stays in the candidate set — it still wins
    when it is the ONLY holder of the state (the liveness rule)."""
    monkeypatch.setenv("DBX_DECISIONS_H2D_GBPS", "0.000001")  # 1 KB/s
    plane = dec_mod.DecisionPlane(
        fleet=_ViewFleet({
            "degraded": {"stale": True, "stragglers": ("execute",),
                         "resident": [_D[:12]]},
            "clean": {},
        }),
        registry=obs_mod.Registry())
    try:
        table = plane.refresh_placement_table()
        assert set(table.workers) == {"clean", "degraded"}
        pen = table.workers["degraded"]["penalty"]
        assert pen == dec_mod.STALE_PENALTY * dec_mod.STRAGGLER_PENALTY
        # An append job whose base only the degraded worker holds: the
        # carry-hit + residency terms dwarf the 8x penalty — degraded
        # wins anyway (scored down, never excluded).
        ctx = {"units": 1000.0, "family": "sma_crossover", "digest": "",
               "base_digest": _D, "panel_b": 100_000, "frac": 0.01,
               "rate": dec_mod.h2d_rate_bps(),
               "cold": dec_mod.compile_wall_s()}
        mine, best_wid, best = table.rank(ctx, "clean")
        assert best_wid == "degraded"
        assert best["carry_hit"] and best["penalty"] == pen
        assert mine["transfer_s"] > 0.0 and best["transfer_s"] == 0.0
        # A plain job held nowhere: the penalty makes degraded LOSE the
        # otherwise-tied rank.
        plain = dict(ctx, base_digest="", frac=1.0)
        _, best_wid2, _ = table.rank(plain, "clean")
        assert best_wid2 == "clean"
    finally:
        plane.close()


def test_cross_pin_live_table_and_shadow_score_identically():
    """THE single-op-model rule: for the same (job, worker-state) pins
    the live table's score and the shadow scorer's per-candidate cost
    are the same numbers — one ``placement_cost`` implementation, no
    drift between the policy that routes and the regret that audits."""
    blob = b"\0" * 40 * 512                    # 512 "bars" at ~40 B/bar
    delivered = {"fast": {_D}}
    plane = dec_mod.DecisionPlane(fleet=None, registry=obs_mod.Registry())
    try:
        plane.attach_placement(lambda: delivered)
        # Calibrate one completion on ``fast`` so both sides price with
        # measured spu and real family warmth (any_warmth semantics).
        plane.submit([{
            "jid": "cal", "trace_id": "cal", "worker": "fast",
            "tenant": "default", "strategy": "sma_crossover",
            "combos": 4.0, "affinity_skips": 0, "wfq": None,
            "digest": _D, "panel_b": len(blob), "append_parent": "",
            "base_len": 0, "bars": len(blob) // 40, "t_take": 1.0,
            "route": "digest_only"}])
        plane.observe_completion("fast", "cal", elapsed_s=0.5)
        assert plane.flush()

        rec = JobRecord(id="x1", strategy="sma_crossover", grid=GRID,
                        ohlcv=blob, panel_digest=_D)
        ctx = dec_mod.placement_ctx(rec)
        table = plane.refresh_placement_table()

        plane.submit([{
            "jid": "x1", "trace_id": "x1", "worker": "slow",
            "tenant": "default", "strategy": "sma_crossover",
            "combos": float(rec.combos), "affinity_skips": 0,
            "wfq": None, "digest": _D, "panel_b": len(blob),
            "append_parent": "", "base_len": 0,
            "bars": len(blob) // 40, "t_take": 2.0, "route": "full"}])
        assert plane.flush()
        shadow = plane.recent()[-1]["shadow"]
        assert shadow["candidates"] == 2
        for wid in ("fast", "slow"):
            live = table.score(ctx, wid)
            for k in ("cost_s", "exec_s", "transfer_s", "compile_s"):
                assert shadow["costs"][wid][k] == pytest.approx(
                    live[k], rel=1e-9, abs=1e-12), (wid, k)
        # And the pins mean what they should: the delivered-set holder
        # skips the transfer, the uncalibrated worker pays the cold wall.
        assert table.score(ctx, "fast")["transfer_s"] == 0.0
        assert table.score(ctx, "slow")["transfer_s"] > 0.0
        assert table.score(ctx, "slow")["compile_s"] > 0.0
    finally:
        plane.close()


# ---------------------------------------------------------------------------
# Dispatcher-level: deferral to the holder, cap exhaustion, chain settling
# ---------------------------------------------------------------------------

def _chain_blobs(n0=128, dt=8, seed=50):
    full = data.synthetic_ohlcv(1, n0 + dt, seed=seed)

    def cut(lo, hi):
        return data.to_wire_bytes(
            type(full)(*(np.asarray(f[0, lo:hi]) for f in full)))

    return cut(0, n0), cut(n0, n0 + dt), cut(0, n0 + dt)


def _poll(disp, wid, n=4):
    """One direct RequestJobs poll with a deterministic table refresh
    (tests never race the decision plane's 50 ms daemon tick)."""
    disp.decisions.refresh_placement_table()
    return list(disp.RequestJobs(pb.JobsRequest(
        worker_id=wid, chips=1, jobs_per_chip=n,
        accepts_digest_only=True), None).jobs)


def _complete(disp, wid, jids):
    disp.CompleteJobs(pb.CompleteBatch(
        worker_id=wid, items=[pb.CompleteItem(id=j) for j in jids]), None)


def test_defers_to_carry_holder_then_caps_work_conserving():
    """A single live non-holder is deferred exactly defer_cap() polls
    for the (silent) carry holder, then served in full — work conserving
    with `drained` never flickering while the job is held. The decision
    record carries the placement verdict (outcome=cap, defers==cap) and
    dbxwhy renders it."""
    base_blob, delta_blob, _ = _chain_blobs(seed=51)
    q = JobQueue()
    q.enqueue(JobRecord(id="base", strategy="sma_crossover", grid=GRID,
                        ohlcv=base_blob))
    disp = Dispatcher(q, PeerRegistry(prune_window_s=60.0))
    reg = obs_mod.get_registry()
    c0 = {o: reg.counter("dbx_placement_total", outcome=o).value
          for o in ("served", "deferred", "cap")}
    try:
        (bjob,) = _poll(disp, "holder")
        assert bjob.id == "base" and bjob.ohlcv
        _complete(disp, "holder", ["base"])
        arec, outcome, _, _ = q.append_bars(
            q._records["base"].panel_digest, 128, delta_blob,
            strategy="sma_crossover", grid=GRID)
        assert outcome == "extended"

        cap = placement.defer_cap()
        for i in range(cap):
            assert _poll(disp, "other") == []   # held for the holder
            assert not q.drained                # never flickers
            assert q._records[arec.id].affinity_skips == i + 1
        got = _poll(disp, "other")
        assert [j.id for j in got] == [arec.id]
        assert got[0].ohlcv                     # non-holder: full bytes
        _complete(disp, "other", [arec.id])
        assert q.drained

        c1 = {o: reg.counter("dbx_placement_total", outcome=o).value
              for o in ("served", "deferred", "cap")}
        assert c1["deferred"] - c0["deferred"] == cap
        assert c1["cap"] - c0["cap"] == 1

        disp.decisions.flush(timeout=10.0)
        rec = next(r for r in disp.decisions.recent()
                   if r["jid"] == arec.id)
        pl = rec["placement"]
        assert pl["outcome"] == "cap" and pl["defers"] == cap
        assert pl["best"] == "holder" and pl["live"] is True
        text = why.render_decision(rec, 0, 1)
        assert "placement: outcome=cap" in text
        assert "best-placed was holder" in text
        assert f"defers={cap}/{cap}" in text
    finally:
        disp.close()


def test_chain_settling_defers_until_parent_dispatches():
    """An append link popped BEFORE its parent job has dispatched has no
    carry holder anywhere (equal scores — the ratio bar can never fire):
    the chain-settling rule holds it, the parent dispatches first, and
    the next poll routes the link delta-only to the parent's worker."""
    base_blob, delta_blob, ext_blob = _chain_blobs(seed=52)
    base_d = panel_store.panel_digest(base_blob)
    q = JobQueue()
    # Adversarial intake order: the child lands AHEAD of its parent.
    q.enqueue(JobRecord(id="child", strategy="sma_crossover", grid=GRID,
                        ohlcv=ext_blob, append_parent=base_d,
                        append_base_len=128, delta=delta_blob))
    q.enqueue(JobRecord(id="parent", strategy="sma_crossover", grid=GRID,
                        ohlcv=base_blob))
    disp = Dispatcher(q, PeerRegistry(prune_window_s=60.0))
    try:
        # Arm the table with the poller (no deliveries yet): the gate
        # only runs with a live table.
        with disp._delivered_lock:
            disp._delivered.setdefault("w1", set())
        got = _poll(disp, "w1", n=1)
        # The child was popped first (FIFO), held for its parent; the
        # SAME take then served the parent — no wasted poll.
        assert [j.id for j in got] == ["parent"]
        assert q._records["child"].affinity_skips == 1
        # Parent settled and held by w1 now: the child follows it,
        # delta-only (w1 holds the base).
        got2 = _poll(disp, "w1", n=1)
        assert [j.id for j in got2] == ["child"]
        assert got2[0].ohlcv == b"" and got2[0].append_delta
        _complete(disp, "w1", ["parent", "child"])
        assert q.drained
    finally:
        disp.close()


def test_same_poll_parent_then_child_needs_no_deferral():
    """A parent served earlier in the SAME poll counts as settled: a
    chain enqueued in order rides one batch with zero deferrals (the
    gate's served-digest grace, not the pending-refcount — that only
    drops at commit, after the admit loop)."""
    base_blob, delta_blob, ext_blob = _chain_blobs(seed=53)
    base_d = panel_store.panel_digest(base_blob)
    q = JobQueue()
    q.enqueue(JobRecord(id="parent", strategy="sma_crossover", grid=GRID,
                        ohlcv=base_blob))
    q.enqueue(JobRecord(id="child", strategy="sma_crossover", grid=GRID,
                        ohlcv=ext_blob, append_parent=base_d,
                        append_base_len=128, delta=delta_blob))
    disp = Dispatcher(q, PeerRegistry(prune_window_s=60.0))
    try:
        with disp._delivered_lock:
            disp._delivered.setdefault("w1", set())
        got = _poll(disp, "w1", n=4)
        assert [j.id for j in got] == ["parent", "child"]
        assert q._records["child"].affinity_skips == 0
        _complete(disp, "w1", ["parent", "child"])
        assert q.drained
    finally:
        disp.close()


# ---------------------------------------------------------------------------
# Degradation ladder: kill switch and empty fleet view are pure WFQ
# ---------------------------------------------------------------------------

def _tenant_recs(prefix=""):
    """The round-9 whale-vs-smalls adversarial intake, fresh records."""
    recs = []
    for i in range(6):
        recs.append(JobRecord(
            id=f"{prefix}whale-{i}", strategy="sma_crossover",
            grid={"fast": np.arange(32.0, dtype=np.float32) + 5.0},
            ohlcv=b"W-payload-%02d" % i, tenant="whale"))
    for t in ("small_a", "small_b"):
        for i in range(4):
            recs.append(JobRecord(
                id=f"{prefix}{t}-{i}", strategy="sma_crossover",
                grid={"fast": np.arange(4.0, dtype=np.float32) + 5.0},
                ohlcv=f"{t}-payload-{i}".encode(), tenant=t))
    return recs


def _drain_order(disp, q, wid, n=4, max_polls=200):
    order = []
    for _ in range(max_polls):
        order.extend(j.id for j in _poll(disp, wid, n=n))
        if len(order) == len(q._records):
            return order
    raise AssertionError(f"drain wedged after {max_polls} polls: {order}")


def test_kill_switch_and_empty_view_are_pure_wfq_bit_identical(
        monkeypatch):
    """The degradation ladder's floor, pinned: DBX_PLACEMENT=0 (with a
    live, biased table!) and placement-on-but-empty-fleet both serve the
    EXACT round-19 WFQ order — and a raw queue with no dispatcher at all
    agrees. affinity_skips stays untouched on the kill-switch path."""
    # Rung 0: the raw queue's WFQ order (round-19 behavior).
    q0 = JobQueue()
    for r in _tenant_recs():
        q0.enqueue(r)
    want = [r.id for r, _ in q0.take(14, "w2")]

    # Rung 1: kill switch down, despite a table biased toward w1.
    monkeypatch.setenv("DBX_PLACEMENT", "0")
    monkeypatch.setenv("DBX_DECISIONS_H2D_GBPS", "0.000001")
    q1 = JobQueue()
    recs = _tenant_recs()
    for r in recs:
        q1.enqueue(r)
    disp1 = Dispatcher(q1, PeerRegistry(prune_window_s=60.0))
    try:
        with disp1._delivered_lock:
            disp1._delivered["w1"] = {r.panel_digest for r in recs}
        assert _drain_order(disp1, q1, "w2", n=14) == want
        assert all(r.affinity_skips == 0 for r in q1._records.values())
    finally:
        disp1.close()

    # Rung 2: placement on, but nothing to score with (no frames, no
    # deliveries -> no table): same order again.
    monkeypatch.setenv("DBX_PLACEMENT", "1")
    q2 = JobQueue()
    for r in _tenant_recs():
        q2.enqueue(r)
    disp2 = Dispatcher(q2, PeerRegistry(prune_window_s=60.0))
    try:
        assert _drain_order(disp2, q2, "w2", n=14) == want
    finally:
        disp2.close()

    # Rung 3: a biased table EXISTS but has aged past TABLE_MAX_AGE_S
    # (wedged scorer thread): the take path refuses it — same order,
    # no deferrals. Polls go direct (no refresh), unlike _poll().
    q3 = JobQueue()
    recs3 = _tenant_recs()
    for r in recs3:
        q3.enqueue(r)
    disp3 = Dispatcher(q3, PeerRegistry(prune_window_s=60.0))
    try:
        with disp3._delivered_lock:
            disp3._delivered["w1"] = {r.panel_digest for r in recs3}
        table = disp3.decisions.refresh_placement_table()
        table.built_s -= 10.0 * dec_mod.DecisionPlane.TABLE_MAX_AGE_S
        got = [j.id for j in disp3.RequestJobs(pb.JobsRequest(
            worker_id="w2", chips=1, jobs_per_chip=14,
            accepts_digest_only=True), None).jobs]
        assert got == want
        assert all(r.affinity_skips == 0 for r in q3._records.values())
    finally:
        disp3.close()


def test_whale_fairness_survives_live_placement(monkeypatch):
    """PR-8's fairness bar under the round-20 stage: with every whale
    panel resident on a worker that never polls, the whale's jobs burn
    their full deferral budget — yet the polling worker still drains
    everything (work conservation) and the small tenants' mean serve
    position inflates by well under 2x vs the locality-blind order."""
    monkeypatch.setenv("DBX_DECISIONS_H2D_GBPS", "0.000001")  # 1 KB/s

    def positions(order):
        out = {}
        for t in ("whale", "small_a", "small_b"):
            idx = [i for i, j in enumerate(order) if j.startswith(t)]
            out[t] = sum(idx) / len(idx)
        return out

    # Blind arm.
    monkeypatch.setenv("DBX_PLACEMENT", "0")
    qa = JobQueue()
    for r in _tenant_recs():
        qa.enqueue(r)
    da = Dispatcher(qa, PeerRegistry(prune_window_s=60.0))
    try:
        pos_blind = positions(_drain_order(da, qa, "w2"))
    finally:
        da.close()

    # Live arm: w1 holds every whale panel but never polls.
    monkeypatch.setenv("DBX_PLACEMENT", "1")
    qb = JobQueue()
    recs = _tenant_recs()
    for r in recs:
        qb.enqueue(r)
    db = Dispatcher(qb, PeerRegistry(prune_window_s=60.0))
    try:
        with db._delivered_lock:
            db._delivered["w1"] = {
                r.panel_digest for r in recs if r.tenant == "whale"}
        order = _drain_order(db, qb, "w2")
        assert len(order) == len(recs)          # work conserving
        cap = placement.defer_cap()
        assert all(r.affinity_skips <= cap for r in qb._records.values())
        pos_live = positions(order)
        for t in ("small_a", "small_b"):
            assert pos_live[t] <= 2.0 * max(pos_blind[t], 1.0), (
                t, pos_live, pos_blind)
    finally:
        db.close()


# ---------------------------------------------------------------------------
# Restart: placement state is NOT journaled
# ---------------------------------------------------------------------------

def test_restart_restarts_placement_state_cold(tmp_path):
    """affinity_skips and the pending-digest refcounts die with the
    process: a journal-replayed queue restores every pending job with a
    zero deferral budget spent and refcounts rebuilt purely from the
    replayed intake — locality evidence is never trusted across a
    restart."""
    base_blob, delta_blob, ext_blob = _chain_blobs(seed=54)
    base_d = panel_store.panel_digest(base_blob)
    jp = str(tmp_path / "j.jsonl")
    q = JobQueue(Journal(jp))
    q.enqueue(JobRecord(id="parent", strategy="sma_crossover", grid=GRID,
                        ohlcv=base_blob))
    q.enqueue(JobRecord(id="child", strategy="sma_crossover", grid=GRID,
                        ohlcv=ext_blob, append_parent=base_d,
                        append_base_len=128, delta=delta_blob))
    ext_d = q._records["child"].panel_digest
    assert q._pending_digests == {base_d: 1, ext_d: 1}

    # Burn deferral budget (a deny-all admit is the placement hook's
    # worst case), then serve the parent so the refcounts diverge.
    def deny(r):
        r.affinity_skips += 1
        return False

    assert q.take(2, "w1", admit=deny) == []
    got = q.take(1, "w1", admit=lambda r: r.id == "parent")
    assert [r.id for r, _ in got] == ["parent"]
    assert q._pending_digests == {ext_d: 1}
    assert q._records["child"].affinity_skips >= 1

    q2 = JobQueue()
    assert q2.restore(jp) == 2     # parent never completed: replayed too
    assert all(r.affinity_skips == 0 for r in q2._records.values())
    assert q2._pending_digests == {base_d: 1, ext_d: 1}
    # And the restored queue serves everything.
    assert {r.id for r, _ in q2.take(4, "w2")} == {"parent", "child"}
