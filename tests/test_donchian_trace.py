"""Donchian breakout, traced-window extrema, trace utils, fused routing."""

import logging
import warnings

import jax.numpy as jnp
import numpy as np

from distributed_backtesting_exploration_tpu.models.base import get_strategy
from distributed_backtesting_exploration_tpu.ops import rolling
from distributed_backtesting_exploration_tpu.parallel import sweep
from distributed_backtesting_exploration_tpu.utils import data

with warnings.catch_warnings():
    # The deprecation shim over obs is exactly what this module exercises.
    warnings.simplefilter("ignore", DeprecationWarning)
    from distributed_backtesting_exploration_tpu.utils import trace


def test_rolling_extrema_traced_matches_static():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(200), jnp.float32)
    for w in (3, 10, 32):
        got = rolling.rolling_extrema_traced(
            x, jnp.asarray(w), max_window=64, mode="max", fill=0.0)
        want = rolling.rolling_max(x, w, fill=0.0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))
        got = rolling.rolling_extrema_traced(
            x, jnp.asarray(w), max_window=64, mode="min", fill=0.0)
        want = rolling.rolling_min(x, w, fill=0.0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_donchian_breakout_behaviour():
    # Monotonic rally then crash: long during the rally, short after the
    # breakdown.
    up = np.linspace(100, 150, 60)
    down = np.linspace(150, 80, 60)
    close = jnp.asarray(np.concatenate([up, down]), jnp.float32)
    ohlcv = data.OHLCV(*(close for _ in range(5)))
    pos = get_strategy("donchian").positions(ohlcv, {"window": jnp.asarray(10)})
    p = np.asarray(pos)
    assert (p[15:59] == 1.0).all(), "should be long during the rally"
    assert (p[80:] == -1.0).all(), "should be short after the breakdown"


def test_donchian_sweeps_over_window_grid():
    ohlcv = data.synthetic_ohlcv(3, 256, seed=2)
    panel = type(ohlcv)(*(jnp.asarray(f) for f in ohlcv))
    grid = sweep.product_grid(window=jnp.array([10., 20., 40.]))
    m = sweep.jit_sweep(panel, get_strategy("donchian"), dict(grid), cost=1e-3)
    assert m.sharpe.shape == (3, 3)
    assert np.isfinite(np.asarray(m.sharpe)).all()


def test_timed_logs_duration(caplog):
    with caplog.at_level(logging.INFO, logger="dbx.trace"):
        with trace.timed("unit-test-phase"):
            pass
    assert any("unit-test-phase took" in r.message for r in caplog.records)


def test_step_timer_rate():
    t = trace.StepTimer()
    t.add(100)
    assert t.rate > 0


def test_fused_routing_eligibility():
    from distributed_backtesting_exploration_tpu.rpc import backtesting_pb2 as pb
    from distributed_backtesting_exploration_tpu.rpc.compute import (
        JaxSweepBackend)

    ok_job = pb.JobSpec(strategy="sma_crossover")
    grids = {"fast": np.array([5.0, 10.0]), "slow": np.array([20.0, 40.0])}
    assert JaxSweepBackend._fused_eligible(ok_job, grids, [64, 64])
    # Mixed lengths stay fused (round 3): the kernels take per-ticker
    # real lengths, so a ragged fleet no longer drops to the generic path.
    assert JaxSweepBackend._fused_eligible(ok_job, grids, [64, 128])
    assert not JaxSweepBackend._fused_eligible(ok_job, grids, [64, 30000])
    # bollinger has its own fused kernel keyed on (window, k) axes.
    boll = pb.JobSpec(strategy="bollinger")
    bgrid = {"window": np.array([10.0, 20.0]), "k": np.array([1.0, 2.5])}
    assert JaxSweepBackend._fused_eligible(boll, bgrid, [64, 64])
    assert not JaxSweepBackend._fused_eligible(boll, grids, [64, 64])
    assert not JaxSweepBackend._fused_eligible(
        boll, {"window": np.array([10.5]), "k": np.array([1.0])}, [64])
    # non-integral k is fine — k is a band width, not a bar count.
    assert JaxSweepBackend._fused_eligible(
        boll, {"window": np.array([10.0]), "k": np.array([1.37])}, [64])
    # momentum/donchian gained fused kernels in round 3.
    assert JaxSweepBackend._fused_eligible(
        pb.JobSpec(strategy="momentum"),
        {"lookback": np.array([10.0, 21.0])}, [64, 64])
    assert not JaxSweepBackend._fused_eligible(
        pb.JobSpec(strategy="momentum"), grids, [64, 64])  # wrong axes
    don = pb.JobSpec(strategy="donchian")
    assert JaxSweepBackend._fused_eligible(
        don, {"window": np.array([20.0, 55.0])}, [64])
    # beyond the generic path's static view bound -> stays generic
    assert not JaxSweepBackend._fused_eligible(
        don, {"window": np.array([20.0, 300.0])}, [64])
    assert not JaxSweepBackend._fused_eligible(
        ok_job, {"fast": np.array([5.0])}, [64])
    assert not JaxSweepBackend._fused_eligible(
        ok_job, {"fast": np.array([5.5]), "slow": np.array([20.0])}, [64])


def test_extrema_traced_poisons_oversized_window():
    x = jnp.ones(64)
    out = rolling.rolling_extrema_traced(
        x, jnp.asarray(40), max_window=32, mode="max", fill=0.0)
    assert np.isnan(np.asarray(out)[60])


def test_fused_eligibility_resource_bounds():
    from distributed_backtesting_exploration_tpu.rpc import backtesting_pb2 as pb
    from distributed_backtesting_exploration_tpu.rpc.compute import (
        JaxSweepBackend)
    job = pb.JobSpec(strategy="sma_crossover")
    g = {"fast": np.array([5.0]), "slow": np.array([20.0])}
    assert not JaxSweepBackend._fused_eligible(job, g, [30000])  # too long
    wide = {"fast": np.arange(2, 120, dtype=np.float64),
            "slow": np.arange(120, 240, dtype=np.float64)}
    assert not JaxSweepBackend._fused_eligible(job, wide, [64])  # >128 windows
