"""The associative band machine must match the serial scan bit-for-bit."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.ops import rolling, signals


@pytest.mark.parametrize("z_entry,z_exit", [(1.0, 0.0), (1.5, 0.5), (0.2, 0.0)])
def test_assoc_matches_scan(z_entry, z_exit):
    rng = np.random.default_rng(3)
    z = jnp.asarray(rng.standard_normal((4, 257)), jnp.float32)
    valid = rolling.valid_mask(257, 20)
    want = signals.band_hysteresis(z, valid, z_entry, z_exit)
    got = signals.band_hysteresis_assoc(z, valid, z_entry, z_exit)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_assoc_matches_scan_knife_edge():
    # Values exactly on the bands: ties must resolve identically.
    z = jnp.asarray(
        [[-1.0, -1.0000001, 0.0, 1.0, 1.0000001, 0.0, -2.0, -0.0, 2.0, 0.5]],
        jnp.float32)
    valid = jnp.ones((10,), bool)
    want = signals.band_hysteresis(z, valid, 1.0, 0.0)
    got = signals.band_hysteresis_assoc(z, valid, 1.0, 0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_assoc_traced_params_vmap():
    import jax

    rng = np.random.default_rng(5)
    z = jnp.asarray(rng.standard_normal((513,)), jnp.float32)
    valid = rolling.valid_mask(513, 10)
    ks = jnp.asarray([0.5, 1.0, 2.0], jnp.float32)
    got = jax.vmap(lambda k: signals.band_hysteresis_assoc(z, valid, k))(ks)
    want = jnp.stack([signals.band_hysteresis(z, valid, float(k)) for k in ks])
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_assoc_invalid_bars_force_flat():
    z = jnp.asarray([[-3.0, -3.0, -3.0, 3.0, 3.0, -3.0]], jnp.float32)
    valid = jnp.asarray([True, False, True, True, False, True])
    want = signals.band_hysteresis(z, valid, 1.0, 0.0)
    got = signals.band_hysteresis_assoc(z, valid, 1.0, 0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert np.asarray(got)[0, 1] == 0.0 and np.asarray(got)[0, 4] == 0.0
