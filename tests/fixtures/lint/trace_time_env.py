"""Seeded violation: the pre-PR-1 ``DBX_LANES_CAP`` bug class, verbatim
shape — an ``os.environ`` read inside a helper reachable from a
jit-compiled kernel launcher (ops/fused.py:68 before the round-5 fix).
Never imported; the trace-time-env rule works on the AST alone."""

import functools
import os

import jax


def _widest_lanes(P_pad, cap):
    # VIOLATION: read at trace time, invisible to the jit cache key — an
    # in-process change silently reuses the stale compile.
    env = os.environ.get("DBX_LANES_CAP")
    if env:
        cap = min(cap, int(env))
    for cand in (1024, 512, 256, 128):
        if cand <= cap and P_pad % cand == 0:
            return cand
    return P_pad


@functools.partial(jax.jit, static_argnames=("P_pad",))
def _fused_call(close, *, P_pad):
    lanes = _widest_lanes(P_pad, 512)
    return close * lanes


def _tuned_schedule_lookup():
    # VIOLATION: the round-11 bug class — consulting the schedule
    # registry (DBX_SCHEDULE_DIR) inside a traced root. Registry
    # consultation must stay host-side: the worker backend resolves the
    # tuned substrates BEFORE the jit call and threads them as statics.
    return os.environ.get("DBX_SCHEDULE_DIR", "")


@jax.jit
def _tuned_kernel(close):
    sched = _tuned_schedule_lookup()
    return close * (2.0 if sched else 1.0)


def host_side_helper():
    # NOT a violation: host-side read, not reachable from any traced root.
    return os.environ.get("DBX_HOST_ONLY", "")


def host_side_autotune_mode():
    # NOT a violation: the autotuner's mode knob is resolved host-side at
    # group-submit time (tune.autotune.autotune_mode), never in a trace.
    return os.environ.get("DBX_AUTOTUNE", "off")
