"""Seeded violations: configuration captured at import time."""

import os

# VIOLATION: env read frozen at import.
_CAP = os.environ.get("DBX_FIXTURE_CAP")

# VIOLATION: file IO at import.
_CONFIG = open("/dev/null")


def runtime_read():
    # NOT a violation: function-scope read happens at call time.
    return os.environ.get("DBX_FIXTURE_CAP")


if __name__ == "__main__":
    # NOT a violation: main-guard blocks are runtime, not import time.
    print(os.environ.get("DBX_FIXTURE_CAP"))
