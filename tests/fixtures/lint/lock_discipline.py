"""Seeded violation: a guarded field mutated outside the owning lock."""

import threading


class LeakyQueue:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = []
        self._done = set()

    def push(self, item):
        with self._lock:
            self._pending.append(item)

    def complete(self, item):
        # VIOLATION: `_pending` is mutated under the lock in push() but
        # mutated here without holding it.
        self._pending.remove(item)
        self._done.add(item)   # `_done` never mutated under lock: not guarded

    def drain(self):
        with self._lock:
            out = list(self._pending)
            self._pending.clear()
        return out
