"""Seeded violation: check-then-act on a guarded field across a lock
release — the PR-8 quota-charge bug class (read under lock, branch
unlocked, re-acquire and write a value computed from the stale read).
The double-checked and single-critical-section forms are the clean
counterparts."""

import threading


class QuotaLedger:
    def __init__(self):
        self._lock = threading.Lock()
        self._spent = {}

    def charge(self, tenant, cost, quota):
        with self._lock:
            spent = self._spent.get(tenant, 0.0)
        if spent + cost > quota:          # decision on the stale read
            return False
        with self._lock:
            # VIOLATION: another thread may have charged in the window;
            # this write acts on the pre-window value.
            self._spent[tenant] = spent + cost
        return True

    def charge_checked(self, tenant, cost, quota):
        with self._lock:
            spent = self._spent.get(tenant, 0.0)
        if spent + cost > quota:
            return False
        with self._lock:
            # CLEAN: re-validated under the second acquisition (the
            # double-checked fix).
            if self._spent.get(tenant, 0.0) + cost > quota:
                return False
            self._spent[tenant] = self._spent.get(tenant, 0.0) + cost
        return True

    def charge_atomic(self, tenant, cost, quota):
        with self._lock:
            # CLEAN: one critical section end to end.
            spent = self._spent.get(tenant, 0.0)
            if spent + cost > quota:
                return False
            self._spent[tenant] = spent + cost
        return True
