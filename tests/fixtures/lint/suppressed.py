"""Seeded-but-suppressed violations: the directive must silence exactly
the named rule, same line or the comment line directly above."""

import os

# Same-line directive:
_A = os.environ.get("DBX_SUP_A")  # dbxlint: disable=import-time-config -- fixture: suppression-respected test

# Directive on the comment line above:
# dbxlint: disable=import-time-config -- fixture: line-above form
_B = os.environ.get("DBX_SUP_B")

# Directive naming a DIFFERENT rule does NOT suppress (stays a finding):
_C = os.environ.get("DBX_SUP_C")  # dbxlint: disable=blocking-call -- wrong rule on purpose
