"""Seeded violations: a sleep, a device sync and a timeout'd queue wait
inside gRPC servicer handlers / the worker control loop (blocking-call),
a sleep while holding a lock (lock-blocking — the PR-9 PagePool
scrape-stall class), and a bounded handoff put under the producer's
accounting lock (lock-blocking — the round-14 pipeline handoff class).
The allowlisted pipeline waits (Worker._collect_loop) are the clean
counterparts."""

import threading
import time

import jax


class _Queue:
    """Stand-in for a bounded handoff queue."""

    def get(self, timeout=None):
        return None

    def put(self, item, timeout=None):
        return None


class DispatcherServicer:
    """Stand-in for the generated base class."""


class SlowDispatcher(DispatcherServicer):
    def RequestJobs(self, request, context):
        # VIOLATION: a sleeping handler steals a slot from the shared
        # gRPC thread pool.
        time.sleep(0.5)
        return None

    def GetStats(self, request, context):
        # VIOLATION (device-sync vocabulary): the handler blocks for as
        # long as the accelerator takes to drain.
        jax.block_until_ready(request)
        return None

    def _helper(self):
        # NOT in the allowlist either; helpers of a servicer class count.
        return 1

    def Subscribe(self, request, context):
        # VIOLATION (timeout'd wait vocabulary, round 14): a bounded
        # queue wait parks the shared gRPC thread pool exactly like a
        # sleep of the timeout's length.
        return self._q.get(timeout=5.0)


class Worker:
    """Stand-in for the worker control loop (scanned by class name)."""

    def __init__(self):
        self._q = _Queue()

    def run(self):
        # VIOLATION: a timeout'd handoff wait on the CONTROL thread
        # starves the liveness heartbeat (qualname not allowlisted).
        return self._q.get(timeout=1.0)

    def _collect_loop(self, handoff):
        # Clean: Worker._collect_loop is the allowlisted pipeline
        # handoff wait — the collector thread's whole job is to wait.
        return handoff.get(timeout=0.25)


class PipelineHandoff:
    """The round-14 producer/consumer handoff, lock-blocking case."""

    def __init__(self):
        self._lock = threading.Lock()
        self._q = _Queue()
        self._inflight = 0

    def submit(self, item):
        with self._lock:
            self._inflight += 1
            # VIOLATION (lock-blocking): the bounded handoff put runs
            # under the accounting lock — a full queue parks the
            # producer while every reader of the lock stalls behind it.
            self._q.put(item, timeout=1.0)

    def collect(self):
        item = self._q.get(timeout=1.0)   # clean: waits lock-free
        with self._lock:
            self._inflight -= 1
        return item


class StallingPool:
    def __init__(self):
        self._lock = threading.Lock()
        self._pages = {}

    def upload(self, key, page):
        with self._lock:
            self._pages[key] = page
            # VIOLATION (lock-blocking): the device sync runs under the
            # index lock — every concurrent stats scrape stalls for it.
            jax.block_until_ready(page)

    def scrape(self):
        with self._lock:
            return len(self._pages)
