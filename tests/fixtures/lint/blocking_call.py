"""Seeded violations: a sleep and a device sync inside gRPC servicer
handlers (blocking-call), and a sleep while holding a lock
(lock-blocking — the PR-9 PagePool scrape-stall class)."""

import threading
import time

import jax


class DispatcherServicer:
    """Stand-in for the generated base class."""


class SlowDispatcher(DispatcherServicer):
    def RequestJobs(self, request, context):
        # VIOLATION: a sleeping handler steals a slot from the shared
        # gRPC thread pool.
        time.sleep(0.5)
        return None

    def GetStats(self, request, context):
        # VIOLATION (device-sync vocabulary): the handler blocks for as
        # long as the accelerator takes to drain.
        jax.block_until_ready(request)
        return None

    def _helper(self):
        # NOT in the allowlist either; helpers of a servicer class count.
        return 1


class StallingPool:
    def __init__(self):
        self._lock = threading.Lock()
        self._pages = {}

    def upload(self, key, page):
        with self._lock:
            self._pages[key] = page
            # VIOLATION (lock-blocking): the device sync runs under the
            # index lock — every concurrent stats scrape stalls for it.
            jax.block_until_ready(page)

    def scrape(self):
        with self._lock:
            return len(self._pages)
