"""Seeded violation: a sleep inside a gRPC servicer handler."""

import time


class DispatcherServicer:
    """Stand-in for the generated base class."""


class SlowDispatcher(DispatcherServicer):
    def RequestJobs(self, request, context):
        # VIOLATION: a sleeping handler steals a slot from the shared
        # gRPC thread pool.
        time.sleep(0.5)
        return None

    def _helper(self):
        # NOT in the allowlist either; helpers of a servicer class count.
        return 1
