"""Seeded jaxpr-layer violations for the kernel-hygiene rule.

Unlike the AST fixtures this one IS imported (the rule lints traced
jaxprs, not source): one kernel with a host callback, one with a float64
leak, one with a weak-type escape, one clean."""

import jax
import jax.numpy as jnp
import numpy as np


def kernel_with_callback(x):
    def host_side(v):
        return np.asarray(v)

    y = jax.pure_callback(
        host_side, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
    return jnp.asarray(y, jnp.float32) * jnp.float32(2.0)


def kernel_with_f64(x):
    return (x.astype(jnp.float64) * 2.0).astype(jnp.float32)


def kernel_weak_output(x):
    # A Python-scalar constant fill: the output is weakly typed, so its
    # dtype downstream depends on promotion rules, not an explicit anchor.
    return jnp.full(x.shape, 2.0)


def kernel_clean(x):
    return jnp.asarray(x, jnp.float32) * jnp.float32(2.0)
