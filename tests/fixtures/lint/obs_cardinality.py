"""Seeded obs-cardinality violations: metric labels fed from unbounded
runtime data (job ids, file paths, peer addresses). The lint engine never
imports this module — AST only."""

from distributed_backtesting_exploration_tpu import obs


class FleetRecorder:
    def __init__(self, worker_id):
        self.worker_id = worker_id

    def publish(self, reg):
        wid = "bootstrap"
        wid = self.worker_id
        # one-hop alias of an unbounded attribute: flagged — the LAST
        # binding wins; the earlier literal must not launder it
        reg.gauge("fx_worker_busy", worker=wid).set(1)
        endpoint = self.worker_id
        endpoint = "pool-a"
        # rebound to a literal before use: NOT flagged (last wins)
        reg.gauge("fx_pool_up", pool=endpoint).set(1)


def record(reg, job_id, path, peer_addr, lineno):
    reg.counter("fx_jobs_total", job=job_id).inc()            # flagged: param
    reg.histogram("fx_read_seconds", file=path).observe(0.1)  # flagged: path
    reg.gauge("fx_peer_up", peer=peer_addr).set(1)            # flagged: addr
    # f-string built from unbounded data: flagged
    reg.counter("fx_sites_total", site=f"{path}:{lineno}").inc()


def record_panel(reg, panel_digest):
    # content digests are the canonical unbounded vocabulary of the panel
    # cache: one time series per distinct panel, forever — flagged
    reg.counter("fx_panel_hits_total", panel=panel_digest).inc()
    # bounded cache-level label: NOT flagged
    reg.counter("fx_cache_hits_total", level="host").inc()
    # bounded literals and non-matching names: NOT flagged
    reg.counter("fx_ok_total", method="RequestJobs").inc()
    strategy = "sma_crossover"
    reg.counter("fx_by_kernel_total", kernel=strategy).inc()


def record_tenant(reg, tenant_id):
    from distributed_backtesting_exploration_tpu.sched import tenant_bucket

    # raw tenant identity: unbounded operator-chosen strings (one time
    # series per tenant, forever) — flagged
    reg.gauge("fx_tenant_depth", tenant=tenant_id).set(1)
    # routed through the bounded tenant-bucket map (first N tenants keep
    # their name, the rest share "other"): sanctioned — NOT flagged
    reg.gauge("fx_tenant_depth_ok",
              tenant=tenant_bucket(tenant_id)).set(1)
    # one-hop alias of a sanctioned call: still bounded — NOT flagged
    bucket = tenant_bucket(tenant_id)
    reg.counter("fx_tenant_served_total", tenant=bucket).inc()


def record_shape(reg, panel_key, n_bars, n_combos):
    from distributed_backtesting_exploration_tpu.tune import shape_bucket

    # raw shape key: unbounded (one series per distinct shape) — flagged
    reg.gauge("fx_shape_depth", shape=panel_key).set(1)
    # routed through the clamped power-of-two shape-bucket rails (a
    # finite label set by construction): sanctioned — NOT flagged
    reg.gauge("fx_shape_depth_ok",
              shape=shape_bucket(n_bars, n_combos)).set(1)


def record_stream(reg, stream_key, subscriber_id):
    from distributed_backtesting_exploration_tpu.sched import stream_bucket

    # raw stream identity: param-block digests are unbounded (one time
    # series per distinct grid/cost/strategy tuple, forever) — flagged
    reg.counter("fx_stream_pushes_total", stream=stream_key).inc()
    # subscriber identity: same class — flagged
    reg.gauge("fx_sub_depth", sub=subscriber_id).set(1)
    # routed through the bounded stream-bucket map (first N keys keep a
    # short sticky prefix, the rest share "other"): sanctioned — NOT
    # flagged
    reg.counter("fx_stream_pushes_ok_total",
                stream=stream_bucket(stream_key)).inc()


def record_worker(reg, worker_id):
    from distributed_backtesting_exploration_tpu.sched import worker_bucket

    # raw worker identity: worker-chosen wire strings that churn per
    # restart (one permanent time series per registration) — flagged
    reg.gauge("fx_worker_rate", worker=worker_id).set(1)
    # routed through the bounded worker-bucket map (first N workers keep
    # their name, the rest share "other"): sanctioned — NOT flagged
    reg.gauge("fx_worker_rate_ok",
              worker=worker_bucket(worker_id)).set(1)


def record_decision(reg, worker, candidate, regret_s):
    from distributed_backtesting_exploration_tpu.sched import worker_bucket

    # decision-plane vocabulary (round 19): the actual and candidate
    # worker ids in a decision record are raw registration strings that
    # churn per restart — flagged
    reg.counter("fx_decisions_total", worker=worker).inc()
    reg.gauge("fx_shadow_best", candidate=candidate).set(1)
    # per-decision regret as a LABEL is a continuous measurement: one
    # time series per distinct float, forever — flagged (it belongs in
    # a histogram's observe(), not a label)
    reg.counter("fx_regret_total", regret=regret_s).inc()
    # bounded route/outcome literals from the decision record: NOT
    # flagged
    reg.counter("fx_decisions_ok_total", route="digest_only").inc()
    reg.counter("fx_shadow_ok_total", outcome="agree").inc()
    # sanctioned worker-bucket rails: NOT flagged
    reg.counter("fx_decisions_bucketed_total",
                worker=worker_bucket(worker)).inc()


def suppressed(reg, job_id):
    # dbxlint: disable=obs-cardinality -- demo: suppression carries a why
    reg.counter("fx_sup_total", job=job_id).inc()
