"""Seeded violations: a 2-lock acquisition-order cycle (ABBA), a nested
re-acquisition of a non-reentrant lock through a helper, and — as the
clean counterpart — a 2-lock hierarchy acquired in ONE consistent order
everywhere."""

import threading

_alpha = threading.Lock()
_beta = threading.Lock()

_outer = threading.Lock()
_inner = threading.Lock()


def transfer_ab():
    with _alpha:
        with _beta:        # VIOLATION: beta-under-alpha
            return 1


def transfer_ba():
    with _beta:
        with _alpha:       # VIOLATION: alpha-under-beta (the reverse)
            return 2


def hierarchy_one():
    with _outer:
        with _inner:       # clean: outer -> inner everywhere
            return 3


def hierarchy_two():
    with _outer:
        with _inner:       # same order: no cycle, no finding
            return 4


def reenter():
    with _outer:
        return _locked_helper()


def _locked_helper():
    with _outer:           # VIOLATION: self-nest via reenter()
        return 5
