"""Seeded violations: a 2-lock acquisition-order cycle (ABBA), a nested
re-acquisition of a non-reentrant lock through a helper, and — as the
clean counterpart — a 2-lock hierarchy acquired in ONE consistent order
everywhere."""

import threading

_alpha = threading.Lock()
_beta = threading.Lock()

_outer = threading.Lock()
_inner = threading.Lock()


def transfer_ab():
    with _alpha:
        with _beta:        # VIOLATION: beta-under-alpha
            return 1


def transfer_ba():
    with _beta:
        with _alpha:       # VIOLATION: alpha-under-beta (the reverse)
            return 2


def hierarchy_one():
    with _outer:
        with _inner:       # clean: outer -> inner everywhere
            return 3


def hierarchy_two():
    with _outer:
        with _inner:       # same order: no cycle, no finding
            return 4


class PipelinedHandoff:
    """Producer/consumer pair (the round-14 pipelined-executor shape):
    the submit side nests the stats lock under the pipeline lock, the
    collect side nests them the other way — an ABBA a busy pipeline
    WILL eventually schedule."""

    def __init__(self):
        self._pipeline = threading.Lock()
        self._stats = threading.Lock()
        self.inflight = 0
        self.collected = 0

    def submit_side(self):
        with self._pipeline:
            with self._stats:       # VIOLATION: stats-under-pipeline
                self.inflight += 1

    def collect_side(self):
        with self._stats:
            with self._pipeline:    # VIOLATION: pipeline-under-stats
                self.collected += 1


def reenter():
    with _outer:
        return _locked_helper()


def _locked_helper():
    with _outer:           # VIOLATION: self-nest via reenter()
        return 5
