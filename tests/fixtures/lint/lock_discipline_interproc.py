"""Seeded interprocedural lock-discipline scenario: a private helper
mutating a guarded field is CLEAN when every caller path holds the lock
(the PagePool ``prepare()`` shape — previously only expressible as a
suppression), and a violation when one reachable path does not."""

import threading


class PageIndex:
    def __init__(self):
        self._lock = threading.Lock()
        self._slots = {}
        self._free = []

    def prepare(self, key):
        with self._lock:
            return self._take_slot(key)

    def _take_slot(self, key):
        # CLEAN: every caller (prepare, and _grow via prepare) holds
        # self._lock — provable from the call graph, no suppression.
        slot = self._free.pop() if self._free else self._grow()
        self._slots[key] = slot
        return slot

    def _grow(self):
        # CLEAN for the same reason (reached only via _take_slot).
        self._free.extend(range(8))
        return self._free.pop()

    def forget(self, key):
        # VIOLATION: public method, lock-free entry path, mutates the
        # guarded `_slots`.
        self._slots.pop(key, None)

    def _sweep(self):
        # VIOLATION: private, but reachable lock-free through audit().
        self._slots.clear()

    def audit(self):
        return self._sweep()
