"""Lint fixture: journaled-state mutation BEFORE the journal append.

``buggy_enqueue_many`` mirrors the dispatcher's real ``enqueue_many``
but publishes into live state FIRST and journals AFTER — the exact
reordering the ``journal-discipline`` rule flags statically, and the
reordering dbxmc's ``journal-append-first`` invariant catches
dynamically when this function is monkeypatched over the real method
(tests/test_mc_clean.py): a crash between the state push and the
append holds live jobs no restart can restore.
"""

DEFAULT_TENANT = "default"


def buggy_enqueue_many(self, recs, journal=True):
    for rec in recs:
        if not rec.tenant:
            rec.tenant = DEFAULT_TENANT
        if rec.ohlcv is not None and not rec.panel_digest:
            rec.panel_digest = self.panel_store.put(rec.ohlcv)
    with self._lock:
        for rec in recs:
            self._records[rec.id] = rec  # BUG: published before journaled
            if rec.panel_digest:
                self._digest_jobs[rec.panel_digest] = rec.id
        self._state.enqueue_n([rec.id for rec in recs],
                              [float(rec.combos) for rec in recs])
        for jid in self._state.take_begin_n(len(recs)):
            r = self._records[jid]
            self._sched.push(jid, r.tenant, float(r.combos))
    if journal and self._journal.enabled:
        for rec in recs:
            # Too late: the batch is already takeable; a crash above
            # this line orphans every job in it.
            self._journal.append("enqueue", **rec.journal_form())
