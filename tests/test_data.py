"""Codec round-trips and padding semantics for the market-data layer."""

import numpy as np
import pytest

from distributed_backtesting_exploration_tpu.utils import data as data_mod


def one_ticker(n=100, seed=1):
    batch = data_mod.synthetic_ohlcv(1, n, seed=seed)
    return data_mod.OHLCV(*(f[0] for f in batch))


def test_synthetic_shapes_and_determinism():
    a = data_mod.synthetic_ohlcv(3, 50, seed=9)
    b = data_mod.synthetic_ohlcv(3, 50, seed=9)
    assert a.close.shape == (3, 50)
    np.testing.assert_array_equal(a.close, b.close)
    assert (a.high >= a.close).all() and (a.low <= a.close).all()
    assert (a.high >= a.open).all() and (a.low <= a.open).all()


def test_csv_roundtrip():
    s = one_ticker(64)
    back = data_mod.from_csv_bytes(data_mod.to_csv_bytes(s))
    for f in ("open", "high", "low", "close", "volume"):
        np.testing.assert_allclose(getattr(back, f), getattr(s, f), rtol=1e-6)


def test_csv_with_date_column_and_reordered_header():
    body = "date,close,open,low,high,volume\n"
    body += "2024-01-01,10,9,8,11,100\n2024-01-02,11,10,9,12,110\n"
    s = data_mod.from_csv_bytes(body.encode())
    np.testing.assert_allclose(s.close, [10, 11])
    np.testing.assert_allclose(s.high, [11, 12])


def test_wire_roundtrip_and_size():
    s = one_ticker(500)
    blob = data_mod.to_wire_bytes(s)
    assert len(blob) == 8 + 5 * 4 * 500
    back = data_mod.from_wire_bytes(blob)
    for f in ("open", "high", "low", "close", "volume"):
        np.testing.assert_array_equal(getattr(back, f), getattr(s, f))


def test_wire_rejects_garbage():
    with pytest.raises(ValueError):
        data_mod.from_wire_bytes(b"nope")
    s = one_ticker(10)
    with pytest.raises(ValueError):
        data_mod.from_wire_bytes(data_mod.to_wire_bytes(s)[:-4])


def test_pad_and_stack():
    series = [one_ticker(100, seed=1), one_ticker(260, seed=2)]
    batch, lengths, mask = data_mod.pad_and_stack(series, lane_multiple=128)
    assert batch.close.shape == (2, 384)
    np.testing.assert_array_equal(lengths, [100, 260])
    assert mask[0, :100].all() and not mask[0, 100:].any()
    # padding repeats the final bar -> zero returns in the padded tail
    np.testing.assert_array_equal(batch.close[0, 100:], batch.close[0, 99])


def test_parquet_roundtrip():
    s = one_ticker(64)
    back = data_mod.from_parquet_bytes(data_mod.to_parquet_bytes(s))
    for f in ("open", "high", "low", "close", "volume"):
        np.testing.assert_allclose(getattr(back, f), getattr(s, f),
                                   rtol=1e-6)


def test_parquet_extra_columns_and_case():
    import pyarrow as pa
    import pyarrow.parquet as pq
    import io as io_mod

    table = pa.table({"Date": ["a", "b"], "Close": [10.0, 11.0],
                      "open": [9.0, 10.0], "LOW": [8.0, 9.0],
                      "High": [11.0, 12.0], "volume": [100.0, 110.0]})
    sink = io_mod.BytesIO()
    pq.write_table(table, sink)
    s = data_mod.from_parquet_bytes(sink.getvalue())
    np.testing.assert_allclose(s.close, [10, 11])
    np.testing.assert_allclose(s.high, [11, 12])


def test_parquet_missing_column_and_garbage():
    import pyarrow as pa
    import pyarrow.parquet as pq
    import io as io_mod

    table = pa.table({"close": [1.0]})
    sink = io_mod.BytesIO()
    pq.write_table(table, sink)
    with pytest.raises(ValueError, match="missing columns"):
        data_mod.from_parquet_bytes(sink.getvalue())
    with pytest.raises(ValueError, match="Parquet"):
        data_mod.from_parquet_bytes(b"PAR1 definitely not parquet")


def test_dispatcher_reads_parquet_payload(tmp_path):
    """File-backed Parquet jobs transcode to DBX1 at dispatch, like CSV."""
    from distributed_backtesting_exploration_tpu.rpc.dispatcher import (
        _read_payload)

    s = one_ticker(32)
    p = tmp_path / "t.parquet"
    p.write_bytes(data_mod.to_parquet_bytes(s))
    blob = _read_payload(str(p))
    back = data_mod.from_wire_bytes(blob)
    np.testing.assert_allclose(back.close, np.asarray(s.close, np.float32),
                               rtol=1e-6)
