"""Subscription registry, result cache and push fan-out (package doc in
``__init__``).

Concurrency contract (dbxlint lock-order / lock-blocking / atomicity,
and the DBX_LOCKDEP=1 runtime harness, all hold it):

- ``SubscriptionHub._lock`` guards ONLY the registry maps (chains,
  streams, subscribers, in-flight advance index). Nothing is pushed,
  cached, diffed or waited on while it is held — every mutation phase
  snapshots what it needs under the lock and does the work after
  release.
- each :class:`Subscription` has its own leaf mutex around its bounded
  queue; the wake-up signal is a ``threading.Event`` set AFTER the
  mutex releases. The hub lock and a subscription mutex are never held
  together, so no ordering between them can ever form.
- :class:`ResultCache` wraps its ByteLRU in its own leaf lock; cache
  calls happen outside the hub lock.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import logging
import os
import threading
import time

import numpy as np

from .. import obs
from ..rpc.panel_store import ByteLRU
from ..sched import (DEFAULT_TENANT, parse_tenant_map, stream_bucket,
                     tenant_bucket)
from ..streaming.delta import metric_delta

log = logging.getLogger("dbx.serve")

_DEFAULT_RESULT_CACHE_MB = 64
_DEFAULT_SUB_QUEUE_MAX = 256


def result_cache_max_bytes() -> int:
    """Result-cache budget, read lazily (import-time env capture would
    pin the knob before tests/operators can set it)."""
    return int(float(os.environ.get("DBX_RESULT_CACHE_MB",
                                    _DEFAULT_RESULT_CACHE_MB)) * 1024 * 1024)


def sub_queue_max() -> int:
    """Per-subscriber push-queue bound (items, not bytes: each item is
    one small DBXM block + metadata; the bound exists to cap a slow
    consumer's memory and staleness, not its byte rate)."""
    return int(os.environ.get("DBX_SUB_QUEUE_MAX", _DEFAULT_SUB_QUEUE_MAX))


def stream_key(strategy: str, grid, cost: float, ppy: int) -> str:
    """Content key of a stream's parameter block.

    EXACT mirror of ``streaming.recurrent.stream_key`` — the digest
    that, together with the panel digest, addresses a worker carry
    checkpoint — duplicated here so the dispatcher's subscription path
    never imports the jax-backed carry machinery just to hash a grid
    (the ``STREAMABLE_STRATEGIES`` literal-set precedent; pinned
    against the real implementation in tests/test_serve.py).
    """
    h = hashlib.blake2b(digest_size=16)
    h.update(strategy.encode())
    for name in sorted(grid):
        h.update(name.encode())
        h.update(np.ascontiguousarray(np.asarray(grid[name],
                                                 np.float32)).tobytes())
    h.update(np.float32(cost).tobytes())
    h.update(str(int(ppy)).encode())
    return h.hexdigest()


@dataclasses.dataclass
class StreamSpec:
    """One stream's identity: the sweep a tick must advance."""

    strategy: str
    grid: dict                      # axis name -> float32 array
    cost: float = 0.0
    ppy: int = 252
    tenant: str = DEFAULT_TENANT    # tenant charged for the advance job
    digest: str = ""                # chain link the subscriber named

    @property
    def key(self) -> str:
        return stream_key(self.strategy, self.grid, self.cost, self.ppy)


@dataclasses.dataclass
class PushItem:
    """One queued push (the wire PushUpdate, pre-serialization)."""

    digest: str
    key: str
    seq: int
    metrics: bytes
    new_len: int
    tick_unix: float
    changed: int
    dropped: int
    catch_up: bool = False


@dataclasses.dataclass
class _TickPlan:
    """What one chain tick must do (returned by :meth:`
    SubscriptionHub.on_tick` under no lock): the advances to enqueue —
    one per unique live stream whose spec the tick's own job template
    does not already cover — plus whether the template's stream itself
    has subscribers (its job id should then be registered for fan-out
    too)."""

    chain: str
    advances: list
    template_live: bool


@dataclasses.dataclass
class _Advance:
    """An in-flight advance job's fan-out address."""

    chain: str
    key: str
    digest: str
    new_len: int
    tick_unix: float


class ResultCache:
    """Byte-bounded LRU of ``(panel_digest, stream_key) -> DBXM block``.

    The serving tier's memo: a new subscriber catches up from here
    without any compute, and the push path diffs against the previous
    entry. Invalidated by chain extension — when a stream's result for
    the extended digest lands, its superseded entry is dropped (entries
    are digest-keyed and immutable, so "invalidation" is the head
    moving, not a mutate-in-place). Eviction is never an error: the
    next tick repopulates, and a catch-up miss merely means the client
    waits one tick.
    """

    def __init__(self, max_bytes: int | None = None,
                 registry: "obs.Registry | None" = None):
        self.max_bytes = (result_cache_max_bytes() if max_bytes is None
                          else int(max_bytes))
        self._lock = threading.Lock()
        self._lru = ByteLRU(self.max_bytes)
        reg = registry or obs.get_registry()
        self._c_hits = reg.counter(
            "dbx_result_cache_hits_total",
            help="result-cache hits (catch-up pushes + delta diffs)")
        self._c_misses = reg.counter(
            "dbx_result_cache_misses_total",
            help="result-cache misses (evicted or never computed)")
        self._g_bytes = reg.gauge(
            "dbx_result_cache_bytes",
            help="bytes resident in the push result cache")

    def get(self, key) -> bytes | None:
        with self._lock:
            blob = self._lru.get(key)
        if blob is None:
            self._c_misses.inc()
        else:
            self._c_hits.inc()
        return blob

    def put(self, key, blob: bytes, *, drop=None) -> None:
        """Store ``key``; ``drop`` (the superseded chain link's key, if
        any) is removed under the same acquisition so the cache never
        holds two generations of one stream."""
        with self._lock:
            if drop is not None:
                self._lru.pop(drop)
            self._lru.put(key, blob)
            self._g_bytes.set(self._lru.bytes)

    def pop(self, key) -> None:
        with self._lock:
            self._lru.pop(key)
            self._g_bytes.set(self._lru.bytes)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._lru), "bytes": self._lru.bytes,
                    "max_bytes": self.max_bytes}


class Subscription:
    """One Subscribe connection: a bounded push queue + wake-up event.

    The queue is the degradation ladder's middle rung: a slow consumer
    fills it, after which the OLDEST item is dropped and counted — a
    live client wants the freshest result, and the tick path must never
    block on (or allocate unboundedly for) a stalled socket. ``pull``
    waits on the event OUTSIDE the mutex (no wait-under-lock), drains
    everything queued, and returns; the gRPC handler turns each item
    into a PushUpdate.
    """

    def __init__(self, subscriber_id: str, tenant: str, *,
                 queue_max: int | None = None):
        self.subscriber_id = subscriber_id
        self.tenant = tenant
        self.demoted = False
        self.queue_max = sub_queue_max() if queue_max is None \
            else int(queue_max)
        self.dropped = 0          # cumulative, rides every PushUpdate
        self.closed = False
        # Interests this connection was charged for against
        # DBX_TENANT_SUB_QUOTA (may exceed len(streams) when interests
        # duplicate); unsubscribe releases exactly this charge.
        self.n_interests = 0
        self._seq = 0
        self._mutex = threading.Lock()
        self._ready = threading.Event()
        self._items: collections.deque = collections.deque()
        # (chain, key) memberships, maintained by the hub UNDER ITS lock
        # (the hub owns registry state; this is just the reverse index
        # unsubscribe walks).
        self.streams: set = set()

    def push(self, item: PushItem) -> bool:
        """Queue one push; returns False when it displaced an older item
        (bounded-queue overflow) or the subscription is closed."""
        ok = True
        with self._mutex:
            if self.closed:
                return False
            if len(self._items) >= self.queue_max:
                self._items.popleft()
                self.dropped += 1
                ok = False
            self._seq += 1
            item = dataclasses.replace(item, seq=self._seq,
                                       dropped=self.dropped)
            self._items.append(item)
        # Set AFTER the mutex releases: the waiter re-takes the mutex to
        # drain, and the event itself is stdlib-internal (lockdep passes
        # it through raw).
        self._ready.set()
        return ok

    def pull(self, timeout: float = 0.25) -> list[PushItem]:
        """Drain queued pushes, waiting up to ``timeout`` for the first.
        Returns [] on timeout or close (the caller re-checks liveness).
        The event clears BEFORE the drain (same mutex hold): a push
        racing the drain must itself take the mutex to append, so its
        set() lands after our clear and the next pull wakes immediately
        — clearing after the drain would park that item for a full
        timeout."""
        self._ready.wait(timeout)
        with self._mutex:
            self._ready.clear()
            items = list(self._items)
            self._items.clear()
        return items

    def close(self) -> None:
        with self._mutex:
            self.closed = True
            self._items.clear()
        self._ready.set()


class SubscriptionHub:
    """The dispatcher's subscription registry + fan-out engine.

    Maps ``(chain, stream_key)`` to its subscriber set; chains are
    identified by the FIRST digest the hub saw for them (a subscribe or
    the parent of a tick) and follow ``AppendBars`` extensions. The hub
    never touches the job queue — the dispatcher asks it what a tick
    implies (:meth:`on_tick`), enqueues the advance jobs itself, tells
    the hub their ids (:meth:`register_advance`, BEFORE the enqueue so
    a completion can never outrun its registration), and reports
    completions (:meth:`on_result`).
    """

    #: Chain links kept addressable per chain (a subscriber naming any
    #: recent link — e.g. the head it learned before a tick raced it —
    #: still lands on the chain; older links age out of the alias map).
    CHAIN_ALIAS_KEEP = 8

    #: In-flight advance index bound. Entries normally pop at completion
    #: (every append-job rung COMPLETES, never fails — the PR-6 ladder),
    #: but a job failed at materialization (corrupted chain) never
    #: completes and would pin its entry forever; past the bound the
    #: OLDEST entry drops — that push is lost (counted), the stream's
    #: next tick serves fresh.
    MAX_INFLIGHT_ADVANCES = 1 << 16

    def __init__(self, *, registry: "obs.Registry | None" = None,
                 streamable: frozenset | None = None,
                 queue_max: int | None = None,
                 cache_bytes: int | None = None):
        self._lock = threading.Lock()
        self.obs = registry or obs.get_registry()
        self.streamable = streamable
        self._queue_max = queue_max
        # digest -> chain id (the chain's first-seen digest).
        self._chain_of: dict[str, str] = {}
        # chain id -> (head digest, head bars).
        self._heads: dict[str, tuple[str, int]] = {}
        # chain id -> recent link digests (alias-map aging, oldest first).
        self._links: dict[str, collections.deque] = {}
        # (chain, stream_key) -> stream state.
        self._streams: dict[tuple, "_Stream"] = {}
        # live Subscription objects (identity set; sized gauge source).
        self._subs: set = set()
        self._tenant_subs: collections.Counter = collections.Counter()
        # advance job id -> fan-out address (insertion-ordered: the
        # MAX_INFLIGHT_ADVANCES overflow drops oldest-first).
        self._advances: collections.OrderedDict = collections.OrderedDict()
        # (digest, stream_key) advances already enqueued (a duplicate
        # tick of the same delta must not double-advance one stream).
        self._inflight: set = set()
        self.cache = ResultCache(cache_bytes, registry=self.obs)
        self._quotas = parse_tenant_map(
            os.environ.get("DBX_TENANT_SUB_QUOTA", ""))
        self._c_ticks = self.obs.counter(
            "dbx_sub_ticks_total",
            help="AppendBars ticks that touched a subscribed chain")
        self._c_advances = self.obs.counter(
            "dbx_stream_advances_total",
            help="advance-job completions fanned out (one per unique "
                 "live stream per tick — the O(unique streams) cost)")
        self._c_pushes = {
            o: self.obs.counter(
                "dbx_sub_pushes_total",
                help="pushes by outcome (queued = handed to a "
                     "subscriber queue; dropped = displaced an older "
                     "item past DBX_SUB_QUEUE_MAX or unusable "
                     "completion bytes; catch_up = served from the "
                     "result cache at subscribe time; stale = a raced "
                     "advance completing after a longer chain link "
                     "already fanned out, suppressed)",
                outcome=o)
            for o in ("queued", "dropped", "catch_up", "stale")}
        self._c_demotions = self.obs.counter(
            "dbx_sub_demotions_total",
            help="subscriptions admitted over DBX_TENANT_SUB_QUOTA "
                 "(demoted: fan-out-last, never rejected)")
        self._h_push_latency = self.obs.histogram(
            "dbx_tick_to_push_seconds",
            help="AppendBars tick -> push handed to the subscriber "
                 "stream (dispatcher-side delivery wall)")
        self.obs.gauge_fn("dbx_subscriptions", self._n_subs,
                          help="live Subscribe connections")
        self.obs.gauge_fn("dbx_streams_live", self._n_streams,
                          help="unique live (chain, param-block) streams")

    def _n_subs(self) -> int:
        with self._lock:
            return len(self._subs)

    def _n_streams(self) -> int:
        with self._lock:
            return len(self._streams)

    def _quota(self, tenant: str) -> float:
        return self._quotas.get(tenant,
                                self._quotas.get("*", float("inf")))

    # -- subscribe / unsubscribe ------------------------------------------

    def subscribe(self, subscriber_id: str, tenant: str,
                  interests: list[StreamSpec]) -> Subscription:
        """Register one connection's interests; returns its live
        :class:`Subscription` (already receiving). Unknown digests are
        accepted — the stream activates when its chain first ticks —
        and unsupported strategies raise ``ValueError`` (the handler
        turns that into INVALID_ARGUMENT). Catch-up: interests whose
        stream already has a cached head result receive it immediately
        (seq 1, ``catch_up`` flag) so a reconnecting dashboard renders
        without waiting a tick."""
        tenant = tenant or DEFAULT_TENANT
        if self.streamable is not None:
            for spec in interests:
                if spec.strategy not in self.streamable:
                    raise ValueError(
                        f"strategy {spec.strategy!r} is not streamable "
                        "(no carry form; pairs cannot ride a one-panel "
                        "chain)")
        sub = Subscription(subscriber_id, tenant,
                           queue_max=self._queue_max)
        catch_up: list[tuple] = []   # (digest, key, n_bars)
        with self._lock:
            # Quota check counts INTERESTS (a connection carrying 500
            # interests is 500 subscriptions), demotes the whole
            # connection, never rejects: demoted subscriptions are
            # fanned out last and their drops bite first under
            # pressure, but they stay live — the PR-8
            # demotion-not-blocking semantics.
            n_before = self._tenant_subs[tenant]
            if n_before + len(interests) > self._quota(tenant):
                sub.demoted = True
            sub.n_interests = len(interests)
            self._tenant_subs[tenant] += sub.n_interests
            self._subs.add(sub)
            for spec in interests:
                chain = self._chain_of.get(spec.digest, spec.digest)
                self._register_link(chain, spec.digest)
                self._heads.setdefault(chain, (spec.digest, 0))
                skey = (chain, spec.key)
                stream = self._streams.get(skey)
                if stream is None:
                    stream = self._streams[skey] = _Stream(
                        spec=spec, chain=chain)
                stream.members[id(sub)] = sub
                sub.streams.add(skey)
                if stream.last_digest:
                    catch_up.append((stream.last_digest, spec.key,
                                     stream.last_len))
        if sub.demoted:
            self._c_demotions.inc()
        # Cache reads + pushes OUTSIDE the registry lock.
        for digest, key, n_bars in catch_up:
            blob = self.cache.get((digest, key))
            if blob is None:
                continue
            sub.push(PushItem(digest=digest, key=key, seq=0,
                              metrics=blob, new_len=n_bars, tick_unix=0.0,
                              changed=-1, dropped=0, catch_up=True))
            self._c_pushes["catch_up"].inc()
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        """Drop a connection: remove it from every stream, prune
        streams with no members left, and age the chain bookkeeping out
        once its last stream goes (wire-controlled input must not
        accumulate — the WfqScheduler pruning discipline)."""
        with self._lock:
            if sub not in self._subs:
                return
            self._subs.discard(sub)
            self._tenant_subs[sub.tenant] -= sub.n_interests
            if self._tenant_subs[sub.tenant] <= 0:
                del self._tenant_subs[sub.tenant]
            for skey in sub.streams:
                stream = self._streams.get(skey)
                if stream is None:
                    continue
                stream.members.pop(id(sub), None)
                if not stream.members:
                    del self._streams[skey]
            live_chains = {c for c, _ in self._streams}
            for chain in list(self._heads):
                if chain not in live_chains:
                    self._drop_chain(chain)
        sub.close()

    def close(self) -> None:
        """Close every subscription (dispatcher shutdown): their pull
        loops wake and exit, the registry empties."""
        with self._lock:
            subs = list(self._subs)
            self._subs.clear()
            self._streams.clear()
            self._tenant_subs.clear()
            for chain in list(self._heads):
                self._drop_chain(chain)
            self._advances.clear()
            self._inflight.clear()
        for sub in subs:
            sub.close()

    def _drop_chain(self, chain: str) -> None:
        """Caller holds ``self._lock``."""
        self._heads.pop(chain, None)
        for d in self._links.pop(chain, ()):
            self._chain_of.pop(d, None)

    def _register_link(self, chain: str, digest: str) -> None:
        """Caller holds ``self._lock``: digest joins the chain's alias
        map, aging the oldest link out past CHAIN_ALIAS_KEEP."""
        if self._chain_of.get(digest) == chain:
            return
        self._chain_of[digest] = chain
        links = self._links.setdefault(chain, collections.deque())
        links.append(digest)
        while len(links) > self.CHAIN_ALIAS_KEEP:
            old = links.popleft()
            if old != chain:      # the chain id itself stays addressable
                self._chain_of.pop(old, None)
            else:
                links.append(old)  # rotate: keep id, age the next-oldest
                if len(links) <= self.CHAIN_ALIAS_KEEP:
                    break

    # -- the tick path -----------------------------------------------------

    def on_tick(self, parent_digest: str, new_digest: str, new_len: int,
                *, template_key: str | None = None) -> _TickPlan | None:
        """An AppendBars tick extended ``parent -> new``. Returns the
        tick's plan — the unique live streams needing an advance job
        (minus the one the tick's own job template covers, minus any
        already in flight for this digest) — or None when the chain has
        no subscribers (the overwhelming non-serving case: one dict
        probe under the lock)."""
        with self._lock:
            chain = self._chain_of.get(parent_digest)
            if chain is None:
                return None
            self._register_link(chain, new_digest)
            self._heads[chain] = (new_digest, int(new_len))
            advances = []
            template_live = False
            for (c, key), stream in self._streams.items():
                if c != chain:
                    continue
                if template_key is not None and key == template_key:
                    template_live = True
                    continue
                if (new_digest, key) in self._inflight:
                    continue
                self._inflight.add((new_digest, key))
                advances.append(stream.spec)
        self._c_ticks.inc()
        return _TickPlan(chain=chain, advances=advances,
                         template_live=template_live)

    def register_advance(self, job_id: str, chain: str, key: str,
                         digest: str, new_len: int,
                         tick_unix: float) -> None:
        """Index an advance job for fan-out. MUST run before the job is
        enqueued: a worker can take and complete a job the instant it is
        published, and an unregistered completion would drop the push on
        the floor."""
        dropped = 0
        with self._lock:
            self._advances[job_id] = _Advance(
                chain=chain, key=key, digest=digest, new_len=int(new_len),
                tick_unix=tick_unix)
            self._inflight.add((digest, key))
            while len(self._advances) > self.MAX_INFLIGHT_ADVANCES:
                _, old = self._advances.popitem(last=False)
                self._inflight.discard((old.digest, old.key))
                dropped += 1
        if dropped:
            self._c_pushes["dropped"].inc(dropped)

    def has_advances(self) -> bool:
        """Lock-free fast-path probe for the completion hot path: a
        dispatcher serving zero subscriptions pays one attribute read
        per completion batch, not a lock acquisition per item. (A racy
        False is impossible for a registered job: registration happens
        before enqueue, so the dict is non-empty by the time any
        completion for it can arrive.)"""
        return bool(self._advances)

    def on_result(self, job_id: str, metrics: bytes,
                  trace_id: str = "") -> int:
        """An advance job completed: cache its block, diff against the
        stream's previous result, and fan out to every subscriber.
        Returns the number of pushes queued (0 for non-advance jobs).

        Fan-out never blocks: each subscriber queue is bounded with
        drop-oldest-and-count, and nothing here runs under the registry
        lock. Demoted (over-quota) subscriptions are fanned out LAST —
        under equal queue pressure their staleness grows first.

        Ordering: chain lengths strictly grow, so ``new_len`` totally
        orders a stream's advances. A completion arriving AFTER a
        longer chain link already fanned out (two quick ticks, the
        advances raced on different workers) is STALE — suppressed and
        counted, never pushed: delivering it would regress every
        subscriber's view (seq grows while the panel shrinks) and
        caching it would evict the newer block new subscribers catch up
        from."""
        t0 = time.time()
        with self._lock:
            adv = self._advances.pop(job_id, None)
            if adv is None:
                return 0
            self._inflight.discard((adv.digest, adv.key))
            stream = self._streams.get((adv.chain, adv.key))
            if stream is None:      # everyone unsubscribed mid-flight
                return 0
            if adv.new_len <= stream.last_len:
                stale = True
            else:
                stale = False
                prev_digest = stream.last_digest
                stream.last_digest = adv.digest
                stream.last_len = adv.new_len
                members = sorted(stream.members.values(),
                                 key=lambda s: s.demoted)
        if stale:
            self._c_pushes["stale"].inc()
            return 0
        try:
            prev = (self.cache.get((prev_digest, adv.key))
                    if prev_digest and prev_digest != adv.digest else None)
            changed, _total = metric_delta(prev, metrics)
        except ValueError as e:
            # Worker-supplied bytes that do not parse as a DBXM block:
            # nothing a subscriber could use, and an exception here
            # would fail the whole CompleteJobs batch. The completion
            # itself stays recorded (the queue's concern); the push is
            # dropped loudly.
            log.warning("advance %s: completion bytes not a DBXM block "
                        "(%s); push dropped", job_id, e)
            self._c_pushes["dropped"].inc()
            return 0
        self.cache.put((adv.digest, adv.key), metrics,
                       drop=((prev_digest, adv.key)
                             if prev_digest and prev_digest != adv.digest
                             else None))
        self._c_advances.inc()
        item = PushItem(digest=adv.digest, key=adv.key, seq=0,
                        metrics=metrics, new_len=adv.new_len,
                        tick_unix=adv.tick_unix, changed=changed,
                        dropped=0)
        queued = dropped = 0
        for sub in members:
            if sub.push(item):
                queued += 1
            else:
                dropped += 1
        self._c_pushes["queued"].inc(queued)
        if dropped:
            self._c_pushes["dropped"].inc(dropped)
        if trace_id:
            # The dispatcher-side `push` timeline stage: completion
            # recorded -> fanned onto every subscriber queue. Emitted
            # before the caller closes the job's e2e span so the window
            # lands inside the attribution.
            obs.emit_span("job.push", t0, time.time() - t0,
                          trace_id=trace_id, job=job_id, n_subs=queued,
                          dropped=dropped,
                          stream=stream_bucket(adv.key))
        return queued

    def observe_delivery(self, item: PushItem) -> None:
        """Tick-to-push latency at the moment a push is handed to the
        subscriber's stream (the Subscribe generator calls this per
        yielded item; catch-up pushes carry no tick to measure from)."""
        if item.tick_unix:
            self._h_push_latency.observe(
                max(time.time() - item.tick_unix, 0.0))

    def stats(self) -> dict:
        """Registry snapshot (tests + /stats.json consumers)."""
        with self._lock:
            return {
                "subscriptions": len(self._subs),
                "interests": int(sum(self._tenant_subs.values())),
                "streams": len(self._streams),
                "chains": len(self._heads),
                "advances_inflight": len(self._advances),
                "tenants": {tenant_bucket(t): int(n)
                            for t, n in self._tenant_subs.items()},
            }


@dataclasses.dataclass
class _Stream:
    """One unique (chain, param-block) stream's registry state."""

    spec: StreamSpec
    chain: str
    members: dict = dataclasses.field(default_factory=dict)
    last_digest: str = ""    # newest chain link with a fanned-out result
    last_len: int = 0
