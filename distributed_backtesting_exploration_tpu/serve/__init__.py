"""Live signal fan-out: the streaming subscription tier over append chains.

The north star serves millions of users, and millions of users are
READERS — until this package the control plane had only write-shaped
RPCs (enqueue, append, complete). :mod:`.registry` owns the read path:
clients register interests keyed by (panel chain, strategy, param-block
grid, tenant) over the server-streaming ``Subscribe`` RPC, and every
``AppendBars`` tick on a subscribed chain schedules exactly ONE O(ΔT)
carry advance per unique live stream — riding the ordinary append-job
dispatch and the workers' CarryStore machinery — then fans the
resulting metric block out to every subscriber of that stream from a
result cache keyed ``(digest, stream_key)``. N followers of a hot
symbol cost one advance: serving cost is O(unique streams), not
O(subscribers) — the cached-recurrent-state serving discipline of
PAPERS.md "Compiler-First State Space Duality and Portable O(1)
Autoregressive Caching" applied to a fleet instead of a decoder.

Degradation ladder (never block the tick path): slow subscriber ->
bounded per-subscriber queue (``DBX_SUB_QUEUE_MAX``) -> drop-oldest-and-
count (the client sees the gap in ``PushUpdate.dropped`` and in its
``seq`` holes). Tenancy rides the PR-8 machinery: per-tenant
subscription quotas (``DBX_TENANT_SUB_QUOTA``) demote, never reject,
and fan-out order + per-subscriber isolation keep a whale subscriber
from moving small tenants' push latency. Subscriptions are in-memory
only — a dispatcher restart drops them cleanly and a re-subscribe
resumes from the journal-replayed chain.
"""

from .registry import (  # noqa: F401
    PushItem, ResultCache, StreamSpec, Subscription, SubscriptionHub,
    result_cache_max_bytes, stream_key, sub_queue_max)
