"""On-balance-volume trend (path-free): OBV vs its own rolling mean.

``obv[t] = sum_{s<=t} sign(close[s] - close[s-1]) * v[s]`` — the classic
volume-flow accumulator — traded as ``sign(obv - sma_w(obv))``: long while
volume flow runs above its ``window``-bar average, short below. This is
the framework's first *volume-led* trend family (VWAP reversion consumes
volume too, but as a price anchor; here volume IS the signal).

Numerics: ``v = volume / volume[..., :1]`` — the traded quantity
``sign(obv - sma)`` is invariant under positive scaling of volume (both
terms are linear in ``v``), and normalizing by the first bar keeps the
double accumulation (cumsum for OBV, cumsum-difference for its SMA) at
O(1) magnitudes instead of raw-volume ~1e6 scale, so the f32 error budget
tracks the signal. The first bar is always real, even in ragged panels
(padding is appended), so the normalizer never reads a padded value.

The padding discipline holds for free: appended pad bars repeat the last
close, so ``diff = 0`` and the OBV step is exactly zero — OBV is flat over
padding and trailing windows never look forward.

Warmup: positions are masked flat for ``t < window - 1`` (the SMA's rule).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import rolling
from .base import Strategy, register


#: Shared with the fused kernel prep (``ops.fused._fused_obv_call``) so the
#: generic and fused paths evaluate ONE definition — see ``rolling.obv_series``.
obv_series = rolling.obv_series


def _positions(ohlcv, params):
    close = ohlcv.close
    w = params["window"]
    obv = obv_series(close, ohlcv.volume)
    sma = rolling.rolling_mean(obv, w)
    valid = rolling.valid_mask(close.shape[-1], w)
    return jnp.where(valid, jnp.sign(obv - sma), 0.0)


OBV_TREND = register(Strategy(
    name="obv_trend",
    param_fields=("window",),
    positions_fn=_positions,
    stateful=False,
))
