"""MACD signal-line crossover (path-free).

``macd = ema(close, fast) - ema(close, slow)``; the trade is the sign of
``macd - ema(macd, signal)``. Every EMA evaluates as an associative scan
(``ops.rolling.ema`` — O(log T) fused VPU passes), so the whole strategy is
prefix-engine work with no serial time loop: the same shape as the SMA
crossover but with exponential windows, giving the sweep engine a second
path-free trend family.

Warmup: EMAs are defined from bar 0 (seed ``y0 = x0``) but are dominated by
the seed early on; positions are masked flat for ``t < slow + signal - 2``
— the span after which every constituent EMA has seen a full window's worth
of decay, mirroring the SMA crossover's ``max(fast, slow)`` warmup rule.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import rolling
from .base import Strategy, register


def macd_lines(close, fast, slow, signal):
    """``(macd, signal_line)`` for spans ``fast``/``slow``/``signal``
    (traced scalars allowed; shapes ``(..., T)``)."""
    macd = rolling.ema(close, span=fast) - rolling.ema(close, span=slow)
    return macd, rolling.ema(macd, span=signal)


def _positions(ohlcv, params):
    close = ohlcv.close
    macd, sig = macd_lines(close, params["fast"], params["slow"],
                           params["signal"])
    warm = jnp.asarray(params["slow"]) + jnp.asarray(params["signal"]) - 1.0
    valid = rolling.valid_mask(close.shape[-1], warm)
    return jnp.where(valid, jnp.sign(macd - sig), 0.0)


MACD = register(Strategy(
    name="macd",
    param_fields=("fast", "slow", "signal"),
    positions_fn=_positions,
    stateful=False,
))
