"""MACD signal-line crossover (path-free).

``macd = ema(close, fast) - ema(close, slow)``; the trade is the sign of
``macd - ema(macd, signal)``. Every EMA evaluates as a Hillis–Steele
shift-doubling ladder (``ops.rolling.ema_ladder`` — ~log2(T) fused VPU
passes), so the whole strategy is prefix-engine work with no serial time
loop: the same shape as the SMA crossover but with exponential windows,
giving the sweep engine a second path-free trend family.

Two deliberate numeric choices (both are exact-arithmetic identities for
the traded quantity ``sign(macd - signal_line)``, chosen so the generic
path and the fused kernel resolve the same knife edges):

- **The close series is demeaned** (``close - close[..., :1]``) before the
  EMAs. EMA weights sum to one, so a constant shift passes through both
  EMAs and cancels in the difference — ``macd`` is shift-invariant — but in
  f32 the absolute rounding error scales with the *level* of the input
  (~price x eps), while the crossing margin scales with price *deviations*.
  Demeaning makes the error budget track the signal, not the level.
- **The ladder, not ``associative_scan``**: the fused MACD kernel evaluates
  its EMAs with the same shift-doubling ladder, so using
  :func:`~..ops.rolling.ema_ladder` here makes the two paths rounding
  twins (measured: 26/6400 verify cells flipped with associative_scan,
  0 with the ladder on the same grid).

Warmup: EMAs are defined from bar 0 (seed ``y0 = x0``) but are dominated by
the seed early on; positions are masked flat for ``t < slow + signal - 2``
— the span after which every constituent EMA has seen a full window's worth
of decay, mirroring the SMA crossover's ``max(fast, slow)`` warmup rule.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import rolling
from .base import Strategy, register


def macd_lines(close, fast, slow, signal):
    """``(macd, signal_line)`` for spans ``fast``/``slow``/``signal``
    (traced scalars allowed; shapes ``(..., T)``).

    Computed on the demeaned series — identical to the textbook value in
    exact arithmetic (see module docstring), ~100x less f32 rounding error
    on realistically-priced inputs.
    """
    x = close - close[..., :1]
    macd = (rolling.ema_ladder(x, span=fast)
            - rolling.ema_ladder(x, span=slow))
    return macd, rolling.ema_ladder(macd, span=signal)


def _positions(ohlcv, params):
    close = ohlcv.close
    macd, sig = macd_lines(close, params["fast"], params["slow"],
                           params["signal"])
    warm = jnp.asarray(params["slow"]) + jnp.asarray(params["signal"]) - 1.0
    valid = rolling.valid_mask(close.shape[-1], warm)
    return jnp.where(valid, jnp.sign(macd - sig), 0.0)


MACD = register(Strategy(
    name="macd",
    param_fields=("fast", "slow", "signal"),
    positions_fn=_positions,
    stateful=False,
))
