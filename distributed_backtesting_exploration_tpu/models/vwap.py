"""VWAP-deviation mean-reversion (stateful): the volume-weighted family.

Rolling VWAP over the trailing ``window`` bars is
``sum(close * volume) / sum(volume)`` — two O(T) cumsum-difference rolling
sums. The trade: z-score the close's deviation from VWAP (std of the
deviation over the same window) and run the shared band machine — enter
when price stretches ``k`` deviations from the volume-weighted anchor,
exit when it re-crosses it.

This is the first family whose signal consumes the ``volume`` field, so
the OHLCV panel's non-close columns carry real information through the
sweep engine (every panel op is already struct-of-arrays; nothing changes
shape-wise).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import rolling, signals
from .base import Strategy, register


def rolling_vwap(close, volume, window, *, eps=1e-12):
    """Trailing-``window`` volume-weighted average price; ``(..., T)``.

    ``window`` may be traced (vmap over window grids). Bars with zero total
    volume in the window fall back to the plain close (deviation 0).
    """
    pv = rolling.rolling_sum(close * volume, window, fill=jnp.nan)
    v = rolling.rolling_sum(volume, window, fill=jnp.nan)
    return jnp.where(v > eps, pv / (v + eps), close)


def _positions(ohlcv, params):
    close, volume = ohlcv.close, ohlcv.volume
    w = params["window"]
    vwap = rolling_vwap(close, volume, w)
    dev = close - vwap
    z = rolling.rolling_zscore(dev, w, fill=0.0)
    # VWAP needs `w` bars, its deviation's z-score another `w`.
    valid = rolling.valid_mask(close.shape[-1], 2 * jnp.asarray(w) - 1)
    return signals.band_hysteresis_assoc(
        jnp.where(valid, z, 0.0), valid, params["k"], 0.0)


VWAP_REVERSION = register(Strategy(
    name="vwap_reversion",
    param_fields=("window", "k"),
    positions_fn=_positions,
    stateful=True,
))
