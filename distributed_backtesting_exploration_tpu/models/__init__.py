"""Strategy families (the framework's "model zoo").

Each strategy maps OHLCV arrays + a parameter set to a position series; the
sweep engine vmaps it over (ticker x parameter) grids. See ``models.base`` for
the Strategy API and the registry.
"""

from .base import Strategy, register, get_strategy, available_strategies  # noqa: F401
from . import (  # noqa: F401
    bollinger, donchian, keltner, macd, momentum, obv, pairs, rsi,
    sma_crossover, stochastic, trix, vwap)
