"""SMA-crossover strategy (path-free).

The canonical sweep workload (``BASELINE.json`` configs[0] and [1], and the
north-star benchmark: a 500-ticker SMA-crossover sweep over 5y of daily bars).
Long when the fast SMA is above the slow SMA, short when below, flat during
warmup. Because the position is a pure function of the two SMAs at bar ``t``,
this runs entirely on the vectorized prefix engine — no scan.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import rolling
from .base import Strategy, register


def _positions(ohlcv, params):
    close = ohlcv.close
    fast = rolling.rolling_mean(close, params["fast"], fill=0.0)
    slow = rolling.rolling_mean(close, params["slow"], fill=0.0)
    valid = rolling.valid_mask(close.shape[-1], params["slow"]) & \
        rolling.valid_mask(close.shape[-1], params["fast"])
    return jnp.where(valid, jnp.sign(fast - slow), 0.0)


SMA_CROSSOVER = register(Strategy(
    name="sma_crossover",
    param_fields=("fast", "slow"),
    positions_fn=_positions,
    stateful=False,
))
