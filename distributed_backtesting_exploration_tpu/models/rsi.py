"""RSI mean-reversion (stateful): Wilder's relative strength index with the
shared band-hysteresis machine.

RSI is an EMA-smoothed ratio of up-moves to down-moves mapped into
``[0, 100]``. The classic symmetric mean-reversion trade: enter long when
RSI drops below ``50 - band`` (oversold), enter short above ``50 + band``
(overbought), hold until RSI re-crosses 50. Centering the index
(``rsi - 50``) makes this exactly the band machine shared with Bollinger
and pairs (``ops.signals.band_hysteresis_assoc`` — O(log T) depth, no
serial scan), so one hysteresis implementation serves all three families.

Smoothing uses this library's EMA (``y0 = x0`` seed, associative-scan form,
``alpha = 1/period`` — Wilder's decay). Classic Wilder seeds the average
with an SMA over the first ``period`` bars instead; after a few multiples
of ``period`` the two are indistinguishable, and the warmup region is
masked flat anyway. The golden test pins these semantics against a pure
NumPy recurrence.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import rolling, signals
from .base import Strategy, register


def rsi_index(close, period):
    """Wilder's RSI in ``[0, 100]``; shapes ``(..., T)`` -> same.

    ``period`` may be traced (vmap over period grids).
    """
    diff = jnp.diff(close, axis=-1, prepend=close[..., :1])
    gains = jnp.maximum(diff, 0.0)
    losses = jnp.maximum(-diff, 0.0)
    alpha = 1.0 / jnp.asarray(period, close.dtype)
    avg_gain = rolling.ema(gains, alpha=alpha)
    avg_loss = rolling.ema(losses, alpha=alpha)
    return 100.0 - 100.0 / (1.0 + avg_gain / (avg_loss + 1e-12))


def _positions(ohlcv, params):
    close = ohlcv.close
    rsi = rsi_index(close, params["period"])
    valid = rolling.valid_mask(close.shape[-1],
                               jnp.asarray(params["period"]) + 1)
    # Centered index: long when rsi < 50 - band, short when rsi > 50 + band,
    # exit when rsi re-crosses 50 — the shared machine with z_exit = 0.
    return signals.band_hysteresis_assoc(
        rsi - 50.0, valid, params["band"], 0.0)


RSI = register(Strategy(
    name="rsi",
    param_fields=("period", "band"),
    positions_fn=_positions,
    stateful=True,
))
