"""Stochastic-oscillator mean-reversion (stateful): %K with the shared
band-hysteresis machine.

``%K = 100 * (close - LL_w) / (HH_w - LL_w)`` locates the close inside the
trailing ``window``-bar high/low channel — the second family (after the
high/low Donchian) consuming the HIGH/LOW columns, and the classic
overbought/oversold oscillator. Centering (``%K - 50``) makes the trade
exactly the band machine shared with Bollinger/RSI/VWAP
(``ops.signals.band_hysteresis_assoc``): enter long below ``50 - band``
(oversold), short above ``50 + band``, hold until %K re-crosses 50.

Channel extrema use the traced-window masked-view kernel
(``rolling.rolling_extrema_traced``) so the sweep engine can vmap over
``window`` grids; ``MAX_WINDOW`` bounds the static view, as in
``models.donchian``. A flat channel (HH == LL) yields %K = 50 (neutral).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import rolling, signals
from .base import Strategy, register

MAX_WINDOW = 256


def stochastic_k(high, low, close, window, *, max_window: int = MAX_WINDOW,
                 eps: float = 1e-12):
    """%K in ``[0, 100]``; shapes ``(..., T)`` -> same. ``window`` may be
    traced (vmap over window grids, bounded by ``max_window``)."""
    hh = rolling.rolling_extrema_traced(
        high, window, max_window=max_window, mode="max", fill=jnp.inf)
    ll = rolling.rolling_extrema_traced(
        low, window, max_window=max_window, mode="min", fill=-jnp.inf)
    rng = hh - ll
    return jnp.where(rng > eps, 100.0 * (close - ll) / (rng + eps), 50.0)


def _positions(ohlcv, params):
    w = params["window"]
    k_pct = stochastic_k(ohlcv.high, ohlcv.low, ohlcv.close, w)
    valid = rolling.valid_mask(ohlcv.close.shape[-1], jnp.asarray(w))
    return signals.band_hysteresis_assoc(
        jnp.where(valid, k_pct - 50.0, 0.0), valid, params["band"], 0.0)


STOCHASTIC = register(Strategy(
    name="stochastic",
    param_fields=("window", "band"),
    positions_fn=_positions,
    stateful=True,
))
