"""Keltner-channel mean-reversion (stateful): EMA midline + ATR bands.

The channel midline is an EMA of the close; the band half-width is ``k``
average true ranges (ATR = rolling mean of the true range, which consumes
the high/low columns). Normalizing the close's deviation from the midline
by the ATR gives a z-like score fed to the shared band machine
(``ops.signals.band_hysteresis_assoc``): enter long when price stretches
``k`` ATRs below the midline, short above, hold until it re-crosses the
midline — the volatility-scaled cousin of the Bollinger trade (which
normalizes by the rolling *standard deviation* instead).

True range per bar: ``max(high - low, |high - prev_close|,
|low - prev_close|)`` (the first bar has no previous close and uses
``high - low``). Both the EMA span and the ATR window equal ``window``;
a zero-ATR window (constant prices) yields deviation 0 (neutral).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import rolling, signals
from .base import Strategy, register


def true_range(high, low, close):
    """Per-bar true range; shapes ``(..., T)`` -> same."""
    prev_close = jnp.concatenate([close[..., :1], close[..., :-1]], axis=-1)
    return jnp.maximum(high - low,
                       jnp.maximum(jnp.abs(high - prev_close),
                                   jnp.abs(low - prev_close)))


def keltner_z(high, low, close, window, *, eps: float = 1e-12):
    """``(close - EMA_w(close)) / ATR_w`` — ATR-normalized deviation.

    ``window`` may be traced (vmap over window grids); zero-ATR windows
    yield 0 (neutral).
    """
    mid = rolling.ema(close, span=window)
    atr = rolling.rolling_mean(true_range(high, low, close), window,
                               fill=jnp.nan)
    dev = close - mid
    return jnp.where(atr > eps, dev / (atr + eps), 0.0)


def _positions(ohlcv, params):
    w = params["window"]
    z = keltner_z(ohlcv.high, ohlcv.low, ohlcv.close, w)
    valid = rolling.valid_mask(ohlcv.close.shape[-1], jnp.asarray(w))
    return signals.band_hysteresis_assoc(
        jnp.where(valid, z, 0.0), valid, params["k"], 0.0)


KELTNER = register(Strategy(
    name="keltner",
    param_fields=("window", "k"),
    positions_fn=_positions,
    stateful=True,
))
