"""TRIX: triple-EMA rate-of-change with a signal-line crossover (path-free).

``trix = roc(ema(ema(ema(close, span), span), span))`` — one-bar rate of
change of a triple-smoothed close — traded as ``sign(trix - ema(trix,
signal))``, the same crossover shape as MACD but on a triple-filtered
oscillator, giving the sweep engine a third path-free trend family with a
*different* noise/lag trade-off (three cascaded poles vs MACD's two spans).

Every EMA evaluates as a Hillis–Steele shift-doubling ladder
(``ops.rolling.ema_ladder`` — ~log2(T) fused VPU passes), the exact
rounding twin of the fused kernel's in-kernel ladder, so the generic and
fused paths resolve the same knife edges (the MACD family's round-4
lesson). No demeaning is needed here: the rate of change is a *ratio*, so
the price level cancels instead of inflating the f32 error budget.

Warmup: each EMA stage is seed-dominated for ~span bars; positions are
masked flat for ``t < 3*span + signal - 3`` (three cascaded spans plus the
signal span, the MACD rule extended to the triple cascade, plus one bar
for the rate-of-change difference).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import rolling
from .base import Strategy, register


def trix_lines(close, span, signal):
    """``(trix, signal_line)`` for spans ``span``/``signal`` (traced scalars
    allowed; shapes ``(..., T)``). ``trix[0] = 0`` (the one-bar rate of
    change has no history at bar 0)."""
    e3 = rolling.ema_ladder(
        rolling.ema_ladder(
            rolling.ema_ladder(close, span=span), span=span), span=span)
    prev = jnp.concatenate([e3[..., :1], e3[..., :-1]], axis=-1)
    trix = e3 / prev - 1.0
    return trix, rolling.ema_ladder(trix, span=signal)


def _positions(ohlcv, params):
    close = ohlcv.close
    trix, sig = trix_lines(close, params["span"], params["signal"])
    warm = 3.0 * jnp.asarray(params["span"]) + jnp.asarray(params["signal"]) - 2.0
    valid = rolling.valid_mask(close.shape[-1], warm)
    return jnp.where(valid, jnp.sign(trix - sig), 0.0)


TRIX = register(Strategy(
    name="trix",
    param_fields=("span", "signal"),
    positions_fn=_positions,
    stateful=False,
))
