"""Donchian-channel breakout (stateful).

Classic trend-following: go long when the close breaks above the trailing
``window``-bar high, short when it breaks below the trailing low, and hold
until the opposite channel is touched. The channel at bar ``t`` uses bars
``t-window .. t-1`` (the breakout bar itself is excluded, else every bar
"breaks" its own high). Path dependence (hold until reversal) runs as a
``lax.scan``; the channel extrema use the traced-window masked-view kernel
so the sweep engine can vmap over ``window`` grids (``max_window`` bounds
the view and is a static field of the strategy construction).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops import rolling
from .base import Strategy, register

MAX_WINDOW = 256


def _latch(close, hi, lo, w):
    """Shared breakout latch: +1 above the prior channel high, -1 below the
    prior low, hold otherwise; warmup flat."""
    # Channel known at the close of t-1, applied to bar t.
    hi_prev = jnp.concatenate([jnp.full_like(hi[..., :1], jnp.inf),
                               hi[..., :-1]], axis=-1)
    lo_prev = jnp.concatenate([jnp.full_like(lo[..., :1], -jnp.inf),
                               lo[..., :-1]], axis=-1)
    up = close >= hi_prev
    down = close <= lo_prev
    valid = rolling.valid_mask(close.shape[-1], jnp.asarray(w) + 1)

    def step(pos, inp):
        up_t, down_t, valid_t = inp
        nxt = jnp.where(up_t, 1.0, jnp.where(down_t, -1.0, pos))
        nxt = jnp.where(valid_t, nxt, 0.0)
        return nxt, nxt

    xs = (jnp.moveaxis(up, -1, 0), jnp.moveaxis(down, -1, 0),
          jnp.moveaxis(jnp.broadcast_to(valid, up.shape), -1, 0))
    _, pos_t = jax.lax.scan(step, jnp.zeros(up.shape[:-1]), xs, unroll=8)
    return jnp.moveaxis(pos_t, 0, -1)


def _positions(ohlcv, params):
    close = ohlcv.close
    w = params["window"]
    hi = rolling.rolling_extrema_traced(
        close, w, max_window=MAX_WINDOW, mode="max", fill=jnp.inf)
    lo = rolling.rolling_extrema_traced(
        close, w, max_window=MAX_WINDOW, mode="min", fill=-jnp.inf)
    return _latch(close, hi, lo, w)


def _positions_hl(ohlcv, params):
    """Classic Donchian channels from the HIGH/LOW columns: breakout when
    the close clears the trailing extreme of the *highs*/*lows* — the first
    family to consume the high/low fields (the close-only variant above is
    kept as `donchian`; both route to `ops.fused` kernels)."""
    w = params["window"]
    hi = rolling.rolling_extrema_traced(
        ohlcv.high, w, max_window=MAX_WINDOW, mode="max", fill=jnp.inf)
    lo = rolling.rolling_extrema_traced(
        ohlcv.low, w, max_window=MAX_WINDOW, mode="min", fill=-jnp.inf)
    return _latch(ohlcv.close, hi, lo, w)


DONCHIAN = register(Strategy(
    name="donchian",
    param_fields=("window",),
    positions_fn=_positions,
    stateful=True,
))

DONCHIAN_HL = register(Strategy(
    name="donchian_hl",
    param_fields=("window",),
    positions_fn=_positions_hl,
    stateful=True,
))
