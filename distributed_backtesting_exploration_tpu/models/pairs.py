"""Rolling-OLS pairs trade (``BASELINE.json`` configs[3]).

A pair is (y, x) close series. Per bar: a rolling OLS of y on x gives the
hedge ratio ``beta``; the spread ``y - (alpha + beta x)`` is z-scored over the
same lookback; the machine enters a unit spread position when ``|z|`` exceeds
``z_entry`` and exits when z re-crosses ``z_exit`` (hysteresis, evaluated in
log depth via the associative band machine).
Spread return per bar is ``pos[t-1] * (r_y[t] - beta[t-1] * r_x[t]) / (1 + |beta|)``
(gross exposure normalized), with cost charged on both legs' turnover.

Pairs don't fit the single-asset :class:`~.base.Strategy` seam (two inputs),
so this module owns its sweep entry point :func:`run_pairs_sweep`, vmapped
over (pair x param) exactly like the single-asset engine — one fused XLA
program per job.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..ops import metrics as metrics_mod
from ..ops import pnl as pnl_mod
from ..ops import rolling, signals

Array = jax.Array


def pair_signals(y: Array, x: Array, lookback):
    """Rolling hedge ratio and spread z-score for one pair; ``(T,)`` each.

    ``lookback`` may be traced (vmap over lookback grids).
    """
    alpha, beta = rolling.rolling_ols(y, x, lookback, fill=0.0)
    spread = y - (alpha + beta * x)
    z = rolling.rolling_zscore(spread, lookback, fill=0.0)
    # The spread itself needs `lookback` bars of OLS warmup, and its z-score
    # another `lookback`; mask both.
    valid = rolling.valid_mask(y.shape[-1], 2 * jnp.asarray(lookback) - 1)
    return beta, jnp.where(valid, z, 0.0), valid


def pairs_positions(y: Array, x: Array, params) -> tuple[Array, Array]:
    """Stateful entry/exit machine; returns ``(pos, beta)`` each ``(T,)``.

    pos = +1: long spread (long y, short beta*x); -1: short spread; 0 flat.
    Shares the band-hysteresis scan with Bollinger mean-reversion.
    """
    beta, z, valid = pair_signals(y, x, params["lookback"])
    pos = signals.band_hysteresis_assoc(
        z, valid, params["z_entry"], params.get("z_exit", 0.0))
    return pos, beta


def pair_net_returns(y: Array, x: Array, params, *, cost=0.0):
    """Positions + per-bar net spread returns + hedged-return factor.

    THE semantics-defining PnL of the pairs trade — the sweep, the fused
    kernel's parity contract, and the walk-forward engine all price
    against this one function. Returns ``(pos, net, hr)`` where
    ``hr[t] = (r_y[t] - beta[t-1]*r_x[t]) / max(1 + |beta[t-1]|, 1)`` is
    the gross-normalized spread return of holding one unit into bar t and
    ``net = prev_pos * hr - cost * |Δpos|``. Returns are per unit of gross
    book, so cost is too: leg notional ``|Δpos|*(1+|beta|)`` over the same
    gross normalizer reduces to ``|Δpos|``.
    """
    pos, beta = pairs_positions(y, x, params)
    ry = pnl_mod.simple_returns(y)
    rx = pnl_mod.simple_returns(x)
    prev_pos = jnp.concatenate(
        [jnp.zeros_like(pos[..., :1]), pos[..., :-1]], axis=-1)
    prev_beta = jnp.concatenate(
        [jnp.zeros_like(beta[..., :1]), beta[..., :-1]], axis=-1)
    gross = 1.0 + jnp.abs(prev_beta)
    hr = (ry - prev_beta * rx) / jnp.maximum(gross, 1.0)
    turnover = jnp.abs(pos - prev_pos)
    net = prev_pos * hr - jnp.asarray(cost, y.dtype) * turnover
    return pos, net, hr


def pair_backtest(y: Array, x: Array, params, *, cost=0.0,
                  periods_per_year: int = 252) -> metrics_mod.Metrics:
    """Full backtest of one pair under one param set (vmap target)."""
    pos, net, _ = pair_net_returns(y, x, params, cost=cost)
    equity = 1.0 + jnp.cumsum(net, axis=-1)
    return metrics_mod.summary_metrics(
        net, equity, pos, periods_per_year=periods_per_year)


def _pairs_sweep(y_close: Array, x_close: Array, grid, *, cost,
                 periods_per_year: int) -> metrics_mod.Metrics:
    def per_param(y1, x1, p):
        return pair_backtest(y1, x1, p, cost=cost,
                             periods_per_year=periods_per_year)

    def per_pair(y1, x1):
        return jax.vmap(lambda p: per_param(y1, x1, p))(dict(grid))

    return jax.vmap(per_pair)(y_close, x_close)


@functools.partial(jax.jit, static_argnames=("periods_per_year",))
def run_pairs_sweep(y_close: Array, x_close: Array, grid, *, cost=0.0,
                    periods_per_year: int = 252) -> metrics_mod.Metrics:
    """Evaluate every (pair, param) combo; fields come back ``(n_pairs, P)``.

    ``y_close``/``x_close`` are ``(n_pairs, T)``; ``grid`` maps param name ->
    ``(P,)`` (see :func:`~..parallel.sweep.product_grid`).
    """
    return _pairs_sweep(y_close, x_close, grid, cost=cost,
                        periods_per_year=periods_per_year)


@functools.partial(
    jax.jit, static_argnames=("param_chunk", "periods_per_year"))
def chunked_pairs_sweep(y_close: Array, x_close: Array, grid, *,
                        param_chunk: int, cost=0.0,
                        periods_per_year: int = 252) -> metrics_mod.Metrics:
    """Memory-bounded pairs sweep: ``lax.map`` over param chunks.

    A fully-vmapped pairs sweep materializes ``(pairs, P, T)`` intermediates
    (several live at once — beta, spread, z, positions), which blows past HBM
    at the 1k-pairs x 500-param baseline scale. Chunking the param axis
    bounds live memory exactly like ``sweep.chunked_sweep`` does for the
    single-asset engine. ``P`` must be divisible by ``param_chunk``.
    """
    from ..parallel.sweep import map_param_chunks

    def one_chunk(g):
        return _pairs_sweep(y_close, x_close, g, cost=cost,
                            periods_per_year=periods_per_year)

    return map_param_chunks(grid, param_chunk, one_chunk)
