"""Time-series momentum (path-free): sign of the trailing ``lookback`` return."""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import rolling
from .base import Strategy, register


def _positions(ohlcv, params):
    close = ohlcv.close
    lb = params["lookback"]
    T = close.shape[-1]
    idx = jnp.arange(T) - jnp.asarray(lb)
    past = jnp.take(close, jnp.clip(idx, 0, T - 1).astype(jnp.int32), axis=-1)
    valid = rolling.valid_mask(T, jnp.asarray(lb) + 1)
    return jnp.where(valid, jnp.sign(close - past), 0.0)


MOMENTUM = register(Strategy(
    name="momentum",
    param_fields=("lookback",),
    positions_fn=_positions,
    stateful=False,
))
