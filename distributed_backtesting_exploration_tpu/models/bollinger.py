"""Bollinger-band mean-reversion (stateful) and band-touch (path-free).

``BASELINE.json`` configs[2]: 500 tickers x 1k (window, sigma) grid.

``bollinger`` is the classic hysteresis machine — enter long when the z-score
drops below ``-k``, enter short above ``+k``, hold until the price re-crosses
the rolling mean — so the position depends on its own past: a genuine
``lax.scan`` over bars with a one-scalar carry per (ticker, param) lane.

``bollinger_touch`` is the path-free variant (exposure = which band you are
currently outside of), used where prefix-engine throughput matters more than
the hold-until-exit semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops import rolling
from .base import Strategy, register


def _z_and_valid(ohlcv, params):
    close = ohlcv.close
    z = rolling.rolling_zscore(close, params["window"], fill=0.0)
    valid = rolling.valid_mask(close.shape[-1], params["window"])
    return z, valid


def _touch_positions(ohlcv, params):
    z, valid = _z_and_valid(ohlcv, params)
    k = params["k"]
    pos = jnp.where(z < -k, 1.0, jnp.where(z > k, -1.0, 0.0))
    return jnp.where(valid, pos, 0.0)


def _mr_positions(ohlcv, params):
    z, valid = _z_and_valid(ohlcv, params)
    k = params["k"]

    def step(pos, inp):
        z_t, valid_t = inp
        entered = jnp.where(z_t < -k, 1.0, jnp.where(z_t > k, -1.0, 0.0))
        # exit when price re-crosses the rolling mean, in the held direction
        exit_long = (pos > 0) & (z_t >= 0)
        exit_short = (pos < 0) & (z_t <= 0)
        held = jnp.where(exit_long | exit_short, 0.0, pos)
        nxt = jnp.where(pos == 0, entered, held)
        nxt = jnp.where(valid_t, nxt, 0.0)
        return nxt, nxt

    xs = (jnp.moveaxis(z, -1, 0), jnp.moveaxis(
        jnp.broadcast_to(valid, z.shape), -1, 0))
    _, pos_tmajor = jax.lax.scan(step, jnp.zeros(z.shape[:-1]), xs, unroll=8)
    return jnp.moveaxis(pos_tmajor, 0, -1)


BOLLINGER = register(Strategy(
    name="bollinger",
    param_fields=("window", "k"),
    positions_fn=_mr_positions,
    stateful=True,
))

BOLLINGER_TOUCH = register(Strategy(
    name="bollinger_touch",
    param_fields=("window", "k"),
    positions_fn=_touch_positions,
    stateful=False,
))
