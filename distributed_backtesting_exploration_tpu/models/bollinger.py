"""Bollinger-band mean-reversion (stateful) and band-touch (path-free).

``BASELINE.json`` configs[2]: 500 tickers x 1k (window, sigma) grid.

``bollinger`` is the classic hysteresis machine — enter long when the z-score
drops below ``-k``, enter short above ``+k``, hold until the price re-crosses
the rolling mean — so the position depends on its own past. The 3-state
transition maps compose associatively, so the machine evaluates in O(log T)
depth (``ops.signals.band_hysteresis_assoc``) instead of a serial scan.

``bollinger_touch`` is the path-free variant (exposure = which band you are
currently outside of), used where prefix-engine throughput matters more than
the hold-until-exit semantics.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import rolling, signals
from .base import Strategy, register


def _z_and_valid(ohlcv, params):
    close = ohlcv.close
    z = rolling.rolling_zscore(close, params["window"], fill=0.0)
    valid = rolling.valid_mask(close.shape[-1], params["window"])
    return z, valid


def _touch_positions(ohlcv, params):
    z, valid = _z_and_valid(ohlcv, params)
    k = params["k"]
    pos = jnp.where(z < -k, 1.0, jnp.where(z > k, -1.0, 0.0))
    return jnp.where(valid, pos, 0.0)


def _mr_positions(ohlcv, params):
    # Exit at the rolling mean = the shared band machine with z_exit=0.
    # The associative form evaluates the hysteresis in O(log T) depth —
    # identical states, no serial scan (see ops.signals).
    z, valid = _z_and_valid(ohlcv, params)
    return signals.band_hysteresis_assoc(z, valid, params["k"], 0.0)


BOLLINGER = register(Strategy(
    name="bollinger",
    param_fields=("window", "k"),
    positions_fn=_mr_positions,
    stateful=True,
))

BOLLINGER_TOUCH = register(Strategy(
    name="bollinger_touch",
    param_fields=("window", "k"),
    positions_fn=_touch_positions,
    stateful=False,
))
