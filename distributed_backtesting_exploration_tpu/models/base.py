"""Strategy API and registry.

A strategy is a pure function from (OHLCV arrays, scalar parameter set) to a
position series ``(T,)`` in ``[-1, 1]`` — the seam the sweep engine vmaps over
(ticker x param) grids. The reference has no strategy layer at all
(reference ``README.md:84`` "No actual backtesting strategies are implemented");
this registry is the slot its sleep stub reserved.

Stateful strategies (hysteresis/hold-until-exit) run their tiny per-bar state
machine with ``lax.scan`` *inside* ``positions``; indicator math stays in the
vectorized rolling ops. Path-free strategies are pure elementwise transforms.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Mapping

import jax

Array = jax.Array
ParamSet = Mapping[str, Array]  # scalar leaves (possibly traced) keyed by name


@dataclasses.dataclass(frozen=True)
class Strategy:
    """A named, registrable strategy.

    Attributes:
        name: registry key (also the wire-level strategy id in JobSpec).
        param_fields: ordered names of the scalar parameters it consumes.
        positions_fn: ``(ohlcv, params) -> (T,)`` target-exposure series.
        stateful: True if positions carry path dependence (uses lax.scan).
    """

    name: str
    param_fields: tuple[str, ...]
    positions_fn: Callable[[object, ParamSet], Array]
    stateful: bool = False

    def positions(self, ohlcv, params: ParamSet) -> Array:
        missing = [f for f in self.param_fields if f not in params]
        if missing:
            raise KeyError(f"strategy {self.name!r} missing params {missing}")
        return self.positions_fn(ohlcv, params)


_REGISTRY: dict[str, Strategy] = {}


def register(strategy: Strategy) -> Strategy:
    """Register a strategy under its name (last registration wins)."""
    _REGISTRY[strategy.name] = strategy
    return strategy


def get_strategy(name: str) -> Strategy:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown strategy {name!r}; available: {sorted(_REGISTRY)}") from None


def available_strategies() -> list[str]:
    return sorted(_REGISTRY)
