"""ctypes bindings to the native runtime core (cpp/libdbx_core.so).

pybind11 is not in this image, so the boundary is a plain C ABI loaded with
ctypes (see ``cpp/dbx_core.h`` for the contract). The library is built on
first use if a toolchain is present (cmake+ninja, falling back to a direct
g++ invocation) and cached under ``cpp/build/``; every consumer must degrade
gracefully to the pure-Python path when :func:`load` returns None, so the
framework stays functional on machines without a compiler.
"""

from __future__ import annotations

import ctypes
import logging
import os
import shutil
import subprocess
import threading

import numpy as np

log = logging.getLogger("dbx.runtime")

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))
_CPP_DIR = os.path.join(_REPO_ROOT, "cpp")
_BUILD_DIR = os.path.join(_CPP_DIR, "build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libdbx_core.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


class _Ohlcv(ctypes.Structure):
    _fields_ = [
        ("n_bars", ctypes.c_uint32),
        ("open", ctypes.POINTER(ctypes.c_float)),
        ("high", ctypes.POINTER(ctypes.c_float)),
        ("low", ctypes.POINTER(ctypes.c_float)),
        ("close", ctypes.POINTER(ctypes.c_float)),
        ("volume", ctypes.POINTER(ctypes.c_float)),
    ]


def _build() -> bool:
    # Runs under load()'s module lock BY DESIGN: the lock exists to
    # serialize exactly this once-per-process compile — a second thread
    # racing load() must wait for (not duplicate) the build, and nothing
    # else ever contends on the lock. Hence the lock-blocking
    # suppressions below (the rule cannot know the lock is build-scoped).
    if not os.path.isdir(_CPP_DIR):
        return False
    try:
        if shutil.which("cmake") and shutil.which("ninja"):
            # dbxlint: disable=lock-blocking -- build-serialization lock
            subprocess.run(
                ["cmake", "-S", _CPP_DIR, "-B", _BUILD_DIR, "-G", "Ninja"],
                check=True, capture_output=True, timeout=120)
            # dbxlint: disable=lock-blocking -- build-serialization lock
            subprocess.run(["cmake", "--build", _BUILD_DIR],
                           check=True, capture_output=True, timeout=300)
            if os.path.exists(_LIB_PATH):
                # _stale() keys on the .so's mtime, but ninja relinks it
                # only when dbx_core sources changed — touching e.g.
                # worker_native.cc would otherwise leave the .so "stale"
                # forever and re-run cmake in every fresh process.
                os.utime(_LIB_PATH)
                return True
            return False
        if shutil.which("g++"):
            # dbxlint: disable=lock-blocking -- build-serialization lock
            os.makedirs(_BUILD_DIR, exist_ok=True)
            # dbxlint: disable=lock-blocking -- build-serialization lock
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                 os.path.join(_CPP_DIR, "dbx_core.cc"), "-o", _LIB_PATH],
                check=True, capture_output=True, timeout=300)
            return True
    except (subprocess.SubprocessError, OSError) as e:
        log.warning("native core build failed: %s", e)
    return False


_PRUNED_CB = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p)


class _JobqStats(ctypes.Structure):
    _fields_ = [
        ("pending", ctypes.c_int64),
        ("leased", ctypes.c_int64),
        ("completed", ctypes.c_int64),
        ("requeued", ctypes.c_int64),
        ("failed", ctypes.c_int64),
        ("combos_done", ctypes.c_double),
    ]


def _stale() -> bool:
    """True when the .so is missing or older than any cpp/ source file."""
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    # worker_native.cc / CMakeLists.txt / the shared .proto feed the other
    # cmake targets; building on any of them changing keeps the shell binary
    # and its generated proto code fresh too (one cmake --build covers all).
    srcs = [os.path.join(_CPP_DIR, n)
            for n in ("dbx_core.cc", "dbx_core.h", "worker_native.cc",
                      "CMakeLists.txt")]
    srcs.append(os.path.join(
        _REPO_ROOT, "distributed_backtesting_exploration_tpu", "rpc",
        "backtesting.proto"))
    for src in srcs:
        if os.path.exists(src) and os.path.getmtime(src) > lib_mtime:
            return True
    return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.dbx_csv_decode.restype = ctypes.c_int
    lib.dbx_csv_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(_Ohlcv),
        ctypes.c_char_p, ctypes.c_size_t]
    lib.dbx_wire_decode.restype = ctypes.c_int
    lib.dbx_wire_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(_Ohlcv),
        ctypes.c_char_p, ctypes.c_size_t]
    lib.dbx_ohlcv_to_wire.restype = ctypes.c_size_t
    lib.dbx_ohlcv_to_wire.argtypes = [
        ctypes.POINTER(_Ohlcv), ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    lib.dbx_ohlcv_free.argtypes = [ctypes.POINTER(_Ohlcv)]
    lib.dbx_bytes_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.dbx_queue_new.restype = ctypes.c_void_p
    lib.dbx_queue_new.argtypes = [ctypes.c_size_t]
    lib.dbx_queue_push.restype = ctypes.c_int
    lib.dbx_queue_push.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int64]
    lib.dbx_queue_push_front.restype = ctypes.c_int
    lib.dbx_queue_push_front.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int64]
    lib.dbx_queue_pop.restype = ctypes.c_int
    lib.dbx_queue_pop.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t), ctypes.c_int64]
    lib.dbx_queue_close.argtypes = [ctypes.c_void_p]
    lib.dbx_queue_size.restype = ctypes.c_size_t
    lib.dbx_queue_size.argtypes = [ctypes.c_void_p]
    lib.dbx_queue_free.argtypes = [ctypes.c_void_p]
    lib.dbx_jobq_new.restype = ctypes.c_void_p
    lib.dbx_jobq_new.argtypes = []
    lib.dbx_jobq_free.argtypes = [ctypes.c_void_p]
    lib.dbx_jobq_register.restype = ctypes.c_int
    lib.dbx_jobq_register.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_double]
    lib.dbx_jobq_push_pending.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dbx_jobq_mark_completed.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dbx_jobq_mark_failed.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dbx_jobq_take_begin.restype = ctypes.c_int
    lib.dbx_jobq_take_begin.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
    lib.dbx_jobq_take_commit.restype = ctypes.c_int
    lib.dbx_jobq_take_commit.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64]
    lib.dbx_jobq_fail.restype = ctypes.c_int
    lib.dbx_jobq_fail.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dbx_jobq_complete.restype = ctypes.c_int
    lib.dbx_jobq_complete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dbx_jobq_enqueue_n.restype = ctypes.c_int
    lib.dbx_jobq_enqueue_n.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char), ctypes.c_int,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int]
    lib.dbx_jobq_take_begin_idx_n.restype = ctypes.c_int
    lib.dbx_jobq_take_begin_idx_n.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
    lib.dbx_jobq_take_commit_idx_n.restype = ctypes.c_int
    lib.dbx_jobq_take_commit_idx_n.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8)]
    lib.dbx_jobq_complete_idx_n.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8)]
    lib.dbx_jobq_requeue_expired.restype = ctypes.c_int
    lib.dbx_jobq_requeue_expired.argtypes = [
        ctypes.c_void_p, _PRUNED_CB, ctypes.c_void_p]
    lib.dbx_jobq_requeue_worker.restype = ctypes.c_int
    lib.dbx_jobq_requeue_worker.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, _PRUNED_CB, ctypes.c_void_p]
    lib.dbx_jobq_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(_JobqStats)]
    lib.dbx_jobq_drained.restype = ctypes.c_int
    lib.dbx_jobq_drained.argtypes = [ctypes.c_void_p]
    lib.dbx_registry_new.restype = ctypes.c_void_p
    lib.dbx_registry_new.argtypes = [ctypes.c_int64]
    lib.dbx_registry_touch.restype = ctypes.c_int
    lib.dbx_registry_touch.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dbx_registry_prune.restype = ctypes.c_int
    lib.dbx_registry_prune.argtypes = [
        ctypes.c_void_p, _PRUNED_CB, ctypes.c_void_p]
    lib.dbx_registry_alive.restype = ctypes.c_int
    lib.dbx_registry_alive.argtypes = [ctypes.c_void_p]
    lib.dbx_registry_free.argtypes = [ctypes.c_void_p]
    return lib


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native core; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("DBX_NO_NATIVE") == "1":
            return None
        if _stale() and not _build():
            log.info("native core unavailable; using pure-Python paths")
            return None
        try:
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except AttributeError as e:
            # A prebuilt/copied .so whose mtime passes _stale() but predates
            # the current C ABI (missing symbol). Rebuild once from source,
            # then degrade gracefully like any other load failure.
            log.warning("stale ABI in %s (%s); rebuilding", _LIB_PATH, e)
            try:
                os.remove(_LIB_PATH)
            except OSError:
                pass
            _lib = None
            if _build():
                try:
                    _lib = _bind(ctypes.CDLL(_LIB_PATH))
                except (OSError, AttributeError) as e2:
                    log.warning("rebuild of %s did not load: %s",
                                _LIB_PATH, e2)
                    _lib = None
        except OSError as e:
            log.warning("failed to load %s: %s", _LIB_PATH, e)
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


def _take_fields(lib, o: _Ohlcv) -> tuple[np.ndarray, ...]:
    n = int(o.n_bars)
    out = tuple(
        np.ctypeslib.as_array(getattr(o, f), shape=(n,)).copy()
        for f in ("open", "high", "low", "close", "volume"))
    lib.dbx_ohlcv_free(ctypes.byref(o))
    return out


def csv_decode(data: bytes) -> tuple[np.ndarray, ...]:
    """Native CSV -> five float32 ``(T,)`` arrays. Raises ValueError."""
    lib = load()
    if lib is None:
        raise RuntimeError("native core not available")
    o = _Ohlcv()
    err = ctypes.create_string_buffer(256)
    rc = lib.dbx_csv_decode(data, len(data), ctypes.byref(o), err, len(err))
    if rc != 0:
        raise ValueError(err.value.decode() or "native CSV decode failed")
    return _take_fields(lib, o)


def wire_decode(data: bytes) -> tuple[np.ndarray, ...]:
    """Native DBX1 -> five float32 ``(T,)`` arrays. Raises ValueError."""
    lib = load()
    if lib is None:
        raise RuntimeError("native core not available")
    o = _Ohlcv()
    err = ctypes.create_string_buffer(256)
    rc = lib.dbx_wire_decode(data, len(data), ctypes.byref(o), err, len(err))
    if rc != 0:
        raise ValueError(err.value.decode() or "native wire decode failed")
    return _take_fields(lib, o)


class NativeQueue:
    """Bounded MPMC byte-blob queue backed by the C++ core.

    Mirrors the semantics of the worker's channel substrate; used by tests to
    validate the native queue and available as a drop-in for byte payloads.
    """

    def __init__(self, capacity: int = 1024):
        lib = load()
        if lib is None:
            raise RuntimeError("native core not available")
        self._lib = lib
        self._h = lib.dbx_queue_new(capacity)

    def push(self, data: bytes, timeout_ms: int = -1) -> bool:
        rc = self._lib.dbx_queue_push(self._h, data, len(data), timeout_ms)
        if rc == 2:
            raise ValueError("queue closed")
        return rc == 0

    def push_front(self, data: bytes, timeout_ms: int = -1) -> bool:
        """LIFO insert: the next pop returns ``data`` (requeue-at-front)."""
        rc = self._lib.dbx_queue_push_front(
            self._h, data, len(data), timeout_ms)
        if rc == 2:
            raise ValueError("queue closed")
        return rc == 0

    def pop(self, timeout_ms: int = -1) -> bytes | None:
        """None on timeout; raises ValueError once closed and drained."""
        buf = ctypes.POINTER(ctypes.c_uint8)()
        ln = ctypes.c_size_t()
        rc = self._lib.dbx_queue_pop(
            self._h, ctypes.byref(buf), ctypes.byref(ln), timeout_ms)
        if rc == 1:
            return None
        if rc == 2:
            raise ValueError("queue closed")
        out = ctypes.string_at(buf, ln.value)
        self._lib.dbx_bytes_free(buf)
        return out

    def close(self) -> None:
        self._lib.dbx_queue_close(self._h)

    def __len__(self) -> int:
        return self._lib.dbx_queue_size(self._h)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            # Close first so threads blocked in pop/push wake and return
            # before the underlying mutex/condvars are deleted. Callers are
            # responsible for joining consumers before dropping the queue.
            self._lib.dbx_queue_close(h)
            self._lib.dbx_queue_free(h)
            self._h = None


class NativeJobQueue:
    """The dispatcher's lease/tombstone/completion state machine, native.

    Owns the id-state hot path (pending FIFO, tombstone skip, lease table,
    completion idempotency, expiry/prune requeue) behind the C ABI in
    ``cpp/dbx_core.h``; callers keep the full job records (grids, payload
    paths) in Python keyed by the same ids. Method contracts mirror
    ``rpc/dispatcher.py``'s pure-Python fallback exactly — the parity tests
    in ``tests/test_rpc_unit.py`` run both substrates through the same
    scenarios. (The reference's whole dispatcher state is native, reference
    ``src/server/main.rs:20-190``; a C++ gRPC *server* is infeasible in this
    environment, so serving stays in Python and the state machine is the
    part that goes native.)
    """

    _ID_BUF = 512   # DBX_JOBQ_MAX_ID + NUL

    # Model-checker seam (analysis/modelcheck): when set, called as
    # ``step_hook(method, n)`` before each batched boundary crossing —
    # the native twin of the python substrate's per-op visibility, used
    # for transition counting/parity (the C state itself stays opaque).
    step_hook = None

    def __init__(self):
        lib = load()
        if lib is None:
            raise RuntimeError("native core not available")
        self._lib = lib
        self._h = lib.dbx_jobq_new()
        # id<->index mirror of the core's intern table: every method that
        # can intern an id C-side interns it here in the SAME call order,
        # so the dense indices agree without ever crossing the boundary.
        self._ids: list[str] = []
        self._idx: dict[str, int] = {}

    def register(self, jid: str, combos: float) -> None:
        if self._lib.dbx_jobq_register(self._h, jid.encode(),
                                       float(combos)) != 0:
            raise ValueError(f"job id exceeds {self._ID_BUF - 1} bytes")
        self._intern(jid)

    def push_pending(self, jid: str) -> None:
        self._lib.dbx_jobq_push_pending(self._h, jid.encode())
        self._intern(jid)

    def mark_completed(self, jid: str) -> None:
        self._lib.dbx_jobq_mark_completed(self._h, jid.encode())
        self._intern(jid)

    def mark_failed(self, jid: str) -> None:
        self._lib.dbx_jobq_mark_failed(self._h, jid.encode())
        self._intern(jid)

    def take_begin(self) -> str | None:
        buf = ctypes.create_string_buffer(self._ID_BUF)
        rc = self._lib.dbx_jobq_take_begin(self._h, buf, len(buf))
        if rc == 0:
            return None
        if rc < 0:   # unreachable with this buffer; ids cap at register
            raise RuntimeError("take_begin buffer smaller than next job id")
        return buf.value.decode()

    def take_commit(self, jid: str, worker_id: str, lease_s: float) -> bool:
        """False when the job completed in the take window (not leased)."""
        rc = self._lib.dbx_jobq_take_commit(
            self._h, jid.encode(), worker_id.encode(),
            int(lease_s * 1000)) == 0
        self._intern(jid)
        return rc

    def fail(self, jid: str) -> bool:
        """False when the job completed in the take window (not failed)."""
        rc = self._lib.dbx_jobq_fail(self._h, jid.encode()) == 0
        self._intern(jid)
        return rc

    def complete(self, jid: str) -> str:
        rc = self._lib.dbx_jobq_complete(self._h, jid.encode())
        return ("new", "dup", "unknown")[rc]

    # -- batched transitions: ONE ctypes crossing per RPC-sized batch,
    # moving int32 HANDLES instead of strings (per-id string marshalling
    # made the string-keyed batch surface slower than the dict fallback).
    # The id<->index mirror lives here: the C core assigns dense indices
    # in first-registration order, and this class performs registrations
    # in the same order it appends to ``_ids``, so the index never has to
    # cross the boundary at registration time.

    def _intern(self, jid: str) -> int:
        idx = self._idx.get(jid)
        if idx is None:
            idx = self._idx[jid] = len(self._ids)
            self._ids.append(jid)
        return idx

    # Reusable per-instance scratch (every call arrives under
    # JobQueue._lock, so one set of buffers is safe): ctypes array
    # construction per call was a measurable share of the per-batch glue.
    _SCRATCH = 4096

    def _idx_buf(self, n: int, vals=None) -> "ctypes.Array":
        buf = self.__dict__.get("_idxs")
        if buf is None or len(buf) < n:
            buf = self._idxs = (ctypes.c_int32 * max(n, self._SCRATCH))()
        if vals is not None:
            buf[:n] = vals
        return buf

    def _u8_buf(self, n: int) -> "ctypes.Array":
        buf = self.__dict__.get("_u8s")
        if buf is None or len(buf) < n:
            buf = self._u8s = (ctypes.c_uint8 * max(n, self._SCRATCH))()
        return buf

    def enqueue_n(self, jids: list[str], combos: list[float]) -> None:
        """Register + push a batch in one crossing (the one call where
        the id strings DO cross — once per job lifetime). Ids pack
        NUL-separated (stride 0: the core walks strlen) — join beats any
        per-id buffer arithmetic."""
        if not jids:
            return
        if self.step_hook is not None:
            self.step_hook("enqueue_n", len(jids))
        import array as array_mod

        raws = [j.encode() for j in jids]
        if max(map(len, raws)) >= self._ID_BUF:
            raise ValueError(f"job id exceeds {self._ID_BUF - 1} bytes")
        if any(b"\0" in r for r in raws):
            # An embedded NUL would split the pack: the C side would
            # intern a truncated id while the mirror interns the full
            # one, desynchronizing every later index.
            raise ValueError("job ids must not contain NUL bytes")
        blob = b"\0".join(raws) + b"\0"
        arr = array_mod.array("d", combos)
        addr, _ = arr.buffer_info()
        # Mirror BEFORE the native call: the C side interns accepted ids
        # as a side effect, so raising between the call and the mirror
        # update would leave the id<->index translation permanently
        # desynced (every later take would return wrong ids). With the
        # mirror written first, the only divergent path is a C-side
        # reject — impossible while both sides enforce the same cap
        # (pre-validated above) — and that path raises below with the
        # substrate declared unusable rather than silently corrupt.
        idx, ids = self._idx, self._ids
        for jid in jids:            # inlined _intern: the per-id hot loop
            if jid not in idx:
                idx[jid] = len(ids)
                ids.append(jid)
        accepted = self._lib.dbx_jobq_enqueue_n(
            self._h, blob, 0,
            ctypes.cast(addr, ctypes.POINTER(ctypes.c_double)), len(jids))
        if accepted != len(jids):   # cap enforced above
            raise RuntimeError(
                "native enqueue_n rejected ids post-cap; the C intern "
                "table and the Python id mirror may now disagree — this "
                "queue instance must not be reused")

    def take_begin_n(self, n: int) -> list[str]:
        """Pop up to ``n`` live pending ids in one crossing."""
        if n <= 0:
            return []
        if self.step_hook is not None:
            self.step_hook("take_begin_n", int(n))
        out = self._idx_buf(min(int(n), 1 << 20))
        got = self._lib.dbx_jobq_take_begin_idx_n(
            self._h, out, min(int(n), len(out)))
        ids = self._ids
        return [ids[i] for i in out[:got]]

    def take_commit_n(self, jids: list[str], worker_id: str,
                      lease_s: float) -> list[bool]:
        """Lease a popped batch in one crossing; False entries completed
        in the take window (dropped, not leased)."""
        if not jids:
            return []
        if self.step_hook is not None:
            self.step_hook("take_commit_n", len(jids))
        idxs = self._idx_buf(len(jids), [self._idx[j] for j in jids])
        flags = self._u8_buf(len(jids))
        self._lib.dbx_jobq_take_commit_idx_n(
            self._h, idxs, len(jids), worker_id.encode(),
            int(lease_s * 1000), flags)
        return [bool(f) for f in flags[:len(jids)]]

    def complete_n(self, jids: list[str]) -> list[str]:
        """Record a completion batch in one crossing. Ids the queue has
        never seen (possible from a stray RPC) map to index -1, which the
        core reports "unknown"."""
        if not jids:
            return []
        if self.step_hook is not None:
            self.step_hook("complete_n", len(jids))
        get = self._idx.get
        idxs = self._idx_buf(len(jids), [get(j, -1) for j in jids])
        outcomes = self._u8_buf(len(jids))
        self._lib.dbx_jobq_complete_idx_n(
            self._h, idxs, len(jids), outcomes)
        kinds = ("new", "dup", "unknown")
        return [kinds[o] for o in outcomes[:len(jids)]]

    def _requeue(self, call, *args) -> list[str]:
        hit: list[str] = []

        @_PRUNED_CB
        def collect(jid, _ctx):
            hit.append(jid.decode())

        call(self._h, *args, collect, None)
        return hit

    def requeue_expired(self) -> list[str]:
        if self.step_hook is not None:
            self.step_hook("requeue_expired", 0)
        return self._requeue(self._lib.dbx_jobq_requeue_expired)

    def requeue_worker(self, worker_id: str) -> list[str]:
        if self.step_hook is not None:
            self.step_hook("requeue_worker", 0)
        return self._requeue(self._lib.dbx_jobq_requeue_worker,
                             worker_id.encode())

    def stats(self) -> dict:
        s = _JobqStats()
        self._lib.dbx_jobq_stats(self._h, ctypes.byref(s))
        return {"pending": int(s.pending), "leased": int(s.leased),
                "completed": int(s.completed), "requeued": int(s.requeued),
                "failed": int(s.failed), "combos_done": float(s.combos_done)}

    def drained(self) -> bool:
        return self._lib.dbx_jobq_drained(self._h) == 1

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.dbx_jobq_free(h)
            self._h = None


class NativeRegistry:
    """Peer liveness map backed by the C++ core (last-seen + windowed prune).

    Owns only the *timing* state; callers keep any per-peer metadata
    (status, capacity) in their own map keyed by the same ids.
    """

    def __init__(self, prune_window_s: float):
        lib = load()
        if lib is None:
            raise RuntimeError("native core not available")
        self._lib = lib
        self._h = lib.dbx_registry_new(int(prune_window_s * 1000))

    def touch(self, peer_id: str) -> bool:
        """Stamp alive-now; True if newly registered."""
        return self._lib.dbx_registry_touch(self._h, peer_id.encode()) == 1

    def prune(self) -> list[str]:
        """Drop peers silent past the window; return their ids."""
        dead: list[str] = []

        @_PRUNED_CB
        def collect(peer_id, _ctx):
            dead.append(peer_id.decode())

        self._lib.dbx_registry_prune(self._h, collect, None)
        return dead

    def alive(self) -> int:
        return self._lib.dbx_registry_alive(self._h)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.dbx_registry_free(h)
            self._h = None
