"""Native runtime layer: ctypes bindings to the C++ core (cpp/).

The compute path is JAX/XLA; the runtime substrate around it — OHLCV
decoding, bounded inter-thread queues, peer liveness — has a native C++
implementation mirroring the reference's all-native runtime (SURVEY.md
§2.2), loaded here via ctypes with transparent fallback to pure Python when
no toolchain is available.
"""

from ._core import available, csv_decode, wire_decode, NativeQueue, load  # noqa: F401
