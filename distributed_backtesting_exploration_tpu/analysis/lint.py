"""dbxlint CLI: ``python -m distributed_backtesting_exploration_tpu.analysis.lint``.

Runs the registered rule set over the package (default) or over explicit
paths, prints findings as text or JSON, and exits non-zero when any
finding survives suppression — the tier-1 ``tests/test_lint_clean.py``
gate and the ``dbxlint`` console script both drive this entry point.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import core

_PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="dbxlint",
        description="static analysis for the dbx codebase "
                    "(AST + jaxpr + proto layers)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: the "
                         "installed distributed_backtesting_exploration_tpu "
                         "package)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="output format (json is the CI-artifact form)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule names to run "
                         "(default: all; see --list-rules)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--certify", action="store_true",
                    help="run the numerics certifier only (analysis."
                         "certify): exit 0 clean / 1 findings / 2 "
                         "contract drift — the `dbxcert` console script "
                         "is this mode")
    ap.add_argument("--update-contract", action="store_true",
                    help="with --certify: regenerate and write "
                         "numerics.contract.json from the live trace")
    return ap


def _select_rules(spec: str | None):
    rules = core.all_rules()
    if spec is None:
        return rules
    wanted = {r.strip() for r in spec.split(",") if r.strip()}
    known = {r.name for r in rules}
    unknown = wanted - known
    if unknown:
        raise SystemExit(
            f"unknown rule(s): {', '.join(sorted(unknown))}; "
            f"known: {', '.join(sorted(known))}")
    return [r for r in rules if r.name in wanted]


def run(paths, rules) -> dict:
    """Lint ``paths`` with ``rules``; returns the JSON-able result dict.

    ``rules`` lists only rules that actually RAN on at least one path;
    ``rules_skipped`` names the rest (e.g. kernel-hygiene outside the
    package) — a skipped rule must never read as clean coverage."""
    all_findings: list[core.Finding] = []
    suppressed = 0
    skipped: list = []
    ran: set = set()
    for path in paths:
        findings, n_sup, ctx = core.lint_path(path, rules)
        all_findings.extend(findings)
        suppressed += n_sup
        skipped.extend(ctx.skipped)
        ran |= set(ctx.rules_ran)
    return {
        "clean": not all_findings and not skipped,
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.line,
             "message": f.message}
            for f in all_findings],
        "suppressed": suppressed,
        "unparseable": [{"path": p, "error": e} for p, e in skipped],
        "rules": [r.name for r in rules if r.name in ran],
        "rules_skipped": [r.name for r in rules if r.name not in ran],
    }


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    if args.certify:
        from . import certify

        # The certifier traces the installed package's registries; path
        # and rule selectors don't apply — reject them loudly rather
        # than silently running the full certifier anyway.
        if args.paths or args.rules or args.list_rules:
            raise SystemExit(
                "dbxlint --certify runs the whole certified registry of "
                "the installed package: positional paths, --rules and "
                "--list-rules do not apply (use plain dbxlint for "
                "scoped lint runs)")
        result = certify.run_certify(update=args.update_contract)
        if args.format == "json":
            print(json.dumps(result, indent=2))
        else:
            certify.render_text(result, prog="dbxlint --certify")
        return certify.exit_code(result)
    rules = _select_rules(args.rules)
    if args.list_rules:
        for r in rules:
            print(f"{r.name:20s} {r.doc}")
        return 0
    paths = args.paths or [_PACKAGE_DIR]
    result = run(paths, rules)
    if args.format == "json":
        print(json.dumps(result, indent=2))
    else:
        for f in result["findings"]:
            print(f"{f['path']}:{f['line']}: [{f['rule']}] {f['message']}")
        for s in result["unparseable"]:
            print(f"{s['path']}:1: [engine] unparseable: {s['error']}")
        n = len(result["findings"])
        tail = (f"{n} finding(s)" if n else "clean")
        if result["suppressed"]:
            tail += f" ({result['suppressed']} suppressed)"
        line = f"dbxlint: {tail} [rules: {', '.join(result['rules'])}]"
        if result["rules_skipped"]:
            line += (f" [skipped (not applicable here): "
                     f"{', '.join(result['rules_skipped'])}]")
        print(line)
    return 0 if result["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
