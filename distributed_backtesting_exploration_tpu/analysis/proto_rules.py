"""dbxlint wire-layer rule: ``.proto`` source vs generated ``_pb2`` drift.

This repo regenerates ``backtesting_pb2.py`` WITHOUT protoc (the image has
no grpc_tools) by editing the serialized FileDescriptorProto by hand —
PR 1 did exactly that to add ``StatsReply.obs_json``. Nothing but review
kept the two in sync; a drifted pb2 silently reads/writes the wrong field
numbers on the wire. This rule parses the ``.proto`` text with a small
tokenizer and structurally compares messages (field name -> number),
enums, and service methods against the imported pb2 module's descriptor.
"""

from __future__ import annotations

import dataclasses
import importlib
import os
import re

from .core import Finding, LintContext, PACKAGE_NAME


# ---------------------------------------------------------------------------
# Proto text parsing (proto3 subset: messages w/ scalar+map fields, nested
# messages, enums, services — exactly what this repo's contract uses)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ProtoModel:
    """Structural view of one .proto file (or one pb2 descriptor)."""

    messages: dict       # name -> {field_name: number}
    enums: dict          # name -> {value_name: number}
    services: dict       # name -> {method: (input_type, output_type)}
    lines: dict = dataclasses.field(default_factory=dict)
    # lines: (kind, container, item) -> 1-indexed source line (text side
    # only; used to anchor findings).


# Content patterns are unanchored and finditer'd so one-line blocks
# (`message Ping { int32 n = 1; }`) and several `;`-separated declarations
# on one line all parse.
_FIELD_RE = re.compile(
    r"(?:\b(?:optional|repeated|required)\s+)?"
    r"(?:map\s*<[^>]+>|[A-Za-z0-9_.]+)\s+"
    r"([A-Za-z0-9_]+)\s*=\s*(\d+)\s*(?:\[[^\]]*\])?\s*;")
_ENUM_VALUE_RE = re.compile(r"([A-Za-z0-9_]+)\s*=\s*(\d+)\s*;")
# The optional `stream` keywords are CAPTURED, not skipped: a pb2 whose
# method drops (or invents) server streaming is a wire-breaking drift —
# the client would issue a unary call against a streaming handler. The
# model encodes streaming-ness as a "stream " prefix on the type name,
# so unary signatures stay plain (input, output) tuples.
_RPC_RE = re.compile(
    r"\brpc\s+([A-Za-z0-9_]+)\s*\(\s*(stream\s+)?([A-Za-z0-9_.]+)\s*\)\s*"
    r"returns\s*\(\s*(stream\s+)?([A-Za-z0-9_.]+)\s*\)")
_BLOCK_RE = re.compile(r"^\s*(message|enum|service)\s+([A-Za-z0-9_]+)\s*\{")


def _strip_comments(text: str) -> str:
    """Remove // and /* */ comments, preserving line structure."""
    text = re.sub(r"/\*.*?\*/",
                  lambda m: "\n" * m.group(0).count("\n"),
                  text, flags=re.S)
    return "\n".join(line.split("//")[0] for line in text.splitlines())


def parse_proto_text(text: str) -> ProtoModel:
    """Parse a proto3 file into a :class:`ProtoModel` (line-numbered).

    Unrecognized braced blocks (``oneof``, ``extensions``, option
    aggregates) push anonymous frames so their closing brace pops only
    themselves — fields inside a ``oneof`` attribute to the enclosing
    message, exactly like the descriptor flattens them, and fields AFTER
    the block stay attributed correctly."""
    model = ProtoModel({}, {}, {})
    stack: list[tuple[str, str | None]] = []   # (kind, name) of open blocks

    def adjust(segment: str) -> None:
        for _ in range(segment.count("{")):
            stack.append(("anon", None))
        for _ in range(min(segment.count("}"), len(stack))):
            stack.pop()

    def consume(content: str, lineno: int) -> None:
        """Match field/enum/rpc declarations in ``content``, attributed to
        the innermost NAMED frame (a oneof's fields belong to its
        enclosing message in the descriptor)."""
        kind, name = next(
            ((k, n) for k, n in reversed(stack) if k != "anon"),
            (None, None))
        if kind == "service":
            for m in _RPC_RE.finditer(content):
                meth, in_stream, inp, out_stream, outp = m.groups()
                model.services[name][meth] = (
                    ("stream " if in_stream else "") + inp.split(".")[-1],
                    ("stream " if out_stream else "") + outp.split(".")[-1])
                model.lines[("rpc", name, meth)] = lineno
        elif kind == "enum":
            for m in _ENUM_VALUE_RE.finditer(content):
                model.enums[name][m.group(1)] = int(m.group(2))
                model.lines[("enumval", name, m.group(1))] = lineno
        elif kind == "message":
            for m in _FIELD_RE.finditer(content):
                model.messages[name][m.group(1)] = int(m.group(2))
                model.lines[("field", name, m.group(1))] = lineno

    for lineno, line in enumerate(_strip_comments(text).splitlines(), 1):
        m = _BLOCK_RE.match(line)
        if m:
            kind, name = m.group(1), m.group(2)
            if kind == "message":
                # Nested messages key by their simple name — the pb2
                # descriptor side is flattened the same way.
                model.messages.setdefault(name, {})
            elif kind == "enum":
                model.enums.setdefault(name, {})
            elif kind == "service":
                model.services.setdefault(name, {})
            model.lines[(kind, name, None)] = lineno
            stack.append((kind, name))
            tail = line.split("{", 1)[1]
            consume(tail, lineno)       # one-liner blocks keep their fields
            adjust(tail)
            continue
        if stack:
            consume(line, lineno)
        adjust(line)
    return model


def describe_pb2(pb2_module) -> ProtoModel:
    """ProtoModel of a generated pb2 module's file descriptor."""
    fd = pb2_module.DESCRIPTOR
    messages: dict = {}

    def add_message(desc):
        messages[desc.name] = {f.name: f.number for f in desc.fields}
        for nested in desc.nested_types:
            if nested.GetOptions().map_entry:
                continue   # synthesized map-entry types have no proto text
            add_message(nested)

    for desc in fd.message_types_by_name.values():
        add_message(desc)
    enums = {e.name: {v.name: v.number for v in e.values}
             for e in fd.enum_types_by_name.values()}
    # Streaming flags live on the serialized FileDescriptorProto, not the
    # runtime MethodDescriptor surface (portable across protobuf
    # generations) — re-parse it for the same "stream " prefix encoding
    # the text side uses.
    from google.protobuf import descriptor_pb2

    fdp = descriptor_pb2.FileDescriptorProto.FromString(fd.serialized_pb)
    streaming = {
        (s.name, m.name): (m.client_streaming, m.server_streaming)
        for s in fdp.service for m in s.method}
    services = {}
    for s in fd.services_by_name.values():
        sigs = {}
        for m in s.methods:
            c_stream, s_stream = streaming.get((s.name, m.name),
                                               (False, False))
            sigs[m.name] = (
                ("stream " if c_stream else "") + m.input_type.name,
                ("stream " if s_stream else "") + m.output_type.name)
        services[s.name] = sigs
    return ProtoModel(messages, enums, services)


def diff_models(proto: ProtoModel, pb2: ProtoModel, *, path: str,
                rule: str = "proto-drift") -> list[Finding]:
    """Structural diff, proto text as the source of truth."""
    out: list[Finding] = []

    def line(kind, container, item=None) -> int:
        return proto.lines.get((kind, container, item),
                               proto.lines.get((kind, container, None), 1))

    for name, fields in proto.messages.items():
        got = pb2.messages.get(name)
        if got is None:
            out.append(Finding(rule, path, line("message", name),
                               f"message `{name}` missing from the "
                               "generated pb2 descriptor"))
            continue
        for fname, num in fields.items():
            if fname not in got:
                out.append(Finding(
                    rule, path, line("field", name, fname),
                    f"field `{name}.{fname}` missing from the pb2 "
                    "descriptor"))
            elif got[fname] != num:
                out.append(Finding(
                    rule, path, line("field", name, fname),
                    f"field `{name}.{fname}` is number {num} in the "
                    f".proto but {got[fname]} in the pb2 descriptor — "
                    "wire-incompatible drift"))
        for fname in sorted(set(got) - set(fields)):
            out.append(Finding(
                rule, path, line("message", name),
                f"pb2 descriptor has field `{name}.{fname}` "
                f"(number {got[fname]}) that the .proto does not declare"))
    for name in sorted(set(pb2.messages) - set(proto.messages)):
        out.append(Finding(rule, path, 1,
                           f"pb2 descriptor has message `{name}` that the "
                           ".proto does not declare"))

    for name, values in proto.enums.items():
        got = pb2.enums.get(name)
        if got is None:
            out.append(Finding(rule, path, line("enum", name),
                               f"enum `{name}` missing from the pb2 "
                               "descriptor"))
            continue
        if got != values:
            out.append(Finding(
                rule, path, line("enum", name),
                f"enum `{name}` values differ: .proto {values} vs "
                f"pb2 {got}"))

    for name, methods in proto.services.items():
        got = pb2.services.get(name, None)
        if got is None:
            # Message-only codegen (this repo's case: the service layer is
            # hand-written in service.py) — nothing to compare.
            continue
        for meth, sig in methods.items():
            if meth not in got:
                out.append(Finding(
                    rule, path, line("rpc", name, meth),
                    f"rpc `{name}.{meth}` missing from the pb2 "
                    "descriptor"))
            elif got[meth] != sig:
                out.append(Finding(
                    rule, path, line("rpc", name, meth),
                    f"rpc `{name}.{meth}` signature differs: .proto "
                    f"{sig} vs pb2 {got[meth]}"))
        for meth in sorted(set(got) - set(methods)):
            out.append(Finding(
                rule, path, line("service", name),
                f"pb2 descriptor has rpc `{name}.{meth}` that the "
                ".proto does not declare"))
    return out


class ProtoDriftRule:
    """Compare every ``.proto`` under the root against its ``_pb2`` module."""

    name = "proto-drift"
    doc = ".proto source vs generated _pb2 descriptor divergence"

    def applicable(self, ctx: LintContext) -> bool:
        # Single-file lint targets have no proto scan: report the rule as
        # skipped, never as clean coverage.
        return os.path.isdir(ctx.root)

    def check(self, ctx: LintContext) -> list[Finding]:
        out: list[Finding] = []
        if not self.applicable(ctx):
            return out
        base = ctx.root
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fname in sorted(filenames):
                if not fname.endswith(".proto"):
                    continue
                proto_path = os.path.join(dirpath, fname)
                stem = fname[:-len(".proto")]
                pb2_path = os.path.join(dirpath, f"{stem}_pb2.py")
                rel = os.path.relpath(proto_path, base)
                if not os.path.exists(pb2_path):
                    out.append(Finding(
                        self.name, rel, 1,
                        f"`{fname}` has no sibling `{stem}_pb2.py` — the "
                        "wire contract is declared but not generated"))
                    continue
                pb2_module = self._import_pb2(ctx, pb2_path, base)
                if pb2_module is None:
                    out.append(Finding(
                        self.name, rel, 1,
                        f"could not import `{stem}_pb2.py` for structural "
                        "comparison"))
                    continue
                with open(proto_path, encoding="utf-8") as fh:
                    model = parse_proto_text(fh.read())
                out.extend(diff_models(model, describe_pb2(pb2_module),
                                       path=rel, rule=self.name))
        return out

    @staticmethod
    def _import_pb2(ctx: LintContext, pb2_path: str, base: str):
        """Import the pb2 via its dotted package name (a second standalone
        load would re-register descriptors in the default pool and fail)."""
        rel = os.path.relpath(pb2_path, base)
        parts = rel[:-len(".py")].split(os.sep)
        if ctx.package:
            dotted = ".".join([PACKAGE_NAME] + parts)
        else:
            dotted = ".".join(parts)
        try:
            return importlib.import_module(dotted)
        except Exception:
            return None
