"""dbxlint: static analysis for the dbx codebase (AST + jaxpr layers).

Round-5 review found two real bugs of ONE class — ``os.environ`` read at
trace time inside a jit-compiled kernel, invisible to the jit cache key —
and the fix was manual. Compiler-first systems (TVM, arxiv 1802.04799;
the Julia->TPU full-compilation work, arxiv 1810.09868) get reliability
from mechanical invariant checks over their IR rather than from review
vigilance. This package gives the repo the same treatment across two
layers it already has IRs for:

- **AST layer** (:mod:`.ast_rules`): *trace-time-env* (env reads reachable
  from jit/pallas-traced code), *import-time-config* (module-level
  env/IO capture), *blocking-call* (sleeps/subprocesses/device syncs
  inside gRPC servicer handlers and the worker control loop),
  *obs-cardinality* (metric labels fed from unbounded runtime data).
- **concurrency layer** (:mod:`.locks`): one whole-package lock model —
  cross-module call graph + interprocedural held-lock sets + the global
  lock-acquisition-order graph — behind *lock-discipline* (guarded-field
  mutations on lock-free paths, helper mutations proven clean when every
  caller holds the lock), *lock-order* (acquisition-order cycles and
  non-reentrant re-acquisition), *atomicity* (check-then-act on guarded
  fields across lock release) and *lock-blocking* (blocking/device-sync
  calls while holding a lock). Its runtime twin, :mod:`.lockdep`, is an
  opt-in (``DBX_LOCKDEP=1``) instrumented-lock shim recording ACTUAL
  acquisition edges, cycles and blocking-under-lock at runtime onto the
  obs surface.
- **jaxpr/IR layer** (:mod:`.dataflow` + :mod:`.jaxpr_rules` +
  :mod:`.certify`): one abstract-interpretation traversal over traced
  programs backs *kernel-hygiene* (host callbacks, float64 leaks,
  weak-type promotions — now with introducing equation chains) and
  **dbxcert**, the numerics certifier: per-output provenance classes
  (exact / selection / int-exact / float-accum / nondet) and an
  association-boundary census for every streaming family x epilogue
  substrate x scan/recurrent form plus the digest cones, pinned as the
  committed ``numerics.contract.json`` and enforced by
  *substrate-contract*, *weak-type-provenance* and *digest-determinism*
  (``dbxcert`` CLI / ``dbxlint --certify``: exit 0/1/2 =
  clean/findings/drift).
- **wire layer** (:mod:`.proto_rules`): *proto-drift* — structural
  comparison of ``.proto`` source against the generated ``_pb2``
  serialized descriptor (this repo regenerates pb2 without protoc, so
  drift is a real failure mode).

CLI::

    python -m distributed_backtesting_exploration_tpu.analysis.lint \
        [paths ...] [--format text|json] [--rules a,b] [--list-rules]

Inline suppression (same line or the comment line directly above), only
with a justifying comment::

    x = os.environ.get("DBX_X")  # dbxlint: disable=trace-time-env -- <why>

See DESIGN.md "Static analysis" for the rule catalogue and how to add a
rule.
"""

from .core import Finding, LintContext, all_rules, lint_path  # noqa: F401
