"""dbxmc schedule layer: ops, interleavings, DPOR-lite pruning, and the
lock-boundary controlled scheduler.

The model checker (:mod:`.modelcheck`) runs the REAL dispatcher code, so
"a schedule" here is not an abstract trace — it is a concrete order in
which per-thread op programs (enqueue / take / complete / requeue /
append) are executed against a live :class:`rpc.dispatcher.JobQueue`.
This module owns the combinatorics:

- the op vocabulary (:class:`Op`) with a declared *footprint* per op —
  the static job-id set it may touch plus whether it reorders the
  shared pending pool. The footprint is deliberately over-approximate
  (ops with dynamic id sets, like ``take``, get the wildcard): an
  over-declared conflict only costs pruning, an under-declared one
  would merge genuinely different schedules;
- interleaving generation (:func:`generate_schedules`): seeded random
  topological merges of the per-thread programs, deduplicated through a
  Foata-style canonical form (:func:`canonical_key`) — adjacent
  independent ops are bubbled into a fixed thread order until fixpoint,
  so two interleavings that differ only by commuting independent ops
  count as ONE explored schedule. This is the DPOR idea run in
  normalize-and-dedupe form: cheaper than persistent-set bookkeeping,
  and sound for *counting* and for not re-executing equivalent
  schedules (:func:`enumerate_schedules` is the exhaustive DFS twin for
  small programs);
- the controlled scheduler (:class:`ControlledScheduler`) for
  ``--depth > 0``: ops run on real threads serialized by a token, and
  the lockdep instrumentation seam (``lockdep.set_schedule_hook``)
  turns every instrumented-lock acquire into a potential preemption
  point — bounded by ``depth`` preemptions per schedule, CHESS-style.
  Lock ownership is tracked from the hook events so the scheduler
  never parks a lock holder while running a thread that needs that
  lock; every wait is bounded, so a real deadlock reports ``wedged``
  instead of hanging the suite.
"""

from __future__ import annotations

import dataclasses
import threading

from . import lockdep

# Canonical thread order for Foata normalization (also the order the
# program builder assigns roles). Stable across runs by construction.
THREADS = ("client", "workerA", "workerB", "maint")

# Footprint wildcard: the op's id set is dynamic (depends on queue state
# at execution time) — conflicts with every non-observer op.
WILD = "*"


@dataclasses.dataclass(frozen=True)
class Op:
    """One schedulable operation of a thread's program.

    ``ids`` / ``pool`` / ``readonly`` are the conflict footprint;
    ``args`` is the op-specific payload the harness interprets. Ops are
    value objects (frozen) so schedules hash and replay scripts
    round-trip through JSON losslessly.
    """

    thread: str
    name: str
    args: tuple = ()           # flat (key, value) pairs, JSON-safe
    ids: frozenset = frozenset()
    pool: bool = False         # reorders the shared pending pool
    readonly: bool = True      # observer op (stats/drained probes)

    def arg(self, key, default=None):
        for k, v in self.args:
            if k == key:
                return v
        return default

    def to_json(self) -> dict:
        return {"thread": self.thread, "name": self.name,
                "args": {k: list(v) if isinstance(v, tuple) else v
                         for k, v in self.args}}

    @staticmethod
    def from_json(rec: dict) -> "Op":
        return make_op(rec["thread"], rec["name"],
                       **{k: tuple(v) if isinstance(v, list) else v
                          for k, v in rec.get("args", {}).items()})


# name -> (pool, readonly, id-args) — the footprint table. Ops not in
# the table are rejected loudly (a replay script with a typo'd op name
# must be a config error, not a silent no-op).
_OP_KINDS = {
    # intake: static ids, adds to the pending pool
    "enqueue": dict(pool=True, readonly=False, id_args=("ids",)),
    # tick-only AppendBars onto the digest of a previously enqueued job:
    # journals a `delta` chain link, enqueues nothing
    "append": dict(pool=False, readonly=False, id_args=("src",)),
    # dispatch/completion: dynamic id sets -> wildcard footprint
    "take": dict(pool=True, readonly=False, id_args=None),
    "complete_taken": dict(pool=False, readonly=False, id_args=None),
    "complete_deferred": dict(pool=False, readonly=False, id_args=None),
    "complete_dup": dict(pool=False, readonly=False, id_args=None),
    # completion of STATIC ids regardless of lease state (exercises the
    # completed-while-pending tombstone path and the unknown-id reply);
    # touches the pool (tombstone install / parked-lane discard)
    "complete_ids": dict(pool=True, readonly=False, id_args=("ids",)),
    # recovery: dynamic (whatever is leased) -> wildcard
    "requeue_expired": dict(pool=True, readonly=False, id_args=None),
    "requeue_worker": dict(pool=True, readonly=False, id_args=None),
    # python-substrate virtual lease clock (no-op on native)
    "advance_clock": dict(pool=True, readonly=False, id_args=None),
    # observer: reads stats()/drained, mutates nothing
    "stats": dict(pool=False, readonly=True, id_args=()),
}


def make_op(thread: str, name: str, **args) -> Op:
    """Construct an op with its footprint derived from the kind table."""
    kind = _OP_KINDS.get(name)
    if kind is None:
        raise ValueError(f"unknown op {name!r}")
    ids: frozenset = frozenset()
    if kind["id_args"] is None:
        ids = frozenset([WILD])
    else:
        for key in kind["id_args"]:
            v = args.get(key)
            if isinstance(v, str):
                ids |= {v}
            elif v is not None:
                ids |= frozenset(v)
    return Op(thread=thread, name=name,
              args=tuple(sorted(args.items())),
              ids=ids, pool=kind["pool"], readonly=kind["readonly"])


def conflict(a: Op, b: Op) -> bool:
    """True when the two ops may NOT commute (same thread, or footprints
    intersect). Over-approximate by design — see the module docstring."""
    if a.thread == b.thread:
        return True
    if a.readonly or b.readonly:
        return False
    if a.pool and b.pool:
        return True
    if WILD in a.ids or WILD in b.ids:
        return True
    return bool(a.ids & b.ids)


# ---------------------------------------------------------------------------
# Program generation
# ---------------------------------------------------------------------------

def build_programs(n_ops: int, rng) -> dict[str, list[Op]]:
    """Deterministic per-thread op programs totalling ~``n_ops`` ops.

    The shape covers every queue transition family the invariants talk
    about: batched intake across two tenants, an append-chain link, two
    competing workers (take / complete / deferred-journal complete /
    duplicate complete / static-id completes hitting the tombstone
    path), and a maintenance thread running both requeue flavors. Sizes
    and orderings vary with the seed; ids are ``j0..jN`` so traces read
    and replay deterministically.
    """
    n_ops = max(int(n_ops), 8)
    n_jobs = max(2, n_ops // 3)
    jids = [f"j{i}" for i in range(n_jobs)]
    tenants = ["default", "tenantB"]

    client: list[Op] = []
    i = 0
    while i < n_jobs:
        k = min(rng.choice([1, 1, 2, 3]), n_jobs - i)
        client.append(make_op(
            "client", "enqueue", ids=tuple(jids[i:i + k]),
            tenant=tenants[(i // 2) % 2],
            combos=tuple(float(2 + (i + j) % 3) for j in range(k))))
        i += k
    # One tick-only append onto the first job's panel, somewhere after
    # its enqueue: exercises the delta-event/enqueue-record crash window
    # and the chain-reachability invariant at every later crash point.
    client.insert(rng.randrange(1, len(client) + 1),
                  make_op("client", "append", src=jids[0], bars=2))

    def worker(name: str, other: str) -> list[Op]:
        ops = [make_op(name, "take", worker=name,
                       n=rng.choice([1, 2, 3]))]
        for _ in range(max(1, n_ops // 6)):
            ops.append(make_op(name, "take", worker=name,
                               n=rng.choice([1, 2])))
            ops.append(make_op(
                name,
                rng.choice(["complete_taken", "complete_taken",
                            "complete_deferred"]),
                worker=name))
        if rng.random() < 0.7:
            ops.append(make_op(name, "complete_dup", worker=name))
        if rng.random() < 0.6:
            # Static-id completes: a pending (never-taken) id hits the
            # tombstone path, an unknown id the "unknown" reply; either
            # may race the other worker's take of the same id.
            ops.append(make_op(name, "complete_ids", worker=name,
                               ids=(rng.choice(jids), "never-enqueued")))
        ops.append(make_op(name, "complete_taken", worker=name))
        return ops

    maint = [make_op("maint", "stats")]
    for _ in range(max(1, n_ops // 8)):
        maint.append(make_op("maint", rng.choice(
            ["requeue_expired", "requeue_expired", "requeue_worker"]),
            worker=rng.choice(["workerA", "workerB"])))
    maint.append(make_op("maint", "stats"))

    return {"client": client,
            "workerA": worker("workerA", "workerB"),
            "workerB": worker("workerB", "workerA"),
            "maint": maint}


# ---------------------------------------------------------------------------
# Canonical form + schedule generation
# ---------------------------------------------------------------------------

def _thread_rank(op: Op) -> int:
    try:
        return THREADS.index(op.thread)
    except ValueError:
        return len(THREADS)


def canonical_key(schedule: list[Op]) -> tuple:
    """Foata-style normal form: bubble adjacent INDEPENDENT ops into the
    fixed thread order until fixpoint, then key by (thread, per-thread
    op index). Two interleavings with the same key are reachable from
    each other by commuting independent ops — equivalent executions."""
    seq = list(schedule)
    changed = True
    while changed:
        changed = False
        for i in range(len(seq) - 1):
            a, b = seq[i], seq[i + 1]
            if (not conflict(a, b)
                    and _thread_rank(a) > _thread_rank(b)):
                seq[i], seq[i + 1] = b, a
                changed = True
    counters: dict[str, int] = {}
    key = []
    for op in seq:
        k = counters.get(op.thread, 0)
        counters[op.thread] = k + 1
        key.append((op.thread, k))
    return tuple(key)


def merge_for_key(threads: dict[str, list[Op]], key: tuple) -> list[Op]:
    """Rebuild the concrete op list for a canonical key (replay path)."""
    counters: dict[str, int] = {}
    out = []
    for thread, _idx in key:
        i = counters.get(thread, 0)
        counters[thread] = i + 1
        out.append(threads[thread][i])
    return out


def random_merge(threads: dict[str, list[Op]], rng) -> list[Op]:
    """One seeded topological merge preserving per-thread order."""
    cursors = {t: 0 for t in threads}
    live = [t for t in threads if threads[t]]
    out: list[Op] = []
    while live:
        t = rng.choice(live)
        out.append(threads[t][cursors[t]])
        cursors[t] += 1
        if cursors[t] >= len(threads[t]):
            live.remove(t)
    return out


def generate_schedules(threads: dict[str, list[Op]], rng, limit: int,
                       max_attempts: int | None = None):
    """Yield up to ``limit`` DISTINCT schedules (distinct canonical
    forms) as ``(canonical_key, ops)`` pairs. Seeded-random merges with
    canonical dedupe: every yielded schedule is a genuinely inequivalent
    interleaving; commuting-only variants are pruned, never re-run."""
    seen: set = set()
    attempts = 0
    budget = max_attempts if max_attempts is not None else limit * 40
    while len(seen) < limit and attempts < budget:
        attempts += 1
        sched = random_merge(threads, rng)
        key = canonical_key(sched)
        if key in seen:
            continue
        seen.add(key)
        yield key, sched


def enumerate_schedules(threads: dict[str, list[Op]], limit: int):
    """Exhaustive DFS twin of :func:`generate_schedules` for small
    programs (the `slow` deep-exploration config): yields every distinct
    canonical class, deterministically, up to ``limit``."""
    seen: set = set()
    names = sorted(threads)

    def rec(cursors: dict[str, int], prefix: list[Op]):
        if len(seen) >= limit:
            return
        done = all(cursors[t] >= len(threads[t]) for t in names)
        if done:
            key = canonical_key(prefix)
            if key not in seen:
                seen.add(key)
                yield key, list(prefix)
            return
        for t in names:
            if cursors[t] < len(threads[t]):
                cursors[t] += 1
                prefix.append(threads[t][cursors[t] - 1])
                yield from rec(cursors, prefix)
                prefix.pop()
                cursors[t] -= 1

    yield from rec({t: 0 for t in names}, [])


# ---------------------------------------------------------------------------
# Controlled scheduler (--depth > 0): intra-op preemption at lock points
# ---------------------------------------------------------------------------

class Wedged(RuntimeError):
    """A controlled run stopped making progress (real deadlock or a
    hook wait past the bound) — reported, never hung."""


class ControlledScheduler:
    """Run per-thread op programs on REAL threads, serialized by a token,
    preempting at instrumented-lock acquire points (lockdep seam).

    At most one managed thread runs at a time; at every ``acquire``
    hook event the scheduler may (seeded, bounded by ``depth``) park the
    runner and wake another. Ownership is tracked from the
    ``acquired``/``release`` events: a thread about to block on a lock
    a PARKED thread holds hands the token to the holder instead (and
    gets it back at the release), so the controlled run explores
    genuine in-critical-section interleavings without self-inflicted
    deadlock. All waits are bounded: exceeding ``timeout_s`` raises
    :class:`Wedged` with the stuck thread set — a finding, not a hang.
    """

    def __init__(self, threads: dict[str, list[Op]], runner, *,
                 depth: int, rng, timeout_s: float = 20.0):
        self._programs = threads
        self._runner = runner          # callable(op) -> None
        self._depth = int(depth)
        self._rng = rng
        self._timeout = float(timeout_s)
        self._events = {t: threading.Event() for t in threads}
        # RAW lock (never the lockdep factory): the scheduler's own
        # bookkeeping must not become an instrumented scheduling point —
        # the hook would re-enter itself on its own mutex.
        self._mutex = lockdep._RealLock()
        self._current: str | None = None
        self._finished: set[str] = set()
        self._lock_owner: dict[str, str] = {}   # lock key -> thread name
        self._want: dict[str, str] = {}         # thread -> lock key waited
        self._preemptions = 0
        self._paused = 0               # crash-check reentrancy guard
        self._error: BaseException | None = None

    # -- public -----------------------------------------------------------

    def run(self) -> int:
        """Execute every program to completion; returns the number of
        preemptions taken. Raises :class:`Wedged` on a stuck run and
        re-raises the first op exception otherwise."""
        names = [t for t in THREADS if t in self._programs]
        names += [t for t in self._programs if t not in names]
        workers = [threading.Thread(target=self._thread_main, args=(t,),
                                    name=f"mc-{t}", daemon=True)
                   for t in names]
        lockdep.set_schedule_hook(self._hook)
        try:
            for w in workers:
                w.start()
            with self._mutex:
                self._current = names[0]
            self._events[names[0]].set()
            deadline = self._timeout
            for w in workers:
                w.join(timeout=deadline)
                if w.is_alive():
                    raise Wedged(
                        f"controlled schedule wedged: thread {w.name} "
                        f"still running; waiting-on={self._want}, "
                        f"owners={self._lock_owner}")
        finally:
            lockdep.set_schedule_hook(None)
            # Release any survivors so daemon threads can exit.
            for ev in self._events.values():
                ev.set()
        if self._error is not None:
            raise self._error
        return self._preemptions

    def pause(self) -> None:
        """Disable preemption (crash-check reentrancy: replay/restore
        work creates and takes fresh locks that must not become
        scheduling points)."""
        with self._mutex:
            self._paused += 1

    def resume(self) -> None:
        with self._mutex:
            self._paused -= 1

    # -- thread body -------------------------------------------------------

    def _thread_main(self, name: str) -> None:
        try:
            self._wait_for_token(name)
            for op in self._programs[name]:
                self._runner(op)
            with self._mutex:
                self._finished.add(name)
                nxt = self._pick_runnable(exclude=name)
            if nxt is not None:
                self._events[nxt].set()
        except BaseException as e:   # first error wins, run must unwind
            with self._mutex:
                if self._error is None:
                    self._error = e
                self._finished.add(name)
                nxt = self._pick_runnable(exclude=name)
            if nxt is not None:
                self._events[nxt].set()

    def _wait_for_token(self, name: str) -> None:
        if not self._events[name].wait(timeout=self._timeout):
            raise Wedged(f"thread {name} never received the token")
        with self._mutex:
            self._current = name

    # -- the lockdep hook --------------------------------------------------

    def _hook(self, phase: str, key: str) -> None:
        name = threading.current_thread().name
        if not name.startswith("mc-"):
            return
        name = name[3:]
        if name not in self._events:
            return
        if phase == "acquired":
            with self._mutex:
                self._lock_owner[key] = name
            return
        if phase == "release":
            self._switch_after_release(name, key)
            return
        # phase == "acquire": the preemption point.
        self._before_acquire(name, key)

    def _before_acquire(self, name: str, key: str) -> None:
        while True:
            with self._mutex:
                if self._paused or self._error is not None:
                    return
                owner = self._lock_owner.get(key)
                if owner is not None and owner != name:
                    # The holder is parked (only one thread runs at a
                    # time): hand it the token until it releases.
                    self._want[name] = key
                    self._events[name].clear()
                    nxt = owner
                elif (self._preemptions < self._depth
                        and self._rng.random() < 0.5):
                    nxt = self._pick_runnable(exclude=name)
                    if nxt is None:
                        return
                    self._preemptions += 1
                    self._events[name].clear()
                else:
                    return
                self._current = nxt
            self._events[nxt].set()
            if not self._events[name].wait(timeout=self._timeout):
                raise Wedged(
                    f"thread {name} starved waiting to acquire {key} "
                    f"(owner={self._lock_owner.get(key)})")
            with self._mutex:
                self._current = name
                self._want.pop(name, None)
                if self._lock_owner.get(key) in (None, name):
                    return   # free now — proceed into the real acquire

    def _switch_after_release(self, name: str, key: str) -> None:
        with self._mutex:
            if self._lock_owner.get(key) == name:
                del self._lock_owner[key]
            waiter = next((t for t, k in self._want.items() if k == key),
                          None)
            if waiter is None or self._paused:
                return
            self._events[name].clear()
            self._current = waiter
        self._events[waiter].set()
        if not self._events[name].wait(timeout=self._timeout):
            raise Wedged(f"thread {name} starved after releasing {key}")
        with self._mutex:
            self._current = name

    def _pick_runnable(self, exclude: str) -> str | None:
        """A thread that can make progress: not finished, not waiting on
        a lock someone still owns (caller holds ``self._mutex``)."""
        held = set(self._lock_owner.values())
        cands = [t for t in self._programs
                 if t != exclude and t not in self._finished
                 and (t not in self._want
                      or self._lock_owner.get(self._want[t]) is None)
                 and t not in held - {exclude}]
        # Threads currently holding a lock are parked mid-critical-
        # section; they stay eligible (they must eventually run to
        # release), but prefer lock-free threads for diversity.
        if not cands:
            cands = [t for t in self._programs
                     if t != exclude and t not in self._finished]
        if not cands:
            return None
        return self._rng.choice(sorted(cands))
