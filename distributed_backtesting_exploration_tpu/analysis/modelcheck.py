"""dbxmc: interleaving + crash-point model checker over the dispatcher's
journaled state machines.

dbxcert (PR 15) machine-checks the NUMERICS contract; this module does
the same for the CONTROL-PLANE contract — the crash-recovery and
scheduling invariants ROADMAP item 1 (federated dispatch) leans on. It
runs the REAL ``JobQueue`` / ``Journal`` / ``WfqScheduler`` /
``PanelStore`` code, never an abstract model:

- :mod:`.schedules` enumerates distinct interleavings of per-thread op
  programs (enqueue / take / complete / requeue / append), pruning
  schedules equivalent under commutation of independent ops
  (DPOR-lite canonical forms); ``--depth > 0`` additionally preempts
  INSIDE ops at instrumented-lock acquire points via the lockdep seam;
- every journal append is a crash boundary: the ``Journal.crash_hook``
  seam fires on both sides of the write, where the checker replays the
  journal as a restarting dispatcher would and diffs the restored state
  against a canonical projection of the live queue;
- sampled boundaries fork a FULL crash replay — copy the journal
  (optionally ``Journal.compact`` it first), restore into a fresh
  ``JobQueue`` on the same substrate, then drive the restored queue to
  completion, checking the declared invariant table
  (:data:`INVARIANTS`) along the way;
- a violation is reported as a minimized, REPLAYABLE op script
  (greedy delta-debugging over the schedule, re-run deterministically)
  — `dbxmc --replay script.json` reproduces it exactly.

Exit codes mirror dbxcert: 0 clean / 1 violations / 2 config error.
Env knobs: ``DBX_MC_OPS`` (program size), ``DBX_MC_SEED``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import shutil
import sys
import tempfile
import time
import zlib

import numpy as np

from . import lockdep
from . import schedules as sched_mod
from .schedules import Op, make_op

# The declared invariant catalogue (DESIGN.md "Protocol model checking"
# documents each; ``dbxmc --list-invariants`` prints this table). Adding
# an invariant = add a row here + a check in _Run/_fork that reports
# violations under the new name.
INVARIANTS = {
    "replay-integrity":
        "strict journal replay succeeds at every crash boundary "
        "(a torn line is legal only as the final record)",
    "journal-append-first":
        "every live registered job id is covered by a journaled enqueue "
        "record — the publish-side append-first discipline",
    "completion-durability":
        "journaled completions never LEAD live state (state completes "
        "first; the journal may lag — that window only re-runs a job "
        "idempotently)",
    "job-conservation":
        "journaled jobs partition exactly into pending/completed/failed; "
        "restore re-enqueues precisely the pending set",
    "exactly-once-completion":
        "completion outcomes are idempotent: first 'new', repeats 'dup', "
        "never-enqueued 'unknown' — live, and again after restore",
    "drained-monotonic":
        "`drained` is exactly 'no live work': never True while work is "
        "pending/leased/in-take, True after a full drain",
    "lane-fifo-consistency":
        "the state FIFO is empty between public calls (WFQ lanes own all "
        "pending work) and queue stats equal the op ledger",
    "quota-balance":
        "per-tenant in-flight quota charges equal the combos of currently "
        "leased jobs; zero once drained",
    "chain-reachability":
        "append-chain digests re-materialize after restore, including "
        "post-compaction (chain ROOT payloads survive slimming)",
    "scenario-base-reachability":
        "pending scenario jobs' base-digest chains reach a "
        "payload-carrying record, including post-compaction",
    "digest-soundness":
        "every delivered payload hashes to the job's journaled digest",
    "wedged":
        "a controlled (--depth) schedule stopped making progress — the "
        "runtime shape of a real deadlock",
}


@dataclasses.dataclass
class MCConfig:
    """One exploration's knobs (CLI flags map 1:1)."""

    ops: int = 12                # program size (total ops, ~)
    depth: int = 0               # intra-op preemption bound (0 = op-grain)
    seed: int = 0
    schedules: int = 500         # distinct schedules to explore
    substrate: str = "python"    # python | native
    lease_s: float = -1.0        # already-expired leases: requeue_expired
                                 # is deterministic on BOTH substrates
    crash_every: int = 3         # arm a full crash fork every N schedules
    fork_all: bool = False       # fork at every boundary (minimizer mode)
    max_violations: int = 3      # stop exploring after this many
    minimize: bool = True
    timeout_s: float = 20.0      # controlled-run wedge bound


class _Violation(Exception):
    """Internal control flow: first invariant violation aborts the
    schedule (the queue under test is in a state the invariant says is
    unreachable — further ops would only cascade)."""

    def __init__(self, invariant: str, detail: str):
        super().__init__(f"[{invariant}] {detail}")
        self.invariant = invariant
        self.detail = detail


def _panel_bytes(key: str, n_bars: int = 6) -> bytes:
    """Deterministic tiny DBX1 panel for job ``key`` (real wire bytes —
    append splices and digest checks parse them)."""
    from ..utils import data as data_mod

    base = 1.0 + (zlib.crc32(key.encode()) % 997) / 10.0
    close = base + 0.1 * np.arange(n_bars, dtype=np.float32)
    return data_mod.to_wire_bytes(data_mod.OHLCV(
        open=close - 0.05, high=close + 0.1, low=close - 0.1,
        close=close,
        volume=np.full(n_bars, 100.0, dtype=np.float32)))


class _Ledger:
    """The checker's own transition ledger — what the queue SHOULD hold,
    derived purely from op outcomes (never from queue internals)."""

    def __init__(self):
        self.enqueued: dict[str, tuple[str, int]] = {}  # id->(tenant,combos)
        self.completed: set[str] = set()
        self.failed: set[str] = set()
        self.leases: dict[str, str] = {}                # id -> worker
        self.taken: dict[str, list[str]] = {}           # worker -> open ids
        self.done_by: dict[str, list[str]] = {}         # worker -> completed
        self.deltas: list[str] = []                     # extended digests

    def pending(self) -> set[str]:
        return (set(self.enqueued) - self.completed - self.failed
                - set(self.leases))

    def lease_drop(self, jid: str) -> None:
        self.leases.pop(jid, None)
        for ids in self.taken.values():
            if jid in ids:
                ids.remove(jid)


class _Run:
    """One schedule executed against a fresh queue + journal."""

    def __init__(self, cfg: MCConfig, workdir: str, index: int,
                 fork_at: int | None, compact_fork: bool):
        from ..rpc.dispatcher import JobQueue
        from ..rpc.journal import Journal

        self.cfg = cfg
        self.index = index
        self.path = os.path.join(workdir, f"mc{index}.jsonl")
        if os.path.exists(self.path):
            os.remove(self.path)
        self.journal = Journal(self.path, fsync=False)
        self.vclock = [0.0]
        clock = (lambda: self.vclock[0]) \
            if cfg.substrate == "python" else None
        self.q = JobQueue(self.journal, lease_s=cfg.lease_s,
                          use_native=(cfg.substrate == "native"),
                          clock=clock)
        if self.q.substrate != cfg.substrate:
            raise RuntimeError(
                f"substrate {cfg.substrate} requested but queue came up "
                f"{self.q.substrate}")
        self.ledger = _Ledger()
        self.payloads: dict[str, bytes] = {}   # jid -> inline wire bytes
        self.boundaries = 0
        self.crash_points = 0
        self.fork_at = fork_at                 # boundary index to fork at
        self.fork_fired = False
        self.compact_fork = compact_fork
        self.strict = True        # ledger expectations (off when depth>0)
        self.preemptions = 0
        # Native substrate: count C-ABI state-machine crossings through
        # the runtime step_hook seam — the native twin of the journal
        # boundary counter (each crossing is an atomic transition the
        # schedule explorer is permuting around).
        self.native_steps = 0
        if cfg.substrate == "native":
            self.q._state.step_hook = self._native_step
        self._final = False       # final drain in progress: no more forks
        self.scheduler = None     # ControlledScheduler when depth>0
        self.executed: list[Op] = []
        self.journal.crash_hook = self._crash_hook

    def _native_step(self, name: str, n: int) -> None:
        self.native_steps += 1

    # -- op execution ------------------------------------------------------

    def execute(self, ops: list[Op]) -> None:
        try:
            for op in ops:
                self.do_op(op)
                if self.strict:
                    self._boundary_checks(op)
            if self.fork_at is not None and not self.fork_fired:
                self._fork()              # schedule had fewer boundaries
            self._final_checks()
        finally:
            self.journal.crash_hook = None
            self.journal.close()

    def execute_controlled(self, programs: dict[str, list[Op]],
                           rng) -> None:
        """--depth mode: ops on real threads, preempted at lock points.
        Ledger expectations are relaxed (ops genuinely interleave) — the
        crash-boundary and final-state invariants carry the checking."""
        self.strict = False
        installed_here = not lockdep.active()
        if installed_here:
            lockdep.install()
        self.scheduler = sched_mod.ControlledScheduler(
            programs, self.do_op, depth=self.cfg.depth, rng=rng,
            timeout_s=self.cfg.timeout_s)
        try:
            self.preemptions = self.scheduler.run()
            self._final_checks()
        except sched_mod.Wedged as e:
            raise _Violation("wedged", str(e)) from e
        finally:
            self.scheduler = None
            self.journal.crash_hook = None
            self.journal.close()
            if installed_here:
                lockdep.uninstall()

    def do_op(self, op: Op) -> None:
        self.executed.append(op)
        getattr(self, f"_op_{op.name}")(op)

    def _op_enqueue(self, op: Op) -> None:
        from ..rpc.dispatcher import JobRecord

        ids = list(op.arg("ids", ()))
        combos = list(op.arg("combos", ())) or [2.0] * len(ids)
        tenant = op.arg("tenant", "default")
        recs = []
        for jid, c in zip(ids, combos):
            payload = self.payloads.setdefault(jid, _panel_bytes(jid))
            recs.append(JobRecord(
                id=jid, strategy="sma_crossover",
                grid={"p": np.arange(int(c), dtype=np.float32)},
                ohlcv=payload, tenant=tenant))
        self.q.enqueue_many(recs)
        for jid, c in zip(ids, combos):
            self.ledger.enqueued[jid] = (tenant, int(c))

    def _op_append(self, op: Op) -> None:
        from ..rpc import panel_store as panel_store_mod

        src = op.arg("src")
        base = self.payloads.get(src)
        if base is None:
            return       # src removed by the minimizer: benign no-op
        parent = panel_store_mod.panel_digest(base)
        delta = _panel_bytes(f"{src}:delta", n_bars=int(op.arg("bars", 2)))
        rec, outcome, ndig, _n = self.q.append_bars(
            parent, 0, delta, strategy="", grid={})
        if outcome == "extended":
            self.ledger.deltas.append(ndig)
        elif self.strict:
            raise _Violation(
                "chain-reachability",
                f"append onto live inline panel {parent[:12]} rejected "
                f"with {outcome!r}")

    def _op_take(self, op: Op) -> None:
        from ..rpc import panel_store as panel_store_mod

        worker = op.arg("worker")
        got = self.q.take(int(op.arg("n", 1)), worker)
        for rec, payload in got:
            if rec.panel_digest and (panel_store_mod.panel_digest(payload)
                                     != rec.panel_digest):
                raise _Violation(
                    "digest-soundness",
                    f"take({worker}) delivered bytes for {rec.id} that "
                    f"hash differently from its digest {rec.panel_digest}")
            self.ledger.leases[rec.id] = worker
            self.ledger.taken.setdefault(worker, []).append(rec.id)

    def _complete(self, worker: str, ids: list[str],
                  journal: bool = True) -> list[str]:
        outcomes = self.q.complete_batch(ids, worker, journal=journal)
        new = [j for j, o in zip(ids, outcomes) if o == "new"]
        for jid, outcome in zip(ids, outcomes):
            expect = ("unknown" if jid not in self.ledger.enqueued
                      else "dup" if jid in self.ledger.completed
                      else "new")
            if self.strict and outcome != expect:
                raise _Violation(
                    "exactly-once-completion",
                    f"complete({jid}) by {worker} returned {outcome!r}, "
                    f"ledger expected {expect!r}")
            if outcome == "new":
                self.ledger.completed.add(jid)
                self.ledger.lease_drop(jid)
                self.ledger.done_by.setdefault(worker, []).append(jid)
        return new

    def _op_complete_taken(self, op: Op) -> None:
        worker = op.arg("worker")
        ids = list(self.ledger.taken.get(worker, ()))
        self._complete(worker, ids)

    def _op_complete_deferred(self, op: Op) -> None:
        # The persist-results-first protocol: state completes now, the
        # durable records land in a second step — the crash window in
        # between is LEGAL (re-run idempotently), and the hook forks
        # right inside it.
        worker = op.arg("worker")
        ids = list(self.ledger.taken.get(worker, ()))
        new = self._complete(worker, ids, journal=False)
        self.q.journal_completions(new, worker)

    def _op_complete_dup(self, op: Op) -> None:
        worker = op.arg("worker")
        ids = self.ledger.done_by.get(worker, [])[-2:]
        if ids:
            self._complete(worker, ids)

    def _op_complete_ids(self, op: Op) -> None:
        worker = op.arg("worker")
        for jid in op.arg("ids", ()):
            outcome = self.q.complete(jid, worker)
            expect = ("unknown" if jid not in self.ledger.enqueued
                      else "dup" if jid in self.ledger.completed
                      else "new")
            if self.strict and outcome != expect:
                raise _Violation(
                    "exactly-once-completion",
                    f"complete({jid}) by {worker} returned {outcome!r}, "
                    f"ledger expected {expect!r}")
            if outcome == "new":
                self.ledger.completed.add(jid)
                self.ledger.lease_drop(jid)
                self.ledger.done_by.setdefault(worker, []).append(jid)

    def _op_requeue_expired(self, op: Op) -> None:
        jids = self.q.requeue_expired()
        # lease_s < 0: every live lease is expired by construction.
        if self.strict and set(jids) != set(self.ledger.leases):
            raise _Violation(
                "job-conservation",
                f"requeue_expired returned {sorted(jids)}, ledger holds "
                f"leases {sorted(self.ledger.leases)}")
        for jid in jids:
            self.ledger.lease_drop(jid)

    def _op_requeue_worker(self, op: Op) -> None:
        worker = op.arg("worker")
        jids = self.q.requeue_worker(worker)
        held = {j for j, w in self.ledger.leases.items() if w == worker}
        if self.strict and set(jids) != held:
            raise _Violation(
                "job-conservation",
                f"requeue_worker({worker}) returned {sorted(jids)}, "
                f"ledger holds {sorted(held)}")
        for jid in jids:
            self.ledger.lease_drop(jid)

    def _op_advance_clock(self, op: Op) -> None:
        self.vclock[0] += float(op.arg("dt", 1.0))

    def _op_stats(self, op: Op) -> None:
        self.q.stats()
        _ = self.q.drained

    # -- per-op boundary invariants (op-granularity mode only) -------------

    def _boundary_checks(self, op: Op) -> None:
        led = self.ledger
        s = self.q.stats()
        pending = led.pending()
        if (s["jobs_pending"] != len(pending)
                or s["jobs_leased"] != len(led.leases)
                or s["jobs_completed"] != len(led.completed)):
            raise _Violation(
                "lane-fifo-consistency",
                f"after {op.name}: stats pending/leased/completed = "
                f"{s['jobs_pending']}/{s['jobs_leased']}/"
                f"{s['jobs_completed']}, ledger = {len(pending)}/"
                f"{len(led.leases)}/{len(led.completed)}")
        if self.q._state.stats()["pending"] != 0:
            raise _Violation(
                "lane-fifo-consistency",
                f"after {op.name}: state FIFO not empty between public "
                "calls (WFQ lanes must own all pending work)")
        want_drained = not pending and not led.leases
        if self.q.drained != want_drained:
            raise _Violation(
                "drained-monotonic",
                f"after {op.name}: drained={self.q.drained} but ledger "
                f"has {len(pending)} pending / {len(led.leases)} leased")
        ts = self.q.tenant_stats()
        charge: dict[str, float] = {}
        for jid, worker in led.leases.items():
            t, c = led.enqueued[jid]
            charge[t] = charge.get(t, 0.0) + float(c)
        for t, expect in charge.items():
            got = ts.get(t, {}).get("inflight_combos", 0.0)
            if abs(got - expect) > 1e-9:
                raise _Violation(
                    "quota-balance",
                    f"after {op.name}: tenant {t} inflight charge {got} "
                    f"!= leased combo total {expect}")
        for t, row in ts.items():
            if t not in charge and row["inflight_combos"]:
                raise _Violation(
                    "quota-balance",
                    f"after {op.name}: tenant {t} charged "
                    f"{row['inflight_combos']} with nothing leased")

    # -- crash boundaries --------------------------------------------------

    def _crash_hook(self, phase: str, event: str, rec: dict) -> None:
        if self._final:
            return
        if self.scheduler is not None:
            self.scheduler.pause()
        try:
            self.boundaries += 1
            self._light_checks(phase, event)
            if self.cfg.fork_all and phase == "post":
                self._fork()
            elif (self.fork_at is not None and not self.fork_fired
                    and self.boundaries >= self.fork_at):
                self._fork()
        finally:
            if self.scheduler is not None:
                self.scheduler.resume()

    def _light_checks(self, phase: str, event: str) -> None:
        from ..rpc.journal import Journal, JournalCorruptError

        try:
            replay = Journal.replay(self.path)
        except JournalCorruptError as e:
            raise _Violation("replay-integrity", str(e)) from e
        live_ids = set(self.q._records)
        extra = live_ids - set(replay.jobs)
        if extra:
            raise _Violation(
                "journal-append-first",
                f"at {phase}-append({event}) boundary {self.boundaries}: "
                f"live state holds {sorted(extra)} with no journaled "
                "enqueue record — a crash here loses them")
        ahead = replay.completed - self.q.completed_ids()
        if ahead:
            raise _Violation(
                "completion-durability",
                f"at {phase}-append({event}): journal records completions "
                f"{sorted(ahead)} that live state never saw")

    def _fork(self) -> None:
        self.fork_fired = True
        self._check_restore(compact=False)
        if self.compact_fork:
            self._check_restore(compact=True)

    def _check_restore(self, compact: bool) -> None:
        from ..rpc.dispatcher import JobQueue
        from ..rpc.journal import Journal

        self.crash_points += 1
        fork = f"{self.path}.fork"
        shutil.copyfile(self.path, fork)
        try:
            if compact:
                Journal.compact(fork)
            replay = Journal.replay(fork)
            tag = "post-compaction " if compact else ""
            jobs = set(replay.jobs)
            if (replay.completed | replay.failed) - jobs:
                raise _Violation(
                    "job-conservation",
                    f"{tag}replay has terminal records for jobs with no "
                    "enqueue record")
            q2 = JobQueue(use_native=(self.cfg.substrate == "native"))
            n = q2.restore(fork)
            if n != len(replay.pending):
                raise _Violation(
                    "job-conservation",
                    f"{tag}restore re-enqueued {n} jobs, replay says "
                    f"{len(replay.pending)} pending")
            for jid in sorted(replay.completed)[:2]:
                if q2.complete(jid, "mc-probe") != "dup":
                    raise _Violation(
                        "exactly-once-completion",
                        f"{tag}restored queue re-recorded completed job "
                        f"{jid} as new — a retrying worker double-counts")
            if q2.complete("mc-never-enqueued", "mc-probe") != "unknown":
                raise _Violation(
                    "exactly-once-completion",
                    f"{tag}restored queue answered an id it never saw")
            self._check_chains(q2, replay, tag)
            _check_scenario_roots(replay, tag)
            self._drain(q2, replay, tag)
        finally:
            if os.path.exists(fork):
                os.remove(fork)

    def _check_chains(self, q2, replay, tag: str) -> None:
        from ..rpc import panel_store as panel_store_mod

        for ndig in replay.deltas:
            blob = q2.payload_for_digest(ndig)
            if blob is None:
                raise _Violation(
                    "chain-reachability",
                    f"{tag}append-chain digest {ndig[:12]} is unservable "
                    "after restore (orphaned root or slimmed payload)")
            if panel_store_mod.panel_digest(blob) != ndig:
                raise _Violation(
                    "digest-soundness",
                    f"{tag}chain splice for {ndig[:12]} produced bytes "
                    "with a different digest")

    def _drain(self, q2, replay, tag: str) -> None:
        from ..rpc import panel_store as panel_store_mod

        expected = len(replay.pending)
        drained_n = 0
        for _ in range(expected + 4):
            got = q2.take(4, "mc-restore")
            if not got:
                break
            for rec, payload in got:
                if rec.panel_digest and (
                        panel_store_mod.panel_digest(payload)
                        != rec.panel_digest):
                    raise _Violation(
                        "digest-soundness",
                        f"{tag}restored dispatch of {rec.id} delivered "
                        "bytes that hash differently from its journaled "
                        "digest")
                drained_n += 1
            q2.complete_batch([rec.id for rec, _ in got], "mc-restore")
        if drained_n != expected:
            raise _Violation(
                "job-conservation",
                f"{tag}drain dispatched {drained_n} jobs, replay says "
                f"{expected} were pending")
        if not q2.drained:
            raise _Violation(
                "drained-monotonic",
                f"{tag}restored queue not drained after completing every "
                "pending job")
        for t, row in q2.tenant_stats().items():
            if row["inflight_combos"] or row["pending"]:
                raise _Violation(
                    "quota-balance",
                    f"{tag}tenant {t} still charged/parked after a full "
                    f"drain: {row}")

    # -- end of schedule ---------------------------------------------------

    def _final_checks(self) -> None:
        self._final = True
        from ..rpc.journal import Journal

        replay = Journal.replay(self.path)
        live_ids = set(self.q._records)
        if live_ids - set(replay.jobs):
            raise _Violation(
                "journal-append-first",
                f"end of schedule: live ids "
                f"{sorted(live_ids - set(replay.jobs))} never journaled")
        # Drive the LIVE queue to completion: every enqueued job must be
        # dispatchable and completable exactly once, after which drained
        # and the quota ledger must both read empty.
        self.q.requeue_expired()
        for _ in range(len(live_ids) + 4):
            got = self.q.take(8, "mc-final")
            if not got:
                break
            self.q.complete_batch([rec.id for rec, _ in got], "mc-final")
        if not self.q.drained:
            raise _Violation(
                "drained-monotonic",
                "end of schedule: queue not drained after completing "
                "every dispatchable job")
        for t, row in self.q.tenant_stats().items():
            if row["inflight_combos"] or row["pending"]:
                raise _Violation(
                    "quota-balance",
                    f"end of schedule: tenant {t} still charged/parked "
                    f"after full drain: {row}")


def _check_scenario_roots(replay, tag: str = "") -> None:
    """Every PENDING scenario job's base chain must end at a record that
    still carries a payload source (inline bytes or a path) — the walk
    ``Journal.compact`` protects; checked here so a compaction bug that
    slims a scenario root is a dbxmc finding, not a first-take failure
    after the next restart."""
    by_digest: dict = {}
    for r in replay.jobs.values():
        for dkey in ("pdig", "pdig2"):
            if r.get(dkey):
                by_digest.setdefault(r[dkey], r)
    for jid in replay.pending:
        rec = replay.jobs[jid]
        scn = rec.get("scn")
        if not scn:
            continue
        d = scn.get("base")
        seen: set = set()
        while d and d not in seen:
            seen.add(d)
            r = by_digest.get(d)
            if r is None:
                if d in replay.deltas:
                    break      # served through the append chain
                raise _Violation(
                    "scenario-base-reachability",
                    f"{tag}pending scenario job {jid} walks base "
                    f"{d[:12]} that no journaled record carries")
            if r.get("scn") and r.get("pdig") == d:
                d = r["scn"].get("base")
                continue
            if not (r.get("ohlcv_b64") or r.get("path")):
                raise _Violation(
                    "scenario-base-reachability",
                    f"{tag}scenario root {d[:12]} for pending job {jid} "
                    "has no payload source (slimmed at compaction?)")
            break


# ---------------------------------------------------------------------------
# Exploration driver
# ---------------------------------------------------------------------------

def run_ops(cfg: MCConfig, ops: list[Op], workdir: str, index: int = 0,
            fork_all: bool | None = None) -> _Run:
    """Execute one explicit op list (replay / minimizer path). Violations
    surface as ``_Violation`` on the returned run's ``.violation``."""
    eff = dataclasses.replace(
        cfg, fork_all=cfg.fork_all if fork_all is None else fork_all)
    run = _Run(eff, workdir, index, fork_at=None, compact_fork=True)
    run.violation = None
    try:
        run.execute(ops)
    except _Violation as v:
        run.violation = v
    return run


def _minimize(cfg: MCConfig, ops: list[Op], invariant: str,
              workdir: str) -> list[Op]:
    """Greedy delta-debugging: drop ops one at a time while the same
    invariant still trips on a deterministic re-run."""
    def trips(cand: list[Op]) -> bool:
        run = run_ops(cfg, cand, workdir, index=999983, fork_all=True)
        return (run.violation is not None
                and run.violation.invariant == invariant)

    cur = list(ops)
    changed = True
    while changed:
        changed = False
        for i in range(len(cur)):
            cand = cur[:i] + cur[i + 1:]
            if trips(cand):
                cur = cand
                changed = True
                break
    return cur


def _violation_record(cfg: MCConfig, v: _Violation, ops: list[Op],
                      minimized: list[Op] | None) -> dict:
    rec = {
        "invariant": v.invariant,
        "detail": v.detail,
        "substrate": cfg.substrate,
        "schedule_ops": len(ops),
        "script": script_dump(cfg, minimized if minimized is not None
                              else ops, v.invariant),
    }
    if minimized is not None:
        rec["minimized_ops"] = len(minimized)
    return rec


def explore_substrate(cfg: MCConfig) -> dict:
    """Bounded exploration on one substrate; returns the telemetry +
    violation summary dict the CLI/bench/tests consume."""
    t0 = time.perf_counter()
    rng = random.Random(cfg.seed)
    programs = sched_mod.build_programs(cfg.ops, rng)
    out = {"substrate": cfg.substrate, "schedules": 0, "crash_points": 0,
           "boundaries": 0, "preemptions": 0, "native_steps": 0,
           "violations": [], "depth": cfg.depth}
    with tempfile.TemporaryDirectory(prefix="dbxmc-") as workdir:
        if cfg.depth > 0:
            _explore_controlled(cfg, programs, rng, workdir, out)
        else:
            _explore_opgrain(cfg, programs, rng, workdir, out)
    out["wall_s"] = round(time.perf_counter() - t0, 3)
    out["clean"] = not out["violations"]
    return out


def _explore_opgrain(cfg, programs, rng, workdir, out) -> None:
    gen = sched_mod.generate_schedules(programs, rng, cfg.schedules)
    for i, (_key, sched) in enumerate(gen):
        armed = (i % cfg.crash_every == 0)
        fork_at = 1 + (i // cfg.crash_every) % 11 if armed else None
        run = _Run(cfg, workdir, i, fork_at=fork_at,
                   compact_fork=armed and (i // cfg.crash_every) % 2 == 0)
        try:
            run.execute(sched)
        except _Violation as v:
            minimized = (_minimize(cfg, run.executed, v.invariant, workdir)
                         if cfg.minimize else None)
            out["violations"].append(
                _violation_record(cfg, v, run.executed, minimized))
        out["schedules"] += 1
        out["crash_points"] += run.crash_points
        out["boundaries"] += run.boundaries
        out["native_steps"] += run.native_steps
        if len(out["violations"]) >= cfg.max_violations:
            break


def _explore_controlled(cfg, programs, rng, workdir, out) -> None:
    # Install lockdep BEFORE any queue exists: preemption points are the
    # instrumented-lock acquires, and only locks created while lockdep is
    # active are instrumented.
    installed_here = not lockdep.active()
    if installed_here:
        lockdep.install()
    try:
        _controlled_loop(cfg, programs, rng, workdir, out)
    finally:
        if installed_here:
            lockdep.uninstall()


def _controlled_loop(cfg, programs, rng, workdir, out) -> None:
    seen: set = set()
    for i in range(cfg.schedules):
        armed = (i % cfg.crash_every == 0)
        run = _Run(cfg, workdir, i,
                   fork_at=1 + i % 7 if armed else None,
                   compact_fork=armed and i % 2 == 0)
        try:
            run.execute_controlled(programs, random.Random(cfg.seed + i))
        except _Violation as v:
            out["violations"].append(
                _violation_record(cfg, v, run.executed, None))
        seen.add(sched_mod.canonical_key(run.executed))
        out["schedules"] = len(seen)
        out["crash_points"] += run.crash_points
        out["boundaries"] += run.boundaries
        out["preemptions"] += getattr(run, "preemptions", 0)
        out["native_steps"] += run.native_steps
        if len(out["violations"]) >= cfg.max_violations:
            break


def available_substrates() -> list[str]:
    from ..runtime import _core as native_core

    subs = ["python"]
    if native_core.available():
        subs.append("native")
    return subs


def explore(cfg: MCConfig, substrates: list[str]) -> dict:
    results = [explore_substrate(dataclasses.replace(cfg, substrate=s))
               for s in substrates]
    return {
        "substrates": {r["substrate"]: r for r in results},
        "schedules": sum(r["schedules"] for r in results),
        "crash_points": sum(r["crash_points"] for r in results),
        "boundaries": sum(r["boundaries"] for r in results),
        "wall_s": round(sum(r["wall_s"] for r in results), 3),
        "violations": [v for r in results for v in r["violations"]],
        "clean": all(r["clean"] for r in results),
    }


# ---------------------------------------------------------------------------
# Replayable op scripts
# ---------------------------------------------------------------------------

def script_dump(cfg: MCConfig, ops: list[Op], invariant: str = "") -> dict:
    return {"dbxmc": 1, "substrate": cfg.substrate,
            "lease_s": cfg.lease_s, "invariant": invariant,
            "ops": [op.to_json() for op in ops]}


def script_load(rec: dict) -> tuple[MCConfig, list[Op], str]:
    if rec.get("dbxmc") != 1:
        raise ValueError("not a dbxmc op script (missing `dbxmc: 1`)")
    cfg = MCConfig(substrate=rec.get("substrate", "python"),
                   lease_s=float(rec.get("lease_s", -1.0)),
                   minimize=False)
    ops = [Op.from_json(o) for o in rec.get("ops", [])]
    return cfg, ops, str(rec.get("invariant", ""))


def replay_script(rec: dict) -> dict:
    """Re-execute a violation script deterministically; returns a result
    dict with ``reproduced`` set when the named invariant trips again."""
    cfg, ops, invariant = script_load(rec)
    with tempfile.TemporaryDirectory(prefix="dbxmc-replay-") as workdir:
        run = run_ops(cfg, ops, workdir, fork_all=True)
    v = run.violation
    return {
        "substrate": cfg.substrate,
        "ops": len(ops),
        "invariant_expected": invariant,
        "violation": (None if v is None
                      else {"invariant": v.invariant, "detail": v.detail}),
        "reproduced": bool(v is not None
                           and (not invariant or v.invariant == invariant)),
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dbxmc",
        description="interleaving + crash-point model checker over the "
                    "dispatcher's journaled state machines")
    p.add_argument("--ops", type=int,
                   default=int(os.environ.get("DBX_MC_OPS", "12")),
                   help="program size: ~total ops across the four "
                        "logical threads (env DBX_MC_OPS)")
    p.add_argument("--depth", type=int, default=0,
                   help="intra-op preemption bound at instrumented-lock "
                        "acquire points (0 = op-granularity)")
    p.add_argument("--seed", type=int,
                   default=int(os.environ.get("DBX_MC_SEED", "0")),
                   help="exploration seed (env DBX_MC_SEED)")
    p.add_argument("--schedules", type=int, default=500,
                   help="distinct schedules to explore per substrate")
    p.add_argument("--substrate", default="auto",
                   choices=["auto", "python", "native", "both"],
                   help="queue substrate(s); auto = python + native "
                        "when the C++ core is loadable")
    p.add_argument("--format", default="text", choices=["text", "json"])
    p.add_argument("--replay", metavar="FILE",
                   help="re-run a violation op script instead of "
                        "exploring")
    p.add_argument("--no-minimize", action="store_true",
                   help="report raw violating schedules (skip "
                        "delta-debugging)")
    p.add_argument("--list-invariants", action="store_true")
    return p


def _resolve_substrates(choice: str) -> list[str]:
    avail = available_substrates()
    if choice == "auto":
        return avail
    if choice == "both":
        if "native" not in avail:
            raise SystemExit(2)
        return ["python", "native"]
    if choice == "native" and "native" not in avail:
        raise SystemExit(2)
    return [choice]


def exit_code(result: dict) -> int:
    return 0 if result.get("clean") else 1


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    if args.list_invariants:
        for name, doc in INVARIANTS.items():
            print(f"{name}: {doc}")
        return 0
    if args.replay:
        try:
            with open(args.replay, encoding="utf-8") as fh:
                rec = json.load(fh)
            result = replay_script(rec)
        except (OSError, ValueError) as e:
            print(f"dbxmc: bad replay script: {e}", file=sys.stderr)
            return 2
        if args.format == "json":
            print(json.dumps(result, indent=2, sort_keys=True))
        else:
            v = result["violation"]
            print(f"dbxmc replay: {result['ops']} ops on "
                  f"{result['substrate']}: "
                  + (f"violated [{v['invariant']}] {v['detail']}" if v
                     else "clean"))
        return 1 if result["violation"] else 0
    try:
        substrates = _resolve_substrates(args.substrate)
    except SystemExit:
        print(f"dbxmc: substrate {args.substrate!r} requested but the "
              "native core is not loadable", file=sys.stderr)
        return 2
    cfg = MCConfig(ops=args.ops, depth=args.depth, seed=args.seed,
                   schedules=args.schedules,
                   minimize=not args.no_minimize)
    result = explore(cfg, substrates)
    if args.format == "json":
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        for s, r in result["substrates"].items():
            print(f"dbxmc [{s}] schedules={r['schedules']} "
                  f"crash_points={r['crash_points']} "
                  f"boundaries={r['boundaries']} depth={r['depth']} "
                  f"wall={r['wall_s']}s "
                  f"violations={len(r['violations'])}")
        for v in result["violations"]:
            print(f"\nVIOLATION [{v['invariant']}] on {v['substrate']}: "
                  f"{v['detail']}")
            print("replayable script (dbxmc --replay <file>):")
            print(json.dumps(v["script"], indent=2))
        if result["clean"]:
            print(f"dbxmc: clean — {result['schedules']} schedules, "
                  f"{result['crash_points']} crash points, all "
                  f"{len(INVARIANTS)} invariants hold")
    return exit_code(result)


if __name__ == "__main__":
    raise SystemExit(main())
